"""Segment re-batching: fold isomorphic sibling tasks back into
full-batch ops inside one segment program.

The flagship DAG splits the batch into M microbatch chains so the
*scheduler* has placement freedom (SURVEY §7); the price on one device is
M copies of every op at 1/M batch — shapes XLA will not horizontally
merge on its own (measured r3: the mb8+vs8 segment program runs 1.3-1.7x
the fused forward's wall; the mb1 build runs at exactly fused speed).
This pass recovers the fused shapes WITHOUT touching placement: within a
segment, tasks that are provably the same computation applied to
different data slices (same fn object, same global params, isomorphic
argument structure) are executed as ONE call on their concatenated
inputs, and consumers slice members back out (XLA elides
concat-then-slice chains between adjacent batched classes).

Correctness is opt-in per op: only fns marked batch-axis-0 polymorphic
(:func:`..core.graph.mark_batch0` — ``fn(p, concat(xs)) ==
concat(fn(p, x))``) are eligible; chain fusion propagates the marker.
Sibling detection is partition refinement (Weisfeiler-Lehman style):
initial color = (fn identity, global param names); refined by positional
argument colors until fixpoint — the standard way to find a graph's
isomorphic sub-structures without relying on task-id naming conventions.
Classes whose members depend on each other, whose outputs are not single
arrays, or that participate in a condensed-graph cycle are demoted to
singles, so the pass degrades to exactly the unbatched program.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..core.graph import TaskGraph, is_batch0, rootslice_of


def extract_steps(
    graph: TaskGraph, tids: Sequence[str]
) -> Tuple[Tuple[str, Any, Tuple[Tuple[str, str], ...], Tuple[str, ...]], ...]:
    """Per-task ``(tid, fn, param_items, arg_ids)`` extracted up front.

    Shared by every multi-task callable builder (segment fusion here and in
    ``DeviceBackend._segment_callable``, coalesced launch groups in
    :mod:`.dispatch_plan`): closures built over these tuples never capture
    ``graph``, so a cache value keyed weakly by the graph cannot keep its
    own key alive.
    """
    return tuple(
        (
            tid,
            graph[tid].fn,
            tuple(graph[tid].param_items()),
            tuple(graph[tid].arg_tasks or graph[tid].dependencies),
        )
        for tid in tids
    )


@dataclasses.dataclass(frozen=True)
class RebatchPlan:
    """Static execution plan for one segment.

    ``units``: topologically ordered ``("single", tid)`` /
    ``("batched", class_index)`` entries.  ``classes``: member tids (in
    dispatch order) per batched class.  ``arg_sources``: per batched
    class, per argument position, the ordered per-member source ids
    (in-segment tids or ext ids).  ``arg_class``: the passthrough
    marker — the producer class index when an argument's sources are
    exactly that class's members in order (the batched value is used
    directly, no re-concat), else ``None``.  ``sizes``: per class, each
    member's leading-axis extent (for slicing members back out).
    """

    units: Tuple[Tuple[str, Any], ...]
    classes: Tuple[Tuple[str, ...], ...]
    arg_sources: Tuple[Tuple[Tuple[str, ...], ...], ...]
    arg_class: Tuple[Tuple[Optional[int], ...], ...]
    sizes: Tuple[Tuple[int, ...], ...]

    @property
    def n_batched_tasks(self) -> int:
        return sum(len(c) for c in self.classes)


def _leading_dim(spec: Any) -> Optional[int]:
    """Leading-axis extent of a single-array spec; None if not a single
    array with at least one axis (pytree outputs are not batchable)."""
    try:
        leaves = _tree_leaves(spec)
    except Exception:
        return None
    if len(leaves) != 1:
        return None
    shape = getattr(leaves[0], "shape", None)
    if not shape:  # scalar or unknown
        return None
    return int(shape[0])


def _tree_leaves(x: Any) -> List[Any]:
    import jax

    return jax.tree_util.tree_leaves(x)


def _spec_sig(graph: TaskGraph, d: str, tag: str) -> Tuple:
    """Color signature of a value by SPEC rather than identity.

    Used for argument sources that are not themselves batchable — ext
    values from other segments, and in-segment solo tasks (e.g. the
    per-microbatch embedding roots).  Siblings consuming *different*
    such values of the same shape may still merge: the runtime routes
    each member's exact sources (``arg_sources``) and stacks them, so
    identity does not matter for correctness — only the spec must align.
    Without this, the distinct root tasks of isomorphic microbatch
    chains would propagate unique colors down the entire chain and no
    sibling would ever merge."""
    if d in graph:
        spec = graph[d].out_shape
        if spec is not None:
            leaves = _tree_leaves(spec)
            return (
                tag,
                tuple(
                    (tuple(l.shape), str(getattr(l, "dtype", "?")))
                    for l in leaves
                ),
            )
    return ("id", d)  # unknown spec: never merge across it


def plan_rebatch(graph: TaskGraph, tids: Sequence[str]) -> RebatchPlan:
    """Compute the re-batching plan for one segment's tasks (pure)."""
    tid_set = set(tids)
    order = list(tids)

    # -- initial colors ----------------------------------------------------
    color: Dict[str, Any] = {}
    for t in order:
        task = graph[t]
        aids = task.arg_tasks or task.dependencies
        rs = rootslice_of(task.fn) if task.fn is not None else None
        if (
            task.fn is not None
            and is_batch0(task.fn)
            and aids  # roots consume the shared graph input, not task args
            and _leading_dim(task.out_shape) is not None
        ):
            # full (local, global) pairs, not globals alone: members with
            # permuted param_alias mappings must NOT merge — the batched
            # call binds every member to member[0]'s loc->global mapping
            color[t] = ("fn", id(task.fn), tuple(task.param_items()))
        elif (
            rs is not None
            and not aids
            and _leading_dim(task.out_shape) is not None
        ):
            # slice-family root (mark_rootslice): the family key, not the
            # fn identity — each member is a distinct (lo, hi) closure.
            # Contiguity of the slices is checked after grouping.
            color[t] = ("rootfn", rs[0], tuple(task.param_items()))
        else:
            color[t] = ("solo", t)

    # -- refinement to fixpoint -------------------------------------------
    def arg_color(d: str) -> Tuple:
        if d not in tid_set:
            return _spec_sig(graph, d, "ext")
        c = color[d]
        if c[0] == "solo":
            # spec, not identity: distinct solo sources (microbatch
            # roots) must not poison their consumers' colors
            return _spec_sig(graph, d, "solo")
        return c

    prev: Optional[Dict[str, int]] = None
    for _ in range(len(order) + 2):
        canon: Dict[Any, int] = {}
        comp: Dict[str, int] = {}
        for t in order:
            task = graph[t]
            aids = task.arg_tasks or task.dependencies
            acolors = tuple(arg_color(d) for d in aids)
            key = (color[t], acolors)
            comp[t] = canon.setdefault(key, len(canon))
        if comp == prev:
            break
        prev = comp
        # solo-ness must survive relabeling (a solo task may share a
        # refined integer with nothing, but keep the marker explicit)
        color = {
            t: (("solo", t) if color[t][0] == "solo" else ("c", comp[t]))
            for t in order
        }

    # -- classes (dispatch-order members) ---------------------------------
    groups: Dict[Any, List[str]] = {}
    for t in order:
        groups.setdefault(color[t], []).append(t)
    candidate_classes = [
        members for c, members in groups.items()
        if c[0] == "c" and len(members) > 1
    ]

    # -- in-segment ancestor sets: members must be mutually independent ---
    anc: Dict[str, set] = {}
    for t in order:  # dispatch order is topologically consistent
        task = graph[t]
        aids = task.arg_tasks or task.dependencies
        s: set = set()
        for d in aids:
            if d in tid_set:
                s.add(d)
                s |= anc.get(d, set())
        anc[t] = s

    def independent(members: List[str]) -> bool:
        mset = set(members)
        return all(not (anc[m] & mset) for m in members)

    candidate_classes = [m for m in candidate_classes if independent(m)]

    # -- root classes: each class must tile ONE contiguous slice range ----
    # (re-ordered by lo so the class offsets equal the slice offsets).
    # A gap or overlap splits the members into maximal contiguous runs:
    # co-located pairs still merge even when a sibling landed elsewhere;
    # length-1 runs fall back to singles.
    checked: List[List[str]] = []
    for members in candidate_classes:
        m0 = graph[members[0]]
        if m0.arg_tasks or m0.dependencies:
            checked.append(members)
            continue
        slices = [rootslice_of(graph[m].fn) for m in members]
        if any(s is None for s in slices):  # unreachable: color requires it
            continue
        by_lo = sorted(zip(members, slices), key=lambda p: p[1][1])
        run: List[str] = [by_lo[0][0]]
        for i in range(1, len(by_lo)):
            if by_lo[i - 1][1][2] == by_lo[i][1][1]:  # prev hi == lo
                run.append(by_lo[i][0])
            else:
                if len(run) > 1:
                    checked.append(run)
                run = [by_lo[i][0]]
        if len(run) > 1:
            checked.append(run)
    candidate_classes = checked

    # -- argument alignment ------------------------------------------------
    kept: List[List[str]] = []
    kept_sources: List[List[Optional[Tuple[str, ...]]]] = []
    for members in candidate_classes:
        arity = len(
            graph[members[0]].arg_tasks or graph[members[0]].dependencies
        )
        per_arg: List[Optional[Tuple[str, ...]]] = []
        ok = True
        for j in range(arity):
            srcs = []
            for m in members:
                aids = graph[m].arg_tasks or graph[m].dependencies
                srcs.append(aids[j])
            # every source must have a known single-array leading dim
            # (in-segment: producer out_shape; ext: graph spec) so the
            # runtime concat/slice arithmetic is static
            for d in srcs:
                dim = _leading_dim(graph[d].out_shape) if d in graph else None
                if dim is None:
                    ok = False
                    break
            if not ok:
                break
            per_arg.append(tuple(srcs))
        if ok:
            kept.append(members)
            kept_sources.append(per_arg)

    # -- condensed unit graph: Kahn order, demoting classes in cycles -----
    # (a cross-class cycle is impossible for genuinely isomorphic sibling
    # chains, but partition refinement alone does not forbid it; demotion
    # keeps the pass strictly-correct-or-degraded)
    while True:
        class_of = {
            m: ci for ci, members in enumerate(kept) for m in members
        }
        single_ids = [t for t in order if t not in class_of]
        uid_single = {
            t: len(kept) + i for i, t in enumerate(single_ids)
        }

        def uid(t: str) -> int:
            return class_of[t] if t in class_of else uid_single[t]

        n_units = len(kept) + len(single_ids)
        preds: List[set] = [set() for _ in range(n_units)]
        first_pos: List[int] = [len(order)] * n_units
        for i, t in enumerate(order):
            first_pos[uid(t)] = min(first_pos[uid(t)], i)
            aids = graph[t].arg_tasks or graph[t].dependencies
            for d in aids:
                if d in tid_set and uid(d) != uid(t):
                    preds[uid(t)].add(uid(d))
        done: set = set()
        topo: List[int] = []
        while len(topo) < n_units:
            ready = [
                i for i in range(n_units)
                if i not in done and preds[i] <= done
            ]
            if not ready:
                break
            for i in sorted(ready, key=lambda i: first_pos[i]):
                done.add(i)
                topo.append(i)
        if len(topo) == n_units:
            final_units = [
                ("batched", i) if i < len(kept)
                else ("single", single_ids[i - len(kept)])
                for i in topo
            ]
            break
        stuck = {i for i in range(len(kept)) if i not in done}
        if not stuck:  # cycle purely among singles: impossible in a DAG
            raise AssertionError("unit cycle without batched classes")
        kept = [m for ci, m in enumerate(kept) if ci not in stuck]
        kept_sources = [
            s for ci, s in enumerate(kept_sources) if ci not in stuck
        ]

    class_of = {m: ci for ci, members in enumerate(kept) for m in members}

    # per-class arg: mark args that are exactly the producer class's
    # batched value (no re-concat at runtime)
    arg_class: List[List[Optional[int]]] = []
    for ci, members in enumerate(kept):
        row: List[Optional[int]] = []
        for srcs in kept_sources[ci]:
            cj = None
            if srcs is not None and all(d in class_of for d in srcs):
                cjs = {class_of[d] for d in srcs}
                if len(cjs) == 1:
                    cand = next(iter(cjs))
                    if list(srcs) == list(kept[cand]):
                        cj = cand
            row.append(cj)
        arg_class.append(row)

    sizes = tuple(
        tuple(_leading_dim(graph[m].out_shape) for m in members)
        for members in kept
    )
    return RebatchPlan(
        units=tuple(final_units),
        classes=tuple(tuple(m) for m in kept),
        arg_sources=tuple(
            tuple(s for s in srcs) for srcs in kept_sources
        ),
        arg_class=tuple(tuple(r) for r in arg_class),
        sizes=sizes,
    )


def build_rebatched_seg_fn(
    graph: TaskGraph,
    tids: Tuple[str, ...],
    exports: Tuple[str, ...],
    plan: RebatchPlan,
):
    """The segment callable executing ``plan``: (params-by-global-name,
    ext-values-by-task-id) -> {export tid: output}.  Same contract as the
    linear seg_fn in ``DeviceBackend._segment_callable``."""
    import jax.numpy as jnp

    from ..core.graph import is_concat0

    # precompute per-task static info (the closure must not hold `graph`)
    step_info = {
        t: (fn, pitems, aids)
        for t, fn, pitems, aids in extract_steps(graph, tids)
    }
    class_of: Dict[str, Tuple[int, int]] = {}
    offsets: List[List[int]] = []
    for ci, members in enumerate(plan.classes):
        offs = []
        acc = 0
        for mi, m in enumerate(members):
            class_of[m] = (ci, mi)
            offs.append(acc)
            acc += plan.sizes[ci][mi]
        offsets.append(offs)

    # merged-root classes (mark_rootslice): members tile one contiguous
    # slice range (plan ordered them by lo), so the whole class is one
    # call of the family's fn over [lo0, hiN) of the shared graph input
    merged_root: Dict[int, Any] = {}
    for ci, members in enumerate(plan.classes):
        fn0, _, aids0 = step_info[members[0]]
        if not aids0:
            fam, lo0, _, make = rootslice_of(fn0)
            _, _, hiN, _ = rootslice_of(step_info[members[-1]][0])
            merged_root[ci] = make(lo0, hiN)

    # single tasks that are declared axis-0 concats of exactly one
    # batched class's members in order: identity on the batched value
    concat_passthrough: Dict[str, int] = {}
    members_of = {tuple(m): ci for ci, m in enumerate(plan.classes)}
    for t in tids:
        fn, _, aids = step_info[t]
        if (
            fn is not None
            and is_concat0(fn)
            and t not in class_of
            and aids
            and tuple(aids) in members_of
        ):
            concat_passthrough[t] = members_of[tuple(aids)]

    def seg_fn(seg_params, ext):
        singles: Dict[str, Any] = {}
        class_val: Dict[int, Any] = {}

        def value_of(d):
            if d in singles:
                return singles[d]
            if d in class_of:
                ci, mi = class_of[d]
                lo = offsets[ci][mi]
                return class_val[ci][lo:lo + plan.sizes[ci][mi]]
            return ext[d]

        for kind, val in plan.units:
            if kind == "single":
                t = val
                fn, pitems, aids = step_info[t]
                if t in concat_passthrough:
                    # declared axis-0 concat of exactly one batched
                    # class's members in order: the batched value IS the
                    # result — skip the slice-and-recopy round-trip
                    singles[t] = class_val[concat_passthrough[t]]
                    continue
                pd = {loc: seg_params[g] for loc, g in pitems}
                args = (
                    [value_of(d) for d in aids]
                    if aids else [ext["__input__"]]
                )
                singles[t] = fn(pd, *args)
            else:
                ci = val
                members = plan.classes[ci]
                fn, pitems, _ = step_info[members[0]]
                pd = {loc: seg_params[g] for loc, g in pitems}
                if ci in merged_root:
                    # root class: one family call over the merged slice
                    # of the shared graph input
                    class_val[ci] = merged_root[ci](pd, ext["__input__"])
                    continue
                args = []
                for j, srcs in enumerate(plan.arg_sources[ci]):
                    cj = plan.arg_class[ci][j]
                    if cj is not None and cj in class_val:
                        args.append(class_val[cj])
                    else:
                        args.append(
                            jnp.concatenate(
                                [value_of(d) for d in srcs], axis=0
                            )
                        )
                class_val[ci] = fn(pd, *args)
        return {t: value_of(t) for t in exports}

    return seg_fn
