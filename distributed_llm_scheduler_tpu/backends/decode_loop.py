"""On-device multi-step decode over a scheduled decode-step DAG.

The task-graph decode path's end-to-end rate was owned by the host: one
dispatch + one token readback per step costs a full device round-trip
(71 ms/step through the tunnel — ``DECODE_r04.json.task_graph``: 11.25
tok/s against a 1.73 ms device-side step).  This module folds K decode
steps into ONE dispatched XLA program: the step DAG's tasks are composed
in the schedule's assignment order into a single traced step function
(the same composition the segment-fused dispatch mode runs — the
placement still comes from the scheduler), each layer's ``k_new``/
``v_new`` is folded into its cache slab in-graph, and ``lax.scan``
iterates the step with the cache buffers donated.  The host pays one
round-trip per K tokens instead of per token (VERDICT r4 next #6).

Single-node placements only: a multi-node placement needs per-step
host-mediated transfers, which is exactly the per-task dispatch path
(``DeviceBackend.execute``); this loop exists to amortize the host out
of the single-device steady state.

Reference anchor: the scheduler-owns-inference story is this repo's own
(``frontend/decode_dag.py``); the reference has no execution path at all
(reference ``simulation.py:216-278`` replays schedules against constants).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp

from ..core.graph import TaskGraph
from ..core.schedule import Schedule
from ..frontend.decode_dag import cache_dims


def compose_step_fn(
    graph: TaskGraph,
    schedule: Schedule,
    config: Any,
) -> Callable[[Dict[str, Any], Dict[str, Any], jax.Array, jax.Array],
              Tuple[jax.Array, Dict[str, Any]]]:
    """Compose the placed decode-step DAG into one traced step function.

    Tasks run in the schedule's assignment order (dependency-valid by
    construction), params resolve through each task's alias table, and
    the per-layer cache updates are folded with ``dynamic_update_slice``
    at the traced position — the functional step advance that
    ``apply_cache_updates`` performs on the host, moved in-graph.

    Returns ``step(weights, caches, ids, pos) -> (logits, new_caches)``.
    """
    placement = schedule.placement
    nodes = {placement[tid] for tid in placement}
    if len(nodes) > 1:
        raise ValueError(
            f"decode loop requires a single-node placement, got {len(nodes)} "
            "nodes — multi-node decode steps go through per-task dispatch "
            "(DeviceBackend.execute)"
        )
    # assignment order re-linearized topologically: validate_schedule only
    # guarantees a permutation, not producer-before-consumer (the device
    # backend re-linearizes through dispatch_order for the same reason)
    topo_pos = {tid: i for i, tid in enumerate(graph.topo_order)}
    order = sorted(
        (tid for tid in schedule.assignment_order if tid in placement),
        key=topo_pos.__getitem__,
    )
    missing = set(graph.task_ids()) - set(order)
    if missing:
        raise ValueError(f"placement does not cover tasks {sorted(missing)}")
    sinks = [tid for tid in order if not graph.dependents(tid)]
    if len(sinks) != 1:
        raise ValueError(f"expected one sink (logits) task, got {sinks}")
    sink = sinks[0]
    n_layers, _, _ = cache_dims(config)

    def step(weights, caches, ids, pos):
        inputs = {"ids": ids, "pos": pos}
        outs: Dict[str, Any] = {}
        for tid in order:
            task = graph[tid]
            alias = task.param_alias or {}
            p = {
                loc: (caches[glob] if glob in caches else weights[glob])
                for loc, glob in alias.items()
            }
            if task.dependencies:
                args = [outs[d] for d in (task.arg_tasks or task.dependencies)]
            else:
                args = [inputs]
            outs[tid] = task.fn(p, *args)
        logits = outs[sink]
        new_caches = dict(caches)
        for i in range(n_layers):
            o = outs[f"layer_{i}"]
            for kind in ("k", "v"):
                buf = new_caches[f"cache_{kind}_{i}"]
                new_caches[f"cache_{kind}_{i}"] = jax.lax.dynamic_update_slice(
                    buf, o[f"{kind}_new"].astype(buf.dtype),
                    (jnp.int32(0), jnp.int32(0), pos, jnp.int32(0)),
                )
        return logits, new_caches

    return step


def build_decode_loop(
    graph: TaskGraph,
    schedule: Schedule,
    config: Any,
    steps: int,
) -> Callable[[Dict[str, Any], Dict[str, Any], jax.Array, jax.Array],
              Tuple[jax.Array, Dict[str, Any]]]:
    """Jit one program that greedily decodes ``steps`` tokens through the
    scheduled step DAG, cache buffers donated.

    ``run(weights, caches, ids, pos) -> (tokens, new_caches)`` where
    ``ids`` is the (B, 1) current token, ``pos`` the current cache
    position, and ``tokens`` the (B, steps) greedy continuation.  The
    caller chains calls by feeding the returned caches (and
    ``tokens[:, -1:]`` / ``pos + steps``) back in; donation makes the
    chain allocation-free on device.
    """
    step = compose_step_fn(graph, schedule, config)

    def run(weights, caches, ids, pos):
        def body(carry, _):
            ids, pos, caches = carry
            logits, caches = step(weights, caches, ids, pos)
            # same argmax the whole-program loop runs (models/decode.py
            # sample_token at temperature 0: bf16 logits, no f32 cast)
            nxt = jnp.argmax(
                logits[:, -1, :], axis=-1
            ).astype(jnp.int32)[:, None]
            return (nxt, pos + 1, caches), nxt[:, 0]

        (_, _, caches2), toks = jax.lax.scan(
            body, (ids, pos, caches), None, length=steps
        )
        return toks.T, caches2  # (B, steps)

    return jax.jit(run, donate_argnums=(1,))


def split_cache_params(
    params: Dict[str, Any],
) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """(weights, caches) views of a decode-DAG param dict."""
    weights = {k: v for k, v in params.items() if not k.startswith("cache_")}
    caches = {k: v for k, v in params.items() if k.startswith("cache_")}
    return weights, caches
