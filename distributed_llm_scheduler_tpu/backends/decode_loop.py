"""On-device multi-step decode over a scheduled decode-step DAG.

The task-graph decode path's end-to-end rate was owned by the host: one
dispatch + one token readback per step costs a full device round-trip
(71 ms/step through the tunnel — ``DECODE_r04.json.task_graph``: 11.25
tok/s against a 1.73 ms device-side step).  This module folds K decode
steps into ONE dispatched XLA program: the step DAG's tasks are composed
in the schedule's assignment order into a single traced step function
(the same composition the segment-fused dispatch mode runs — the
placement still comes from the scheduler), each layer's ``k_new``/
``v_new`` is folded into its cache slab in-graph, and ``lax.scan``
iterates the step with the cache buffers donated.  The host pays one
round-trip per K tokens instead of per token (VERDICT r4 next #6).

Single-node placements only: a multi-node placement needs per-step
host-mediated transfers, which is exactly the per-task dispatch path
(``DeviceBackend.execute``); this loop exists to amortize the host out
of the single-device steady state.

Reference anchor: the scheduler-owns-inference story is this repo's own
(``frontend/decode_dag.py``); the reference has no execution path at all
(reference ``simulation.py:216-278`` replays schedules against constants).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..core.graph import TaskGraph
from ..core.schedule import Schedule
from ..frontend.decode_dag import cache_dims


def compose_step_fn(
    graph: TaskGraph,
    schedule: Schedule,
    config: Any,
) -> Callable[[Dict[str, Any], Dict[str, Any], jax.Array, jax.Array],
              Tuple[jax.Array, Dict[str, Any]]]:
    """Compose the placed decode-step DAG into one traced step function.

    Tasks run in the schedule's assignment order (dependency-valid by
    construction), params resolve through each task's alias table, and
    the per-layer cache updates are folded with ``dynamic_update_slice``
    at the traced position — the functional step advance that
    ``apply_cache_updates`` performs on the host, moved in-graph.

    Returns ``step(weights, caches, ids, pos) -> (logits, new_caches)``.
    """
    placement = schedule.placement
    nodes = {placement[tid] for tid in placement}
    if len(nodes) > 1:
        raise ValueError(
            f"decode loop requires a single-node placement, got {len(nodes)} "
            "nodes — multi-node decode steps go through per-task dispatch "
            "(DeviceBackend.execute)"
        )
    # assignment order re-linearized topologically: validate_schedule only
    # guarantees a permutation, not producer-before-consumer (the device
    # backend re-linearizes through dispatch_order for the same reason)
    topo_pos = {tid: i for i, tid in enumerate(graph.topo_order)}
    order = sorted(
        (tid for tid in schedule.assignment_order if tid in placement),
        key=topo_pos.__getitem__,
    )
    missing = set(graph.task_ids()) - set(order)
    if missing:
        raise ValueError(f"placement does not cover tasks {sorted(missing)}")
    sinks = [tid for tid in order if not graph.dependents(tid)]
    if len(sinks) != 1:
        raise ValueError(f"expected one sink (logits) task, got {sinks}")
    sink = sinks[0]
    n_layers, _, _ = cache_dims(config)

    def step(weights, caches, ids, pos):
        inputs = {"ids": ids, "pos": pos}
        outs: Dict[str, Any] = {}
        for tid in order:
            task = graph[tid]
            alias = task.param_alias or {}
            p = {
                loc: (caches[glob] if glob in caches else weights[glob])
                for loc, glob in alias.items()
            }
            if task.dependencies:
                args = [outs[d] for d in (task.arg_tasks or task.dependencies)]
            else:
                args = [inputs]
            outs[tid] = task.fn(p, *args)
        logits = outs[sink]
        new_caches = dict(caches)
        for i in range(n_layers):
            o = outs[f"layer_{i}"]
            for kind in ("k", "v"):
                buf = new_caches[f"cache_{kind}_{i}"]
                new_caches[f"cache_{kind}_{i}"] = jax.lax.dynamic_update_slice(
                    buf, o[f"{kind}_new"].astype(buf.dtype),
                    (jnp.int32(0), jnp.int32(0), pos, jnp.int32(0)),
                )
        return logits, new_caches

    return step


def build_decode_loop(
    graph: TaskGraph,
    schedule: Schedule,
    config: Any,
    steps: int,
) -> Callable[[Dict[str, Any], Dict[str, Any], jax.Array, jax.Array],
              Tuple[jax.Array, Dict[str, Any]]]:
    """Jit one program that greedily decodes ``steps`` tokens through the
    scheduled step DAG, cache buffers donated.

    ``run(weights, caches, ids, pos) -> (tokens, new_caches)`` where
    ``ids`` is the (B, 1) current token, ``pos`` the current cache
    position, and ``tokens`` the (B, steps) greedy continuation.  The
    caller chains calls by feeding the returned caches (and
    ``tokens[:, -1:]`` / ``pos + steps``) back in; donation makes the
    chain allocation-free on device.
    """
    step = compose_step_fn(graph, schedule, config)

    def run(weights, caches, ids, pos):
        def body(carry, _):
            ids, pos, caches = carry
            logits, caches = step(weights, caches, ids, pos)
            # same argmax the whole-program loop runs (models/decode.py
            # sample_token at temperature 0: bf16 logits, no f32 cast)
            nxt = jnp.argmax(
                logits[:, -1, :], axis=-1
            ).astype(jnp.int32)[:, None]
            return (nxt, pos + 1, caches), nxt[:, 0]

        (_, _, caches2), toks = jax.lax.scan(
            body, (ids, pos, caches), None, length=steps
        )
        return toks.T, caches2  # (B, steps)

    return jax.jit(run, donate_argnums=(1,))


def split_cache_params(
    params: Dict[str, Any],
) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """(weights, caches) views of a decode-DAG param dict."""
    weights = {k: v for k, v in params.items() if not k.startswith("cache_")}
    caches = {k: v for k, v in params.items() if k.startswith("cache_")}
    return weights, caches


def _placed_order(graph: TaskGraph, schedule: Schedule) -> list:
    """Schedule assignment order, single-node-validated and re-linearized
    topologically (shared by the dense and paged step composers)."""
    placement = schedule.placement
    nodes = {placement[tid] for tid in placement}
    if len(nodes) > 1:
        raise ValueError(
            f"decode loop requires a single-node placement, got {len(nodes)} "
            "nodes — multi-node decode steps go through per-task dispatch "
            "(DeviceBackend.execute)"
        )
    topo_pos = {tid: i for i, tid in enumerate(graph.topo_order)}
    order = sorted(
        (tid for tid in schedule.assignment_order if tid in placement),
        key=topo_pos.__getitem__,
    )
    missing = set(graph.task_ids()) - set(order)
    if missing:
        raise ValueError(f"placement does not cover tasks {sorted(missing)}")
    sinks = [tid for tid in order if not graph.dependents(tid)]
    if len(sinks) != 1:
        raise ValueError(f"expected one sink (logits) task, got {sinks}")
    return order


def compose_paged_step_fn(
    graph: TaskGraph,
    schedule: Schedule,
    config: Any,
) -> Callable[..., Tuple[jax.Array, Dict[str, Any]]]:
    """Compose the placed PAGED decode-step DAG (``build_paged_decode_dag``)
    into one traced step function.

    Same contract as :func:`compose_step_fn` — tasks run in the
    schedule's order, placement stays scheduler-owned — but the cache
    params are shared page pools, positions are the per-slot ``lengths``
    vector, and the per-layer fold is a page-table-directed scatter
    (:func:`...models.kv_pages.write_token_kv`) gated by the ``active``
    mask: inactive slots (retired or not yet admitted) write the trash
    page, so one compiled step serves every admission/retirement state.

    Returns ``step(weights, pools, page_table, ids, lengths, active)
    -> (logits, new_pools)``.
    """
    from ..models.kv_pages import write_token_kv

    order = _placed_order(graph, schedule)
    sink = [tid for tid in order if not graph.dependents(tid)][0]
    n_layers, _, _ = cache_dims(config)

    def step(weights, pools, page_table, ids, lengths, active):
        inputs = {"ids": ids, "lengths": lengths}
        outs: Dict[str, Any] = {}
        for tid in order:
            task = graph[tid]
            alias = task.param_alias or {}
            p = {}
            for loc, glob in alias.items():
                if glob == "page_table":
                    p[loc] = page_table
                elif glob in pools:
                    p[loc] = pools[glob]
                else:
                    p[loc] = weights[glob]
            if task.dependencies:
                args = [outs[d] for d in (task.arg_tasks or task.dependencies)]
            else:
                args = [inputs]
            outs[tid] = task.fn(p, *args)
        logits = outs[sink]
        new_pools = dict(pools)
        for i in range(n_layers):
            o = outs[f"layer_{i}"]
            for kind in ("k", "v"):
                new_pools[f"cache_{kind}_{i}"] = write_token_kv(
                    new_pools[f"cache_{kind}_{i}"], o[f"{kind}_new"],
                    page_table, lengths, active,
                )
        return logits, new_pools

    return step


def build_paged_decode_loop(
    graph: TaskGraph,
    schedule: Schedule,
    config: Any,
    steps: int,
    weights: Optional[Dict[str, Any]] = None,
) -> Callable[..., Tuple[jax.Array, Dict[str, Any]]]:
    """Jit one K-step greedy segment over the scheduled paged step DAG,
    page pools donated.

    ``seg(weights, pools, page_table, lengths, cur_tok, remaining) ->
    (tokens, new_pools)`` where ``cur_tok`` is each slot's (S, 1)
    current token, ``remaining`` the (S,) int32 decode steps each slot
    still owes, and ``tokens`` the (S, steps) greedy continuation (rows
    past a slot's ``remaining`` are garbage — the caller truncates).
    Slots stay active exactly while ``remaining > 0``: lengths stop
    advancing and pool writes divert to the trash page the step after a
    slot finishes, so admission and retirement between segments never
    recompile — the shapes are the static ``slots`` geometry, only array
    contents change.

    Pass ``weights`` to BIND them into the compiled program as
    captured constants: the returned callable drops the leading
    ``weights`` argument (``seg(pools, page_table, ...)``), and every
    call skips flattening the weight pytree — measurable per-call
    overhead at serving segment rates.  The engine always binds; the
    unbound form exists for callers that swap weights between calls.
    """
    step = compose_paged_step_fn(graph, schedule, config)

    def seg(weights, pools, page_table, lengths, cur_tok, remaining):
        def body(carry, _):
            pools, lengths, cur_tok, remaining = carry
            active = remaining > 0
            logits, pools = step(
                weights, pools, page_table, cur_tok, lengths, active
            )
            nxt = jnp.argmax(
                logits[:, -1, :], axis=-1
            ).astype(jnp.int32)[:, None]
            cur_tok = jnp.where(active[:, None], nxt, cur_tok)
            lengths = lengths + active.astype(jnp.int32)
            remaining = jnp.maximum(remaining - 1, 0)
            return (pools, lengths, cur_tok, remaining), nxt[:, 0]

        (pools2, _, _, _), toks = jax.lax.scan(
            body, (pools, lengths, cur_tok, remaining), None, length=steps
        )
        # slot state is NOT returned: the host reconstructs lengths /
        # cur_tok / remaining from ``toks`` exactly (they're deterministic
        # functions of the emitted tokens), saving per-segment readbacks
        return toks.T, pools2

    if weights is not None:
        w = weights
        return jax.jit(
            lambda pools, page_table, lengths, cur_tok, remaining: seg(
                w, pools, page_table, lengths, cur_tok, remaining
            ),
            donate_argnums=(0,),
        )
    return jax.jit(seg, donate_argnums=(1,))


class PagedDecodeEngine:
    """Continuous-batching paged decode: admit and retire variable-length
    requests between scanned K-step segments.

    The serving loop the dense path cannot run: ``slots`` static batch
    lanes share one paged KV pool; a host-side :class:`...models.kv_pages.
    PagePool` free-list hands each admitted request exactly the pages its
    ``prompt + max_new`` horizon needs (exhaustion leaves requests queued
    — backpressure, not corruption); retirement returns them.  Between
    segments the host folds results, frees, and admits; the segment
    itself is ONE dispatched XLA program (``build_paged_decode_loop``,
    pools donated), so steady-state decode pays one host round-trip per
    ``seg_steps`` tokens across ALL active requests — and because slot
    state is data, not shape, admission never recompiles.

    Placement stays scheduler-owned: the engine composes the placed
    paged decode-step DAG, exactly like the dense loop.  Construct via
    ``DeviceBackend.paged_decode_engine`` to run the pre-execution
    analysis gate first.
    """

    def __init__(
        self,
        graph: TaskGraph,
        schedule: Schedule,
        config: Any,
        weights: Dict[str, Any],
        pool: Any,
        slots: int,
        pages_per_seq: int,
        seg_steps: int = 8,
        tracer: Any = None,
        metrics: Any = None,
        clock: Any = None,
        memprof: Any = None,
        flight: Any = None,
        attention_impl: Optional[str] = None,
        chunk_tokens: Optional[int] = None,
    ):
        import numpy as np

        from ..frontend.decode_dag import cache_dims as _cd
        from ..models.kv_pages import TRASH_PAGE, init_paged_kv
        from ..obs import (
            MetricsRegistry,
            RequestLog,
            RequestTraceRecorder,
            TeeTracer,
            ambient_flight,
            ambient_metrics,
            ambient_tracer,
            resolve_clock,
        )

        self.config = config
        self.weights = weights
        self.pool = pool
        self.slots = slots
        self.pages_per_seq = pages_per_seq
        # the impl is baked into the graph's layer tasks at DAG build
        # time; the engine records it so (a) the prefill compile-class
        # key can never alias programs traced from differently-dispatched
        # graphs and (b) summary()/benches can report which path ran
        self.attention_impl = (
            attention_impl if attention_impl is not None
            else getattr(graph, "attention_impl", None)
        )
        self.page_size = pool.page_size
        self.capacity = pages_per_seq * pool.page_size
        self.seg_steps = seg_steps
        # chunked prefill: prompts longer than this admit in fixed-token
        # chunks co-scheduled with decode segments instead of one whole-
        # prompt wave.  None (the default) keeps whole-prompt admission
        # — every pre-chunking workload is bit-identical.  Mutable: the
        # serve bench toggles it between legs like ``pool.sharing``.
        if chunk_tokens is not None and chunk_tokens < 1:
            raise ValueError(
                f"chunk_tokens must be >= 1, got {chunk_tokens}"
            )
        self.chunk_tokens = chunk_tokens
        # per-slot in-progress prefill state: slot -> {rid, ids (np
        # (1, P)), P, max_new, next} where ``next`` is the count of
        # prompt tokens already prefilled+scattered.  The slot is
        # occupied (``_slot_req`` set) but decodes nothing
        # (``remaining == 0`` diverts its segment writes to the trash
        # page) until the last chunk folds.
        self._chunk_state: Dict[int, Dict[str, Any]] = {}
        self._chunk_rr = 0
        # drain seam (fleet failover): while set, submit() hard-rejects
        # new work — already-queued and in-flight requests keep running
        # to completion, which is what lets a sick replica empty itself
        # before a restart.  Cleared by reset()/rebind_obs().
        self._draining = False
        # virtual-time seam: when set, called with the REAL token count
        # right before every prefill dispatch (whole wave, stitched
        # tail, or chunk) so a VirtualClock frontend can charge prefill
        # compute time proportional to tokens.  None costs nothing.
        self.prefill_time_charge: Optional[Callable[[int], None]] = None
        self._np = np
        n_layers, n_kv, hd = _cd(config)
        self.n_layers = n_layers
        self._seg = build_paged_decode_loop(
            graph, schedule, config, seg_steps, weights=weights
        )
        # device state: ONLY the pools live on device (donated through
        # every call); slot bookkeeping stays host-side numpy — lengths /
        # cur_tok / remaining are deterministic functions of the emitted
        # tokens, so keeping them on host avoids a flurry of tiny .at[]
        # dispatches per admission and per-segment readbacks (at serving
        # granularity that overhead was the whole paged-vs-dense margin)
        self.pools = init_paged_kv(
            n_layers, pool.n_pages, pool.page_size, n_kv, hd, config.dtype
        )
        self.page_table = np.full(
            (slots, pages_per_seq), TRASH_PAGE, np.int32
        )
        self.lengths = np.zeros((slots,), np.int32)
        self.cur_tok = np.zeros((slots, 1), np.int32)
        self.remaining = np.zeros((slots,), np.int32)
        # host state
        self._queue: list = []
        self._slot_req: list = [None] * slots   # request id per busy slot
        self._slot_pages: list = [[] for _ in range(slots)]
        self._tokens: Dict[Any, list] = {}
        self.results: Dict[Any, Any] = {}
        # compile-class bookkeeping is split in two: `_prefill_cache` is
        # the PER-RUN seen-set (cleared by reset(), so the
        # ``decode.jit_cache_entries`` series a reused engine emits is
        # identical to a fresh build's — the soak determinism gate) and
        # `_prefill_store` holds the compiled executables themselves,
        # which survive reset() so warm reruns never pay XLA again
        self._prefill_cache: Dict[Any, Any] = {}
        self._prefill_store: Dict[Any, Any] = {}
        self.segments_run = 0
        # obs: the tracer is optional (ambient under DLS_TRACE, else off);
        # the registry always exists so benches can snapshot per-engine
        # TTFT/TPOT/occupancy unconditionally — recording happens only at
        # segment boundaries (host side), never inside the scanned program
        self.tracer = tracer if tracer is not None else ambient_tracer()
        self.metrics = (
            metrics if metrics is not None
            else (ambient_metrics() or MetricsRegistry())
        )
        # injectable clock (tests script TTFT/TPOT deterministically);
        # reads happen between dispatches, so the shared obs default
        # keeps the engine on the host tracer's timebase
        self._clock = resolve_clock(clock)
        self._submit_t: Dict[Any, float] = {}     # rid -> submit() time
        self._first_tok_t: Dict[Any, float] = {}  # rid -> first-token time
        # flight recorder (explicit, or ambient under DLS_FLIGHT): its
        # ring tracer joins the span stream — alone when no tracer was
        # wired, teed alongside an explicit/ambient one otherwise
        self.flight = flight if flight is not None else ambient_flight()
        if self.flight is not None:
            if self.tracer is None:
                self.tracer = self.flight.tracer
            else:
                self.tracer = TeeTracer(self.tracer, self.flight.tracer)
        # per-request waterfall recorder: rides the tracer, inheriting
        # its None-guard contract — no tracer, no recorder, no work
        self.reqtrace = (
            RequestTraceRecorder(self.tracer)
            if self.tracer is not None else None
        )
        # request lifecycle log: always on, like the registry — recording
        # is a dict write per lifecycle seam, host side, outside the
        # scanned program.  Timestamps are the SAME clock reads the
        # ttft/tpot histograms observe (bitwise-match contract).
        self.reqlog = RequestLog(clock=self._clock)
        self._reqlogs = self._req_sinks()
        # memory doctor: per-request KV page occupancy folds onto the
        # profiler's timeline as kv_pages-bucket allocations (born at
        # admission, freed at retirement) sized by the physical page —
        # page_size rows x (Hkv, hd) x k+v x n_layers.  Explicit only;
        # None costs nothing (every record below is None-guarded).
        self.memprof = memprof
        # page-ownership event seam (analysis/page_pass): None by default
        # — every record site below is None-guarded, so the bare engine
        # is bit-identical to an instrumented one.  Wire it with
        # attach_ownership_log() or rebind_obs(ownlog=...).
        self.ownlog = None
        self._page_bytes = (
            n_layers * 2 * pool.page_size * n_kv * hd
            * np.dtype(config.dtype).itemsize
        )
        # the pools are one placed slab: attribute kv pages to the node
        # the schedule put the decode step on
        self._mem_node = next(iter(schedule.placement.values()), "node0")

    def _req_sinks(self):
        """The engine's full log plus (when wired) the flight ring."""
        if self.flight is not None:
            return (self.reqlog, self.flight.reqlog)
        return (self.reqlog,)

    def attach_ownership_log(self, log: Any) -> None:
        """Wire (or, with ``None``, unwire) the append-only
        page-ownership event seam (:class:`...models.kv_pages.
        PageOwnershipLog`).

        The engine records the owner-attributed ``assign``/``release``
        events at its lifecycle edges; the pool itself records the
        low-level ``alloc``/``free`` events with the tiling counts —
        fault injectors wrap the pool in a delegating proxy, so the
        recorder is planted on the INNER pool (the proxy's withheld
        pages then surface as allocs that never see a free, which is
        exactly what the prover flags)."""
        self.ownlog = log
        pool = self.pool
        inner = getattr(pool, "_inner", None)
        if inner is not None:
            pool = inner
        pool.ownlog = log
        if log is not None and getattr(log, "n_pages", None) is None:
            log.n_pages = pool.n_pages

    def reset(self) -> None:
        """Fresh pool/table/queue state, compiled programs kept.

        The segment, prefill, and scatter executables are keyed to this
        instance (``_prefill_store``), so benchmarks warm up once, reset,
        and re-time the exact workload without paying compilation again.
        The per-run seen-set ``_prefill_cache`` IS cleared: the
        ``decode.jit_cache_entries`` series counts compile classes seen
        *this run*, and a reused engine must emit the same series a fresh
        build would."""
        from ..models.kv_pages import TRASH_PAGE, init_paged_kv

        self._prefill_cache = {}

        np = self._np
        for s, pages in enumerate(self._slot_pages):
            if pages:
                self._release_pages(pages, str(self._slot_req[s]), "reset")
                if self.memprof is not None:
                    self.memprof.free(
                        self._mem_node, f"kv:{self._slot_req[s]}"
                    )
        # the KV arrays below are REBUILT, so retained prefix intern
        # entries would point at zeroed pages — and a warm cache makes
        # same-seed repeat runs diverge.  Fault-injector wrappers may
        # not expose the method; pristine pools always do.
        drop = getattr(self.pool, "drop_cached", None)
        if drop is not None:
            drop()
        n_layers = self.n_layers
        n_kv, hd = self.pools["cache_k_0"].shape[2:]
        self.pools = init_paged_kv(
            n_layers, self.pool.n_pages, self.pool.page_size, n_kv, hd,
            self.config.dtype,
        )
        self.page_table = np.full(
            (self.slots, self.pages_per_seq), TRASH_PAGE, np.int32
        )
        self.lengths = np.zeros((self.slots,), np.int32)
        self.cur_tok = np.zeros((self.slots, 1), np.int32)
        self.remaining = np.zeros((self.slots,), np.int32)
        self._queue = []
        self._slot_req = [None] * self.slots
        self._slot_pages = [[] for _ in range(self.slots)]
        self._tokens = {}
        self.results = {}
        self.segments_run = 0
        self._submit_t = {}
        self._first_tok_t = {}
        self._chunk_state = {}
        self._chunk_rr = 0
        self._draining = False
        # fresh request log per run (benches reset between reps); the
        # flight ring deliberately survives — it is the always-on
        # last-N record across runs
        from ..obs import RequestLog

        self.reqlog = RequestLog(clock=self._clock)
        self._reqlogs = self._req_sinks()
        if self.reqtrace is not None:
            self.reqtrace.reset()

    def rebind_obs(
        self,
        *,
        clock: Any = None,
        tracer: Any = None,
        metrics: Any = None,
        flight: Any = None,
        memprof: Any = None,
        ownlog: Any = None,
    ) -> None:
        """Re-wire the observability surfaces and wipe run state, keeping
        the compiled executables.

        This is the seam that lets one engine serve many independent legs
        (benches, soaks, test sessions) without re-paying XLA: each leg
        hands in its own clock/tracer/metrics/flight exactly as it would
        to ``__init__``, and gets an engine indistinguishable from a
        fresh build except for the warm ``_prefill_store`` and segment
        executables.  Fault injectors are explicitly undone: a leaky
        pool wrapper is replaced by a pristine :class:`...models.
        kv_pages.PagePool` of the same geometry, and an instance-level
        ``step_segment`` override (jit-churn injection) is popped so the
        class method is reachable again."""
        from ..models.kv_pages import PagePool
        from ..obs import (
            MetricsRegistry,
            RequestLog,
            RequestTraceRecorder,
            TeeTracer,
            ambient_flight,
            ambient_metrics,
            ambient_tracer,
            resolve_clock,
        )

        # same wiring as __init__, in the same order
        self.tracer = tracer if tracer is not None else ambient_tracer()
        self.metrics = (
            metrics if metrics is not None
            else (ambient_metrics() or MetricsRegistry())
        )
        self._clock = resolve_clock(clock)
        self.flight = flight if flight is not None else ambient_flight()
        if self.flight is not None:
            if self.tracer is None:
                self.tracer = self.flight.tracer
            else:
                self.tracer = TeeTracer(self.tracer, self.flight.tracer)
        self.reqtrace = (
            RequestTraceRecorder(self.tracer)
            if self.tracer is not None else None
        )
        self.memprof = memprof
        # undo fault injectors before reset(): a wrapped pool must not
        # receive the stale pages reset() frees, so drop the slot->page
        # bookkeeping and swap in a pristine pool of the same geometry
        self._slot_pages = [[] for _ in range(self.slots)]
        self.pool = PagePool(
            n_pages=self.pool.n_pages, page_size=self.pool.page_size,
            sharing=bool(getattr(self.pool, "sharing", False)),
        )
        self.attach_ownership_log(ownlog)
        # the hook belongs to the leg that set it (a frontend with a
        # virtual clock); a re-bound engine starts uncharged
        self.prefill_time_charge = None
        self.__dict__.pop("step_segment", None)
        # reset() rebuilds pools/tables/reqlog against the just-bound
        # clock and flight sinks
        self.reset()

    # -- prefix sharing ----------------------------------------------------
    @property
    def sharing(self) -> bool:
        """Whether the pool interns prefix chunks (read live off the
        pool, so ``rebind_obs``'s pristine replacement keeps the mode)."""
        return bool(getattr(self.pool, "sharing", False))

    def _release_pages(self, pages, owner: str, site: str) -> None:
        """The ONE page-release path for retire/preempt/reset: records
        the owner-attributed ``release`` (with live refcounts when
        sharing), then drops the reference — last release frees
        physically, earlier ones only decrement.  With sharing off this
        is byte-for-byte the pre-sharing record+free sequence."""
        if self.sharing:
            if self.ownlog is not None:
                self.ownlog.record(
                    "release", pages, owner=owner, site=site,
                    refcounts=[self.pool.refcount(p) for p in pages],
                )
            self.pool.release_ref(pages)
        else:
            if self.ownlog is not None:
                self.ownlog.record(
                    "release", pages, owner=owner, site=site,
                )
            self.pool.free(pages)

    def fresh_pages_needed(self, prompt_ids: Any, max_new_tokens: int) -> int:
        """Pages a request would newly allocate if admitted NOW: its
        ``prompt + max_new`` footprint minus currently-resident shared
        prefix chunks.  The serving frontend's admission check calls
        this so backlog ordering sees the same headroom admission will.
        With sharing off it is exactly ``pages_needed``."""
        from ..models.kv_pages import pages_needed, prefix_chunk_keys

        P = int(prompt_ids.shape[1])
        need = pages_needed(P + max_new_tokens, self.page_size)
        if not self.sharing:
            return need
        h_max = (P - 1) // self.page_size
        keys = prefix_chunk_keys(
            prompt_ids, self.page_size
        )[:h_max]
        h, spages = self.pool.match_prefix(keys)
        # a matched page that is CACHED-FREE (LRU-retained intern entry)
        # still satisfies the prefix, but reviving it consumes one
        # free-list page — count it as physical demand or the headroom
        # check would over-admit and MemoryError mid-wave
        is_cached = getattr(self.pool, "is_cached", None)
        revive = (
            sum(1 for p in spages if is_cached(p))
            if is_cached is not None else 0
        )
        return need - h + revive

    def chunk_eligible(self, prompt_len: int) -> bool:
        """Whether a prompt admits CHUNKED: chunking is on, the prompt
        is longer than one chunk, and the padded chunk grid fits the
        per-slot capacity (the final chunk is padded to ``chunk_tokens``
        rows, so ``ceil(P/chunk) * chunk`` dense-cache rows must exist —
        otherwise the request falls back to whole-prompt admission)."""
        ct = self.chunk_tokens
        if ct is None or prompt_len <= ct:
            return False
        return -(-prompt_len // ct) * ct <= self.capacity

    def admission_pages_needed(
        self, prompt_ids: Any, max_new_tokens: int
    ) -> int:
        """Free-list pages admission must find for this request NOW:
        the first chunk only when it admits chunked (later chunks alloc
        lazily per segment), the fresh-tail footprint otherwise.  The
        serving frontend's backlog check calls this so its headroom
        arithmetic matches the engine allocator's."""
        from ..models.kv_pages import pages_needed

        P = int(prompt_ids.shape[1])
        if self.chunk_eligible(P):
            return pages_needed(
                min(self.chunk_tokens, P), self.page_size
            )
        return self.fresh_pages_needed(prompt_ids, max_new_tokens)

    def is_prefilling(self, rid: Any) -> bool:
        """Whether ``rid`` holds a slot mid-chunked-prefill.  Such a
        request is NOT preemptible — it has produced no resumable
        prefix yet (no first token), so eviction would only waste the
        chunks already scattered."""
        return any(st["rid"] == rid for st in self._chunk_state.values())

    def _ensure_exclusive(self) -> None:
        """Copy-on-write guard before a segment: any page the coming
        writes would land in while other requests still alias it is
        split — a fresh page is allocated, the content copied on device,
        and the shared reference released (alloc-before-release, the
        ordering PGL007 proves).  Structurally unreachable under the
        admission rule (generation always lands in exclusive tail
        pages), but the seam is real: tests force an alias onto a write
        page and the split must keep every request's tokens bitwise."""
        if not self.sharing:
            return
        np = self._np
        for s in range(self.slots):
            if self._slot_req[s] is None or self.remaining[s] <= 0:
                continue
            lo = int(self.lengths[s])
            hi = lo + min(int(self.remaining[s]), self.seg_steps)
            for li in range(lo // self.page_size,
                            (hi - 1) // self.page_size + 1):
                src = int(self.page_table[s, li])
                if self.pool.refcount(src) <= 1:
                    continue
                t_c0 = (self._clock()
                        if self.reqtrace is not None else None)
                dst = self.pool.alloc(1)[0]
                rid = str(self._slot_req[s])
                if self.ownlog is not None:
                    self.ownlog.record(
                        "cow", [src, dst], owner=rid, site="cow",
                        refcounts=[self.pool.refcount(src),
                                   self.pool.refcount(dst)],
                    )
                self.pools = self._cow_copy(
                    self.pools, jnp.int32(src), jnp.int32(dst)
                )
                self.page_table[s, li] = dst
                pages = self._slot_pages[s]
                pages[pages.index(src)] = dst
                self.pool.release_ref([src])
                if self.ownlog is not None:
                    self.ownlog.record(
                        "write", [dst], owner=rid, site="cow",
                        refcounts=[self.pool.refcount(dst)],
                    )
                self.metrics.counter("decode.cow_splits").inc()
                if self.reqtrace is not None:
                    self.reqtrace.cow(rid, t_c0, self._clock(),
                                      src=src, dst=dst)

    @property
    def _cow_copy(self):
        fn = self._prefill_store.get("cow_copy")
        if fn is None:
            def _fn(pools, src, dst):
                new = dict(pools)
                for k in new:
                    new[k] = new[k].at[dst].set(new[k][src])
                return new

            fn = jax.jit(_fn, donate_argnums=(0,))
            self._prefill_store["cow_copy"] = fn
        return fn

    # -- request intake ----------------------------------------------------
    def _emit_queue_depth(self) -> None:
        """The ONE place queue depth reaches both surfaces: the metrics
        gauge and (when tracing) the tracer counter track sample the
        same value at the same event, so they cannot disagree."""
        depth = len(self._queue)
        self.metrics.gauge("decode.queue_depth").set(depth)
        if self.tracer is not None:
            self.tracer.counter("decode.queue_depth", depth)

    # -- drain (fleet failover) --------------------------------------------
    @property
    def draining(self) -> bool:
        """True while the engine rejects new submissions (fleet drain)."""
        return self._draining

    def begin_drain(self) -> None:
        """Stop accepting new work: ``submit()`` raises until the drain
        ends.  Queued and in-flight requests are commitments — they keep
        admitting and decoding to completion, so a draining engine
        empties itself instead of wedging its queue.  Idempotent."""
        if not self._draining:
            self._draining = True
            self.metrics.counter("decode.drains_begun").inc()
            if self.tracer is not None:
                self.tracer.instant(
                    "drain_begin", track="decode", cat="decode",
                    t=self._clock(),
                )

    def end_drain(self) -> None:
        """Re-open submission without a restart (``reset()`` and
        ``rebind_obs()`` also clear the drain flag)."""
        self._draining = False

    # -- pool headroom (ONE surface) ---------------------------------------
    @property
    def free_slots(self) -> int:
        """Batch lanes currently unoccupied."""
        return sum(1 for r in self._slot_req if r is None)

    def page_occupancy(self) -> Dict[str, Any]:
        """Pool headroom as a first-class surface: free/used totals plus
        per-request page counts.  The serving frontend's admission check,
        the engine summary, and the ``decode.page_pool`` metric/trace
        tracks all read THIS dict, so they cannot disagree."""
        per_request = {
            str(self._slot_req[s]): len(self._slot_pages[s])
            for s in range(self.slots)
            if self._slot_req[s] is not None
        }
        occ = {
            "n_pages": self.pool.n_pages - 1,  # page 0 is the trash page
            "free_pages": self.pool.free_pages,
            "used_pages": self.pool.used_pages,
            "per_request": per_request,
        }
        if self.sharing:
            # logical-vs-physical accounting exists only in sharing mode:
            # the disabled engine's occupancy dict stays bitwise-identical
            # to the pre-sharing one
            occ["logical_pages"] = self.pool.logical_pages
            occ["shared_pages"] = self.pool.shared_pages
            occ["per_request_exclusive"] = {
                str(self._slot_req[s]): sum(
                    1 for p in self._slot_pages[s]
                    if self.pool.refcount(p) == 1
                )
                for s in range(self.slots)
                if self._slot_req[s] is not None
            }
        return occ

    def _emit_pool_occupancy(self) -> None:
        """Sample :meth:`page_occupancy` into the ``decode.page_pool``
        gauge and (when tracing) counter track."""
        used = self.page_occupancy()["used_pages"]
        self.metrics.gauge(
            "decode.page_pool_occupancy_pages", unit="pages"
        ).set(used)
        if self.tracer is not None:
            self.tracer.counter("decode.page_pool_occupancy_pages", used)

    def _emit_jit_cache_size(self) -> None:
        """Sample the prefill compile-class cache size per tick — the
        soak doctor's recompile-churn series: a healthy engine closes
        its compile classes during warmup and this gauge goes flat."""
        entries = len(self._prefill_cache)
        self.metrics.gauge(
            "decode.jit_cache_entries", unit="entries"
        ).set(entries)
        if self.tracer is not None:
            self.tracer.counter("decode.jit_cache_entries", entries)

    def summary(self) -> Dict[str, Any]:
        """Engine-state snapshot: slot/queue/pool headroom at this
        segment boundary (what admission policies key off)."""
        out = {
            "slots": self.slots,
            "free_slots": self.free_slots,
            "queued": len(self._queue),
            "in_flight": self.slots - self.free_slots,
            "completed": len(self.results),
            "segments_run": self.segments_run,
            "attention_impl": self.attention_impl or "auto",
            "page_occupancy": self.page_occupancy(),
        }
        if self.sharing:
            out["prefix_sharing"] = True
        if self.chunk_tokens is not None:
            out["chunk_tokens"] = self.chunk_tokens
            out["prefilling"] = len(self._chunk_state)
        if self._draining:
            out["draining"] = True
        return out

    def submit(self, rid: Any, prompt_ids: Any, max_new_tokens: int) -> None:
        """Queue a request; admitted into a free slot (and its pages
        allocated) at the next segment boundary.

        Request ids must be unique for the life of the engine state: a
        duplicate would silently clobber ``_submit_t``/``results`` and
        collide lifecycle-log rows, so it is a hard error.  A PREEMPTED
        rid is also spent — the serving layer re-queues the generated
        prefix under a derived rid (``reset()`` clears everything)."""
        if self._draining:
            raise RuntimeError(
                f"engine is draining: rejecting submit of rid {rid!r}"
            )
        if rid in self.results:
            raise ValueError(f"duplicate rid {rid!r}: already retired")
        if rid in self._tokens:
            raise ValueError(f"duplicate rid {rid!r}: already in flight")
        if any(q[0] == rid for q in self._queue):
            raise ValueError(f"duplicate rid {rid!r}: already queued")
        if self.reqlog.get(rid) is not None:
            raise ValueError(
                f"duplicate rid {rid!r}: already has a lifecycle record"
            )
        prompt_ids = jnp.asarray(prompt_ids, jnp.int32)
        if prompt_ids.ndim != 2 or prompt_ids.shape[0] != 1:
            raise ValueError("prompt_ids must be (1, prompt_len)")
        total = prompt_ids.shape[1] + max_new_tokens
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if total > self.capacity:
            raise ValueError(
                f"request needs {total} rows > per-slot capacity "
                f"{self.capacity} ({self.pages_per_seq} pages x "
                f"{self.page_size})"
            )
        self._queue.append((rid, prompt_ids, max_new_tokens))
        t_sub = self._clock()
        self._submit_t[rid] = t_sub
        for rl in self._reqlogs:
            rl.submit(rid, int(prompt_ids.shape[1]), max_new_tokens, t_sub)
        if self.reqtrace is not None:
            # idempotent: a serving frontend may have registered this
            # rid already at its ARRIVAL anchor; a derived resume rid
            # re-joins the first pass's track
            self.reqtrace.submit(
                rid, t_sub, prompt_len=int(prompt_ids.shape[1]),
                max_new_tokens=max_new_tokens,
            )
        self.metrics.counter("decode.requests_submitted").inc()
        self._emit_queue_depth()

    # -- prefill + page scatter (ONE call per admission ROUND; one
    # compiled class per (prompt length, batch size)) ----------------------
    def _prefill_scatter(self, prompt_ids: jax.Array, pt_rows):
        """Prefill ``b`` same-length prompts and scatter all their cache
        rows into their pages in ONE jitted, pool-donating call.

        ``prompt_ids`` (b, P); ``pt_rows`` (b, pages_per_seq) physical
        page rows (trash-padded tails).  Returns the (b,) first greedy
        tokens.  Weights are bound constants (see the segment fn)."""
        from ..frontend.decode_dag import cache_dims as _cd
        from ..models import decode as _decode
        from ..parallel.decode import _family_of, _module_for

        b, P = prompt_ids.shape
        key = (P, b, self.attention_impl)
        fn = self._prefill_store.get(key)
        if fn is None:
            mod = _module_for(_family_of(self.config))
            n_layers, n_kv, hd = _cd(self.config)
            cap, cfg = self.capacity, self.config
            ppseq, ps = self.pages_per_seq, self.page_size

            w = self.weights  # bound constants, same as the segment fn

            def _fn(ids, pools, pages):
                cache = _decode.init_cache(
                    n_layers, b, n_kv, cap, hd, cfg.dtype
                )
                logits, cache = mod.forward_cached(
                    w, ids, cache, 0, cfg
                )
                first = jnp.argmax(
                    logits[:, -1, :], axis=-1
                ).astype(jnp.int32)
                flat_pages = pages.reshape(b * ppseq)
                new = dict(pools)
                for i in range(n_layers):
                    for kind in ("k", "v"):
                        # (b, cap, Hkv, hd) scatter-ready, page-chunked
                        rows = cache[kind][i].transpose(0, 2, 1, 3)
                        paged = rows.reshape(b * ppseq, ps, n_kv, hd)
                        pool = new[f"cache_{kind}_{i}"]
                        new[f"cache_{kind}_{i}"] = pool.at[flat_pages].set(
                            paged.astype(pool.dtype), mode="drop"
                        )
                return first, new

            fn = jax.jit(_fn, donate_argnums=(1,))
            self._prefill_store[key] = fn
        # seen-set entry even on store hits: a reused engine's first
        # encounter of a compile class this run counts, warm or not
        if key not in self._prefill_cache:
            self._prefill_cache[key] = fn
        if self.prefill_time_charge is not None:
            self.prefill_time_charge(b * P)
        first, self.pools = fn(prompt_ids, self.pools, jnp.asarray(pt_rows))
        return first

    def _prefill_scatter_shared(
        self, prompt_ids: jax.Array, h: int, shared_rows, wt_rows
    ):
        """Stitched prefill for a wave whose first ``h`` prefix pages are
        already resident: gather the shared pages into the dense cache,
        run the transformer over ONLY the tail ``[h*ps, P)`` at
        ``pos_start = h*ps``, and scatter through the write table (shared
        entries diverted to the trash page, so aliased content is never
        re-written).

        Bitwise contract: ``cached_attention`` masks cache columns
        beyond the write cursor AFTER computing scores, so masked
        operand values never reach the output — the same property the
        preemption-resume path proves cross-shape.  Resident rows are
        bitwise what a full prefill would have produced (KV at position
        j depends only on tokens[0..j]), the tail runs the identical
        ``forward_cached`` at a later ``pos_start``, and rows past P
        stay zero exactly as in the unshared path — so first token,
        scattered pages, and every subsequent decode step match the
        unshared run bit for bit.

        ``prompt_ids`` (b, P) FULL prompts (the resident portion is
        sliced off here, keeping the caller symmetric with
        :meth:`_prefill_scatter`); ``shared_rows`` (b, h) physical ids
        of the resident prefix pages; ``wt_rows`` (b, pages_per_seq)
        the write table.  One compile class per ``(P, h, b, impl)``.
        """
        from ..frontend.decode_dag import cache_dims as _cd
        from ..models import decode as _decode
        from ..parallel.decode import _family_of, _module_for

        b, P = prompt_ids.shape
        h = int(h)
        key = ("shared", P, h, b, self.attention_impl)
        fn = self._prefill_store.get(key)
        if fn is None:
            mod = _module_for(_family_of(self.config))
            n_layers, n_kv, hd = _cd(self.config)
            cap, cfg = self.capacity, self.config
            ppseq, ps = self.pages_per_seq, self.page_size
            pre = h * ps

            w = self.weights  # bound constants, same as the segment fn

            def _fn(ids_tail, pools, spages, wpages):
                cache = _decode.init_cache(
                    n_layers, b, n_kv, cap, hd, cfg.dtype
                )
                flat_sh = spages.reshape(b * h)
                for i in range(n_layers):
                    for kind in ("k", "v"):
                        poolarr = pools[f"cache_{kind}_{i}"]
                        rows = jnp.take(poolarr, flat_sh, axis=0)
                        rows = rows.reshape(b, pre, n_kv, hd)
                        rows = rows.transpose(0, 2, 1, 3)  # (b,Hkv,pre,hd)
                        buf = cache[kind]
                        cache[kind] = buf.at[i, :, :, :pre, :].set(
                            rows.astype(buf.dtype)
                        )
                logits, cache = mod.forward_cached(
                    w, ids_tail, cache, pre, cfg
                )
                first = jnp.argmax(
                    logits[:, -1, :], axis=-1
                ).astype(jnp.int32)
                flat_pages = wpages.reshape(b * ppseq)
                new = dict(pools)
                for i in range(n_layers):
                    for kind in ("k", "v"):
                        rows = cache[kind][i].transpose(0, 2, 1, 3)
                        paged = rows.reshape(b * ppseq, ps, n_kv, hd)
                        pool = new[f"cache_{kind}_{i}"]
                        new[f"cache_{kind}_{i}"] = pool.at[flat_pages].set(
                            paged.astype(pool.dtype), mode="drop"
                        )
                return first, new

            fn = jax.jit(_fn, donate_argnums=(1,))
            self._prefill_store[key] = fn
        if key not in self._prefill_cache:
            self._prefill_cache[key] = fn
        tail = prompt_ids[:, h * self.page_size:]
        if self.prefill_time_charge is not None:
            self.prefill_time_charge(b * (P - h * self.page_size))
        first, self.pools = fn(
            tail, self.pools,
            jnp.asarray(shared_rows), jnp.asarray(wt_rows),
        )
        return first

    # -- chunked prefill (co-scheduled with decode segments) ---------------
    def _chunk_prefill(self, ids_chunk, pt_row, base: int, creal: int):
        """Run ONE prefill chunk for one slot: gather the slot's pages
        into a dense per-slot cache, run the transformer over the
        ``chunk_tokens`` chunk at traced ``pos_start = base``, and
        scatter every page back through the slot's table row.

        ONE compile class per ``("chunk", chunk_tokens, 1, impl)`` —
        prompt length, chunk index, and the final chunk's real length
        ``creal`` are all DATA (the final chunk is padded to
        ``chunk_tokens`` with token 0; causal masking keeps pad rows out
        of every real row's scores, and their K/V rows land at positions
        ``>= P`` that stay masked until decode overwrites them).  The
        gather covers ALL ``pages_per_seq`` table entries (trash entries
        gather masked garbage; the scatter-back writes it harmlessly to
        the trash page) so page count is data too.

        Bitwise contract: the dense cache has exactly the per-slot
        ``capacity`` rows a whole-prompt prefill uses, positions
        ``[0, base)`` hold the bytes the earlier chunks scattered, and
        ``forward_cached`` masks cache columns beyond the write cursor
        AFTER the scores — the same stitching argument as
        :meth:`_prefill_scatter_shared`, so the chunk's rows, the final
        logits row, and every downstream decode step match a
        whole-prompt run bit for bit."""
        from ..frontend.decode_dag import cache_dims as _cd
        from ..models import decode as _decode
        from ..parallel.decode import _family_of, _module_for

        key = ("chunk", self.chunk_tokens, 1, self.attention_impl)
        fn = self._prefill_store.get(key)
        if fn is None:
            mod = _module_for(_family_of(self.config))
            n_layers, n_kv, hd = _cd(self.config)
            cap, cfg = self.capacity, self.config
            ppseq, ps = self.pages_per_seq, self.page_size

            w = self.weights  # bound constants, same as the segment fn

            def _fn(ids, pools, pages, pos0, creal):
                cache = _decode.init_cache(
                    n_layers, 1, n_kv, cap, hd, cfg.dtype
                )
                for i in range(n_layers):
                    for kind in ("k", "v"):
                        poolarr = pools[f"cache_{kind}_{i}"]
                        rows = jnp.take(poolarr, pages, axis=0)
                        rows = rows.reshape(1, cap, n_kv, hd)
                        rows = rows.transpose(0, 2, 1, 3)
                        buf = cache[kind]
                        cache[kind] = buf.at[i].set(rows.astype(buf.dtype))
                logits, cache = mod.forward_cached(
                    w, ids, cache, pos0, cfg
                )
                last = jax.lax.dynamic_index_in_dim(
                    logits, creal - 1, 1, keepdims=False
                )
                first = jnp.argmax(last, axis=-1).astype(jnp.int32)
                new = dict(pools)
                for i in range(n_layers):
                    for kind in ("k", "v"):
                        rows = cache[kind][i].transpose(0, 2, 1, 3)
                        paged = rows.reshape(ppseq, ps, n_kv, hd)
                        poolarr = new[f"cache_{kind}_{i}"]
                        new[f"cache_{kind}_{i}"] = poolarr.at[pages].set(
                            paged.astype(poolarr.dtype), mode="drop"
                        )
                return first, new

            fn = jax.jit(_fn, donate_argnums=(1,))
            self._prefill_store[key] = fn
        if key not in self._prefill_cache:
            self._prefill_cache[key] = fn
        if self.prefill_time_charge is not None:
            self.prefill_time_charge(int(creal))
        first, self.pools = fn(
            ids_chunk, self.pools, jnp.asarray(pt_row, jnp.int32),
            jnp.int32(base), jnp.int32(creal),
        )
        return first

    def _admit_chunked(self, s: int) -> None:
        """Admit the queue head into slot ``s`` in CHUNK mode: the slot
        and the FIRST chunk's pages are claimed now; prefill itself
        happens one chunk per segment in :meth:`_advance_chunks`.  The
        slot decodes nothing (``remaining == 0``) until the last chunk
        folds, and first-token delivery fires there."""
        from ..models.kv_pages import TRASH_PAGE, pages_needed

        rid, ids, max_new = self._queue.pop(0)
        P = int(ids.shape[1])
        need = pages_needed(min(self.chunk_tokens, P), self.page_size)
        pages = self.pool.alloc(need)
        t0 = self._clock()
        self._slot_req[s] = rid
        self._slot_pages[s] = list(pages)
        # the WHOLE table row is rewritten: stale entries from the
        # slot's previous occupant would make the chunk prefill's
        # scatter-back land in pages other requests now own
        for i in range(self.pages_per_seq):
            self.page_table[s, i] = (
                pages[i] if i < len(pages) else TRASH_PAGE
            )
        self.lengths[s] = 0
        self.cur_tok[s, 0] = 0
        self.remaining[s] = 0
        self._chunk_state[s] = {
            "rid": rid, "ids": self._np.asarray(ids), "P": P,
            "max_new": max_new, "next": 0,
        }
        if self.memprof is not None:
            # full-horizon footprint, like whole-prompt admission: the
            # profiler tracks the request's eventual residency, not the
            # lazy alloc schedule
            self.memprof.alloc(
                self._mem_node, f"kv:{rid}",
                pages_needed(P + max_new, self.page_size)
                * self._page_bytes,
                "kv_pages",
            )
        if self.ownlog is not None:
            if self.sharing:
                self.ownlog.record(
                    "assign", pages, owner=str(rid), site="admit",
                    refcounts=[self.pool.refcount(p) for p in pages],
                )
            else:
                self.ownlog.record(
                    "assign", pages, owner=str(rid), site="admit"
                )
        for rl in self._reqlogs:
            rl.admit(rid, t0)
        self.metrics.counter("decode.chunk_admitted").inc()
        if self.tracer is not None:
            self.tracer.instant(
                "admit_chunked", track="decode", cat="decode", t=t0,
                rid=str(rid), prompt_len=P,
            )
        if self.reqtrace is not None:
            self.reqtrace.admitted(rid, t0, chunked=True)
        self._emit_pool_occupancy()
        self._emit_queue_depth()

    def _advance_chunks(self, budget: Optional[int] = None) -> int:
        """Advance pending prefills by up to ``budget`` prompt tokens
        this segment — the per-segment prefill token budget that keeps
        a long prompt from starving in-flight decode.  The default
        budget is the segment's own decode-token capacity
        ``slots * seg_steps`` (floored at one chunk so progress is
        always possible): prefill may consume at most as many
        model-forward tokens per segment as the decode work it rides
        alongside.  Round-robin across prefilling slots; a slot whose
        next chunk cannot get its pages stalls (``decode.chunk_stalls``)
        and retries next segment without blocking the others.  Returns
        tokens prefilled."""
        if not self._chunk_state:
            return 0
        from ..models.kv_pages import pages_needed

        ct = self.chunk_tokens
        if budget is None:
            budget = max(ct, self.slots * self.seg_steps)
        advanced = 0
        spent_by: list = []   # rids whose chunks consumed budget here
        order = sorted(self._chunk_state)
        n = len(order)
        rr = self._chunk_rr % n
        for k in range(n):
            if budget <= 0:
                self._trace_budget_stalls(spent_by)
                break
            s = order[(rr + k) % n]
            st = self._chunk_state[s]
            P, base = st["P"], st["next"]
            C = min(ct, P - base)
            if C > budget:
                self._trace_budget_stalls(spent_by)
                break
            final = base + C >= P
            target_rows = P + st["max_new"] if final else base + C
            need = pages_needed(target_rows, self.page_size) - len(
                self._slot_pages[s]
            )
            if need > 0:
                if not self.pool.can_alloc(need):
                    self.metrics.counter("decode.chunk_stalls").inc()
                    if self.tracer is not None:
                        # the counter TOTAL rides the ring so the
                        # flight recorder's chunk_stall trigger can see
                        # sustained growth post hoc
                        self.tracer.counter(
                            "decode.chunk_stalls",
                            self.metrics.counter(
                                "decode.chunk_stalls"
                            ).value,
                        )
                    if self.reqtrace is not None:
                        self.reqtrace.wait(
                            st["rid"], self._clock(), "page_pool",
                            by=[
                                str(r) for r in self._slot_req
                                if r is not None and r != st["rid"]
                            ],
                        )
                    continue
                fresh = self.pool.alloc(need)
                k0 = len(self._slot_pages[s])
                self._slot_pages[s].extend(fresh)
                for i, p in enumerate(fresh):
                    self.page_table[s, k0 + i] = p
                if self.ownlog is not None:
                    if self.sharing:
                        self.ownlog.record(
                            "assign", fresh, owner=str(st["rid"]),
                            site="admit",
                            refcounts=[
                                self.pool.refcount(p) for p in fresh
                            ],
                        )
                    else:
                        self.ownlog.record(
                            "assign", fresh, owner=str(st["rid"]),
                            site="admit",
                        )
            chunk = self._np.zeros((1, ct), self._np.int32)
            chunk[0, :C] = st["ids"][0, base:base + C]
            ev = None
            if self.tracer is not None:
                ev = self.tracer.begin(
                    "prefill_chunk", track="decode", cat="decode",
                    rid=str(st["rid"]), base=base, tokens=C,
                )
            first = self._chunk_prefill(
                jnp.asarray(chunk), self.page_table[s], base, C
            )
            if ev is not None:
                self.tracer.end(ev)
                if self.reqtrace is not None:
                    # same timestamps as the decode-track span: the
                    # waterfall and the engine timeline cannot disagree
                    self.reqtrace.chunk(
                        st["rid"], ev["t0"], ev["t1"], base=base,
                        tokens=C,
                    )
            spent_by.append(str(st["rid"]))
            st["next"] = base + C
            advanced += C
            budget -= C
            self.metrics.counter("decode.chunk_prefill_tokens").inc(C)
            self.metrics.counter("decode.chunk_waves").inc()
            if st["next"] >= P:
                self._fold_chunked(s, st, first)
        self._chunk_rr = (rr + 1) % n
        if advanced:
            self._emit_pool_occupancy()
        return advanced

    def _fold_chunked(self, s: int, st: Dict[str, Any], first) -> None:
        """The LAST chunk folded: its final-row logits are the first
        token, the slot flips from prefilling to decoding, and TTFT
        anchors here — mirroring the whole-prompt admission fold."""
        rid = st["rid"]
        t_done = self._clock()
        self.lengths[s] = st["P"]
        self.cur_tok[s, 0] = int(first[0])
        self.remaining[s] = st["max_new"] - 1
        self._tokens[rid] = [int(first[0])]
        self._first_tok_t[rid] = t_done
        del self._chunk_state[s]
        for rl in self._reqlogs:
            rl.first_token(rid, t_done)
        if self.reqtrace is not None:
            self.reqtrace.first_token(rid, t_done)
        sub_t = self._submit_t.pop(rid, None)
        if sub_t is not None:
            self.metrics.histogram("decode.ttft_s", unit="s").observe(
                t_done - sub_t
            )
        if st["max_new"] == 1:  # the fold produced the only token
            self._retire(s)

    def _trace_budget_stalls(self, spent_by: list) -> None:
        """The per-segment prefill token budget ran out: every chunk
        slot still mid-prefill waits on ``chunk_budget``, charged to
        the requests whose chunks consumed the budget this segment and
        the co-resident decoders the budget is sized around."""
        rt = self.reqtrace
        if rt is None:
            return
        t = self._clock()
        decoders = [
            str(self._slot_req[s]) for s in range(self.slots)
            if self._slot_req[s] is not None and self.remaining[s] > 0
        ]
        by = list(dict.fromkeys(list(spent_by) + decoders))
        for st in self._chunk_state.values():
            rid = str(st["rid"])
            if rid in spent_by or st["next"] >= st["P"]:
                continue
            rt.wait(rid, t, "chunk_budget", by=by)

    def _trace_queue_block(self, cause: str) -> None:
        """Stamp WHY admission stopped onto every queued request's
        waterfall: the head waits on the named resource (aggressors =
        the current residents holding it), everyone behind it waits on
        the head — FIFO head-of-line blocking made visible."""
        rt = self.reqtrace
        if rt is None or not self._queue:
            return
        t = self._clock()
        holders = [str(r) for r in self._slot_req if r is not None]
        head = str(self._queue[0][0])
        rt.wait(head, t, cause, by=holders)
        for entry in self._queue[1:]:
            rt.wait(str(entry[0]), t, "head_of_line", by=[head])

    # -- admission / retirement (between segments) -------------------------
    def _admit(self) -> int:
        """FIFO admission, batched: the longest same-prompt-length prefix
        of the queue that fits the free slots and the page pool is
        prefilled in one call.  Head-of-line blocking is deliberate —
        admission order stays strict FIFO (no starvation of big
        requests), batching only coalesces what FIFO would have admitted
        anyway.

        With prefix sharing the batch key tightens to ``(P, h)``: every
        request in a wave matches the same NUMBER of resident prefix
        chunks (the matched page ids are data, not shape), its page need
        drops to the fresh tail only, and the wave runs the stitched
        prefill that skips the resident portion entirely."""
        from ..models.kv_pages import (
            TRASH_PAGE,
            pages_needed,
            prefix_chunk_keys,
        )

        admitted = 0
        sharing = self.sharing
        while self._queue:
            free_slots = [
                s for s in range(self.slots) if self._slot_req[s] is None
            ]
            if not free_slots:
                self._trace_queue_block("slots_full")
                break
            P = self._queue[0][1].shape[1]
            if self.chunk_eligible(int(P)):
                # long prompt: claim a slot + first-chunk pages only and
                # prefill one chunk per segment (no whole-prompt wave)
                if pages_needed(
                    min(self.chunk_tokens, int(P)), self.page_size
                ) > self.pool.free_pages:
                    self._trace_queue_block("page_pool")
                    break  # backpressure: head waits for frees
                self._admit_chunked(free_slots[0])
                admitted += 1
                continue
            h0 = 0
            if sharing:
                h_max = (P - 1) // self.page_size
                keys0 = prefix_chunk_keys(self._queue[0][1], self.page_size)
                h0, _ = self.pool.match_prefix(keys0[:h_max])
            batch, hits, budget = [], [], self.pool.free_pages
            seen_keys: set = set()
            for rid, ids, max_new in self._queue:
                if ids.shape[1] != P or len(batch) >= len(free_slots):
                    break
                if self.chunk_eligible(int(ids.shape[1])):
                    break  # chunk-eligible twin of a short head: next wave
                if sharing:
                    keys = prefix_chunk_keys(ids, self.page_size)
                    kt = tuple(keys[:h_max])
                    if kt and kt in seen_keys:
                        # same-wave twin: defer it ONE wave so it aliases
                        # the pages this wave is about to intern instead
                        # of prefilling its own copies
                        break
                    h, spages = self.pool.match_prefix(keys[:h_max])
                    if h != h0:
                        break
                    # fresh tail pages, plus one free-list page per
                    # matched page that is cached-free (revival draws
                    # from the free list even though the page is matched)
                    revive = sum(
                        1 for p in spages if self.pool.is_cached(p)
                    )
                    need = pages_needed(
                        ids.shape[1] + max_new, self.page_size
                    ) - h
                    if need + revive > budget:
                        break
                    budget -= revive
                else:
                    need = pages_needed(ids.shape[1] + max_new,
                                        self.page_size)
                if need > budget:
                    break
                budget -= need
                batch.append((rid, ids, max_new, need))
                if sharing:
                    if kt:
                        seen_keys.add(kt)
                    hits.append((spages, keys))
            if not batch:
                self._trace_queue_block("page_pool")
                break  # backpressure: head waits for frees
            del self._queue[:len(batch)]
            ev_wave = None
            if self.tracer is not None:
                ev_wave = self.tracer.begin(
                    "admission_wave", track="decode", cat="decode",
                    requests=len(batch), prompt_len=P,
                )
            pt_rows = self._np.full(
                (len(batch), self.pages_per_seq), TRASH_PAGE, self._np.int32
            )
            wt_rows = sh_rows = None
            if sharing and h0 > 0:
                # write table: shared prefix pages divert the prefill
                # scatter to the trash page (overwriting it is harmless
                # by design); gather table: the resident sources
                wt_rows = pt_rows.copy()
                sh_rows = self._np.zeros(
                    (len(batch), h0), self._np.int32
                )
            page_lists = []
            for j, (rid, _, _, need) in enumerate(batch):
                if sharing:
                    spages, _keys = hits[j]
                    if spages:
                        # share BEFORE alloc: a matched cached-free page
                        # must be revived before alloc pressure can
                        # evict its intern entry out from under us
                        self.pool.share(spages)
                    fresh = self.pool.alloc(need)
                    pages = list(spages) + fresh
                    # intern every FULL prompt page NOW — before the
                    # wave's prefill — so the NEXT wave of this _admit
                    # call (a same-wave twin deferred by the seen_keys
                    # break) aliases these pages instead of re-prefilling
                    # (first writer wins; the prefill that writes the
                    # content runs before any aliasing wave's stitched
                    # gather reads it)
                    for i in range(P // self.page_size):
                        self.pool.register(int(pages[i]), _keys[i])
                    if h0 > 0:
                        wt_rows[j, :len(pages)] = (
                            [TRASH_PAGE] * h0 + fresh
                        )
                        sh_rows[j] = spages
                else:
                    pages = self.pool.alloc(need)
                page_lists.append(pages)
                pt_rows[j, :len(pages)] = pages
                if self.memprof is not None:
                    self.memprof.alloc(
                        self._mem_node, f"kv:{rid}",
                        need * self._page_bytes, "kv_pages",
                    )
                if self.ownlog is not None:
                    if sharing:
                        self.ownlog.record(
                            "assign", pages, owner=str(rid), site="admit",
                            refcounts=[
                                self.pool.refcount(p) for p in pages
                            ],
                        )
                    else:
                        self.ownlog.record(
                            "assign", pages, owner=str(rid), site="admit"
                        )
            # unconditional read: t_pf0 is each batched request's
            # admission timestamp in the lifecycle log
            t_pf0 = self._clock()
            all_ids = jnp.concatenate(
                [ids for _, ids, _, _ in batch], axis=0
            )
            if sharing and h0 > 0:
                first = self._prefill_scatter_shared(
                    all_ids, h0, sh_rows, wt_rows
                )
            else:
                first = self._prefill_scatter(all_ids, pt_rows)
            first = self._np.asarray(first)
            # first token exists NOW (the prefill's readback): the
            # admission timestamp is each request's TTFT anchor
            t_adm = self._clock()
            if self.tracer is not None:
                self.tracer.complete(
                    "prefill", t_pf0, t_adm, track="decode", cat="decode",
                    requests=len(batch), prompt_len=P,
                )
            ttft_h = self.metrics.histogram("decode.ttft_s", unit="s")
            for j, (rid, ids, max_new, _) in enumerate(batch):
                s = free_slots[j]
                self.page_table[s] = pt_rows[j]
                self.lengths[s] = P
                self.cur_tok[s, 0] = int(first[j])
                self.remaining[s] = max_new - 1
                self._slot_req[s] = rid
                self._slot_pages[s] = page_lists[j]
                self._tokens[rid] = [int(first[j])]
                self._first_tok_t[rid] = t_adm
                if sharing:
                    # intern happened pre-prefill (same-wave aliasing);
                    # the prefill physically wrote the fresh pages,
                    # which the write witness records here
                    if self.ownlog is not None:
                        freshp = page_lists[j][h0:]
                        self.ownlog.record(
                            "write", freshp, owner=str(rid), site="admit",
                            refcounts=[
                                self.pool.refcount(p) for p in freshp
                            ],
                        )
                # t_pf0/t_adm are the same floats the histograms see:
                # record-derived TTFT == histogram sample, bitwise
                for rl in self._reqlogs:
                    rl.admit(rid, t_pf0)
                    rl.first_token(rid, t_adm)
                if self.reqtrace is not None:
                    self.reqtrace.admitted(
                        rid, t_pf0, wave=[b[0] for b in batch],
                    )
                    self.reqtrace.prefill(
                        rid, t_pf0, t_adm, tokens=int(P),
                        wave_size=len(batch), shared_pages=h0,
                    )
                    self.reqtrace.first_token(rid, t_adm)
                sub_t = self._submit_t.pop(rid, None)
                if sub_t is not None:
                    ttft_h.observe(t_adm - sub_t)
                if max_new == 1:  # prefill produced the only token
                    self._retire(s)
            admitted += len(batch)
            self.metrics.counter("decode.admission_waves").inc()
            if sharing:
                self.metrics.counter("decode.prefix_shared_pages").inc(
                    h0 * len(batch)
                )
                self.metrics.counter("decode.prefix_tokens_skipped").inc(
                    h0 * self.page_size * len(batch)
                )
            if ev_wave is not None:
                self.tracer.end(ev_wave)
            self._emit_pool_occupancy()
            self._emit_queue_depth()
        return admitted

    def _retire(self, s: int) -> None:
        rid = self._slot_req[s]
        self._release_pages(self._slot_pages[s], str(rid), "retire")
        if self.memprof is not None:
            self.memprof.free(self._mem_node, f"kv:{rid}")
        self.results[rid] = self._np.asarray(
            self._tokens.pop(rid), dtype=self._np.int32
        )
        self._slot_req[s] = None
        self._slot_pages[s] = []
        self.metrics.counter("decode.requests_completed").inc()
        # TPOT = steady-state inter-token gap: last token's arrival (this
        # retire happens at the segment fold that produced it) minus the
        # first token's, over n-1 gaps; single-token requests have none
        n = len(self.results[rid])
        t_first = self._first_tok_t.pop(rid, None)
        # ONE clock read feeds the histogram, the lifecycle log, and the
        # trace marker — record-derived TPOT == histogram sample, bitwise
        t_ret = self._clock()
        if t_first is not None and n > 1:
            self.metrics.histogram("decode.tpot_s", unit="s").observe(
                (t_ret - t_first) / (n - 1)
            )
        for rl in self._reqlogs:
            rl.retire(rid, t_ret)
        if self.tracer is not None:
            self.tracer.instant(
                "retire", track="decode", cat="decode", t=t_ret,
                rid=str(rid), tokens=n,
            )
        if self.reqtrace is not None:
            self.reqtrace.retire(rid, t_ret, tokens=n)

    def preempt(
        self, rid: Any, *, cause: Optional[str] = None, by: Any = None,
    ) -> Dict[str, Any]:
        """Evict an in-flight request: free its pages back to the pool
        and hand the generated prefix to the caller for re-queueing.
        ``cause`` stamps the lifecycle record's terminal cause code
        (e.g. ``preempt_tier0_victim``); ``by`` names the request the
        eviction made room for (the waterfall's interference arrow).

        Preemption is the capacity lever priority scheduling needs: a
        high-tier arrival that cannot be admitted (no free slot, no free
        pages) reclaims a low-tier slot NOW instead of waiting out its
        decode.  No progress is lost — greedy decode is deterministic,
        so re-submitting ``prompt + tokens`` (under a new rid) with the
        returned ``remaining`` budget reproduces the exact continuation
        an unpreempted run of that prompt would generate (asserted by
        ``tests/test_serve.py``).

        Only valid between segments, for a rid currently occupying a
        slot (queued/retired rids raise — nothing to evict).  Returns
        ``{"rid", "tokens", "remaining"}``: ``tokens`` the (k,) int32
        generated prefix (prefill token included), ``remaining`` the
        decode steps still owed.  The lifecycle record ends in the
        terminal ``preempted`` state.
        """
        from ..models.kv_pages import TRASH_PAGE

        slot = next(
            (s for s in range(self.slots) if self._slot_req[s] == rid),
            None,
        )
        if slot is None:
            raise ValueError(f"rid {rid!r} is not in flight")
        if slot in self._chunk_state:
            raise ValueError(
                f"rid {rid!r} is mid-chunked-prefill and not preemptible "
                "(no first token yet — there is no resumable prefix)"
            )
        tokens = self._np.asarray(
            self._tokens.pop(rid), dtype=self._np.int32
        )
        remaining = int(self.remaining[slot])
        self._release_pages(self._slot_pages[slot], str(rid), "preempt")
        if self.memprof is not None:
            self.memprof.free(self._mem_node, f"kv:{rid}")
        self.page_table[slot] = TRASH_PAGE
        self.lengths[slot] = 0
        self.cur_tok[slot, 0] = 0
        self.remaining[slot] = 0
        self._slot_req[slot] = None
        self._slot_pages[slot] = []
        self._first_tok_t.pop(rid, None)
        t_pre = self._clock()
        for rl in self._reqlogs:
            rl.preempt(rid, t_pre, cause)
        self.metrics.counter("decode.requests_preempted").inc()
        if self.tracer is not None:
            self.tracer.instant(
                "preempt", track="decode", cat="decode", t=t_pre,
                rid=str(rid), tokens=int(tokens.shape[0]),
                remaining=remaining,
            )
        if self.reqtrace is not None:
            self.reqtrace.preempt(rid, t_pre, by=by, cause=cause)
        self._emit_pool_occupancy()
        return {"rid": rid, "tokens": tokens, "remaining": remaining}

    # -- the serving loop --------------------------------------------------
    def step_segment(self) -> int:
        """Admit, advance pending prefill chunks (one chunk-token budget
        per segment), run ONE K-step segment, fold tokens, retire
        finished slots.  Returns the number of tokens delivered to
        requests."""
        # in-flight prefills advance BEFORE new admission so chunk
        # slots claim their next pages first (admission would otherwise
        # starve a mid-prefill long of pages every segment); a freshly
        # chunk-admitted request then spends whatever prefill budget is
        # left, so its first chunk still lands this segment
        ct = self.chunk_tokens
        full = (max(ct, self.slots * self.seg_steps)
                if ct is not None else 0)
        spent = self._advance_chunks() if self._chunk_state else 0
        self._admit()
        if (ct is not None and spent < full and any(
                st["next"] == 0 for st in self._chunk_state.values())):
            self._advance_chunks(full - spent)
        owed = self.remaining.copy()
        if not owed.any():
            # nothing to decode: the per-segment prefill throttle
            # protects nobody, so drain pending chunks back-to-back
            # until one folds into decodable work (or all stall on
            # pages) — a lone long prompt prefills at full speed
            while self._chunk_state and not self.remaining.any():
                if not self._advance_chunks():
                    break
            owed = self.remaining.copy()
            if not owed.any():
                return 0
        self._ensure_exclusive()
        t_sg0 = self._clock()
        toks, self.pools = self._seg(
            self.pools, self.page_table, self.lengths,
            self.cur_tok, self.remaining,
        )
        toks = self._np.asarray(toks)  # the one readback per segment
        # the fold timestamp: every token this segment delivered became
        # host-visible at this readback (lifecycle-log delivery events)
        t_sg1 = self._clock()
        if self.tracer is not None:
            self.tracer.complete(
                "segment", t_sg0, t_sg1, track="decode",
                cat="decode", steps=self.seg_steps,
                active=int((owed > 0).sum()),
            )
        if self.reqtrace is not None:
            # per-request decode spans reuse the segment's two hoisted
            # timestamps: the waterfall cannot disagree with the engine
            # timeline, and the bare run reads the clock no extra time
            residents = [
                str(self._slot_req[s]) for s in range(self.slots)
                if self._slot_req[s] is not None and owed[s] > 0
            ]
            for s in range(self.slots):
                rid = self._slot_req[s]
                if rid is None or owed[s] <= 0:
                    continue
                self.reqtrace.segment(
                    rid, t_sg0, t_sg1,
                    tokens=int(min(int(owed[s]), self.seg_steps)),
                    co_resident=residents,
                )
        # slot state advances host-side: each slot ran min(owed, K)
        # active steps, its current token is the last one it emitted
        ran = self._np.minimum(owed, self.seg_steps)
        self.lengths = self.lengths + ran
        self.remaining = self._np.maximum(owed - self.seg_steps, 0)
        delivered = 0
        for s in range(self.slots):
            rid = self._slot_req[s]
            if rid is None:
                continue
            n = int(ran[s])
            if n:
                self._tokens[rid].extend(int(t) for t in toks[s, :n])
                self.cur_tok[s, 0] = toks[s, n - 1]
                delivered += n
                for rl in self._reqlogs:
                    rl.deliver(rid, t_sg1, n)
            # owed == 0 means the slot is mid-chunk-prefill (occupied,
            # decoding nothing yet) — it retires only after its fold
            if 0 < owed[s] <= self.seg_steps:
                self._retire(s)
        self.segments_run += 1
        self.metrics.counter("decode.segments_run").inc()
        self.metrics.counter("decode.tokens_delivered").inc(delivered)
        self._emit_pool_occupancy()
        self._emit_queue_depth()
        self._emit_jit_cache_size()
        return delivered

    def run(self) -> Dict[Any, Any]:
        """Drain the queue and all active slots; returns {rid: np.int32
        tokens} (prompt excluded; exactly ``max_new_tokens`` each)."""
        def _sig():
            # progress signature: any admission, decode step, chunk
            # advance, or retirement changes it.  Two identical
            # consecutive signatures mean NOTHING can ever move again
            # (the engine is deterministic between segments).
            return (
                len(self.results), len(self._queue),
                int(self.lengths.sum()), int(self.remaining.sum()),
                tuple(sorted(
                    (s, st["next"])
                    for s, st in self._chunk_state.items()
                )),
            )

        while self._queue or any(r is not None for r in self._slot_req):
            before = _sig()
            self.step_segment()
            if _sig() == before:
                raise RuntimeError(
                    "engine stalled: queued requests cannot be admitted "
                    f"({self.pool.free_pages} free pages)"
                )
        # every retire returned its pages, so this is 0 on a clean drain —
        # a nonzero value in a snapshot IS the leak check failing
        self.metrics.gauge("decode.pages_leaked", unit="pages").set(
            (self.pool.n_pages - 1) - self.pool.free_pages
        )
        return self.results
