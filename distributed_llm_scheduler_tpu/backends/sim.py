"""Simulated execution backend (CPU-runnable cost-model replay).

Replays a :class:`Schedule` against a cost model and produces per-task
timings plus the reference's metric set.  Two fidelity modes:

* ``fidelity="reference"`` reproduces the reference's replay exactly
  (reference ``simulation.py:216-278``): each node runs its task list
  sequentially at ``compute_time / compute_speed``, cross-node dependency
  waits are ignored, caches start empty, transfers are free.  Kept for
  parity testing against the paper's numbers.
* ``fidelity="full"`` (default) fixes the reference's two acknowledged
  blind spots (SURVEY.md §2 quirks, §5.8): a task cannot start before its
  dependencies *finish* (even on other nodes), and both parameter loads
  (host→device) and cross-node activation edges (device→device) are charged
  at configurable bandwidths.  This is the model the TPU backend's measured
  timings calibrate.

Cache hit/miss accounting replays each node's param cache fresh, as the
reference does, so hit-rate numbers are comparable across modes.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Set

from ..core.cluster import Cluster
from ..core.graph import TaskGraph
from ..core.schedule import Schedule, TaskTiming


@dataclass
class LinkModel:
    """Bandwidth/latency model for data movement, GB and seconds.

    Defaults approximate a v5e slice: ~1 TB/s effective ICI per link for
    core-to-core activation hops, ~50 GB/s host-to-HBM for parameter loads
    (PCIe-ish), plus a per-transfer latency floor.  The reference charges
    zero for both (paper §6.6.1 acknowledges this); set both bandwidths to
    ``None`` to reproduce that.
    """

    param_load_gbps: Optional[float] = 50.0
    interconnect_gbps: Optional[float] = 1000.0
    latency_s: float = 10e-6

    def param_load_time(self, gb: float) -> float:
        if self.param_load_gbps is None:
            return 0.0
        return self.latency_s + gb / self.param_load_gbps

    def transfer_time(
        self,
        gb: float,
        src_slice: Optional[int] = None,
        dst_slice: Optional[int] = None,
    ) -> float:
        """Device-to-device transfer cost.  The slice arguments exist for
        topology-aware subclasses (:class:`TieredLinkModel`); the flat model
        charges every hop at ICI rate regardless."""
        if self.interconnect_gbps is None:
            return 0.0
        return self.latency_s + gb / self.interconnect_gbps


@dataclass
class TieredLinkModel(LinkModel):
    """Two-tier interconnect: ICI within a slice, DCN between slices.

    BASELINE config #3 ("v5e-16, DCN-aware") is two v5e-8 slices joined by
    data-center network: intra-slice hops keep ``interconnect_gbps``;
    cross-slice hops pay ``dcn_gbps`` + ``dcn_latency_s`` (defaults are
    v5e-class estimates: ~12.5 GB/s effective per-host DCN, tens of us
    latency — an order of magnitude below ICI, which is the whole point).
    Call sites without slice information (``None``) are charged the ICI
    tier, so single-slice users never see DCN costs by accident.
    """

    dcn_gbps: Optional[float] = 12.5
    dcn_latency_s: float = 50e-6

    def transfer_time(
        self,
        gb: float,
        src_slice: Optional[int] = None,
        dst_slice: Optional[int] = None,
    ) -> float:
        cross = (
            src_slice is not None
            and dst_slice is not None
            and src_slice != dst_slice
        )
        if not cross:
            return super().transfer_time(gb)
        if self.dcn_gbps is None:
            return 0.0
        return self.dcn_latency_s + gb / self.dcn_gbps


@dataclass
class ExecutionReport:
    """Metric set matching the reference's TestResult fields
    (reference ``simulation.py:15-30``) plus per-task timings."""

    scheduler_name: str
    dag_type: str
    num_nodes: int
    num_tasks: int
    completed_tasks: int
    failed_tasks: int
    makespan: float
    cache_hits: int
    cache_misses: int
    load_balance_score: float
    node_utilization: Dict[str, float]
    scheduling_wall_s: float
    memory_regime: float = 1.0
    transfer_time_total: float = 0.0
    param_load_time_total: float = 0.0
    timings: Dict[str, TaskTiming] = field(default_factory=dict)

    @property
    def completion_rate(self) -> float:
        return self.completed_tasks / self.num_tasks if self.num_tasks else 0.0

    @property
    def cache_hit_rate(self) -> float:
        total = self.cache_hits + self.cache_misses
        return self.cache_hits / total if total else 0.0

    def to_row(self) -> Dict[str, object]:
        """Flat dict for CSV export (column parity with the reference)."""
        return {
            "scheduler": self.scheduler_name,
            "dag_type": self.dag_type,
            "num_nodes": self.num_nodes,
            "memory_regime": self.memory_regime,
            "total_tasks": self.num_tasks,
            "completed_tasks": self.completed_tasks,
            "failed_tasks": self.failed_tasks,
            "completion_rate": self.completion_rate,
            "makespan": self.makespan,
            "cache_hits": self.cache_hits,
            "cache_misses": self.cache_misses,
            "cache_hit_rate": self.cache_hit_rate,
            "load_balance_score": self.load_balance_score,
            "avg_utilization": (
                sum(self.node_utilization.values()) / len(self.node_utilization)
                if self.node_utilization
                else 0.0
            ),
            "execution_time": self.scheduling_wall_s,
            "transfer_time_total": self.transfer_time_total,
            "param_load_time_total": self.param_load_time_total,
        }


def calculate_load_balance(per_node_load: Dict[str, float]) -> float:
    """1/(1+CV) over per-node compute loads (reference simulation.py:280-302).

    Zero/empty loads score 0 (as in the reference): a schedule that ran
    nothing must not outrank working schedulers on balance.
    """
    loads = list(per_node_load.values())
    if not loads or all(v == 0 for v in loads):
        return 0.0
    mean = sum(loads) / len(loads)
    if mean == 0:
        return 0.0
    var = sum((v - mean) ** 2 for v in loads) / len(loads)
    cv = var**0.5 / mean
    return 1.0 / (1.0 + cv)


class SimulatedBackend:
    """Replays schedules under a cost model; no JAX dependency.

    ``prefetch_params=True`` (default in full fidelity) models what the
    device backend actually does (``DeviceBackend.place_params``): parameter
    loads start at t=0 per node in first-use order over the host link (DMA
    overlapping compute), and a task waits until its params' loads complete
    rather than paying the load inline at start.  ``False`` charges loads
    inline at task start (load-on-demand).
    """

    def __init__(self, fidelity: str = "full", link: Optional[LinkModel] = None,
                 prefetch_params: bool = True, host_slots: Optional[int] = None,
                 dispatch_s: float = 0.0,
                 host_synchronous_transfers: bool = False,
                 host_serial_loads: bool = False,
                 pre_analysis: bool = True):
        if fidelity not in ("full", "reference"):
            raise ValueError(
                f"fidelity must be 'full' or 'reference', got {fidelity!r}"
            )
        if host_slots is not None and host_slots < 1:
            raise ValueError(f"host_slots must be >= 1, got {host_slots}")
        self.fidelity = fidelity
        self.prefetch_params = prefetch_params and fidelity == "full"
        # per-task HOST dispatch cost (measured: utils/costmodel): one
        # Python dispatcher enqueues tasks serially in assignment order,
        # so task i cannot start before (i+1) * dispatch_s even when its
        # device/inputs are ready — visible on fine-grained DAGs
        self.dispatch_s = dispatch_s if fidelity == "full" else 0.0
        # Shared-substrate cap: at most this many tasks execute concurrently
        # across ALL nodes.  Real TPU cores are independent (None =
        # unlimited, the default); the CPU-faked mesh shares the host's
        # cores, so predicting what DeviceBackend will *measure* there
        # requires capping concurrency at the physical core count — this is
        # what makes sim-vs-real validation honest on any machine.
        self.host_slots = host_slots
        # Host-mediated transfers: in the real per-task dispatch loop every
        # cross-node edge is an inline ``jax.device_put`` — a HOST call.
        # On platforms where that call blocks while copying (the CPU mesh:
        # device_put is a synchronous memcpy), each transfer's full wire
        # time also occupies the serial dispatcher, delaying every later
        # dispatch.  Without this, a transfer-heavy placement's replay
        # ties a transfer-light one while its measured makespan is ~1.5x
        # worse (found by eval/rankcheck on the flagship structure).  On
        # real TPU (async DMA) leave False; the per-call host cost is
        # covered by dispatch_s below.
        self.host_synchronous_transfers = (
            host_synchronous_transfers and fidelity == "full"
        )
        # Host-mediated parameter staging: DeviceBackend.place_params
        # stages every param with device_put before dispatch.  Real TPU
        # DMA engines give each device its own async queue (per-node
        # prefetch queues below); on the CPU mesh every device_put is a
        # synchronous memcpy on ONE host thread, so all nodes' loads
        # drain through a single serial queue — a placement that
        # duplicates params (round-robin: every node loads every layer)
        # pays the whole duplicated byte count in wall time, which the
        # per-node queues hide behind 8x parallelism (found by the r4
        # flagship rankcheck: predicted spread 1.7% vs measured 37%).
        self.host_serial_loads = host_serial_loads and fidelity == "full"
        # opt-out static pre-execution gate (see analysis/):
        # pre_analysis=False per instance, DLS_SKIP_ANALYSIS=1 globally
        self.pre_analysis = pre_analysis
        if fidelity == "reference":
            # Reference fidelity is *defined* as zero-cost data movement
            # (paper §6.6.1); a caller-supplied link would silently skew
            # totals without affecting timings, so it is rejected.
            if link is not None:
                raise ValueError("fidelity='reference' implies a zero-cost link")
            self.link = LinkModel(
                param_load_gbps=None, interconnect_gbps=None, latency_s=0.0
            )
        else:
            self.link = link or LinkModel()

    def execute(
        self,
        graph: TaskGraph,
        cluster: Cluster,
        schedule: Schedule,
        dag_type: str = "unknown",
        memory_regime: float = 1.0,
        pre_report: Any = None,
    ) -> ExecutionReport:
        if self.pre_analysis:
            # pre_report: a fresh ``analysis.analyze()`` report for this
            # exact schedule lets the gate skip duplicate base passes
            # (signature-checked inside pre_execution_gate)
            from ..analysis import pre_execution_gate

            pre_execution_gate(
                graph, cluster, schedule, backend="sim",
                precomputed=pre_report,
            )
        placement = schedule.placement
        speeds = {d.node_id: d.compute_speed for d in cluster}

        # fresh per-node caches for hit/miss accounting
        # (reference simulation.py:233-244 starts caches empty)
        caches: Dict[str, Set[str]] = {d.node_id: set() for d in cluster}
        hits = misses = 0
        param_load_total = 0.0
        transfer_total = 0.0

        node_clock: Dict[str, float] = {d.node_id: 0.0 for d in cluster}
        finish: Dict[str, float] = {}
        timings: Dict[str, TaskTiming] = {}
        per_node_load: Dict[str, float] = {d.node_id: 0.0 for d in cluster}

        # prefetch model: per-node host-link queue; param p's load completes
        # at the cumulative queue position (first-use order).  Under
        # host_serial_loads the loads charge the dispatcher clock instead.
        load_queue_end: Dict[str, float] = {d.node_id: 0.0 for d in cluster}
        param_ready_at: Dict[tuple, float] = {}

        # shared-substrate slots: classic machine model — one heap entry per
        # slot holding the time that slot next frees up
        import heapq

        slot_free: list = (
            [0.0] * self.host_slots if self.host_slots is not None else []
        )

        # Execute in global assignment order (the order the scheduler decided),
        # which respects dependencies by construction.
        host_clock = 0.0  # serial dispatcher position
        for tid in schedule.assignment_order:
            task = graph[tid]
            node_id = placement[tid]
            cache = caches[node_id]
            host_clock += self.dispatch_s

            # parameter loads
            load_time = 0.0
            params_ready = 0.0
            for p in sorted(task.params_needed):
                if p in cache:
                    hits += 1
                    if self.prefetch_params:
                        params_ready = max(
                            params_ready, param_ready_at.get((node_id, p), 0.0)
                        )
                else:
                    misses += 1
                    cache.add(p)
                    t_load = self.link.param_load_time(graph.param_size_gb(p))
                    load_time += t_load
                    if self.prefetch_params:
                        if self.host_serial_loads:
                            # staging occupies the DISPATCHER: the copy
                            # runs on the same host thread that enqueues
                            # tasks, so every later dispatch waits behind
                            # it (and this task waits for its own copy)
                            host_clock += t_load
                            param_ready_at[(node_id, p)] = host_clock
                            params_ready = max(params_ready, host_clock)
                        else:
                            load_queue_end[node_id] += t_load
                            param_ready_at[(node_id, p)] = (
                                load_queue_end[node_id]
                            )
                            params_ready = max(
                                params_ready, load_queue_end[node_id]
                            )
            param_load_total += load_time

            start = max(node_clock[node_id], host_clock)
            inbound_xfer = 0.0
            if self.fidelity == "full":
                # dependency wait: inputs must exist; cross-node edges pay ICI
                for d in task.dependencies:
                    if d not in finish:
                        continue  # failed dep (shouldn't occur for completed)
                    dep_ready = finish[d]
                    if placement.get(d) != node_id:
                        xfer = self.link.transfer_time(
                            graph.output_gb(d),
                            src_slice=cluster[placement[d]].slice_id,
                            dst_slice=cluster[node_id].slice_id,
                        )
                        dep_ready += xfer
                        transfer_total += xfer
                        inbound_xfer += xfer
                        if self.host_synchronous_transfers:
                            # a cross-node device_put needs CONCRETE
                            # bytes: the dispatcher blocks until the
                            # producer finishes, then performs the copy
                            # itself — so every cross-node edge collapses
                            # the dispatch-ahead window to the producer's
                            # finish time before charging the copy
                            host_clock = max(host_clock, finish[d]) + xfer
                    start = max(start, dep_ready)
                if self.host_synchronous_transfers:
                    # the task cannot start before the dispatcher finished
                    # copying ALL its inputs (start was read from
                    # host_clock before the dep loop advanced it)
                    start = max(start, host_clock)
                if self.prefetch_params:
                    # DMA overlaps compute; task just waits for its weights
                    start = max(start, params_ready)
                else:
                    start += load_time

            if self.host_slots is not None:
                # earliest-available slot executes this task (greedy in
                # assignment order — an approximation, but it keeps full
                # occupancy history unlike dropping finished intervals)
                start = max(start, heapq.heappop(slot_free))

            duration = task.compute_time / speeds[node_id]
            if self.host_synchronous_transfers and self.host_slots is not None:
                # shared-substrate fidelity: the dispatcher's synchronous
                # memcpy runs on the same physical cores that execute
                # compute, so inbound copy time occupies this task's slot
                # too — without this, a transfer-heavy placement's copies
                # hide entirely inside slot waits and the replay predicts
                # a tie where the mesh measures a large spread (the r3
                # rankcheck's 1.3%-predicted vs 29%-measured failure)
                duration += inbound_xfer
            end = start + duration
            if self.host_slots is not None:
                heapq.heappush(slot_free, end)
            node_clock[node_id] = end
            finish[tid] = end
            timings[tid] = TaskTiming(tid, node_id, start, end)
            # load balance counts COMPUTE only (reference metric semantics);
            # the slot-charged copy time above is occupancy, not load
            per_node_load[node_id] += task.compute_time / speeds[node_id]

        makespan = max(node_clock.values()) if node_clock else 0.0
        utilization = {
            n: (per_node_load[n] / makespan if makespan > 0 else 0.0)
            for n in node_clock
        }
        schedule.timings = timings
        return ExecutionReport(
            scheduler_name=schedule.policy,
            dag_type=dag_type,
            num_nodes=len(cluster),
            num_tasks=len(graph),
            completed_tasks=len(schedule.completed),
            failed_tasks=len(schedule.failed),
            makespan=makespan,
            cache_hits=hits,
            cache_misses=misses,
            load_balance_score=calculate_load_balance(per_node_load),
            node_utilization=utilization,
            scheduling_wall_s=schedule.scheduling_wall_s,
            memory_regime=memory_regime,
            transfer_time_total=transfer_total,
            param_load_time_total=param_load_total,
            timings=timings,
        )
