"""Pre-planned per-task dispatch: plan once, launch from a flat table.

The legacy hot loop (``DeviceBackend._run``) re-derives everything per task
per rep: placement dict lookups, param dict comprehensions, per-argument
``device_put`` decisions, upstream-failure checks.  On the flagship GPT-2
DAG that Python bookkeeping is most of the 21.9 ms host dispatch overhead
(BENCH_r05.json) — work whose inputs (graph, schedule, placed params) are
all fixed before the first launch.  This module moves it to plan time:

* **Immutable plan** (:class:`DispatchPlan`): built once per ``execute``
  from the frozen graph, the schedule's dispatch linearization, and the
  placed params.  Each step carries its resolved jitted executable, a
  prebuilt param binding dict, and integer indices into a flat value
  table — the hot loop does list indexing and calls, nothing else.
* **Batched staging**: all of a step's cross-core inputs go up in ONE
  ``jax.device_put([...], dev)`` call (the ``_ParamStreamer._load``
  trick applied to activations).  Transfer edges/bytes are counted
  statically at plan time with the exact per-(task, arg) semantics of the
  legacy loop; bytes are filled during the warmup pass and cached.
* **Donated buffers**: an intermediate output whose globally-last consumer
  is a same-device step is donated to that step via
  ``jax.jit(..., donate_argnums=...)``, so XLA reuses the dying buffer for
  the step's output instead of allocating.  Safety rules (enforced at
  plan time, assertable from the plan): never donate external
  (``ext_outputs``) values or the staged graph input — on-device
  ``device_put`` can return the caller's own array, so deleting it would
  reach outside the run; never donate the final output or a value any
  later step still reads; never donate under ``keep_outputs``; a buffer
  feeding one step at two argument positions is not donated at all.
  Cross-core transfers are fresh copies owned by the consuming step, so
  those are always donated (the producer's original stays live).
* **Coalesced launches** (opt-in ``coalesce=True``): the global dispatch
  order is first re-linearized to maximize runs of consecutive same-device
  tasks — legal because async dispatch only needs a task's upstreams
  *enqueued* first, and both ``Schedule.per_node`` order and topological
  dispatch order are preserved exactly.  Each run (capped at
  :data:`_GROUP_CAP` members to bound XLA program size) becomes ONE jitted
  multi-task call: members read in-group values directly and everything
  else (earlier task outputs, ext values, the staged graph input) as
  launch arguments, so per-task placement semantics survive intact.
  ``jax.lax.optimization_barrier`` between member computations keeps each
  task's numerics bit-identical to separate launches (XLA cannot fuse
  across the barrier).  Opt-in because host-side effects inside task fns
  (``jax.debug.callback(ordered=False)``) have no ordering guarantee
  within one XLA program.

Fail-and-continue is preserved statically: tasks with failed (unplaced or
transitively skipped) upstreams are dropped at plan build, mirroring the
legacy loop's per-task check.  The end-of-run fence reads each device's
last planned output, exactly like the legacy paths.
"""

from __future__ import annotations
# dls-lint: allow-file(DET001) dispatch timing harness: wall time IS the measured quantity

import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
from jax.sharding import SingleDeviceSharding

try:
    # fast transfer path: with the target sharding and source avals known
    # at plan time, calling the runtime's batched_device_put directly skips
    # ~30 us/array of argument normalization inside public
    # ``jax.device_put`` (sharding inference, pytree flatten, aval
    # abstraction).  Semantics match the public path for the cross-device
    # moves the plan issues (the public path's same-device aliasing
    # shortcut never applies to them).
    from jax._src.lib import xla_client as _xc

    def _fast_put(aval, sharding, xs, devices):
        return _xc.batched_device_put(aval, sharding, xs, devices, True)
except Exception:  # pragma: no cover - private API moved; use public path
    _fast_put = None

from .rebatch import extract_steps


def _array_bytes(x: Any) -> int:
    from .device import _array_bytes as f

    return f(x)


def _tuple_getter(slots: Sequence[int]):
    """C-speed multi-index gather over the value table (always a tuple,
    unlike bare ``itemgetter`` which unwraps a single index)."""
    from operator import itemgetter

    if not slots:
        return lambda vals: ()
    if len(slots) == 1:
        s = slots[0]
        return lambda vals: (vals[s],)
    return itemgetter(*slots)


_DONATION_OK: Optional[bool] = None


def donation_supported() -> bool:
    """Probe (once per process) whether this platform honors buffer
    donation: a donated input must actually be deleted.  Platforms that
    ignore ``donate_argnums`` (with a warning) get the undonated path."""
    global _DONATION_OK
    if _DONATION_OK is None:
        import warnings

        import numpy as np

        try:
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                x = jax.device_put(np.ones((4,), np.float32))
                f = jax.jit(lambda v: v + 1.0, donate_argnums=(0,))
                jax.block_until_ready(f(x))
                _DONATION_OK = bool(x.is_deleted())
        except Exception:
            _DONATION_OK = False
    return _DONATION_OK


# sentinel naming a root member's graph-input read in a launch's external
# argument list (the staged per-node input slot backs it at run time)
GRAPH_INPUT = "__graph_input__"

# max members per coalesced launch — bounds XLA program size / compile time
_GROUP_CAP = 16


def group_arg_binds(graph, tids: Tuple[str, ...]):
    """Argument wiring for a (possibly coalesced) launch over ``tids``.

    Returns ``(binds, ext_list)``.  ``ext_list`` is the ordered tuple of
    external inputs the launch takes after the params dict: task ids
    produced outside the group, or :data:`GRAPH_INPUT` for a root member's
    graph-input read — one entry per (member, arg position) occurrence,
    duplicates kept, mirroring the legacy loop's per-argument semantics.
    ``binds[i]`` wires member i's arguments: ``('v', tid)`` reads an
    in-group value, ``('x', k)`` reads ``ext_list[k]``.
    """
    inside: set = set()
    binds: List[Tuple[Tuple[str, Any], ...]] = []
    ext_list: List[str] = []
    for tid in tids:
        aids = graph[tid].arg_tasks or graph[tid].dependencies
        row: List[Tuple[str, Any]] = []
        if aids:
            for d in aids:
                if d in inside:
                    row.append(("v", d))
                else:
                    row.append(("x", len(ext_list)))
                    ext_list.append(d)
        else:
            row.append(("x", len(ext_list)))
            ext_list.append(GRAPH_INPUT)
        binds.append(tuple(row))
        inside.add(tid)
    return tuple(binds), tuple(ext_list)


def _build_group_fn(graph, tids: Tuple[str, ...], exports: Tuple[str, ...]):
    """One callable running ``tids`` in order: (params-by-global-name,
    *external-args) -> tuple of export outputs.

    Members read values produced inside the group directly and everything
    else from the external argument list (wiring from
    :func:`group_arg_binds`).  ``optimization_barrier`` between members
    pins each task's computation as its own fusion island, so per-task
    outputs are bit-identical to separate launches.
    """
    steps = extract_steps(graph, tids)
    binds, _ext = group_arg_binds(graph, tids)

    def group_fn(gp, *ext_args):
        vals: Dict[str, Any] = {}
        for i, (tid, fn, pitems, _aids) in enumerate(steps):
            pd = {loc: gp[g] for loc, g in pitems}
            args = [
                vals[ref] if kind == "v" else ext_args[ref]
                for kind, ref in binds[i]
            ]
            out = fn(pd, *args)
            if i < len(steps) - 1:
                out = jax.lax.optimization_barrier(out)
            vals[tid] = out
        return tuple(vals[t] for t in exports)

    return group_fn


def _sds(x: Any):
    """ShapeDtypeStruct of one concrete leaf (host or device array)."""
    import numpy as np

    if not (hasattr(x, "shape") and hasattr(x, "dtype")):
        x = np.asarray(x)
    return jax.ShapeDtypeStruct(tuple(x.shape), x.dtype)


def propagate_avals(
    graph, order: Sequence[str], params: Dict[str, Any], graph_input: Any
) -> Dict[str, Any]:
    """Abstract output (pytree of ``ShapeDtypeStruct``) per task, by
    ``jax.eval_shape`` propagation along a topological order.

    The whole-program lowering (:mod:`.compiled_schedule`) needs every
    task's output aval *before* tracing: non-owner ``switch`` branches
    return ``zeros_like`` placeholders, and cross-device exchanges size
    their transfers statically.  Shared here (next to the plan's static
    transfer table) so plan-time and compile-time shape reasoning can't
    diverge.  ``order`` must be dependency-closed: every ``arg_tasks``
    reference resolves to an earlier entry or to the graph input.
    """
    param_avals = {
        g: jax.tree_util.tree_map(_sds, params[g])
        for g in graph.unique_params()
        if g in params
    }
    in_aval = jax.tree_util.tree_map(_sds, graph_input)
    avals: Dict[str, Any] = {}
    for tid in order:
        task = graph[tid]
        pd = {loc: param_avals[g] for loc, g in task.param_items()}
        aids = task.arg_tasks or task.dependencies
        args = [avals[d] for d in aids] if aids else [in_aval]
        avals[tid] = jax.eval_shape(task.fn, pd, *args)
    return avals


def _relinearize(graph, schedule, alive: List[str], done: set) -> List[str]:
    """Reorder ``alive`` to maximize consecutive same-device runs.

    Legal because async dispatch only requires a task's upstreams to be
    *enqueued* (not completed) first: the result preserves each node's
    ``Schedule.per_node`` order exactly (tasks only ever leave the front
    of their node's queue) and is a topological order of the alive
    subgraph.  Greedy: stay on the current node while its next task has
    all upstreams already dispatched; when it blocks, switch to the node
    with the longest immediately-dispatchable prefix (longer runs mean
    fewer launches, and more distance between a producer's launch and its
    consumers' transfers).  A switch target always exists: the earliest
    not-yet-dispatched task of the original order is always its node's
    head with every upstream already dispatched."""
    placement = schedule.placement
    from collections import deque
    from itertools import islice

    queues: Dict[str, Any] = {}
    for t in alive:
        queues.setdefault(placement[t], deque()).append(t)
    node_order = sorted(queues)
    done = set(done)
    out: List[str] = []
    cur: Optional[str] = None

    def ready(t: str) -> bool:
        aids = graph[t].arg_tasks or graph[t].dependencies
        return all(d in done for d in aids)

    def ready_prefix(q) -> int:
        n = 0
        local: set = set()
        for t in islice(q, _GROUP_CAP):
            aids = graph[t].arg_tasks or graph[t].dependencies
            if all(d in done or d in local for d in aids):
                local.add(t)
                n += 1
            else:
                break
        return n

    while len(out) < len(alive):
        q = queues.get(cur)
        if q and ready(q[0]):
            t = q.popleft()
        else:
            best_n, best_len = None, 0
            for n in node_order:
                qn = queues[n]
                if not qn or not ready(qn[0]):
                    continue
                ln = ready_prefix(qn)
                if ln > best_len:
                    best_n, best_len = n, ln
                    if ln >= _GROUP_CAP:
                        break
            if best_n is None:  # impossible per the invariant above
                raise RuntimeError("relinearize: no dispatchable node head")
            cur = best_n
            t = queues[cur].popleft()
        out.append(t)
        done.add(t)
    return out


class PlanStep:
    """One launch: a single task or a coalesced same-device group."""

    __slots__ = (
        "tids",          # task ids in this launch (len 1 unless coalesced)
        "node_id",
        "dev",           # jax device the launch runs on
        "fn",            # resolved jitted callable (donating variant baked in)
        "pd",            # prebuilt param binding dict (immutable across runs)
        "arg_slots",     # value-table indices of the launch args, in order
        "get_args",      # itemgetter over arg_slots (C-speed gather)
        "xfer_slots",    # unique slots needing device_put onto `dev`
        "get_srcs",      # itemgetter over xfer_slots
        "xfer_map",      # (arg position, index into xfer_slots) pairs
        "xfer_src_tids",  # producer id per xfer slot (tracing: flow arrows)
        "xfer_src_nodes",  # producer node per xfer slot ("ext" for seeds)
        "xfer_shard",    # SingleDeviceSharding(dev) for the fast put path
        "xfer_devs",     # [dev] for the fast put path
        "xfer_avals",    # per-xfer_slots avals, filled on first run;
                         # False => pytree payloads, public path only
        "n_edges",       # transfer edges this launch contributes (static)
        "xfer_bytes",    # per-run transferred bytes; filled on first run
        "donate_slots",  # slots whose ORIGINAL buffer this launch consumes
        "donate_tids",   # producer task id per donate slot (memprof frees)
        "donate_argnums",  # jit donate positions (params dict is argument 0)
        "out_slots",     # value-table indices written (exports, in order)
        "out_tids",      # exported task id per out_slot (memprof births)
        "group",         # True => fn returns a tuple aligned with out_slots
    )


class DispatchPlan:
    """Immutable dispatch program for one (graph, schedule, ext) triple.

    Built by :meth:`build`; executed by :meth:`run`.  The value table is a
    flat list: slots 0..len(ext)-1 hold external outputs, then one slot per
    device that roots read the graph input from, then one slot per exported
    task output.
    """

    def __init__(
        self,
        backend,
        steps: List[PlanStep],
        n_slots: int,
        ext_slots: Tuple[Tuple[str, int], ...],
        input_slots: Tuple[Tuple[str, Any, int], ...],
        fence_slots: Tuple[Tuple[str, int], ...],
        final_slot: Optional[int],
        keep_list: Tuple[Tuple[str, int], ...],
        transfer_edges: int,
        donate: bool,
        coalesce: bool,
    ):
        self._backend = backend
        self.steps = steps
        self.n_slots = n_slots
        self.ext_slots = ext_slots
        self.input_slots = input_slots       # (node_id, jax device, slot)
        self.fence_slots = fence_slots       # (node_id, slot)
        self.final_slot = final_slot
        self.keep_list = keep_list           # (tid, slot) when keep_outputs
        self.transfer_edges = transfer_edges
        self.donate = donate
        self.coalesce = coalesce

    # -- construction ------------------------------------------------------
    @classmethod
    def build(
        cls,
        backend,
        graph,
        schedule,
        order: Sequence[str],
        placed_params: Dict[Tuple[str, str], Any],
        ext_keys: Tuple[str, ...] = (),
        donate: bool = False,
        coalesce: bool = False,
        keep_outputs: bool = False,
    ) -> "DispatchPlan":
        placement = schedule.placement
        if keep_outputs:
            donate = False  # retained outputs must all outlive the run

        # static fail-and-continue: identical filter to the legacy loop's
        # per-task upstream check (ext values count as live producers)
        live: set = set(ext_keys)
        alive: List[str] = []
        for tid in order:
            aids = graph[tid].arg_tasks or graph[tid].dependencies
            if aids and any(d not in live for d in aids):
                continue
            live.add(tid)
            alive.append(tid)

        # launch groups: singletons unless coalescing is on.  Coalescing
        # first re-linearizes the dispatch order (per-node order and topo
        # dispatch preserved), then cuts it into capped same-device runs.
        groups: List[List[str]] = []
        if coalesce and alive:
            alive = _relinearize(graph, schedule, alive, set(ext_keys))
        if coalesce:
            for tid in alive:
                if (
                    groups
                    and placement[groups[-1][0]] == placement[tid]
                    and len(groups[-1]) < _GROUP_CAP
                ):
                    groups[-1].append(tid)
                else:
                    groups.append([tid])
        else:
            groups = [[t] for t in alive]

        group_of = {t: gi for gi, g in enumerate(groups) for t in g}
        consumers: Dict[str, set] = {t: set() for t in alive}
        for tid in alive:
            for d in graph[tid].arg_tasks or graph[tid].dependencies:
                if d in consumers:
                    consumers[d].add(group_of[tid])
        exports_of: List[Tuple[str, ...]] = []
        for gi, g in enumerate(groups):
            exports_of.append(tuple(
                t for t in g
                if keep_outputs or (consumers[t] - {gi}) or not consumers[t]
            ))

        # slot allocation: ext, then per-device graph input, then exports
        slot_of: Dict[str, int] = {}
        for k in ext_keys:
            slot_of[k] = len(slot_of)
        ext_slots = tuple((k, slot_of[k]) for k in ext_keys)
        input_slot: Dict[str, int] = {}
        n_slots = len(slot_of)
        for tid in alive:
            if not (graph[tid].arg_tasks or graph[tid].dependencies):
                node = placement[tid]
                if node not in input_slot:
                    input_slot[node] = n_slots
                    n_slots += 1
        for exports in exports_of:
            for t in exports:
                slot_of[t] = n_slots
                n_slots += 1

        final_tid = graph.topo_order[-1] if graph.topo_order else None
        final_slot = slot_of.get(final_tid) if final_tid else None
        fence: Dict[str, int] = {}
        for gi, g in enumerate(groups):
            # a group's last member always has outside-or-no consumers,
            # so it is exported and the fence can read it
            fence[placement[g[0]]] = slot_of[g[-1]]
        fence_slots = tuple(sorted(fence.items()))

        # per-group external argument lists (slot-backed launch inputs)
        ext_lists = [group_arg_binds(graph, tuple(g))[1] for g in groups]

        # last consuming group index per slot (donation lifetime analysis)
        last_use: Dict[int, int] = {}
        for gi, ext_list in enumerate(ext_lists):
            for d in ext_list:
                if d != GRAPH_INPUT:
                    last_use[slot_of[d]] = gi

        task_out_slots = set(
            slot_of[t] for exports in exports_of for t in exports
        )
        # reverse map for the memory profiler's donation frees (a donated
        # slot's dying buffer is its producer task's ``out:`` label)
        tid_of_slot = {
            slot_of[t]: t for exports in exports_of for t in exports
        }
        protected = {final_slot} | {s for _, s in fence_slots}

        steps: List[PlanStep] = []
        transfer_edges = 0
        for gi, g in enumerate(groups):
            lead = graph[g[0]]
            node = placement[g[0]]
            dev = backend.cluster[node].jax_device
            ext_list = ext_lists[gi]
            arg_slots = tuple(
                input_slot[node] if d == GRAPH_INPUT else slot_of[d]
                for d in ext_list
            )

            xfer_slots: List[int] = []
            xfer_srcs: List[str] = []  # producer per unique slot (tracing)
            xfer_map: List[Tuple[int, int]] = []
            xfer_ext: set = set()  # xfer indices sourced from ext values
            for pos, d in enumerate(ext_list):
                if d == GRAPH_INPUT or placement.get(d) == node:
                    # graph input is pre-staged per node; same-core edges
                    # need no transfer (legacy parity)
                    continue
                s = slot_of[d]
                if s in xfer_slots:
                    ui = xfer_slots.index(s)
                else:
                    ui = len(xfer_slots)
                    xfer_slots.append(s)
                    xfer_srcs.append(d)
                xfer_map.append((pos, ui))
                if d not in placement:
                    xfer_ext.add(ui)
                transfer_edges += 1

            donate_pos: List[int] = []
            donate_slots: List[int] = []
            if donate:
                pos_of_slot: Dict[int, List[int]] = {}
                for pos, s in enumerate(arg_slots):
                    pos_of_slot.setdefault(s, []).append(pos)
                moved = {pos for pos, _ in xfer_map}
                for s, poss in pos_of_slot.items():
                    if len(poss) != 1:
                        continue  # one buffer at two positions: never donate
                    pos = poss[0]
                    if pos in moved:
                        # the device_put copy is owned by this launch; ext
                        # values are excluded (on-device device_put can
                        # alias the caller's array)
                        ui = next(
                            u for p, u in xfer_map if p == pos
                        )
                        if ui not in xfer_ext:
                            donate_pos.append(pos)
                    elif (
                        s in task_out_slots
                        and last_use.get(s) == gi
                        and s not in protected
                    ):
                        donate_pos.append(pos)
                        donate_slots.append(s)
            donate_argnums = tuple(1 + p for p in sorted(donate_pos))

            step = PlanStep()
            step.tids = tuple(g)
            step.node_id = node
            step.dev = dev
            step.arg_slots = arg_slots
            step.get_args = _tuple_getter(arg_slots)
            step.xfer_slots = tuple(xfer_slots)
            step.get_srcs = _tuple_getter(step.xfer_slots)
            step.xfer_map = tuple(xfer_map)
            step.xfer_src_tids = tuple(xfer_srcs)
            step.xfer_src_nodes = tuple(
                placement.get(d, "ext") for d in xfer_srcs
            )
            step.xfer_shard = SingleDeviceSharding(dev) if xfer_slots else None
            step.xfer_devs = [dev]
            step.xfer_avals = None
            step.n_edges = len(xfer_map)
            step.xfer_bytes = None if xfer_map else 0
            step.donate_slots = tuple(donate_slots)
            step.donate_tids = tuple(tid_of_slot[s] for s in donate_slots)
            step.donate_argnums = donate_argnums
            step.group = len(g) > 1
            if step.group:
                exports = exports_of[gi]
                step.out_slots = tuple(slot_of[t] for t in exports)
                step.out_tids = exports
                step.fn = backend._grouped_jitted(
                    graph, tuple(g), exports, donate_argnums
                )
                step.pd = {
                    glob: placed_params[(glob, node)]
                    for t in g
                    for _, glob in graph[t].param_items()
                }
            else:
                step.out_slots = (slot_of[g[0]],)
                step.out_tids = (g[0],)
                step.fn = backend._jitted(graph, g[0], donate_argnums)
                step.pd = {
                    loc: placed_params[(glob, node)]
                    for loc, glob in lead.param_items()
                }
            steps.append(step)

        keep_list = tuple(
            (t, slot_of[t]) for exports in exports_of for t in exports
        ) if keep_outputs else ()
        plan = cls(
            backend, steps, n_slots, ext_slots,
            tuple(
                (n, backend.cluster[n].jax_device, s)
                for n, s in sorted(input_slot.items())
            ),
            fence_slots, final_slot, keep_list, transfer_edges,
            donate, coalesce,
        )
        # donation self-check (analysis/donation_pass): re-derives the
        # lifetime safety the builder just computed, from the exported
        # metadata alone — a donation bug here frees a live buffer, so
        # it joins the pre-execution gate rather than trusting the
        # builder that produced it
        if donate and getattr(backend, "pre_analysis", True):
            from ..analysis import gate_enabled
            from ..analysis.donation_pass import analyze_donation

            if gate_enabled():
                analyze_donation(plan).raise_if_errors()
        return plan

    # -- analysis metadata -------------------------------------------------
    def donation_table(self) -> Dict[str, Any]:
        """Static donation metadata for ``analysis/donation_pass``:
        per-step slot reads/transfers/donations plus the post-run readers
        (fence, final output, keep list, ext values).  Pure data — the
        pass never touches live buffers or jitted callables, so external
        tooling can verify a plan without being able to run it."""
        return {
            "steps": tuple(
                {
                    "tids": st.tids,
                    "node_id": st.node_id,
                    "arg_slots": st.arg_slots,
                    "xfer_slots": st.xfer_slots,
                    "donate_slots": st.donate_slots,
                    "out_slots": st.out_slots,
                }
                for st in self.steps
            ),
            "fence_slots": self.fence_slots,
            "final_slot": self.final_slot,
            "keep_list": self.keep_list,
            "ext_slots": self.ext_slots,
            "n_slots": self.n_slots,
        }

    # -- identity ----------------------------------------------------------
    def signature(self) -> Tuple:
        """Hashable structural identity: two builds over the same
        (graph, schedule, ext keys, flags) must compare equal.  Contains
        no object identities, only names and slot indices."""
        return (
            self.n_slots,
            self.ext_slots,
            tuple((n, s) for n, _d, s in self.input_slots),
            self.fence_slots,
            self.final_slot,
            self.transfer_edges,
            self.donate,
            self.coalesce,
            tuple(
                (
                    st.tids, st.node_id, st.arg_slots, st.xfer_slots,
                    st.xfer_map, st.donate_slots, st.donate_argnums,
                    st.out_slots,
                )
                for st in self.steps
            ),
        )

    @property
    def n_launches(self) -> int:
        return len(self.steps)

    # -- execution ---------------------------------------------------------
    def run(
        self,
        graph_input: Any,
        ext_outputs: Optional[Dict[str, Any]] = None,
        fence: bool = True,
        tracer: Any = None,
        metrics: Any = None,
        mem: Any = None,
    ) -> Tuple[Any, Dict, int, int, int, int, Dict[str, Any], Dict[str, float]]:
        """Execute the plan once.  Same return contract as the legacy
        runners plus a phase dict: ``(final, timings, transfer_edges,
        transfer_bytes, n_fences, n_dispatches, executed, phases)`` with
        ``phases = {loop_s, stage_s, launch_s}`` — host wall inside the
        dispatch loop (fence excluded), split into staging (input placement
        + batched transfers) and launch (executable calls).

        ``tracer`` (obs.trace.Tracer, optional): records one launch span
        per step on the step's device track, staging spans, and transfer
        flow arrows from producer launches.  ``metrics`` (obs.metrics.
        MetricsRegistry, optional): per-(src->dst) transfer byte counters.
        Both default to None and every instrumentation point is behind a
        None check — the disabled hot loop is the PR 2 fast path
        unchanged (the <2% regression budget is measured by
        ``eval/dispatch_bench.py``).

        ``mem`` (obs.memprof.MemoryProfiler, optional): records input
        staging, transfer copies, task-output births, and donation-driven
        frees (the lifetimes :meth:`donation_table` documents) onto the
        per-device timelines."""
        vals: List[Any] = [None] * self.n_slots
        done: Optional[Dict[str, Tuple[str, float]]] = (
            {} if tracer is not None else None
        )
        t_loop0 = time.perf_counter()
        stage_s = 0.0
        if ext_outputs:
            for k, s in self.ext_slots:
                vals[s] = ext_outputs[k]
        if self.input_slots:
            t0 = time.perf_counter()
            for _n, dev, s in self.input_slots:
                vals[s] = jax.device_put(graph_input, dev)
                if mem is not None:
                    mem.alloc(
                        _n, "input", _array_bytes(vals[s]), "activations"
                    )
            stage_s += time.perf_counter() - t0
            if tracer is not None:
                tracer.complete(
                    "stage_input", t0, time.perf_counter(),
                    track="host", cat="stage", devices=len(self.input_slots),
                )

        tbytes = 0
        for step in self.steps:
            per_edge = None
            if step.xfer_slots:
                args = list(step.get_args(vals))
                srcs = step.get_srcs(vals)
                if step.xfer_bytes is None:
                    step.xfer_bytes = sum(
                        _array_bytes(srcs[ui]) for _p, ui in step.xfer_map
                    )
                if metrics is not None or mem is not None:
                    per_edge = [_array_bytes(x) for x in srcs]
                t0 = time.perf_counter()
                if step.xfer_avals and _fast_put is not None:
                    shard, devs = step.xfer_shard, step.xfer_devs
                    moved = [
                        _fast_put(av, shard, [x], devs)
                        for av, x in zip(step.xfer_avals, srcs)
                    ]
                else:
                    # first (warmup) pass: public path, then cache avals.
                    # Pytree task outputs (dict-of-grads, cache slabs)
                    # have no single aval — those steps stay on the
                    # public path permanently (False sentinel).
                    moved = jax.device_put(srcs, step.dev)
                    if step.xfer_avals is None:
                        step.xfer_avals = (
                            tuple(m.aval for m in moved)
                            if all(hasattr(m, "aval") for m in moved)
                            else False
                        )
                t1 = time.perf_counter()
                stage_s += t1 - t0
                if tracer is not None:
                    tracer.complete(
                        "stage", t0, t1, track=step.node_id, cat="stage",
                        transfers=len(step.xfer_slots),
                    )
                if metrics is not None:
                    for ui, src_node in enumerate(step.xfer_src_nodes):
                        metrics.counter(
                            f"transfer.bytes.{src_node}->{step.node_id}",
                            unit="bytes",
                        ).inc(per_edge[ui])
                if mem is not None:
                    for ui, src in enumerate(step.xfer_src_tids):
                        mem.alloc(
                            step.node_id, f"xfer:{src}", per_edge[ui],
                            "transfers",
                        )
                for pos, ui in step.xfer_map:
                    args[pos] = moved[ui]
            else:
                args = step.get_args(vals)
            tbytes += step.xfer_bytes
            if tracer is not None:
                t_l0 = time.perf_counter()
            if step.group:
                outs = step.fn(step.pd, *args)
                for s, o in zip(step.out_slots, outs):
                    vals[s] = o
            else:
                vals[step.out_slots[0]] = step.fn(step.pd, *args)
            if mem is not None:
                # births, then the donation-consumed producers' deaths —
                # the exact lifetimes donation_table() documents
                for t, s in zip(step.out_tids, step.out_slots):
                    mem.alloc(
                        step.node_id, f"out:{t}", _array_bytes(vals[s]),
                        "activations",
                    )
                for t in step.donate_tids:
                    mem.free(step.node_id, f"out:{t}")
            if tracer is not None:
                t_l1 = time.perf_counter()
                name = (
                    step.tids[0] if len(step.tids) == 1
                    else f"{step.tids[0]}+{len(step.tids) - 1}"
                )
                tracer.complete(
                    name, t_l0, t_l1, track=step.node_id, cat="launch",
                    tasks=len(step.tids), edges=step.n_edges,
                )
                for t in step.tids:
                    done[t] = (step.node_id, t_l1)
                for ui, src in enumerate(step.xfer_src_tids):
                    src_pt = done.get(src)
                    if src_pt is not None:
                        tracer.flow(
                            "transfer", src_pt[0], src_pt[1],
                            step.node_id, t_l0, src=src, dst=step.tids[0],
                        )
        loop_s = time.perf_counter() - t_loop0

        n_fences = 0
        if fence and self.steps:
            if tracer is not None:
                t_f0 = time.perf_counter()
            n_fences = self._backend._fence_run(
                {n: vals[s] for n, s in self.fence_slots}
            )
            if tracer is not None:
                tracer.complete(
                    "fence", t_f0, time.perf_counter(),
                    track="host", cat="collect",
                    devices=len(self.fence_slots),
                )
        final = vals[self.final_slot] if self.final_slot is not None else None
        executed = {t: vals[s] for t, s in self.keep_list}
        return (
            final, {}, self.transfer_edges, tbytes, n_fences,
            len(self.steps), executed,
            {
                "loop_s": loop_s,
                "stage_s": stage_s,
                "launch_s": loop_s - stage_s,
            },
        )
