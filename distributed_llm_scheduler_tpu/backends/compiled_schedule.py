"""Whole-program per-device lowering: the schedule becomes ONE launch.

The execution ladder so far interprets the schedule at ever coarser
granularity — per-task launches (``_run``), pre-planned launches
(:mod:`.dispatch_plan`), fused same-device segments
(``_run_segmented``) — but every rung still mediates cross-device edges
on the host and pays at least one launch per segment.  This module takes
the last step (ROADMAP "compile the schedule"): the **entire** placed
run lowers into a single jitted program whose cross-device edges are
in-program collectives, so the host issues O(devices) staging puts plus
ONE launch per run, and XLA owns overlap along the whole critical path.

Lowering model (MPMD inside SPMD):

* The participating devices form a 1-D mesh (axis ``"dev"``, mesh order
  = cluster order).  The program is SPMD over that mesh via
  ``parallel/compat.shard_map``.
* Per-device heterogeneous compute is a ``lax.switch`` on
  ``lax.axis_index``: phase ``p``'s branch for device ``d`` runs exactly
  device ``d``'s phase-``p`` tasks (each task's computation pinned as
  its own fusion island with ``optimization_barrier``, the same
  bit-identity guarantee as coalesced launches) and returns ``zeros``
  placeholders for other devices' exports, so all branches are
  shape-uniform.  Each task appears in exactly one branch — program size
  stays O(tasks), not O(tasks x devices).
* Cross-device edges are ``lax.ppermute`` point-to-point hops at phase
  boundaries, in the deterministic order fixed by the
  :class:`..sched.linearize.ProgramIR`.  Every device emits every
  collective in the same order (SPMD), so the global collective order is
  deadlock-free by construction — the property the COL00x pass
  (analysis/collective_pass.py) verifies and the pre-execution gate
  enforces.  A received value replaces the consumer's ``zeros`` register
  via an elementwise select (never arithmetic), keeping it bit-exact.
* Parameters load as per-device **slabs**: each device's params flatten
  (per dtype) into one contiguous vector, padded to the mesh-wide max
  and stacked into a ``(n_dev, max)`` array sharded ``P("dev")`` — per-
  device memory stays O(that device's params), not O(model).  Branches
  rebuild their params by static slice+reshape (bytes unchanged, bit-
  exact) behind one ``optimization_barrier``, so task numerics cannot be
  perturbed by fusion into the slab reads.
* Donation: with ``donate=True`` the staged graph-input buffers are
  donated to the program (re-staged per rep); params and the slabs are
  never donated — the "whole-program donation vector" is exactly the
  per-run transient state, which is what makes repeated runs safe.

Semantics note: XLA owns the program, so a value feeding neither the
final output, an exchange, nor the end-of-run fence tip may be
dead-code-eliminated — unlike the interpreted rungs, which dispatch
every placed task.  The DAGs this repo executes route every task into
the final logits, so the distinction is theoretical there.

The single-participating-device special case (every task on one core —
the bench's single-chip legs) skips the mesh entirely: one plain jitted
program with the same per-task barriers.
"""

from __future__ import annotations
# dls-lint: allow-file(DET001) compiled-path timing harness: wall time IS the measured quantity

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.graph import TaskGraph
from ..core.schedule import Schedule
from ..sched.linearize import ProgramIR, linearize
from .rebatch import extract_steps
from .dispatch_plan import propagate_avals


def _leaf_bytes(aval_tree: Any) -> int:
    return sum(
        int(np.prod(s.shape)) * s.dtype.itemsize
        for s in jax.tree_util.tree_leaves(aval_tree)
    )


def _zeros_of(aval_tree: Any) -> Any:
    return jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype), aval_tree
    )


def _input_sig(graph_input: Any) -> Tuple:
    """Structural identity of the graph input (treedef + leaf avals) —
    part of the program signature because the lowered program bakes
    placeholder shapes at trace time."""
    leaves, treedef = jax.tree_util.tree_flatten(graph_input)
    return (
        str(treedef),
        tuple(
            (tuple(np.asarray(l).shape), np.asarray(l).dtype.str)
            for l in leaves
        ),
    )


@dataclass
class CompiledSchedule:
    """One whole-program executable for a placed schedule.

    Build with :meth:`build`; run with :meth:`run` (same return contract
    as the other execution paths).  ``signature()`` is the deterministic
    lowering identity: equal signatures mean structurally identical
    programs (same phases, exchanges, slab layouts, donation).
    """

    backend: Any
    graph: TaskGraph
    ir: ProgramIR
    donate: bool
    n_devices: int
    param_bytes_per_node: Dict[str, int]
    transfer_edges: int
    transfer_bytes: int
    _fn: Any = field(repr=False, default=None)
    _slabs: Tuple[Any, ...] = field(repr=False, default=())
    _in_treedef: Any = field(repr=False, default=None)
    _in_shardings: Tuple[Any, ...] = field(repr=False, default=())
    _final_tid: Optional[str] = None
    _final_treedef: Any = field(repr=False, default=None)
    _owner_index: int = 0
    _tip_nodes: Tuple[str, ...] = ()
    _mesh: Any = field(repr=False, default=None)
    _signature: Tuple = ()
    _single_device: Any = field(repr=False, default=None)
    # static memory-profiler tables: (dst_node, tid, bytes) per exchange,
    # and the final output's (node, bytes) — avals are not retained, so
    # the sizes are frozen at build time
    _exchange_table: Tuple = ()
    _final_out: Tuple = ()

    # -- construction ------------------------------------------------------

    @classmethod
    def build(
        cls,
        backend: Any,
        graph: TaskGraph,
        schedule: Schedule,
        params: Dict[str, Any],
        graph_input: Any,
        donate: bool = False,
        pre_analysis: bool = True,
        pre_report: Any = None,
    ) -> "CompiledSchedule":
        """Lower ``schedule`` over ``backend``'s cluster.

        Raises :class:`..analysis.AnalysisError` when the per-node orders
        admit no global collective order (COL002 — always fatal: there is
        no program to build) or, when the gate is enabled, when the
        collective-ordering pass rejects the lowered IR (COL001/COL004).
        """
        from ..analysis import (
            AnalysisError,
            analyze_schedule_lowerability,
            gate_enabled,
            pre_execution_gate,
        )

        graph.freeze()
        device_order = [d.node_id for d in backend.cluster]
        rep, ir = analyze_schedule_lowerability(
            graph, schedule, device_order=device_order
        )
        if ir is None:
            raise AnalysisError(rep)  # COL002: unlowerable, gate or not
        if pre_analysis and gate_enabled():
            pre_execution_gate(
                graph, backend.cluster, schedule, backend="device",
                program=ir, precomputed=pre_report,
            )
        if not ir.order:
            raise ValueError(
                "schedule places no executable tasks; nothing to lower"
            )
        avals = propagate_avals(graph, ir.order, params, graph_input)
        tbytes = sum(
            _leaf_bytes(avals[ex.tid])
            for ph in ir.phases
            for ex in ph.exchanges
        )
        self = cls(
            backend=backend,
            graph=graph,
            ir=ir,
            donate=donate,
            n_devices=len(ir.devices),
            param_bytes_per_node={},
            transfer_edges=ir.n_exchanges,
            transfer_bytes=tbytes,
        )
        self._exchange_table = tuple(
            (ex.dst, ex.tid, _leaf_bytes(avals[ex.tid]))
            for ph in ir.phases
            for ex in ph.exchanges
        )
        if len(ir.devices) == 1:
            self._build_single(params, graph_input, avals)
        else:
            self._build_mesh(params, graph_input, avals)
        if self._final_tid is not None:
            owner = ir.devices[self._owner_index]
            self._final_out = (
                self._final_tid, owner, _leaf_bytes(avals[self._final_tid])
            )
        if pre_analysis and gate_enabled():
            # donation invariant (analysis/donation_pass): the donation
            # vector must cover only per-run transient inputs — donating
            # the aliased param slab would corrupt every later rep
            from ..analysis.donation_pass import analyze_donation

            analyze_donation(self).raise_if_errors()
        return self

    def donation_summary(self) -> Dict[str, Any]:
        """Static donation metadata for ``analysis/donation_pass``: which
        jit argument positions hold the (aliased, rep-crossing) param
        slabs, which hold the per-run transient input leaves, and which
        the program donates."""
        if self._single_device is not None:
            # program(placed_params, x): donation covers the graph input
            return {
                "path": "single",
                "param_argnums": (0,),
                "input_argnums": (1,),
                "donated_argnums": (1,) if self.donate else (),
            }
        n_in = len(self._in_shardings)
        return {
            "path": "mesh",
            "param_argnums": (0,),  # the dtype-keyed slab tuple
            "input_argnums": tuple(range(1, 1 + n_in)),
            "donated_argnums": (
                tuple(range(1, 1 + n_in)) if self.donate else ()
            ),
        }

    def _needed_globals(self, node: str) -> List[str]:
        """Ordered dedupe of the param globals ``node``'s tasks read."""
        seen: Dict[str, None] = {}
        for ph in self.ir.phases:
            for tid in ph.compute.get(node, ()):
                for _, g in self.graph[tid].param_items():
                    seen.setdefault(g)
        return list(seen)

    # -- single-device lowering -------------------------------------------

    def _build_single(
        self, params: Dict[str, Any], graph_input: Any, avals: Dict[str, Any]
    ) -> None:
        node = self.ir.devices[0]
        dev = self.backend.cluster[node].jax_device
        self._single_device = dev
        globs = self._needed_globals(node)
        placed = {g: jax.device_put(params[g], dev) for g in globs}
        jax.block_until_ready(list(placed.values()))
        self.param_bytes_per_node = {
            node: sum(_leaf_bytes(placed[g]) for g in globs)
        }
        final_tid = (
            self.graph.topo_order[-1]
            if self.graph.topo_order
            and self.graph.topo_order[-1] in set(self.ir.order)
            else self.ir.order[-1]
        )
        self._final_tid = final_tid
        self._tip_nodes = (node,)
        self._slabs = (placed,)
        self._signature = (
            "single", node, self.ir.signature(), tuple(globs), self.donate,
            _input_sig(graph_input),
        )
        cache = self.backend._prog_cache.setdefault(self.graph, {})
        cached = cache.get(self._signature)
        if cached is not None:
            self.backend.jit_cache_hits += 1
            self._fn = cached
            return
        self.backend.jit_cache_misses += 1

        steps = extract_steps(self.graph, self.ir.order)
        last_tid = self.ir.order[-1]

        def program(pvals, x):
            vals: Dict[str, Any] = {}
            for tid, fn, pitems, aids in steps:
                pd = {loc: pvals[g] for loc, g in pitems}
                args = [vals[d] for d in aids] if aids else [x]
                vals[tid] = jax.lax.optimization_barrier(fn(pd, *args))
            tip_leaf = jax.tree_util.tree_leaves(vals[last_tid])[-1]
            tip = tip_leaf.reshape(-1)[:1].astype(jnp.float32)
            return vals[final_tid], tip

        donate_argnums = (1,) if self.donate else ()
        self._fn = jax.jit(program, donate_argnums=donate_argnums)
        cache[self._signature] = self._fn

    # -- mesh lowering -----------------------------------------------------

    def _build_mesh(
        self, params: Dict[str, Any], graph_input: Any, avals: Dict[str, Any]
    ) -> None:
        ir = self.ir
        graph = self.graph
        devices = ir.devices
        n_dev = len(devices)
        jax_devs = [self.backend.cluster[d].jax_device for d in devices]
        mesh = Mesh(np.array(jax_devs), ("dev",))
        self._mesh = mesh
        dix = ir.device_index

        # ---- parameter slabs: per-device per-dtype flat concat -----------
        # layout[node][g] = (treedef, ((dtype_key, offset, size, shape),))
        layout: Dict[str, Dict[str, Tuple[Any, Tuple]]] = {}
        parts: Dict[str, Dict[str, List[np.ndarray]]] = {}
        sizes: Dict[str, Dict[str, int]] = {}
        bytes_per_node: Dict[str, int] = {}
        sig_layout = []
        for node in devices:
            layout[node] = {}
            parts[node] = {}
            sizes[node] = {}
            bytes_per_node[node] = 0
            for g in self._needed_globals(node):
                leaves, treedef = jax.tree_util.tree_flatten(params[g])
                entries = []
                for leaf in leaves:
                    arr = np.asarray(leaf)
                    key = arr.dtype.str
                    off = sizes[node].setdefault(key, 0)
                    parts[node].setdefault(key, []).append(arr.reshape(-1))
                    sizes[node][key] = off + arr.size
                    bytes_per_node[node] += arr.nbytes
                    entries.append((key, off, arr.size, tuple(arr.shape)))
                layout[node][g] = (treedef, tuple(entries))
                sig_layout.append((node, g, tuple(entries)))
        self.param_bytes_per_node = bytes_per_node

        dtype_keys = sorted({k for s in sizes.values() for k in s})
        slab_sharding = NamedSharding(mesh, P("dev"))
        slabs = []
        for key in dtype_keys:
            b_max = max(
                (sizes[n].get(key, 0) for n in devices), default=0
            )
            b_max = max(b_max, 1)
            rows = []
            for i, node in enumerate(devices):
                row = np.zeros((b_max,), dtype=np.dtype(key))
                chunks = parts[node].get(key)
                if chunks:
                    flat = np.concatenate(chunks)
                    row[: flat.size] = flat
                rows.append(
                    jax.device_put(row.reshape(1, b_max), jax_devs[i])
                )
            slabs.append(
                jax.make_array_from_single_device_arrays(
                    (n_dev, b_max), slab_sharding, rows
                )
            )
        jax.block_until_ready(slabs)
        self._slabs = tuple(slabs)
        key_pos = {k: i for i, k in enumerate(dtype_keys)}

        # ---- input staging layout ----------------------------------------
        in_leaves, in_treedef = jax.tree_util.tree_flatten(graph_input)
        self._in_treedef = in_treedef
        in_shardings = []
        for leaf in in_leaves:
            nd = np.asarray(leaf).ndim
            in_shardings.append(
                NamedSharding(mesh, P("dev", *([None] * nd)))
            )
        self._in_shardings = tuple(in_shardings)
        n_in = len(in_leaves)

        # ---- program body -------------------------------------------------
        ordered = set(ir.order)
        final_tid = (
            graph.topo_order[-1]
            if graph.topo_order and graph.topo_order[-1] in ordered
            else ir.order[-1]
        )
        self._final_tid = final_tid
        self._final_treedef = jax.tree_util.tree_structure(avals[final_tid])
        placed_on = {
            t: n for ph in ir.phases for n, ts in ph.compute.items()
            for t in ts
        }
        self._owner_index = dix[placed_on[final_tid]]
        self._tip_nodes = devices
        self._signature = (
            "mesh", devices, ir.signature(), tuple(sig_layout),
            tuple(dtype_keys), self.donate, _input_sig(graph_input),
        )
        cache = self.backend._prog_cache.setdefault(graph, {})
        cached = cache.get(self._signature)
        if cached is not None:
            self.backend.jit_cache_hits += 1
            self._fn = cached
            return
        self.backend.jit_cache_misses += 1

        last_tid = {}
        for tid in ir.order:
            last_tid[placed_on[tid]] = tid

        # static per-(phase, device) step tables; extracted once so the
        # traced closures never capture the graph
        phase_steps = {
            (ph.index, node): extract_steps(graph, ph.compute.get(node, ()))
            for ph in ir.phases
            for node in devices
        }
        reconstruct_layout = layout

        def rebuild_params(node: str, globs_needed: List[str], slabs_local):
            out = {}
            for g in globs_needed:
                treedef, entries = reconstruct_layout[node][g]
                leaves = [
                    jax.lax.dynamic_slice_in_dim(
                        slabs_local[key_pos[key]][0], off, size
                    ).reshape(shape)
                    for key, off, size, shape in entries
                ]
                out[g] = jax.tree_util.tree_unflatten(treedef, leaves)
            return out

        ir_phases = ir.phases
        live_out = ir.live_out

        def program(slabs_local, *in_leaf_local):
            idx = jax.lax.axis_index("dev")
            x_local = jax.tree_util.tree_unflatten(
                in_treedef, [leaf[0] for leaf in in_leaf_local]
            )
            regs: Dict[str, Any] = {}
            for ph in ir_phases:
                exports = live_out.get(ph.index, ())
                if exports:
                    branches = []
                    for node in devices:
                        branches.append(
                            _make_branch(
                                phase_steps[(ph.index, node)],
                                node, exports, regs, slabs_local,
                                x_local, avals, rebuild_params, graph,
                            )
                        )
                    outs = jax.lax.switch(idx, branches, jnp.int32(0))
                    for tid, val in zip(exports, outs):
                        regs[tid] = val
                for ex in ph.exchanges:
                    src_i, dst_i = dix[ex.src], dix[ex.dst]
                    old = regs[ex.tid]
                    recv = jax.tree_util.tree_map(
                        lambda v: jax.lax.ppermute(
                            v, "dev", ((src_i, dst_i),)
                        ),
                        old,
                    )
                    keep_old = idx != jnp.int32(dst_i)
                    regs[ex.tid] = jax.tree_util.tree_map(
                        lambda o, r: jnp.where(keep_old, o, r), old, recv
                    )
            # fence tip: each device's last computed value, one element
            def make_tip(node):
                def tip(_):
                    t = last_tid.get(node)
                    if t is None:
                        return jnp.zeros((1,), jnp.float32)
                    leaf = jax.tree_util.tree_leaves(regs[t])[-1]
                    return leaf.reshape(-1)[:1].astype(jnp.float32)
                return tip

            tip = jax.lax.switch(
                idx, [make_tip(n) for n in devices], jnp.int32(0)
            )
            outs = [jnp.expand_dims(tip, 0)]
            fin_leaves = jax.tree_util.tree_leaves(regs[final_tid])
            outs.extend(jnp.expand_dims(l, 0) for l in fin_leaves)
            return tuple(outs)

        from ..parallel.compat import shard_map

        in_specs = (
            tuple(P("dev") for _ in dtype_keys),
            *(
                P("dev", *([None] * np.asarray(l).ndim))
                for l in in_leaves
            ),
        )
        # outputs: the (1,) fence tip, then every final-value leaf; each
        # gains a leading "dev" axis via the local expand_dims above
        out_ranks = [1] + [
            len(s.shape)
            for s in jax.tree_util.tree_leaves(avals[final_tid])
        ]
        out_specs = tuple(
            P("dev", *([None] * nd)) for nd in out_ranks
        )
        mapped = shard_map(
            program,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            check_vma=False,
        )
        donate_argnums = (
            tuple(range(1, 1 + n_in)) if self.donate else ()
        )
        self._fn = jax.jit(mapped, donate_argnums=donate_argnums)
        cache[self._signature] = self._fn

    # -- identity ----------------------------------------------------------

    def signature(self) -> Tuple:
        return self._signature

    # -- execution ---------------------------------------------------------

    @property
    def n_launches_per_run(self) -> int:
        """Host calls per run: one staging put per input leaf (each a
        single sharded ``device_put``) plus the program launch."""
        n_in = (
            len(jax.tree_util.tree_leaves(self._in_shardings))
            if self._single_device is None else 1
        )
        return n_in + 1

    def run(
        self,
        graph_input: Any,
        fence: bool = True,
        tracer: Any = None,
        metrics: Any = None,
        mem: Any = None,
    ) -> Tuple[
        Any, Dict, int, int, int, int, Dict[str, Any], Dict[str, float]
    ]:
        """Stage, launch, (optionally) fence.  Same 8-tuple contract as
        ``DispatchPlan.run`` / ``_run_segmented``.

        ``mem`` (obs.memprof.MemoryProfiler, optional): the compiled path
        has no per-task host boundaries, so its memory events are the
        build-time model — per-node param slabs, per-node input staging,
        the static per-exchange transfer table, and the final output —
        recorded once per run (labels replace across reps)."""
        t0 = time.perf_counter()
        if self._single_device is not None:
            x = jax.device_put(graph_input, self._single_device)
            t_stage = time.perf_counter()
            final, tip = self._fn(self._slabs[0], x)
            n_disp = 2
            t_launch = time.perf_counter()
            tips_by_node = {self.ir.devices[0]: tip}
        else:
            leaves = jax.tree_util.tree_leaves(graph_input)
            staged = [
                jax.device_put(
                    np.broadcast_to(
                        np.asarray(leaf)[None],
                        (self.n_devices, *np.asarray(leaf).shape),
                    ),
                    sh,
                )
                for leaf, sh in zip(leaves, self._in_shardings)
            ]
            t_stage = time.perf_counter()
            outs = self._fn(self._slabs, *staged)
            n_disp = len(staged) + 1
            t_launch = time.perf_counter()
            # everything below is result COLLECTION, not dispatch: the
            # jitted call above returns at enqueue, but materializing
            # per-device shards (addressable_shards / shard.data) can
            # block on the program's execution, so it sits outside the
            # launch_s window — like the fence, it measures the device,
            # not the host loop
            tips, fin_rows = outs[0], outs[1:]
            node_by_dev = {
                self.backend.cluster[n].jax_device: n
                for n in self.ir.devices
            }
            tips_by_node = {
                node_by_dev[s.device]: s.data
                for s in tips.addressable_shards
            }
            final = None
            if self._final_tid is not None:
                fin_leaves = []
                for row in fin_rows:
                    shard = next(
                        s for s in row.addressable_shards
                        if s.device
                        == self.backend.cluster[
                            self.ir.devices[self._owner_index]
                        ].jax_device
                    )
                    fin_leaves.append(shard.data[0])
                final = jax.tree_util.tree_unflatten(
                    self._final_treedef, fin_leaves
                )

        n_fences = 0
        if fence:
            t_f0 = time.perf_counter() if tracer is not None else 0.0
            n_fences = self.backend._fence_run(tips_by_node)
            if tracer is not None:
                t_f1 = time.perf_counter()
                tracer.complete(
                    "fence", t_f0, t_f1, track="host", cat="collect",
                    devices=len(tips_by_node),
                )
                # one fused program span per device: the compiled path
                # has no per-task boundaries, so the device rows carry a
                # single cat="program" span each (obs/attribution.py
                # degrades to program-level attribution on these)
                for node in self.ir.devices:
                    n_tasks = sum(
                        len(ph.compute.get(node, ()))
                        for ph in self.ir.phases
                    )
                    tracer.complete(
                        "program", t_stage, t_f1, track=node,
                        cat="program", tasks=n_tasks,
                        phases=len(self.ir.phases),
                    )
        if metrics is not None:
            metrics.counter("compiled.launches").inc(n_disp)
            metrics.counter("compiled.exchanges").inc(self.transfer_edges)
        if mem is not None:
            # recorded after the phase windows close so stage_s/launch_s
            # stay clean; sizes are the static build-time tables
            # mesh staging broadcasts: each device holds one row, so the
            # per-device input footprint equals the host input's bytes
            in_bytes = sum(
                np.asarray(l).nbytes
                for l in jax.tree_util.tree_leaves(graph_input)
            )
            for node in self.ir.devices:
                pb = self.param_bytes_per_node.get(node, 0)
                if pb:
                    mem.alloc(node, "slab:params", pb, "params")
                mem.alloc(node, "input", in_bytes, "activations")
            for dst, tid, nb in self._exchange_table:
                mem.alloc(dst, f"xfer:{tid}", nb, "transfers")
            if self._final_out:
                ftid, owner, nb = self._final_out
                mem.alloc(owner, f"out:{ftid}", nb, "activations")
        phases = {
            "loop_s": t_launch - t0,
            "stage_s": t_stage - t0,
            "launch_s": t_launch - t_stage,
        }
        return (
            final, {}, self.transfer_edges, self.transfer_bytes,
            n_fences, n_disp, {}, phases,
        )


def _make_branch(
    steps, node, exports, regs, slabs_local, x_local, avals,
    rebuild_params, graph,
):
    """Phase branch for one device: run its tasks (barrier-separated),
    return the phase's export tuple (zeros for other devices' tasks)."""
    globs: Dict[str, None] = {}
    for _tid, _fn, pitems, _aids in steps:
        for _, g in pitems:
            globs.setdefault(g)
    globs_needed = list(globs)

    def branch(_):
        pvals = rebuild_params(node, globs_needed, slabs_local)
        if pvals:
            # pin slab reconstruction as its own computation: task
            # numerics must match the interpreted path, where params
            # arrive as materialized buffers
            flat, td = jax.tree_util.tree_flatten(pvals)
            flat = jax.lax.optimization_barrier(tuple(flat))
            pvals = jax.tree_util.tree_unflatten(td, list(flat))
        vals: Dict[str, Any] = {}
        for tid, fn, pitems, aids in steps:
            pd = {loc: pvals[g] for loc, g in pitems}
            args = (
                [vals[d] if d in vals else regs[d] for d in aids]
                if aids else [x_local]
            )
            vals[tid] = jax.lax.optimization_barrier(fn(pd, *args))
        return tuple(
            vals[t] if t in vals else _zeros_of(avals[t])
            for t in exports
        )

    return branch
