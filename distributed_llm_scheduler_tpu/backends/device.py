"""Real device execution backend: placed, compiled, measured.

This is the seam the whole rebuild hinges on (SURVEY.md §3.1): the
scheduler's placement decision (host Python, L2) becomes actual dispatch of
XLA-compiled per-task executables onto accelerator devices (L0).  Where the
reference *simulates* completion inside ``assign_task_to_node`` (reference
``schedulers.py:101-102``) and replays a cost model (reference
``simulation.py:216-278``), here:

* each task's ``fn`` is jit-compiled once per placement device and cached;
* parameters are ``jax.device_put`` onto the core that first needs them
  (the reference's ``param_locations`` bookkeeping made physical);
* a dependency edge whose producer and consumer sit on different cores
  becomes a real device-to-device transfer (ICI on a TPU slice) via
  ``jax.device_put`` of the producer's output;
* execution is asynchronous dispatch in the **schedule's order**: each JAX
  device executes its enqueued ops in FIFO stream order, so the order tasks
  are dispatched from Python IS the per-device execution order.  Dispatching
  honors each node's scheduled task list (``Schedule.per_node``), not bare
  topological order — a policy that computed a 1F1B microbatch interleaving
  (sched/eventsim.py) gets that interleaving in real execution, where
  Kahn-wave dispatch would re-introduce the head-of-line blocking the
  ordering was computed to avoid.  Makespan ends at ONE readback fence
  whose value depends on every device's last output (its fixed round-trip
  netted out) because ``block_until_ready`` is unreliable through the
  axon tunnel (``utils/costmodel.readback_fence``); on such platforms the
  measured cost model uses the fence-amortized
  ``utils/costmodel.calibrate``, NOT this backend's ``profile`` mode.

Works identically on a real TPU slice and on the CPU-faked 8-device mesh
(``--xla_force_host_platform_device_count``), which is how tests exercise
multi-device behavior without hardware — mirroring the reference's
in-process "multi-node" strategy (SURVEY.md §4).
"""

from __future__ import annotations
# dls-lint: allow-file(DET001) real-device execution timing: wall time IS the measured quantity

import time
import warnings
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax

from ..core.cluster import Cluster
from ..core.graph import TaskGraph
from ..core.schedule import Schedule, TaskTiming


@dataclass
class DeviceReport:
    """Measured execution result for one placed DAG run."""

    policy: str
    makespan_s: float
    output: Any
    n_devices: int
    transfer_edges: int
    transfer_bytes: int
    param_bytes_placed: Dict[str, int]
    compile_s: float
    # only in profile mode: per-task measured wall times
    timings: Dict[str, TaskTiming] = field(default_factory=dict)
    # per-device HBM peaks, when the platform reports memory_stats
    peak_hbm_bytes: Dict[str, int] = field(default_factory=dict)
    # executable launches issued (== placed tasks per-task; == segments
    # under segment fusion; == plan steps — coalesced groups count once —
    # under planned dispatch)
    n_dispatches: int = 0
    # host wall seconds spent inside the dispatch loop, per rep (launch +
    # staging; end-of-run fence excluded).  Launches return at enqueue, so
    # on async platforms this IS the host-side dispatch overhead the
    # planned path exists to shrink; on platforms where a launch can
    # block on device compute it is an upper bound.
    dispatch_overhead_s: float = 0.0
    # per-rep breakdown of the loop wall: planned dispatch reports
    # {loop_s, stage_s (input placement + batched transfers), launch_s};
    # the legacy paths report {loop_s}
    dispatch_phases: Dict[str, float] = field(default_factory=dict)
    # True when the run used the pre-planned fast path (dispatch_plan)
    planned: bool = False
    # True when the run used the whole-program compiled path
    # (compiled_schedule): ONE launch per run, cross-device edges as
    # in-program collectives
    compiled: bool = False
    # execute(keep_outputs=True): per-task outputs retained for elastic
    # recovery (every executed task per-task; segment exports under
    # segment fusion).  Keys feed reschedule()/execute(ext_outputs=...)
    task_outputs: Dict[str, Any] = field(default_factory=dict)
    # execute(stream_params=True): streaming statistics.  ``streamed`` is
    # the explicit mode flag — a streamed run that happened to load zero
    # params still reports its (all-zero) stats, so the mode is always
    # distinguishable in the JSON
    streamed: bool = False
    param_loads: int = 0
    # batched transfer calls issued (<= param_loads: a task's missing
    # params go up in one device_put) and total bytes streamed — the
    # numerator of the host-link bandwidth bound
    param_load_calls: int = 0
    param_load_bytes: int = 0
    param_evictions: int = 0
    peak_param_bytes: Dict[str, int] = field(default_factory=dict)
    # traced runs only: the run doctor's measured critical-path summary
    # (obs/attribution.py) over this execute's span window — makespan
    # split into compute/transfer/dispatch/idle plus stragglers/bubbles
    attribution: Optional[Dict[str, Any]] = None
    # memprof runs only: the memory doctor's per-device timeline summary
    # (obs/memprof.py) — peaks, watermark attribution buckets, and
    # platform reconciliation where memory_stats() reported
    memory: Optional[Dict[str, Any]] = None

    @property
    def total_param_gb_placed(self) -> float:
        return sum(self.param_bytes_placed.values()) / 1024**3

    def summary(self) -> Dict[str, Any]:
        return {
            "policy": self.policy,
            "makespan_ms": self.makespan_s * 1e3,
            "n_devices": self.n_devices,
            "transfer_edges": self.transfer_edges,
            "transfer_mb": self.transfer_bytes / 1024**2,
            "param_gb_placed": self.total_param_gb_placed,
            "compile_s": self.compile_s,
            "n_dispatches": self.n_dispatches,
            "dispatch_overhead_ms": self.dispatch_overhead_s * 1e3,
            "dispatch_phases_ms": {
                k: v * 1e3 for k, v in self.dispatch_phases.items()
            },
            "planned": self.planned,
            "compiled": self.compiled,
            "peak_hbm_gb": {
                k: v / 1024**3 for k, v in self.peak_hbm_bytes.items()
            },
            **(
                {
                    "param_loads": self.param_loads,
                    "param_load_calls": self.param_load_calls,
                    "param_load_mb": self.param_load_bytes / 1024**2,
                    "param_evictions": self.param_evictions,
                    "peak_param_gb": {
                        k: v / 1024**3
                        for k, v in self.peak_param_bytes.items()
                    },
                }
                if self.streamed
                else {}
            ),
            **(
                {"attribution": self.attribution}
                if self.attribution is not None
                else {}
            ),
            **(
                {"memory": self.memory}
                if self.memory is not None
                else {}
            ),
        }


def _array_bytes(x: Any) -> int:
    """Bytes of an array or an arbitrary pytree of arrays (train-step tasks
    exchange dicts of grads)."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(x):
        try:
            total += leaf.size * leaf.dtype.itemsize
        except Exception:
            pass
    return total


class DeviceBackend:
    """Executes a scheduled TaskGraph on live JAX devices.

    ``cluster`` must be built with ``Cluster.from_jax_devices`` (each
    DeviceState carries its ``jax_device``); the schedule's placement maps
    task -> DeviceState -> real device.
    """

    def __init__(self, cluster: Cluster, pre_analysis: bool = True):
        missing = [d.node_id for d in cluster if d.jax_device is None]
        if missing:
            raise ValueError(
                f"cluster devices {missing} have no bound jax_device; "
                "build the cluster with Cluster.from_jax_devices()"
            )
        self.cluster = cluster
        # opt-out static pre-execution gate (see analysis/):
        # pre_analysis=False per instance, DLS_SKIP_ANALYSIS=1 globally
        self.pre_analysis = pre_analysis
        # fn object -> jitted fn; survives across execute() calls so
        # benchmark reruns don't pay compilation again
        self._jit_cache: Dict[Any, Callable[..., Any]] = {}
        # (fn object, donate_argnums) -> jitted donating variant; separate
        # from _jit_cache so tasks sharing one fn but dying-buffer patterns
        # that differ never collide
        self._donate_jit_cache: Dict[Tuple[Any, Tuple[int, ...]], Any] = {}
        # graph -> {(tids, exports): jitted segment fn}; weak so a dead
        # graph releases its compiled segments
        import weakref

        self._seg_cache: "weakref.WeakKeyDictionary" = (
            weakref.WeakKeyDictionary()
        )
        # graph -> {(tids, exports, donate_argnums): jitted coalesced
        # launch group} (dispatch_plan coalescing); weak like _seg_cache
        self._group_cache: "weakref.WeakKeyDictionary" = (
            weakref.WeakKeyDictionary()
        )
        # graph -> {program signature: jitted whole-program callable}
        # (compiled_schedule); the signature pins every structural input
        # (IR, slab layout, input avals, donation), so repeated executes
        # of one schedule reuse the XLA executable while slabs restage
        # from the CURRENT params
        self._prog_cache: "weakref.WeakKeyDictionary" = (
            weakref.WeakKeyDictionary()
        )
        # cumulative jit-cache hit/miss counts across every cache above;
        # execute() reports the per-call delta into the metrics registry
        # (obs) as compile.jit_cache_{hits,misses}
        self.jit_cache_hits = 0
        self.jit_cache_misses = 0

    def _fence_device(self):
        """The device the end-of-run fence reads back from."""
        return self.cluster.devices[0].jax_device

    def _fence_run(self, last_on_device: Dict[str, Any]) -> int:
        """Fence ALL dispatched work with ONE readback; returns the fence
        count (1) to subtract as RTT.

        One element of each device's last output is pulled onto the fence
        device and their (dependent) combination read back: the bytes
        cannot exist on the host before every contributing device's queue
        drained (per-device queues are FIFO), so the single readback
        proves completion everywhere — one RTT regardless of device
        count.  Per-device sequential fences would over-subtract when an
        early fence's round-trip overlaps a straggler device's remaining
        compute.  Deliberately NO ``block_until_ready`` on the outputs
        first: through the axon tunnel that call costs a full extra
        round-trip (~70-80 ms on a bad reconnect) that the single-RTT
        correction would not net out — exactly the bias that made round
        2's segmented makespan read 82.6 ms for a ~10 ms program (and it
        adds nothing: the dependent readback already implies completion).
        Shared by the per-task and segment-fused paths so their makespan
        measurements cannot drift.
        """
        from ..utils.costmodel import readback_fence

        fence_dev = self._fence_device()
        tips = []
        for out in last_on_device.values():
            leaf = jax.tree_util.tree_leaves(out)[-1]
            tip = leaf[(0,) * leaf.ndim]
            tips.append(jax.device_put(tip, fence_dev))
        combined = tips[0]
        for t in tips[1:]:
            combined = combined + t.astype(combined.dtype)
        readback_fence(combined)
        return 1

    # -- placement ---------------------------------------------------------
    def place_params(
        self,
        graph: TaskGraph,
        schedule: Schedule,
        params: Dict[str, Any],
        mem: Any = None,
    ) -> Tuple[Dict[Tuple[str, str], Any], Dict[str, int]]:
        """Put each param onto every device that runs a task needing it.

        Returns ``(param_name, node_id) -> on-device array`` plus the bytes
        placed per node.  A param needed on k devices is replicated k times —
        the physical realization of the reference's ``param_locations`` sets.
        """
        placement = schedule.placement
        placed: Dict[Tuple[str, str], Any] = {}
        bytes_per_node: Dict[str, int] = {d.node_id: 0 for d in self.cluster}
        for tid, node_id in placement.items():
            task = graph[tid]
            dev = self.cluster[node_id].jax_device
            for p in task.params_needed:
                key = (p, node_id)
                if key not in placed:
                    placed[key] = jax.device_put(params[p], dev)
                    nb = _array_bytes(params[p])
                    bytes_per_node[node_id] += nb
                    if mem is not None:
                        mem.alloc(node_id, f"param:{p}", nb, "params")
        # placed values may be pytrees (e.g. QParam int8+scale pairs), so
        # use the pytree-aware barrier
        jax.block_until_ready(list(placed.values()))
        return placed, bytes_per_node

    # -- parameter streaming ----------------------------------------------
    class _ParamStreamer:
        """On-demand parameter residency with eviction under a per-node HBM
        budget — the reference's param-cache/eviction model (reference
        ``schedulers.py:404-442``) made PHYSICAL: a node whose weights
        exceed its budget loads each param at first use and evicts
        residents to make room, so a model larger than a device's HBM
        still executes (slower — streaming trades bandwidth for capacity,
        exactly the constraint the scheduler's policies optimize around).

        Designed to approach the host-link bandwidth bound (VERDICT r3
        next #2 — the on-demand/fence-per-eviction v1 ran 284x slow,
        RTT-latency-bound, because every eviction drained the whole device
        queue):

        * **Plan-aware prefetch**: the schedule's per-node task order is
          known up front (``plan``), so params for the next ``lookahead``
          tasks are loaded while current compute is in flight — loads
          overlap compute and each other instead of serializing.
        * **Belady eviction**: with the plan, the victim is the resident
          param whose next use is farthest in the future (optimal for
          misses); LRU is the planless fallback.
        * **Batched loads**: all of a task's missing params go up in ONE
          ``device_put`` call (one dispatch per task, not per param).
        * **Minimal-wait deletion**: an evicted buffer may still feed
          queued ops, so it enters a graveyard tagged with its last
          consumer's per-node FIFO step; freeing its memory waits only on
          that consumer's output (per-device queues are FIFO, so that one
          wait proves every earlier consumer finished), and a fence-step
          watermark makes waits on already-fenced steps free.  v1 instead
          fenced the node's LATEST output before every eviction — a full
          queue drain per load.

        The ``bytes`` ledger counts resident + graveyard (memory is not
        free until deletion), so ``peak`` stays physically honest.
        """

        def __init__(
            self,
            cluster: Cluster,
            params: Dict[str, Any],
            plan: Optional[Dict[str, List[Tuple[str, Tuple[str, ...]]]]] = None,
            lookahead: int = 8,
            mem: Any = None,
        ):
            self.cluster = cluster
            self.host_params = params
            # optional obs/memprof recorder: loads are param births,
            # graveyard flushes are the matching frees
            self.mem = mem
            self.resident: Dict[str, Dict[str, Any]] = {
                d.node_id: {} for d in cluster
            }
            self.bytes: Dict[str, int] = {d.node_id: 0 for d in cluster}
            self.peak: Dict[str, int] = {d.node_id: 0 for d in cluster}
            self.budget: Dict[str, int] = {
                d.node_id: int(d.total_memory * 1024**3) for d in cluster
            }
            self.last_use: Dict[str, Dict[str, int]] = {
                d.node_id: {} for d in cluster
            }
            # plan: node -> [(tid, param globals)] in dispatch order
            self.plan = plan or {}
            self.pos: Dict[str, int] = {n: -1 for n in self.plan}
            # node -> param -> ascending plan positions where it is used
            self.uses: Dict[str, Dict[str, List[int]]] = {}
            for n, entries in self.plan.items():
                u: Dict[str, List[int]] = {}
                for i, (_tid, globs) in enumerate(entries):
                    for g in globs:
                        u.setdefault(g, []).append(i)
                self.uses[n] = u
            self.lookahead = lookahead
            # eviction-safety bookkeeping (per node): monotonically
            # increasing dispatch step, last fenced step, each param's last
            # consumer (step, output), evicted-but-not-yet-freed buffers
            self.node_step: Dict[str, int] = {d.node_id: 0 for d in cluster}
            self.fenced_step: Dict[str, int] = {d.node_id: 0 for d in cluster}
            self.last_consumer: Dict[str, Dict[str, Tuple[int, Any]]] = {
                d.node_id: {} for d in cluster
            }
            self.graveyard: Dict[str, List[Tuple[int, Any, Any, int, str]]] = {
                d.node_id: [] for d in cluster
            }
            self.loads = 0
            self.load_calls = 0
            self.load_bytes = 0
            # loads that stalled a task's dispatch (param not resident at
            # get_task time) vs loads the prefetcher issued early — the
            # stall count is what latency-bound links actually pay for
            self.demand_misses = 0
            self.evictions = 0
            self._step = 0

        def note_task(self, node_id: str, globs, out: Any) -> None:
            """Record that a task consuming ``globs`` was dispatched with
            output ``out`` — the eviction fence anchor for those params."""
            self.node_step[node_id] += 1
            s = self.node_step[node_id]
            for g in globs:
                self.last_consumer[node_id][g] = (s, out)

        def _next_use(self, node_id: str, name: str) -> float:
            import bisect

            uses = self.uses.get(node_id, {}).get(name)
            if not uses:
                return float("inf")
            i = bisect.bisect_right(uses, self.pos.get(node_id, -1))
            return uses[i] if i < len(uses) else float("inf")

        def _flush(self, node_id: str, need_bytes: int) -> int:
            """Actually free graveyard memory, oldest consumer first, until
            ``need_bytes`` freed or the graveyard empties.  Waits only when
            an entry's consumer step is past the fence watermark — and then
            on that specific output, not the queue tip."""
            g = self.graveyard[node_id]
            g.sort(key=lambda e: e[0])
            freed = 0
            while g and freed < need_bytes:
                step, out, arr, nbytes, name = g.pop(0)
                if step > self.fenced_step[node_id] and out is not None:
                    jax.block_until_ready(out)
                    self.fenced_step[node_id] = step
                for leaf in jax.tree_util.tree_leaves(arr):
                    leaf.delete()
                self.bytes[node_id] -= nbytes
                freed += nbytes
                if self.mem is not None:
                    self.mem.free(node_id, f"param:{name}")
            return freed

        def _evict_one(
            self, node_id: str, pinned: set, horizon: Optional[int]
        ) -> int:
            """Move one victim to the graveyard.  Returns its bytes, 0 when
            nothing is evictable (only pinned residents), or -1 when the
            best victim is needed at/before ``horizon`` (prefetch would
            thrash — caller stops prefetching)."""
            res = self.resident[node_id]
            victims = [p for p in res if p not in pinned]
            if not victims:
                return 0
            if node_id in self.uses:
                victim = max(
                    victims, key=lambda p: self._next_use(node_id, p)
                )
                if (
                    horizon is not None
                    and self._next_use(node_id, victim) <= horizon
                ):
                    return -1
            else:
                lru = self.last_use[node_id]
                victim = min(victims, key=lambda p: lru.get(p, 0))
            arr = res.pop(victim)
            self.last_use[node_id].pop(victim, None)
            step, out = self.last_consumer[node_id].pop(victim, (0, None))
            nbytes = _array_bytes(arr)
            # bytes stay on the ledger until _flush deletes the buffer
            self.graveyard[node_id].append((step, out, arr, nbytes, victim))
            self.evictions += 1
            return nbytes

        def _load(self, node_id: str, names: List[str]) -> None:
            """ONE batched device_put for all of ``names``."""
            dev = self.cluster[node_id].jax_device
            # bridge through numpy: on CPU platforms device_put can ALIAS
            # the host buffer, and evicting an alias would delete the
            # caller's params out from under them; a numpy view forces the
            # device copy to own fresh memory, so delete() is always safe
            import numpy as _np

            hosts = [
                jax.tree_util.tree_map(
                    lambda leaf: _np.asarray(leaf), self.host_params[n]
                )
                for n in names
            ]
            arrs = jax.device_put(hosts, dev)
            self.load_calls += 1
            for n, a in zip(names, arrs):
                self.resident[node_id][n] = a
                # ledger from the PLACED bytes (dtype canonicalization can
                # make them differ from the host estimate; an asymmetric
                # ledger would drift and shrink the effective budget)
                nb = _array_bytes(a)
                self.bytes[node_id] += nb
                self.load_bytes += nb
                self.loads += 1
                self.last_use[node_id][n] = self._step
                if self.mem is not None:
                    self.mem.alloc(node_id, f"param:{n}", nb, "params")
            self.peak[node_id] = max(self.peak[node_id], self.bytes[node_id])

        def _ensure(
            self,
            node_id: str,
            names: List[str],
            pinned: set,
            horizon: Optional[int] = None,
        ) -> bool:
            """Make ``names`` resident, evicting/freeing as needed.  Returns
            False when stopped by the prefetch ``horizon`` (resident set is
            already needed sooner than the prefetch target)."""
            # dedupe: a fused task can alias two local names to one global
            # (fuse_linear_chains merges members sharing a param); loading
            # it twice would orphan a device buffer and inflate the ledger
            missing = list(dict.fromkeys(
                n for n in names if n not in self.resident[node_id]
            ))
            if not missing:
                return True
            need = sum(
                _array_bytes(self.host_params[n]) for n in missing
            )
            budget = self.budget[node_id]
            while self.bytes[node_id] + need > budget:
                deficit = self.bytes[node_id] + need - budget
                if self.graveyard[node_id]:
                    self._flush(node_id, deficit)
                    continue
                r = self._evict_one(node_id, pinned, horizon)
                if r == -1:
                    return False
                if r == 0:
                    if horizon is not None:
                        # prefetch must NEVER overshoot the budget: the
                        # over-budget escape exists for a task's own pinned
                        # params only (it cannot run without them); a
                        # speculative load has no such excuse
                        return False
                    break  # only the task's own params: allow over-budget
            self._load(node_id, missing)
            return True

        def get_task(self, tid: str, node_id: str, param_items) -> Dict[str, Any]:
            """Resident params for ``tid`` (loc -> array), then prefetch the
            next ``lookahead`` planned tasks' params into the budget."""
            self._step += 1
            items = tuple(param_items)
            names = [g for _, g in items]
            entries = self.plan.get(node_id)
            if entries is not None:
                # advance the plan cursor to this task; tasks skipped at
                # dispatch (failed upstreams) fall out of the walk
                i = self.pos[node_id] + 1
                while i < len(entries) and entries[i][0] != tid:
                    i += 1
                if i < len(entries):
                    self.pos[node_id] = i
            pinned = set(names)
            self.demand_misses += sum(
                1 for n in pinned if n not in self.resident[node_id]
            )
            self._ensure(node_id, names, pinned)
            for n in names:
                self.last_use[node_id][n] = self._step
            out = {loc: self.resident[node_id][g] for loc, g in items}
            if entries is not None:
                p = self.pos[node_id]
                stop = min(p + 1 + self.lookahead, len(entries))
                for j in range(p + 1, stop):
                    _t, globs = entries[j]
                    if not self._ensure(
                        node_id, list(globs), pinned | set(globs), horizon=j
                    ):
                        break
            return out

    # -- compilation -------------------------------------------------------
    def _jitted(self, graph: TaskGraph, tid: str,
                donate_argnums: Tuple[int, ...] = ()):
        """One jitted callable per distinct fn *object*: tasks that share a
        fn (all layers' ln1 via param_alias) share the jit wrapper, so the
        per-layer compile multiplicity disappears.  XLA still compiles one
        executable per placement device (input sharding is part of the
        cache key) — that per-device cost is inherent.

        ``donate_argnums`` (planned dispatch) selects a donating variant,
        cached per (fn, pattern) so differing dying-buffer patterns never
        collide; the empty pattern is the shared plain cache."""
        task = graph[tid]
        if task.fn is None:
            raise ValueError(
                f"task {tid!r} has no fn; this graph is schedule-only "
                "(synthetic DAGs execute on the simulated backend)"
            )
        if donate_argnums:
            key = (task.fn, donate_argnums)
            fn = self._donate_jit_cache.get(key)
            if fn is None:
                self.jit_cache_misses += 1
                fn = jax.jit(task.fn, donate_argnums=donate_argnums)
                self._donate_jit_cache[key] = fn
            else:
                self.jit_cache_hits += 1
            return fn
        fn = self._jit_cache.get(task.fn)
        if fn is None:
            self.jit_cache_misses += 1
            fn = jax.jit(task.fn)
            self._jit_cache[task.fn] = fn
        else:
            self.jit_cache_hits += 1
        return fn

    def _grouped_jitted(
        self,
        graph: TaskGraph,
        tids: Tuple[str, ...],
        exports: Tuple[str, ...],
        donate_argnums: Tuple[int, ...] = (),
    ):
        """Jitted coalesced launch group (dispatch_plan): ``tids`` run in
        order inside ONE executable, ``optimization_barrier`` between
        members keeping per-task numerics bit-identical to separate
        launches.  Cached per (graph, tids, exports, donate pattern) —
        same keying rationale as ``_segment_callable``."""
        per_graph = self._group_cache.setdefault(graph, {})
        key = (tids, exports, donate_argnums)
        fn = per_graph.get(key)
        if fn is None:
            from .dispatch_plan import _build_group_fn

            self.jit_cache_misses += 1
            fn = jax.jit(
                _build_group_fn(graph, tids, exports),
                donate_argnums=donate_argnums or None,
            )
            per_graph[key] = fn
        else:
            self.jit_cache_hits += 1
        return fn

    def warmup(
        self,
        graph: TaskGraph,
        schedule: Schedule,
        placed_params: Dict[Tuple[str, str], Any],
        graph_input: Any,
        segments: bool = False,
        ext_outputs: Optional[Dict[str, Any]] = None,
        streamer: Optional["DeviceBackend._ParamStreamer"] = None,
        rebatch: bool = True,
        segments_pre: Optional[
            List[Tuple[str, Tuple[str, ...], Tuple[str, ...]]]
        ] = None,
    ) -> float:
        """Compile every (fn, placement-device) combination ahead of time;
        returns seconds.

        Runs one full placed execution (outputs discarded) so jit caches are
        hot and subsequent ``execute`` timings measure execution, not
        compilation — the analog of XLA's compile-once/run-many contract.
        """
        t0 = time.perf_counter()
        if segments:
            self._run_segmented(
                graph, schedule, placed_params, graph_input, ext_outputs,
                rebatch=rebatch, streamer=streamer, segments_pre=segments_pre,
            )
        else:
            self._run(
                graph, schedule, placed_params, graph_input, profile=False,
                ext_outputs=ext_outputs, streamer=streamer,
            )
        return time.perf_counter() - t0

    # -- dispatch order ----------------------------------------------------
    @staticmethod
    def dispatch_order(graph: TaskGraph, schedule: Schedule) -> List[str]:
        """Global dispatch linearization honoring per-node scheduled order.

        Per-device XLA streams execute enqueued ops FIFO, so within one node
        the emitted sequence must be exactly ``schedule.per_node[node]`` —
        that list is the policy's decided execution order (1F1B interleaving
        for the pipeline policy).  Across nodes, a task can only be
        dispatched after its producers (Python needs their output handles,
        though not their completion — dispatch is async).  Greedy merge:
        repeatedly emit, among node-queue heads whose deps are all emitted
        (or unplaced, i.e. failed), the one the scheduler assigned earliest.
        If per-node orders are mutually inconsistent (a cross-node ordering
        cycle — no valid policy output does this), the remainder falls back
        to topological order rather than deadlocking.
        """
        placement = schedule.placement
        topo_pos = {tid: i for i, tid in enumerate(graph.topo_order)}
        prio = {tid: i for i, tid in enumerate(schedule.assignment_order)}
        # filter each node's list against `placement` (which keeps the LAST
        # per_node match): a task erroneously present in two nodes' lists is
        # dispatched once, on the node placement says, never twice
        queues = {
            n: [t for t in lst if t in topo_pos and placement.get(t) == n]
            for n, lst in schedule.per_node.items()
            if lst
        }
        queues = {n: q for n, q in queues.items() if q}
        idx = {n: 0 for n in queues}
        emitted: set = set()
        order: List[str] = []

        def head_ready(n: str) -> bool:
            i = idx[n]
            if i >= len(queues[n]):
                return False
            t = queues[n][i]
            return all(
                d in emitted or d not in placement
                for d in graph[t].dependencies
            )

        total = sum(len(q) for q in queues.values())
        while len(order) < total:
            ready_nodes = [n for n in queues if head_ready(n)]
            if not ready_nodes:
                break  # inconsistent per-node orders: topo fallback below
            n = min(
                ready_nodes,
                key=lambda n: (
                    prio.get(
                        queues[n][idx[n]], topo_pos[queues[n][idx[n]]]
                    ),
                    topo_pos[queues[n][idx[n]]],
                ),
            )
            t = queues[n][idx[n]]
            idx[n] += 1
            emitted.add(t)
            order.append(t)
        order.extend(
            t for t in graph.topo_order if t in placement and t not in emitted
        )
        return order

    # -- segment fusion ----------------------------------------------------
    @staticmethod
    def build_segments(
        graph: TaskGraph,
        schedule: Schedule,
        order: List[str],
        max_union_gb: Optional[Dict[str, float]] = None,
        param_gb: Optional[Dict[str, float]] = None,
    ) -> List[Tuple[str, Tuple[str, ...], Tuple[str, ...]]]:
        """Partition the dispatch order into per-device segments.

        A segment is a maximal run of consecutive (in dispatch order) tasks
        placed on the same device; each becomes ONE jitted executable, so
        XLA fuses across task boundaries and the host issues one launch per
        segment instead of one per task — the task-batching answer to
        SURVEY.md §7 hard-part #1 (per-task dispatch overhead swamping many
        small tasks), applied *post-placement* so the scheduler's decisions
        are untouched.  Segment boundaries are exactly the schedule's
        device switches: on one chip the whole DAG is one program (the
        fused forward, recovered automatically); a pipeline's 1F1B
        interleaving yields one segment per microbatch-stage visit, with
        real transfers between them.

        Returns (node_id, tids, exports): ``exports`` are the tasks whose
        outputs are consumed by later segments or by nobody (leaves —
        kept for the end-of-run fence and the final output).

        ``max_union_gb`` (budget-aware segmentation, for segment-granular
        parameter streaming): a per-node cap on a segment's param-global
        union — a run splits when adding a task would push its union past
        the cap, so each fused program's weights fit the streaming budget
        and eviction happens between segments.  A single task whose own
        params exceed the cap still gets a (over-budget) segment — the
        same escape as the streamer's pinned-params rule.  Without the
        cap, one device's whole run is one segment and an oversubscribed
        model's union could never fit.

        ``param_gb`` overrides per-name sizes (callers with the actual
        host arrays pass TRUE device bytes); missing names fall back to
        the graph-wide declared sizes.
        """
        placement = schedule.placement
        runs: List[Tuple[str, List[str]]] = []
        run_names: set = set()   # current run's param-global names
        run_total = 0.0          # its union GB — running total, O(1)/task
        sizes = param_gb or {}

        def size_of(g: str) -> float:
            # caller-supplied TRUE bytes when available (declared/default
            # sizes can under-count and defeat the split); graph-wide
            # declared sizes otherwise
            s = sizes.get(g)
            return s if s is not None else graph.param_size_gb(g)

        for tid in order:
            if tid not in placement:
                continue
            node = placement[tid]
            globs = list(dict.fromkeys(
                g for _, g in graph[tid].param_items()
            ))
            same_node = bool(runs) and runs[-1][0] == node
            if same_node and max_union_gb and node in max_union_gb:
                extra = sum(
                    size_of(g) for g in globs if g not in run_names
                )
                if run_total + extra > max_union_gb[node] and run_names:
                    same_node = False  # budget split (never an empty run)
            if same_node:
                runs[-1][1].append(tid)
            else:
                runs.append((node, [tid]))
                run_names = set()
                run_total = 0.0
            for g in globs:
                if g not in run_names:
                    run_names.add(g)
                    run_total += size_of(g)
        consumers: Dict[str, set] = {tid: set() for tid in placement}
        for seg_i, (_, tids) in enumerate(runs):
            for tid in tids:
                for d in graph[tid].arg_tasks or graph[tid].dependencies:
                    if d in consumers:
                        consumers[d].add(seg_i)
        segments = []
        for seg_i, (node, tids) in enumerate(runs):
            exports = tuple(
                t for t in tids
                if consumers[t] - {seg_i} or not consumers[t]
            )
            segments.append((node, tuple(tids), exports))
        return segments

    def _segment_callable(self, graph: TaskGraph, tids: Tuple[str, ...],
                          exports: Tuple[str, ...],
                          rebatch: bool = True):
        """One jitted fn running ``tids`` in order: (params-by-global-name,
        external-inputs-by-task-id) -> {export tid: output}.

        Cached per (graph, tids, exports, rebatch): the graph key (a
        WeakKey, so dead graphs release their executables) prevents a
        backend reused across graphs with colliding task ids from running
        stale fns, and ``exports`` is part of the key because the same run
        under a different downstream placement must return a different
        output set.

        ``rebatch=True`` applies the segment re-batching pass
        (:mod:`.rebatch`): sibling tasks (isomorphic microbatch chains)
        marked batch-axis-0 polymorphic execute as ONE call on
        concatenated inputs — recovering the fused forward's full-batch
        op shapes that the microbatch split fragments.  Placement,
        transfers, and the export contract are unchanged; graphs with no
        eligible siblings compile to exactly the unbatched program.
        """
        per_graph = self._seg_cache.setdefault(graph, {})
        key = (tids, exports, rebatch)
        fn = per_graph.get(key)
        if fn is not None:
            return fn

        if rebatch:
            from .rebatch import build_rebatched_seg_fn, plan_rebatch

            plan = plan_rebatch(graph, tids)
            if plan.classes:
                fn = jax.jit(
                    build_rebatched_seg_fn(graph, tids, exports, plan)
                )
                per_graph[key] = fn
                return fn

        # extract per-task (fn, params, args) up front: the closure must
        # NOT capture `graph`, or the cache value would strongly reference
        # its own WeakKey and the graph could never be collected
        steps = tuple(
            (
                tid,
                graph[tid].fn,
                tuple(graph[tid].param_items()),
                tuple(graph[tid].arg_tasks or graph[tid].dependencies),
            )
            for tid in tids
        )

        def seg_fn(seg_params, ext):
            vals: Dict[str, Any] = {}
            for tid, task_fn, pitems, aids in steps:
                pd = {loc: seg_params[g] for loc, g in pitems}
                if aids:
                    # KeyError here = a segment-boundary bookkeeping bug;
                    # never silently pass None into a task fn
                    args = [vals[d] if d in vals else ext[d] for d in aids]
                else:
                    args = [ext["__input__"]]
                vals[tid] = task_fn(pd, *args)
            return {t: vals[t] for t in exports}

        fn = jax.jit(seg_fn)
        per_graph[key] = fn
        return fn

    # fraction of a node's streaming budget one segment's param union may
    # occupy: 0.5 leaves room for the NEXT segment's union to prefetch
    # while the current fused program runs (double buffering)
    STREAM_SEGMENT_FRAC = 0.5

    def _stream_segment_caps(self) -> Dict[str, float]:
        return {
            d.node_id: d.total_memory * self.STREAM_SEGMENT_FRAC
            for d in self.cluster
        }

    @staticmethod
    def segment_stream_plan(
        graph: TaskGraph,
        segments: List[Tuple[str, Tuple[str, ...], Tuple[str, ...]]],
    ) -> Dict[str, List[Tuple[str, Tuple[str, ...]]]]:
        """Per-node streamer plan at SEGMENT granularity: each entry is
        (synthetic segment id, the segment's param-global union).  The
        streamer's plan interface is unit-agnostic, so the same prefetch +
        Belady machinery that serves per-task streaming serves segments —
        one batched load per segment, next segment prefetched while the
        current fused program runs."""
        plan: Dict[str, List[Tuple[str, Tuple[str, ...]]]] = {}
        for i, (node, tids, _exports) in enumerate(segments):
            seen: Dict[str, None] = {}
            for tid in tids:
                for _, g in graph[tid].param_items():
                    seen.setdefault(g)
            plan.setdefault(node, []).append((f"__seg{i}", tuple(seen)))
        return plan

    def _run_segmented(
        self,
        graph: TaskGraph,
        schedule: Schedule,
        placed_params: Dict[Tuple[str, str], Any],
        graph_input: Any,
        ext_outputs: Optional[Dict[str, Any]] = None,
        fence: bool = True,
        rebatch: bool = True,
        streamer: Optional["DeviceBackend._ParamStreamer"] = None,
        segments_pre: Optional[
            List[Tuple[str, Tuple[str, ...], Tuple[str, ...]]]
        ] = None,
        order: Optional[List[str]] = None,
        tracer: Any = None,
        metrics: Any = None,
        mem: Any = None,
    ) -> Tuple[
        Any, Dict[str, TaskTiming], int, int, int, int, Dict[str, Any],
        Dict[str, float],
    ]:
        """Segment-fused execution: same placement, one launch per segment.
        Tasks with failed upstreams are dropped at segment-build time (host
        side), preserving fail-and-continue.  Cross-segment inputs are
        deduplicated per segment — a remote value consumed by several tasks
        of one segment transfers once, so transfer counts can be LOWER than
        per-task dispatch (an inherent win of batching, reported as
        measured).

        ``streamer``: segment-granular parameter streaming (oversubscribed
        models at fused dispatch speed): runs are budget-split so each
        segment's param union fits ``STREAM_SEGMENT_FRAC`` of the node's
        budget (leaving room to prefetch the NEXT segment's union while
        the current program runs — double buffering), each union loads as
        one batched transfer, and eviction fences anchor on segment
        outputs.  The streamer must have been built with
        :meth:`segment_stream_plan` over the same budget-split segments
        (``execute`` guarantees this; a drop-filter divergence only costs
        prefetch accuracy, never correctness)."""
        placement = schedule.placement
        if order is None:
            order = self.dispatch_order(graph, schedule)
        # drop tasks whose (transitive) producers are unplaced/skipped —
        # the host-side equivalent of the per-task path's upstream check.
        # ext_outputs (elastic recovery) count as alive producers.
        alive: set = set(ext_outputs or ())
        for tid in order:
            aids = graph[tid].arg_tasks or graph[tid].dependencies
            if all(d in alive for d in aids):
                alive.add(tid)
        order = [t for t in order if t in alive and t not in (ext_outputs or ())]
        # caller-precomputed segments (execute builds them once for the
        # streamer plan, the warmup, and every timed rep — a rebuild here
        # would land inside the makespan window).  Only reusable when no
        # task was drop-filtered: the precomputation ran unfiltered.
        segments = None
        if segments_pre is not None:
            if sum(len(t) for _n, t, _e in segments_pre) == len(order):
                segments = segments_pre
        if segments is None:
            segments = self.build_segments(
                graph, schedule, order,
                max_union_gb=(
                    self._stream_segment_caps() if streamer else None
                ),
                # the drop-filter rebuild must size by true bytes too, or
                # under-declared params defeat the budget split on exactly
                # this path (the streamer holds the host arrays)
                param_gb=(
                    {
                        g: _array_bytes(streamer.host_params[g]) / (1024**3)
                        for g in graph.unique_params()
                        if g in streamer.host_params
                    }
                    if streamer else None
                ),
            )

        outputs: Dict[str, Any] = dict(ext_outputs or {})
        transfer_edges = 0
        transfer_bytes = 0
        # obs: one span per fused segment on its device track, flow
        # arrows for cross-segment transfers (producer export -> consumer
        # segment); all behind None checks
        done_at: Optional[Dict[str, Tuple[str, float]]] = (
            {} if tracer is not None else None
        )
        t_loop0 = time.perf_counter()
        for seg_i, (node, tids, exports) in enumerate(segments):
            dev = self.cluster[node].jax_device
            union: Dict[str, Any] = {}
            ext: Dict[str, Any] = {}
            inside = set(tids)
            needs_input = False
            union_names: Dict[str, None] = {}
            flow_srcs = [] if tracer is not None else None
            t_s0 = time.perf_counter() if tracer is not None else 0.0
            for tid in tids:
                task = graph[tid]
                for _, g in task.param_items():
                    union_names.setdefault(g)
                aids = task.arg_tasks or task.dependencies
                if not aids:
                    needs_input = True
                for d in aids:
                    if d not in inside and d not in ext:
                        x = outputs[d]
                        if placement.get(d) != node:
                            transfer_edges += 1
                            nb = _array_bytes(x)
                            transfer_bytes += nb
                            x = jax.device_put(x, dev)
                            if tracer is not None:
                                flow_srcs.append((d, nb))
                            if metrics is not None:
                                metrics.counter(
                                    "transfer.bytes."
                                    f"{placement.get(d, 'ext')}->{node}",
                                    unit="bytes",
                                ).inc(nb)
                            if mem is not None:
                                mem.alloc(
                                    node, f"xfer:{d}", nb, "transfers"
                                )
                        ext[d] = x
            if streamer is not None:
                union = streamer.get_task(
                    f"__seg{seg_i}", node,
                    [(g, g) for g in union_names],
                )
            else:
                union = {
                    g: placed_params[(g, node)] for g in union_names
                }
            if needs_input:
                ext["__input__"] = jax.device_put(graph_input, dev)
                if mem is not None:
                    mem.alloc(
                        node, "input", _array_bytes(graph_input),
                        "activations",
                    )
            fn = self._segment_callable(graph, tids, exports, rebatch)
            seg_out = fn(union, ext)
            if mem is not None:
                for e in exports:
                    mem.alloc(
                        node, f"out:{e}", _array_bytes(seg_out[e]),
                        "activations",
                    )
            if tracer is not None:
                t_s1 = time.perf_counter()
                tracer.complete(
                    f"seg{seg_i}", t_s0, t_s1, track=node, cat="launch",
                    tasks=len(tids), exports=len(exports),
                )
                for e in exports:
                    done_at[e] = (node, t_s1)
                for d, nb in flow_srcs:
                    src_pt = done_at.get(d)
                    if src_pt is not None:
                        tracer.flow(
                            "transfer", src_pt[0], src_pt[1], node, t_s0,
                            src=d, dst=f"seg{seg_i}", bytes=nb,
                        )
            outputs.update(seg_out)
            if streamer is not None and exports:
                streamer.note_task(
                    node, list(union_names), seg_out[exports[-1]]
                )
        loop_s = time.perf_counter() - t_loop0

        n_fences = 0
        last_on_device: Dict[str, Any] = {}
        for node, tids, exports in segments:
            if exports:
                last_on_device[node] = outputs[exports[-1]]
        # guard on executed segments, not `outputs` — ext_outputs seeds can
        # make `outputs` non-empty when nothing actually ran
        if last_on_device and fence:
            if tracer is not None:
                t_f0 = time.perf_counter()
            n_fences = self._fence_run(last_on_device)
            if tracer is not None:
                tracer.complete(
                    "fence", t_f0, time.perf_counter(),
                    track="host", cat="collect",
                    devices=len(last_on_device),
                )
        # same semantics as the per-task path: None when the graph's last
        # task didn't execute (callers detect incomplete runs by this)
        final = outputs.get(graph.topo_order[-1]) if graph.topo_order else None
        executed = {
            k: v for k, v in outputs.items()
            if not ext_outputs or k not in ext_outputs
        }
        return (
            final, {}, transfer_edges, transfer_bytes, n_fences,
            len(segments), executed, {"loop_s": loop_s},
        )

    # -- execution ---------------------------------------------------------
    def _run(
        self,
        graph: TaskGraph,
        schedule: Schedule,
        placed_params: Dict[Tuple[str, str], Any],
        graph_input: Any,
        profile: bool,
        ext_outputs: Optional[Dict[str, Any]] = None,
        streamer: Optional["DeviceBackend._ParamStreamer"] = None,
        fence: bool = True,
        order: Optional[List[str]] = None,
        tracer: Any = None,
        metrics: Any = None,
        mem: Any = None,
    ) -> Tuple[
        Any, Dict[str, TaskTiming], int, int, int, int, Dict[str, Any],
        Dict[str, float],
    ]:
        placement = schedule.placement
        # obs: per-task spans on the device's track (profile timestamps
        # when available, host dispatch windows otherwise) and transfer
        # flow arrows; all behind None checks — disabled runs unchanged
        done_at: Optional[Dict[str, Tuple[str, float]]] = (
            {} if tracer is not None else None
        )
        # ext_outputs seed the value table: surviving outputs of an earlier
        # (partial) run whose producers are not in this graph — the elastic
        # recovery path (sched/elastic.py).  They count as transfers when
        # consumed (they arrive from outside the consuming core).
        outputs: Dict[str, Any] = dict(ext_outputs or {})
        n_ext = len(outputs)
        timings: Dict[str, TaskTiming] = {}
        transfer_edges = 0
        transfer_bytes = 0
        t_start = time.perf_counter()

        if order is None:
            order = self.dispatch_order(graph, schedule)
        # the shared graph input placed once per device, not once per root
        # task (64 roots on the flagship DAG re-placed the same array 64
        # times per rep through the tunnel)
        input_on: Dict[str, Any] = {}
        t_loop0 = time.perf_counter()
        for tid in order:
            if tid not in placement:
                continue  # failed task: skip (fail-and-continue semantics)
            task = graph[tid]
            node_id = placement[tid]
            dev = self.cluster[node_id].jax_device

            arg_ids = task.arg_tasks or task.dependencies
            if arg_ids and any(d not in outputs for d in arg_ids):
                continue  # upstream failed; propagate skip (BEFORE any
                # param loads: a skipped task must not evict live params)

            if streamer is not None:
                pd = streamer.get_task(tid, node_id, task.param_items())
            else:
                pd = {
                    loc: placed_params[(glob, node_id)]
                    for loc, glob in task.param_items()
                }

            flow_srcs = [] if tracer is not None else None
            if arg_ids:
                args = []
                for d in arg_ids:
                    x = outputs[d]
                    if placement.get(d) != node_id:
                        # cross-core edge: physical transfer (ICI on TPU)
                        transfer_edges += 1
                        nb = _array_bytes(x)
                        transfer_bytes += nb
                        x = jax.device_put(x, dev)
                        if tracer is not None:
                            flow_srcs.append((d, nb))
                        if metrics is not None:
                            metrics.counter(
                                "transfer.bytes."
                                f"{placement.get(d, 'ext')}->{node_id}",
                                unit="bytes",
                            ).inc(nb)
                        if mem is not None:
                            mem.alloc(
                                node_id, f"xfer:{d}", nb, "transfers"
                            )
                    args.append(x)
            else:
                inp = input_on.get(node_id)
                if inp is None:
                    inp = jax.device_put(graph_input, dev)
                    input_on[node_id] = inp
                    if mem is not None:
                        mem.alloc(
                            node_id, "input", _array_bytes(graph_input),
                            "activations",
                        )
                args = [inp]

            fn = self._jitted(graph, tid)
            if profile:
                t0 = time.perf_counter()
                out = fn(pd, *args)
                jax.block_until_ready(out)  # out may be a pytree (train DAG)
                t1 = time.perf_counter()
                timings[tid] = TaskTiming(
                    tid, node_id, t0 - t_start, t1 - t_start
                )
            else:
                if tracer is not None:
                    t0 = time.perf_counter()
                out = fn(pd, *args)
                if tracer is not None:
                    t1 = time.perf_counter()
            if tracer is not None:
                # profile mode: span == measured task wall; otherwise the
                # host dispatch window (launch returns at enqueue)
                tracer.complete(
                    tid, t0, t1, track=node_id,
                    cat="task" if profile else "launch",
                )
                done_at[tid] = (node_id, t1)
                for d, nb in flow_srcs:
                    src_pt = done_at.get(d)
                    if src_pt is not None:
                        tracer.flow(
                            "transfer", src_pt[0], src_pt[1], node_id, t0,
                            src=d, dst=tid, bytes=nb,
                        )
            outputs[tid] = out
            if mem is not None:
                mem.alloc(
                    node_id, f"out:{tid}", _array_bytes(out), "activations"
                )
            if streamer is not None:
                streamer.note_task(
                    node_id, [g for _, g in task.param_items()], out
                )

        loop_s = time.perf_counter() - t_loop0

        # fence ALL dispatched work (not just the topologically-last task:
        # multi-leaf graphs and skipped tails would otherwise under-measure).
        # block_until_ready first, then a per-device readback fence:
        # block_until_ready is unreliable through the axon tunnel (it can
        # return before compute completes — utils/costmodel.readback_fence),
        # and per-device queues are FIFO so one fenced value per device
        # proves that device's whole queue drained.
        n_fences = 0
        if len(outputs) > n_ext and fence:
            last_on_device: Dict[str, Any] = {}
            for tid in order:
                if tid in outputs:
                    last_on_device[placement[tid]] = outputs[tid]
            if tracer is not None:
                t_f0 = time.perf_counter()
            n_fences = self._fence_run(last_on_device)
            if tracer is not None:
                tracer.complete(
                    "fence", t_f0, time.perf_counter(),
                    track="host", cat="collect",
                    devices=len(last_on_device),
                )
        final = outputs.get(graph.topo_order[-1]) if graph.topo_order else None
        executed = {
            k: v for k, v in outputs.items()
            if not ext_outputs or k not in ext_outputs
        }
        return (
            final, timings, transfer_edges, transfer_bytes, n_fences,
            len(outputs) - n_ext, executed, {"loop_s": loop_s},
        )

    def paged_decode_engine(
        self,
        graph: TaskGraph,
        schedule: Schedule,
        config: Any,
        weights: Dict[str, Any],
        pool: Any,
        slots: int,
        pages_per_seq: int,
        seg_steps: int = 8,
        trace: Any = None,
        metrics: Any = None,
        clock: Any = None,
        memprof: Any = None,
        flight: Any = None,
        attention_impl: Optional[str] = None,
        chunk_tokens: Optional[int] = None,
    ):
        """Continuous-batching paged decode engine over a SCHEDULED paged
        decode-step DAG (``frontend.build_paged_decode_dag``).

        Runs the same static pre-execution gate as :meth:`execute` (the
        DEC0xx decode-loop pass checks cache/page-table placement
        coherence) before composing the placed step, so a schedule that
        would mis-place the paged cache is rejected at build time, not
        discovered as garbage tokens.  ``pool`` is the host-side
        ``models.kv_pages.PagePool`` whose geometry must match the
        graph's pool params.
        """
        if self.pre_analysis:
            from ..analysis import pre_execution_gate

            pre_execution_gate(
                graph, self.cluster, schedule, backend="device"
            )
        from .decode_loop import PagedDecodeEngine

        return PagedDecodeEngine(
            graph, schedule, config, weights, pool,
            slots=slots, pages_per_seq=pages_per_seq, seg_steps=seg_steps,
            tracer=trace, metrics=metrics, clock=clock, memprof=memprof,
            flight=flight, attention_impl=attention_impl,
            chunk_tokens=chunk_tokens,
        )

    def execute(
        self,
        graph: TaskGraph,
        schedule: Schedule,
        params: Dict[str, Any],
        graph_input: Any,
        profile: bool = False,
        warmup: bool = True,
        segments: bool = False,
        ext_outputs: Optional[Dict[str, Any]] = None,
        keep_outputs: bool = False,
        stream_params: bool = False,
        stream_lookahead: int = 8,
        reps: int = 1,
        rebatch: bool = True,
        planned: Optional[bool] = None,
        coalesce: bool = False,
        donate: Optional[bool] = None,
        compiled: bool = False,
        fence_rtt: Optional[float] = None,
        trace: Any = None,
        metrics: Any = None,
        memprof: Any = None,
        pre_report: Any = None,
    ) -> DeviceReport:
        """Place params, compile, run, measure.

        ``planned`` selects the pre-planned fast dispatch path
        (:mod:`.dispatch_plan`): an immutable per-task plan built at
        warmup (resolved executables, prebuilt param bindings, integer
        value-table indices, batched per-launch ``device_put`` staging),
        so the hot loop issues only cached-executable calls.  Default
        (``None``) auto-enables it whenever compatible — ``profile``
        (needs per-task timing hooks), ``stream_params`` (param residency
        changes mid-run), and ``segments`` (already fused) keep the
        legacy paths.  Placement, dispatch order, transfer counting, and
        the end-of-run fence are identical to the legacy loop; outputs
        are bit-identical.

        ``compiled`` selects the whole-program path
        (:mod:`.compiled_schedule`): the entire placed run lowers into
        ONE jitted program (per-device compute under a ``lax.switch``
        over the mesh index, cross-device edges as in-program
        ``ppermute`` collectives), so the host issues one staging put
        per input leaf plus a single launch per run.  Outputs stay
        bit-identical to the interpreted paths (per-task
        ``optimization_barrier`` islands).  Lowering runs the COL00x
        collective-ordering gate; a schedule whose per-node orders admit
        no global collective order raises (COL002) instead of silently
        re-linearizing.  Incompatible with every per-task feature
        (``profile``/``segments``/``coalesce``/``keep_outputs``/
        ``ext_outputs``) — see docs/ARCHITECTURE.md's execution ladder
        for when to pick which rung.  ``stream_params`` composes via the
        static stream-safety prover (analysis/stream_pass.py): when
        every node's param union fits its HBM budget (STR001 on all
        nodes) the run compiles as-is — the resident slab subsumes the
        streaming plan — otherwise the call raises ``AnalysisError``
        carrying the per-node STR002/STR003 diagnosis instead of the
        historical blanket refusal.

        ``pre_report``: a report ``analysis.analyze()`` just produced
        for this exact (graph, schedule) — the pre-execution gate then
        skips re-running its base passes (accepted only when the
        report's stamped schedule signature matches).

        ``fence_rtt`` supplies a pre-calibrated fence round-trip
        (seconds) instead of re-probing it inside this call — callers
        timing several executes back-to-back (bench repeat legs)
        calibrate once and share it.

        ``donate`` (planned only): donate intermediate buffers that die
        after their last same-device consumer via ``donate_argnums``.
        Default probes the platform (donation is honored on CPU and TPU);
        forced off by ``keep_outputs`` (retained outputs must outlive the
        run — passing ``donate=True`` with ``keep_outputs`` raises).

        ``coalesce`` (planned only, opt-in): fuse runs of consecutive
        same-device tasks whose non-leading members consume only
        values produced inside the run into ONE launch, with
        ``optimization_barrier`` between members so per-task outputs stay
        bit-identical.  Opt-in because host-side effects inside task fns
        (``jax.debug.callback(ordered=False)``) lose their per-launch
        ordering inside a single XLA program.

        ``reps > 1`` dispatches the whole placed run ``reps`` times
        back-to-back and fences ONCE at the end; ``makespan_s`` is then
        the per-run amortized wall ``(total - fence_rtt) / reps``.  This
        is the trustworthy timing mode on tunneled devices, where the
        fence round-trip (tens of ms on a bad reconnect, jittering by
        several ms between draws) would otherwise be the same order as
        the thing measured: one fence amortized over a long window makes
        the RTT correction's residual error negligible.  Incompatible
        with ``profile`` (per-task fences) and ``stream_params`` (later
        reps would measure a warm param cache, not the cold streaming
        behavior under test).

        ``ext_outputs`` seeds task outputs produced OUTSIDE this graph —
        the elastic-recovery path (``sched/elastic.py``): a remainder
        graph's tasks may consume, via ``arg_tasks``, outputs of completed
        tasks that survived a node failure.  Keys are the external task
        ids; values are host or device arrays (transferred to the
        consuming core on use).

        ``keep_outputs=True`` retains per-task outputs on the report
        (``task_outputs``) so a LATER failure can recover without
        recomputation: pass the surviving subset to ``surviving_work``'s
        ``have_outputs`` and to the re-execution's ``ext_outputs``.
        Per-task dispatch keeps every executed task's output; segment
        fusion keeps segment exports only (internal values never left
        their fused program).  Costs device memory proportional to
        activations held.

        ``stream_params=True`` replaces up-front param placement with
        planned streaming under each node's ``total_memory`` budget
        (:class:`_ParamStreamer`): batched loads prefetched
        ``stream_lookahead`` units ahead of the dispatch cursor, Belady
        (farthest-next-use) eviction, and minimal-wait deletion — a node
        whose assigned weights exceed its HBM budget still executes,
        trading host-link bandwidth for capacity (the reference's
        param-cache eviction made physical) while loads overlap compute.
        Composes with ``segments=True``: the streaming unit becomes the
        SEGMENT (one batched load per fused program's param union, next
        segment prefetched while the current one runs), so oversubscribed
        models run at fused dispatch granularity; a segment whose union
        alone exceeds the budget runs over-budget with the peak recorded
        (same escape as a single task's pinned params).  The report
        carries ``param_loads``/``param_load_calls``/
        ``param_load_bytes``/``param_evictions``/``peak_param_bytes``.

        ``profile=True`` records per-task wall times via per-task
        ``block_until_ready`` (Gantt charts / diagnostics).  CAVEAT: on the
        tunneled TPU those per-task fences are unreliable (they can return
        at dispatch, not completion — see ``utils/costmodel``), so profile
        timings are trustworthy on local platforms (CPU mesh) only;
        ``utils/costmodel.calibrate`` picks the right method per platform.
        ``profile=False`` measures makespan ending at a single combined
        readback fence, its round-trip netted out.

        ``segments=True`` fuses each device's contiguous scheduled run into
        one XLA executable (:meth:`build_segments`): identical placement
        and transfers, one launch per segment — the production execution
        mode where per-task dispatch overhead would otherwise dominate
        (e.g. hundreds of sub-ms tasks).  Incompatible with ``profile``
        (task boundaries vanish inside the fused programs).

        ``trace`` / ``metrics`` attach an :class:`..obs.trace.Tracer` /
        :class:`..obs.metrics.MetricsRegistry` to this run: host phase
        spans (schedule / stage / plan / launch / collect), per-launch
        device-track spans, transfer flow arrows, per-edge byte counters,
        jit-cache hit/miss deltas, and makespan/overhead histograms.
        ``None`` (the default) falls back to the ambient pair when
        ``DLS_TRACE=1`` is set, else recording is fully disabled (the
        hot paths guard every record behind a ``None`` check).

        ``memprof`` attaches an :class:`..obs.memprof.MemoryProfiler`:
        the run records param staging / slab construction, task-output
        births, donation-driven frees, transfer copies, and input
        staging as allocation events on per-device timelines, and the
        report carries ``memory`` (the profiler summary, platform
        ``memory_stats()`` peaks reconciled in where reported).  Warmup
        runs unrecorded, same as the tracer — only the timed reps land
        on the timeline.  Explicit only (no ambient fallback).
        """
        if segments and profile:
            raise ValueError(
                "profile=True needs per-task dispatch; run without segments"
            )
        if compiled:
            if stream_params:
                # historically an unconditional refusal; now the static
                # stream-safety prover (analysis/stream_pass.py) decides:
                # a schedule whose per-node param unions fit their HBM
                # budgets compiles as-is — the resident slab load IS the
                # whole residency plan — while anything that would need
                # eviction stays on the interpreted streaming rung and is
                # refused with the per-node STR diagnosis attached
                from ..analysis import (
                    AnalysisError,
                    analyze_streaming,
                    compiled_stream_refusal,
                    stream_verdict,
                )

                srep = analyze_streaming(graph, self.cluster, schedule)
                if stream_verdict(srep) != "compilable":
                    raise AnalysisError(compiled_stream_refusal(srep))
                stream_params = False
            # the whole run is ONE XLA program: there are no per-task
            # boundaries to time/stream/retain, no host-mediated segments,
            # and external values would have to be program inputs
            incompatible = [
                name for name, flag in (
                    ("profile", profile),
                    ("segments", segments), ("coalesce", coalesce),
                    ("keep_outputs", keep_outputs),
                    ("ext_outputs", ext_outputs is not None),
                    ("planned", bool(planned)),
                ) if flag
            ]
            if incompatible:
                raise ValueError(
                    "compiled=True lowers the whole run into one program "
                    f"and is incompatible with {incompatible}"
                )
            planned = False
        if planned is None:
            planned = not (profile or stream_params or segments)
        elif planned and (profile or stream_params or segments):
            raise ValueError(
                "planned dispatch is incompatible with profile (per-task "
                "timing hooks), stream_params (param residency changes "
                "mid-run), and segments (already fused)"
            )
        if coalesce and not planned:
            raise ValueError("coalesce=True requires the planned path")
        if donate and keep_outputs:
            raise ValueError(
                "donate=True deletes dying intermediates; keep_outputs "
                "must retain them — drop one of the two"
            )
        if planned or compiled:
            from .dispatch_plan import donation_supported

            if donate is None:
                donate = donation_supported() and not keep_outputs
        elif donate:
            raise ValueError(
                "donate=True requires the planned or compiled path"
            )
        else:
            donate = False
        if reps < 1:
            raise ValueError(f"reps must be >= 1, got {reps}")
        if reps > 1 and (profile or stream_params):
            raise ValueError(
                "reps > 1 amortizes over identical repeated runs; profile "
                "mode fences per task and stream_params runs must start "
                "cold — measure those with reps=1"
            )
        if self.pre_analysis and not compiled:
            # the compiled path gates inside CompiledSchedule.build with
            # the lowered program attached (COL00x joins the checks).
            # ``pre_report``: a fresh ``analyze()`` report for this exact
            # schedule skips the duplicate base passes (signature-checked)
            from ..analysis import pre_execution_gate

            pre_execution_gate(
                graph, self.cluster, schedule, backend="device",
                precomputed=pre_report,
            )
        graph.freeze()
        no_fn = [t.task_id for t in graph if t.fn is None]
        if no_fn:
            raise ValueError(
                f"tasks {no_fn[:3]} have no fn; this graph is schedule-only "
                "(synthetic DAGs execute on the simulated backend)"
            )
        missing = sorted(graph.unique_params() - set(params))
        if missing:
            raise ValueError(f"params missing for placement: {missing[:5]}")
        # obs: explicit trace=/metrics= win; else the DLS_TRACE ambient
        # pair; else None — and every instrumented path below guards on
        # None, so a disabled run records nothing and pays only the checks
        from ..obs import ambient_metrics, ambient_tracer

        tracer = trace if trace is not None else ambient_tracer()
        mreg = metrics if metrics is not None else ambient_metrics()
        jit_hits0 = self.jit_cache_hits
        jit_miss0 = self.jit_cache_misses
        ev_exec = None
        if tracer is not None:
            ev_exec = tracer.begin(
                "execute", cat="schedule", policy=schedule.policy,
                segments=segments, reps=reps,
            )
        # one linearization for the stream plan, the segment build, and
        # every rep: dispatch_order is a pure function of (graph,
        # schedule) and costs ~ms on 500-task DAGs
        order_once: List[str] = []
        if not compiled:
            t_ph = time.perf_counter() if tracer is not None else 0.0
            order_once = self.dispatch_order(graph, schedule)
            if tracer is not None:
                tracer.complete(
                    "dispatch_order", t_ph, time.perf_counter(),
                    track="host", cat="schedule", tasks=len(order_once),
                )
        segments_pre = None
        if stream_params:
            placed, bytes_per_node = {}, {d.node_id: 0 for d in self.cluster}
            # per-node dispatch plan for the streamer's prefetch + Belady
            # eviction: the schedule fixes each node's task order, so the
            # streamer knows exactly which params are needed next.  Under
            # segment fusion the streaming unit is the SEGMENT (one
            # batched load per fused program, next segment prefetched
            # while the current one runs)
            if segments:
                segments_pre = self.build_segments(
                    graph, schedule, order_once,
                    max_union_gb=self._stream_segment_caps(),
                    # size by the ACTUAL host arrays: declared/default
                    # sizes can under-count and defeat the budget split
                    param_gb={
                        g: _array_bytes(params[g]) / (1024**3)
                        for g in graph.unique_params()
                    },
                )
                stream_plan = self.segment_stream_plan(graph, segments_pre)
            else:
                stream_plan = {}
                for tid in order_once:
                    node = schedule.placement.get(tid)
                    if node is None:
                        continue
                    stream_plan.setdefault(node, []).append(
                        (tid, tuple(g for _, g in graph[tid].param_items()))
                    )
        elif compiled:
            # the compiled path loads params as sharded slabs inside
            # CompiledSchedule.build — per-global placement never happens
            placed, bytes_per_node = {}, {}
        else:
            t_ph = time.perf_counter() if tracer is not None else 0.0
            placed, bytes_per_node = self.place_params(
                graph, schedule, params, mem=memprof
            )
            if tracer is not None:
                tracer.complete(
                    "place_params", t_ph, time.perf_counter(),
                    track="host", cat="stage",
                    bytes=sum(bytes_per_node.values()),
                )
        if segments and segments_pre is None:
            # plain segmented runs were rebuilding segments inside every
            # timed rep (the same host-work-in-makespan bias the order
            # hoist removes); the length-match guard in _run_segmented
            # still handles drop-filter divergence
            segments_pre = self.build_segments(graph, schedule, order_once)

        # planned fast path: precompute the immutable dispatch plan at
        # warmup time (resolved executables, prebuilt param bindings,
        # slot-indexed staging, donation patterns) so the timed loop does
        # no per-task bookkeeping at all
        plan = None
        prog = None
        if compiled:
            from .compiled_schedule import CompiledSchedule

            t_ph = time.perf_counter() if tracer is not None else 0.0
            prog = CompiledSchedule.build(
                self, graph, schedule, params, graph_input,
                donate=donate, pre_analysis=self.pre_analysis,
                pre_report=pre_report,
            )
            bytes_per_node = prog.param_bytes_per_node
            if tracer is not None:
                tracer.complete(
                    "program_build", t_ph, time.perf_counter(),
                    track="host", cat="plan",
                    phases=len(prog.ir.phases),
                    exchanges=prog.ir.n_exchanges,
                )
        elif planned:
            from .dispatch_plan import DispatchPlan

            t_ph = time.perf_counter() if tracer is not None else 0.0
            plan = DispatchPlan.build(
                self, graph, schedule, order_once, placed,
                ext_keys=tuple(ext_outputs or ()),
                donate=donate, coalesce=coalesce,
                keep_outputs=keep_outputs,
            )
            if tracer is not None:
                tracer.complete(
                    "plan_build", t_ph, time.perf_counter(),
                    track="host", cat="plan", steps=len(plan.steps),
                )

        compile_s = 0.0
        if warmup:
            t_ph = time.perf_counter() if tracer is not None else 0.0
            if prog is not None:
                # first run traces + XLA-compiles the whole-program
                # executable; same donation-warning note as the plan path
                t0 = time.perf_counter()
                with warnings.catch_warnings():
                    warnings.filterwarnings(
                        "ignore",
                        message="Some donated buffers were not usable",
                    )
                    prog.run(graph_input, fence=True)
                compile_s = time.perf_counter() - t0
            elif plan is not None:
                # one full planned execution: jits every resolved
                # executable (donating variants and coalesced groups
                # included) and fills the static transfer-byte table.
                # XLA warns once per lowering when a donated buffer's
                # shape matches no output; the donation is still honored
                # (the buffer is freed), so the warning is noise here.
                t0 = time.perf_counter()
                with warnings.catch_warnings():
                    warnings.filterwarnings(
                        "ignore",
                        message="Some donated buffers were not usable",
                    )
                    plan.run(graph_input, ext_outputs, fence=True)
                compile_s = time.perf_counter() - t0
            else:
                # a throwaway streamer for the warmup pass: jit caches warm
                # up, and the timed run's streamer starts cold (capacity
                # misses are the thing being measured)
                compile_s = self.warmup(
                    graph, schedule, placed, graph_input, segments=segments,
                    ext_outputs=ext_outputs,
                    streamer=(
                        self._ParamStreamer(
                            self.cluster, params, plan=stream_plan,
                            lookahead=stream_lookahead,
                        )
                        if stream_params else None
                    ),
                    rebatch=rebatch,
                    segments_pre=segments_pre,
                )
            if tracer is not None:
                # warmup runs untraced (its transfers/launches are compile
                # artifacts, not steady-state behavior); one host span
                # covers the whole compile window
                tracer.complete(
                    "warmup", t_ph, time.perf_counter(),
                    track="host", cat="plan", compile_s=compile_s,
                )

        # fence round-trip, re-measured per execute (outside the timed
        # region): tunnel RTT demonstrably changes across reconnects, so a
        # backend-lifetime cache would correct post-reconnect runs with a
        # stale value and bias cross-policy comparisons.  Callers timing
        # several executes back-to-back (bench repeat legs) pass a shared
        # ``fence_rtt`` calibrated once: the ~5-sample probe costs several
        # RTTs per call and would otherwise dwarf short measured programs
        if fence_rtt is not None:
            rtt = fence_rtt
        else:
            from ..utils.costmodel import _fence_rtt

            rtt = _fence_rtt(self._fence_device())

        streamer = (
            self._ParamStreamer(
                self.cluster, params, plan=stream_plan,
                lookahead=stream_lookahead, mem=memprof,
            )
            if stream_params else None
        )
        t0 = time.perf_counter()
        loop_s_total = 0.0
        phases_total: Dict[str, float] = {}
        for r in range(reps):
            fence = r == reps - 1  # intermediate reps queue without fencing
            t_ph = time.perf_counter() if tracer is not None else 0.0
            if prog is not None:
                (
                    output, timings, tedges, tbytes, n_fences, n_disp,
                    touts, phases,
                ) = prog.run(
                    graph_input, fence=fence, tracer=tracer, metrics=mreg,
                    mem=memprof,
                )
            elif plan is not None:
                (
                    output, timings, tedges, tbytes, n_fences, n_disp,
                    touts, phases,
                ) = plan.run(
                    graph_input, ext_outputs, fence=fence,
                    tracer=tracer, metrics=mreg, mem=memprof,
                )
            elif segments:
                (
                    output, timings, tedges, tbytes, n_fences, n_disp,
                    touts, phases,
                ) = self._run_segmented(
                    graph, schedule, placed, graph_input, ext_outputs,
                    fence=fence, rebatch=rebatch, streamer=streamer,
                    segments_pre=segments_pre, order=order_once,
                    tracer=tracer, metrics=mreg, mem=memprof,
                )
            else:
                (
                    output, timings, tedges, tbytes, n_fences, n_disp,
                    touts, phases,
                ) = self._run(
                    graph, schedule, placed, graph_input, profile,
                    ext_outputs, streamer, fence=fence, order=order_once,
                    tracer=tracer, metrics=mreg, mem=memprof,
                )
            loop_s_total += phases.get("loop_s", 0.0)
            for k, v in phases.items():
                phases_total[k] = phases_total.get(k, 0.0) + v
            if tracer is not None:
                tracer.complete(
                    f"rep{r}", t_ph, time.perf_counter(),
                    track="host", cat="launch",
                    dispatches=n_disp, fenced=fence,
                )
        wall = time.perf_counter() - t0
        makespan = max((wall - n_fences * rtt) / reps, 1e-9)
        dispatch_overhead_s = loop_s_total / reps
        dispatch_phases = {k: v / reps for k, v in phases_total.items()}

        peaks: Dict[str, int] = {}
        for d in self.cluster:
            try:
                stats = d.jax_device.memory_stats() or {}
                if "peak_bytes_in_use" in stats:
                    peaks[d.node_id] = int(stats["peak_bytes_in_use"])
            except Exception:
                pass
        if memprof is not None:
            # platform truth where PJRT reports it; the profiler's
            # model-derived timeline stands alone elsewhere
            memprof.reconcile(peaks)

        if timings:
            schedule.timings = timings
        if mreg is not None:
            # per-rep counts are identical across reps, so the run totals
            # are a clean multiply; histograms get one sample per execute
            mreg.counter("dispatch.launches").inc(n_disp * reps)
            mreg.counter("dispatch.transfer_edges").inc(tedges * reps)
            mreg.counter("dispatch.transfer_bytes", unit="bytes").inc(
                tbytes * reps
            )
            mreg.histogram("dispatch.overhead_s", unit="s").observe(
                dispatch_overhead_s
            )
            mreg.histogram("execute.makespan_s", unit="s").observe(makespan)
            mreg.histogram("execute.compile_s", unit="s").observe(compile_s)
            mreg.counter("compile.jit_cache_hits").inc(
                self.jit_cache_hits - jit_hits0
            )
            mreg.counter("compile.jit_cache_misses").inc(
                self.jit_cache_misses - jit_miss0
            )
            if timings:
                # profile mode: busy fraction per device over the measured
                # span — the Gantt chart's utilization column as a gauge
                span_end = max(t.finish for t in timings.values())
                busy: Dict[str, float] = {}
                for t in timings.values():
                    busy[t.node_id] = busy.get(t.node_id, 0.0) + t.duration
                for n, b in busy.items():
                    mreg.gauge(f"device.utilization.{n}", unit="frac").set(
                        b / span_end if span_end > 0 else 0.0
                    )
        attribution = None
        if ev_exec is not None:
            tracer.end(ev_exec, makespan_s=makespan)
            # run doctor: attribute this execute's span window (window
            # filtering keeps ambient tracers that accumulated earlier
            # runs correct).  Diagnosis only — never fail the run on it.
            try:
                from ..obs.attribution import attribute_run

                att = attribute_run(
                    tracer, window=(ev_exec["t0"], ev_exec["t1"]),
                )
                if att.critical_path:
                    attribution = att.summary()
            except Exception:
                attribution = None
        return DeviceReport(
            policy=schedule.policy,
            makespan_s=makespan,
            output=output,
            n_devices=len(self.cluster),
            transfer_edges=tedges,
            transfer_bytes=tbytes,
            param_bytes_placed=bytes_per_node,
            compile_s=compile_s,
            timings=timings,
            peak_hbm_bytes=peaks,
            n_dispatches=n_disp,
            dispatch_overhead_s=dispatch_overhead_s,
            dispatch_phases=dispatch_phases,
            planned=plan is not None,
            compiled=prog is not None,
            task_outputs=touts if keep_outputs else {},
            streamed=streamer is not None,
            param_loads=streamer.loads if streamer else 0,
            param_load_calls=streamer.load_calls if streamer else 0,
            param_load_bytes=streamer.load_bytes if streamer else 0,
            param_evictions=streamer.evictions if streamer else 0,
            peak_param_bytes=dict(streamer.peak) if streamer else {},
            attribution=attribution,
            memory=memprof.summary() if memprof is not None else None,
        )
