"""Online serving layer: open-loop load generation (:mod:`.loadgen`)
and the event-loop front-end with SLO-aware admission and priority
preemption (:mod:`.frontend`) over the paged continuous-batching
decode engine.  See ``docs/SERVING.md``."""

from .frontend import ServiceTimeModel, ServingFrontend, VirtualClock
from .loadgen import (
    Arrival,
    TRACE_SCHEMA,
    arrivals_to_json,
    load_trace,
    poisson_arrivals,
    prompt_token_ids,
    save_trace,
    schedule_digest,
    validate_trace_obj,
)

__all__ = [
    "Arrival",
    "ServiceTimeModel",
    "ServingFrontend",
    "TRACE_SCHEMA",
    "VirtualClock",
    "arrivals_to_json",
    "load_trace",
    "poisson_arrivals",
    "prompt_token_ids",
    "save_trace",
    "schedule_digest",
    "validate_trace_obj",
]
