"""Online serving layer: open-loop load generation (:mod:`.loadgen`),
the event-loop front-end with SLO-aware admission and priority
preemption (:mod:`.frontend`) over the paged continuous-batching
decode engine, the duration-bounded soak harness with health gating
(:mod:`.soak`), and the fleet tier — the replica registry
(:mod:`.registry`) and the health-driven router with drain/failover
(:mod:`.router`).  See ``docs/SERVING.md``."""

from .frontend import ServiceTimeModel, ServingFrontend, VirtualClock
from .registry import EngineRegistry, ReplicaHandle
from .router import DuplicateRidError, FleetFrontend
from .soak import (
    SoakConfig,
    inject_jit_churn,
    inject_page_leak,
    inject_refcount_underflow,
    load_soak_artifact,
    run_soak,
    validate_soak_artifact,
)
from .loadgen import (
    Arrival,
    TRACE_SCHEMA,
    arrivals_to_json,
    load_trace,
    poisson_arrivals,
    prompt_token_ids,
    save_trace,
    schedule_digest,
    session_arrivals,
    session_prompt_token_ids,
    validate_trace_obj,
)

__all__ = [
    "Arrival",
    "DuplicateRidError",
    "EngineRegistry",
    "FleetFrontend",
    "ReplicaHandle",
    "ServiceTimeModel",
    "ServingFrontend",
    "TRACE_SCHEMA",
    "VirtualClock",
    "arrivals_to_json",
    "load_trace",
    "poisson_arrivals",
    "prompt_token_ids",
    "save_trace",
    "schedule_digest",
    "session_arrivals",
    "session_prompt_token_ids",
    "SoakConfig",
    "inject_jit_churn",
    "inject_page_leak",
    "inject_refcount_underflow",
    "load_soak_artifact",
    "run_soak",
    "validate_soak_artifact",
    "validate_trace_obj",
]
