"""Event-loop serving front-end over the paged continuous-batching engine.

The :class:`~..backends.decode_loop.PagedDecodeEngine` is synchronously
driven: callers pre-stage requests and ``run()`` drains them.  This
module turns it into an ONLINE server: a single-threaded event loop
(:class:`ServingFrontend`) injects open-loop arrivals (:mod:`.loadgen`)
as their deadlines pass, holds the not-yet-admitted work in its own
request queue, and drives the engine one ``step_segment()`` at a time —
the engine's incremental API is the event granularity, so admission,
preemption, and SLO control all act at segment boundaries, exactly
where the engine's host-side state is mutable.

Three policies compose per tick:

* **Admission** — ``"fifo"`` (admit-all: every arrival goes straight to
  the engine's FIFO queue; the baseline that collapses under overload)
  or ``"slo"``: the frontend submits only what the engine can admit at
  THIS boundary (reading :meth:`PagedDecodeEngine.page_occupancy` and
  ``free_slots`` — the same headroom surface the metrics sample), and
  uses :func:`~..obs.slo.evaluate_slo` window stats over the serving
  log as the control signal: while the current p95 TTFT window
  breaches, low-priority (tier > 0) work is DEFERRED, and a low-tier
  request whose wait has already blown the TTFT target is SHED — it can
  no longer produce goodput, so running it would only steal pages from
  requests that still can.
* **Preemption** — a tier-0 arrival that cannot be admitted (no free
  slot / pages) evicts the lowest-tier in-flight victims via
  :meth:`PagedDecodeEngine.preempt`: pages return to the pool, the
  victim's generated prefix becomes the new prompt of a re-queued
  resume pass (engine rid ``{rid}#p{k}``), and greedy determinism makes
  the resumed continuation bitwise-identical to an unpreempted run of
  the same prompt+prefix.
* **Time** — with a :class:`VirtualClock` on the engine, the loop
  advances time itself via a :class:`ServiceTimeModel` (per admission
  wave, per segment, per idle tick), which makes every timestamp,
  every window, every admission/shed/preempt decision, and therefore
  the whole serving run a deterministic function of the seed — the
  property the serve bench's repeat gate asserts.  With a real clock
  the same loop serves wall-clock arrivals (sleeping while idle).

The per-request truth lives in :meth:`request_rows`: one row per
LOGICAL request (passes stitched across preemptions), with ``t_submit``
anchored at the open-loop ARRIVAL time — so queue-wait and TTFT charge
the frontend's own queueing, not just the engine's.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..obs.reqlog import _percentiles
from ..obs.slo import SLOPolicy, SLOReport, evaluate_slo
from .loadgen import Arrival, prompt_token_ids


class VirtualClock:
    """Deterministic logical clock: reads are pure, time moves only via
    :meth:`advance`.  Share one instance between the engine and the
    frontend so lifecycle timestamps and arrival deadlines live on the
    same (simulated) timeline."""

    def __init__(self, t0: float = 0.0):
        self._t = float(t0)

    def __call__(self) -> float:
        return self._t

    def advance(self, dt: float) -> None:
        if dt < 0:
            raise ValueError(f"cannot advance clock by {dt}")
        self._t += float(dt)

    def reset(self, t0: float = 0.0) -> None:
        """Rewind to ``t0`` — pair with ``engine.reset()`` so a warmed
        engine (compiled programs kept) can replay a scenario on the
        identical timeline it saw the first time."""
        self._t = float(t0)


@dataclass(frozen=True)
class ServiceTimeModel:
    """Virtual service costs the event loop charges per tick: one
    admission (prefill) wave, one K-step decode segment, one idle tick
    (nothing runnable — lets breaching windows roll past).  Only used
    with a :class:`VirtualClock`; real clocks measure instead."""

    wave_s: float = 0.01
    segment_s: float = 0.05
    idle_s: float = 0.005
    #: per-prompt-token prefill cost, charged at each prefill dispatch
    #: (whole-prompt waves pay it in one bulge; chunked admission spreads
    #: it across segments — the interference the chunked bench measures)
    prefill_tok_s: float = 0.0

    def to_json(self) -> Dict[str, float]:
        return {"wave_s": self.wave_s, "segment_s": self.segment_s,
                "idle_s": self.idle_s,
                "prefill_tok_s": self.prefill_tok_s}


class _Req:
    """One logical request's serving state across engine passes."""

    __slots__ = ("a", "cur_prompt", "cur_max_new", "prefix_parts",
                 "preemptions", "state", "passes", "cause")

    def __init__(self, a: Arrival, prompt_ids: np.ndarray):
        self.a = a
        self.cur_prompt = prompt_ids          # (1, P) int32, grows on resume
        self.cur_max_new = a.max_new_tokens
        self.prefix_parts: List[np.ndarray] = []
        self.preemptions = 0
        self.state = "waiting"                # waiting|inflight|shed|done
        self.passes: List[str] = []           # engine rids, in order
        # terminal cause code (shed_deadline | shed_ttft_doomed |
        # preempt_tier0_victim | defer_tier) — why the frontend last
        # acted on this request, None for the untouched happy path
        self.cause: Optional[str] = None

    @property
    def total_rows(self) -> int:
        # invariant across preemptions: prompt grows by exactly the
        # tokens the budget shrank by
        return int(self.cur_prompt.shape[1]) + self.cur_max_new

    def engine_rid(self) -> str:
        return (self.a.rid if self.preemptions == 0
                else f"{self.a.rid}#p{self.preemptions}")

    def record_preemption(self, res: Dict[str, Any]) -> None:
        tokens = np.asarray(res["tokens"], np.int32)
        self.prefix_parts.append(tokens)
        self.cur_prompt = np.concatenate(
            [self.cur_prompt, tokens[None, :]], axis=1
        )
        self.cur_max_new = int(res["remaining"])
        self.preemptions += 1
        self.state = "waiting"


class ServingFrontend:
    """Single-threaded serving event loop over one paged decode engine.

    ``engine`` must be freshly constructed (empty queue/slots) and, for
    deterministic runs, built with a :class:`VirtualClock` — the
    frontend adopts the engine's clock so both sides share a timeline.
    ``arrivals`` is the open-loop schedule (:mod:`.loadgen`); more can
    be injected mid-run via :meth:`submit`.
    """

    def __init__(
        self,
        engine: Any,
        arrivals: Sequence[Arrival],
        policy: Optional[SLOPolicy] = None,
        *,
        admission: str = "slo",
        preemption: bool = True,
        time_model: Optional[ServiceTimeModel] = None,
        prompt_seed: int = 0,
        max_ticks: int = 100_000,
        sleep: Optional[Any] = None,
        prompt_fn: Optional[Any] = None,
    ):
        if admission not in ("fifo", "slo"):
            raise ValueError(
                f"admission must be 'fifo' or 'slo', got {admission!r}"
            )
        if admission == "slo" and (policy is None or policy.ttft_s is None):
            raise ValueError(
                "slo admission needs a policy with a ttft_s target "
                "(it is the shed/defer control signal)"
            )
        self.engine = engine
        self.policy = policy
        self.admission = admission
        self.preemption = preemption and admission == "slo"
        self.clock = engine._clock
        self._virtual = hasattr(self.clock, "advance")
        if time_model is not None and not self._virtual:
            raise ValueError(
                "a ServiceTimeModel needs a VirtualClock on the engine"
            )
        self.tm = time_model or ServiceTimeModel()
        if (self._virtual and self.tm.prefill_tok_s > 0
                and hasattr(engine, "prefill_time_charge")):
            # charge prefill by REAL token count at each dispatch: the
            # engine calls back before every prefill (whole, shared or
            # chunk), so long prompts cost virtual time where they run
            engine.prefill_time_charge = (
                lambda n: self.clock.advance(self.tm.prefill_tok_s * n)
            )
        # injectable idle sleep (real-clock mode only): tests script a
        # fake clock + recording sleep to cover the wall-clock path
        # without spending wall time
        self._sleep = sleep if sleep is not None else time.sleep
        # pluggable prompt materializer (rid, prompt_len, vocab, seed) ->
        # (1, P) int32 — how the shared-prefix workload derives session
        # prompts; the default is the pre-existing per-rid generator, so
        # existing callers are bit-identical
        self.prompt_fn = (
            prompt_fn if prompt_fn is not None else prompt_token_ids
        )
        self.prompt_seed = prompt_seed
        self.max_ticks = max_ticks
        self.vocab_size = int(getattr(engine.config, "vocab_size", 256))
        self._pending: List[Arrival] = sorted(
            arrivals, key=lambda a: (a.t, a.rid)
        )
        if len({a.rid for a in self._pending}) != len(self._pending):
            raise ValueError("duplicate rids in arrival schedule")
        self._backlog: List[_Req] = []
        self._inflight: Dict[str, _Req] = {}
        self._reqs: "Dict[str, _Req]" = {}    # logical rid -> state
        self.results: Dict[str, np.ndarray] = {}
        self.slo_report: Optional[SLOReport] = None
        self.t0: Optional[float] = None
        self.ticks = 0

    # -- external intake ---------------------------------------------------
    def submit(self, arrival: Arrival) -> None:
        """Inject an arrival after construction (its ``t`` is still an
        offset from scenario start)."""
        if arrival.rid in self._reqs or any(
            a.rid == arrival.rid for a in self._pending
        ):
            raise ValueError(f"duplicate rid {arrival.rid!r}")
        self._pending.append(arrival)
        self._pending.sort(key=lambda a: (a.t, a.rid))

    # -- the event loop ----------------------------------------------------
    def run(
        self,
        *,
        deadline: Optional[float] = None,
        on_tick: Optional[Any] = None,
    ) -> Dict[str, Any]:
        """Serve the arrival schedule to completion; returns
        :meth:`report`.

        ``deadline`` bounds the run in seconds since ``t0`` (virtual or
        wall, whichever clock the engine carries) — the soak harness's
        ``--duration``.  At the deadline, not-yet-injected arrivals are
        dropped and the backlog is shed (they can produce no goodput in
        the remaining window), then in-flight work drains normally so
        page accounting ends clean.  ``on_tick(frontend)`` runs after
        every tick — the soak sampler's hook; it must only READ (a
        callback that advances the clock or mutates the engine would
        fork the deterministic timeline).
        """
        if self.t0 is None:
            self.t0 = self.clock()
        while self._pending or self._backlog or self._inflight:
            if (deadline is not None
                    and self.clock() - self.t0 >= deadline):
                self._shed_remaining()
                if not self._inflight:
                    break
            self.ticks += 1
            if self.ticks > self.max_ticks:
                raise RuntimeError(
                    f"serving loop stalled after {self.max_ticks} ticks: "
                    f"{len(self._pending)} pending, "
                    f"{len(self._backlog)} backlogged, "
                    f"{len(self._inflight)} in flight"
                )
            self._tick()
            if on_tick is not None:
                on_tick(self)
        return self.report()

    def _reqtrace(self):
        """The engine's per-request waterfall recorder, or None — the
        same zero-overhead guard the engine hot paths use."""
        return getattr(self.engine, "reqtrace", None)

    def _shed_remaining(self) -> None:
        """Deadline passed: drop arrivals that never happened and shed
        the backlog; in-flight work keeps draining."""
        self._pending.clear()
        rt = self._reqtrace()
        now = self.clock() if (self._backlog and rt is not None) else None
        for req in self._backlog:
            req.state = "shed"
            req.cause = "shed_deadline"
            if rt is not None:
                rt.shed(req.a.rid, now, cause="shed_deadline")
        self._backlog.clear()

    def _tick(self) -> None:
        now = self.clock()
        rel = now - self.t0
        # 1. inject arrivals whose deadline has passed
        rt = self._reqtrace()
        while self._pending and self._pending[0].t <= rel + 1e-9:
            a = self._pending.pop(0)
            req = self._make_req(a)
            self._reqs[a.rid] = req
            if rt is not None:
                # waterfall anchor = ARRIVAL time, matching the serving
                # row's t_submit; the engine's later submit() for the
                # same rid is an idempotent no-op on this track
                rt.submit(
                    a.rid, self.t0 + a.t, prompt_len=a.prompt_len,
                    max_new_tokens=a.max_new_tokens,
                    priority=a.priority,
                )
            if self.admission == "fifo":
                self._submit_to_engine(req)   # admit-all: engine FIFO queues
            else:
                self._backlog.append(req)
        # 2. admission control (slo mode submits exactly what fits NOW)
        if self.admission == "slo":
            waves = self._admit_backlog(now)
        else:
            waves = 1 if (self.engine._queue and self.engine.free_slots) else 0
        # 3. drive the engine one segment; charge virtual service time.
        #    The wave cost lands BEFORE the engine's admission clock
        #    reads so prefill has nonzero virtual duration.
        if self._virtual and waves:
            self.clock.advance(self.tm.wave_s * waves)
        engine_busy = (bool(self.engine._queue)
                       or self.engine.free_slots < self.engine.slots)
        if engine_busy:
            seg_before = self.engine.segments_run
            self.engine.step_segment()
            if self._virtual and self.engine.segments_run > seg_before:
                self.clock.advance(self.tm.segment_s)
        # 4. collect completions (stitch resumed passes)
        done = [e for e in self._inflight if e in self.engine.results]
        for erid in done:
            req = self._inflight.pop(erid)
            req.state = "done"
            toks = self.engine.results[erid]
            if req.prefix_parts:
                toks = np.concatenate(
                    [np.asarray(p, np.int32) for p in req.prefix_parts]
                    + [np.asarray(toks, np.int32)]
                )
            self.results[req.a.rid] = np.asarray(toks, np.int32)
        # 5. idle: nothing ran — move time toward the next arrival (or
        #    just forward, so a breaching window can roll past a
        #    deferred backlog)
        if not engine_busy and not waves:
            if self._virtual:
                if self._pending:
                    gap = (self._pending[0].t - rel)
                    self.clock.advance(max(gap, self.tm.idle_s))
                elif self._backlog:
                    self.clock.advance(self.tm.idle_s)
            else:
                # real clock: actually sleep until the next arrival's
                # deadline (floor keeps the loop from busy-spinning on
                # an imminent arrival; cap keeps mid-run submit()s and
                # soak deadlines responsive within 50 ms)
                wait = 0.001
                if self._pending:
                    wait = max(self._pending[0].t - rel, 0.0005)
                self._sleep(min(wait, 0.05))

    def _make_req(self, a: Arrival) -> _Req:
        """Materialize the serving state for a just-injected arrival
        (the fleet router's subclass swaps in a migration-aware type)."""
        return _Req(a, self.prompt_fn(
            a.rid, a.prompt_len, self.vocab_size, self.prompt_seed
        ))

    # -- admission / preemption -------------------------------------------
    def _submit_to_engine(self, req: _Req) -> None:
        erid = req.engine_rid()
        self.engine.submit(erid, req.cur_prompt, req.cur_max_new)
        req.passes.append(erid)
        req.state = "inflight"
        self._inflight[erid] = req

    def _admit_backlog(self, now: float) -> int:
        """SLO-aware admission at one segment boundary; returns the
        number of prefill waves (distinct prompt lengths) submitted."""
        if not self._backlog:
            return 0
        from ..models.kv_pages import pages_needed

        breaching = self._ttft_breaching(now)
        target = self.policy.ttft_s
        rt = self._reqtrace()
        keep: List[_Req] = []
        for req in self._backlog:
            waited = now - (self.t0 + req.a.t)
            if (req.a.priority > 0 and not req.passes
                    and waited > target):
                # already blew its TTFT budget: zero possible goodput,
                # so shed instead of spending pages on it
                req.state = "shed"
                req.cause = "shed_ttft_doomed"
                if rt is not None:
                    rt.shed(req.a.rid, now, cause="shed_ttft_doomed")
                continue
            keep.append(req)
        self._backlog = keep
        free_slots = self.engine.free_slots
        free_pages = self.engine.page_occupancy()["free_pages"]
        order = sorted(
            self._backlog, key=lambda r: (r.a.priority, r.a.t, r.a.rid)
        )
        submitted: List[_Req] = []
        lens = set()
        sharing = bool(getattr(self.engine, "sharing", False))
        for req in order:
            if breaching and req.a.priority > 0 and not req.passes:
                # defer low tier while the TTFT window breaches
                req.cause = "defer_tier"
                if rt is not None:
                    rt.wait(req.a.rid, now, "defer_tier")
                continue
            adm_need = getattr(
                self.engine, "admission_pages_needed", None
            )
            if adm_need is not None:
                # the engine's own headroom arithmetic: first-chunk-only
                # for chunk-eligible prompts (later chunks alloc lazily),
                # fresh-tail footprint under sharing, full footprint
                # otherwise
                need = adm_need(req.cur_prompt, req.cur_max_new)
            elif sharing:
                # fresh-tail footprint only: resident shared prefix
                # chunks cost no new pages, so admission sees the same
                # headroom the engine's allocator will
                need = self.engine.fresh_pages_needed(
                    req.cur_prompt, req.cur_max_new
                )
            else:
                need = pages_needed(req.total_rows, self.engine.page_size)
            if free_slots < 1 or need > free_pages:
                if rt is not None:
                    # who is the capacity? the in-flight page holders
                    # (pure occupancy read — the same surface the
                    # admission arithmetic above already consumed)
                    holders = sorted(
                        self.engine.page_occupancy()["per_request"]
                    )
                    rt.wait(
                        req.a.rid, now,
                        "slots_full" if free_slots < 1 else "page_pool",
                        by=holders,
                    )
                if not (self.preemption and req.a.priority == 0):
                    continue
                got = self._try_preempt(req, need, free_slots, free_pages)
                if got is None:
                    continue
                free_slots, free_pages = got
            self._submit_to_engine(req)
            submitted.append(req)
            free_slots -= 1
            free_pages -= need
            lens.add(int(req.cur_prompt.shape[1]))
        for req in submitted:
            self._backlog.remove(req)
        return len(lens)

    def _try_preempt(
        self, req: _Req, need: int, free_slots: int, free_pages: int
    ):
        """Evict lower-tier in-flight victims until ``req`` fits;
        returns the new (free_slots, free_pages) or None when no victim
        set suffices (then nothing is evicted)."""
        occ = self.engine.page_occupancy()
        # under sharing, evicting a victim frees only its EXCLUSIVE
        # pages (aliased prefix chunks stay resident for their other
        # owners) — the conservative count keeps the estimate honest
        per_req = occ.get("per_request_exclusive", occ["per_request"])
        prefilling = getattr(self.engine, "is_prefilling", None)
        victims = [
            v for v in self._inflight.values()
            if v.a.priority > req.a.priority and v.passes
            # mid-chunked-prefill slots are not preemptible: no first
            # token yet means no resumable prefix, only wasted chunks
            and not (prefilling is not None
                     and prefilling(v.engine_rid()))
        ]
        # most recently arrived, lowest tier first: evict the work with
        # the least sunk queue-wait
        victims.sort(key=lambda v: (-v.a.priority, -v.a.t, v.a.rid))
        chosen: List[_Req] = []
        gs, gp = free_slots, free_pages
        for v in victims:
            if gs >= 1 and gp >= need:
                break
            chosen.append(v)
            gs += 1
            gp += int(per_req.get(v.engine_rid(), 0))
        if not (gs >= 1 and gp >= need):
            return None
        for v in chosen:
            erid = v.engine_rid()
            res = self.engine.preempt(
                erid, cause="preempt_tier0_victim", by=str(req.a.rid)
            )
            del self._inflight[erid]
            v.record_preemption(res)
            v.cause = "preempt_tier0_victim"
            self._backlog.append(v)
        return gs, gp

    def _ttft_breaching(self, now: float) -> bool:
        """The control signal: does a recent window's TTFT percentile
        breach the policy target?  Evaluated over the serving log
        (arrival-anchored), not the engine log — in slo mode queueing
        happens HERE, before the engine ever sees the request."""
        if self.policy is None or self.policy.ttft_s is None:
            return False
        report = evaluate_slo(
            {"requests": self._rows()}, self.policy, t_end=now
        )
        if not report.breaches:
            return False
        n = len(report.windows)
        return any(
            b["metric"] == "ttft_s" and b["window"] >= n - 2
            for b in report.breaches
        )

    # -- the serving log ---------------------------------------------------
    def _pass_records(self, req: _Req) -> List[Any]:
        """Lifecycle records for each engine pass of ``req``, in pass
        order (the fleet subclass also consults records frozen before a
        replica restart wiped its log)."""
        return [
            r for r in (self.engine.reqlog.get(e) for e in req.passes)
            if r is not None
        ]

    def _row(self, req: _Req) -> Dict[str, Any]:
        t_arr = (self.t0 or 0.0) + req.a.t
        row: Dict[str, Any] = {
            "rid": str(req.a.rid),
            "priority": req.a.priority,
            "prompt_len": req.a.prompt_len,
            "max_new_tokens": req.a.max_new_tokens,
            "state": "queued",
            "t_submit": t_arr,
            "t_admit": None,
            "t_first_token": None,
            "t_retire": None,
            "n_tokens": 0,
            "deliveries": [],
            "preemptions": req.preemptions,
            "cause": req.cause,
        }
        if req.state == "shed":
            row["state"] = "shed"
        else:
            recs = self._pass_records(req)
            if recs:
                row["t_admit"] = recs[0].t_admit
                row["t_first_token"] = recs[0].t_first_token
                deliveries = [d for r in recs for d in r.deliveries]
                row["deliveries"] = [[t, int(n)] for t, n in deliveries]
                row["n_tokens"] = int(sum(n for _, n in deliveries))
                last = recs[-1]
                if last.state == "retired":
                    row["state"] = "retired"
                    row["t_retire"] = last.t_retire
                elif last.state == "preempted":
                    row["state"] = "preempted"
                elif row["t_first_token"] is not None:
                    row["state"] = "decoding"
        row["queue_wait_s"] = (
            row["t_admit"] - t_arr if row["t_admit"] is not None else None
        )
        row["ttft_s"] = (
            row["t_first_token"] - t_arr
            if row["t_first_token"] is not None else None
        )
        row["e2e_s"] = (
            row["t_retire"] - t_arr
            if row["t_retire"] is not None else None
        )
        n = row["n_tokens"]
        row["tpot_s"] = (
            (row["t_retire"] - row["t_first_token"]) / (n - 1)
            if row["t_retire"] is not None
            and row["t_first_token"] is not None and n > 1 else None
        )
        return row

    def _rows(self) -> List[Dict[str, Any]]:
        return [self._row(self._reqs[rid]) for rid in self._reqs]

    def request_rows(self) -> List[Dict[str, Any]]:
        """One row per logical request, ``dls.requests/1``-shaped plus
        ``priority``/``preemptions`` and the serving-only states
        ``shed``/``preempted``; ``t_submit`` is the ARRIVAL time."""
        return self._rows()

    def lint(self, *, final: bool = True):
        """Run the request-lifecycle protocol checker (LCY00x) over this
        frontend's live request rows; returns the
        :class:`~..analysis.diagnostics.AnalysisReport`.  ``final=True``
        (the default) additionally requires every request to have
        reached a terminal state — pass ``False`` mid-run."""
        from ..analysis.lifecycle_pass import analyze_lifecycle

        return analyze_lifecycle(
            self._rows(), final=final, label="serving"
        )

    # -- reporting ---------------------------------------------------------
    def report(self) -> Dict[str, Any]:
        """Serving-leg summary: goodput (tokens/s of SLO-meeting
        completed requests), arrival-anchored latency percentiles, shed
        and preemption counts, and the page-leak check.  Idempotent."""
        t_end = self.clock()
        rows = self._rows()
        makespan = max(t_end - (self.t0 if self.t0 is not None else t_end),
                       1e-12)
        tokens_total = sum(r["n_tokens"] for r in rows)
        tokens_good = tokens_total
        breached = False
        slo_summary = None
        if self.policy is not None:
            rep = evaluate_slo(
                {"requests": rows}, self.policy, t_end=t_end
            )
            self.slo_report = rep
            tokens_good = rep.tokens_good
            breached = rep.exceeds()
            slo_summary = rep.summary()
        completed = [r for r in rows if r["state"] == "retired"]

        def pct_ms(metric: str) -> Dict[str, Optional[float]]:
            vals = [
                float(r[metric]) for r in completed
                if r.get(metric) is not None
            ]
            return {
                k: (v * 1e3 if v is not None else None)
                for k, v in _percentiles(vals).items()
            }

        ttft = pct_ms("ttft_s")
        qwait = pct_ms("queue_wait_s")
        tpot = pct_ms("tpot_s")
        occ = self.engine.page_occupancy()
        return {
            "admission": self.admission,
            "preemption": self.preemption,
            "n_requests": len(rows),
            "completed": len(completed),
            "shed": sum(1 for r in rows if r["state"] == "shed"),
            "preempted_requests": sum(
                1 for r in rows if r["preemptions"] > 0
            ),
            "preemptions": sum(r["preemptions"] for r in rows),
            "tokens_total": int(tokens_total),
            "tokens_good": int(tokens_good),
            "makespan_s": makespan,
            "goodput_tok_s": tokens_good / makespan,
            "throughput_tok_s": tokens_total / makespan,
            "ttft_p50_ms": ttft["p50"],
            "ttft_p95_ms": ttft["p95"],
            "ttft_p99_ms": ttft["p99"],
            "queue_wait_p50_ms": qwait["p50"],
            "queue_wait_p95_ms": qwait["p95"],
            "tpot_p50_ms": tpot["p50"],
            "tpot_p95_ms": tpot["p95"],
            "tpot_p99_ms": tpot["p99"],
            "pages_leaked": occ["n_pages"] - occ["free_pages"],
            "breached": breached,
            "slo": slo_summary,
            "requests": rows,
        }

    def digest(self) -> str:
        """sha256 over the serving log AND every generated token — two
        same-seed virtual-time runs must match exactly (the serve
        bench's determinism gate)."""
        import hashlib
        import json

        payload = json.dumps(
            {
                "requests": self._rows(),
                "tokens": {
                    rid: self.results[rid].tolist()
                    for rid in sorted(self.results)
                },
            },
            sort_keys=True,
        ).encode("utf-8")
        return hashlib.sha256(payload).hexdigest()


__all__ = [
    "ServiceTimeModel",
    "ServingFrontend",
    "VirtualClock",
]
