"""Deterministic open-loop load generation for the serving front-end.

An ONLINE serving evaluation needs requests that arrive over time,
independent of how fast the server drains them (open-loop: a slow
server grows a queue instead of slowing the generator down — the regime
where SLOs break).  This module produces that arrival process two ways:

* :func:`poisson_arrivals` — a seeded Poisson process (exponential
  inter-arrival gaps) with per-request prompt-length / max-tokens /
  priority draws, all from one ``numpy.random.RandomState``.  The
  legacy ``RandomState`` generator is stability-guaranteed by numpy, so
  the same seed yields the bitwise-identical schedule on any machine or
  process — the determinism the serve bench's repeat-run gate and the
  cross-process test lean on.
* trace files (``dls.arrivals/1``) — :func:`save_trace` /
  :func:`load_trace` round-trip an arrival schedule through JSON so a
  scenario can be replayed exactly (or hand-written) without the
  generator.

Prompt CONTENT is derived, not stored: :func:`prompt_token_ids` keys a
``RandomState`` off ``(seed, crc32(rid))``, so any holder of an
:class:`Arrival` reconstructs the same tokens — traces stay small and
replays stay exact.
"""

from __future__ import annotations

import hashlib
import json
import zlib
from dataclasses import asdict, dataclass
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

#: trace-file schema tag (validated by :func:`validate_trace_obj`)
TRACE_SCHEMA = "dls.arrivals/1"


@dataclass(frozen=True)
class Arrival:
    """One open-loop request arrival.

    ``t`` is the arrival offset in seconds from scenario start;
    ``priority`` is the tier (0 = highest; higher numbers are
    load-sheddable).  Prompt tokens are derived from the rid via
    :func:`prompt_token_ids`, not carried here.
    """

    rid: str
    t: float
    prompt_len: int
    max_new_tokens: int
    priority: int = 0

    def to_json(self) -> Dict[str, Any]:
        return asdict(self)


def poisson_arrivals(
    rate_rps: float,
    n_requests: int,
    seed: int,
    *,
    prompt_lens: Sequence[int] = (8,),
    max_new_tokens: Sequence[int] = (8,),
    priorities: Sequence[int] = (0,),
    priority_weights: Optional[Sequence[float]] = None,
    rid_prefix: str = "r",
) -> List[Arrival]:
    """Seeded Poisson arrival schedule: ``n_requests`` arrivals at mean
    rate ``rate_rps``, prompt length / decode budget / priority drawn
    uniformly (or per ``priority_weights``) from the given choices.

    Same ``(seed, parameters)`` -> bitwise-identical schedule, across
    processes and platforms (legacy ``RandomState`` stability).
    """
    if rate_rps <= 0:
        raise ValueError(f"rate_rps must be > 0, got {rate_rps}")
    if n_requests < 1:
        raise ValueError(f"n_requests must be >= 1, got {n_requests}")
    rng = np.random.RandomState(seed)
    p = None
    if priority_weights is not None:
        if len(priority_weights) != len(priorities):
            raise ValueError(
                f"{len(priority_weights)} weights for "
                f"{len(priorities)} priorities"
            )
        total = float(sum(priority_weights))
        p = [w / total for w in priority_weights]
    out: List[Arrival] = []
    t = 0.0
    for i in range(n_requests):
        t += float(rng.exponential(1.0 / rate_rps))
        out.append(Arrival(
            rid=f"{rid_prefix}{i}",
            t=t,
            prompt_len=int(rng.choice(list(prompt_lens))),
            max_new_tokens=int(rng.choice(list(max_new_tokens))),
            priority=int(rng.choice(list(priorities), p=p)),
        ))
    return out


def session_arrivals(
    rate_rps: float,
    n_sessions: int,
    seed: int,
    *,
    system_len: int,
    user_len: int,
    turns: int = 2,
    max_new_tokens: Sequence[int] = (8,),
    priorities: Sequence[int] = (0,),
    priority_weights: Optional[Sequence[float]] = None,
    think_time_s: float = 0.05,
    rid_prefix: str = "s",
) -> List[Arrival]:
    """Deterministic shared-prefix / multi-turn session schedule.

    Session STARTS are a seeded Poisson process at ``rate_rps``; each
    session then resubmits ``turns`` times with exponential think-time
    gaps, every turn growing the prompt by ``user_len`` tokens on top of
    the shared ``system_len``-token system prompt (turn ``k`` arrives
    with ``prompt_len = system_len + (k+1) * user_len``).  Rids are
    derived — ``{prefix}{i}t{k}`` — so :func:`session_prompt_token_ids`
    can reconstruct each turn's prompt as the EXACT extension of the
    previous turn's (and of every other session's system prompt), which
    is what makes the workload prefix-shareable.  Plain
    :class:`Arrival` rows: the same ``dls.arrivals/1`` trace round-trip,
    digest, and replay machinery applies unchanged.
    """
    if rate_rps <= 0:
        raise ValueError(f"rate_rps must be > 0, got {rate_rps}")
    if n_sessions < 1:
        raise ValueError(f"n_sessions must be >= 1, got {n_sessions}")
    if turns < 1:
        raise ValueError(f"turns must be >= 1, got {turns}")
    if system_len < 1 or user_len < 1:
        raise ValueError(
            f"system_len/user_len must be >= 1, got "
            f"{system_len}/{user_len}"
        )
    rng = np.random.RandomState(seed)
    p = None
    if priority_weights is not None:
        if len(priority_weights) != len(priorities):
            raise ValueError(
                f"{len(priority_weights)} weights for "
                f"{len(priorities)} priorities"
            )
        total = float(sum(priority_weights))
        p = [w / total for w in priority_weights]
    out: List[Arrival] = []
    t = 0.0
    for i in range(n_sessions):
        t += float(rng.exponential(1.0 / rate_rps))
        prio = int(rng.choice(list(priorities), p=p))
        tk = t
        for k in range(turns):
            if k > 0:
                tk += float(rng.exponential(think_time_s))
            out.append(Arrival(
                rid=f"{rid_prefix}{i}t{k}",
                t=tk,
                prompt_len=system_len + (k + 1) * user_len,
                max_new_tokens=int(rng.choice(list(max_new_tokens))),
                priority=prio,
            ))
    out.sort(key=lambda a: (a.t, a.rid))
    return out


def mixed_long_prompt_arrivals(
    rate_rps: float,
    n_requests: int,
    seed: int,
    *,
    short_lens: Sequence[int] = (3, 5, 8),
    long_len: int = 24,
    long_every: int = 8,
    max_new_tokens: Sequence[int] = (3,),
    long_max_new_tokens: int = 4,
    priorities: Sequence[int] = (0,),
    priority_weights: Optional[Sequence[float]] = None,
    rid_prefix: str = "m",
) -> List[Arrival]:
    """Poisson short-prompt traffic with sparse very-long prompts: the
    interference shape where whole-prompt admission cliffs (one long
    prefill stalls every in-flight decode) and chunked prefill pays off.

    Every ``long_every``-th arrival (1-indexed: arrivals ``long_every``,
    ``2*long_every``, ...) is a ``long_len``-token prompt with its own
    decode budget; the rest draw from ``short_lens``.  The long cadence
    is deterministic by POSITION, not by draw, so the long/short
    interleaving is identical across seeds that only reshuffle the
    short-prompt draws.  Plain :class:`Arrival` rows — the same
    ``dls.arrivals/1`` trace round-trip, digest, and replay machinery
    applies unchanged.
    """
    if rate_rps <= 0:
        raise ValueError(f"rate_rps must be > 0, got {rate_rps}")
    if n_requests < 1:
        raise ValueError(f"n_requests must be >= 1, got {n_requests}")
    if long_every < 2:
        raise ValueError(f"long_every must be >= 2, got {long_every}")
    if long_len <= max(short_lens):
        raise ValueError(
            f"long_len {long_len} must exceed the longest short prompt "
            f"{max(short_lens)}"
        )
    rng = np.random.RandomState(seed)
    p = None
    if priority_weights is not None:
        if len(priority_weights) != len(priorities):
            raise ValueError(
                f"{len(priority_weights)} weights for "
                f"{len(priorities)} priorities"
            )
        total = float(sum(priority_weights))
        p = [w / total for w in priority_weights]
    out: List[Arrival] = []
    t = 0.0
    for i in range(n_requests):
        t += float(rng.exponential(1.0 / rate_rps))
        # draw unconditionally so the short-prompt stream is identical
        # whether or not this position is a long one
        plen = int(rng.choice(list(short_lens)))
        mnew = int(rng.choice(list(max_new_tokens)))
        prio = int(rng.choice(list(priorities), p=p))
        if (i + 1) % long_every == 0:
            plen, mnew = long_len, long_max_new_tokens
        out.append(Arrival(
            rid=f"{rid_prefix}{i}",
            t=t,
            prompt_len=plen,
            max_new_tokens=mnew,
            priority=prio,
        ))
    return out


def session_prompt_token_ids(
    rid: Any,
    prompt_len: int,
    vocab_size: int,
    seed: int = 0,
    *,
    system_len: int,
    user_len: int,
) -> np.ndarray:
    """Prompt materializer for :func:`session_arrivals` rids: the shared
    system chunk, then one derived user chunk per turn — so turn ``k``'s
    prompt is bitwise turn ``k-1``'s plus one more chunk, and EVERY
    session starts with the identical ``system_len`` tokens.

    ``rid`` must be ``{session}t{k}``; the chunks are derived through
    :func:`prompt_token_ids` under synthetic rids (``__system__`` and
    ``{session}u{j}``), so determinism and trace-free replay carry over.
    """
    srid = str(rid)
    sid, _, turn = srid.rpartition("t")
    if not sid or not turn.isdigit():
        raise ValueError(
            f"session rid must look like '<session>t<turn>', got {srid!r}"
        )
    k = int(turn)
    want = system_len + (k + 1) * user_len
    if prompt_len != want:
        raise ValueError(
            f"rid {srid!r} turn {k} implies prompt_len {want}, "
            f"got {prompt_len}"
        )
    parts = [prompt_token_ids("__system__", system_len, vocab_size, seed)]
    for j in range(k + 1):
        parts.append(
            prompt_token_ids(f"{sid}u{j}", user_len, vocab_size, seed)
        )
    return np.concatenate(parts, axis=1)


def prompt_token_ids(
    rid: Any, prompt_len: int, vocab_size: int, seed: int = 0
) -> np.ndarray:
    """Deterministic (1, prompt_len) int32 prompt for ``rid``.

    Keyed off ``(seed, crc32(rid))`` so the generator, the frontend,
    and a replay from a trace file all materialize the same tokens
    without the trace carrying them.  Token 0 is avoided (it doubles as
    padding in parts of the model zoo).
    """
    if prompt_len < 1:
        raise ValueError(f"prompt_len must be >= 1, got {prompt_len}")
    key = zlib.crc32(str(rid).encode("utf-8")) & 0xFFFFFFFF
    rng = np.random.RandomState([seed & 0xFFFFFFFF, key])
    lo, hi = 1, max(2, vocab_size)
    return rng.randint(lo, hi, size=(1, prompt_len)).astype(np.int32)


# -- trace files ----------------------------------------------------------
def arrivals_to_json(arrivals: Sequence[Arrival]) -> Dict[str, Any]:
    return {
        "schema": TRACE_SCHEMA,
        "arrivals": [a.to_json() for a in arrivals],
    }


def validate_trace_obj(obj: Any) -> List[str]:
    """Structural check of a ``dls.arrivals/1`` dict; returns
    human-readable problems (empty list == valid)."""
    errs: List[str] = []
    if not isinstance(obj, dict):
        return [f"trace is {type(obj).__name__}, not dict"]
    if obj.get("schema") != TRACE_SCHEMA:
        errs.append(
            f"schema is {obj.get('schema')!r}, want {TRACE_SCHEMA!r}"
        )
    rows = obj.get("arrivals")
    if not isinstance(rows, list) or not rows:
        return errs + ["arrivals block missing, not a list, or empty"]
    seen = set()
    for i, row in enumerate(rows):
        if not isinstance(row, dict):
            errs.append(f"arrivals[{i}] is not a dict")
            continue
        rid = row.get("rid")
        if not isinstance(rid, str) or not rid:
            errs.append(f"arrivals[{i}] rid missing or not a string")
        elif rid in seen:
            errs.append(f"arrivals[{i}] duplicate rid {rid!r}")
        else:
            seen.add(rid)
        t = row.get("t")
        if not isinstance(t, (int, float)) or isinstance(t, bool) or t < 0:
            errs.append(f"arrivals[{i}] t must be a number >= 0")
        for f, lo in (("prompt_len", 1), ("max_new_tokens", 1),
                      ("priority", 0)):
            v = row.get(f)
            if not isinstance(v, int) or isinstance(v, bool) or v < lo:
                errs.append(f"arrivals[{i}] {f} must be an int >= {lo}")
    return errs


def load_trace(path: str) -> List[Arrival]:
    """Parse + validate a ``dls.arrivals/1`` trace file; raises
    ``ValueError`` on malformed content (the ``serve`` CLI maps that to
    exit 2)."""
    with open(path) as f:
        obj = json.load(f)
    errs = validate_trace_obj(obj)
    if errs:
        raise ValueError(
            f"malformed arrival trace {path}: " + "; ".join(errs[:5])
        )
    return [
        Arrival(
            rid=row["rid"], t=float(row["t"]),
            prompt_len=int(row["prompt_len"]),
            max_new_tokens=int(row["max_new_tokens"]),
            priority=int(row["priority"]),
        )
        for row in obj["arrivals"]
    ]


def save_trace(arrivals: Sequence[Arrival], path: str) -> None:
    with open(path, "w") as f:
        json.dump(arrivals_to_json(arrivals), f, indent=1, sort_keys=True)


def schedule_digest(arrivals: Sequence[Arrival]) -> str:
    """sha256 over the canonical JSON schedule — the cross-process
    determinism probe (two processes with the same seed must print the
    same digest)."""
    payload = json.dumps(
        [a.to_json() for a in arrivals], sort_keys=True
    ).encode("utf-8")
    return hashlib.sha256(payload).hexdigest()


__all__ = [
    "Arrival",
    "TRACE_SCHEMA",
    "arrivals_to_json",
    "load_trace",
    "mixed_long_prompt_arrivals",
    "poisson_arrivals",
    "prompt_token_ids",
    "save_trace",
    "schedule_digest",
    "session_arrivals",
    "session_prompt_token_ids",
    "validate_trace_obj",
]
