"""Replica registry: N named engines, each with its own obs universe.

The fleet tier (``serve/router.py``) needs replicas that are genuinely
independent observability domains — per-replica metrics registry
(``{rid}.``-prefixed, ``replica``-labeled so snapshots merge without
key collisions), per-replica request log and ownership log (owned by
the engine itself), and a per-replica :class:`~..obs.timeseries.
TimeSeriesStore` the health detectors judge.  This module owns that
wiring so the router can stay pure policy.

``EngineRegistry`` builds engines through a caller-supplied factory::

    factory(rid, *, clock, metrics) -> engine

The factory either constructs a fresh ``PagedDecodeEngine`` with that
clock/metrics (tests on a cold cache) or takes a POOLED engine and
``rebind_obs(clock=..., metrics=...)``s it (the session-fixture path —
no fresh XLA builds per test).  Either way the registry hands back a
:class:`ReplicaHandle` whose obs surfaces are exclusively this
replica's.

:meth:`EngineRegistry.restart` is the failover primitive: rebind the
SAME engine (compiled programs kept) against the SAME clock (the fleet
timeline must not rewind) but FRESH metrics and a FRESH series store —
a restarted replica's trends start from its restart epoch, which is
why the handle records ``epoch_t0``: detector warmup is measured from
there, not from fleet t0.  ``rebind_obs`` also swaps any fault-injected
pool wrapper for a pristine one, so a restart genuinely cures a
``_LeakyPool``.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

from ..obs.metrics import MetricsRegistry
from ..obs.timeseries import TimeSeriesStore
from .frontend import VirtualClock


class ReplicaHandle:
    """One replica's engine plus its private observability surfaces and
    the router-visible health state machine
    (``active`` → ``draining`` → ``probation`` → ``active``)."""

    __slots__ = ("rid", "engine", "clock", "metrics", "store",
                 "epoch_t0", "restarts", "state", "probation_until",
                 "routed", "drains")

    def __init__(self, rid: str, engine: Any, clock: Any,
                 metrics: MetricsRegistry, store: TimeSeriesStore):
        self.rid = rid
        self.engine = engine
        self.clock = clock
        self.metrics = metrics
        self.store = store
        self.epoch_t0 = float(clock())   # start of current obs epoch
        self.restarts = 0
        self.state = "active"            # active | draining | probation
        self.probation_until: Optional[float] = None
        self.routed = 0                  # arrivals routed here
        self.drains = 0                  # times drained

    @property
    def admitting(self) -> bool:
        """Whether the router may place NEW arrivals here (probation
        replicas serve what they have but take no new work until the
        window passes — the router flips them back to active)."""
        return self.state == "active"

    def summary(self) -> Dict[str, Any]:
        return {
            "rid": self.rid,
            "state": self.state,
            "restarts": self.restarts,
            "drains": self.drains,
            "routed": self.routed,
            "epoch_t0": self.epoch_t0,
            "engine": self.engine.summary(),
        }


class EngineRegistry:
    """Replica-id-addressed engine set sharing one factory seam.

    Replica ids are caller-chosen strings (the fleet bench uses
    ``n0..n2`` — disjoint from request rids ``r*`` so merged logs stay
    unambiguous).  Duplicate ids are a hard error: an id is an obs
    namespace, and two engines writing one namespace is exactly the
    collision this layer exists to prevent.
    """

    def __init__(
        self,
        factory: Callable[..., Any],
        *,
        series_capacity: int = 512,
    ):
        self.factory = factory
        self.series_capacity = int(series_capacity)
        self._replicas: Dict[str, ReplicaHandle] = {}

    def __len__(self) -> int:
        return len(self._replicas)

    def __contains__(self, rid: str) -> bool:
        return rid in self._replicas

    def rids(self) -> List[str]:
        return sorted(self._replicas)

    def replicas(self) -> List[ReplicaHandle]:
        """Handles in sorted-rid order (the router's deterministic
        iteration order)."""
        return [self._replicas[r] for r in sorted(self._replicas)]

    def get(self, rid: str) -> ReplicaHandle:
        h = self._replicas.get(rid)
        if h is None:
            raise KeyError(f"unknown replica {rid!r}; "
                           f"have {self.rids()}")
        return h

    def _obs_for(self, rid: str, clock: Any):
        metrics = MetricsRegistry(prefix=f"{rid}.", replica=rid)
        store = TimeSeriesStore(
            capacity=self.series_capacity, clock=clock
        )
        return metrics, store

    def add(self, rid: str, *, clock: Any = None) -> ReplicaHandle:
        """Build (or rebind) an engine for ``rid`` and register it.
        ``clock`` defaults to a fresh :class:`VirtualClock` at t=0 so
        all replicas start on aligned timelines."""
        rid = str(rid)
        if rid in self._replicas:
            raise ValueError(f"duplicate replica id {rid!r}")
        clk = clock if clock is not None else VirtualClock()
        metrics, store = self._obs_for(rid, clk)
        engine = self.factory(rid, clock=clk, metrics=metrics)
        if engine is None:
            raise ValueError(
                f"factory returned None for replica {rid!r}"
            )
        h = ReplicaHandle(rid, engine, clk, metrics, store)
        self._replicas[rid] = h
        return h

    def restart(self, rid: str) -> ReplicaHandle:
        """Failover restart: same engine and clock, fresh obs epoch.

        ``rebind_obs`` wipes run state (queue/slots/pages/reqlog),
        swaps a fault-injected pool wrapper for a pristine pool, and
        clears any drain flag; the handle gets a fresh metrics registry
        and series store so post-restart trends are judged only on
        post-restart samples (``epoch_t0`` moves to now)."""
        h = self.get(rid)
        metrics, store = self._obs_for(rid, h.clock)
        h.engine.rebind_obs(clock=h.clock, metrics=metrics)
        h.metrics = metrics
        h.store = store
        h.epoch_t0 = float(h.clock())
        h.restarts += 1
        return h

    def merged_metrics(self) -> Dict[str, Any]:
        """One ``dls.metrics/1`` snapshot over every replica (see
        :func:`~..obs.fleet.merge_snapshots`)."""
        from ..obs.fleet import merge_snapshots

        return merge_snapshots(
            [h.metrics.snapshot() for h in self.replicas()]
        )


__all__ = ["EngineRegistry", "ReplicaHandle"]
