"""Fleet router: health-driven placement over an engine registry.

:class:`FleetFrontend` runs N per-replica serving event loops
(:class:`~.frontend.ServingFrontend` instances, one per
:class:`~.registry.ReplicaHandle`) in deterministic lockstep on
parallel virtual clocks:

* **Routing** — each arrival is placed when its deadline passes, by
  scoring every admitting replica on the same headroom surface the
  admission policies read (``page_occupancy()`` free-page fraction +
  free-slot fraction, minus queue pressure: backlog + engine queue +
  not-yet-injected pending).  Highest score wins, ties break to the
  lowest replica id — placement is a pure function of observable
  state.  ``routing="round_robin"`` is the health-blind baseline the
  fleet bench must beat.
* **Affinity** — preempt/resume stays replica-local by construction: a
  preempted request re-enters ITS OWN replica's backlog and resumes
  under ``{rid}#p{k}`` against the prefix pages it already paid for.
  Only an explicit drain migrates work across replicas.
* **Health policing** — when a detector battery is given (default
  :func:`~..obs.fleet.fleet_detectors`: HLT001 page-leak only), each
  replica's own series store is sampled on a fixed virtual cadence and
  re-judged whenever new samples exist, with warmup measured from the
  replica's CURRENT obs epoch.  A breaching replica is **drained**
  (``engine.begin_drain()``; its backlog is re-routed, eligible
  in-flight work is preempt-migrated, mid-prefill work finishes in
  place), then **restarted** through the registry once empty (same
  compiled engine, fresh obs epoch, pristine pool — the cure for an
  injected leak), then held in **probation** (serving nothing new)
  until the window passes.
* **Migration** — a preempt-migrated request is resubmitted on the
  target as ``{rid}#m{m}`` (``#p{k}`` still appended per preemption)
  with its generated prefix stitched into the prompt, exactly like a
  local resume — greedy determinism makes the continuation bitwise
  identical to an uninterrupted run on the target.  Records from the
  source replica are frozen on the request before the source's log is
  wiped, so merged serving rows survive the restart.

**Lockstep time.**  Every round routes due arrivals, polices health,
ticks each replica that has runnable work exactly once, then advances
every replica clock to the maximum ("barrier") — parallel timelines
never drift, which is what makes cross-replica timestamps comparable
and same-seed runs digest-identical.  With a single replica and no
detectors the loop reduces exactly to the standalone
``ServingFrontend`` schedule (the N=1 digest-parity gate).

Global rid uniqueness is enforced HERE (each engine only guards its
own log): a rid seen by any replica — including one that migrated away
— can never be resubmitted (:class:`DuplicateRidError`).
"""

from __future__ import annotations

import hashlib
import json
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..obs.slo import SLOPolicy
from ..obs.timeseries import SoakSampler, TimeSeriesStore
from .frontend import ServiceTimeModel, ServingFrontend, _Req
from .loadgen import Arrival
from .registry import EngineRegistry, ReplicaHandle


class DuplicateRidError(ValueError):
    """A logical rid was submitted twice anywhere in the fleet."""


class _FleetReq(_Req):
    """A logical request that can additionally hop replicas."""

    __slots__ = ("migrations", "frozen_recs")

    def __init__(self, a: Arrival, prompt_ids: np.ndarray):
        super().__init__(a, prompt_ids)
        self.migrations = 0
        # engine rid -> RequestRecord captured before a source replica's
        # log was wiped (migration or restart); _pass_records prefers
        # these over the live log
        self.frozen_recs: Dict[str, Any] = {}

    def engine_rid(self) -> str:
        base = (self.a.rid if self.migrations == 0
                else f"{self.a.rid}#m{self.migrations}")
        return (base if self.preemptions == 0
                else f"{base}#p{self.preemptions}")

    def record_migration(self, res: Dict[str, Any]) -> None:
        """Fold a preempt-for-migration result into the request: the
        generated prefix joins the prompt (same stitching as a local
        preemption) but the derived rid advances ``#m`` not ``#p`` —
        the move was the fleet's decision, not SLO pressure, and the
        serving row must not count it as a preemption."""
        tokens = np.asarray(res["tokens"], np.int32)
        self.prefix_parts.append(tokens)
        self.cur_prompt = np.concatenate(
            [self.cur_prompt, tokens[None, :]], axis=1
        )
        self.cur_max_new = int(res["remaining"])
        self.migrations += 1
        self.state = "waiting"


class _ReplicaFrontend(ServingFrontend):
    """Per-replica event loop: migration-aware request state, frozen
    record lookup, and a drain guard on admission."""

    def _make_req(self, a: Arrival) -> _FleetReq:
        return _FleetReq(a, self.prompt_fn(
            a.rid, a.prompt_len, self.vocab_size, self.prompt_seed
        ))

    def _pass_records(self, req: _Req) -> List[Any]:
        frozen = getattr(req, "frozen_recs", None)
        recs = []
        for e in req.passes:
            r = frozen.get(e) if frozen else None
            if r is None:
                r = self.engine.reqlog.get(e)
            if r is not None:
                recs.append(r)
        return recs

    def _row(self, req: _Req) -> Dict[str, Any]:
        row = super()._row(req)
        m = getattr(req, "migrations", 0)
        if m:
            # only on hopped rows: N=1 fleet rows stay byte-identical
            # to the standalone frontend's
            row["migrations"] = m
        return row

    def _admit_backlog(self, now: float) -> int:
        # a draining engine hard-rejects submit(); its backlog is being
        # re-routed by the fleet — never admit into the drain
        if getattr(self.engine, "draining", False):
            return 0
        return super()._admit_backlog(now)


class FleetFrontend:
    """Deterministic fleet serving loop over an
    :class:`~.registry.EngineRegistry` (see module docstring).

    ``detectors=None`` disables policing AND per-replica sampling
    entirely (the zero-overhead/baseline mode); pass
    :func:`~..obs.fleet.fleet_detectors` (or any battery) to turn the
    observability layer into the control plane.  ``warmup_s`` and
    ``probation_s`` are in virtual seconds; ``sample_every_s`` is the
    per-replica series cadence.
    """

    def __init__(
        self,
        registry: EngineRegistry,
        arrivals: Sequence[Arrival],
        policy: Optional[SLOPolicy] = None,
        *,
        admission: str = "slo",
        preemption: bool = True,
        time_model: Optional[ServiceTimeModel] = None,
        prompt_seed: int = 0,
        prompt_fn: Optional[Any] = None,
        routing: str = "score",
        detectors: Optional[List[Any]] = None,
        warmup_s: float = 0.25,
        sample_every_s: float = 0.05,
        probation_s: float = 1.0,
        max_rounds: int = 200_000,
    ):
        if routing not in ("score", "round_robin"):
            raise ValueError(
                f"routing must be 'score' or 'round_robin', "
                f"got {routing!r}"
            )
        if len(registry) == 0:
            raise ValueError("registry has no replicas")
        self.registry = registry
        self.routing = routing
        self.admission = admission
        self.detectors = list(detectors) if detectors else []
        self.warmup_s = float(warmup_s)
        self.sample_every_s = float(sample_every_s)
        self.probation_s = float(probation_s)
        self.max_rounds = int(max_rounds)
        self.tm = time_model or ServiceTimeModel()
        self._fes: Dict[str, _ReplicaFrontend] = {}
        self._samplers: Dict[str, SoakSampler] = {}
        self._next_sample: Dict[str, float] = {}
        self._eval_samples: Dict[str, int] = {}
        for h in registry.replicas():
            fe = _ReplicaFrontend(
                h.engine, [], policy,
                admission=admission, preemption=preemption,
                time_model=self.tm, prompt_seed=prompt_seed,
                prompt_fn=prompt_fn,
            )
            self._fes[h.rid] = fe
            self._bind_sampler(h)
        self._unrouted: List[Arrival] = sorted(
            arrivals, key=lambda a: (a.t, a.rid)
        )
        self._rids: set = set()
        for a in self._unrouted:
            if a.rid in self._rids:
                raise DuplicateRidError(
                    f"duplicate rid {a.rid!r} in arrival schedule"
                )
            self._rids.add(a.rid)
        self._owner: Dict[str, str] = {}   # logical rid -> replica rid
        self._rr = 0
        # fleet-level series (counters + per-replica tokens); recorded
        # only when policing is on, always with explicit fleet time
        self.fleet_store = TimeSeriesStore()
        self.history: List[Dict[str, Any]] = []
        self.migrations = 0
        self.rounds = 0
        self.t0: Optional[float] = None

    # -- external intake ---------------------------------------------------
    def submit(self, arrival: Arrival) -> None:
        """Inject an arrival mid-run; rid must be fleet-unique for all
        time (a migrated-away rid is still spent)."""
        if arrival.rid in self._rids:
            raise DuplicateRidError(
                f"duplicate rid {arrival.rid!r}: already known to the "
                f"fleet (owner: {self._owner.get(arrival.rid, 'unrouted')})"
            )
        self._rids.add(arrival.rid)
        self._unrouted.append(arrival)
        self._unrouted.sort(key=lambda a: (a.t, a.rid))

    # -- plumbing ----------------------------------------------------------
    def _bind_sampler(self, h: ReplicaHandle) -> None:
        """(Re)bind the per-replica sampler to the handle's CURRENT
        store/metrics — called at construction and after each restart
        (the old epoch's series must not leak into the new one)."""
        if self.detectors:
            self._samplers[h.rid] = SoakSampler(
                h.store, engine=h.engine, metrics=h.metrics,
                frontend=self._fes[h.rid],
            )
        self._next_sample.setdefault(h.rid, 0.0)
        self._eval_samples[h.rid] = 0

    def _fe_busy(self, fe: _ReplicaFrontend) -> bool:
        return bool(fe._pending or fe._backlog or fe._inflight)

    def _fe_runnable(self, fe: _ReplicaFrontend, rel: float) -> bool:
        """Work it could advance THIS round (a future-only pending
        arrival is not runnable — ticking it would jump its clock past
        busier replicas)."""
        return bool(
            fe._backlog or fe._inflight
            or (fe._pending and fe._pending[0].t <= rel + 1e-9)
        )

    def _event(self, t: float, event: str, rid: str,
               detail: str = "") -> None:
        self.history.append(
            {"t": float(t), "event": event, "replica": rid,
             "detail": detail}
        )

    # -- routing -----------------------------------------------------------
    def _score(self, h: ReplicaHandle) -> float:
        fe = self._fes[h.rid]
        occ = h.engine.page_occupancy()
        pressure = (len(fe._backlog) + len(h.engine._queue)
                    + len(fe._pending))
        return (occ["free_pages"] / max(occ["n_pages"], 1)
                + h.engine.free_slots / max(h.engine.slots, 1)
                - 0.25 * pressure)

    def _pick_target(
        self, exclude: Optional[str] = None
    ) -> Optional[str]:
        cands = [
            h for h in self.registry.replicas()
            if h.admitting and h.rid != exclude
        ]
        if not cands:
            return None
        if self.routing == "round_robin":
            h = cands[self._rr % len(cands)]
            self._rr += 1
            return h.rid
        # max score, ties to lowest rid (replicas() is rid-sorted and
        # max() keeps the first of equals)
        return max(cands, key=self._score).rid

    def _route_due(self, rel: float) -> None:
        while self._unrouted and self._unrouted[0].t <= rel + 1e-9:
            target = self._pick_target()
            if target is None:
                return   # whole fleet draining/probation; time must pass
            a = self._unrouted.pop(0)
            h = self.registry.get(target)
            self._fes[target].submit(a)
            self._owner[a.rid] = target
            h.routed += 1

    # -- drain / migrate / restart ----------------------------------------
    def _freeze_records(self, fe: _ReplicaFrontend,
                        req: _FleetReq) -> None:
        for e in req.passes:
            if e not in req.frozen_recs:
                r = fe.engine.reqlog.get(e)
                if r is not None:
                    req.frozen_recs[e] = r

    def _receive_migrant(self, target: str, req: _FleetReq) -> None:
        fe = self._fes[target]
        fe._reqs[req.a.rid] = req
        self._owner[req.a.rid] = target
        self.registry.get(target).routed += 1
        if fe.admission == "fifo":
            fe._submit_to_engine(req)
        else:
            fe._backlog.append(req)

    def _drain(self, h: ReplicaHandle, rel: float, why: str) -> None:
        fe = self._fes[h.rid]
        h.state = "draining"
        h.drains += 1
        h.engine.begin_drain()
        self._event(rel, "drain", h.rid, why)
        # 1. backlogged (never-submitted) work re-routes whole
        for req in list(fe._backlog):
            target = self._pick_target(exclude=h.rid)
            if target is None:
                break
            fe._backlog.remove(req)
            del fe._reqs[req.a.rid]
            self._receive_migrant(target, req)
            self.migrations += 1
            self._event(rel, "migrate", h.rid,
                        f"{req.a.rid} -> {target} (backlog)")
        # 2. decoding in-flight work preempt-migrates with its prefix;
        #    mid-prefill and engine-queued work finishes in place (no
        #    resumable prefix yet / submit order is engine-internal)
        prefilling = getattr(h.engine, "is_prefilling", None)
        for erid in sorted(fe._inflight):
            req = fe._inflight[erid]
            if erid not in h.engine._slot_req:
                continue
            if prefilling is not None and prefilling(erid):
                continue
            target = self._pick_target(exclude=h.rid)
            if target is None:
                break
            res = h.engine.preempt(
                erid, cause="preempt_migrate", by=f"fleet:{why}"
            )
            self._freeze_records(fe, req)
            req.record_migration(res)
            del fe._inflight[erid]
            del fe._reqs[req.a.rid]
            self._receive_migrant(target, req)
            self.migrations += 1
            self._event(rel, "migrate", h.rid,
                        f"{req.a.rid} -> {target} as "
                        f"{req.engine_rid()} (in-flight)")

    def _maybe_restart(self, h: ReplicaHandle, rel: float) -> None:
        fe = self._fes[h.rid]
        eng = h.engine
        if fe._inflight or eng._queue or eng.free_slots < eng.slots:
            return   # still emptying
        # the restart wipes the engine's request log — freeze every
        # surviving request's pass records first so merged serving rows
        # (and the LCY lint over them) outlive the epoch
        for req in fe._reqs.values():
            self._freeze_records(fe, req)
        self.registry.restart(h.rid)
        self._bind_sampler(h)
        h.state = "probation"
        h.probation_until = rel + self.probation_s
        self._event(rel, "restart", h.rid,
                    f"restart #{h.restarts}; probation until "
                    f"{h.probation_until:g}")

    def _police(self, rel: float) -> None:
        if not self.detectors:
            return
        for h in self.registry.replicas():
            if h.state == "probation":
                if (h.probation_until is not None
                        and rel >= h.probation_until - 1e-9):
                    h.state = "active"
                    h.probation_until = None
                    self._event(rel, "readmit", h.rid, "probation over")
                continue
            if h.state == "draining":
                self._maybe_restart(h, rel)
                continue
            sampler = self._samplers.get(h.rid)
            if sampler is None or sampler.samples <= self._eval_samples[h.rid]:
                continue   # nothing new to judge
            self._eval_samples[h.rid] = sampler.samples
            for d in self.detectors:
                f = d.evaluate(h.store, h.epoch_t0 + self.warmup_s)
                if f.severity == "error":
                    self._event(rel, "breach", h.rid,
                                f"{f.code} {f.message}")
                    self._drain(h, rel, f.code)
                    break

    # -- the fleet loop ----------------------------------------------------
    def run(self, *, deadline: Optional[float] = None) -> Dict[str, Any]:
        """Serve the schedule to completion (or ``deadline`` virtual
        seconds: unrouted arrivals drop, backlogs shed, in-flight work
        drains); returns :meth:`report`."""
        fes = [self._fes[r] for r in sorted(self._fes)]
        for fe in fes:
            if fe.t0 is None:
                fe.t0 = fe.clock()
        if self.t0 is None:
            self.t0 = min(fe.t0 for fe in fes)
        while self._unrouted or any(self._fe_busy(fe) for fe in fes):
            self.rounds += 1
            if self.rounds > self.max_rounds:
                raise RuntimeError(
                    f"fleet loop stalled after {self.max_rounds} "
                    f"rounds: {len(self._unrouted)} unrouted, "
                    f"{sum(self._fe_busy(fe) for fe in fes)} busy "
                    f"replica(s)"
                )
            rel = max(fe.clock() - fe.t0 for fe in fes)
            if deadline is not None and rel >= deadline:
                self._unrouted.clear()
                for fe in fes:
                    fe._shed_remaining()
                if not any(fe._inflight for fe in fes):
                    break
            self._route_due(rel)
            self._police(rel)
            ticked = False
            for fe in fes:
                if self._fe_runnable(fe, fe.clock() - fe.t0):
                    fe.ticks += 1
                    fe._tick()
                    ticked = True
            # barrier: pull every timeline up to the furthest one so
            # cross-replica timestamps stay comparable and idle
            # replicas keep receiving arrivals
            tmax = max(fe.clock() for fe in fes)
            for fe in fes:
                fe.clock.advance(tmax - fe.clock())
            if not ticked:
                # nothing runnable: jump to the next arrival (or just
                # forward, so probation/SLO windows can roll past)
                rel = tmax - self.t0
                nexts = [a.t for a in self._unrouted[:1]] + [
                    fe._pending[0].t for fe in fes if fe._pending
                ]
                dt = (max(min(nexts) - rel, self.tm.idle_s)
                      if nexts else self.tm.idle_s)
                for fe in fes:
                    fe.clock.advance(dt)
            self._sample(max(fe.clock() for fe in fes))
        return self.report()

    def _sample(self, now: float) -> None:
        if not self.detectors:
            return
        rel = now - (self.t0 or 0.0)
        for h in self.registry.replicas():
            if rel + 1e-9 >= self._next_sample[h.rid]:
                self._samplers[h.rid].sample(t=now)
        due = rel + 1e-9 >= min(self._next_sample.values())
        for rid in self._next_sample:
            if rel + 1e-9 >= self._next_sample[rid]:
                self._next_sample[rid] = rel + self.sample_every_s
        if due:
            rec = self.fleet_store.record
            for h in self.registry.replicas():
                tok = h.metrics.counter("decode.tokens_delivered").value
                rec(f"tokens.{h.rid}", tok, t=now, unit="tokens")
                rec(f"routed.{h.rid}", h.routed, t=now, unit="requests")
                rec(f"drained.{h.rid}", h.drains, t=now, unit="events")
                rec(f"restarted.{h.rid}", h.restarts, t=now,
                    unit="events")

    # -- merged views ------------------------------------------------------
    @property
    def results(self) -> Dict[str, np.ndarray]:
        out: Dict[str, np.ndarray] = {}
        for r in sorted(self._fes):
            out.update(self._fes[r].results)
        return out

    def request_rows(self) -> List[Dict[str, Any]]:
        """One row per logical request across the whole fleet, sorted
        by (t_submit, rid) — for N=1 this is exactly the standalone
        frontend's insertion order."""
        rows = [
            row for r in sorted(self._fes)
            for row in self._fes[r].request_rows()
        ]
        rows.sort(key=lambda r: (r["t_submit"], r["rid"]))
        return rows

    def lint(self, *, final: bool = True):
        """LCY lifecycle pass over the merged request rows (migrated
        rows included — their source-epoch records are frozen on the
        request)."""
        from ..analysis.lifecycle_pass import analyze_lifecycle

        return analyze_lifecycle(
            self.request_rows(), final=final, label="fleet"
        )

    def health_report(self):
        """Current :class:`~..obs.fleet.FleetHealthReport`: live
        detector verdicts per replica plus the full event history."""
        from ..obs.fleet import FleetHealthReport

        replicas: Dict[str, Dict[str, Any]] = {}
        for h in self.registry.replicas():
            warmup = h.epoch_t0 + self.warmup_s
            replicas[h.rid] = {
                "state": h.state,
                "restarts": h.restarts,
                "drains": h.drains,
                "warmup_s": warmup,
                "findings": [
                    d.evaluate(h.store, warmup) for d in self.detectors
                ],
            }
        return FleetHealthReport(replicas, history=self.history)

    def report(self) -> Dict[str, Any]:
        """Fleet serving summary: merged rows, fleet goodput, failover
        counters, per-replica reports (sans row duplication), health
        block, and the fleet series snapshot.  Idempotent."""
        fes = self._fes
        t_end = max(fes[r].clock() for r in fes)
        t0 = self.t0 if self.t0 is not None else t_end
        makespan = max(t_end - t0, 1e-12)
        rows = self.request_rows()
        per_replica: Dict[str, Any] = {}
        tokens_total = tokens_good = 0
        pages_leaked = 0
        for rid in sorted(fes):
            rep = fes[rid].report()
            rep.pop("requests")
            h = self.registry.get(rid)
            rep["replica"] = h.summary()
            per_replica[rid] = rep
            tokens_total += rep["tokens_total"]
            tokens_good += rep["tokens_good"]
            pages_leaked += rep["pages_leaked"]
        completed = sum(1 for r in rows if r["state"] == "retired")
        return {
            "n_replicas": len(fes),
            "routing": self.routing,
            "admission": self.admission,
            "detectors": [d.name for d in self.detectors],
            "n_requests": len(rows),
            "completed": completed,
            "shed": sum(1 for r in rows if r["state"] == "shed"),
            "migrations": self.migrations,
            "drains": sum(
                h.drains for h in self.registry.replicas()
            ),
            "restarts": sum(
                h.restarts for h in self.registry.replicas()
            ),
            "tokens_total": int(tokens_total),
            "tokens_good": int(tokens_good),
            "makespan_s": makespan,
            "goodput_tok_s": tokens_good / makespan,
            "throughput_tok_s": tokens_total / makespan,
            "pages_leaked": int(pages_leaked),
            "replicas": per_replica,
            "fleet_health": self.health_report().to_json(),
            "fleet_series": self.fleet_store.snapshot(),
            "requests": rows,
        }

    def digest(self) -> str:
        """sha256 over the merged serving log and every generated
        token — same payload shape as ``ServingFrontend.digest()``, so
        an N=1 detector-less fleet must reproduce the standalone digest
        bit for bit."""
        payload = json.dumps(
            {
                "requests": self.request_rows(),
                "tokens": {
                    rid: toks.tolist()
                    for rid, toks in sorted(self.results.items())
                },
            },
            sort_keys=True,
        ).encode("utf-8")
        return hashlib.sha256(payload).hexdigest()


__all__ = [
    "DuplicateRidError",
    "FleetFrontend",
]
