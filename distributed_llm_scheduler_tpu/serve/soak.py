"""Soak harness: duration-bounded serving under sustained load, with
health sampling and leak/degradation gating.

The serve bench answers "which policy wins at this offered load"; a
soak answers the operator's question — *does the engine stay healthy
over sustained traffic?*  This module runs the
:class:`~.frontend.ServingFrontend` against a seeded Poisson schedule
for ``--duration`` seconds (virtual by default, wall-clock with
``real_clock=True``), samples the engine's health surfaces every
``--sample-every`` seconds into a bounded
:class:`~..obs.timeseries.TimeSeriesStore`, and gates the run with the
:class:`~..obs.health.HealthMonitor` detector battery (HLT001–HLT006),
excluding the ``--warmup`` prefix where pool fill and compile-class
growth are expected.  A mid-soak breach triggers the flight recorder,
so the anomaly's events are dumped while they are still in the ring.

The virtual-time leg is fully deterministic: sampling only READS
(occupancy dicts, counter values, completed-row percentiles), never
advances the clock or touches engine state, so an instrumented soak is
bit-identical in served tokens to an un-instrumented same-seed run —
the property ``tests/test_soak.py`` asserts by digest.

The artifact is ``dls.soak/1``: config + clock mode, the embedded
timeseries snapshot (re-gateable offline via ``doctor --soak``), the
serving summary, steady-state goodput, per-detector slopes, and the
verdict.  The regression-gated metrics are flattened at top level:
``soak.goodput_tok_s`` (higher-better) and the ``soak.*_slope_*``
family (lower-better, clamped at 0.0 so the deterministic healthy leg
regresses on ANY positive slope at 0.0 tolerance).

Two test-only fault injectors live here because the detectors need
golden true-positive coverage without a real leak: :func:`
inject_page_leak` swaps the engine's pool for a delegating wrapper
that withholds one page from every N-th ``free`` (occupancy creeps —
HLT001), and :func:`inject_jit_churn` wraps ``step_segment`` to plant
a fresh synthetic compile-class key per segment (cache grows without
paying XLA compile time — HLT003).
"""

from __future__ import annotations

import json
from dataclasses import asdict, dataclass
from typing import Any, Dict, List, Optional

SCHEMA = "dls.soak/1"

#: detector name -> flattened regress metric (lower-better, 0-clamped)
SLOPE_METRICS = {
    "page_leak": "soak.page_leak_slope_pages_s",
    "hbm_growth": "soak.hbm_slope_bytes_s",
    "jit_cache_growth": "soak.jit_cache_slope_entries_s",
    "ttft_degradation": "soak.ttft_p95_slope_s_per_s",
    "queue_wait_degradation": "soak.queue_wait_p95_slope_s_per_s",
    "throughput_decay": "soak.throughput_decay_tok_s2",
}


@dataclass(frozen=True)
class SoakConfig:
    """One soak's knobs.  The engine geometry is the serve bench's
    tuned tiny-GPT2 scenario; the default load (12 req/s against ~26
    req/s of virtual service capacity) is comfortably STEADY — the
    healthy leg must not breach, so overload-induced degradation is
    opt-in via ``rate_rps``."""

    duration_s: float = 4.0
    sample_every_s: float = 0.1
    warmup_s: float = 1.0
    rate_rps: float = 12.0
    seed: int = 7
    admission: str = "slo"
    ttft_s: float = 0.3
    window_s: float = 0.2
    percentile: str = "p95"
    capacity: int = 512
    real_clock: bool = False
    #: paged attention impl baked into the engine's DAG (None = op auto)
    attention_impl: Optional[str] = None
    #: chunked-prefill chunk size (None = whole-prompt admission); the
    #: soak arrival mix is short prompts, so this mostly exercises the
    #: chunk scheduler's steady-state accounting under sustained load
    chunk_tokens: Optional[int] = None

    def validate(self) -> None:
        """Raises ``ValueError`` on a malformed config (CLI exit 2)."""
        if self.duration_s <= 0:
            raise ValueError(f"duration_s must be > 0, got {self.duration_s}")
        if self.sample_every_s <= 0:
            raise ValueError(
                f"sample_every_s must be > 0, got {self.sample_every_s}"
            )
        if not 0 <= self.warmup_s < self.duration_s:
            raise ValueError(
                f"warmup_s must be in [0, duration_s={self.duration_s:g}), "
                f"got {self.warmup_s}"
            )
        if self.rate_rps <= 0:
            raise ValueError(f"rate_rps must be > 0, got {self.rate_rps}")
        if self.admission not in ("fifo", "slo"):
            raise ValueError(
                f"admission must be 'fifo' or 'slo', got {self.admission!r}"
            )
        if self.capacity < 2:
            raise ValueError(f"capacity must be >= 2, got {self.capacity}")
        if self.attention_impl is not None:
            from ..ops.attention import resolve_attention_impl

            resolve_attention_impl(self.attention_impl, lambda _i: True)
        if self.chunk_tokens is not None and self.chunk_tokens < 1:
            raise ValueError(
                f"chunk_tokens must be >= 1, got {self.chunk_tokens}"
            )


# -- test-only fault injectors ---------------------------------------------
class _LeakyPool:
    """Delegating pool wrapper that withholds one page from every
    ``every``-th ``free`` — the withheld pages stay allocated forever,
    so ``used_pages`` creeps exactly the way a real retire-path leak
    would present."""

    def __init__(self, pool: Any, every: int):
        if every < 1:
            raise ValueError(f"every must be >= 1, got {every}")
        self._inner = pool
        self._every = int(every)
        self._frees = 0
        self.withheld: List[int] = []

    def free(self, pages: Any) -> None:
        self._frees += 1
        pages = list(pages)
        if pages and self._frees % self._every == 0:
            self.withheld.append(pages.pop())
        self._inner.free(pages)

    def __getattr__(self, name: str) -> Any:
        return getattr(self._inner, name)


def inject_page_leak(engine: Any, every: int = 2) -> Any:
    """Swap the engine's pool for a :class:`_LeakyPool` (the engine
    reads ``self.pool`` at runtime, so the swap takes effect
    immediately); returns the wrapper for inspection."""
    leaky = _LeakyPool(engine.pool, every)
    engine.pool = leaky
    return leaky


class _UnderflowPool:
    """Delegating pool wrapper that loses the reference taken by the
    first ``share`` — the classic refcount-underflow bug: the alias is
    handed out but never counted, so the LAST release frees a page
    other requests still read.  A buggy pool would also swallow the
    resulting release-of-freed-page errors, so the wrapper does too,
    page by page (otherwise the run crashes instead of being
    convicted)."""

    def __init__(self, pool: Any):
        self._inner = pool
        self.dropped: List[int] = []

    def share(self, pages: Any) -> None:
        pages = list(pages)
        self._inner.share(pages)
        if pages and not self.dropped:
            p = int(pages[0])
            self._inner._refs[p] -= 1
            self.dropped.append(p)

    def release_ref(self, pages: Any) -> None:
        for p in pages:
            try:
                self._inner.release_ref([p])
            except ValueError:
                pass

    def __getattr__(self, name: str) -> Any:
        return getattr(self._inner, name)


def inject_refcount_underflow(engine: Any) -> Any:
    """Swap the engine's pool for an :class:`_UnderflowPool`; returns
    the wrapper for inspection.  The page-lifetime prover convicts the
    bug statically: carried refcount witnesses disagree with the
    replayed counts and the premature free lands on a page with live
    references — PGL006 (plus PGL003 for the still-live owner)."""
    pool = _UnderflowPool(engine.pool)
    engine.pool = pool
    return pool


def inject_jit_churn(engine: Any) -> None:
    """Plant one fresh synthetic compile-class key per segment: the
    prefill cache grows exactly as if every wave hit a new (P, b)
    compile class, without paying XLA compile time.  Only ``len()`` of
    the cache is observed, so the None entries are inert."""
    orig = engine.step_segment
    n = [0]

    def step_segment() -> int:
        n[0] += 1
        engine._prefill_cache[("churn", n[0])] = None
        return orig()

    engine.step_segment = step_segment


# -- the soak run ----------------------------------------------------------
def run_soak(
    config: Optional[SoakConfig] = None,
    *,
    flight_dir: Optional[str] = None,
    instrument: bool = True,
    inject_leak_every: Optional[int] = None,
    inject_churn: bool = False,
    engine_factory: Optional[Any] = None,
) -> Dict[str, Any]:
    """Run one duration-bounded soak; returns the ``dls.soak/1`` dict.

    ``instrument=False`` runs the identical serving schedule with no
    sampler, flight recorder, or health evaluation — the bare leg of
    the bit-identity gate.  The injectors are test/CI-only and recorded
    in the artifact's ``injection`` block.

    ``engine_factory`` (test seam) supplies the engine instead of
    building one: called as ``engine_factory(clock=..., flight=...,
    attention_impl=...)`` and expected to hand back an engine already
    rebound to those surfaces (``PagedDecodeEngine.rebind_obs``) — how
    the test suite shares one compiled engine across every soak leg.
    """
    from ..obs import FlightRecorder, HealthMonitor, SoakSampler, \
        TimeSeriesStore
    from ..obs.slo import SLOPolicy
    from .frontend import ServiceTimeModel, ServingFrontend, VirtualClock
    from .loadgen import poisson_arrivals, schedule_digest

    cfg = config or SoakConfig()
    cfg.validate()

    clock = None if cfg.real_clock else VirtualClock()
    flight = (
        FlightRecorder(clock=clock) if instrument and flight_dir else None
    )
    from ..eval.serve_bench import SCENARIO, build_serve_engine

    if engine_factory is not None:
        eng = engine_factory(
            clock=clock, flight=flight, attention_impl=cfg.attention_impl
        )
        eng.chunk_tokens = cfg.chunk_tokens
    else:
        eng, _pool = build_serve_engine(
            slots=SCENARIO["slots"], page_size=SCENARIO["page_size"],
            n_pages=SCENARIO["n_pages"],
            pages_per_seq=SCENARIO["pages_per_seq"],
            seg_steps=SCENARIO["seg_steps"], clock=clock, flight=flight,
            attention_impl=cfg.attention_impl,
            chunk_tokens=cfg.chunk_tokens,
        )
    injection: Dict[str, Any] = {}
    if inject_leak_every is not None:
        inject_page_leak(eng, every=inject_leak_every)
        injection["page_leak_every"] = int(inject_leak_every)
    if inject_churn:
        inject_jit_churn(eng)
        injection["jit_churn"] = True

    # enough arrivals to span the whole window; the deadline sheds any
    # tail the generator overshot past the duration
    n_req = max(4, int(cfg.rate_rps * cfg.duration_s * 2) + 8)
    arrivals = poisson_arrivals(
        cfg.rate_rps, n_req, cfg.seed,
        prompt_lens=SCENARIO["prompt_lens"],
        max_new_tokens=SCENARIO["max_new_tokens"],
        priorities=SCENARIO["priorities"],
        priority_weights=SCENARIO["priority_weights"],
    )
    in_window = [a for a in arrivals if a.t < cfg.duration_s]
    arrivals = in_window if in_window else arrivals[:1]
    policy = SLOPolicy(
        ttft_s=cfg.ttft_s, window_s=cfg.window_s,
        percentile=cfg.percentile,
    )
    tm = (None if cfg.real_clock else ServiceTimeModel(
        wave_s=SCENARIO["wave_s"], segment_s=SCENARIO["segment_s"],
        idle_s=SCENARIO["idle_s"],
    ))
    fe = ServingFrontend(
        eng, arrivals, policy, admission=cfg.admission,
        time_model=tm,
    )

    monitor = HealthMonitor(warmup_s=cfg.warmup_s)
    store = TimeSeriesStore(capacity=cfg.capacity, clock=eng._clock)
    memprof = None
    if instrument:
        # record-only: kv-page alloc/free events fold onto the memory
        # timeline without touching any engine decision
        from ..obs import MemoryProfiler

        memprof = MemoryProfiler(clock=eng._clock)
        eng.memprof = memprof
    sampler = SoakSampler(store, engine=eng, metrics=eng.metrics,
                          memprof=memprof, frontend=fe)
    next_sample = [0.0]

    def on_tick(fe: Any) -> None:
        rel = fe.clock() - fe.t0
        if rel < next_sample[0] - 1e-9:
            return
        if rel > cfg.duration_s + 1e-9:
            # the post-deadline drain is not load: its falling
            # throughput and settling queues would read as decay
            return
        sampler.sample(t=rel)
        next_sample[0] = rel + cfg.sample_every_s
        # first mid-soak breach dumps the ring while the anomaly's
        # events are still in it; later samples skip (dump-once)
        if flight is not None and not flight.dumps and rel > cfg.warmup_s:
            flight.maybe_dump(flight_dir, health=monitor.evaluate(store))

    report = fe.run(
        deadline=cfg.duration_s,
        on_tick=on_tick if instrument else None,
    )
    health = monitor.evaluate(store) if instrument else None
    if (flight is not None and not flight.dumps
            and health is not None and health.exceeds()):
        flight.maybe_dump(flight_dir, health=health)

    # attribution runs over the full rows BEFORE they are stripped from
    # the serving block (the soak artifact keeps per-request rows out of
    # the summary; the bucket totals + aggressor ranking survive)
    from ..obs.interference import attribute_requests

    interference = attribute_requests(
        report["requests"], ttft_target_s=cfg.ttft_s
    ).summary(requests=False)
    serving = {k: v for k, v in report.items() if k != "requests"}
    art: Dict[str, Any] = {
        "schema": SCHEMA,
        "seed": cfg.seed,
        "config": asdict(cfg),
        "clock": "wall" if cfg.real_clock else "virtual",
        "injection": injection,
        "offered_load": {
            "rate_rps": cfg.rate_rps,
            "n_requests": len(arrivals),
            "schedule_digest": schedule_digest(arrivals),
        },
        "attention_impl": eng.summary()["attention_impl"],
        "serving": serving,
        "interference": interference,
        "digest": fe.digest(),
        "flight_dumps": list(flight.dumps) if flight else [],
    }
    if instrument:
        steady = _steady_state(store, cfg.warmup_s)
        art["timeseries"] = store.snapshot()
        art["health"] = health.to_json()
        art["steady_state"] = steady
        art["verdict"] = "breach" if health.exceeds() else "healthy"
        art["soak.goodput_tok_s"] = (
            steady["goodput_tok_s"]
            if steady["goodput_tok_s"] is not None
            else report["goodput_tok_s"]
        )
        slopes = health.slopes()
        for det, metric in SLOPE_METRICS.items():
            slope = slopes.get(det)
            if slope is None:
                art[metric] = 0.0
            elif det == "throughput_decay":
                # decay magnitude: only a FALLING rate is bad
                art[metric] = max(0.0, -slope)
            else:
                art[metric] = max(0.0, slope)
    return art


def _steady_state(store: Any, warmup_s: float) -> Dict[str, Any]:
    """Post-warmup goodput from the cumulative token series: tokens
    delivered after warmup over the time they took — the number a
    marketing-free soak summary leads with."""
    series = store._series.get("tok.delivered_total")
    if series is None:
        return {"goodput_tok_s": None, "span_s": 0.0, "tokens": 0}
    ts, vs = series.window(since_t=warmup_s)
    if len(ts) < 2 or ts[-1] <= ts[0]:
        return {"goodput_tok_s": None, "span_s": 0.0, "tokens": 0}
    span = ts[-1] - ts[0]
    tokens = vs[-1] - vs[0]
    return {
        "goodput_tok_s": tokens / span,
        "span_s": span,
        "tokens": int(tokens),
    }


# -- artifact schema -------------------------------------------------------
_TOP_REQUIRED = (
    "schema", "seed", "config", "clock", "injection", "offered_load",
    "attention_impl", "serving", "digest", "timeseries", "health",
    "steady_state", "verdict", "soak.goodput_tok_s",
)


def validate_soak_artifact(art: Any) -> List[str]:
    """Structural check of a ``dls.soak/1`` artifact; returns
    human-readable problems (empty list == valid)."""
    from ..obs.timeseries import validate_timeseries

    errs: List[str] = []
    if not isinstance(art, dict):
        return [f"artifact is {type(art).__name__}, not dict"]
    if art.get("schema") != SCHEMA:
        errs.append(f"schema is {art.get('schema')!r}, want {SCHEMA!r}")
    for f in _TOP_REQUIRED:
        if f not in art:
            errs.append(f"missing top-level field {f!r}")
    if art.get("clock") not in ("virtual", "wall"):
        errs.append(f"clock is {art.get('clock')!r}, want virtual|wall")
    if art.get("verdict") not in ("healthy", "breach"):
        errs.append(
            f"verdict is {art.get('verdict')!r}, want healthy|breach"
        )
    ts = art.get("timeseries")
    if ts is not None:
        errs.extend(validate_timeseries(ts))
    health = art.get("health")
    if health is not None:
        if not isinstance(health, dict) or "findings" not in health:
            errs.append("health block missing findings")
        else:
            for i, f in enumerate(health["findings"]):
                if not isinstance(f, dict):
                    errs.append(f"health.findings[{i}] not a dict")
                    continue
                for k in ("code", "severity", "detector", "series",
                          "slope", "threshold", "message"):
                    if k not in f:
                        errs.append(f"health.findings[{i}] missing {k!r}")
    for metric in ("soak.goodput_tok_s",) + tuple(SLOPE_METRICS.values()):
        v = art.get(metric)
        if metric in art and not isinstance(v, (int, float)):
            errs.append(f"{metric} is not numeric")
    for metric in SLOPE_METRICS.values():
        if metric not in art:
            errs.append(f"missing slope metric {metric!r}")
    return errs


def load_soak_artifact(path: str) -> Dict[str, Any]:
    """Load + validate a ``dls.soak/1`` artifact; raises ``ValueError``
    on malformed content (the CLIs map that to exit 2)."""
    with open(path) as f:
        obj = json.load(f)
    errs = validate_soak_artifact(obj)
    if errs:
        raise ValueError(
            f"malformed soak artifact {path}: " + "; ".join(errs[:5])
        )
    return obj


__all__ = [
    "SCHEMA",
    "SLOPE_METRICS",
    "SoakConfig",
    "inject_jit_churn",
    "inject_page_leak",
    "inject_refcount_underflow",
    "load_soak_artifact",
    "run_soak",
    "validate_soak_artifact",
]
