"""distributed_llm_scheduler_tpu — TPU-native memory-constrained DAG
scheduling and execution for LLMs.

A brand-new framework with the capability surface of the reference
``2alaaa/distributed-llm-scheduler`` (DAG extraction → memory-constrained
scheduling → execution → evaluation/visualization), rebuilt TPU-first:

* tasks are XLA-compilable computations with real byte sizes;
* nodes are TPU cores on a ``jax.sharding.Mesh`` under HBM budgets;
* transfers are ``jax.device_put`` / ICI collectives with measured cost;
* the reference's simulated executor survives as a pluggable CPU-runnable
  backend next to the real device backend;
* plus native-scale subsystems the reference lacks: sharded training
  (DP/TP/SP/EP, remat, scanned layers), ring + Ulysses attention for long
  context, multi-slice ICI/DCN topologies, Pallas kernels, pretrained
  checkpoint ingestion, checkpointing, config/CLI, and a native C++
  scheduling engine with bit-identical policies.

See SURVEY.md for the layer map and parity notes.
"""

from .utils.config import env_str as _env_str

# DLS_PLATFORM=cpu|tpu pins the JAX platform before the first backend touch
# (e.g. to keep CLI/dev runs on the host when no accelerator is reachable);
# DLS_FORCE_CPU=1 is shorthand for DLS_PLATFORM=cpu.  Must run before
# anything resolves a backend; importing this package first is enough.
_plat = _env_str("DLS_PLATFORM") or (
    "cpu" if _env_str("DLS_FORCE_CPU") else None
)
if _plat:
    import jax as _jax

    _jax.config.update("jax_platforms", _plat)

from .core.graph import (
    DEFAULT_PARAM_GB,
    GraphValidationError,
    Task,
    TaskGraph,
    TaskStatus,
)
from .core.cluster import Cluster, DeviceState, estimate_cluster_memory_needed
from .core.fusion import fuse_linear_chains
from .core.schedule import Schedule, TaskTiming
from .core.validate import ValidationReport, validate_schedule
from .backends.sim import LinkModel, SimulatedBackend, TieredLinkModel
from .sched.base import BaseScheduler
from .sched.elastic import remainder_graph, reschedule, surviving_work
from .sched.heft import HEFTScheduler
from .sched.pack import GroupPackScheduler
from .sched.pipeline import PipelineStageScheduler
from .sched.policies import (
    ALL_SCHEDULERS,
    CriticalPathScheduler,
    DFSScheduler,
    GreedyScheduler,
    MRUScheduler,
    RoundRobinScheduler,
    get_scheduler,
)
from .sched.refine import RefinedPackScheduler
from .utils.quantize import QParam, quantize_dag

__version__ = "0.1.0"

__all__ = [
    "DEFAULT_PARAM_GB",
    "GraphValidationError",
    "Task",
    "TaskGraph",
    "TaskStatus",
    "Cluster",
    "DeviceState",
    "estimate_cluster_memory_needed",
    "Schedule",
    "TaskTiming",
    "fuse_linear_chains",
    "ValidationReport",
    "validate_schedule",
    "BaseScheduler",
    "ALL_SCHEDULERS",
    "RoundRobinScheduler",
    "DFSScheduler",
    "GreedyScheduler",
    "CriticalPathScheduler",
    "MRUScheduler",
    "HEFTScheduler",
    "PipelineStageScheduler",
    "GroupPackScheduler",
    "RefinedPackScheduler",
    "get_scheduler",
    "LinkModel",
    "TieredLinkModel",
    "SimulatedBackend",
    "QParam",
    "quantize_dag",
    "surviving_work",
    "remainder_graph",
    "reschedule",
]
