"""Fused LayerNorm / RMSNorm as Pallas TPU kernels.

Normalizations are pure HBM-bandwidth ops (read x, write x-shaped output);
the win is one pass over memory with the mean/variance/scale math fused on
the VPU, float32 accumulation regardless of the model dtype.  XLA usually
fuses these well on its own — the kernels exist so the DAG frontend's
per-op task functions have a hand-tuned path on TPU (and to demonstrate
the VMEM row-block pattern the guide recommends for elementwise+reduce).

Grid: 1-D over row blocks of the flattened (rows, D) input; each step
normalizes ``block_rows`` rows held in VMEM.  ``layer_norm``/``rms_norm``
dispatch the same way :func:`..ops.attention.mha` does: Pallas on TPU,
interpret mode for CPU tests, plain-XLA fallback otherwise.

Reference parity: the reference's DAG has ln1/ln2/final-ln tasks as cost
constants only (reference ``test_gpt2.py:63-74,101-110,151-157``); these
are their executable TPU forms.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ..utils.config import env_str


def _ln_kernel(x_ref, g_ref, b_ref, o_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)
    mean = x.mean(axis=-1, keepdims=True)
    xc = x - mean
    var = (xc * xc).mean(axis=-1, keepdims=True)
    y = xc * jax.lax.rsqrt(var + eps)
    g = g_ref[...].astype(jnp.float32)
    b = b_ref[...].astype(jnp.float32)
    o_ref[...] = (y * g + b).astype(o_ref.dtype)


def _rms_kernel(x_ref, g_ref, o_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)
    scale = jax.lax.rsqrt((x * x).mean(axis=-1, keepdims=True) + eps)
    g = g_ref[...].astype(jnp.float32)
    o_ref[...] = (x * scale * g).astype(o_ref.dtype)


def _pick_rows(rows: int, cap: int = 256) -> int:
    block = 1
    while block < cap and rows % (block * 2) == 0:
        block *= 2
    return block


@functools.partial(jax.jit, static_argnames=("eps", "interpret"))
def _ln_pallas(x2d, g, b, *, eps, interpret):
    rows, D = x2d.shape
    block = _pick_rows(rows)
    return pl.pallas_call(
        functools.partial(_ln_kernel, eps=eps),
        out_shape=jax.ShapeDtypeStruct((rows, D), x2d.dtype),
        grid=(rows // block,),
        in_specs=[
            pl.BlockSpec((block, D), lambda i: (i, 0)),
            pl.BlockSpec((D,), lambda i: (0,)),
            pl.BlockSpec((D,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((block, D), lambda i: (i, 0)),
        interpret=interpret,
    )(x2d, g, b)


@functools.partial(jax.jit, static_argnames=("eps", "interpret"))
def _rms_pallas(x2d, g, *, eps, interpret):
    rows, D = x2d.shape
    block = _pick_rows(rows)
    return pl.pallas_call(
        functools.partial(_rms_kernel, eps=eps),
        out_shape=jax.ShapeDtypeStruct((rows, D), x2d.dtype),
        grid=(rows // block,),
        in_specs=[
            pl.BlockSpec((block, D), lambda i: (i, 0)),
            pl.BlockSpec((D,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((block, D), lambda i: (i, 0)),
        interpret=interpret,
    )(x2d, g)


def _auto_impl() -> str:
    forced = env_str("DLS_TPU_NORM_IMPL")
    if forced:
        return forced
    try:
        platform = jax.devices()[0].platform
    except RuntimeError:  # pragma: no cover
        platform = "cpu"
    return "pallas" if platform == "tpu" else "xla"


def layer_norm(
    x: jax.Array,
    g: jax.Array,
    b: jax.Array,
    eps: float = 1e-5,
    impl: Optional[str] = None,
) -> jax.Array:
    """LayerNorm over the last axis of x (any leading shape)."""
    if impl is None:
        impl = _auto_impl()
    if impl == "xla" or x.shape[-1] != g.shape[-1] or x.size == 0:
        xf = x.astype(jnp.float32)
        mean = xf.mean(-1, keepdims=True)
        var = xf.var(-1, keepdims=True)
        out = (xf - mean) * jax.lax.rsqrt(var + eps)
        return (out * g.astype(jnp.float32) + b.astype(jnp.float32)).astype(x.dtype)
    lead = x.shape[:-1]
    out = _ln_pallas(
        x.reshape(-1, x.shape[-1]), g, b,
        eps=eps, interpret=(impl == "pallas_interpret"),
    )
    return out.reshape(*lead, x.shape[-1])


def rms_norm(
    x: jax.Array,
    g: jax.Array,
    eps: float = 1e-5,
    impl: Optional[str] = None,
) -> jax.Array:
    """RMSNorm over the last axis of x (any leading shape)."""
    if impl is None:
        impl = _auto_impl()
    if impl == "xla" or x.shape[-1] != g.shape[-1] or x.size == 0:
        xf = x.astype(jnp.float32)
        scale = jax.lax.rsqrt((xf * xf).mean(-1, keepdims=True) + eps)
        return (xf * scale * g.astype(jnp.float32)).astype(x.dtype)
    lead = x.shape[:-1]
    out = _rms_pallas(
        x.reshape(-1, x.shape[-1]), g,
        eps=eps, interpret=(impl == "pallas_interpret"),
    )
    return out.reshape(*lead, x.shape[-1])
