"""Fused attention kernels (Pallas TPU): causal flash prefill + ragged paged decode.

The hot op of every model family (SURVEY.md §7 "hot parts"): materializing
the (T, T) score matrix costs O(T^2) HBM traffic, which at long context is
the bandwidth bottleneck.  The flash kernel streams K/V blocks through VMEM
with an online-softmax accumulator (running max / denominator), so scores
never leave VMEM and HBM traffic is O(T · d).  The same math drives the ring
attention loop in :mod:`..parallel.ring_attention` — there blocks rotate
across chips over ICI; here they stream within one chip's HBM→VMEM.

Layout: grid (batch·heads, Q blocks); per grid step one Q block lives in
VMEM while the kernel walks K/V blocks with ``lax.fori_loop``.  Causality
prunes the loop: Q block ``i`` only visits K/V blocks ``0..i`` (the trip
count is a traced value — Pallas lowers it to a hardware loop, no
recompilation per block).  Scores/accumulators are float32 for stability;
inputs/outputs stay in the model dtype (bfloat16 on TPU hits the MXU).

The decode-side sibling is the ragged paged kernel (``_paged_kernel``):
grid (slot, logical page), where each grid step's K/V block is selected by
the request's page table through a scalar-prefetch index map — one physical
page DMAs HBM→VMEM per step, the gathered (S, M, Hkv, hd) view is never
materialized, and the same online-softmax carry runs across a slot's pages
(ragged tail and trash pages masked to −inf).  Both paged impls sit behind
:func:`paged_decode_attention`'s ``impl`` switch with the same dispatch
rules as :func:`mha` (:func:`resolve_attention_impl`).

``mha`` is the public entry: it dispatches to the kernel on TPU (or
interpreter mode for CPU tests) and to a plain-XLA reference elsewhere, so
models can call it unconditionally.

The reference never executes attention (its "attention" is a DAG node with
a cost constant, reference ``test_gpt2.py:75-90``); this file exists
because the rebuild executes for real.
"""

from __future__ import annotations

import functools
import math
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ..utils.config import env_str

try:  # pallas TPU backend is absent on some CPU-only builds
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

_NEG_INF = float(jnp.finfo(jnp.float32).min)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, sm_scale, block, causal):
    """One (batch·head, q-block) grid step.

    q_ref/o_ref: (1, block, hd) VMEM; k_ref/v_ref: (1, T, hd) VMEM.
    """
    q_blk = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * sm_scale  # (block, hd)
    hd = q.shape[-1]
    T = k_ref.shape[1]
    n_blocks = T // block

    q_start = q_blk * block
    rows = jax.lax.broadcasted_iota(jnp.int32, (block, block), 0) + q_start

    def body(kv_i, carry):
        acc, m, l = carry
        kv_start = kv_i * block
        k = k_ref[0, pl.ds(kv_start, block), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(kv_start, block), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # (block, block)
        if causal:
            cols = jax.lax.broadcasted_iota(jnp.int32, (block, block), 1) + kv_start
            s = jnp.where(cols <= rows, s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        l = l * alpha + p.sum(axis=-1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return acc, m_new, l

    acc0 = jnp.zeros((block, hd), jnp.float32)
    m0 = jnp.full((block, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block, 1), jnp.float32)
    # causal: Q block i needs K/V blocks 0..i only (diagonal always has the
    # self-position, so no row is ever fully masked and l stays positive)
    trip = jnp.where(causal, q_blk + 1, n_blocks) if causal else n_blocks
    acc, _, l = jax.lax.fori_loop(0, trip, body, (acc0, m0, l0))
    o_ref[0] = (acc / l).astype(o_ref.dtype)


def _pick_block(T: int) -> int:
    """Largest power-of-two divisor of T capped at 512 (MXU-friendly)."""
    block = 1
    while block < 512 and T % (block * 2) == 0:
        block *= 2
    return block


@functools.lru_cache(maxsize=None)
def _flash_with_vjp(causal: bool, sm_scale: float, block: int, interpret: bool):
    """Differentiable flash forward: pallas_call has no autodiff rule, so
    training-step DAGs (``frontend/train_dag.py``) would crash under
    ``jax.vjp`` exactly on TPU where the kernel is selected.  The backward
    recomputes attention through the XLA reference path (flash-style
    rematerialization: residuals are just q/k/v, no O(T^2) tensor is saved
    between fwd and bwd).  Cached per static config so jit sees one stable
    function object per shape family (no retrace churn)."""

    @jax.custom_vjp
    def f(q, k, v):
        return _flash_mha(
            q, k, v, causal=causal, sm_scale=sm_scale, block=block,
            interpret=interpret,
        )

    def f_fwd(q, k, v):
        out = _flash_mha(
            q, k, v, causal=causal, sm_scale=sm_scale, block=block,
            interpret=interpret,
        )
        return out, (q, k, v)

    def f_bwd(res, g):
        q, k, v = res
        _, vjp = jax.vjp(
            lambda q_, k_, v_: reference_mha(
                q_, k_, v_, causal=causal, sm_scale=sm_scale
            ),
            q, k, v,
        )
        return vjp(g)

    f.defvjp(f_fwd, f_bwd)
    return f


@functools.partial(
    jax.jit, static_argnames=("causal", "sm_scale", "block", "interpret")
)
def _flash_mha(q, k, v, *, causal, sm_scale, block, interpret):
    B, H, T, hd = q.shape
    flat = lambda t: t.reshape(B * H, T, hd)
    grid = (B * H, T // block)
    out = pl.pallas_call(
        functools.partial(
            _flash_kernel, sm_scale=sm_scale, block=block, causal=causal
        ),
        out_shape=jax.ShapeDtypeStruct((B * H, T, hd), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block, hd), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, T, hd), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, T, hd), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block, hd), lambda b, i: (b, i, 0)),
        interpret=interpret,
    )(flat(q), flat(k), flat(v))
    return out.reshape(B, H, T, hd)


def reference_mha(q, k, v, causal: bool = True, sm_scale: Optional[float] = None):
    """Plain-XLA oracle: same contract as :func:`mha`, O(T^2) memory."""
    hd = q.shape[-1]
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(hd)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        T = q.shape[-2]
        i = jax.lax.broadcasted_iota(jnp.int32, (T, T), 0)
        j = jax.lax.broadcasted_iota(jnp.int32, (T, T), 1)
        scores = jnp.where(j <= i, scores, jnp.finfo(scores.dtype).min)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(v.dtype), v)


def _auto_impl() -> str:
    forced = env_str("DLS_TPU_ATTENTION_IMPL")
    if forced:
        return forced
    try:
        platform = jax.devices()[0].platform
    except RuntimeError:  # pragma: no cover - backend init failure
        platform = "cpu"
    return "pallas" if (platform == "tpu" and _HAS_PLTPU) else "xla"


def pallas_supported(q_shape, block_min: int = 8) -> bool:
    """Kernel preconditions: T divisible by a tile-worthy block."""
    T = q_shape[-2]
    return T >= 2 * block_min and _pick_block(T) >= block_min


def resolve_attention_impl(impl: Optional[str], supported) -> str:
    """The ONE dispatch rule shared by the dense (:func:`mha`) and paged
    (:func:`paged_decode_attention`) entry points, so the two paths cannot
    drift on platform/eligibility behavior.

    ``None`` / ``"auto"`` resolve via :func:`_auto_impl` (the
    ``DLS_TPU_ATTENTION_IMPL`` env override, else pallas-on-TPU / xla
    elsewhere).  A pallas impl the shape does not qualify for silently
    downgrades to ``"xla"`` — ``supported`` is a callable taking the
    resolved impl name (``"pallas"`` / ``"pallas_interpret"``), so callers
    can keep compiled-mode tiling constraints out of the interpret path.
    Anything outside the three known impls raises ``ValueError``.
    """
    if impl is None or impl == "auto":
        impl = _auto_impl()
        if impl == "auto":  # env var literally forced "auto": no loop
            impl = "xla"
    if impl not in ("xla", "pallas", "pallas_interpret"):
        raise ValueError(f"unknown attention impl {impl!r}")
    if impl != "xla" and not supported(impl):
        return "xla"
    return impl


def paged_kernel_constraints(
    page_size: int,
    head_dim: int,
    n_kv_heads: int,
    n_q_heads: Optional[int] = None,
    dtype: Any = jnp.float32,
    q_tokens: Optional[int] = None,
) -> list:
    """Violated tiling/layout constraints for the COMPILED ragged paged
    kernel — empty list means the geometry is kernel-eligible.

    One source of truth for three consumers: the ``impl="auto"``/
    ``"pallas"`` dispatch (silent gather fallback when non-empty), the
    DEC005 analysis warning (which quotes these strings verbatim), and the
    docs.  The constraints are the VMEM block shapes the kernel asks for:
    each grid step loads one ``(page_size, n_kv_heads, head_dim)`` page,
    so ``page_size`` must fill the dtype's sublane tile and ``head_dim``
    must pack the 8-row sublane dimension of the score/accumulator tiles
    (interpret mode has no tiling and skips this check entirely).
    """
    sublane = {2: 16, 1: 32}.get(jnp.dtype(dtype).itemsize, 8)
    out = []
    if page_size % sublane:
        out.append(
            f"page_size {page_size} is not a multiple of the {sublane}-row "
            f"sublane tile for {jnp.dtype(dtype).name} K/V page blocks"
        )
    if head_dim % 8:
        out.append(
            f"head_dim {head_dim} is not a multiple of the 8-lane sublane "
            "tile of the per-page score/accumulator blocks"
        )
    if n_kv_heads < 1:
        out.append(f"n_kv_heads {n_kv_heads} must be >= 1")
    if n_q_heads is not None and n_q_heads % max(n_kv_heads, 1):
        out.append(
            f"n_q_heads {n_q_heads} is not a multiple of n_kv_heads "
            f"{n_kv_heads} (GQA group mapping)"
        )
    if q_tokens is not None:
        if q_tokens < 1:
            out.append(f"q_tokens {q_tokens} must be >= 1")
        elif q_tokens > 1 and q_tokens % sublane:
            out.append(
                f"q_tokens {q_tokens} is not a multiple of the "
                f"{sublane}-row sublane tile of the ragged multi-token "
                "query block"
            )
    return out


def paged_pallas_supported(
    q_shape, pool_shape, interpret: bool = False
) -> bool:
    """Eligibility of the ragged paged kernel for this call.

    Structural preconditions (every mode): query heads an exact multiple
    of KV heads, matching head_dim, at least one query token (Tn == 1 is
    the decode step; Tn > 1 is a ragged prefill chunk with per-slot
    ``q_lens``).  Compiled mode additionally requires the
    :func:`paged_kernel_constraints` tiling rules; interpret mode (CPU
    parity tests) has no tiling constraints.
    """
    S, Hq, Tn, hd = q_shape
    n_pages, page_size, Hkv, pool_hd = pool_shape
    if Tn < 1 or Hkv < 1 or Hq % Hkv or hd != pool_hd:
        return False
    if not _HAS_PLTPU:  # PrefetchScalarGridSpec lives in pltpu
        return False
    if interpret:
        return True
    return not paged_kernel_constraints(
        page_size, hd, Hkv, n_q_heads=Hq,
        q_tokens=Tn if Tn > 1 else None,
    )


def mha(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    impl: Optional[str] = None,
) -> jax.Array:
    """Multi-head attention on (B, H, T, hd) tensors.

    impl: "pallas" (TPU kernel), "pallas_interpret" (CPU-debuggable kernel),
    "xla" (reference einsum path), or None/"auto" = auto (pallas on TPU
    when the shape qualifies, xla otherwise).
    """
    impl = resolve_attention_impl(
        impl, lambda _i: pallas_supported(q.shape)
    )
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(q.shape[-1])
    if impl == "pallas" or impl == "pallas_interpret":
        return _flash_with_vjp(
            causal,
            float(scale),
            _pick_block(q.shape[-2]),
            impl == "pallas_interpret",
        )(q, k, v)
    return reference_mha(q, k, v, causal=causal, sm_scale=scale)


def _paged_kernel(
    pt_ref, len_ref, q_ref, k_ref, v_ref, kn_ref, vn_ref, o_ref,
    acc_ref, m_ref, l_ref, *, sm_scale, page_size, groups, has_new,
):
    """One (slot, logical page) grid step of the ragged paged kernel.

    The grid walks slot-major / page-minor, so the online-softmax carry
    (``acc``/``m``/``l`` VMEM scratch, persistent across grid steps) is
    initialized at a slot's first page and folded into ``o_ref`` at its
    last.  ``k_ref``/``v_ref`` hold ONE physical page — the BlockSpec
    index map reads the scalar-prefetched page table, so the DMA engine
    fetches exactly ``page_table[s, j]`` and the gathered view never
    exists in HBM.  Masking: global row position ``j*page_size + r`` must
    be ``<= lengths[s]`` — the same comparison that masks the ragged tail
    also zeroes every trash-page row (a live sequence's length never
    reaches into an unallocated page).  ``has_new`` statically compiles
    in the write-then-attend insert: the page containing position
    ``lengths[s]`` gets this step's K/V row substituted before the scores
    (clamped to the last row like the gather path's
    ``dynamic_update_slice``).
    """
    s_idx = pl.program_id(0)
    j = pl.program_id(1)
    n_j = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    L = len_ref[s_idx]
    hd = q_ref.shape[-1]
    Hkv = k_ref.shape[2]
    q = (q_ref[0].astype(jnp.float32) * sm_scale).reshape(Hkv, groups, hd)
    k = k_ref[0].astype(jnp.float32)  # (page_size, Hkv, hd)
    v = v_ref[0].astype(jnp.float32)
    if has_new:
        # insert this step's row at position L (clamped to the capacity's
        # last row — dynamic_update_slice semantics, gather-path parity)
        capacity = n_j * page_size
        ins = jnp.minimum(L, capacity - 1) - j * page_size
        sel = (
            jax.lax.broadcasted_iota(jnp.int32, (page_size, 1, 1), 0) == ins
        )
        k = jnp.where(sel, kn_ref[0].astype(jnp.float32)[None], k)
        v = jnp.where(sel, vn_ref[0].astype(jnp.float32)[None], v)
    # scores (Hkv, page_size, G): K @ q, the gather path's orientation
    s = jax.lax.dot_general(
        k, q, (((2,), (2,)), ((1,), (0,))),
        preferred_element_type=jnp.float32,
    )
    pos = (
        jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) + j * page_size
    )
    s = jnp.where(pos <= L, s, _NEG_INF)
    # position 0 is unmasked for every slot, so after page 0 the running
    # max is a real (finite) score and the exp() arguments stay finite
    m_prev = m_ref[...]                       # (Hkv, G)
    m_new = jnp.maximum(m_prev, s.max(axis=1))
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new[:, None, :])        # (Hkv, page_size, G)
    l_ref[...] = l_ref[...] * alpha + p.sum(axis=1)
    acc_ref[...] = acc_ref[...] * alpha[:, :, None] + jax.lax.dot_general(
        p, v, (((1,), (0,)), ((0,), (1,))),
        preferred_element_type=jnp.float32,
    )  # (Hkv, G, hd)
    m_ref[...] = m_new

    @pl.when(j == n_j - 1)
    def _finalize():
        out = acc_ref[...] / l_ref[...][:, :, None]
        o_ref[0] = out.reshape(Hkv * groups, hd).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("sm_scale", "has_new", "interpret")
)
def _paged_flash(
    q, k_pool, v_pool, page_table, lengths, k_new, v_new, *,
    sm_scale, has_new, interpret,
):
    """Fused ragged paged attention: page-table-directed block loads.

    Grid (slots, pages_per_seq); the page table and lengths ride as
    scalar-prefetch operands so the K/V BlockSpec index maps can point
    each grid step's DMA at the slot's physical page.  Per grid step the
    only HBM traffic is one (page_size, Hkv, hd) page per pool — the
    dense gather's (S, M, Hkv, hd) intermediate never exists.
    """
    S, Hq, _, hd = q.shape
    _, page_size, Hkv, _ = k_pool.shape
    G = Hq // Hkv
    ppseq = page_table.shape[1]
    q3 = q.reshape(S, Hq, hd)
    if has_new:
        kn = k_new.reshape(S, Hkv, hd)
        vn = v_new.reshape(S, Hkv, hd)
    else:  # zero placeholders keep the arity static; kernel never reads
        kn = jnp.zeros((S, Hkv, hd), k_pool.dtype)
        vn = jnp.zeros((S, Hkv, hd), v_pool.dtype)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(S, ppseq),
        in_specs=[
            pl.BlockSpec((1, Hq, hd), lambda s, j, pt, ln: (s, 0, 0)),
            pl.BlockSpec(
                (1, page_size, Hkv, hd),
                lambda s, j, pt, ln: (pt[s, j], 0, 0, 0),
            ),
            pl.BlockSpec(
                (1, page_size, Hkv, hd),
                lambda s, j, pt, ln: (pt[s, j], 0, 0, 0),
            ),
            pl.BlockSpec((1, Hkv, hd), lambda s, j, pt, ln: (s, 0, 0)),
            pl.BlockSpec((1, Hkv, hd), lambda s, j, pt, ln: (s, 0, 0)),
        ],
        out_specs=pl.BlockSpec(
            (1, Hq, hd), lambda s, j, pt, ln: (s, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((Hkv, G, hd), jnp.float32),
            pltpu.VMEM((Hkv, G), jnp.float32),
            pltpu.VMEM((Hkv, G), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _paged_kernel, sm_scale=sm_scale, page_size=page_size,
            groups=G, has_new=has_new,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, Hq, hd), q.dtype),
        interpret=interpret,
    )(
        page_table.astype(jnp.int32), lengths.astype(jnp.int32),
        q3, k_pool, v_pool, kn, vn,
    )
    return out.reshape(S, Hq, 1, hd)


def _paged_ragged_kernel(
    pt_ref, len_ref, ql_ref, q_ref, k_ref, v_ref, o_ref,
    acc_ref, m_ref, l_ref, *, sm_scale, page_size, groups, q_tokens,
):
    """One (slot, logical page) grid step of the ragged MULTI-token-q
    paged kernel — the prefill-chunk shape of :func:`_paged_kernel`.

    The query block carries ``q_tokens`` rows per slot; per-slot
    ``q_lens`` rides scalar prefetch next to the page table and lengths.
    Query row ``t`` of slot ``s`` sits at absolute position
    ``lengths[s] + t`` and attends KV positions ``<= lengths[s] + t``
    (causal within the chunk, full history before it) — write-then-
    attend: the chunk's own K/V rows are already scattered into the
    pool.  Rows at or past ``q_lens[s]`` are padding; their mask is
    clamped to the last real row so every output row stays finite and
    trash-page-invariant (the caller discards them).  The online-softmax
    carry is the single-token kernel's with the (groups) axis widened to
    (groups * q_tokens).
    """
    s_idx = pl.program_id(0)
    j = pl.program_id(1)
    n_j = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    L = len_ref[s_idx]
    QL = ql_ref[s_idx]
    hd = q_ref.shape[-1]
    Hkv = k_ref.shape[2]
    # (Hq, Tn, hd) -> (Hkv, G*Tn, hd): adjacent-axis merge, column
    # c = g*q_tokens + t, so t recovers as c % q_tokens
    q = (q_ref[0].astype(jnp.float32) * sm_scale).reshape(
        Hkv, groups * q_tokens, hd
    )
    k = k_ref[0].astype(jnp.float32)  # (page_size, Hkv, hd)
    v = v_ref[0].astype(jnp.float32)
    # scores (Hkv, page_size, G*Tn): K @ q, the gather path's orientation
    s = jax.lax.dot_general(
        k, q, (((2,), (2,)), ((1,), (0,))),
        preferred_element_type=jnp.float32,
    )
    pos = (
        jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) + j * page_size
    )
    t = jax.lax.broadcasted_iota(jnp.int32, s.shape, 2) % q_tokens
    t_eff = jnp.clip(t, 0, jnp.maximum(QL - 1, 0))
    s = jnp.where(pos <= L + t_eff, s, _NEG_INF)
    # position 0 is unmasked for every row (L + t_eff >= 0), so the
    # running max turns finite at page 0 and the exp() args stay finite
    m_prev = m_ref[...]                       # (Hkv, G*Tn)
    m_new = jnp.maximum(m_prev, s.max(axis=1))
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new[:, None, :])        # (Hkv, page_size, G*Tn)
    l_ref[...] = l_ref[...] * alpha + p.sum(axis=1)
    acc_ref[...] = acc_ref[...] * alpha[:, :, None] + jax.lax.dot_general(
        p, v, (((1,), (0,)), ((0,), (1,))),
        preferred_element_type=jnp.float32,
    )  # (Hkv, G*Tn, hd)
    m_ref[...] = m_new

    @pl.when(j == n_j - 1)
    def _finalize():
        out = acc_ref[...] / l_ref[...][:, :, None]
        o_ref[0] = out.reshape(
            Hkv * groups, q_tokens, hd
        ).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("sm_scale", "interpret"))
def _paged_flash_ragged(
    q, k_pool, v_pool, page_table, lengths, q_lens, *,
    sm_scale, interpret,
):
    """Fused ragged multi-token-q paged attention (prefill chunks).

    Same (slots, pages_per_seq) grid and page-table-directed block loads
    as :func:`_paged_flash`, with a (1, Hq, Tn, hd) query block per slot
    and per-slot ``q_lens`` as a third scalar-prefetch operand.  No
    in-kernel insert: chunk K/V rows are scattered into the pool before
    the call (write-then-attend at chunk granularity).
    """
    S, Hq, Tn, hd = q.shape
    _, page_size, Hkv, _ = k_pool.shape
    G = Hq // Hkv
    ppseq = page_table.shape[1]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(S, ppseq),
        in_specs=[
            pl.BlockSpec(
                (1, Hq, Tn, hd), lambda s, j, pt, ln, ql: (s, 0, 0, 0)
            ),
            pl.BlockSpec(
                (1, page_size, Hkv, hd),
                lambda s, j, pt, ln, ql: (pt[s, j], 0, 0, 0),
            ),
            pl.BlockSpec(
                (1, page_size, Hkv, hd),
                lambda s, j, pt, ln, ql: (pt[s, j], 0, 0, 0),
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, Hq, Tn, hd), lambda s, j, pt, ln, ql: (s, 0, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((Hkv, G * Tn, hd), jnp.float32),
            pltpu.VMEM((Hkv, G * Tn), jnp.float32),
            pltpu.VMEM((Hkv, G * Tn), jnp.float32),
        ],
    )
    return pl.pallas_call(
        functools.partial(
            _paged_ragged_kernel, sm_scale=sm_scale,
            page_size=page_size, groups=G, q_tokens=Tn,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, Hq, Tn, hd), q.dtype),
        interpret=interpret,
    )(
        page_table.astype(jnp.int32), lengths.astype(jnp.int32),
        q_lens.astype(jnp.int32), q, k_pool, v_pool,
    )


def _gather_chunk_attention(
    q, k_pool, v_pool, page_table, lengths, q_lens, scale
):
    """XLA gather path for ragged multi-token q — the op-level parity
    reference for :func:`_paged_flash_ragged`.

    Identical orientation and masking to the single-token gather path
    with the (G) column axis widened to (G*Tn) and the length mask
    shifted per query row: row ``t`` attends positions ``<=
    lengths[s] + t`` (padding rows clamp to the last real row, matching
    the kernel).  Chunk rows must already be resident in the pools.
    """
    from ..models.kv_pages import gather_kv_flat  # lazy: models imports ops

    S, Hq, Tn, hd = q.shape
    k_view = gather_kv_flat(k_pool, page_table)  # (S, M, Hkv, hd)
    v_view = gather_kv_flat(v_pool, page_table)
    Hkv = k_view.shape[2]
    G = Hq // Hkv
    qg = (q * scale).reshape(S, Hkv, G * Tn, hd)
    s = jax.lax.dot_general(
        k_view.astype(qg.dtype), qg,
        (((3,), (3,)), ((0, 2), (0, 1))),
        preferred_element_type=jnp.float32,
    )  # (S, Hkv, M, G*Tn)
    rows = jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
    t = jax.lax.broadcasted_iota(jnp.int32, s.shape, 3) % Tn
    ql = q_lens.reshape(S, 1, 1, 1).astype(jnp.int32)
    t_eff = jnp.clip(t, 0, jnp.maximum(ql - 1, 0))
    valid = rows <= lengths.reshape(S, 1, 1, 1) + t_eff
    s = jnp.where(valid, s, jnp.finfo(s.dtype).min)
    m = s.max(axis=2, keepdims=True)
    p = jnp.exp(s - m)
    l = p.sum(axis=2, keepdims=True)
    out_dtype = q.dtype
    o = jax.lax.dot_general(
        p.astype(out_dtype), v_view.astype(out_dtype),
        (((2,), (1,)), ((0, 1), (0, 2))),
        preferred_element_type=jnp.float32,
    )  # (S, Hkv, G*Tn, hd)
    return (o / l.reshape(S, Hkv, G * Tn, 1)).astype(out_dtype).reshape(
        S, Hq, Tn, hd
    )


def paged_decode_attention(
    q: jax.Array,
    k_pool: jax.Array,
    v_pool: jax.Array,
    page_table: jax.Array,
    lengths: jax.Array,
    sm_scale: Optional[float] = None,
    k_new: Optional[jax.Array] = None,
    v_new: Optional[jax.Array] = None,
    impl: Optional[str] = None,
    q_lens: Optional[jax.Array] = None,
) -> jax.Array:
    """Ragged paged single-token attention: gather-by-page-table,
    per-sequence length-masked, static shapes throughout.

    ``q`` (S, Hq, 1, hd) — one new token per batch slot; ``k_pool`` /
    ``v_pool`` (P, page_size, Hkv, hd) — the shared page pools
    (:mod:`..models.kv_pages`); ``page_table`` (S, pages_per_seq) int32
    — slot ``s``'s logical page ``j`` lives in physical page
    ``page_table[s, j]``; ``lengths`` (S,) int32 — tokens already cached
    per slot.  ``k_new``/``v_new`` (S, Hkv, 1, hd), when given, are this
    step's rows, inserted into the gathered view at ``lengths[s]``
    BEFORE the scores — the write-then-attend order of the dense path
    (:func:`...models.decode.cached_attention`), so outputs are
    bit-identical to a dense cache of the same per-sequence capacity.
    Slot ``s`` attends positions ``m <= lengths[s]``; rows past a
    sequence's last allocated page gather the trash page and are masked
    by the same comparison.

    The math after the gather is the dense decode path's MXU-natural
    orientation (``_decode_attention_natural``: K @ q, scores
    (S, Hkv, M, G), softmax over M) — deliberately, for two reasons:
    scores are elementwise identical to the dense cache's (the parity
    the mixed-length benchmark gates on), and the (pages, page_size)
    leading axes of the pools are exactly the block structure the Pallas
    ragged-paged-attention kernel (:func:`_paged_flash`) consumes.

    ``impl`` mirrors :func:`mha`: ``"xla"`` is the gather path above,
    ``"pallas"`` the fused kernel (page-table-directed VMEM block loads,
    online softmax — no gathered intermediate), ``"pallas_interpret"``
    the same kernel through the Pallas interpreter (CPU parity tests),
    and ``None``/``"auto"`` picks the kernel on TPU when the geometry
    passes :func:`paged_kernel_constraints`, the gather path otherwise
    (the silent-fallback seam DEC005 warns about).  Kernel outputs are
    allclose — not bitwise — to the gather path (page-blocked online
    softmax associates its reductions differently), which keeps greedy
    argmax tokens identical at engine scale (pinned by the parity gate).

    ``q`` with Tn > 1 is a ragged prefill chunk: per-slot ``q_lens``
    (S,) int32 gives the number of REAL query rows (rows past it are
    padding, returned finite but meaningless), query row ``t`` of slot
    ``s`` sits at absolute position ``lengths[s] + t`` and attends
    causally, and the chunk's K/V rows must already be scattered into
    the pools (``k_new`` is not accepted — write-then-attend is at
    chunk granularity, not per-row).
    """
    S, Hq, Tn, hd = q.shape
    if Tn != 1:
        if q_lens is None:
            raise ValueError(
                f"multi-token q (Tn={Tn}) requires per-slot q_lens"
            )
        if k_new is not None:
            raise ValueError(
                "multi-token q takes no k_new/v_new: scatter the chunk "
                "into the pools first (write-then-attend at chunk "
                "granularity)"
            )
        impl = resolve_attention_impl(
            impl,
            lambda i: paged_pallas_supported(
                q.shape, k_pool.shape, interpret=(i == "pallas_interpret")
            ),
        )
        scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(hd)
        if impl in ("pallas", "pallas_interpret"):
            return _paged_flash_ragged(
                q, k_pool, v_pool, page_table, lengths, q_lens,
                sm_scale=float(scale),
                interpret=impl == "pallas_interpret",
            )
        return _gather_chunk_attention(
            q, k_pool, v_pool, page_table, lengths, q_lens, scale
        )
    impl = resolve_attention_impl(
        impl,
        lambda i: paged_pallas_supported(
            q.shape, k_pool.shape, interpret=(i == "pallas_interpret")
        ),
    )
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(hd)
    if impl in ("pallas", "pallas_interpret"):
        return _paged_flash(
            q, k_pool, v_pool, page_table, lengths, k_new, v_new,
            sm_scale=float(scale), has_new=k_new is not None,
            interpret=impl == "pallas_interpret",
        )
    from ..models.kv_pages import gather_kv_flat  # lazy: models imports ops

    # flat (S, M, Hkv, hd) gather: a free reshape of the page gather's
    # output, where the dense (S, Hkv, M, hd) orientation would pay a
    # materializing transpose of the whole working set every step.  The
    # dot_general batch dims below are permuted to match — contraction
    # and softmax reductions see the SAME operands in the SAME logical
    # order, so outputs stay bit-identical to the dense-orientation math
    # (pinned by the parity tests).
    k_view = gather_kv_flat(k_pool, page_table)  # (S, M, Hkv, hd)
    v_view = gather_kv_flat(v_pool, page_table)
    M, Hkv = k_view.shape[1], k_view.shape[2]
    G = Hq // Hkv

    if k_new is not None:
        insert = jax.vmap(
            lambda buf, row, at: jax.lax.dynamic_update_slice(
                buf, row.transpose(1, 0, 2).astype(buf.dtype),
                (at, jnp.int32(0), jnp.int32(0)),
            )
        )
        # (S, Hkv, 1, hd) rows land at per-sequence position lengths[s]
        k_view = insert(k_view, k_new, lengths)
        v_view = insert(v_view, v_new, lengths)

    qg = (q * scale).reshape(S, Hkv, G, hd)
    s = jax.lax.dot_general(
        k_view.astype(qg.dtype), qg,
        (((3,), (3,)), ((0, 2), (0, 1))),
        preferred_element_type=jnp.float32,
    )  # (S, Hkv, M, G)
    rows = jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
    valid = rows <= lengths.reshape(S, 1, 1, 1)
    s = jnp.where(valid, s, jnp.finfo(s.dtype).min)
    m = s.max(axis=2, keepdims=True)
    p = jnp.exp(s - m)
    l = p.sum(axis=2, keepdims=True)
    out_dtype = q.dtype
    o = jax.lax.dot_general(
        p.astype(out_dtype), v_view.astype(out_dtype),
        (((2,), (1,)), ((0, 1), (0, 2))),
        preferred_element_type=jnp.float32,
    )
    return (o / l.reshape(S, Hkv, G, 1)).astype(out_dtype).reshape(
        S, Hq, 1, hd
    )


def gqa_mha(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    impl: Optional[str] = None,
) -> jax.Array:
    """Grouped-query attention: q (B, Hq, T, hd), k/v (B, Hkv, T, hd) with
    Hq a multiple of Hkv.  KV heads are broadcast across their query group
    (an O(T·d) repeat — negligible next to the O(T^2) attention savings)."""
    Hq, Hkv = q.shape[1], k.shape[1]
    if Hq != Hkv:
        group = Hq // Hkv
        k = jnp.repeat(k, group, axis=1)
        v = jnp.repeat(v, group, axis=1)
    return mha(q, k, v, causal=causal, sm_scale=sm_scale, impl=impl)
