"""Fused causal flash attention as a Pallas TPU kernel.

The hot op of every model family (SURVEY.md §7 "hot parts"): materializing
the (T, T) score matrix costs O(T^2) HBM traffic, which at long context is
the bandwidth bottleneck.  This kernel streams K/V blocks through VMEM with
an online-softmax accumulator (running max / denominator), so scores never
leave VMEM and HBM traffic is O(T · d).  The same math drives the ring
attention loop in :mod:`..parallel.ring_attention` — there blocks rotate
across chips over ICI; here they stream within one chip's HBM→VMEM.

Layout: grid (batch·heads, Q blocks); per grid step one Q block lives in
VMEM while the kernel walks K/V blocks with ``lax.fori_loop``.  Causality
prunes the loop: Q block ``i`` only visits K/V blocks ``0..i`` (the trip
count is a traced value — Pallas lowers it to a hardware loop, no
recompilation per block).  Scores/accumulators are float32 for stability;
inputs/outputs stay in the model dtype (bfloat16 on TPU hits the MXU).

``mha`` is the public entry: it dispatches to the kernel on TPU (or
interpreter mode for CPU tests) and to a plain-XLA reference elsewhere, so
models can call it unconditionally.

The reference never executes attention (its "attention" is a DAG node with
a cost constant, reference ``test_gpt2.py:75-90``); this file exists
because the rebuild executes for real.
"""

from __future__ import annotations

import functools
import math
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend is absent on some CPU-only builds
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

_NEG_INF = float(jnp.finfo(jnp.float32).min)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, sm_scale, block, causal):
    """One (batch·head, q-block) grid step.

    q_ref/o_ref: (1, block, hd) VMEM; k_ref/v_ref: (1, T, hd) VMEM.
    """
    q_blk = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * sm_scale  # (block, hd)
    hd = q.shape[-1]
    T = k_ref.shape[1]
    n_blocks = T // block

    q_start = q_blk * block
    rows = jax.lax.broadcasted_iota(jnp.int32, (block, block), 0) + q_start

    def body(kv_i, carry):
        acc, m, l = carry
        kv_start = kv_i * block
        k = k_ref[0, pl.ds(kv_start, block), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(kv_start, block), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # (block, block)
        if causal:
            cols = jax.lax.broadcasted_iota(jnp.int32, (block, block), 1) + kv_start
            s = jnp.where(cols <= rows, s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        l = l * alpha + p.sum(axis=-1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return acc, m_new, l

    acc0 = jnp.zeros((block, hd), jnp.float32)
    m0 = jnp.full((block, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block, 1), jnp.float32)
    # causal: Q block i needs K/V blocks 0..i only (diagonal always has the
    # self-position, so no row is ever fully masked and l stays positive)
    trip = jnp.where(causal, q_blk + 1, n_blocks) if causal else n_blocks
    acc, _, l = jax.lax.fori_loop(0, trip, body, (acc0, m0, l0))
    o_ref[0] = (acc / l).astype(o_ref.dtype)


def _pick_block(T: int) -> int:
    """Largest power-of-two divisor of T capped at 512 (MXU-friendly)."""
    block = 1
    while block < 512 and T % (block * 2) == 0:
        block *= 2
    return block


@functools.lru_cache(maxsize=None)
def _flash_with_vjp(causal: bool, sm_scale: float, block: int, interpret: bool):
    """Differentiable flash forward: pallas_call has no autodiff rule, so
    training-step DAGs (``frontend/train_dag.py``) would crash under
    ``jax.vjp`` exactly on TPU where the kernel is selected.  The backward
    recomputes attention through the XLA reference path (flash-style
    rematerialization: residuals are just q/k/v, no O(T^2) tensor is saved
    between fwd and bwd).  Cached per static config so jit sees one stable
    function object per shape family (no retrace churn)."""

    @jax.custom_vjp
    def f(q, k, v):
        return _flash_mha(
            q, k, v, causal=causal, sm_scale=sm_scale, block=block,
            interpret=interpret,
        )

    def f_fwd(q, k, v):
        out = _flash_mha(
            q, k, v, causal=causal, sm_scale=sm_scale, block=block,
            interpret=interpret,
        )
        return out, (q, k, v)

    def f_bwd(res, g):
        q, k, v = res
        _, vjp = jax.vjp(
            lambda q_, k_, v_: reference_mha(
                q_, k_, v_, causal=causal, sm_scale=sm_scale
            ),
            q, k, v,
        )
        return vjp(g)

    f.defvjp(f_fwd, f_bwd)
    return f


@functools.partial(
    jax.jit, static_argnames=("causal", "sm_scale", "block", "interpret")
)
def _flash_mha(q, k, v, *, causal, sm_scale, block, interpret):
    B, H, T, hd = q.shape
    flat = lambda t: t.reshape(B * H, T, hd)
    grid = (B * H, T // block)
    out = pl.pallas_call(
        functools.partial(
            _flash_kernel, sm_scale=sm_scale, block=block, causal=causal
        ),
        out_shape=jax.ShapeDtypeStruct((B * H, T, hd), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block, hd), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, T, hd), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, T, hd), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block, hd), lambda b, i: (b, i, 0)),
        interpret=interpret,
    )(flat(q), flat(k), flat(v))
    return out.reshape(B, H, T, hd)


def reference_mha(q, k, v, causal: bool = True, sm_scale: Optional[float] = None):
    """Plain-XLA oracle: same contract as :func:`mha`, O(T^2) memory."""
    hd = q.shape[-1]
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(hd)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        T = q.shape[-2]
        i = jax.lax.broadcasted_iota(jnp.int32, (T, T), 0)
        j = jax.lax.broadcasted_iota(jnp.int32, (T, T), 1)
        scores = jnp.where(j <= i, scores, jnp.finfo(scores.dtype).min)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(v.dtype), v)


def _auto_impl() -> str:
    forced = os.environ.get("DLS_TPU_ATTENTION_IMPL")
    if forced:
        return forced
    try:
        platform = jax.devices()[0].platform
    except RuntimeError:  # pragma: no cover - backend init failure
        platform = "cpu"
    return "pallas" if (platform == "tpu" and _HAS_PLTPU) else "xla"


def pallas_supported(q_shape, block_min: int = 8) -> bool:
    """Kernel preconditions: T divisible by a tile-worthy block."""
    T = q_shape[-2]
    return T >= 2 * block_min and _pick_block(T) >= block_min


def mha(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    impl: Optional[str] = None,
) -> jax.Array:
    """Multi-head attention on (B, H, T, hd) tensors.

    impl: "pallas" (TPU kernel), "pallas_interpret" (CPU-debuggable kernel),
    "xla" (reference einsum path), or None = auto (pallas on TPU when the
    shape qualifies, xla otherwise).
    """
    if impl is None:
        impl = _auto_impl()
    if impl.startswith("pallas") and not pallas_supported(q.shape):
        impl = "xla"
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(q.shape[-1])
    if impl == "pallas" or impl == "pallas_interpret":
        return _flash_with_vjp(
            causal,
            float(scale),
            _pick_block(q.shape[-2]),
            impl == "pallas_interpret",
        )(q, k, v)
    if impl == "xla":
        return reference_mha(q, k, v, causal=causal, sm_scale=scale)
    raise ValueError(f"unknown attention impl {impl!r}")


def paged_decode_attention(
    q: jax.Array,
    k_pool: jax.Array,
    v_pool: jax.Array,
    page_table: jax.Array,
    lengths: jax.Array,
    sm_scale: Optional[float] = None,
    k_new: Optional[jax.Array] = None,
    v_new: Optional[jax.Array] = None,
    impl: Optional[str] = None,
) -> jax.Array:
    """Ragged paged single-token attention: gather-by-page-table,
    per-sequence length-masked, static shapes throughout.

    ``q`` (S, Hq, 1, hd) — one new token per batch slot; ``k_pool`` /
    ``v_pool`` (P, page_size, Hkv, hd) — the shared page pools
    (:mod:`..models.kv_pages`); ``page_table`` (S, pages_per_seq) int32
    — slot ``s``'s logical page ``j`` lives in physical page
    ``page_table[s, j]``; ``lengths`` (S,) int32 — tokens already cached
    per slot.  ``k_new``/``v_new`` (S, Hkv, 1, hd), when given, are this
    step's rows, inserted into the gathered view at ``lengths[s]``
    BEFORE the scores — the write-then-attend order of the dense path
    (:func:`...models.decode.cached_attention`), so outputs are
    bit-identical to a dense cache of the same per-sequence capacity.
    Slot ``s`` attends positions ``m <= lengths[s]``; rows past a
    sequence's last allocated page gather the trash page and are masked
    by the same comparison.

    The math after the gather is the dense decode path's MXU-natural
    orientation (``_decode_attention_natural``: K @ q, scores
    (S, Hkv, M, G), softmax over M) — deliberately, for two reasons:
    scores are elementwise identical to the dense cache's (the parity
    the mixed-length benchmark gates on), and the (pages, page_size)
    leading axes of the pools are exactly the block structure a Pallas
    ragged-paged-attention kernel consumes, so the kernel drops in
    behind ``impl="pallas"`` without changing this contract.  Until
    then ``impl`` accepts "xla" (default); "pallas" raises.
    """
    if impl is None:
        impl = "xla"
    if impl != "xla":
        raise NotImplementedError(
            f"paged attention impl {impl!r}: only the XLA path exists; "
            "the Pallas ragged kernel slots in behind this signature "
            "(pools are already page-blocked on the leading axes)"
        )
    from ..models.kv_pages import gather_kv_flat  # lazy: models imports ops

    S, Hq, Tn, hd = q.shape
    if Tn != 1:
        raise ValueError(f"paged decode attention is single-token, Tn={Tn}")
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(hd)
    # flat (S, M, Hkv, hd) gather: a free reshape of the page gather's
    # output, where the dense (S, Hkv, M, hd) orientation would pay a
    # materializing transpose of the whole working set every step.  The
    # dot_general batch dims below are permuted to match — contraction
    # and softmax reductions see the SAME operands in the SAME logical
    # order, so outputs stay bit-identical to the dense-orientation math
    # (pinned by the parity tests).
    k_view = gather_kv_flat(k_pool, page_table)  # (S, M, Hkv, hd)
    v_view = gather_kv_flat(v_pool, page_table)
    M, Hkv = k_view.shape[1], k_view.shape[2]
    G = Hq // Hkv

    if k_new is not None:
        insert = jax.vmap(
            lambda buf, row, at: jax.lax.dynamic_update_slice(
                buf, row.transpose(1, 0, 2).astype(buf.dtype),
                (at, jnp.int32(0), jnp.int32(0)),
            )
        )
        # (S, Hkv, 1, hd) rows land at per-sequence position lengths[s]
        k_view = insert(k_view, k_new, lengths)
        v_view = insert(v_view, v_new, lengths)

    qg = (q * scale).reshape(S, Hkv, G, hd)
    s = jax.lax.dot_general(
        k_view.astype(qg.dtype), qg,
        (((3,), (3,)), ((0, 2), (0, 1))),
        preferred_element_type=jnp.float32,
    )  # (S, Hkv, M, G)
    rows = jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
    valid = rows <= lengths.reshape(S, 1, 1, 1)
    s = jnp.where(valid, s, jnp.finfo(s.dtype).min)
    m = s.max(axis=2, keepdims=True)
    p = jnp.exp(s - m)
    l = p.sum(axis=2, keepdims=True)
    out_dtype = q.dtype
    o = jax.lax.dot_general(
        p.astype(out_dtype), v_view.astype(out_dtype),
        (((2,), (1,)), ((0, 1), (0, 2))),
        preferred_element_type=jnp.float32,
    )
    return (o / l.reshape(S, Hkv, G, 1)).astype(out_dtype).reshape(
        S, Hq, 1, hd
    )


def gqa_mha(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    impl: Optional[str] = None,
) -> jax.Array:
    """Grouped-query attention: q (B, Hq, T, hd), k/v (B, Hkv, T, hd) with
    Hq a multiple of Hkv.  KV heads are broadcast across their query group
    (an O(T·d) repeat — negligible next to the O(T^2) attention savings)."""
    Hq, Hkv = q.shape[1], k.shape[1]
    if Hq != Hkv:
        group = Hq // Hkv
        k = jnp.repeat(k, group, axis=1)
        v = jnp.repeat(v, group, axis=1)
    return mha(q, k, v, causal=causal, sm_scale=sm_scale, impl=impl)
