"""Pallas TPU kernels for the hot ops, with XLA fallbacks.

``mha``/``gqa_mha`` (fused flash attention) dispatch per platform:
hand-written Pallas kernels on TPU, interpreter mode for CPU debugging,
plain-XLA reference paths everywhere else.  The model families' attention
routes through these unconditionally (``models/gpt2.py``,
``models/llama.py`` — Mixtral shares Llama's); differentiation works via a
custom_vjp (rematerializing backward).  ``layer_norm``/``rms_norm`` are
standalone fused-norm kernels with the same dispatch scheme — the models
keep their plain-jnp norms so XLA can fuse them into neighbors inside the
whole-model forward; the kernels are for task-granular/standalone use.
Tests pin ``impl="pallas_interpret"`` vs ``impl="xla"`` to check kernel
numerics on CPU.  Env overrides: ``DLS_TPU_ATTENTION_IMPL`` /
``DLS_TPU_NORM_IMPL``.
"""

from .attention import gqa_mha, mha, pallas_supported, reference_mha
from .norms import layer_norm, rms_norm

__all__ = [
    "mha",
    "gqa_mha",
    "reference_mha",
    "pallas_supported",
    "layer_norm",
    "rms_norm",
]
