"""DAG and schedule visualization.

Capability parity with the reference's ``visu.py`` (components #21-23 in
SURVEY.md §2): simple and detailed DAG renderings (node color = memory,
size = compute) and per-node Gantt charts — but drawing from the real
framework types (one ``Task`` definition, not ``visu.py``'s duplicate
dataclasses, SURVEY.md §1 wart) and from *timestamped* schedules produced
by a backend, not hand-written ones (the reference's Gantt scales durations
by node speed because it has no real timings, ``visu.py:206-248``).

Non-interactive by default: figures save to files (Agg).  The reference's
interactive menu loop (``visu.py:294-339``) is replaced by an opt-in
``show=True`` / CLI ``--show``, which opens the rendered figure in a
window on display-capable machines after saving it.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

from ..core.graph import TaskGraph
from ..core.schedule import Schedule


def _savefig(fig, path: str) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    fig.savefig(path, dpi=120)


def _plt(show: bool = False):
    import matplotlib

    if not show:
        matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    return plt


def _layout(graph: TaskGraph) -> Dict[str, tuple]:
    """Layered layout from DAG depths (deterministic; no networkx spring
    randomness): x = depth, y = slot within depth."""
    depths = graph.depths()
    by_depth: Dict[int, list] = {}
    for tid in graph.topo_order:
        by_depth.setdefault(depths[tid], []).append(tid)
    pos = {}
    for d, tids in by_depth.items():
        n = len(tids)
        for i, tid in enumerate(tids):
            pos[tid] = (d, (i - (n - 1) / 2.0))
    return pos


def visualize_dag(
    graph: TaskGraph,
    path: str = "dag.png",
    detailed: bool = False,
    max_labels: int = 60,
    show: bool = False,
) -> str:
    """Render the DAG.  ``detailed`` colors nodes by activation memory and
    sizes them by compute time (reference visu.py:122-204)."""
    plt = _plt(show)
    pos = _layout(graph)
    fig, ax = plt.subplots(
        figsize=(max(8, len(set(x for x, _ in pos.values())) * 0.9), 8)
    )

    for t in graph:
        x1, y1 = pos[t.task_id]
        for d in t.dependencies:
            x0, y0 = pos[d]
            ax.annotate(
                "",
                xy=(x1, y1),
                xytext=(x0, y0),
                arrowprops=dict(arrowstyle="->", color="0.7", lw=0.7),
            )

    xs = [pos[t.task_id][0] for t in graph]
    ys = [pos[t.task_id][1] for t in graph]
    if detailed:
        mems = [t.memory_required for t in graph]
        comps = [t.compute_time for t in graph]
        cmax = max(comps) or 1.0
        sizes = [60 + 400 * c / cmax for c in comps]
        sc = ax.scatter(xs, ys, s=sizes, c=mems, cmap="viridis", zorder=3)
        fig.colorbar(sc, ax=ax, label="activation memory (GB)")
    else:
        ax.scatter(xs, ys, s=80, c="#4C72B0", zorder=3)

    if len(graph) <= max_labels:
        for t in graph:
            x, y = pos[t.task_id]
            ax.annotate(t.task_id, (x, y), fontsize=6,
                        xytext=(0, 6), textcoords="offset points", ha="center")

    ax.set_title(f"{graph.name}: {len(graph)} tasks")
    ax.set_xlabel("DAG depth")
    ax.set_yticks([])
    fig.tight_layout()
    _savefig(fig, path)
    if show:
        plt.show()
    plt.close(fig)
    return path


def visualize_schedule(
    schedule: Schedule,
    path: str = "schedule.png",
    title: Optional[str] = None,
    show: bool = False,
) -> str:
    """Gantt chart from a timestamped schedule (run a backend first to fill
    ``schedule.timings``; reference analog visu.py:206-248)."""
    if not schedule.timings:
        raise ValueError(
            "schedule has no timings; execute it on a backend first "
            "(SimulatedBackend.execute or DeviceBackend profile mode)"
        )
    plt = _plt(show)
    nodes = sorted(schedule.per_node)
    ypos = {n: i for i, n in enumerate(nodes)}
    cmap = plt.get_cmap("tab20")

    fig, ax = plt.subplots(figsize=(12, 1.2 + 0.6 * len(nodes)))
    groups = {}
    for i, t in enumerate(sorted(schedule.timings.values(), key=lambda t: t.start)):
        grp = t.task_id.rsplit("_", 1)[0]
        color = groups.setdefault(grp, cmap(len(groups) % 20))
        ax.barh(
            ypos[t.node_id],
            t.duration,
            left=t.start,
            height=0.6,
            color=color,
            edgecolor="white",
            linewidth=0.3,
        )
    ax.set_yticks(range(len(nodes)))
    ax.set_yticklabels(nodes)
    ax.set_xlabel("time (s)")
    ax.set_title(title or f"{schedule.policy}: makespan {schedule.makespan:.4f}s")
    fig.tight_layout()
    _savefig(fig, path)
    if show:
        plt.show()
    plt.close(fig)
    return path


def visualize_trace_gantt(
    trace: object,
    path: str = "trace_gantt.png",
    title: Optional[str] = None,
    show: bool = False,
) -> str:
    """Gantt chart from an exported Chrome/Perfetto trace JSON (path or
    loaded dict) — the *measured* timeline a ``DLS_TRACE=1`` run wrote,
    rather than the simulated schedule.  Device task/launch spans render
    exactly like :func:`visualize_schedule` bars; spans on the measured
    critical path (``obs/attribution.py``) get a highlight edge."""
    from ..obs.attribution import attribute_trace

    att = attribute_trace(trace)
    if not att.critical_path and not att.per_device:
        raise ValueError(
            "trace has no device spans; export one from a traced run "
            "(DLS_TRACE=1 or the `trace` CLI) first"
        )
    plt = _plt(show)
    # re-read the spans the attribution walked: per-device rows come
    # from its per_device keys, bars from the exported X events
    import json as _json
    import os as _os

    obj = trace
    if isinstance(trace, (str, _os.PathLike)):
        with open(trace) as f:
            obj = _json.load(f)
    events = obj.get("traceEvents", [])
    track_of = {
        ev.get("tid"): ev.get("args", {}).get("name", "")
        for ev in events
        if ev.get("ph") == "M" and ev.get("name") == "thread_name"
    }
    nodes = sorted(att.per_device)
    ypos = {n: i for i, n in enumerate(nodes)}
    on_path = {(s.name, s.track) for s in att.critical_path}
    cmap = plt.get_cmap("tab20")

    fig, ax = plt.subplots(figsize=(12, 1.2 + 0.6 * len(nodes)))
    groups: Dict[str, tuple] = {}
    for ev in events:
        if ev.get("ph") != "X":
            continue
        track = track_of.get(ev.get("tid"), "")
        if track not in ypos or ev.get("cat") not in ("task", "launch"):
            continue
        name = ev.get("name", "")
        grp = name.rsplit("_", 1)[0]
        color = groups.setdefault(grp, cmap(len(groups) % 20))
        critical = (name, track) in on_path
        ax.barh(
            ypos[track],
            ev.get("dur", 0.0) / 1e6,
            left=ev.get("ts", 0.0) / 1e6,
            height=0.6,
            color=color,
            edgecolor="#C44E52" if critical else "white",
            linewidth=1.2 if critical else 0.3,
        )
    ax.set_yticks(range(len(nodes)))
    ax.set_yticklabels(nodes)
    ax.set_xlabel("time (s)")
    ax.set_title(
        title
        or f"measured: makespan {att.makespan_s:.4f}s "
        f"(critical path {len(att.critical_path)} spans)"
    )
    fig.tight_layout()
    _savefig(fig, path)
    if show:
        plt.show()
    plt.close(fig)
    return path
