"""CLI: ``python -m distributed_llm_scheduler_tpu <command>``.

Replaces the reference's four bare ``python <file>.py`` entry points
(reference README.md:16-59 — no flags anywhere) with one CLI:

* ``schedule``  — build a DAG, place it with a policy, report + save
* ``sweep``     — the full evaluation sweep (CSV + PNG + summary)
* ``execute``   — run a scheduled model DAG on live JAX devices
* ``visualize`` — DAG structure and Gantt renderings
* ``train``     — a few sharded (dp x tp) training steps
* ``generate``  — autoregressive KV-cache decoding (any model family)
* ``bench``     — the north-star benchmark (one JSON line)
* ``trace``     — traced execute (+ paged-decode leg) -> Perfetto JSON
* ``metrics``   — same run, metrics-registry snapshot JSON
* ``doctor``    — measured critical-path attribution + cost-model drift
* ``regress``   — fresh bench artifact vs committed baseline (gating)
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys


def _add_common(p: argparse.ArgumentParser) -> None:
    p.add_argument("--model", default="gpt2",
                   help="gpt2[-medium|-tiny] | llama[-8b|-tiny] | "
                        "mixtral[-8x7b|-tiny] | llm | random | pipeline")
    p.add_argument("--backend", default="sim",
                   help="sim | sim-reference (replay fidelity for schedule/visualize)")
    p.add_argument("--batch", type=int, default=1)
    p.add_argument("--seq-len", type=int, default=512)
    p.add_argument("--microbatches", type=int, default=1)
    p.add_argument("--vocab-shards", type=int, default=1, dest="vocab_shards",
                   help="shard the embedding/LM-head tables across tasks")
    p.add_argument("--fuse", action="store_true",
                   help="fuse linear task chains before scheduling")
    p.add_argument("--quantize", default="none", choices=["none", "int8"],
                   help="int8: per-channel weight quantization — halves/"
                        "quarters param bytes for placement, loads, and HBM")
    p.add_argument("--train-step", action="store_true",
                   help="schedule one fwd+bwd+optimizer step (gpt2* models)")
    p.add_argument("--routed", action="store_true",
                   help="mixtral*: expert tasks compute capacity-buffer "
                        "sparse dispatch (top_k/E of the dense FLOPs) "
                        "instead of dense every-expert-sees-every-token")
    p.add_argument("--capacity-factor", type=float, default=2.0,
                   dest="capacity_factor",
                   help="routed capacity slack (x k*N/E tokens per expert; "
                        "over-capacity assignments drop)")
    p.add_argument("--num-layers", type=int, default=None)
    p.add_argument("--num-nodes", type=int, default=8)
    p.add_argument("--slices", type=int, default=1,
                   help=">1: multi-slice topology (nodes split slice-by-"
                        "slice, DCN charged between slices)")
    p.add_argument("--hbm-gb", type=float, default=14.0)
    p.add_argument("--memory-regime", type=float, default=1.0)
    p.add_argument("--scheduler", default="heft")
    p.add_argument("--search-budget", type=int, default=None,
                   dest="search_budget",
                   help="--scheduler search: evaluation budget for the "
                        "annealed placement search (default 800)")
    p.add_argument("--search-seed", type=int, default=None,
                   dest="search_seed",
                   help="--scheduler search: RNG seed; same seed + "
                        "budget reproduces the placement digest exactly")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--out-dir", default="evaluation_results")


def _config_from(args: argparse.Namespace):
    from .utils.config import RunConfig

    fields = {f.name for f in dataclasses.fields(RunConfig)}
    kw = {k: v for k, v in vars(args).items() if k in fields and v is not None}
    return RunConfig(**kw)


# families with HF name maps (frontend/pretrained.py); drives both the
# fail-fast family check and the mapper dispatch
_WEIGHT_MAPPERS = {
    "gpt2": "gpt2_params_from_state_dict",
    "llama": "llama_params_from_state_dict",
    "mixtral": "mixtral_params_from_state_dict",
}
_WEIGHTS_UNSUPPORTED = (
    f"--weights supports the {', '.join(sorted(_WEIGHT_MAPPERS))} "
    "families (HF name maps in frontend/pretrained.py)"
)


def _weights_family(model_name: str):
    return next(
        (f for f in _WEIGHT_MAPPERS if model_name.startswith(f)), None
    )


def _load_pretrained_weights(path: str, config, model_name: str):
    """torch state-dict file -> flat param dict, or None after printing the
    error (shared by ``execute --weights`` and ``generate --weights``)."""
    import torch

    from .frontend import pretrained

    family = _weights_family(model_name)
    if family is None:
        print(_WEIGHTS_UNSUPPORTED, file=sys.stderr)
        return None
    mapper = getattr(pretrained, _WEIGHT_MAPPERS[family])
    try:
        sd = torch.load(path, map_location="cpu", weights_only=True)
        params = mapper(sd, config)
    except (OSError, ValueError, RuntimeError) as e:
        print(f"--weights {path}: {e}", file=sys.stderr)
        return None
    print(f"loaded {len(params)} params from {path}", file=sys.stderr)
    return params


def _export_trace(schedule, path: str, graph=None) -> int:
    """Shared --trace export: 0 on success, 2 (with stderr) on failure.
    ``graph`` adds cross-device transfer-edge flow arrows."""
    from .utils.profiling import export_chrome_trace

    try:
        print("trace ->", export_chrome_trace(schedule, path, graph=graph),
              file=sys.stderr)
        return 0
    except ValueError as e:  # degenerate replay with no timed tasks
        print(str(e), file=sys.stderr)
        return 2


def _replay_backend(cfg):
    """The sim backend the schedule/visualize replay commands accept; the
    device backend has a different execute() contract (live params/inputs)
    and is driven by the ``execute`` command instead."""
    if cfg.backend not in ("sim", "sim-reference"):
        raise SystemExit(
            f"--backend {cfg.backend!r} is not valid here; schedule/visualize "
            "replay with sim | sim-reference (run live devices via `execute`)"
        )
    return cfg.build_backend()


def cmd_schedule(args) -> int:
    from .utils.serialization import save_graph, save_schedule

    cfg = _config_from(args)
    dag = cfg.build_graph()
    graph = getattr(dag, "graph", dag)
    cluster = cfg.build_cluster()
    schedule = cfg.build_scheduler().schedule(graph, cluster)
    if args.validate:
        from .core.validate import validate_schedule

        vrep = validate_schedule(graph, cluster, schedule)
        print(f"validator: {vrep.summary()}", file=sys.stderr)
        if not vrep.ok:
            return 2
    rep = _replay_backend(cfg).execute(
        graph, cluster, schedule, dag_type=cfg.model
    )
    print(json.dumps({
        "graph": graph.summary(),
        "schedule": {k: v for k, v in schedule.summary().items()},
        "makespan_s": rep.makespan,
        "cache_hit_rate": rep.cache_hit_rate,
        "load_balance": rep.load_balance_score,
    }, indent=1, default=str))
    if args.trace and _export_trace(schedule, args.trace, graph=graph):
        return 2
    if args.save:
        print("graph ->", save_graph(graph, f"{cfg.out_dir}/{graph.name}.graph.json"))
        print("schedule ->", save_schedule(
            schedule, f"{cfg.out_dir}/{graph.name}.{cfg.scheduler}.schedule.json"
        ))
    return 0


def _cmd_lint_serving(args) -> int:
    """The serving half of the lint (``lint --serving``): run the
    serve_bench scenario with the page-ownership seam attached, then
    the three serving-safety passes — the page-lifetime prover
    (PGL00x) over the recorded event stream, the request-lifecycle
    checker (LCY00x) over both the frontend's rows and the engine's
    reqlog, and the repo-wide determinism lint (DET00x).
    ``--prefix`` serves the shared-prefix session workload on a
    sharing-enabled engine instead, so the prover replays the
    ref-counted share/unshare/cow/write lattice (PGL006/PGL007).
    ``--inject-leak N`` swaps in the leaky-pool fault injector (the CI
    must-fail leg: exit 1 naming PGL001); ``--inject-underflow`` (with
    ``--prefix``) swaps in the refcount-underflow injector (exit 1
    naming PGL006)."""
    import functools

    from .analysis import (
        Severity,
        analyze_determinism,
        analyze_lifecycle,
        analyze_pages,
    )
    from .eval.serve_bench import (
        PREFIX_SCENARIO,
        SCENARIO,
        build_serve_engine,
    )
    from .models.kv_pages import PageOwnershipLog
    from .obs.slo import SLOPolicy
    from .serve.frontend import (
        ServiceTimeModel,
        ServingFrontend,
        VirtualClock,
    )
    from .serve.loadgen import (
        poisson_arrivals,
        session_arrivals,
        session_prompt_token_ids,
    )
    from .serve.soak import inject_page_leak, inject_refcount_underflow

    if args.inject_leak is not None and args.inject_leak < 1:
        print(f"--inject-leak must be >= 1, got {args.inject_leak}",
              file=sys.stderr)
        return 2
    prefix = bool(getattr(args, "prefix", False))
    prompt_fn = None
    if prefix:
        sc = dict(SCENARIO, **PREFIX_SCENARIO)
        arrivals = session_arrivals(
            sc["prefix_rate_rps"], sc["n_sessions"], args.seed,
            system_len=sc["system_len"], user_len=sc["user_len"],
            turns=sc["turns"],
            max_new_tokens=sc["prefix_max_new_tokens"],
            priorities=sc["priorities"],
            priority_weights=sc["priority_weights"],
            think_time_s=sc["think_time_s"],
        )
        prompt_fn = functools.partial(
            session_prompt_token_ids,
            system_len=sc["system_len"], user_len=sc["user_len"],
        )
    else:
        sc = SCENARIO
        arrivals = poisson_arrivals(
            sc["rate_rps"], sc["n_requests"], args.seed,
            prompt_lens=sc["prompt_lens"],
            max_new_tokens=sc["max_new_tokens"],
            priorities=sc["priorities"],
            priority_weights=sc["priority_weights"],
        )
    eng, _pool = build_serve_engine(
        slots=sc["slots"], page_size=sc["page_size"],
        n_pages=sc["n_pages"], pages_per_seq=sc["pages_per_seq"],
        seg_steps=sc["seg_steps"], clock=VirtualClock(),
        sharing=prefix,
    )
    ownlog = PageOwnershipLog()
    eng.attach_ownership_log(ownlog)
    if args.inject_leak is not None:
        inject_page_leak(eng, args.inject_leak)
    if getattr(args, "inject_underflow", False):
        inject_refcount_underflow(eng)
    fe = ServingFrontend(
        eng, arrivals,
        SLOPolicy(ttft_s=sc["ttft_s"], window_s=sc["window_s"],
                  percentile=sc["percentile"]),
        admission="slo", preemption=True,
        time_model=ServiceTimeModel(
            wave_s=sc["wave_s"], segment_s=sc["segment_s"],
            idle_s=sc["idle_s"],
        ),
        prompt_fn=prompt_fn,
    )
    fe.run()
    rep = analyze_determinism()
    rep.extend(analyze_pages(ownlog))
    rep.extend(analyze_lifecycle(fe.request_rows(), final=True,
                                 label="serving"))
    rep.extend(analyze_lifecycle(eng.reqlog.snapshot(), final=True,
                                 label="engine"))
    rep = rep.dedupe()
    if args.json:
        print(json.dumps(rep.to_json()))
        return rep.exit_code
    min_sev = Severity.INFO if args.verbose else Severity.WARNING
    print(rep.render(min_severity=min_sev))
    if not rep.diagnostics:
        n_pool = sum(
            1 for e in ownlog.events
            if e["kind"] in ("alloc", "free", "share", "unshare")
        )
        shared = sum(
            len(e["pages"]) for e in ownlog.events
            if e["kind"] == "share"
        )
        extra = (
            f" ({shared} shared-page references ref-counted)"
            if prefix else ""
        )
        print(
            f"serving lint clean: {len(ownlog)} ownership events "
            f"replayed, free+used tiling proven at all {n_pool} pool "
            f"events{extra}; lifecycle and determinism passes found "
            "nothing",
            file=sys.stderr,
        )
    return rep.exit_code


def cmd_lint(args) -> int:
    """Static analysis (analysis/): build the DAG, schedule it, and lint
    graph + schedule + memory + sharding + quantization without executing
    anything.  Exit 1 on errors, 0 otherwise."""
    from .analysis import _spec_shapes, analyze
    from .parallel.mesh import factorize_mesh

    if getattr(args, "serving", False):
        if args.parallel or args.decode or args.paged or args.preflight \
                or args.fix:
            print("--serving runs the serving-safety passes and combines "
                  "only with --json/--verbose/--prefix/--inject-leak/"
                  "--inject-underflow/--seed",
                  file=sys.stderr)
            return 2
        if getattr(args, "inject_underflow", False) \
                and not getattr(args, "prefix", False):
            print("--inject-underflow needs the sharing-enabled workload: "
                  "use lint --serving --prefix --inject-underflow",
                  file=sys.stderr)
            return 2
        return _cmd_lint_serving(args)
    if getattr(args, "inject_leak", None) is not None:
        print("--inject-leak only applies to lint --serving",
              file=sys.stderr)
        return 2
    if getattr(args, "prefix", False) \
            or getattr(args, "inject_underflow", False):
        print("--prefix/--inject-underflow only apply to lint --serving",
              file=sys.stderr)
        return 2

    if args.parallel:
        if args.decode or args.paged or args.preflight or args.fix:
            print("--parallel lints the hand-written parallel layer and "
                  "combines only with --verbose", file=sys.stderr)
            return 2
        from .analysis import (
            Severity,
            analyze_happens_before,
            stage_programs_1f1b,
            sweep_parallel_collectives,
        )

        rep = sweep_parallel_collectives()
        # self-check the MPMD model on the canonical clean schedule: any
        # COL005/006/007 here means the 1F1B generator or the
        # happens-before pass itself regressed
        rep.extend(analyze_happens_before(stage_programs_1f1b(4, 8)))
        rep = rep.dedupe()
        if args.json:
            print(json.dumps(rep.to_json()))
            return rep.exit_code
        min_sev = Severity.INFO if args.verbose else Severity.WARNING
        print(rep.render(min_severity=min_sev))
        return rep.exit_code

    cfg = _config_from(args)
    if args.decode and _weights_family(cfg.model) is None:
        print("--decode needs a real model family (gpt2*/llama*/mixtral*)",
              file=sys.stderr)
        return 2
    if args.paged and _weights_family(cfg.model) != "gpt2":
        print("--paged lints the paged decode step (gpt2 family only)",
              file=sys.stderr)
        return 2
    if args.paged:
        from .frontend.decode_dag import build_paged_decode_dag

        dag = build_paged_decode_dag(
            cfg.model_config(), slots=cfg.batch,
            page_size=getattr(args, "page_size", 16),
        )
    elif args.decode:
        from .frontend.decode_dag import build_decode_dag_any

        dag = build_decode_dag_any(cfg.model_config(), batch=cfg.batch)
        if cfg.quantize == "int8":
            from .utils.quantize import quantize_dag

            dag = quantize_dag(dag)
    else:
        dag = cfg.build_graph()
    graph = getattr(dag, "graph", dag)
    if args.fix:
        from .analysis import fix_duplicate_dependencies

        fixed = fix_duplicate_dependencies(graph)
        if fixed:
            shown = ", ".join(fixed[:5]) + ("..." if len(fixed) > 5 else "")
            print(f"--fix: deduplicated dependencies on {len(fixed)} "
                  f"task(s): {shown}", file=sys.stderr)
    compiled_gb = analytic_gb = None
    if args.preflight:
        if not hasattr(dag, "init_params"):
            print("--preflight needs a model DAG (gpt2*/llama*/mixtral*): "
                  "XLA compiles the real task fns", file=sys.stderr)
            return 2
        from .utils.hbm import preflight_task_memory

        # preflight mutates memory_required up to max(analytic,
        # compiled): snapshot the analytic estimates first so the cost
        # pass compares against what the frontend actually declared
        analytic_gb = {t.task_id: t.memory_required for t in graph}
        compiled_gb = preflight_task_memory(
            graph, dag.init_params(), dag.make_inputs()
        )
    cluster = cfg.build_cluster()
    schedule = cfg.build_scheduler().schedule(graph, cluster)
    if args.fix:
        from .analysis import fix_per_node_order

        resorted = fix_per_node_order(graph, schedule)
        if resorted is None:
            print("--fix: no legal topological order exists (dependency "
                  "cycle among placed tasks); order left as scheduled",
                  file=sys.stderr)
        elif resorted:
            shown = ", ".join(resorted[:5]) + (
                "..." if len(resorted) > 5 else ""
            )
            print(f"--fix: re-sorted execution order on {len(resorted)} "
                  f"node(s): {shown}", file=sys.stderr)

    family = _weights_family(cfg.model)
    param_specs = getattr(dag, "param_specs", None)
    param_shapes = mesh_axes = None
    if family is not None and param_specs:
        param_shapes = _spec_shapes(param_specs)
        mesh_axes = factorize_mesh(cfg.num_nodes)
    rep = analyze(
        graph,
        cluster,
        schedule,
        strict=args.strict,
        param_shapes=param_shapes,
        mesh_axes=mesh_axes,
        family=family or "gpt2",
        param_specs=param_specs if cfg.quantize == "int8" else None,
        compiled_gb=compiled_gb,
        analytic_gb=analytic_gb,
        # typecheck (TYP001-TYP004) inputs: param *specs* carry the same
        # avals as initialized weights without materializing any arrays
        params=param_specs,
        graph_input=getattr(dag, "input_spec", None),
        chunk_tokens=getattr(args, "chunk_tokens", None),
        decode_budget=(
            cfg.batch * args.seg_steps
            if getattr(args, "chunk_tokens", None) is not None
            else None
        ),
    )
    if schedule.failed and not args.json:
        print(f"note: scheduler failed {len(schedule.failed)} task(s) "
              "under this memory regime (not a schedule defect)",
              file=sys.stderr)
    if args.json:
        print(json.dumps(rep.to_json()))
        return rep.exit_code
    from .analysis import Severity

    min_sev = Severity.INFO if args.verbose else Severity.WARNING
    print(rep.render(min_severity=min_sev))
    return rep.exit_code


def cmd_sweep(args) -> int:
    from .eval.evaluator import Evaluator

    cfg = _config_from(args)
    try:
        ev = Evaluator(
            node_counts=cfg.node_counts,
            memory_regimes=cfg.memory_regimes,
            slices=cfg.slices,
        )
    except ValueError as e:  # e.g. no node count divisible by --slices
        print(str(e), file=sys.stderr)
        return 2
    ev.run_experiments(num_runs=args.num_runs, seed=cfg.seed)
    print("csv ->", ev.write_csv(f"{cfg.out_dir}/raw_results.csv"))
    print("png ->", ev.write_plots(f"{cfg.out_dir}/scheduler_performance.png"))
    ev.print_summary()
    return 0


def cmd_execute(args) -> int:
    from .backends.device import DeviceBackend

    cfg = _config_from(args)
    if args.profile and args.segments:
        print("--segments fuses away task boundaries; per-task --profile "
              "timings need per-task dispatch", file=sys.stderr)
        return 2
    if args.trace and not args.profile:
        # fail BEFORE the device run: timings only exist in profile mode
        print("--trace needs per-task timings; add --profile",
              file=sys.stderr)
        return 2
    if cfg.slices > 1:
        # live clusters carry their REAL slice topology (from_jax_devices
        # reads device.slice_index); an artificial --slices would silently
        # not apply
        print("execute binds live devices, whose slice topology is "
              "detected, not configured; drop --slices (use `schedule "
              "--slices N` for modeled multislice runs)", file=sys.stderr)
        return 2
    if cfg.weights and _weights_family(cfg.model) is None:
        # fail fast, before graph build / device binding / scheduling
        print(_WEIGHTS_UNSUPPORTED, file=sys.stderr)
        return 2
    dag = cfg.build_graph()
    if not hasattr(dag, "graph"):
        print("execute needs a model DAG (gpt2* / llama* / mixtral*); "
              "synthetic graphs have no fns", file=sys.stderr)
        return 2
    cluster = cfg.build_cluster_with_devices()
    schedule = cfg.build_scheduler().schedule(dag.graph, cluster)
    backend = DeviceBackend(cluster)
    if cfg.weights:
        from .frontend.pretrained import fit_params_to_dag

        params = _load_pretrained_weights(cfg.weights, dag.config, cfg.model)
        if params is None:
            return 2
        try:
            params = fit_params_to_dag(dag, params)
        except ValueError as e:
            print(f"--weights {cfg.weights}: {e}", file=sys.stderr)
            return 2
        if cfg.quantize == "int8":
            # checkpoints load in fp; convert to the quantized DAG's layout
            from .utils.quantize import quantize_like

            params = quantize_like(dag, params)
    else:
        params = dag.init_params()
    ids = dag.make_inputs()
    inject = None
    if args.inject_failure:
        # validate the spec BEFORE the expensive device run
        inject = _parse_injection(args.inject_failure, cluster)
        if inject is None:
            return 2
    rep = backend.execute(
        dag.graph, schedule, params, ids, profile=args.profile,
        segments=args.segments, keep_outputs=bool(inject),
        stream_params=args.stream_params,
    )
    summary = rep.summary()
    if inject:
        recovery = _injected_recovery(
            inject, dag, schedule, cluster, cfg, rep, params, ids,
            segments=args.segments, stream_params=args.stream_params,
        )
        summary["recovery"] = recovery
        print(json.dumps(summary, indent=1, default=str))
        if not recovery["output_matches_uninterrupted"]:
            # a failed recovery must be scriptable, not buried in JSON
            msg = (
                "remainder could not be placed on the survivors"
                if "reschedule_failed_tasks" in recovery
                else "recovered output does NOT match the uninterrupted run"
            )
            print(f"--inject-failure: {msg}", file=sys.stderr)
            return 1
    else:
        print(json.dumps(summary, indent=1, default=str))
    if args.trace and _export_trace(schedule, args.trace, graph=dag.graph):
        return 2
    from .obs import ambient_tracer, trace_enabled

    if trace_enabled():
        # DLS_TRACE=1: the run recorded into the ambient tracer with no
        # flags; export its unified timeline next to the other artifacts
        amb = ambient_tracer()
        if amb is not None and len(amb):
            from .obs.export import export_perfetto

            os.makedirs(cfg.out_dir, exist_ok=True)
            print("ambient trace ->", export_perfetto(
                amb, f"{cfg.out_dir}/execute.trace.json"
            ), file=sys.stderr)
    return 0


def _parse_injection(spec: str, cluster):
    """Validate `--inject-failure NODE[:FRAC]`; (node_id, frac) or None."""
    node, _, frac_s = spec.partition(":")
    try:
        frac = float(frac_s) if frac_s else 0.5
    except ValueError:
        print(f"--inject-failure: bad fraction {frac_s!r}", file=sys.stderr)
        return None
    if not 0.0 <= frac <= 1.0:
        print(f"--inject-failure: fraction {frac} outside [0, 1]",
              file=sys.stderr)
        return None
    # literal node id first: a cluster whose ids are themselves numeric
    # strings must stay addressable by id (the index reading would shadow
    # it and could resolve to a different device)
    if node not in cluster and node.isdigit():
        idx = int(node)
        if idx >= len(cluster):
            print(f"--inject-failure: node index {idx} out of range "
                  f"(cluster has {len(cluster)} devices)", file=sys.stderr)
            return None
        node = cluster.devices[idx].node_id
    if node not in cluster:
        print(f"--inject-failure: unknown node {node!r} "
              f"(have {cluster.ids()})", file=sys.stderr)
        return None
    if len(cluster) < 2:
        print("--inject-failure needs >= 2 devices", file=sys.stderr)
        return None
    return node, frac


def _injected_recovery(
    inject, dag, schedule, cluster, cfg, first_rep, params, ids,
    segments: bool, stream_params: bool = False,
):
    """Fault injection for `execute --inject-failure NODE[:FRAC]`: treat
    the first FRAC of the assignment order as completed when NODE dies,
    re-place the remainder on the survivors, re-execute feeding the
    retained surviving outputs, and verify the recovered output matches
    the uninterrupted run.  Returns the recovery summary dict."""
    import numpy as np

    from .backends.device import DeviceBackend
    from .sched.elastic import reschedule

    node, frac = inject
    order = schedule.assignment_order
    completed = set(order[: int(len(order) * frac)])
    survivors = cluster.without(node)
    new_s, remainder, must_run, available = reschedule(
        dag.graph, schedule, completed, {node}, survivors,
        cfg.build_scheduler(), have_outputs=first_rep.task_outputs,
    )
    summary = {
        "killed_node": node,
        "completed_before_failure": len(completed),
        "reused_outputs": len(available),
        "rerun_tasks": len(must_run),
    }
    if new_s.failed:
        # distinguish "remainder would not fit on the survivors" from a
        # numerical recovery failure
        summary["reschedule_failed_tasks"] = len(new_s.failed)
        summary["output_matches_uninterrupted"] = False
        return summary
    ext = {t: first_rep.task_outputs[t] for t in available}
    rec = DeviceBackend(survivors).execute(
        remainder, new_s, params, ids,
        ext_outputs=ext, segments=segments, keep_outputs=True,
        stream_params=stream_params,
    )
    # compare the ORIGINAL graph's final task: retained if it survived the
    # failure, recomputed (rec.task_outputs) otherwise — rec.output is the
    # remainder's own last task, which need not be the model's output
    final = dag.graph.topo_order[-1]
    recovered_final = (
        ext[final] if final in available else rec.task_outputs.get(final)
    )
    ok = first_rep.output is not None and recovered_final is not None and (
        bool(np.allclose(
            np.asarray(first_rep.output), np.asarray(recovered_final),
            rtol=2e-4, atol=2e-4,
        ))
    )
    summary["recovered_makespan_ms"] = rec.makespan_s * 1e3
    summary["output_matches_uninterrupted"] = ok
    return summary


def _visualize_menu(args, cfg) -> int:
    """Stdin-driven visualization menu (reference ``visu.py:294-339``):
    re-render, switch policy, and inspect without re-running the CLI.
    Figures still save to files; ``--show`` additionally opens them."""
    from .visu.plots import visualize_dag, visualize_schedule

    dag = cfg.build_graph()
    graph = getattr(dag, "graph", dag)
    banner = ("[1] simple DAG  [2] detailed DAG  [3 <policy>] gantt "
              f"(default {cfg.scheduler})  [4] summary  [q] quit")
    print(banner)
    while True:
        try:
            choice = input("> ").strip()
        except EOFError:
            return 0
        if choice in ("q", "quit", "exit"):
            return 0
        if choice in ("1", "2"):
            print("dag ->", visualize_dag(
                graph, f"{cfg.out_dir}/{graph.name}.dag.png",
                detailed=choice == "2", show=args.show,
            ))
        elif choice == "3" or choice.startswith("3 "):
            policy = choice[1:].strip() or cfg.scheduler
            from . import get_scheduler

            try:
                sched_cls = get_scheduler(policy)
            except KeyError as e:
                print(e)
                continue
            # fresh graph + cluster per render: scheduling mutates state
            d2 = cfg.build_graph()
            g2 = getattr(d2, "graph", d2)
            cluster = cfg.build_cluster()
            schedule = sched_cls.schedule(g2, cluster)
            if schedule.failed:
                print(f"{policy}: {len(schedule.failed)} tasks failed to "
                      "place; no gantt", file=sys.stderr)
                continue
            _replay_backend(cfg).execute(g2, cluster, schedule)
            print("gantt ->", visualize_schedule(
                schedule, f"{cfg.out_dir}/{g2.name}.{policy}.gantt.png",
                show=args.show,
            ))
        elif choice == "4":
            for k, v in graph.summary().items():
                print(f"  {k}: {v}")
        else:
            print(f"unknown choice {choice!r}; {banner}")


def cmd_visualize(args) -> int:
    from .visu.plots import visualize_dag, visualize_schedule

    cfg = _config_from(args)
    if getattr(args, "from_trace", None):
        # measured gantt: render the exported trace's device spans (what
        # actually ran under DLS_TRACE=1), not a fresh simulated replay
        from .visu.plots import visualize_trace_gantt

        stem = os.path.splitext(os.path.basename(args.from_trace))[0]
        try:
            print("gantt ->", visualize_trace_gantt(
                args.from_trace, f"{cfg.out_dir}/{stem}.gantt.png",
                show=args.show,
            ))
        except (OSError, ValueError) as e:
            print(f"--from-trace {args.from_trace}: {e}", file=sys.stderr)
            return 2
        return 0
    if getattr(args, "menu", False):
        return _visualize_menu(args, cfg)
    dag = cfg.build_graph()
    graph = getattr(dag, "graph", dag)
    print("dag ->", visualize_dag(
        graph, f"{cfg.out_dir}/{graph.name}.dag.png", detailed=args.detailed,
        show=args.show,
    ))
    cluster = cfg.build_cluster()
    schedule = cfg.build_scheduler().schedule(graph, cluster)
    _replay_backend(cfg).execute(graph, cluster, schedule)
    print("gantt ->", visualize_schedule(
        schedule, f"{cfg.out_dir}/{graph.name}.{cfg.scheduler}.gantt.png",
        show=args.show,
    ))
    return 0


def cmd_train(args) -> int:
    import jax
    import jax.numpy as jnp

    from .models.gpt2 import GPT2Config
    from .parallel.mesh import factorize_mesh, make_mesh
    from .parallel.train import make_train_step

    if args.model.startswith("mixtral"):
        return _cmd_train_moe(args)
    cfg_map = {"gpt2": GPT2Config.small, "gpt2-medium": GPT2Config.medium,
               "gpt2-tiny": GPT2Config.tiny}
    if args.model not in cfg_map:
        # silently training a default GPT-2 when asked for llama would be
        # worse than refusing
        print(f"train supports {sorted(cfg_map)} and mixtral* (dp x ep "
              "expert parallelism, --routed for sparse dispatch); llama "
              "trains via the task-graph path: --train-step on "
              "schedule/execute", file=sys.stderr)
        return 2
    mcfg = cfg_map[args.model]()
    pp_mb = 0
    if args.pp:
        # pipeline-parallel training: stages as mesh shards, one GPipe
        # scan per step (parallel/pipeline_pp.py)
        import numpy as np
        from jax.sharding import Mesh

        from .parallel.pipeline_pp import make_pp_train_step

        if args.scan:
            # stages already lax.scan their layer blocks; a separate
            # --scan would be a no-op claim
            print("--pp already scans layer blocks within each stage; "
                  "drop --scan", file=sys.stderr)
            return 2
        layers = mcfg.n_layer
        if (
            args.pp < 1
            or layers % args.pp
            or args.pp > len(jax.devices())
        ):
            print(f"--pp {args.pp} must be >= 1, divide n_layer={layers}, "
                  f"and not exceed {len(jax.devices())} devices",
                  file=sys.stderr)
            return 2
        mesh = Mesh(np.array(jax.devices()[:args.pp]), ("pp",))
        axes = {"dp": 1, "tp": 1, "sp": 1}
        # ONE effective microbatch count, baked into the compiled step AND
        # used for batch sizing below
        pp_mb = max(args.microbatches, args.pp)
        train_step, init_state = make_pp_train_step(
            mcfg, mesh, microbatches=pp_mb, remat=args.remat
        )
    else:
        axes = factorize_mesh(len(jax.devices()))
        mesh = make_mesh(**axes)
        train_step, init_state = make_train_step(
            mcfg, mesh, remat=args.remat, scan=args.scan
        )
    batch = max(2 * axes["dp"], 2)
    if pp_mb:
        batch = max(batch, pp_mb)  # each microbatch needs >= 1 sequence
    return _run_train_loop(
        args, train_step, init_state, batch,
        seq=min(args.seq_len, mcfg.n_positions),
        vocab_size=mcfg.vocab_size,
    )


def _run_train_loop(args, train_step, init_state, batch, seq, vocab_size):
    """Shared train-subcommand scaffold: init (+ checkpoint resume),
    synthetic batch, step loop, checkpoint save — one implementation for
    the GPT-2 (dp x tp / pp) and MoE (dp x ep) paths so checkpoint
    handling and the loss-print contract cannot diverge."""
    import jax
    import jax.numpy as jnp

    state = init_state(jax.random.PRNGKey(args.seed))
    if args.ckpt and os.path.exists(args.ckpt):
        from .utils.checkpoint import load_state

        state = load_state(args.ckpt, state)
        print(f"resumed from {args.ckpt} at step {int(state.step)}",
              file=sys.stderr)
    ids = jax.random.randint(
        jax.random.PRNGKey(1), (batch, seq), 0, vocab_size, dtype=jnp.int32
    )
    targets = jnp.roll(ids, -1, axis=1)
    for _ in range(args.steps):
        state, loss = train_step(state, ids, targets)
        print(f"step {int(state.step)}: loss {float(loss):.4f}")
    if args.ckpt:
        from .utils.checkpoint import save_state

        print(f"saved {save_state(state, args.ckpt)}", file=sys.stderr)
    return 0


def _cmd_train_moe(args) -> int:
    """Mixtral training on a dp x ep mesh (dense or routed dispatch) —
    the CLI face of ``parallel/expert.make_moe_train_step``."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    from .models.mixtral import MixtralConfig
    from .parallel.expert import make_moe_train_step

    cfg_map = {
        "mixtral": MixtralConfig.mixtral_8x7b,
        "mixtral-8x7b": MixtralConfig.mixtral_8x7b,
        "mixtral-tiny": MixtralConfig.tiny,
    }
    if args.model not in cfg_map:
        print(f"unknown model {args.model!r}; mixtral variants are "
              f"{sorted(cfg_map)}", file=sys.stderr)
        return 2
    if args.pp or args.scan:
        print("--pp/--scan are the GPT-2 train path's flags; the MoE "
              "path trains dp x ep", file=sys.stderr)
        return 2
    mcfg = cfg_map[args.model]()
    n_dev = len(jax.devices())
    # widest ep that divides both the expert count and the device count;
    # remaining devices become dp
    ep = 1
    for cand in range(min(mcfg.n_experts, n_dev), 0, -1):
        if mcfg.n_experts % cand == 0 and n_dev % cand == 0:
            ep = cand
            break
    dp = n_dev // ep
    mesh = Mesh(np.array(jax.devices()[:n_dev]).reshape(dp, ep), ("dp", "ep"))
    print(f"mesh dp={dp} x ep={ep}"
          + (f", routed (capacity x{args.capacity_factor})"
             if args.routed else ", dense dispatch"),
          file=sys.stderr)
    train_step, init_state = make_moe_train_step(
        mcfg, mesh, remat=args.remat, routed=args.routed,
        capacity_factor=args.capacity_factor,
    )
    return _run_train_loop(
        args, train_step, init_state, batch=max(2 * dp, 2),
        seq=min(args.seq_len, mcfg.max_seq_len),
        vocab_size=mcfg.vocab_size,
    )


def cmd_generate(args) -> int:
    # flag validation FIRST — before config resolution, checkpoint
    # loading, or any device-touching work: scheduling flags without
    # --task-graph are dead (the whole-program loop does no scheduling),
    # and --task-graph sampling is greedy-only
    if not getattr(args, "task_graph", False):
        passed = [
            k for k in ("scheduler", "num_nodes", "hbm_gb", "loop_steps")
            if getattr(args, k, None) is not None
        ]
        if passed:
            print(f"--{'/--'.join(p.replace('_', '-') for p in passed)} "
                  "only apply with --task-graph (the whole-program decode "
                  "loop does no scheduling)", file=sys.stderr)
            return 2
    elif args.temperature != 0.0:
        print("--task-graph generation is greedy; drop --temperature",
              file=sys.stderr)
        return 2
    elif getattr(args, "kv_int8", False):
        print("--kv-int8 applies to the whole-program decode loop; the "
              "task-graph path places dense cache slabs", file=sys.stderr)
        return 2
    elif getattr(args, "loop_steps", None) is not None and args.loop_steps < 1:
        print("--loop-steps must be >= 1", file=sys.stderr)
        return 2
    # --quantize composes with --task-graph: weights quantize (channel
    # scheme — the DAG path's byte-accounting contract), cache slabs
    # stay fp (quantize_dag exclude_prefixes)

    import jax
    import jax.numpy as jnp

    from .models import gpt2, llama, mixtral
    from .utils.config import RunConfig

    # same variant table as every other subcommand (utils/config.py)
    try:
        config = RunConfig(model=args.model).model_config()
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2
    if config is None:
        print("generate needs a real model family (gpt2* / llama* / "
              "mixtral*); synthetic graphs have no decode path",
              file=sys.stderr)
        return 2
    # family resolution shared with the weights table (prefix match, not
    # first letter: a future 'mistral-*' must not silently bind mixtral)
    mod = {
        "gpt2": gpt2, "llama": llama, "mixtral": mixtral,
    }[_weights_family(args.model)]

    if args.weights:
        params = _load_pretrained_weights(args.weights, config, args.model)
        if params is None:
            return 2
    else:
        params = mod.init_params(config, jax.random.PRNGKey(args.seed))

    try:
        prompt = [int(t) for t in args.prompt_ids.split(",") if t.strip()]
    except ValueError:
        print(f"--prompt-ids must be comma-separated token ids, got "
              f"{args.prompt_ids!r}", file=sys.stderr)
        return 2
    if not prompt or any(t < 0 or t >= config.vocab_size for t in prompt):
        print(f"prompt ids must be in [0, {config.vocab_size})", file=sys.stderr)
        return 2
    ids = jnp.asarray([prompt], dtype=jnp.int32)

    if getattr(args, "task_graph", False):
        # inference through the scheduling layer (frontend/decode_dag):
        # prefill + per-token decode-step DAGs, placed by --scheduler,
        # functional cache updates between steps.  Greedy only (the step
        # DAG exports logits; sampling would add a host RNG loop).
        # Real defaults for the scheduled path (None = not passed):
        if args.scheduler is None:
            args.scheduler = "heft"
        if args.num_nodes is None:
            args.num_nodes = 1
        if args.hbm_gb is None:
            args.hbm_gb = 14.0
        import numpy as np

        from .backends.device import DeviceBackend
        from .frontend.decode_dag import (
            apply_cache_updates,
            build_decode_dag_any,
            cache_dims,
            decode_inputs,
        )
        from .models.decode import _position_limit

        max_len = len(prompt) + args.max_new_tokens
        limit = _position_limit(config)
        if limit and max_len > limit:
            # same clean error the whole-program path produces
            print(f"prompt ({len(prompt)}) + max_new_tokens "
                  f"({args.max_new_tokens}) exceeds the model's position "
                  f"limit {limit}", file=sys.stderr)
            return 2
        cfg = _config_from(args)
        cluster = cfg.build_cluster_with_devices()
        backend = DeviceBackend(cluster)
        new = []
        # weights + zero cache slabs, allocated ONCE (shapes are fixed by
        # max_len); each step's updates fold back in functionally
        params_c = dict(params)
        n_layers, nkv, hd = cache_dims(config)
        for i in range(n_layers):
            for kind in ("k", "v"):
                params_c[f"cache_{kind}_{i}"] = jnp.zeros(
                    (1, nkv, max_len, hd), config.dtype
                )
        # position is runtime data: ONE graph + schedule per step_len
        # class (prefill, then single-token) serves every position — an
        # N-token generation compiles 2 programs, not N
        loop_k = getattr(args, "loop_steps", None)
        quantize_tg = getattr(args, "quantize", "none") == "int8"
        if quantize_tg:
            # int8 WEIGHTS through the scheduler (channel scheme — the
            # DAG path's byte-accounting contract); cache slabs stay fp,
            # the per-step write path updates them in place
            from .utils.quantize import quantize_dag, quantize_like

        def _tg_dag(step_len):
            d = build_decode_dag_any(
                config, batch=1, step_len=step_len, max_len=max_len
            )
            return quantize_dag(
                d, exclude_prefixes=("cache_",)
            ) if quantize_tg else d

        if args.max_new_tokens > 0:
            # shared prefill: one scheduled dispatch of the prompt-length
            # class, cache updates folded functionally, first token by
            # on-device argmax (one int32 crosses the link, not logits)
            pdag = _tg_dag(len(prompt))
            if quantize_tg:
                params_c = quantize_like(pdag, params_c)
            sched_p = cfg.build_scheduler().schedule(pdag.graph, cluster)
            if sched_p.failed:
                print(f"prefill: {len(sched_p.failed)} tasks failed to "
                      "place", file=sys.stderr)
                return 1
            rep = backend.execute(
                pdag.graph, sched_p, params_c,
                decode_inputs(ids, 0, max_len=max_len), keep_outputs=True,
            )
            if args.max_new_tokens > 1:  # sole step's update unused
                params_c = apply_cache_updates(
                    params_c, rep.task_outputs, config, pos=0
                )
            cur = jnp.argmax(
                rep.output[:, -1, :], axis=-1
            ).astype(jnp.int32)[:, None]
            new.append(int(np.asarray(cur)[0, 0]))
            pos = len(prompt)
        remaining = max(args.max_new_tokens - 1, 0)
        if remaining:
            ddag = _tg_dag(1)
            sched_d = cfg.build_scheduler().schedule(ddag.graph, cluster)
            if sched_d.failed:
                print(f"decode step: {len(sched_d.failed)} tasks failed "
                      "to place", file=sys.stderr)
                return 1
        if remaining and loop_k is not None:
            # amortized path: decode runs in loop_k-token windows — one
            # composed lax.scan program over the scheduled step DAG per
            # window (backends/decode_loop), one host round-trip per
            # window instead of per token
            from .backends.decode_loop import (
                build_decode_loop,
                split_cache_params,
            )

            weights, caches = split_cache_params(params_c)
            loops: dict = {}  # two jits at most: full + tail window
            while remaining:
                k = min(loop_k, remaining)
                if k not in loops:
                    try:
                        loops[k] = build_decode_loop(
                            ddag.graph, sched_d, config, steps=k
                        )
                    except ValueError as e:
                        if "single-node placement" not in str(e):
                            raise
                        # the loop only amortizes the single-device
                        # steady state
                        print(f"{e}; drop --loop-steps for the "
                              "per-token dispatch path", file=sys.stderr)
                        return 2
                toks, caches = loops[k](
                    weights, caches, cur, jnp.int32(pos)
                )
                new.extend(int(t) for t in np.asarray(toks)[0])
                cur = toks[:, -1:]
                pos += k
                remaining -= k
        elif remaining:
            first_of_class = True
            while remaining:
                rep = backend.execute(
                    ddag.graph, sched_d, params_c,
                    decode_inputs(cur, pos, max_len=max_len),
                    keep_outputs=True,
                    # jit caches are hot after a class's first step: skip
                    # the throwaway warmup run or every later token
                    # executes twice
                    warmup=first_of_class,
                )
                first_of_class = False
                cur = jnp.argmax(
                    rep.output[:, -1, :], axis=-1
                ).astype(jnp.int32)[:, None]
                new.append(int(np.asarray(cur)[0, 0]))
                remaining -= 1
                if remaining:  # last step's update unused
                    params_c = apply_cache_updates(
                        params_c, rep.task_outputs, config, pos=pos
                    )
                pos += 1
        result = {
            "model": args.model,
            "prompt_ids": prompt,
            "generated_ids": new,
            "task_graph": True,
            "scheduler": cfg.scheduler,
        }
        if loop_k is not None:
            result["loop_steps"] = loop_k
        if quantize_tg:
            result["weights"] = "int8"
        print(json.dumps(result))
        return 0

    quantized = getattr(args, "quantize", "none") == "int8"
    try:
        if quantized:
            # int8 weights in HBM (decode is bandwidth-bound), dequantized
            # inside the jitted step — the grouped+rowwise fidelity scheme
            # the decode bench measures (utils/quantize.quantize_params)
            from .models import decode as decode_mod
            from .utils.quantize import (
                ROWWISE_EMBED_KEYS,
                dequantize,
                quantize_params,
            )

            fam = _weights_family(args.model)
            qparams = quantize_params(
                params, scheme="grouped",
                rowwise_keys=ROWWISE_EMBED_KEYS.get(fam, ()),
            )
            dt = jnp.dtype(config.dtype)

            def fwd_q(p, *a, **kw):
                return mod.forward_cached(
                    {k: dequantize(v, dt) for k, v in p.items()}, *a, **kw
                )

            out = decode_mod.generate(
                fwd_q, mod.init_cache, qparams, ids, config,
                max_new_tokens=args.max_new_tokens,
                temperature=args.temperature, top_k=args.top_k,
                key=jax.random.PRNGKey(args.seed),
                kv_int8=bool(getattr(args, "kv_int8", False)),
            )
        else:
            out = mod.generate(
                params, ids, config, max_new_tokens=args.max_new_tokens,
                temperature=args.temperature, top_k=args.top_k,
                key=jax.random.PRNGKey(args.seed),
                kv_int8=bool(getattr(args, "kv_int8", False)),
            )
    except ValueError as e:  # e.g. past the model's position limit
        print(str(e), file=sys.stderr)
        return 2
    new = [int(t) for t in out[0, len(prompt):]]
    result = {
        "model": args.model,
        "prompt_ids": prompt,
        "generated_ids": new,
        "temperature": args.temperature,
    }
    if quantized:
        result["weights"] = "int8"
    print(json.dumps(result))
    return 0


def cmd_rankcheck(args) -> int:
    """Sim-vs-real rank agreement (VERDICT r2 #2): schedule with several
    policies, predict makespans with the full-fidelity simulator, execute
    each placement on the live devices, report rank agreement as JSON."""
    from .eval.rankcheck import run_rank_check

    kwargs = {}
    if args.stress:
        # the separating configuration (VERDICT r3 next #3): transfer-bound
        # by construction, so the sim claims a winner and the check bites
        import jax

        from .core.cluster import Cluster
        from .frontend.stress_dag import build_transfer_stress_dag

        if len(jax.devices()) < 4:
            # fewer devices collapse the regime back into a tie (1 device:
            # no cross edges at all; 2-3 divide the 6 chains, so
            # round-robin accidentally gets perfect chain locality) — a
            # vacuous pass here would defeat the flag's whole point
            print("rankcheck --stress needs >= 4 devices (run under "
                  "XLA_FLAGS=--xla_force_host_platform_device_count=8)",
                  file=sys.stderr)
            return 2
        dag = build_transfer_stress_dag(chains=6, length=6, edge_mb=8.0)
        kwargs["cluster"] = Cluster.from_jax_devices(
            jax.devices()[:4], hbm_cap_gb=4.0
        )
        if args.policies is None:
            # five policies spanning distinct makespan tiers on this
            # graph (pipeline ~100 < greedy ~110 < dfs ~145 < critical
            # ~155 < roundrobin ~165 ms measured): the wider 8-policy
            # default contained two near-tie clusters whose members trade
            # run-to-run, which measures host noise, not rank fidelity
            args.policies = "roundrobin,critical,dfs,greedy,pipeline"
    else:
        cfg = _config_from(args)
        dag = cfg.build_graph()  # applies --fuse / --quantize per RunConfig
        if not hasattr(dag, "graph"):
            print("rankcheck needs a model DAG (gpt2* / llama* / mixtral*); "
                  "synthetic graphs have no fns", file=sys.stderr)
            return 2
        kwargs["hbm_cap_gb"] = cfg.hbm_gb
    if args.policies is None:
        args.policies = "roundrobin,critical,pipeline,pack"
    report = run_rank_check(
        dag.graph,
        dag.init_params(),
        dag.make_inputs(),
        policies=[p.strip() for p in args.policies.split(",") if p.strip()],
        measure_repeats=args.measure_repeats,
        reps=args.reps,
        anchor_calibrate=args.anchor_calibrate,
        **kwargs,
    )
    print(json.dumps(report, indent=1))
    if report["winner_agreement"] is None:
        # <2 surviving policies: nothing was rankable — distinct exit code
        # so callers don't conflate it with a measured rank refutation
        print("rankcheck: fewer than 2 policies produced complete "
              "placements; no ranking to check", file=sys.stderr)
        return 3
    return 0 if report["winner_agreement"] else 1


def _observed_run(args, tracer, metrics) -> int:
    """Shared ``trace``/``metrics`` runner: one observed
    ``DeviceBackend.execute`` of the model DAG on the live mesh, plus
    (gpt2 family, unless --skip-decode) a small paged continuous-batching
    decode leg so the decode counter tracks (queue depth, page-pool
    occupancy) and TTFT/TPOT histograms populate.  0, or 2 when the
    configuration cannot run."""
    from .backends.device import DeviceBackend

    cfg = _config_from(args)
    dag = cfg.build_graph()
    if not hasattr(dag, "graph"):
        print("trace/metrics need a model DAG (gpt2* / llama* / mixtral*); "
              "synthetic graphs have no fns", file=sys.stderr)
        return 2
    cluster = cfg.build_cluster_with_devices()
    schedule = cfg.build_scheduler().schedule(dag.graph, cluster)
    backend = DeviceBackend(cluster)
    backend.execute(
        dag.graph, schedule, dag.init_params(), dag.make_inputs(),
        trace=tracer, metrics=metrics,
    )
    if getattr(args, "skip_decode", False):
        return 0
    if _weights_family(cfg.model) != "gpt2":
        print("decode leg skipped: paged decode is gpt2-family only "
              "(the execute leg above still traced)", file=sys.stderr)
        return 0
    import jax
    import jax.numpy as jnp

    from .core.cluster import Cluster
    from .frontend.decode_dag import build_paged_decode_dag
    from .models.kv_pages import PagePool

    mcfg = cfg.model_config()
    slots, ps, n_pages, ppseq = 2, 8, 32, 4
    ddag = build_paged_decode_dag(
        mcfg, slots=slots, page_size=ps, n_pages=n_pages,
        pages_per_seq=ppseq,
    )
    params = ddag.init_params()
    weights = {k: v for k, v in params.items()
               if not (k.startswith("cache_") or k == "page_table")}
    dcluster = Cluster.from_jax_devices(jax.devices()[:1])
    pool = PagePool(n_pages=n_pages, page_size=ps)
    eng = DeviceBackend(dcluster).paged_decode_engine(
        ddag.graph, cfg.build_scheduler().schedule(ddag.graph, dcluster),
        mcfg, weights, pool, slots=slots, pages_per_seq=ppseq, seg_steps=4,
        trace=tracer, metrics=metrics,
    )
    # 4 requests over 2 slots: admission waves, retirement churn, and
    # queue-depth movement — enough to exercise every decode counter
    for i in range(4):
        ids = jnp.asarray([[1 + (i % 3), 2, 3, 4, 5, 6, 7, 8]], jnp.int32)
        eng.submit(f"r{i}", ids, 6)
    eng.run()
    return 0


def cmd_trace(args) -> int:
    from .obs.export import export_perfetto, trace_summary, validate_trace
    from .obs.metrics import MetricsRegistry
    from .obs.trace import Tracer

    tracer = Tracer()
    rc = _observed_run(args, tracer, MetricsRegistry())
    if rc:
        return rc
    if not len(tracer):
        print("trace: no events recorded", file=sys.stderr)
        return 2
    path = export_perfetto(tracer, args.out)
    errs = validate_trace(path)
    if errs:
        for e in errs[:10]:
            print(f"trace: {e}", file=sys.stderr)
        return 2
    print("trace ->", path, file=sys.stderr)
    print(json.dumps(trace_summary(path), indent=1))
    return 0


def cmd_metrics(args) -> int:
    from .obs.metrics import MetricsRegistry, validate_snapshot

    reg = MetricsRegistry()
    rc = _observed_run(args, None, reg)
    if rc:
        return rc
    snap = reg.snapshot()
    errs = validate_snapshot(snap)
    if errs:
        for e in errs[:10]:
            print(f"metrics: {e}", file=sys.stderr)
        return 2
    if args.out:
        with open(args.out, "w") as f:
            json.dump(snap, f, indent=1)
        print("metrics ->", args.out, file=sys.stderr)
    print(json.dumps(snap, indent=1))
    return 0


def _slo_live_requests(args, flight):
    """One small paged continuous-batching leg (gpt2 family, 2 slots)
    with the flight recorder wired; returns ``(rc, dls.requests/1
    snapshot)`` — rc 2 when the configuration cannot run."""
    from .backends.device import DeviceBackend

    cfg = _config_from(args)
    if _weights_family(cfg.model) != "gpt2":
        print("slo: live run needs a gpt2-family model (paged decode)",
              file=sys.stderr)
        return 2, None
    import jax
    import jax.numpy as jnp

    from .core.cluster import Cluster
    from .frontend.decode_dag import build_paged_decode_dag
    from .models.kv_pages import PagePool

    mcfg = cfg.model_config()
    slots, ps, n_pages, ppseq = 2, 8, 32, 4
    ddag = build_paged_decode_dag(
        mcfg, slots=slots, page_size=ps, n_pages=n_pages,
        pages_per_seq=ppseq,
    )
    params = ddag.init_params()
    weights = {k: v for k, v in params.items()
               if not (k.startswith("cache_") or k == "page_table")}
    dcluster = Cluster.from_jax_devices(jax.devices()[:1])
    pool = PagePool(n_pages=n_pages, page_size=ps)
    eng = DeviceBackend(dcluster).paged_decode_engine(
        ddag.graph, cfg.build_scheduler().schedule(ddag.graph, dcluster),
        mcfg, weights, pool, slots=slots, pages_per_seq=ppseq, seg_steps=4,
        flight=flight,
    )
    n_req = getattr(args, "n_requests", 4) or 4
    for i in range(n_req):
        ids = jnp.asarray([[1 + (i % 3), 2, 3, 4, 5, 6, 7, 8]], jnp.int32)
        eng.submit(f"r{i}", ids, 6)
    eng.run()
    return 0, eng.reqlog.snapshot()


def cmd_slo(args) -> int:
    """SLO report + gate over a request log (``--requests``: a
    ``dls.requests/1`` snapshot, a flight dump, or a decode-bench
    artifact with a paged leg) or a fresh live paged-decode run.  Exit 0
    when every window meets the policy, 1 on breach (the worst window
    and metric are named on stderr), 2 on malformed/empty request logs
    or an unrunnable configuration."""
    from .obs import FlightRecorder, SLOPolicy, evaluate_slo
    from .obs import reqlog as _reqlog

    try:
        policy = SLOPolicy(
            ttft_s=args.ttft, tpot_s=args.tpot, e2e_s=args.e2e,
            window_s=args.window, percentile=args.percentile,
        )
    except ValueError as e:
        print(f"slo: {e} (pass --ttft/--tpot/--e2e)", file=sys.stderr)
        return 2

    flight = None
    if args.requests:
        try:
            with open(args.requests) as f:
                obj = json.load(f)
        except (OSError, ValueError) as e:
            print(f"slo: unreadable request log {args.requests}: {e}",
                  file=sys.stderr)
            return 2
        if not isinstance(obj, dict):
            print(f"slo: {args.requests} is not a JSON object",
                  file=sys.stderr)
            return 2
        if obj.get("schema") == _reqlog.SCHEMA:
            snap = obj
        elif isinstance(obj.get("request_log"), dict):
            snap = obj["request_log"]       # a flight-recorder dump
        elif (isinstance(obj.get("paged"), dict)
              and isinstance(obj["paged"].get("requests"), dict)):
            snap = obj["paged"]["requests"]  # a decode-bench artifact
        else:
            print(f"slo: no dls.requests/1 block found in {args.requests}",
                  file=sys.stderr)
            return 2
    else:
        flight = FlightRecorder()
        rc, snap = _slo_live_requests(args, flight)
        if rc:
            return rc

    errs = _reqlog.validate_request_log(snap)
    if errs:
        for e in errs[:10]:
            print(f"slo: {e}", file=sys.stderr)
        return 2
    if not snap.get("requests"):
        print("slo: request log is empty", file=sys.stderr)
        return 2

    report = evaluate_slo(snap, policy)
    out = {
        "requests": _reqlog.summarize_request_log(snap),
        "slo": report.summary(),
    }
    if report.exceeds() and flight is not None and args.flight_dir:
        from .obs.export import validate_trace

        rec = flight.maybe_dump(args.flight_dir, slo_report=report)
        out["flight_dump"] = dict(
            rec, trace_valid=validate_trace(rec["trace"]) == []
        )
    print(json.dumps(out, indent=1))
    if report.exceeds():
        b = report.worst_breach()
        print(
            f"slo: {b['metric']} {b['percentile']}={b['value']:.6g}s "
            f"exceeds target {b['target']:.6g}s in window {b['window']} "
            f"[{b['t_start']:.3f}s, {b['t_end']:.3f}s)", file=sys.stderr,
        )
        return 1
    return 0


def cmd_serve(args) -> int:
    """Online serving run: open-loop arrivals (seeded Poisson or a
    ``dls.arrivals/1`` trace) through the event-loop front-end over the
    paged decode engine on a virtual clock — SLO-aware admission and
    priority preemption when ``--admission slo`` (the default).  Exit 0
    when the run meets the policy, 1 on SLO breach (flight rings dumped
    to --flight-dir when given), 2 on malformed traces / policies /
    configurations."""
    from .obs import FlightRecorder, SLOPolicy
    from .serve import (
        ServiceTimeModel,
        ServingFrontend,
        VirtualClock,
        load_trace,
        poisson_arrivals,
        save_trace,
    )

    try:
        policy = SLOPolicy(
            ttft_s=args.ttft, tpot_s=args.tpot, e2e_s=args.e2e,
            window_s=args.window, percentile=args.percentile,
        )
    except ValueError as e:
        print(f"serve: {e} (pass --ttft/--tpot/--e2e)", file=sys.stderr)
        return 2
    if args.admission == "slo" and policy.ttft_s is None:
        print("serve: slo admission needs a --ttft target",
              file=sys.stderr)
        return 2

    if args.trace:
        try:
            arrivals = load_trace(args.trace)
        except (OSError, ValueError) as e:
            print(f"serve: {e}", file=sys.stderr)
            return 2
    else:
        try:
            arrivals = poisson_arrivals(
                args.rate, args.n_requests, args.seed,
                prompt_lens=(8, 16), max_new_tokens=(8, 16),
                priorities=(0, 1), priority_weights=(0.3, 0.7),
            )
        except ValueError as e:
            print(f"serve: {e}", file=sys.stderr)
            return 2
    if args.save_trace:
        save_trace(arrivals, args.save_trace)
        print(f"serve: trace -> {args.save_trace}", file=sys.stderr)

    cfg = _config_from(args)
    if _weights_family(cfg.model) != "gpt2":
        print("serve: needs a gpt2-family model (paged decode)",
              file=sys.stderr)
        return 2
    slots, ps, n_pages, ppseq = 4, 8, 13, 4
    too_big = [a.rid for a in arrivals
               if a.prompt_len + a.max_new_tokens > ppseq * ps]
    if too_big:
        print(f"serve: {len(too_big)} arrival(s) exceed the per-request "
              f"KV capacity of {ppseq * ps} tokens (first: "
              f"{too_big[0]!r})", file=sys.stderr)
        return 2

    import jax

    from .backends.device import DeviceBackend
    from .core.cluster import Cluster
    from .frontend.decode_dag import build_paged_decode_dag
    from .models.kv_pages import PagePool

    clock = VirtualClock()
    flight = FlightRecorder(clock=clock)
    mcfg = cfg.model_config()
    ddag = build_paged_decode_dag(
        mcfg, slots=slots, page_size=ps, n_pages=n_pages,
        pages_per_seq=ppseq, attention_impl=args.attention_impl,
    )
    params = ddag.init_params()
    weights = {k: v for k, v in params.items()
               if not (k.startswith("cache_") or k == "page_table")}
    dcluster = Cluster.from_jax_devices(jax.devices()[:1])
    pool = PagePool(n_pages=n_pages, page_size=ps)
    eng = DeviceBackend(dcluster).paged_decode_engine(
        ddag.graph, cfg.build_scheduler().schedule(ddag.graph, dcluster),
        mcfg, weights, pool, slots=slots, pages_per_seq=ppseq,
        seg_steps=4, clock=clock, flight=flight,
        attention_impl=args.attention_impl,
        chunk_tokens=args.chunk_tokens,
    )
    fe = ServingFrontend(
        eng, arrivals, policy, admission=args.admission,
        preemption=not args.no_preempt,
        time_model=ServiceTimeModel(),
    )
    report = fe.run()

    out = {k: v for k, v in report.items() if k != "requests"}
    if report["breached"] and args.flight_dir:
        from .obs.export import validate_trace

        rec = flight.maybe_dump(args.flight_dir,
                                slo_report=fe.slo_report)
        out["flight_dump"] = dict(
            rec, trace_valid=validate_trace(rec["trace"]) == []
        )
    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=1, sort_keys=True)
        print(f"serve: report -> {args.out}", file=sys.stderr)
    print(json.dumps(out, indent=1, sort_keys=True))
    if report["breached"]:
        b = fe.slo_report.worst_breach()
        print(
            f"serve: {b['metric']} {b['percentile']}={b['value']:.6g}s "
            f"exceeds target {b['target']:.6g}s in window {b['window']} "
            f"[{b['t_start']:.3f}s, {b['t_end']:.3f}s)", file=sys.stderr,
        )
        return 1
    return 0


def cmd_soak(args) -> int:
    """Duration-bounded serving soak with health gating: sustained
    seeded Poisson load over the paged decode engine (virtual time by
    default; ``--real-clock`` serves wall-clock arrivals), sampled every
    ``--sample-every`` seconds into the bounded time-series store and
    gated by the leak/degradation detector battery (HLT001–HLT006)
    after ``--warmup`` exclusion.  Exit 0 healthy (schema-valid
    ``dls.soak/1`` artifact), 1 on a detector breach (the worst
    series+slope named on stderr; flight rings dumped to --flight-dir),
    2 on a malformed config or artifact.  ``--inject-leak`` /
    ``--inject-jit-churn`` are the test/CI fault injectors."""
    from .serve.soak import SoakConfig, run_soak, validate_soak_artifact

    try:
        cfg = SoakConfig(
            duration_s=args.duration, sample_every_s=args.sample_every,
            warmup_s=args.warmup, rate_rps=args.rate, seed=args.seed,
            admission=args.admission, ttft_s=args.ttft,
            window_s=args.window, percentile=args.percentile,
            capacity=args.capacity, real_clock=args.real_clock,
            attention_impl=args.attention_impl,
            chunk_tokens=args.chunk_tokens,
        )
        cfg.validate()
        if args.inject_leak is not None and args.inject_leak < 1:
            raise ValueError(
                f"--inject-leak must be >= 1, got {args.inject_leak}"
            )
    except ValueError as e:
        print(f"soak: {e}", file=sys.stderr)
        return 2
    art = run_soak(
        cfg, flight_dir=args.flight_dir,
        inject_leak_every=args.inject_leak,
        inject_churn=args.inject_jit_churn,
    )
    errs = validate_soak_artifact(art)
    if errs:
        for e in errs[:10]:
            print(f"soak: artifact invalid: {e}", file=sys.stderr)
        return 2
    if art["flight_dumps"]:
        from .obs.export import validate_trace

        for rec in art["flight_dumps"]:
            rec["trace_valid"] = validate_trace(rec["trace"]) == []
    if args.out:
        with open(args.out, "w") as f:
            json.dump(art, f, indent=1, sort_keys=True)
        print(f"soak: artifact -> {args.out}", file=sys.stderr)
    print(json.dumps(
        {k: v for k, v in art.items() if k != "timeseries"},
        indent=1, sort_keys=True,
    ))
    if art["verdict"] == "breach":
        worst = max(
            (f for f in art["health"]["findings"]
             if f["severity"] == "error" and f["slope"] is not None),
            key=lambda f: abs(f["slope"]) / f["threshold"],
        )
        print(
            f"soak: {worst['code']} {worst['detector']}: "
            f"{worst['series']} slope {worst['slope']:+.6g}/s exceeds "
            f"{worst['threshold']:g}/s past warmup "
            f"({art['config']['warmup_s']:g}s)", file=sys.stderr,
        )
        return 1
    steady = art["steady_state"]
    print(
        f"soak: healthy — {art['soak.goodput_tok_s']:.1f} tok/s steady "
        f"state over {steady['span_s']:.2f}s "
        f"({art['clock']} clock, {art['serving']['completed']} completed, "
        f"{art['serving']['pages_leaked']} pages leaked)",
        file=sys.stderr,
    )
    return 0


def cmd_doctor(args) -> int:
    """Run doctor: measured critical-path attribution (+ cost-model
    drift when the run is live).  ``--trace`` diagnoses an exported
    trace JSON offline; without it, one profiled ``DeviceBackend``
    execute of the model DAG is attributed directly.  Exit 2 when
    nothing is attributable, 1 when drift exceeds ``--drift-threshold``,
    0 otherwise.

    ``--slo`` switches to the SLO doctor: one flight-recorded paged
    decode leg, the sliding-window report for the ``--slo-*`` targets,
    exit 1 on breach.

    ``--memory`` switches to the MEMORY doctor: one memprof-instrumented
    execute (the default planned path — no per-task profile fences
    needed), printing the per-device HBM timelines/watermarks
    (``memory``) and the measured-vs-predicted peak comparison
    (``mem_drift``).  Exit 2 when nothing was recorded or the timeline
    invariant fails, 1 when any device's two-sided drift ratio exceeds
    ``--mem-drift-threshold``, 0 otherwise.

    ``--requests`` switches to the REQUEST doctor: per-request
    waterfall latency attribution with exact tiling and ranked
    aggressor→victim interference pairs, live (bare flag) or offline
    over a saved serve artifact / flight dump / request log.  Exit 1
    when a breaching request's dominant wait bucket exceeds
    ``--dominant-threshold``, 2 malformed.

    ``--fleet`` switches to the FLEET doctor: the per-replica health
    battery over a live chaos leg (bare flag) or a saved
    ``dls.fleet/1`` artifact, exit 1 when any replica currently
    breaches."""
    from .obs.attribution import attribute_run, attribute_trace

    if getattr(args, "memory", False):
        return _cmd_doctor_memory(args)
    if getattr(args, "slo", False):
        return _cmd_doctor_slo(args)
    if getattr(args, "soak", None):
        return _cmd_doctor_soak(args)
    if getattr(args, "fleet", None):
        return _cmd_doctor_fleet(args)
    if getattr(args, "serve", None):
        return _cmd_doctor_serve(args)
    if getattr(args, "requests", None):
        return _cmd_doctor_requests(args)
    if args.trace:
        try:
            att = attribute_trace(args.trace)
        except (OSError, ValueError) as e:
            print(f"doctor: unreadable trace {args.trace}: {e}",
                  file=sys.stderr)
            return 2
        if not att.critical_path:
            print("doctor: trace has no attributable device spans",
                  file=sys.stderr)
            return 2
        print(json.dumps({"attribution": att.summary()}, indent=1))
        return 0

    from .backends.device import DeviceBackend
    from .obs.drift import compute_drift
    from .obs.trace import Tracer

    cfg = _config_from(args)
    dag = cfg.build_graph()
    if not hasattr(dag, "graph"):
        print("doctor needs a model DAG (gpt2* / llama* / mixtral*) or "
              "an exported trace via --trace", file=sys.stderr)
        return 2
    cost_model = None
    if args.costmodel:
        from .utils.costmodel import CostModel

        try:
            cost_model = CostModel.load(args.costmodel)
        except (OSError, ValueError) as e:
            print(f"doctor: --costmodel {args.costmodel}: {e}",
                  file=sys.stderr)
            return 2
        # schedule against the predictions being audited, exactly like
        # a calibrated bench run would
        cost_model.apply(dag.graph)
    cluster = cfg.build_cluster_with_devices()
    schedule = cfg.build_scheduler().schedule(dag.graph, cluster)
    tracer = Tracer()
    DeviceBackend(cluster).execute(
        dag.graph, schedule, dag.init_params(), dag.make_inputs(),
        profile=True, trace=tracer,
    )
    att = attribute_run(tracer)
    drift = compute_drift(dag.graph, schedule, cost_model)
    print(json.dumps(
        {"attribution": att.summary(), "drift": drift.summary()},
        indent=1,
    ))
    if not att.critical_path:
        print("doctor: run produced no attributable device spans",
              file=sys.stderr)
        return 2
    if drift.exceeds(args.drift_threshold):
        print(f"doctor: worst per-task drift ratio "
              f"{drift.worst_ratio():.2f}x exceeds the "
              f"--drift-threshold {args.drift_threshold:g}x gate",
              file=sys.stderr)
        return 1
    return 0


def _cmd_doctor_memory(args) -> int:
    """The memory half of the doctor (``doctor --memory``)."""
    from .backends.device import DeviceBackend
    from .obs import MemoryProfiler, compute_mem_drift
    from .obs.trace import Tracer

    cfg = _config_from(args)
    dag = cfg.build_graph()
    if not hasattr(dag, "graph"):
        print("doctor --memory needs a model DAG (gpt2* / llama* / "
              "mixtral*); synthetic graphs have no fns", file=sys.stderr)
        return 2
    cluster = cfg.build_cluster_with_devices()
    schedule = cfg.build_scheduler().schedule(dag.graph, cluster)
    tracer = Tracer()
    mem = MemoryProfiler(tracer=tracer)
    DeviceBackend(cluster).execute(
        dag.graph, schedule, dag.init_params(), dag.make_inputs(),
        trace=tracer, memprof=mem,
    )
    if not len(mem):
        print("doctor: run recorded no memory events", file=sys.stderr)
        return 2
    errs = mem.verify()
    if errs:
        for e in errs[:10]:
            print(f"doctor: memory timeline invariant: {e}",
                  file=sys.stderr)
        return 2
    drift = compute_mem_drift(dag.graph, cluster, schedule, mem)
    print(json.dumps(
        {"memory": mem.summary(), "mem_drift": drift.summary()},
        indent=1,
    ))
    for w in drift.warnings:
        print(f"doctor: {w}", file=sys.stderr)
    if drift.exceeds(args.mem_drift_threshold):
        print(f"doctor: worst per-device memory drift ratio "
              f"{drift.worst_ratio():.2f}x exceeds the "
              f"--mem-drift-threshold {args.mem_drift_threshold:g}x gate",
              file=sys.stderr)
        return 1
    return 0


def _cmd_doctor_slo(args) -> int:
    """The SLO half of the doctor (``doctor --slo``)."""
    from .obs import FlightRecorder, SLOPolicy, evaluate_slo
    from .obs.reqlog import summarize_request_log

    try:
        policy = SLOPolicy(
            ttft_s=args.slo_ttft, tpot_s=args.slo_tpot,
            e2e_s=args.slo_e2e, window_s=args.slo_window,
        )
    except ValueError as e:
        print(f"doctor --slo: {e} (pass --slo-ttft/--slo-tpot/--slo-e2e)",
              file=sys.stderr)
        return 2
    flight = FlightRecorder()
    rc, snap = _slo_live_requests(args, flight)
    if rc:
        return rc
    if not snap.get("requests"):
        print("doctor --slo: run recorded no requests", file=sys.stderr)
        return 2
    report = evaluate_slo(snap, policy)
    print(json.dumps(
        {"requests": summarize_request_log(snap), "slo": report.summary()},
        indent=1,
    ))
    if report.exceeds():
        b = report.worst_breach()
        print(
            f"doctor: {b['metric']} {b['percentile']}={b['value']:.6g}s "
            f"exceeds the --slo target {b['target']:.6g}s in window "
            f"{b['window']}", file=sys.stderr,
        )
        return 1
    return 0


def _cmd_doctor_soak(args) -> int:
    """The soak half of the doctor (``doctor --soak SOAK_JSON``):
    re-gate a saved ``dls.soak/1`` artifact offline by rebuilding the
    time-series store from its embedded snapshot and re-running the
    default detector battery.  Exit 2 malformed, 1 on breach, 0
    healthy."""
    from .obs.health import report_from_soak_artifact
    from .serve.soak import load_soak_artifact

    try:
        art = load_soak_artifact(args.soak)
        report = report_from_soak_artifact(art)
    except (OSError, ValueError) as e:
        print(f"doctor --soak: {e}", file=sys.stderr)
        return 2
    print(json.dumps(
        {
            "soak": {
                "clock": art["clock"],
                "verdict_recorded": art["verdict"],
                "steady_state": art["steady_state"],
                "injection": art.get("injection", {}),
            },
            "health": report.to_json(),
        },
        indent=1,
    ))
    if report.exceeds():
        w = report.worst_breach()
        print(
            f"doctor: {w.code} {w.detector}: {w.series} slope "
            f"{w.slope:+.6g}/s exceeds {w.threshold:g}/s past warmup "
            f"({report.warmup_s:g}s)", file=sys.stderr,
        )
        return 1
    return 0


def _cmd_doctor_fleet(args) -> int:
    """The fleet doctor (``doctor --fleet [live|ART_JSON]``): gate a
    replica fleet on the per-replica health battery.

    ``live`` (the default when the flag is bare) runs the serve-bench
    fleet chaos leg — N=3 replicas on the lockstep virtual clock, the
    page leak injected on one, scored routing + the HLT001 battery —
    and gates the resulting :class:`~.obs.fleet.FleetHealthReport`.  A
    healed breach (drained, restarted, readmitted) lives in the event
    history, not the current findings, so a fleet that failed over
    cleanly exits 0.  A path re-gates a saved ``dls.fleet/1`` artifact
    (or a bare ``dls.fleet-health/1`` block) offline.  Exit 2
    malformed, 1 when any replica currently breaches, 0 healthy."""
    from .obs.fleet import report_from_fleet_artifact

    if args.fleet == "live":
        from .eval import serve_bench
        from .obs.fleet import fleet_detectors
        from .obs.slo import SLOPolicy
        from .serve.frontend import ServiceTimeModel
        from .serve.loadgen import poisson_arrivals

        sc = dict(serve_bench.SCENARIO, **serve_bench.FLEET_SCENARIO)
        arrivals = poisson_arrivals(
            sc["fleet_rate_rps"], sc["fleet_n_requests"], args.seed or 7,
            prompt_lens=sc["prompt_lens"],
            max_new_tokens=sc["max_new_tokens"],
            priorities=sc["priorities"],
            priority_weights=sc["priority_weights"],
        )
        policy = SLOPolicy(
            ttft_s=sc["ttft_s"], window_s=sc["window_s"],
            percentile=sc["percentile"],
        )
        tm = ServiceTimeModel(
            wave_s=sc["wave_s"], segment_s=sc["segment_s"],
            idle_s=sc["idle_s"],
        )
        leg = serve_bench.run_fleet_leg(
            arrivals, policy, tm, sc, routing="score",
            detectors=fleet_detectors(), leak=True,
        )
        obj = {"fleet_health": leg["fleet_health"]}
        context = {
            "mode": "live",
            "goodput_tok_s": leg["goodput_tok_s"],
            "drains": leg["drains"],
            "restarts": leg["restarts"],
            "migrations": leg["migrations"],
            "pages_leaked": leg["pages_leaked"],
        }
    else:
        try:
            with open(args.fleet) as f:
                obj = json.load(f)
        except (OSError, ValueError) as e:
            print(f"doctor --fleet: {e}", file=sys.stderr)
            return 2
        context = {"mode": "offline", "path": args.fleet}
        if isinstance(obj, dict):
            context["schema"] = obj.get("schema")
    try:
        report = report_from_fleet_artifact(obj)
    except ValueError as e:
        print(f"doctor --fleet: {e}", file=sys.stderr)
        return 2
    print(json.dumps(
        {"fleet": context, "fleet_health": report.to_json()},
        indent=1,
    ))
    if report.exceeds():
        rid, w = report.worst_breach() or report.breaches()[0]
        print(
            f"doctor: replica {rid}: {w.code} {w.detector}: {w.series} "
            f"slope {w.slope:+.6g}/s exceeds {w.threshold:g}/s",
            file=sys.stderr,
        )
        return 1
    n = len(report.replicas)
    print(
        f"fleet: healthy — {n} replicas, {report.drains()} drains, "
        f"{report.restarts()} restarts on record", file=sys.stderr,
    )
    return 0


def _cmd_doctor_serve(args) -> int:
    """The serving-safety half of the doctor (``doctor --serve
    ART_JSON``): re-gate a committed ``dls.serve/1`` or ``dls.soak/1``
    artifact offline through the page-lifetime and request-lifecycle
    passes — leaked-page gauges become PGL001 errors, embedded
    ownership-event streams are replayed page by page, and per-request
    rows are protocol-checked.  Exit 2 malformed/unknown schema, 1 when
    any pass errors, 0 clean — mirroring ``doctor --soak``."""
    from .analysis import analyze_serve_artifact
    from .eval.serve_bench import validate_serve_artifact
    from .serve.soak import validate_soak_artifact

    try:
        with open(args.serve) as f:
            art = json.load(f)
    except (OSError, ValueError) as e:
        print(f"doctor --serve: {e}", file=sys.stderr)
        return 2
    schema = art.get("schema") if isinstance(art, dict) else None
    if schema == "dls.serve/1":
        problems = validate_serve_artifact(art)
    elif schema == "dls.soak/1":
        problems = validate_soak_artifact(art)
    else:
        print(f"doctor --serve: unknown artifact schema {schema!r} "
              "(want dls.serve/1 or dls.soak/1)", file=sys.stderr)
        return 2
    if problems:
        for p in problems:
            print(f"doctor --serve: {p}", file=sys.stderr)
        return 2
    try:
        rep = analyze_serve_artifact(art).dedupe()
    except ValueError as e:
        print(f"doctor --serve: {e}", file=sys.stderr)
        return 2
    print(json.dumps(
        {
            "serve": {
                "schema": schema,
                "seed": art.get("seed"),
                "clock": art.get("clock"),
            },
            "lint": rep.to_json(),
        },
        indent=1,
    ))
    if rep.errors:
        d = rep.errors[0]
        print(f"doctor: {d.code}: {d.message}", file=sys.stderr)
        return 1
    return 0


def _cmd_doctor_requests(args) -> int:
    """The request doctor (``doctor --requests [live|ART_JSON]``):
    per-request waterfall attribution — each request's e2e decomposed
    into the eight interference buckets (exact tiling to 1e-9) with the
    ranked aggressor→victim pairs.

    ``live`` (the default when the flag is bare) serves the serve-bench
    overload scenario on a virtual clock with the waterfall recorder
    wired, so the attribution runs span-exact.  A path re-gates a saved
    artifact offline: a ``dls.serve/1`` artifact (each leg's rows), a
    flight-recorder dump (its ``request_log``; pass the matching
    ``flight_trace.json`` via ``--requests-trace`` to upgrade rows-only
    to span attribution), or a bare ``dls.requests/1`` snapshot.  Exit 2
    malformed/empty, 1 when a breaching request's dominant wait bucket
    exceeds ``--dominant-threshold``, 0 otherwise."""
    from .obs.interference import attribute_requests, events_from_perfetto

    events = None
    if getattr(args, "requests_trace", None):
        try:
            with open(args.requests_trace) as f:
                events = events_from_perfetto(json.load(f))
        except (OSError, ValueError) as e:
            print(f"doctor --requests-trace: {e}", file=sys.stderr)
            return 2
    ttft_target = getattr(args, "slo_ttft", None)
    threshold = getattr(args, "dominant_threshold", 0.5)

    legs = {}
    if args.requests == "live":
        from .eval import serve_bench
        from .obs.slo import SLOPolicy
        from .obs.trace import Tracer
        from .serve.frontend import (
            ServiceTimeModel,
            ServingFrontend,
            VirtualClock,
        )
        from .serve.loadgen import poisson_arrivals

        sc = serve_bench.SCENARIO
        clock = VirtualClock()
        eng, _pool = serve_bench.build_serve_engine(
            slots=sc["slots"], page_size=sc["page_size"],
            n_pages=sc["n_pages"], pages_per_seq=sc["pages_per_seq"],
            seg_steps=sc["seg_steps"], clock=clock,
        )
        eng.rebind_obs(clock=clock, tracer=Tracer(clock=clock))
        arrivals = poisson_arrivals(
            sc["rate_rps"], sc["n_requests"], args.seed or 7,
            prompt_lens=sc["prompt_lens"],
            max_new_tokens=sc["max_new_tokens"],
            priorities=sc["priorities"],
            priority_weights=sc["priority_weights"],
        )
        policy = SLOPolicy(
            ttft_s=sc["ttft_s"], window_s=sc["window_s"],
            percentile=sc["percentile"],
        )
        tm = ServiceTimeModel(
            wave_s=sc["wave_s"], segment_s=sc["segment_s"],
            idle_s=sc["idle_s"],
        )
        fe = ServingFrontend(
            eng, arrivals, policy, admission="slo", preemption=True,
            time_model=tm,
        )
        rep = fe.run()
        if ttft_target is None:
            ttft_target = sc["ttft_s"]
        legs["live"] = (rep["requests"], list(eng.tracer.events))
    else:
        try:
            with open(args.requests) as f:
                obj = json.load(f)
        except (OSError, ValueError) as e:
            print(f"doctor --requests: {e}", file=sys.stderr)
            return 2
        if not isinstance(obj, dict):
            print(f"doctor --requests: {args.requests} is not a JSON "
                  "object", file=sys.stderr)
            return 2
        schema = obj.get("schema")
        if schema == "dls.serve/1":
            if ttft_target is None:
                ttft_target = (obj.get("policy") or {}).get("ttft_s")
            for name, leg in (obj.get("legs") or {}).items():
                rows = leg.get("requests")
                if rows:
                    legs[name] = (rows, events)
        elif isinstance(obj.get("request_log"), dict):
            legs["flight"] = (
                obj["request_log"].get("requests") or [], events
            )
        elif schema == "dls.requests/1":
            legs["requests"] = (obj.get("requests") or [], events)
        else:
            print(f"doctor --requests: no request rows in "
                  f"{args.requests} (want dls.serve/1, a flight dump, "
                  "or dls.requests/1)", file=sys.stderr)
            return 2
    reports = {
        name: attribute_requests(
            rows, events=evs, ttft_target_s=ttft_target,
            threshold=threshold,
        )
        for name, (rows, evs) in legs.items()
    }
    print(json.dumps(
        {"interference": {
            name: r.summary() for name, r in reports.items()
        }},
        indent=1, sort_keys=True,
    ))
    if not any(r.n_attributed for r in reports.values()):
        print("doctor --requests: no attributable requests "
              "(every row lacks a terminal timestamp)", file=sys.stderr)
        return 2
    for name, r in sorted(reports.items()):
        if r.exceeds():
            f0 = r.findings[0]
            agg = f0.get("top_aggressor")
            print(
                f"doctor: [{name}] request {f0['rid']} breached "
                f"ttft {f0['ttft_s']:.6g}s > {ttft_target:.6g}s with "
                f"{f0['dominant']} = {f0['dominant_frac']:.0%} of e2e"
                + (f" (top aggressor: {agg})" if agg else ""),
                file=sys.stderr,
            )
            return 1
    return 0


def cmd_metrics_diff(args) -> int:
    """``metrics diff A B``: counter/gauge deltas and histogram quantile
    shifts between two ``dls.metrics/1`` snapshots — or, with
    ``--at I --vs J``, between two sample indices of ONE
    ``dls.timeseries/1`` file (a ``dls.soak/1`` artifact's embedded
    series also works), so start-of-soak vs end-of-soak diffs need no
    hand-edited JSON.  Exit 2 on an unreadable file, schema mismatch, or
    an index no series can satisfy."""
    from .obs.metrics import diff_snapshots

    if args.at is not None or args.vs is not None:
        if args.at is None or args.vs is None:
            print("metrics diff: --at and --vs go together",
                  file=sys.stderr)
            return 2
        if args.snapshot_b is not None:
            print("metrics diff: --at/--vs index ONE timeseries file, "
                  "not two snapshots", file=sys.stderr)
            return 2
        from .obs.timeseries import snapshot_at

        try:
            with open(args.snapshot_a) as f:
                obj = json.load(f)
        except (OSError, ValueError) as e:
            print(f"metrics diff: unreadable timeseries "
                  f"{args.snapshot_a}: {e}", file=sys.stderr)
            return 2
        if isinstance(obj, dict) and "timeseries" in obj:
            obj = obj["timeseries"]     # a dls.soak/1 artifact
        try:
            snaps = [snapshot_at(obj, args.at), snapshot_at(obj, args.vs)]
        except ValueError as e:
            print(f"metrics diff: {e}", file=sys.stderr)
            return 2
        if not snaps[0]["gauges"] or not snaps[1]["gauges"]:
            which = args.at if not snaps[0]["gauges"] else args.vs
            print(f"metrics diff: no series holds sample index {which}",
                  file=sys.stderr)
            return 2
    else:
        if args.snapshot_b is None:
            print("metrics diff: need two snapshot files (or --at/--vs "
                  "over one timeseries)", file=sys.stderr)
            return 2
        snaps = []
        for path in (args.snapshot_a, args.snapshot_b):
            try:
                with open(path) as f:
                    snaps.append(json.load(f))
            except (OSError, ValueError) as e:
                print(f"metrics diff: unreadable snapshot {path}: {e}",
                      file=sys.stderr)
                return 2
    try:
        diff = diff_snapshots(*snaps)
    except ValueError as e:
        print(f"metrics diff: {e}", file=sys.stderr)
        return 2
    print(json.dumps(diff, indent=1))
    return 0


def cmd_regress(args) -> int:
    """Compare a fresh bench artifact against a committed baseline;
    exit with the verdict (non-zero on any regressed/missing metric)."""
    from .eval.regress import compare_artifacts, parse_tolerances

    try:
        tolerances = parse_tolerances(args.tolerance or [])
    except ValueError as e:
        print(f"regress: {e}", file=sys.stderr)
        return 2
    metrics = None
    if args.metrics:
        metrics = [m.strip() for m in args.metrics.split(",") if m.strip()]
    try:
        verdict = compare_artifacts(
            args.fresh, args.baseline,
            tolerances=tolerances, metrics=metrics,
            default_tolerance=args.default_tolerance,
        )
    except (OSError, ValueError) as e:
        print(f"regress: {e}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(verdict.to_json(), indent=1))
    else:
        print(verdict.render())
    return verdict.exit_code


def cmd_bench(args) -> int:
    import importlib.util
    import os

    path = os.path.abspath(
        os.path.join(os.path.dirname(__file__), "..", "bench.py")
    )
    if not os.path.exists(path):
        print("bench.py not found (the benchmark runs from a source "
              "checkout, not an installed package)", file=sys.stderr)
        return 2
    spec = importlib.util.spec_from_file_location("bench", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    # explicit config: this process's sys.argv holds the CLI's own args
    # ('bench'), which bench.main() must not parse as a config name
    mod.main(args.config)
    return 0


def main(argv=None) -> int:
    # DLS_PLATFORM / DLS_FORCE_CPU are applied by the package __init__,
    # which python -m imports before this function runs.
    ap = argparse.ArgumentParser(
        prog="distributed_llm_scheduler_tpu",
        description="TPU-native memory-constrained DAG scheduling for LLMs",
    )
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("schedule", help="place a DAG and report metrics")
    _add_common(p)
    p.add_argument("--trace", default=None,
                   help="write the replay timeline as a Chrome/Perfetto "
                        "trace JSON to this path")
    p.add_argument("--save", action="store_true", help="save graph+schedule JSON")
    p.add_argument("--validate", action="store_true",
                   help="run the independent schedule checker (exit 2 on violations)")
    p.set_defaults(fn=cmd_schedule)

    p = sub.add_parser(
        "lint",
        help="static analysis: lint a DAG + schedule + sharding config "
             "without executing (exit 1 on errors)",
    )
    _add_common(p)
    p.add_argument("--parallel", action="store_true",
                   help="sweep the hand-written parallel layer instead of "
                        "a DAG: trace every registered entry point "
                        "(parallel/*) and check collective ordering "
                        "(COL003/COL004/COL008) plus the MPMD "
                        "happens-before self-check (COL005-COL007)")
    p.add_argument("--serving", action="store_true",
                   help="run the serving-safety passes instead of a DAG: "
                        "page-lifetime prover (PGL00x) over an "
                        "ownership-instrumented serve_bench scenario, "
                        "request-lifecycle checker (LCY00x) over frontend "
                        "+ engine logs, repo-wide determinism lint "
                        "(DET00x)")
    p.add_argument("--prefix", action="store_true",
                   help="with --serving: serve the shared-prefix session "
                        "workload on a sharing-enabled engine so the "
                        "prover replays the ref-counted "
                        "share/unshare/cow/write lattice "
                        "(PGL006/PGL007)")
    p.add_argument("--inject-leak", type=int, default=None,
                   dest="inject_leak", metavar="N",
                   help="with --serving: withhold one page from every "
                        "Nth free (the leaky-pool fault injector) — the "
                        "prover must exit 1 naming PGL001")
    p.add_argument("--inject-underflow", action="store_true",
                   dest="inject_underflow",
                   help="with --serving --prefix: lose one reference per "
                        "share (the refcount-underflow fault injector) — "
                        "the prover must exit 1 naming PGL006")
    p.add_argument("--decode", action="store_true",
                   help="lint the single-token decode-step DAG instead of "
                        "the full forward")
    p.add_argument("--paged", action="store_true",
                   help="lint the paged KV-cache decode-step DAG "
                        "(--batch sets the slot count; gpt2 family only)")
    p.add_argument("--page-size", type=int, default=16,
                   help="rows per KV page for --paged (default 16); "
                        "DEC005 warns when the geometry makes the fused "
                        "Pallas kernel ineligible (gather fallback)")
    p.add_argument("--chunk-tokens", type=int, default=None,
                   dest="chunk_tokens", metavar="N",
                   help="with --paged: also lint the chunked-prefill "
                        "chunk size (DEC006 warns when the ragged "
                        "multi-token-q kernel is ineligible at this "
                        "size, or when one chunk exceeds the "
                        "slots*seg-steps per-segment prefill budget)")
    p.add_argument("--seg-steps", type=int, default=8,
                   dest="seg_steps", metavar="K",
                   help="decode steps per segment for the DEC006 budget "
                        "check (default 8, the engine default; --batch "
                        "sets the slot count)")
    p.add_argument("--fix", action="store_true",
                   help="apply mechanical fixes before linting "
                        "(DAG003 duplicate-dependency dedup keeping the "
                        "original call arity; SCH005/PIP001 per-node "
                        "order re-sort when a legal topological order "
                        "exists)")
    p.add_argument("--preflight", action="store_true",
                   help="also run the XLA compiled-memory preflight and "
                        "flag tasks whose analytic estimate diverges >2x "
                        "from it (CST00x warnings; model DAGs only)")
    p.add_argument("--strict", action="store_true",
                   help="treat eviction-required residency (MEM002) as an "
                        "error")
    p.add_argument("--verbose", action="store_true",
                   help="also print info-level diagnostics (per-node peak "
                        "residency)")
    p.add_argument("--json", action="store_true",
                   help="emit the report as one machine-readable JSON "
                        "object (schema dls.lint/1) on stdout instead of "
                        "rendered text; exit codes unchanged")
    p.set_defaults(fn=cmd_lint)

    p = sub.add_parser("sweep", help="full evaluation sweep (CSV+PNG)")
    _add_common(p)
    p.add_argument("--num-runs", type=int, default=3)
    p.set_defaults(fn=cmd_sweep)

    p = sub.add_parser("execute", help="run a scheduled DAG on live devices")
    _add_common(p)
    p.add_argument("--profile", action="store_true")
    p.add_argument("--segments", action="store_true",
                   help="fuse each device's contiguous scheduled run into "
                        "one XLA launch (incompatible with --profile)")
    p.add_argument("--trace", default=None,
                   help="write measured task timeline (needs --profile) as "
                        "a Chrome/Perfetto trace JSON to this path")
    p.add_argument("--stream-params", action="store_true",
                   dest="stream_params",
                   help="planned param streaming (prefetch + Belady "
                        "eviction) under each node's HBM budget — executes "
                        "models whose weights exceed the budget (bandwidth "
                        "for capacity); composes with --segments (one "
                        "batched load per fused program)")
    p.add_argument("--inject-failure", default=None, metavar="NODE[:FRAC]",
                   dest="inject_failure",
                   help="fault injection: kill NODE (id or index) after "
                        "FRAC (default 0.5) of the run, reschedule the "
                        "remainder on the survivors with retained outputs, "
                        "and verify the recovered result")
    p.add_argument("--weights", default=None,
                   help="torch state-dict file with pretrained GPT-2 / "
                        "Llama / Mixtral weights (HF layout); random "
                        "init when omitted")
    p.set_defaults(fn=cmd_execute)

    p = sub.add_parser("visualize", help="render DAG + Gantt PNGs")
    _add_common(p)
    p.add_argument("--detailed", action="store_true")
    p.add_argument("--show", action="store_true",
                   help="also open figures in a window (interactive analog "
                        "of the reference's visu menu)")
    p.add_argument("--menu", action="store_true",
                   help="stdin-driven menu loop: re-render DAG/Gantt, "
                        "switch policies, and print summaries without "
                        "re-running the CLI")
    p.add_argument("--from-trace", default=None, dest="from_trace",
                   metavar="TRACE_JSON",
                   help="render the gantt from an exported trace JSON "
                        "(measured spans from a DLS_TRACE=1 run) instead "
                        "of a simulated replay; critical-path spans get "
                        "a highlight edge")
    p.set_defaults(fn=cmd_visualize)

    p = sub.add_parser("train", help="run sharded training steps")
    _add_common(p)
    p.add_argument("--steps", type=int, default=3)
    p.add_argument("--pp", type=int, default=0,
                   help="N>0: pipeline-parallel training over N stage "
                        "devices (GPipe scan; microbatches default to N)")
    p.add_argument("--remat", action="store_true",
                   help="rematerialize transformer blocks in the backward "
                        "pass (jax.checkpoint): HBM for FLOPs")
    p.add_argument("--scan", action="store_true",
                   help="scan over stacked layers (lax.scan): one compiled "
                        "block regardless of depth")
    p.add_argument("--ckpt", default=None,
                   help="checkpoint directory: resumed from if it exists, "
                        "written (params + optimizer state + step) at the "
                        "end of the run")
    p.set_defaults(fn=cmd_train)

    p = sub.add_parser(
        "generate", help="autoregressive KV-cache decoding (one JSON line)"
    )
    p.add_argument("--model", default="gpt2-tiny",
                   help="gpt2[-medium|-tiny] | llama-8b|-tiny | "
                        "mixtral-8x7b|-tiny")
    p.add_argument("--prompt-ids", default="1,2,3", dest="prompt_ids",
                   help="comma-separated prompt token ids")
    p.add_argument("--max-new-tokens", type=int, default=16)
    p.add_argument("--temperature", type=float, default=0.0,
                   help="0 = greedy")
    p.add_argument("--top-k", type=int, default=0, dest="top_k")
    p.add_argument("--weights", default=None,
                   help="torch state-dict file with pretrained GPT-2 / "
                        "Llama / Mixtral weights (HF layout); random "
                        "init when omitted")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--kv-int8", action="store_true", dest="kv_int8",
                   help="store the KV cache as int8 with per-row scales "
                        "(models/decode.quantize_cache): ~2x fewer cache "
                        "bytes re-read per step; lossy (greedy tokens can "
                        "differ from the bf16-cache run)")
    p.add_argument("--quantize", default="none", choices=["none", "int8"],
                   help="int8 weights, dequantized on device inside the "
                        "jitted step: ~half the weight bytes re-read per "
                        "token; lossy like --kv-int8.  Whole-program path "
                        "uses the grouped+rowwise fidelity scheme; "
                        "--task-graph quantizes the placed weight tasks "
                        "(channel scheme, cache slabs stay fp)")
    p.add_argument("--task-graph", action="store_true", dest="task_graph",
                   help="generate through the scheduling layer: decode "
                        "steps as task DAGs (KV-cache slabs as placeable "
                        "params) placed by --scheduler and executed on "
                        "live devices; greedy sampling, all three "
                        "families. Position is a runtime input, so the "
                        "whole generation compiles two programs (prefill "
                        "+ decode step), independent of token count")
    # None defaults so flags passed WITHOUT --task-graph fail fast
    # (the whole-program path does no scheduling; silent acceptance
    # would be a dead-flag lie)
    p.add_argument("--scheduler", default=None)
    p.add_argument("--num-nodes", type=int, default=None)
    p.add_argument("--hbm-gb", type=float, default=None)
    p.add_argument("--loop-steps", type=int, default=None, dest="loop_steps",
                   help="with --task-graph: fold N decode steps into one "
                        "dispatched program (backends/decode_loop — "
                        "lax.scan over the scheduled step DAG, caches "
                        "donated), paying one host round-trip per N "
                        "tokens instead of per token; requires the "
                        "schedule to place on a single node")
    p.set_defaults(fn=cmd_generate)

    p = sub.add_parser("bench", help="north-star benchmark (one JSON line)")
    p.add_argument("config", nargs="?", default="small",
                   choices=("small", "medium"),
                   help="bench config: GPT-2 small (flagship, default) or "
                        "medium (BASELINE config #2)")
    p.set_defaults(fn=cmd_bench)

    p = sub.add_parser(
        "trace",
        help="run an observed execute (+ small paged-decode leg) and "
             "write one Perfetto-loadable trace JSON",
    )
    _add_common(p)
    p.add_argument("--out", default="trace.json",
                   help="output trace path (open at ui.perfetto.dev)")
    p.add_argument("--skip-decode", action="store_true", dest="skip_decode",
                   help="skip the paged continuous-batching decode leg "
                        "(its counter tracks and TTFT/TPOT samples)")
    p.set_defaults(fn=cmd_trace)

    p = sub.add_parser(
        "metrics",
        help="same observed run, print the metrics-registry snapshot "
             "(dls.metrics/1 JSON)",
    )
    _add_common(p)
    p.add_argument("--out", default=None,
                   help="also write the snapshot JSON to this path")
    p.add_argument("--skip-decode", action="store_true", dest="skip_decode",
                   help="skip the paged decode leg")
    p.set_defaults(fn=cmd_metrics)
    msub = p.add_subparsers(dest="metrics_cmd")
    pd = msub.add_parser(
        "diff",
        help="diff two dls.metrics/1 snapshot files: counter/gauge "
             "deltas + histogram p50/p95 shifts (exit 2 on schema "
             "mismatch); or with --at/--vs, diff two sample indices of "
             "one dls.timeseries/1 file (dls.soak/1 artifacts work too)",
    )
    pd.add_argument("snapshot_a",
                    help="before snapshot JSON (with --at/--vs: the "
                         "timeseries or soak-artifact JSON)")
    pd.add_argument("snapshot_b", nargs="?", default=None,
                    help="after snapshot JSON (omit with --at/--vs)")
    pd.add_argument("--at", type=int, default=None, metavar="INDEX",
                    help="'before' sample index into each series "
                         "(Python-style; negatives count from the end)")
    pd.add_argument("--vs", type=int, default=None, metavar="INDEX",
                    help="'after' sample index into each series")
    pd.set_defaults(fn=cmd_metrics_diff)

    p = sub.add_parser(
        "slo",
        help="sliding-window SLO report + gate (exit 1 on breach) over "
             "a request log or a fresh flight-recorded paged-decode run",
    )
    _add_common(p)
    p.add_argument("--requests", default=None, metavar="PATH",
                   help="offline mode: evaluate this dls.requests/1 "
                        "snapshot (also accepts a flight-recorder dump "
                        "or a decode-bench artifact with a paged leg) "
                        "instead of running live")
    p.add_argument("--ttft", type=float, default=None, metavar="SECONDS",
                   help="per-window TTFT target at --percentile")
    p.add_argument("--tpot", type=float, default=None, metavar="SECONDS",
                   help="per-window TPOT (inter-token) target")
    p.add_argument("--e2e", type=float, default=None, metavar="SECONDS",
                   help="per-window end-to-end latency target")
    p.add_argument("--window", type=float, default=1.0, metavar="SECONDS",
                   help="sliding wall-clock window size (default 1.0)")
    p.add_argument("--percentile", default="p95",
                   choices=("p50", "p95", "p99"),
                   help="which per-window quantile gates (default p95)")
    p.add_argument("--n-requests", type=int, default=4, dest="n_requests",
                   help="live mode: requests to submit over the 2-slot "
                        "engine (default 4)")
    p.add_argument("--flight-dir", default=None, dest="flight_dir",
                   metavar="DIR",
                   help="live mode: on breach, dump the flight-recorder "
                        "rings (Perfetto trace + request log) here")
    p.set_defaults(fn=cmd_slo)

    p = sub.add_parser(
        "serve",
        help="online serving run on a virtual clock: open-loop arrivals "
             "through the SLO-aware front-end over the paged decode "
             "engine (exit 1 on SLO breach, 2 on malformed input)",
    )
    _add_common(p)
    p.add_argument("--rate", type=float, default=40.0, metavar="RPS",
                   help="offered load for the seeded Poisson generator "
                        "(default 40.0 req/s; ignored with --trace)")
    p.add_argument("--requests", type=int, default=32, dest="n_requests",
                   help="number of arrivals to generate (default 32; "
                        "ignored with --trace)")
    p.add_argument("--trace", default=None, metavar="PATH",
                   help="replay this dls.arrivals/1 trace instead of "
                        "generating arrivals (malformed -> exit 2)")
    p.add_argument("--save-trace", default=None, dest="save_trace",
                   metavar="PATH",
                   help="write the arrival schedule as a dls.arrivals/1 "
                        "trace for exact replay")
    p.add_argument("--admission", default="slo", choices=("slo", "fifo"),
                   help="admission policy: slo (shed/defer low tiers on "
                        "TTFT-window breach; default) or fifo admit-all")
    p.add_argument("--no-preempt", action="store_true", dest="no_preempt",
                   help="disable priority preemption (slo admission only)")
    p.add_argument("--ttft", type=float, default=2.0, metavar="SECONDS",
                   help="per-window TTFT target at --percentile "
                        "(default 2.0)")
    p.add_argument("--tpot", type=float, default=None, metavar="SECONDS",
                   help="per-window TPOT (inter-token) target")
    p.add_argument("--e2e", type=float, default=None, metavar="SECONDS",
                   help="per-window end-to-end latency target")
    p.add_argument("--window", type=float, default=0.5, metavar="SECONDS",
                   help="sliding virtual-time window size (default 0.5)")
    p.add_argument("--percentile", default="p95",
                   choices=("p50", "p95", "p99"),
                   help="which per-window quantile gates (default p95)")
    p.add_argument("--flight-dir", default=None, dest="flight_dir",
                   metavar="DIR",
                   help="on breach, dump the flight-recorder rings "
                        "(Perfetto trace + request log) here")
    p.add_argument("--out", default=None, metavar="PATH",
                   help="write the full serving report (including "
                        "per-request rows) here")
    p.add_argument("--attention-impl", default=None, dest="attention_impl",
                   choices=("auto", "xla", "pallas", "pallas_interpret"),
                   help="paged attention implementation baked into the "
                        "engine (default: op-level auto — fused Pallas "
                        "kernel on TPU when eligible, XLA gather "
                        "otherwise)")
    p.add_argument("--chunk-tokens", type=int, default=None,
                   dest="chunk_tokens", metavar="N",
                   help="chunked prefill: prompts longer than N tokens "
                        "admit with first-chunk pages only and prefill "
                        "N tokens per segment fused into the decode "
                        "waves (default: whole-prompt admission; "
                        "greedy tokens are bitwise identical either "
                        "way)")
    p.set_defaults(fn=cmd_serve)

    p = sub.add_parser(
        "soak",
        help="duration-bounded serving soak with bounded time-series "
             "telemetry and trend health gating (exit 1 on "
             "leak/degradation breach, 2 on malformed input)",
    )
    _add_common(p)
    p.add_argument("--duration", type=float, default=4.0, metavar="SECONDS",
                   help="soak length in clock seconds (default 4.0)")
    p.add_argument("--sample-every", type=float, default=0.1,
                   dest="sample_every", metavar="SECONDS",
                   help="telemetry sampling cadence (default 0.1)")
    p.add_argument("--warmup", type=float, default=1.0, metavar="SECONDS",
                   help="prefix excluded from every trend (default 1.0)")
    p.add_argument("--rate", type=float, default=12.0, metavar="RPS",
                   help="sustained offered load for the seeded Poisson "
                        "generator (default 12.0 req/s)")
    p.add_argument("--admission", default="slo", choices=("slo", "fifo"),
                   help="front-end admission policy (default slo)")
    p.add_argument("--ttft", type=float, default=0.3, metavar="SECONDS",
                   help="admission TTFT target at --percentile "
                        "(default 0.3)")
    p.add_argument("--window", type=float, default=0.2, metavar="SECONDS",
                   help="admission sliding-window size (default 0.2)")
    p.add_argument("--percentile", default="p95",
                   choices=("p50", "p95", "p99"),
                   help="which per-window quantile gates admission "
                        "(default p95)")
    p.add_argument("--capacity", type=int, default=512,
                   help="per-series ring capacity; overflow decimates "
                        "2:1 (default 512)")
    p.add_argument("--real-clock", action="store_true", dest="real_clock",
                   help="run against the wall clock (monotonic time, "
                        "real idle sleeps) instead of the virtual clock")
    p.add_argument("--flight-dir", default=None, dest="flight_dir",
                   metavar="DIR",
                   help="on the first health breach, dump the flight-"
                        "recorder rings (Perfetto trace + request log) "
                        "here while the anomaly is still in them")
    p.add_argument("--out", default=None, metavar="PATH",
                   help="write the full dls.soak/1 artifact (including "
                        "the timeseries snapshot) here")
    p.add_argument("--inject-leak", type=int, default=None,
                   dest="inject_leak", metavar="N",
                   help="testing: withhold one page from every Nth "
                        "free() — must trip HLT001")
    p.add_argument("--inject-jit-churn", action="store_true",
                   dest="inject_jit_churn",
                   help="testing: plant a fresh prefill compile-cache "
                        "entry every segment — must trip HLT003")
    p.add_argument("--attention-impl", default=None, dest="attention_impl",
                   choices=("auto", "xla", "pallas", "pallas_interpret"),
                   help="paged attention implementation baked into the "
                        "engine (default: op-level auto)")
    p.add_argument("--chunk-tokens", type=int, default=None,
                   dest="chunk_tokens", metavar="N",
                   help="chunked prefill chunk size for the soak engine "
                        "(default: whole-prompt admission)")
    p.set_defaults(fn=cmd_soak)

    p = sub.add_parser(
        "doctor",
        help="explain a run: measured critical-path attribution "
             "(compute/transfer/dispatch/idle) + cost-model drift",
    )
    _add_common(p)
    p.add_argument("--trace", default=None, metavar="TRACE_JSON",
                   help="diagnose an exported trace JSON offline instead "
                        "of running a profiled execute")
    p.add_argument("--costmodel", default=None, metavar="PATH",
                   help="calibrated CostModel JSON (utils/costmodel "
                        "cache entry) to audit; defaults to the graph's "
                        "analytic compute_time estimates")
    p.add_argument("--drift-threshold", type=float, default=None,
                   dest="drift_threshold", metavar="RATIO",
                   help="exit 1 when any task's two-sided predicted-vs-"
                        "measured ratio max(r, 1/r) exceeds RATIO "
                        "(default: report only, never gate)")
    p.add_argument("--memory", action="store_true",
                   help="memory doctor: measured per-device HBM "
                        "timelines, watermark attribution, and "
                        "measured-vs-predicted peak drift instead of the "
                        "time doctor")
    p.add_argument("--mem-drift-threshold", type=float, default=None,
                   dest="mem_drift_threshold", metavar="RATIO",
                   help="with --memory: exit 1 when any device's "
                        "two-sided measured-vs-predicted peak ratio "
                        "max(r, 1/r) exceeds RATIO (default: report "
                        "only, never gate)")
    p.add_argument("--slo", action="store_true",
                   help="SLO doctor: one flight-recorded paged decode "
                        "leg, sliding-window report for the --slo-* "
                        "targets, exit 1 on breach")
    p.add_argument("--slo-ttft", type=float, default=None, dest="slo_ttft",
                   metavar="SECONDS", help="with --slo: TTFT target")
    p.add_argument("--slo-tpot", type=float, default=None, dest="slo_tpot",
                   metavar="SECONDS", help="with --slo: TPOT target")
    p.add_argument("--slo-e2e", type=float, default=None, dest="slo_e2e",
                   metavar="SECONDS", help="with --slo: e2e target")
    p.add_argument("--slo-window", type=float, default=1.0,
                   dest="slo_window", metavar="SECONDS",
                   help="with --slo: window size (default 1.0)")
    p.add_argument("--soak", default=None, metavar="SOAK_JSON",
                   help="soak doctor: re-gate a saved dls.soak/1 "
                        "artifact offline — rebuild its timeseries and "
                        "re-run the leak/degradation detector battery "
                        "(exit 1 on breach, 2 malformed)")
    p.add_argument("--fleet", nargs="?", const="live", default=None,
                   metavar="FLEET_JSON",
                   help="fleet doctor: gate the per-replica health "
                        "battery — bare flag runs the fleet chaos leg "
                        "live (leak injected, drain/restart must heal "
                        "it); a path re-gates a saved dls.fleet/1 "
                        "artifact or dls.fleet-health/1 block offline "
                        "(exit 1 when any replica currently breaches, "
                        "2 malformed)")
    p.add_argument("--serve", default=None, metavar="ART_JSON",
                   help="serving-safety doctor: re-gate a committed "
                        "dls.serve/1 or dls.soak/1 artifact offline "
                        "through the page-lifetime (PGL00x) and "
                        "request-lifecycle (LCY00x) passes (exit 1 on "
                        "findings, 2 malformed)")
    p.add_argument("--requests", nargs="?", const="live", default=None,
                   metavar="ART_JSON",
                   help="request doctor: per-request waterfall latency "
                        "attribution (exact bucket tiling + ranked "
                        "aggressor→victim pairs) — bare flag runs the "
                        "serve-bench scenario live with the waterfall "
                        "recorder; a path re-gates a dls.serve/1 "
                        "artifact, flight dump, or dls.requests/1 "
                        "snapshot offline (exit 1 when a breaching "
                        "request is wait-dominated, 2 malformed)")
    p.add_argument("--requests-trace", default=None, dest="requests_trace",
                   metavar="TRACE_JSON",
                   help="with --requests FLIGHT_DUMP: the matching "
                        "flight_trace.json, upgrading rows-only "
                        "attribution to span-exact")
    p.add_argument("--dominant-threshold", type=float, default=0.5,
                   dest="dominant_threshold", metavar="FRAC",
                   help="with --requests: exit 1 when a breaching "
                        "request's dominant wait bucket exceeds this "
                        "fraction of its e2e (default 0.5)")
    p.set_defaults(fn=cmd_doctor)

    p = sub.add_parser(
        "regress",
        help="perf-regression gate: fresh bench artifact vs committed "
             "baseline with per-metric tolerances (non-zero on regression)",
    )
    p.add_argument("--fresh", required=True,
                   help="freshly measured bench artifact JSON")
    p.add_argument("--baseline", required=True,
                   help="committed baseline artifact (e.g. "
                        "BENCH_MEDIUM_r05.json)")
    p.add_argument("--tolerance", action="append", default=None,
                   metavar="METRIC=FRAC",
                   help="per-metric relative tolerance (repeatable), "
                        "e.g. --tolerance value=0.25")
    p.add_argument("--default-tolerance", type=float, default=0.10,
                   dest="default_tolerance",
                   help="tolerance for metrics without an explicit "
                        "--tolerance (default 0.10)")
    p.add_argument("--metrics", default=None,
                   help="comma-separated metric names to check (default: "
                        "the quality set present in the baseline)")
    p.add_argument("--json", action="store_true",
                   help="print the structured verdict instead of the "
                        "table")
    p.set_defaults(fn=cmd_regress)

    p = sub.add_parser(
        "rankcheck",
        help="sim-vs-real policy rank agreement on live devices (JSON)",
    )
    _add_common(p)
    p.add_argument("--policies", default=None,
                   help="comma-separated policies to rank (default: "
                        "roundrobin,critical,pipeline,pack; --stress "
                        "defaults to all 8 distinct-tier policies)")
    p.add_argument("--measure-repeats", type=int, default=3)
    p.add_argument("--reps", type=int, default=1,
                   help="amortized repetitions per measured run")
    p.add_argument("--anchor-calibrate", action="store_true",
                   help="two-anchor in-situ calibration (busy-host "
                        "compute scale + dispatcher-blocking staging "
                        "rate) before predicting; anchors are in-sample, "
                        "other policies and the ordering out-of-sample "
                        "(eval/rankcheck.py)")
    p.add_argument("--stress", action="store_true",
                   help="use the transfer-stress DAG (frontend/stress_dag): "
                        "cheap compute, large cross-device activations — "
                        "the regime where the sim PREDICTS separation, so "
                        "rank agreement is asserted without the tie escape "
                        "(ignores --model; 4 devices, 8 policies unless "
                        "--policies given explicitly)")
    p.set_defaults(fn=cmd_rankcheck)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
