"""Import reference-format pickled DAG artifacts.

The reference persists its extracted GPT-2 DAG as a pickled
``List[schedulers.Task]`` (reference ``test_gpt2.py:266-269`` writes
``gpt2_dag.pkl``).  Our own serialization is JSON
(:mod:`..utils.serialization`) — strictly better for interchange — but a
user migrating from the reference may hold ``.pkl`` artifacts whose
producing module no longer exists on their path.  This loader reads them
*without* the reference code installed: a restricted unpickler maps the
reference's ``Task``/``Node`` globals onto attribute-bag shims and refuses
everything else (pickle is code execution; an allowlist is the only safe
way to open third-party pickles).

Converted tasks keep the reference's semantics: per-param sizes are not in
the artifact (the reference hardcodes 0.5 GB/param, reference
``schedulers.py:70,89``), so the resulting graph uses our default param
size, which is the same 0.5 GB.
"""

from __future__ import annotations

import io
import pickle
from collections import deque
from typing import Any, List, Union

from ..core.graph import Task, TaskGraph

# (module, qualname) globals a reference artifact may legitimately contain.
_SHIM_CLASSES = {
    ("schedulers", "Task"),
    ("schedulers", "Node"),
    ("test_gpt2", "Task"),
    ("visu", "Task"),
    ("visu", "Node"),
    ("__main__", "Task"),
    ("__main__", "Node"),
}
_SAFE_GLOBALS = {
    ("collections", "deque"): deque,
    ("builtins", "set"): set,
    ("builtins", "frozenset"): frozenset,
    ("builtins", "list"): list,
    ("builtins", "dict"): dict,
}


class _Shim:
    """Attribute bag standing in for the reference's mutable classes."""

    def __init__(self, *args: Any, **kwargs: Any) -> None:
        pass  # reference pickles carry state in __dict__, not ctor args


class _RefUnpickler(pickle.Unpickler):
    def find_class(self, module: str, name: str):
        if (module, name) in _SHIM_CLASSES:
            return _Shim
        if (module, name) in _SAFE_GLOBALS:
            return _SAFE_GLOBALS[(module, name)]
        raise pickle.UnpicklingError(
            f"refusing to unpickle {module}.{name}: reference DAG artifacts "
            f"contain only Task/Node objects and builtin containers"
        )


def load_reference_pickle(source: Union[str, bytes, io.IOBase]) -> TaskGraph:
    """Reference ``gpt2_dag.pkl``-style artifact -> :class:`TaskGraph`.

    Accepts a path, raw bytes, or a binary file object.  The artifact must
    be a list of reference ``Task`` objects (``id``, ``memory_required``,
    ``compute_time``, ``dependencies``, ``params_needed`` — reference
    ``schedulers.py:7-17``); scheduling state (``completed``,
    ``assigned_node``) is discarded, as a fresh schedule recomputes it.
    """
    if isinstance(source, (str,)):
        with open(source, "rb") as f:
            data = f.read()
    elif isinstance(source, bytes):
        data = source
    else:
        data = source.read()
    obj = _RefUnpickler(io.BytesIO(data)).load()
    if not isinstance(obj, list):
        raise ValueError(
            f"expected a pickled list of reference Tasks, got {type(obj).__name__}"
        )
    tasks: List[Task] = []
    for i, rt in enumerate(obj):
        d = getattr(rt, "__dict__", None)
        if d is None or "id" not in d:
            raise ValueError(f"artifact entry {i} is not a reference Task")
        tasks.append(
            Task(
                task_id=str(d["id"]),
                memory_required=float(d.get("memory_required", 0.0)),
                compute_time=float(d.get("compute_time", 0.0)),
                dependencies=[str(x) for x in d.get("dependencies", [])],
                params_needed=set(d.get("params_needed", ()) or ()),
            )
        )
    return TaskGraph(tasks, name="reference_import").freeze()
