"""Synthetic workload generators.

Capability parity with the reference's ``DAGGenerator``
(reference ``simulation.py:33-151``): the same three DAG *families* and
topologies (parallel attention heads, ≤3-dep random, all-to-all pipeline
stages), seedable (the reference draws unseeded RNG, so its sweeps aren't
reproducible — SURVEY.md §4).  Sizes and parameter-sharing are deliberate
variants, not byte-identical to the reference: attention weights are shared
across a layer's heads and the output is weight-tied to the embedding so
locality policies face the same sharing patterns real models have.  Parity
against the paper's *numbers* therefore holds qualitatively (ordering of
schedulers), not trial-for-trial.

Families:

* **LLM** — embedding → per-layer {parallel attention heads → attn-output →
  ffn → layer-output} → final output, with per-layer shared weights
  (reference ``simulation.py:36-88``).
* **Random** — topologically random DAG, ≤3 deps per task
  (reference ``simulation.py:90-114``).
* **Pipeline** — stages × width with all-to-all stage edges and a final
  aggregation task (reference ``simulation.py:116-151``).
"""

from __future__ import annotations

import random
from typing import List, Optional

from ..core.graph import Task, TaskGraph


def generate_llm_dag(
    num_layers: int = 4,
    num_heads: int = 8,
    seed: Optional[int] = 0,
) -> TaskGraph:
    """LLM-shaped DAG: embedding, per-layer parallel heads + ffn, output.

    Head count per layer is capped at 4 parallel branch tasks as in the
    reference (simulation.py:52-59); weights are shared per layer
    (attention weights across heads, ffn weights per layer), so locality
    policies have something to exploit.
    """
    rng = random.Random(seed)
    tasks: List[Task] = [
        Task("embedding", rng.uniform(0.5, 1.0), rng.uniform(0.05, 0.1),
             [], {"embed_weights"})
    ]
    prev = "embedding"
    for layer in range(num_layers):
        head_ids = []
        for h in range(min(num_heads, 4)):
            tid = f"l{layer}_head{h}"
            tasks.append(
                Task(tid, rng.uniform(0.3, 0.6), rng.uniform(0.02, 0.05),
                     [prev], {f"l{layer}_attn_w"})
            )
            head_ids.append(tid)
        attn_out = f"l{layer}_attn_out"
        tasks.append(
            Task(attn_out, rng.uniform(0.4, 0.8), rng.uniform(0.03, 0.06),
                 head_ids, {f"l{layer}_attn_w", f"l{layer}_proj_w"})
        )
        ffn = f"l{layer}_ffn"
        tasks.append(
            Task(ffn, rng.uniform(0.6, 1.2), rng.uniform(0.08, 0.15),
                 [attn_out], {f"l{layer}_ffn_w"})
        )
        layer_out = f"l{layer}_out"
        tasks.append(
            Task(layer_out, rng.uniform(0.2, 0.4), rng.uniform(0.01, 0.03),
                 [ffn], {f"l{layer}_ln_w"})
        )
        prev = layer_out
    tasks.append(
        Task("output", rng.uniform(0.5, 1.0), rng.uniform(0.05, 0.1),
             [prev], {"embed_weights"})  # weight tying with embedding
    )
    return TaskGraph(tasks, name=f"llm_{num_layers}l").freeze()


def generate_random_dag(
    num_tasks: int = 20,
    max_deps: int = 3,
    seed: Optional[int] = 0,
) -> TaskGraph:
    """Topologically random DAG: task i may depend on up to ``max_deps``
    earlier tasks (reference simulation.py:90-114)."""
    rng = random.Random(seed)
    tasks: List[Task] = []
    for i in range(num_tasks):
        deps: List[str] = []
        if i > 0:
            k = rng.randint(0, min(max_deps, i))
            deps = [f"task_{j}" for j in sorted(rng.sample(range(i), k))]
        n_params = rng.randint(1, 3)
        params = {f"param_{rng.randint(0, num_tasks // 2)}" for _ in range(n_params)}
        tasks.append(
            Task(f"task_{i}", rng.uniform(0.2, 1.5), rng.uniform(0.02, 0.2),
                 deps, params)
        )
    return TaskGraph(tasks, name=f"random_{num_tasks}").freeze()


def generate_pipeline_dag(
    num_stages: int = 4,
    tasks_per_stage: int = 3,
    seed: Optional[int] = 0,
) -> TaskGraph:
    """Pipeline-shaped DAG: all-to-all edges between consecutive stages plus
    a final aggregation task (reference simulation.py:116-151).  Tasks in a
    stage share that stage's weights."""
    rng = random.Random(seed)
    tasks: List[Task] = []
    prev_stage: List[str] = []
    for s in range(num_stages):
        stage_ids = []
        for i in range(tasks_per_stage):
            tid = f"s{s}_t{i}"
            tasks.append(
                Task(tid, rng.uniform(0.3, 1.0), rng.uniform(0.03, 0.12),
                     list(prev_stage), {f"stage{s}_w"})
            )
            stage_ids.append(tid)
        prev_stage = stage_ids
    tasks.append(
        Task("aggregate", rng.uniform(0.3, 0.6), rng.uniform(0.02, 0.05),
             list(prev_stage), {"agg_w"})
    )
    return TaskGraph(tasks, name=f"pipeline_{num_stages}x{tasks_per_stage}").freeze()


# The reference evaluator's six-workload sweep (simulation.py:366-373):
# small/large variants of each family.
SWEEP_WORKLOADS = {
    "llm_small": lambda seed=0: generate_llm_dag(num_layers=4, seed=seed),
    "llm_large": lambda seed=0: generate_llm_dag(num_layers=12, seed=seed),
    "random_small": lambda seed=0: generate_random_dag(num_tasks=20, seed=seed),
    "random_large": lambda seed=0: generate_random_dag(num_tasks=50, seed=seed),
    "pipeline_small": lambda seed=0: generate_pipeline_dag(
        num_stages=4, tasks_per_stage=3, seed=seed
    ),
    "pipeline_large": lambda seed=0: generate_pipeline_dag(
        num_stages=8, tasks_per_stage=4, seed=seed
    ),
}
