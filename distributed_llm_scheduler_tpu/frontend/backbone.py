"""Shared decoder-DAG backbone for the Llama-architecture families.

Llama (:mod:`.llama_dag`) and Mixtral (:mod:`.moe_dag`) differ only in the
FFN section of each layer (SwiGLU vs router+experts+combine); everything
else — embedding, RMSNorm, GQA attention, residual joins, final norm,
LM head, microbatch chains — is the same task structure with the same
param-naming scheme.  This module owns that shared assembly so FLOP
formulas and task-granularity conventions stay in one place; each family
supplies only an ``ffn_section`` callback.

(The GPT-2 frontend keeps its own assembly in :mod:`.gpt2_dag`: LayerNorm
with biases, learned positions, fused-QKV attention, and weight tying make
its structure genuinely different, and its task ids mirror the reference's
extractor, reference ``test_gpt2.py:54-166``.)
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List

import jax
import jax.numpy as jnp

from ..core.graph import (
    Task,
    TaskGraph,
    mark_batch0,
    mark_concat0,
    mark_rootslice,
)
from .gpt2_dag import ModelDAG, make_task_adder
from .vocab_sharding import logit_concat_fn, make_embed_partial_fn, shard_bounds

# ffn_section(add, mb, layer, ffn_norm_tid, group) -> FFN output task id
FfnSection = Callable[[Callable[..., None], str, int, str, str], str]


def build_decoder_dag(
    config: Any,
    module: Any,
    *,
    batch: int,
    seq_len: int,
    microbatches: int,
    effective_flops: float,
    ffn_section: FfnSection,
    name: str,
    vocab_shards: int = 1,
) -> ModelDAG:
    """Assemble a llama-architecture forward DAG.

    ``config`` must expose vocab_size/max_seq_len/d_model/n_layers/n_heads/
    n_kv_heads/head_dim/rope_theta/rms_eps; ``module`` the functional ops
    (embedding, rms_norm, gqa_attention, residual_add, lm_head) plus
    init_params/param_shapes/forward.

    ``vocab_shards > 1`` splits the two vocab-sized tables — ``tok_emb``
    row-wise, ``lm_head`` column-wise — into balanced shards, turning the
    embedding into partial-lookup tasks summed by a combine and the head
    into logit-slice tasks concatenated along the vocab axis (exact vs the
    fused forward).  Shard *k*'s embedding partial and logit slice share
    group ``vocab_shard_k``: parked on one device by the pipeline policy,
    their host-link loads spread across the cluster instead of gating the
    pipeline start/drain — for Llama-3-8B-class vocabularies the two tables
    are ~1 GB each in bf16, the largest serialized loads in the model.
    """
    if seq_len > config.max_seq_len:
        raise ValueError(f"seq_len {seq_len} exceeds max_seq_len {config.max_seq_len}")
    if batch % microbatches != 0:
        raise ValueError(f"batch {batch} not divisible by microbatches {microbatches}")
    B, T, D, V = batch, seq_len, config.d_model, config.vocab_size
    H, Hkv, hd = config.n_heads, config.n_kv_heads, config.head_dim
    Bm = B // microbatches
    S = vocab_shards
    eps = config.rms_eps

    specs = {
        pname: jax.ShapeDtypeStruct(shape, dtype)
        for pname, (shape, dtype) in module.param_shapes(config).items()
    }
    shard_lo = shard_bounds(V, S)
    if S > 1:
        for k in range(S):
            rows = shard_lo[k + 1] - shard_lo[k]
            specs[f"tok_emb_shard_{k}"] = jax.ShapeDtypeStruct(
                (rows, D), specs["tok_emb"].dtype
            )
            specs[f"lm_head_shard_{k}"] = jax.ShapeDtypeStruct(
                (D, rows), specs["lm_head"].dtype
            )
    input_spec = jax.ShapeDtypeStruct((B, T), jnp.int32)

    tasks: List[Task] = []
    out_specs: Dict[str, Any] = {}
    add = make_task_adder(tasks, out_specs, specs, input_spec, effective_flops)

    # ---- shared task fns: fn(params_dict, *dep_outputs) ------------------
    def make_f_embedding(lo, hi):
        def f_embedding(p, input_ids):
            return module.embedding(input_ids[lo:hi], p["tok_emb"])

        return mark_rootslice(
            f_embedding, "backbone_embedding", lo, hi, make_f_embedding
        )

    @mark_concat0
    def f_concat(p, *chunks):
        return jnp.concatenate(chunks, axis=0)

    @mark_batch0
    def f_norm(p, x):
        return module.rms_norm(x, p["g"], eps)

    @mark_batch0
    def f_attn(p, x):
        return module.gqa_attention(
            x, p["wq"], p["wk"], p["wv"], p["wo"],
            config.n_heads, config.n_kv_heads, config.rope_theta,
        )

    @mark_batch0
    def f_residual(p, a, b):
        return module.residual_add(a, b)

    @mark_batch0
    def f_lm_head(p, x):
        return module.lm_head(x, p["w"])

    @mark_batch0
    def f_embed_combine(p, *partials):
        out = partials[0]
        for part in partials[1:]:
            out = out + part
        return out

    @mark_batch0
    def f_logit_shard(p, x):
        # lm_head is (D, V): column shards, unlike gpt2's tied row shards
        return x @ p["shard"]

    attn_flops = (
        2.0 * Bm * T * D * (H * hd)            # q projection
        + 2.0 * 2.0 * Bm * T * D * (Hkv * hd)  # k and v projections
        + 2.0 * 2.0 * Bm * H * T * T * hd      # scores + probs@v
        + 2.0 * Bm * T * (H * hd) * D          # output projection
    )

    # ---- graph assembly --------------------------------------------------
    mb_outputs: List[str] = []
    for m in range(microbatches):
        mb = f"mb{m}_" if microbatches > 1 else ""
        emb = f"{mb}embedding"
        if S > 1:
            part_ids = []
            for k in range(S):
                rows = specs[f"tok_emb_shard_{k}"].shape[0]
                pid = f"{mb}embedding_shard_{k}"
                add(pid,
                    make_embed_partial_fn(m * Bm, (m + 1) * Bm, shard_lo[k], rows),
                    [], {"shard": f"tok_emb_shard_{k}"},
                    3.0 * Bm * T * D, f"vocab_shard_{k}")
                part_ids.append(pid)
            add(emb, f_embed_combine, part_ids, {}, S * 1.0 * Bm * T * D,
                "embed")
        else:
            add(emb, make_f_embedding(m * Bm, (m + 1) * Bm), [],
                {"tok_emb": "tok_emb"}, 2.0 * Bm * T * D, "embed")

        prev = emb
        for i in range(config.n_layers):
            pre, grp = f"l{i}_", f"layer_{i}"
            an = f"{mb}layer_{i}_attn_norm"
            add(an, f_norm, [prev], {"g": pre + "attn_norm_g"},
                4.0 * Bm * T * D, grp)

            attn = f"{mb}layer_{i}_attention"
            add(attn, f_attn, [an],
                {"wq": pre + "wq", "wk": pre + "wk",
                 "wv": pre + "wv", "wo": pre + "wo"}, attn_flops, grp)

            ares = f"{mb}layer_{i}_attn_residual"
            add(ares, f_residual, [prev, attn], {}, 1.0 * Bm * T * D, grp)

            fnorm = f"{mb}layer_{i}_ffn_norm"
            add(fnorm, f_norm, [ares], {"g": pre + "ffn_norm_g"},
                4.0 * Bm * T * D, grp)

            ffn_out = ffn_section(add, mb, i, fnorm, grp)

            lout = f"{mb}layer_{i}_output"
            add(lout, f_residual, [ares, ffn_out], {}, 1.0 * Bm * T * D, grp)
            prev = lout

        fnorm_id = f"{mb}final_norm"
        add(fnorm_id, f_norm, [prev], {"g": "final_norm_g"},
            4.0 * Bm * T * D, "head")
        head = f"{mb}lm_head"
        if S > 1:
            slice_ids = []
            for k in range(S):
                rows = specs[f"lm_head_shard_{k}"].shape[1]
                sid = f"{mb}lm_head_shard_{k}"
                add(sid, f_logit_shard, [fnorm_id],
                    {"shard": f"lm_head_shard_{k}"},
                    2.0 * Bm * T * D * rows, f"vocab_shard_{k}")
                slice_ids.append(sid)
            add(head, logit_concat_fn, slice_ids, {}, 1.0 * Bm * T * V, "head")
        else:
            add(head, f_lm_head, [fnorm_id], {"w": "lm_head"},
                2.0 * Bm * T * D * V, "head")
        mb_outputs.append(head)

    if microbatches > 1:
        add("output_concat", f_concat, mb_outputs, {}, 1.0 * B * T * V, "head")

    graph = TaskGraph(tasks, name=name).freeze()

    def reference_forward(p, ids):
        return module.forward(p, ids, config)

    def init_fn(key):
        params = module.init_params(config, key)
        for k in range(S if S > 1 else 0):
            lo, hi = shard_lo[k], shard_lo[k + 1]
            params[f"tok_emb_shard_{k}"] = params["tok_emb"][lo:hi]
            params[f"lm_head_shard_{k}"] = params["lm_head"][:, lo:hi]
        return params

    return ModelDAG(
        graph=graph,
        config=config,
        input_spec=input_spec,
        param_specs=specs,
        reference_forward=reference_forward,
        init_fn=init_fn,
    )
