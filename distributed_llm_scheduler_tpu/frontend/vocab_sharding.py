"""Shared vocab-sharding pieces for the model-family DAG builders.

Task-graph tensor parallelism for the vocab-sized tables (embedding,
LM head): balanced row/column shards, partial-lookup tasks whose sum equals
the full lookup exactly, and logit-slice concatenation.  GPT-2
(:mod:`.gpt2_dag`, tied table: row shards serve both ends) and the
llama-architecture backbone (:mod:`.backbone`, separate ``tok_emb`` /
``lm_head``) both build their shard tasks from these helpers so the split
arithmetic and the masked-lookup semantics cannot drift between families.
"""

from __future__ import annotations

from typing import Callable, List

import jax.numpy as jnp

from ..core.graph import mark_batch0, mark_rootslice


def shard_bounds(vocab_size: int, shards: int) -> List[int]:
    """Balanced split boundaries: ``shards + 1`` cumulative offsets where the
    first ``vocab_size % shards`` shards get one extra row — every shard is
    non-empty for any ``1 <= shards <= vocab_size``."""
    if not 1 <= shards <= vocab_size:
        raise ValueError(
            f"vocab_shards {shards} out of range [1, {vocab_size}]"
        )
    base, extra = divmod(vocab_size, shards)
    lo = [0]
    for k in range(shards):
        lo.append(lo[-1] + base + (1 if k < extra else 0))
    return lo


def make_embed_partial_fn(
    lo_b: int, hi_b: int, lo_v: int, rows: int
) -> Callable:
    """Partial lookup over one row shard (``p["shard"]``): token ids outside
    ``[lo_v, lo_v + rows)`` contribute 0, so the shard-sum equals the full
    lookup exactly (each id hits exactly one shard).  ``[lo_b, hi_b)`` slices
    the microbatch from the full input batch."""

    def f_embed_partial(p, input_ids):
        local = input_ids[lo_b:hi_b] - lo_v
        mask = (local >= 0) & (local < rows)
        emb = p["shard"][jnp.clip(local, 0, rows - 1)]
        return emb * mask[..., None].astype(emb.dtype)

    # slice family per vocab shard: sibling microbatch roots co-located in
    # one segment merge into a single full-batch gather (rebatch pass)
    return mark_rootslice(
        f_embed_partial, ("embed_partial", lo_v, rows), lo_b, hi_b,
        lambda a, b: make_embed_partial_fn(a, b, lo_v, rows),
    )


@mark_batch0  # last-axis concat: batch-axis-0 polymorphic
def logit_concat_fn(p, *slices):
    """Concatenate per-shard logit slices along the vocab axis."""
    return jnp.concatenate(slices, axis=-1)
