"""Shared vocab-sharding pieces for the model-family DAG builders.

Task-graph tensor parallelism for the vocab-sized tables (embedding,
LM head): balanced row/column shards, partial-lookup tasks whose sum equals
the full lookup exactly, and logit-slice concatenation.  GPT-2
(:mod:`.gpt2_dag`, tied table: row shards serve both ends) and the
llama-architecture backbone (:mod:`.backbone`, separate ``tok_emb`` /
``lm_head``) both build their shard tasks from these helpers so the split
arithmetic and the masked-lookup semantics cannot drift between families.
"""

from __future__ import annotations

from typing import Callable, List

import jax.numpy as jnp

from ..core.graph import mark_batch0, mark_rootslice


def shard_bounds(vocab_size: int, shards: int, align: int = 128) -> List[int]:
    """Near-balanced split boundaries: ``shards + 1`` cumulative offsets,
    every shard non-empty for any ``1 <= shards <= vocab_size``.

    Interior boundaries snap to multiples of ``align`` (the TPU lane
    width) when the vocab is large enough: a 50257/8 balanced split puts
    every logit-shard matmul and concat slice at a 6283-column offset —
    off the 128-lane grid, so each shard pads/relayouts.  Aligned
    boundaries keep all but the last shard exactly on the grid.  Any
    split is semantically exact (each id hits exactly one shard); tiny
    vocabs where alignment would empty a shard fall back to the balanced
    split."""
    if not 1 <= shards <= vocab_size:
        raise ValueError(
            f"vocab_shards {shards} out of range [1, {vocab_size}]"
        )
    base, extra = divmod(vocab_size, shards)
    lo = [0]
    for k in range(shards):
        lo.append(lo[-1] + base + (1 if k < extra else 0))
    if align > 1 and vocab_size >= shards * align:
        aligned = [0]
        for k in range(1, shards):
            b = round(lo[k] / align) * align
            # monotone and room for the remaining shards
            b = max(b, aligned[-1] + align)
            b = min(b, vocab_size - (shards - k) * align)
            aligned.append(b)
        aligned.append(vocab_size)
        lo = aligned
    return lo


def make_embed_partial_fn(
    lo_b: int, hi_b: int, lo_v: int, rows: int
) -> Callable:
    """Partial lookup over one row shard (``p["shard"]``): token ids outside
    ``[lo_v, lo_v + rows)`` contribute 0, so the shard-sum equals the full
    lookup exactly (each id hits exactly one shard).  ``[lo_b, hi_b)`` slices
    the microbatch from the full input batch."""

    def f_embed_partial(p, input_ids):
        local = input_ids[lo_b:hi_b] - lo_v
        mask = (local >= 0) & (local < rows)
        emb = p["shard"][jnp.clip(local, 0, rows - 1)]
        return emb * mask[..., None].astype(emb.dtype)

    # slice family per vocab shard: sibling microbatch roots co-located in
    # one segment merge into a single full-batch gather (rebatch pass)
    return mark_rootslice(
        f_embed_partial, ("embed_partial", lo_v, rows), lo_b, hi_b,
        lambda a, b: make_embed_partial_fn(a, b, lo_v, rows),
    )


@mark_batch0  # last-axis concat: batch-axis-0 polymorphic
def logit_concat_fn(p, *slices):
    """Concatenate per-shard logit slices along the vocab axis."""
    return jnp.concatenate(slices, axis=-1)
