"""GPT-2 training-step DAG: forward + backward + optimizer as tasks
(BASELINE.json config #5).

The reference schedules forward passes only; its paper lists training as
future work.  Here one SGD training step becomes a task DAG whose backward
edges invert the forward chain — the activation-memory-stress workload
SURVEY.md §7 stage 8 calls for:

* ``batch`` — identity root carrying ``{"ids", "targets"}`` to consumers;
* ``embedding_fwd``, ``layer_{i}_fwd`` — layer-granular forward; each
  output (the residual stream entering layer i+1) must stay live until
  ``layer_{i}_bwd`` consumes it at the far end of the schedule;
* ``head_bwd`` — final LN + tied-weight logits + cross-entropy loss and
  its VJP in one task (returns loss, dL/dx_L, head param grads);
* ``layer_{i}_bwd`` — **rematerializing** VJP: recomputes layer i's
  forward from its saved input inside ``jax.vjp`` (the ``jax.checkpoint``
  trade of FLOPs for memory, TPU-idiomatic) — so tasks exchange only
  plain arrays/pytrees, and each layer's params are needed a *second*
  time, far from the first — the eviction-stress pattern;
* ``opt_layer_{i}`` / ``opt_head`` / ``opt_embed`` — SGD updates; the
  tied embedding table receives summed grads from ``embedding_bwd`` and
  ``head_bwd`` (weight tying, reference ``test_gpt2.py:160-166``);
* ``step_out`` — gathers the new params + loss (the training-step state
  handoff).

Total: ``3 * n_layer + 7`` tasks.  Backward FLOPs are seeded at 2x forward
(standard ratio); calibration replaces them with measurements.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp

from ..core.graph import Task, TaskGraph
from ..models import gpt2
from ..models.gpt2 import GPT2Config
from .gpt2_dag import DEFAULT_EFFECTIVE_FLOPS, ModelDAG, make_task_adder


class TrainDAG(ModelDAG):
    """ModelDAG whose input is ``{"ids", "targets"}`` and whose
    ``reference_forward`` is the fused one-step oracle returning
    ``{"loss", "params"}``."""

    def make_inputs(self, key: Optional[jax.Array] = None) -> Dict[str, jax.Array]:
        key = key if key is not None else jax.random.PRNGKey(1)
        k1, k2 = jax.random.split(key)
        shape = self.input_spec["ids"].shape
        V = self.config.vocab_size
        return {
            "ids": jax.random.randint(k1, shape, 0, V, dtype=jnp.int32),
            "targets": jax.random.randint(k2, shape, 0, V, dtype=jnp.int32),
        }


def _layer_params(i: int) -> List[str]:
    p = f"h{i}_"
    return [p + s for s in (
        "ln1_g", "ln1_b", "attn_qkv_w", "attn_qkv_b", "attn_proj_w",
        "attn_proj_b", "ln2_g", "ln2_b", "mlp_fc_w", "mlp_fc_b",
        "mlp_proj_w", "mlp_proj_b",
    )]


def build_gpt2_train_dag(
    config: Optional[GPT2Config] = None,
    batch: int = 1,
    seq_len: int = 128,
    lr: float = 1e-3,
    effective_flops: float = DEFAULT_EFFECTIVE_FLOPS,
) -> TrainDAG:
    """One SGD step over our GPT-2 as a schedulable task DAG."""
    config = config or GPT2Config.small()
    if seq_len > config.n_positions:
        raise ValueError(f"seq_len {seq_len} exceeds n_positions {config.n_positions}")
    B, T, D, V = batch, seq_len, config.n_embd, config.vocab_size
    eps, n_head = config.ln_eps, config.n_head

    specs = {
        name: jax.ShapeDtypeStruct(shape, dtype)
        for name, (shape, dtype) in gpt2.param_shapes(config).items()
    }
    input_spec = {
        "ids": jax.ShapeDtypeStruct((B, T), jnp.int32),
        "targets": jax.ShapeDtypeStruct((B, T), jnp.int32),
    }

    tasks: List[Task] = []
    out_specs: Dict[str, Any] = {}
    add = make_task_adder(tasks, out_specs, specs, input_spec, effective_flops)

    # ---- model pieces ----------------------------------------------------
    def layer_fwd(p: Dict[str, jax.Array], x: jax.Array) -> jax.Array:
        """One transformer block with LOCAL param names (alias-mapped)."""
        ln1 = gpt2.layer_norm(x, p["ln1_g"], p["ln1_b"], eps)
        attn = gpt2.causal_attention(
            ln1, p["attn_qkv_w"], p["attn_qkv_b"], p["attn_proj_w"],
            p["attn_proj_b"], n_head,
        )
        x = x + attn
        ln2 = gpt2.layer_norm(x, p["ln2_g"], p["ln2_b"], eps)
        h = gpt2.ffn_expand(ln2, p["mlp_fc_w"], p["mlp_fc_b"])
        h = gpt2.ffn_activation(h)
        h = gpt2.ffn_contract(h, p["mlp_proj_w"], p["mlp_proj_b"])
        return x + h

    def head_loss(p: Dict[str, jax.Array], x: jax.Array,
                  targets: jax.Array) -> jax.Array:
        h = gpt2.layer_norm(x, p["ln_f_g"], p["ln_f_b"], eps)
        logits = gpt2.output_projection(h, p["wte"])
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        return nll.mean()

    # ---- task fns --------------------------------------------------------
    def f_batch(p, inputs):
        return inputs

    def f_emb_fwd(p, inputs):
        return gpt2.embedding(inputs["ids"], p["wte"], p["wpe"])

    def f_layer_fwd(p, x):
        return layer_fwd(p, x)

    def f_head_bwd(p, x, inputs):
        """Loss + VJP of (final LN -> tied logits -> cross-entropy)."""
        loss, vjp = jax.vjp(lambda pp, xx: head_loss(pp, xx, inputs["targets"]), p, x)
        grads_p, grad_x = vjp(jnp.ones((), loss.dtype))
        return {"loss": loss, "grad_x": grad_x, "grads": grads_p}

    def f_layer_bwd(p, x_in, upstream):
        """Rematerializing VJP of one block: recompute fwd from the saved
        input, pull the upstream cotangent back through it."""
        _, vjp = jax.vjp(layer_fwd, p, x_in)
        grads_p, grad_x = vjp(upstream["grad_x"])
        return {"grad_x": grad_x, "grads": grads_p}

    def f_emb_bwd(p, inputs, upstream):
        _, vjp = jax.vjp(
            lambda pp: gpt2.embedding(inputs["ids"], pp["wte"], pp["wpe"]), p
        )
        (grads_p,) = vjp(upstream["grad_x"])
        return {"grads": grads_p}

    def make_f_opt(prefix: str) -> Callable[..., Dict[str, jax.Array]]:
        """SGD update emitting GLOBAL param names (`h{i}_...`) so step_out
        can merge per-layer outputs without collisions."""

        def f_opt(p, bwd_out):
            return {
                prefix + k: p[k] - lr * bwd_out["grads"][k].astype(p[k].dtype)
                for k in p
            }

        return f_opt

    def f_opt_embed(p, emb_bwd_out, head_bwd_out):
        """Tied wte: sum the embedding-lookup and logits-projection grads."""
        g_wte = (emb_bwd_out["grads"]["wte"] + head_bwd_out["grads"]["wte"])
        return {
            "wte": p["wte"] - lr * g_wte.astype(p["wte"].dtype),
            "wpe": p["wpe"] - lr * emb_bwd_out["grads"]["wpe"].astype(p["wpe"].dtype),
        }

    def f_opt_head(p, head_bwd_out):
        g = head_bwd_out["grads"]
        return {
            "ln_f_g": p["ln_f_g"] - lr * g["ln_f_g"].astype(p["ln_f_g"].dtype),
            "ln_f_b": p["ln_f_b"] - lr * g["ln_f_b"].astype(p["ln_f_b"].dtype),
        }

    def f_step_out(p, head_bwd_out, *opt_outs):
        merged: Dict[str, jax.Array] = {}
        for o in opt_outs:
            merged.update(o)
        return {"loss": head_bwd_out["loss"], "params": merged}

    # ---- graph assembly --------------------------------------------------
    L = config.n_layer
    layer_flops = (
        2.0 * B * T * D * 3 * D + 4.0 * B * n_head * T * T * (D // n_head)
        + 2.0 * B * T * D * D + 16.0 * B * T * D * D + 12.0 * B * T * D
    )
    head_flops = 2.0 * B * T * D * V
    emb_flops = 2.0 * B * T * D

    add("batch", f_batch, [], {}, 1.0 * B * T, "io")
    add("embedding_fwd", f_emb_fwd, ["batch"],
        {"wte": "wte", "wpe": "wpe"}, emb_flops, "embed")

    prev = "embedding_fwd"
    for i in range(L):
        alias = {s.split("_", 1)[1]: s for s in _layer_params(i)}
        add(f"layer_{i}_fwd", f_layer_fwd, [prev], alias,
            layer_flops, f"layer_{i}")
        prev = f"layer_{i}_fwd"

    # head: loss + its backward in one task (weight-tied wte grads included)
    add("head_bwd", f_head_bwd, [prev, "batch"],
        {"ln_f_g": "ln_f_g", "ln_f_b": "ln_f_b", "wte": "wte"},
        3.0 * head_flops, "head")

    upstream = "head_bwd"
    for i in reversed(range(L)):
        x_in = "embedding_fwd" if i == 0 else f"layer_{i - 1}_fwd"
        alias = {s.split("_", 1)[1]: s for s in _layer_params(i)}
        add(f"layer_{i}_bwd", f_layer_bwd, [x_in, upstream], alias,
            2.0 * layer_flops, f"layer_{i}")
        upstream = f"layer_{i}_bwd"

    add("embedding_bwd", f_emb_bwd, ["batch", upstream],
        {"wte": "wte", "wpe": "wpe"}, 2.0 * emb_flops, "embed")

    opt_ids: List[str] = []
    for i in range(L):
        alias = {s.split("_", 1)[1]: s for s in _layer_params(i)}
        tid = f"opt_layer_{i}"
        add(tid, make_f_opt(f"h{i}_"), [f"layer_{i}_bwd"], alias,
            2.0 * sum(
                math.prod(specs[g].shape) for g in _layer_params(i)
            ), f"layer_{i}")
        opt_ids.append(tid)
    add("opt_embed", f_opt_embed, ["embedding_bwd", "head_bwd"],
        {"wte": "wte", "wpe": "wpe"},
        2.0 * (V + config.n_positions) * D, "embed")
    opt_ids.append("opt_embed")
    add("opt_head", f_opt_head, ["head_bwd"],
        {"ln_f_g": "ln_f_g", "ln_f_b": "ln_f_b"}, 4.0 * D, "head")
    opt_ids.append("opt_head")

    add("step_out", f_step_out, ["head_bwd"] + opt_ids, {},
        1.0 * B * T, "io")

    # ---- fused one-step oracle ------------------------------------------
    def reference_step(params: Dict[str, jax.Array],
                       inputs: Dict[str, jax.Array]) -> Dict[str, Any]:
        loss, grads = jax.value_and_grad(gpt2.loss_fn)(
            params, inputs["ids"], inputs["targets"], config
        )
        new = {k: params[k] - lr * grads[k].astype(params[k].dtype) for k in params}
        return {"loss": loss, "params": new}

    name = f"gpt2_train_{L}l_d{D}_b{B}_t{T}"
    graph = TaskGraph(tasks, name=name).freeze()
    return TrainDAG(
        graph=graph,
        config=config,
        input_spec=input_spec,
        param_specs=specs,
        reference_forward=reference_step,
        init_fn=lambda key: gpt2.init_params(config, key),
    )
