"""Jaxpr-based model tracer.

The TPU analog of the reference's dynamic trace path
(``extract_from_traced_model``, reference ``test_gpt2.py:170-216``): the
reference registers torch forward hooks on leaf modules and emits a **linear
chain** of tasks in execution order (each task depending only on the
previous op).  Here we trace any JAX-traceable ``fn(*args)`` with
``jax.make_jaxpr`` and emit one task per (non-trivial) equation, chained
linearly in trace order, with real output byte sizes from the equation's
abstract values.

This intentionally keeps the reference's linear-chain fidelity — it's a
fallback extractor for arbitrary models.  Structured frontends (e.g.
``build_gpt2_dag``) produce true-dependency DAGs and should be preferred.
"""

from __future__ import annotations

# dls-lint: allow-file(DET004) jaxpr vars are unhashable-by-value; the
#   id()-keyed const-origin memo lives and dies inside one trace call
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp

from ..core.graph import Task, TaskGraph

# primitives too trivial to stand as scheduling units on their own —
# folded into the following equation's task (the reference's analog is
# hooking only leaf *modules*, not every aten op)
_TRIVIAL_PRIMITIVES = {
    "broadcast_in_dim", "reshape", "transpose", "convert_element_type",
    "squeeze", "expand_dims", "slice", "concatenate", "iota", "copy",
    "stop_gradient",
}

# rough per-class seed times (seconds), mirroring the reference's class-based
# constants (test_gpt2.py:33-43); calibration replaces these
_PRIMITIVE_TIME = {
    "dot_general": 1e-4,
    "conv_general_dilated": 1e-4,
    "scan": 5e-4,
    "custom_jvp_call": 5e-5,
    "pjit": 5e-5,
}
_DEFAULT_TIME = 2e-5


def _aval_bytes(aval: Any) -> int:
    try:
        size = 1
        for s in aval.shape:
            size *= int(s)
        return size * jnp.dtype(aval.dtype).itemsize
    except Exception:
        return 0


def trace_to_chain(
    fn: Callable[..., Any],
    *example_args: Any,
    name: str = "traced",
    min_task_bytes: int = 0,
) -> TaskGraph:
    """Trace ``fn(*example_args)`` and build a linear-chain TaskGraph.

    Constant inputs (closed-over arrays, ``constvars``) become the traced
    tasks' named params with real byte sizes.
    """
    jaxpr = jax.make_jaxpr(fn)(*example_args)
    gb = 1024**3

    tasks = []
    prev: Optional[str] = None
    pending_trivial = 0
    const_sizes = {
        f"{name}_const_{i}": _aval_bytes(v.aval)
        for i, v in enumerate(jaxpr.jaxpr.constvars)
    }
    # var id -> set of const names it (transitively) derives from.  Skipped
    # equations propagate origins to their outputs, so a weight consumed only
    # through a transpose/cast/reshape still charges the downstream task.
    const_origin: Dict[int, set] = {
        id(v): {f"{name}_const_{i}"}
        for i, v in enumerate(jaxpr.jaxpr.constvars)
    }

    def origins_of(eqn) -> set:
        out: set = set()
        for v in eqn.invars:
            out |= const_origin.get(id(v), set())
        return out

    for idx, eqn in enumerate(jaxpr.jaxpr.eqns):
        prim = eqn.primitive.name
        out_bytes = sum(_aval_bytes(v.aval) for v in eqn.outvars)
        if prim in _TRIVIAL_PRIMITIVES or out_bytes < min_task_bytes:
            pending_trivial += 1
            carried = origins_of(eqn)
            if carried:
                for v in eqn.outvars:
                    const_origin[id(v)] = (
                        const_origin.get(id(v), set()) | carried
                    )
            continue
        tid = f"{name}_op{idx}_{prim}"
        params = origins_of(eqn)
        tasks.append(
            Task(
                tid,
                memory_required=out_bytes / gb,
                compute_time=_PRIMITIVE_TIME.get(prim, _DEFAULT_TIME)
                * (1 + pending_trivial * 0.1),
                dependencies=[prev] if prev else [],
                params_needed=params,
                param_bytes={p: const_sizes[p] for p in params},
                group=prim,
            )
        )
        pending_trivial = 0
        prev = tid

    return TaskGraph(tasks, name=name).freeze()
