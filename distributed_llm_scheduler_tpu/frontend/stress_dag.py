"""Transfer-stress DAG: a workload whose makespan is decided by placement.

Purpose (VERDICT r3 next #3): the flagship rank check runs in the CPU
mesh's compute-tied regime, where every reasonable placement predicts (and
measures) a near-tie — an agreement check there "passes" only by tie
semantics and guards nothing.  This builder constructs the opposite
regime: ``chains`` independent chains of ``length`` cheap elementwise
tasks, each edge carrying a ``edge_mb``-sized activation, with one tiny
per-chain reduction and a scalar aggregation at the end.  Compute is
negligible; cross-device edges are host-serialized ``device_put`` copies
of real megabytes.  A locality-aware policy keeps each chain on one
device (near-zero transfer); a placement that alternates devices pays the
full wire time for every edge.  The simulator (with
``host_synchronous_transfers``) predicts that separation, so rank
agreement can be asserted WITHOUT the tie escape.

Reference lineage: the reference's pipeline-shaped synthetic DAG
(reference ``simulation.py:116-151``) is the closest shape; this one adds
real jittable fns and true byte sizes so the same graph runs on live
devices.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp

from ..core.graph import GB, Task, TaskGraph
from .gpt2_dag import ModelDAG


def build_transfer_stress_dag(
    chains: int = 8,
    length: int = 6,
    edge_mb: float = 8.0,
    dtype=jnp.float32,
) -> ModelDAG:
    """``chains`` independent chains of ``length`` elementwise tasks over a
    ``edge_mb`` MB activation, then per-chain scalar reduce + global sum.

    Every chain shares one tiny param (its locality signal for
    greedy-style policies); task fns are shared across chains via
    ``param_alias`` so jit compiles each op once.
    """
    if chains < 1 or length < 2:
        raise ValueError(f"need chains >= 1, length >= 2, got {chains}/{length}")
    n_elem = max(1, int(edge_mb * 1024**2 / jnp.dtype(dtype).itemsize))
    # 2-D shape keeps XLA layouts happy; cols fixed at 1024
    cols = 1024
    rows = max(1, n_elem // cols)
    shape = (rows, cols)
    edge_bytes = rows * cols * jnp.dtype(dtype).itemsize
    edge_gb = edge_bytes / GB

    def root_fn(p, x):
        # broadcast the (tiny) graph input up to the big edge tensor
        return jnp.full(shape, p["w"], dtype) + x.astype(dtype).sum()

    def step_fn(p, y):
        return y * jnp.asarray(1.0001, dtype) + p["w"]

    def reduce_fn(p, y):
        del p
        return jnp.sum(y, dtype=jnp.float32).reshape(1)

    def agg_fn(p, *tails):
        del p
        acc = tails[0]
        for t in tails[1:]:
            acc = acc + t
        return acc

    graph = TaskGraph(name=f"xfer_stress_c{chains}_l{length}_{int(edge_mb)}mb")
    flops_step = 2.0 * rows * cols  # mul + add per element
    param_specs: Dict[str, jax.ShapeDtypeStruct] = {}
    tails = []
    for c in range(chains):
        w = f"chain{c}_w"
        param_specs[w] = jax.ShapeDtypeStruct((), dtype)
        prev: Optional[str] = None
        for i in range(length):
            tid = f"c{c}_t{i}"
            graph.add_task(Task(
                task_id=tid,
                memory_required=edge_gb,
                compute_time=1e-4,  # seed; calibration overwrites
                dependencies=[prev] if prev else [],
                params_needed={w},
                param_bytes={w: jnp.dtype(dtype).itemsize},
                fn=root_fn if prev is None else step_fn,
                param_alias={"w": w},
                out_bytes=edge_bytes,
                flops=flops_step,
                group=f"chain{c}",
            ))
            prev = tid
        rid = f"c{c}_reduce"
        graph.add_task(Task(
            task_id=rid,
            memory_required=edge_gb,
            compute_time=1e-4,
            dependencies=[prev],
            fn=reduce_fn,
            out_bytes=4,
            flops=rows * cols,
            group=f"chain{c}",
        ))
        tails.append(rid)
    graph.add_task(Task(
        task_id="agg",
        memory_required=1e-6,
        compute_time=1e-5,
        dependencies=list(tails),
        fn=agg_fn,
        out_bytes=4,
        flops=chains,
    ))
    graph.freeze()

    def init_fn(key) -> Dict[str, jax.Array]:
        ws = jax.random.uniform(key, (chains,), dtype, 0.5, 1.5)
        return {f"chain{c}_w": ws[c] for c in range(chains)}

    def reference_forward(params, x):
        acc = jnp.zeros((1,), jnp.float32)
        for c in range(chains):
            y = root_fn({"w": params[f"chain{c}_w"]}, x)
            for _ in range(length - 1):
                y = step_fn({"w": params[f"chain{c}_w"]}, y)
            acc = acc + reduce_fn({}, y)
        return acc

    input_spec = jax.ShapeDtypeStruct((1,), jnp.int32)

    dag = ModelDAG(
        graph=graph,
        config=_StressConfig(dtype=dtype, chains=chains, length=length,
                             edge_mb=edge_mb),
        input_spec=input_spec,
        param_specs=param_specs,
        reference_forward=reference_forward,
        init_fn=init_fn,
    )
    return dag


class _StressConfig:
    """Minimal config shim (ModelDAG expects .dtype and .vocab_size)."""

    vocab_size = 2  # make_inputs draws int32 in [0, 2)

    def __init__(self, dtype, chains, length, edge_mb):
        self.dtype = dtype
        self.chains = chains
        self.length = length
        self.edge_mb = edge_mb
