"""Llama-3 layer-wise forward DAG builder (BASELINE.json config #3).

Same design as :mod:`.gpt2_dag` but for the Llama architecture: per layer
the tasks are {attn_norm, attention (GQA+RoPE), attn_residual, ffn_norm,
ffn_gate, ffn_up, ffn_glu, ffn_down, layer_output} — 9 tasks/layer — plus
embedding, final_norm, and lm_head: ``9 * n_layers + 3`` tasks (291 for
Llama-3 8B).  The reference has no Llama frontend (its extractor is
GPT-2-only, reference ``test_gpt2.py:45-168``); the task-granularity
conventions (attention incl. its projections as ONE task, residual adds as
join tasks) mirror the reference's GPT-2 structure so every scheduling
policy treats both families uniformly.

Every task carries a jittable fn, real param byte sizes, eval_shape'd
activation sizes, and analytic FLOPs — see ``gpt2_dag.py`` for rationale.
``microbatches > 1`` produces the pipeline-shaped workload used by the
pipeline-stage scheduler (``sched/pipeline.py``) for the "Llama-3 8B
pipeline-stage scheduling across v5e-16" config.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp

from ..core.graph import Task, TaskGraph
from ..models import llama
from ..models.llama import LlamaConfig
from .gpt2_dag import DEFAULT_EFFECTIVE_FLOPS, ModelDAG, _bytes_of, _GB


def build_llama_dag(
    config: Optional[LlamaConfig] = None,
    batch: int = 1,
    seq_len: int = 512,
    microbatches: int = 1,
    effective_flops: float = DEFAULT_EFFECTIVE_FLOPS,
) -> ModelDAG:
    """Build the per-op forward DAG for a Llama config.

    With ``microbatches > 1`` the batch splits into independent chains
    sharing layer weights, joined by a final concat — the DAG shape of
    pipeline parallelism (see ``gpt2_dag.build_gpt2_dag``).
    """
    config = config or LlamaConfig.llama3_8b()
    if seq_len > config.max_seq_len:
        raise ValueError(f"seq_len {seq_len} exceeds max_seq_len {config.max_seq_len}")
    if batch % microbatches != 0:
        raise ValueError(f"batch {batch} not divisible by microbatches {microbatches}")
    B, T, D, V = batch, seq_len, config.d_model, config.vocab_size
    H, Hkv, hd, F = config.n_heads, config.n_kv_heads, config.head_dim, config.ffn_hidden
    Bm = B // microbatches
    eps = config.rms_eps

    specs = {
        name: jax.ShapeDtypeStruct(shape, dtype)
        for name, (shape, dtype) in llama.param_shapes(config).items()
    }
    input_spec = jax.ShapeDtypeStruct((B, T), jnp.int32)

    tasks: List[Task] = []
    out_specs: Dict[str, Any] = {}

    def add(tid, fn, deps, alias, flops, group):
        dep_specs = [out_specs[d] for d in deps] if deps else [input_spec]
        pspec = {loc: specs[glob] for loc, glob in alias.items()}
        out = jax.eval_shape(lambda pd, *a: fn(pd, *a), pspec, *dep_specs)
        out_specs[tid] = out
        globals_ = list(alias.values())
        tasks.append(
            Task(
                tid,
                memory_required=_bytes_of(out) / _GB,
                compute_time=max(flops / effective_flops, 1e-7),
                dependencies=list(deps),
                params_needed=set(globals_),
                param_bytes={g: _bytes_of(specs[g]) for g in globals_},
                fn=fn,
                arg_tasks=list(deps),
                param_alias=dict(alias),
                out_shape=out,
                flops=flops,
                group=group,
            )
        )

    # ---- shared task fns: fn(params_dict, *dep_outputs) ------------------
    def make_f_embedding(lo, hi):
        def f_embedding(p, input_ids):
            return llama.embedding(input_ids[lo:hi], p["tok_emb"])

        return f_embedding

    def f_concat(p, *chunks):
        return jnp.concatenate(chunks, axis=0)

    def f_norm(p, x):
        return llama.rms_norm(x, p["g"], eps)

    def f_attn(p, x):
        return llama.gqa_attention(
            x, p["wq"], p["wk"], p["wv"], p["wo"],
            config.n_heads, config.n_kv_heads, config.rope_theta,
        )

    def f_residual(p, a, b):
        return llama.residual_add(a, b)

    def f_gate(p, x):
        return llama.ffn_gate(x, p["w"])

    def f_up(p, x):
        return llama.ffn_up(x, p["w"])

    def f_glu(p, g, u):
        return llama.ffn_glu(g, u)

    def f_down(p, x):
        return llama.ffn_down(x, p["w"])

    def f_lm_head(p, x):
        return llama.lm_head(x, p["w"])

    # ---- graph assembly --------------------------------------------------
    mb_outputs: List[str] = []
    for m in range(microbatches):
        mb = f"mb{m}_" if microbatches > 1 else ""
        emb = f"{mb}embedding"
        add(emb, make_f_embedding(m * Bm, (m + 1) * Bm), [],
            {"tok_emb": "tok_emb"}, 2.0 * Bm * T * D, "embed")

        prev = emb
        for i in range(config.n_layers):
            pre, grp = f"l{i}_", f"layer_{i}"
            an = f"{mb}layer_{i}_attn_norm"
            add(an, f_norm, [prev], {"g": pre + "attn_norm_g"},
                4.0 * Bm * T * D, grp)

            attn = f"{mb}layer_{i}_attention"
            attn_flops = (
                2.0 * Bm * T * D * (H * hd)        # q projection
                + 2.0 * 2.0 * Bm * T * D * (Hkv * hd)  # k and v projections
                + 2.0 * 2.0 * Bm * H * T * T * hd  # scores + probs@v
                + 2.0 * Bm * T * (H * hd) * D      # output projection
            )
            add(attn, f_attn, [an],
                {"wq": pre + "wq", "wk": pre + "wk",
                 "wv": pre + "wv", "wo": pre + "wo"}, attn_flops, grp)

            ares = f"{mb}layer_{i}_attn_residual"
            add(ares, f_residual, [prev, attn], {}, 1.0 * Bm * T * D, grp)

            fn_ = f"{mb}layer_{i}_ffn_norm"
            add(fn_, f_norm, [ares], {"g": pre + "ffn_norm_g"},
                4.0 * Bm * T * D, grp)

            gate = f"{mb}layer_{i}_ffn_gate"
            add(gate, f_gate, [fn_], {"w": pre + "w_gate"},
                2.0 * Bm * T * D * F, grp)
            up = f"{mb}layer_{i}_ffn_up"
            add(up, f_up, [fn_], {"w": pre + "w_up"},
                2.0 * Bm * T * D * F, grp)
            glu = f"{mb}layer_{i}_ffn_glu"
            add(glu, f_glu, [gate, up], {}, 6.0 * Bm * T * F, grp)
            down = f"{mb}layer_{i}_ffn_down"
            add(down, f_down, [glu], {"w": pre + "w_down"},
                2.0 * Bm * T * F * D, grp)

            lout = f"{mb}layer_{i}_output"
            add(lout, f_residual, [ares, down], {}, 1.0 * Bm * T * D, grp)
            prev = lout

        fn_norm_id = f"{mb}final_norm"
        add(fn_norm_id, f_norm, [prev], {"g": "final_norm_g"},
            4.0 * Bm * T * D, "head")
        head = f"{mb}lm_head"
        add(head, f_lm_head, [fn_norm_id], {"w": "lm_head"},
            2.0 * Bm * T * D * V, "head")
        mb_outputs.append(head)

    if microbatches > 1:
        add("output_concat", f_concat, mb_outputs, {}, 1.0 * B * T * V, "head")

    name = f"llama_{config.n_layers}l_d{D}_b{B}_t{T}" + (
        f"_mb{microbatches}" if microbatches > 1 else ""
    )
    graph = TaskGraph(tasks, name=name).freeze()
    return ModelDAG(
        graph=graph,
        config=config,
        input_spec=input_spec,
        param_specs=specs,
        reference_forward=partial(llama.forward, config=config),
        init_fn=lambda key: llama.init_params(config, key),
    )
