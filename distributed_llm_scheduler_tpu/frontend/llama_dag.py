"""Llama-3 layer-wise forward DAG builder (BASELINE.json config #3).

Per layer the tasks are {attn_norm, attention (GQA+RoPE), attn_residual,
ffn_norm, ffn_gate, ffn_up, ffn_glu, ffn_down, layer_output} — 9
tasks/layer — plus embedding, final_norm, and lm_head: ``9 * n_layers + 3``
tasks (291 for Llama-3 8B).  The reference has no Llama frontend (its
extractor is GPT-2-only, reference ``test_gpt2.py:45-168``); the
task-granularity conventions mirror the reference's GPT-2 structure so
every scheduling policy treats both families uniformly.

The backbone assembly (embedding/attention/norms/residuals/head) lives in
:mod:`.backbone`, shared with the Mixtral frontend; only the SwiGLU FFN
section is defined here.  ``microbatches > 1`` produces the
pipeline-shaped workload used by the pipeline-stage scheduler
(``sched/pipeline.py``) for the "Llama-3 8B pipeline-stage scheduling
across v5e-16" config.
"""

from __future__ import annotations

from typing import Optional


from ..models import llama
from ..models.llama import LlamaConfig
from .backbone import build_decoder_dag
from ..core.graph import mark_batch0
from .gpt2_dag import DEFAULT_EFFECTIVE_FLOPS, ModelDAG, graph_name_tags


def build_llama_dag(
    config: Optional[LlamaConfig] = None,
    batch: int = 1,
    seq_len: int = 512,
    microbatches: int = 1,
    vocab_shards: int = 1,
    effective_flops: float = DEFAULT_EFFECTIVE_FLOPS,
) -> ModelDAG:
    """Build the per-op forward DAG for a Llama config."""
    config = config or LlamaConfig.llama3_8b()
    if microbatches < 1:
        raise ValueError(f"microbatches must be >= 1, got {microbatches}")
    D, F = config.d_model, config.ffn_hidden
    Bm = batch // microbatches
    T = seq_len

    @mark_batch0
    def f_gate(p, x):
        return llama.ffn_gate(x, p["w"])

    @mark_batch0
    def f_up(p, x):
        return llama.ffn_up(x, p["w"])

    @mark_batch0
    def f_glu(p, g, u):
        return llama.ffn_glu(g, u)

    @mark_batch0
    def f_down(p, x):
        return llama.ffn_down(x, p["w"])

    def ffn_section(add, mb, i, fnorm, grp):
        """SwiGLU as four tasks: gate and up matmuls in parallel, the GLU
        join, then the down projection."""
        pre = f"l{i}_"
        gate = f"{mb}layer_{i}_ffn_gate"
        add(gate, f_gate, [fnorm], {"w": pre + "w_gate"},
            2.0 * Bm * T * D * F, grp)
        up = f"{mb}layer_{i}_ffn_up"
        add(up, f_up, [fnorm], {"w": pre + "w_up"},
            2.0 * Bm * T * D * F, grp)
        glu = f"{mb}layer_{i}_ffn_glu"
        add(glu, f_glu, [gate, up], {}, 6.0 * Bm * T * F, grp)
        down = f"{mb}layer_{i}_ffn_down"
        add(down, f_down, [glu], {"w": pre + "w_down"},
            2.0 * Bm * T * F * D, grp)
        return down

    name = f"llama_{config.n_layers}l_d{D}_b{batch}_t{T}" + graph_name_tags(
        microbatches, vocab_shards, config.dtype
    )
    return build_decoder_dag(
        config, llama,
        batch=batch, seq_len=seq_len, microbatches=microbatches,
        effective_flops=effective_flops, ffn_section=ffn_section, name=name,
        vocab_shards=vocab_shards,
    )
