"""Pretrained-weight ingestion: external GPT-2 checkpoints -> flat params.

The reference instantiates a real HuggingFace ``GPT2Model`` and hooks
arbitrary torch models (reference ``test_gpt2.py:47-48``, ``183-194``) but
never runs them — weights exist only to size the DAG.  Here ingestion is a
real execution path: a HF/torch GPT-2 state dict is name-mapped into the
flat param dict shared by :mod:`..models.gpt2`, the DAG frontends, and the
backends, so "schedule a real LLM" means scheduling the *actual weights*,
and the fused-forward oracle can be checked against the donor model's own
logits (``tests/test_pretrained.py``).

Layout note: HF GPT-2 uses ``Conv1D`` modules whose weights are stored
``(in_features, out_features)`` — the same orientation as our matmuls — so
the mapping is transpose-free; only names change.  Attention causal-mask
buffers (``attn.bias``/``attn.masked_bias``) and the tied ``lm_head.weight``
are dropped (we tie the head to ``wte`` the same way GPT-2 does).
"""

from __future__ import annotations

from typing import Any, Dict, Mapping, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..models.gpt2 import GPT2Config, param_shapes

# HF name (layer-index formatted in) -> our flat name.  Complete for
# GPT2Model; GPT2LMHeadModel adds a "transformer." prefix (stripped) and
# "lm_head.weight" (tied; dropped).
_TOP_LEVEL = {
    "wte.weight": "wte",
    "wpe.weight": "wpe",
    "ln_f.weight": "ln_f_g",
    "ln_f.bias": "ln_f_b",
}
_PER_LAYER = {
    "ln_1.weight": "ln1_g",
    "ln_1.bias": "ln1_b",
    "attn.c_attn.weight": "attn_qkv_w",
    "attn.c_attn.bias": "attn_qkv_b",
    "attn.c_proj.weight": "attn_proj_w",
    "attn.c_proj.bias": "attn_proj_b",
    "ln_2.weight": "ln2_g",
    "ln_2.bias": "ln2_b",
    "mlp.c_fc.weight": "mlp_fc_w",
    "mlp.c_fc.bias": "mlp_fc_b",
    "mlp.c_proj.weight": "mlp_proj_w",
    "mlp.c_proj.bias": "mlp_proj_b",
}
# non-parameter buffers present in HF state dicts
_SKIP_SUFFIXES = (".attn.bias", ".attn.masked_bias")


def _to_numpy(v: Any) -> np.ndarray:
    """Torch tensor / jax array / numpy -> numpy, without importing torch."""
    detach = getattr(v, "detach", None)
    if detach is not None:  # torch tensor
        v = detach().cpu().numpy()
    return np.asarray(v)


def gpt2_params_from_state_dict(
    state_dict: Mapping[str, Any],
    config: GPT2Config,
    dtype: Optional[Any] = None,
) -> Dict[str, jnp.ndarray]:
    """Name-map a HF GPT-2 state dict into our flat param dict.

    Accepts ``GPT2Model`` or ``GPT2LMHeadModel`` state dicts (torch tensors
    or numpy arrays).  Every mapped tensor is shape-checked against
    :func:`..models.gpt2.param_shapes` for ``config``; missing or unknown
    parameter entries raise ``ValueError`` — silent partial loads are how
    wrong-model bugs hide.
    """
    dtype = dtype if dtype is not None else config.dtype
    expected = {k: shape for k, (shape, _) in param_shapes(config).items()}

    out: Dict[str, jnp.ndarray] = {}
    unknown = []
    for name, value in state_dict.items():
        if name.startswith("transformer."):
            name = name[len("transformer."):]
        if name == "lm_head.weight" or name.endswith(_SKIP_SUFFIXES):
            continue
        ours = _TOP_LEVEL.get(name)
        if ours is None and name.startswith("h."):
            _, idx, rest = name.split(".", 2)
            per = _PER_LAYER.get(rest)
            if per is not None and idx.isdigit():
                ours = f"h{idx}_{per}"
        if ours is None:
            unknown.append(name)
            continue
        arr = _to_numpy(value)
        want = expected.get(ours)
        if want is None:
            raise ValueError(
                f"{name!r} maps to {ours!r} which is not a parameter of "
                f"this config (n_layer={config.n_layer}?)"
            )
        if tuple(arr.shape) != tuple(want):
            raise ValueError(
                f"shape mismatch for {name!r} -> {ours!r}: "
                f"checkpoint {tuple(arr.shape)} vs config {tuple(want)}"
            )
        out[ours] = jnp.asarray(arr, dtype=dtype)

    if unknown:
        raise ValueError(f"unrecognized state-dict entries: {sorted(unknown)}")
    missing = sorted(set(expected) - set(out))
    if missing:
        raise ValueError(f"state dict is missing parameters: {missing}")
    return out


def config_from_hf(hf_config: Any, dtype: Any = jnp.float32) -> GPT2Config:
    """Our config from a ``transformers.GPT2Config`` (structure fields only)."""
    return GPT2Config(
        vocab_size=hf_config.vocab_size,
        n_positions=hf_config.n_positions,
        n_embd=hf_config.n_embd,
        n_layer=hf_config.n_layer,
        n_head=hf_config.n_head,
        dtype=dtype,
        ln_eps=getattr(hf_config, "layer_norm_epsilon", 1e-5),
    )


def load_gpt2_pretrained(
    model_name: str = "gpt2", dtype: Any = jnp.float32
) -> Tuple[GPT2Config, Dict[str, jnp.ndarray]]:
    """Load real GPT-2 weights via transformers -> (config, flat params).

    Requires the checkpoint in the local HF cache (this environment has no
    network egress); raises ``RuntimeError`` with that context otherwise.
    """
    try:
        from transformers import GPT2LMHeadModel
    except ImportError as e:  # pragma: no cover - transformers is baked in
        raise RuntimeError("transformers is required for HF ingestion") from e
    try:
        model = GPT2LMHeadModel.from_pretrained(
            model_name, local_files_only=True
        )
    except Exception as e:
        raise RuntimeError(
            f"could not load {model_name!r} from the local HF cache "
            f"(offline environment: the checkpoint must already be cached)"
        ) from e
    config = config_from_hf(model.config, dtype=dtype)
    return config, gpt2_params_from_state_dict(
        model.state_dict(), config, dtype=dtype
    )


# -- Llama family ------------------------------------------------------------

# HF stores torch.nn.Linear weights (out_features, in_features); our matmuls
# are x @ w with w (in, out), so every projection transposes on ingestion.
_LLAMA_TOP = {
    "embed_tokens.weight": ("tok_emb", False),
    "norm.weight": ("final_norm_g", False),
}
_LLAMA_PER_LAYER = {
    "input_layernorm.weight": ("attn_norm_g", False),
    "self_attn.q_proj.weight": ("wq", True),
    "self_attn.k_proj.weight": ("wk", True),
    "self_attn.v_proj.weight": ("wv", True),
    "self_attn.o_proj.weight": ("wo", True),
    "post_attention_layernorm.weight": ("ffn_norm_g", False),
    "mlp.gate_proj.weight": ("w_gate", True),
    "mlp.up_proj.weight": ("w_up", True),
    "mlp.down_proj.weight": ("w_down", True),
}


def _interleave_rope_columns(w: "np.ndarray", n_heads: int) -> "np.ndarray":
    """Permute q/k projection output columns from HF's rotate-half RoPE
    layout to our interleaved-pair layout.

    HF rotates pairs ``(j, j + hd/2)`` within each head; our
    :func:`..models.llama.apply_rope` rotates pairs ``(2j, 2j+1)`` with the
    SAME per-pair frequencies.  Mapping new column ``2j -> old j`` and
    ``2j+1 -> old j + hd/2`` per head makes our rope reproduce HF's math
    exactly; attention is invariant to the (shared) q/k permutation.  The
    same permutation llama.cpp's checkpoint converter applies.
    """
    d_in, out = w.shape
    hd = out // n_heads
    w = w.reshape(d_in, n_heads, 2, hd // 2)
    w = w.transpose(0, 1, 3, 2)
    return w.reshape(d_in, out)


def _llama_backbone_params(
    state_dict: Mapping[str, Any],
    config: Any,
    expected: Dict[str, Tuple[int, ...]],
    per_layer: Mapping[str, Tuple[str, bool]],
    dtype: Any,
) -> Dict[str, jnp.ndarray]:
    """The shared ingestion loop for Llama-backbone families: strip the
    ``model.`` prefix, rename/transpose per the maps, apply the RoPE
    column permutation to q/k, shape-check everything, fall back to tied
    embeddings for a missing ``lm_head.weight``."""
    hd = config.head_dim
    out: Dict[str, jnp.ndarray] = {}
    unknown = []
    for name, value in state_dict.items():
        if name.startswith("model."):
            name = name[len("model."):]
        if name.endswith("rotary_emb.inv_freq"):
            continue  # derived buffer, not a parameter
        transpose = False
        ours = None
        if name == "lm_head.weight":
            ours, transpose = "lm_head", True
        elif name in _LLAMA_TOP:
            ours, transpose = _LLAMA_TOP[name]
        elif name.startswith("layers."):
            _, idx, rest = name.split(".", 2)
            per = per_layer.get(rest)
            if per is not None and idx.isdigit():
                ours, transpose = f"l{idx}_{per[0]}", per[1]
        if ours is None:
            unknown.append(name)
            continue
        arr = _to_numpy(value)
        if transpose:
            arr = arr.T
        if ours.endswith("_wq") or ours.endswith("_wk"):
            heads = arr.shape[1] // hd
            arr = _interleave_rope_columns(arr, heads)
        want = expected.get(ours)
        if want is None:
            raise ValueError(
                f"{name!r} maps to {ours!r} which is not a parameter of "
                f"this config (n_layers={config.n_layers}?)"
            )
        if tuple(arr.shape) != tuple(want):
            raise ValueError(
                f"shape mismatch for {name!r} -> {ours!r}: "
                f"checkpoint {tuple(arr.shape)} vs config {tuple(want)}"
            )
        out[ours] = jnp.asarray(arr, dtype=dtype)

    if unknown:
        raise ValueError(f"unrecognized state-dict entries: {sorted(unknown)}")
    if "lm_head" not in out and "tok_emb" in out:
        out["lm_head"] = out["tok_emb"].T  # tied embeddings
    missing = sorted(set(expected) - set(out))
    if missing:
        raise ValueError(f"state dict is missing parameters: {missing}")
    return out


def llama_params_from_state_dict(
    state_dict: Mapping[str, Any],
    config: Any,
    dtype: Optional[Any] = None,
) -> Dict[str, jnp.ndarray]:
    """Name-map a HF Llama state dict into our flat param dict.

    Accepts ``LlamaModel`` or ``LlamaForCausalLM`` state dicts.  Beyond
    renaming: Linear weights transpose to (in, out), and q/k projections
    additionally permute per head for the RoPE-convention difference
    (:func:`_interleave_rope_columns`) — logits parity against the donor
    torch model is pinned in ``tests/test_pretrained.py``.  A missing
    ``lm_head.weight`` (tied embeddings) falls back to ``tok_emb.T``.
    """
    from ..models.llama import param_shapes as llama_param_shapes

    dtype = dtype if dtype is not None else config.dtype
    expected = {k: shape for k, (shape, _) in llama_param_shapes(config).items()}
    return _llama_backbone_params(
        state_dict, config, expected, _LLAMA_PER_LAYER, dtype
    )


def mixtral_params_from_state_dict(
    state_dict: Mapping[str, Any],
    config: Any,
    dtype: Optional[Any] = None,
) -> Dict[str, jnp.ndarray]:
    """Name-map a HF Mixtral state dict into our flat param dict.

    The attention block is the Llama backbone's (same transposes, same
    RoPE permutation); the MoE block maps ``block_sparse_moe.gate`` to the
    router and each expert's ``w1/w3/w2`` to our ``w_gate/w_up/w_down``.
    HF's routing (softmax over all experts, top-k, renormalize) equals our
    renormalized-top-k softmax, so logits parity holds end-to-end
    (``tests/test_pretrained.py``).
    """
    from ..models.mixtral import param_shapes as mixtral_param_shapes

    dtype = dtype if dtype is not None else config.dtype
    expected = {
        k: shape for k, (shape, _) in mixtral_param_shapes(config).items()
    }
    per_layer = {
        k: v for k, v in _LLAMA_PER_LAYER.items()
        if not k.startswith("mlp.")
    }
    per_layer["block_sparse_moe.gate.weight"] = ("router", True)
    for e in range(config.n_experts):
        pre = f"block_sparse_moe.experts.{e}."
        per_layer[pre + "w1.weight"] = (f"e{e}_w_gate", True)
        per_layer[pre + "w2.weight"] = (f"e{e}_w_down", True)
        per_layer[pre + "w3.weight"] = (f"e{e}_w_up", True)
    return _llama_backbone_params(
        state_dict, config, expected, per_layer, dtype
    )


def mixtral_config_from_hf(hf_config: Any, dtype: Any = jnp.float32):
    """Our MixtralConfig from a ``transformers.MixtralConfig``."""
    from ..models.mixtral import MixtralConfig

    return MixtralConfig(
        vocab_size=hf_config.vocab_size,
        max_seq_len=hf_config.max_position_embeddings,
        d_model=hf_config.hidden_size,
        n_layers=hf_config.num_hidden_layers,
        n_heads=hf_config.num_attention_heads,
        n_kv_heads=hf_config.num_key_value_heads,
        ffn_hidden=hf_config.intermediate_size,
        n_experts=hf_config.num_local_experts,
        top_k=hf_config.num_experts_per_tok,
        rope_theta=float(hf_config.rope_theta),
        rms_eps=float(hf_config.rms_norm_eps),
        dtype=dtype,
    )


def llama_config_from_hf(hf_config: Any, dtype: Any = jnp.float32):
    """Our LlamaConfig from a ``transformers.LlamaConfig`` (structure only)."""
    from ..models.llama import LlamaConfig

    return LlamaConfig(
        vocab_size=hf_config.vocab_size,
        max_seq_len=hf_config.max_position_embeddings,
        d_model=hf_config.hidden_size,
        n_layers=hf_config.num_hidden_layers,
        n_heads=hf_config.num_attention_heads,
        n_kv_heads=hf_config.num_key_value_heads,
        ffn_hidden=hf_config.intermediate_size,
        rope_theta=float(hf_config.rope_theta),
        rms_eps=float(hf_config.rms_norm_eps),
        dtype=dtype,
    )


def fit_params_to_dag(
    dag: Any, params: Dict[str, jnp.ndarray]
) -> Dict[str, jnp.ndarray]:
    """Derive any DAG-build-specific params missing from a base checkpoint.

    Vocab-sharded builds (``build_gpt2_dag(vocab_shards=S)``) consume
    ``wte_shard_k`` row slices of the tied table; checkpoints carry only
    ``wte``.  Returns a new dict with every spec key the DAG's tasks
    reference present.
    """
    from .vocab_sharding import shard_bounds

    out = dict(params)
    # GPT-2 family: row slices of the tied wte table.  Keys constructed
    # from the index — lexicographic sorting would misorder shard_10
    # before shard_2 at 10+ shards
    n_wte = sum(
        1 for k in dag.param_specs if k.startswith("wte_shard_")
    )
    if n_wte:
        lo = shard_bounds(dag.config.vocab_size, n_wte)
        for k in range(n_wte):
            out.setdefault(f"wte_shard_{k}", out["wte"][lo[k]:lo[k + 1]])
    # Llama backbone: tok_emb row slices + lm_head column slices (index
    # keys, like above — never iterate shard names lexicographically)
    n_emb = sum(
        1 for k in dag.param_specs if k.startswith("tok_emb_shard_")
    )
    if n_emb:
        lo = shard_bounds(dag.config.vocab_size, n_emb)
        for k in range(n_emb):
            out.setdefault(
                f"tok_emb_shard_{k}", out["tok_emb"][lo[k]:lo[k + 1]]
            )
            out.setdefault(
                f"lm_head_shard_{k}", out["lm_head"][:, lo[k]:lo[k + 1]]
            )
    missing = sorted(set(dag.param_specs) - set(out))
    if missing:
        raise ValueError(f"params missing for DAG {dag.graph.name}: {missing}")
    return out
