"""Pretrained-weight ingestion: external GPT-2 checkpoints -> flat params.

The reference instantiates a real HuggingFace ``GPT2Model`` and hooks
arbitrary torch models (reference ``test_gpt2.py:47-48``, ``183-194``) but
never runs them — weights exist only to size the DAG.  Here ingestion is a
real execution path: a HF/torch GPT-2 state dict is name-mapped into the
flat param dict shared by :mod:`..models.gpt2`, the DAG frontends, and the
backends, so "schedule a real LLM" means scheduling the *actual weights*,
and the fused-forward oracle can be checked against the donor model's own
logits (``tests/test_pretrained.py``).

Layout note: HF GPT-2 uses ``Conv1D`` modules whose weights are stored
``(in_features, out_features)`` — the same orientation as our matmuls — so
the mapping is transpose-free; only names change.  Attention causal-mask
buffers (``attn.bias``/``attn.masked_bias``) and the tied ``lm_head.weight``
are dropped (we tie the head to ``wte`` the same way GPT-2 does).
"""

from __future__ import annotations

from typing import Any, Dict, Mapping, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..models.gpt2 import GPT2Config, param_shapes

# HF name (layer-index formatted in) -> our flat name.  Complete for
# GPT2Model; GPT2LMHeadModel adds a "transformer." prefix (stripped) and
# "lm_head.weight" (tied; dropped).
_TOP_LEVEL = {
    "wte.weight": "wte",
    "wpe.weight": "wpe",
    "ln_f.weight": "ln_f_g",
    "ln_f.bias": "ln_f_b",
}
_PER_LAYER = {
    "ln_1.weight": "ln1_g",
    "ln_1.bias": "ln1_b",
    "attn.c_attn.weight": "attn_qkv_w",
    "attn.c_attn.bias": "attn_qkv_b",
    "attn.c_proj.weight": "attn_proj_w",
    "attn.c_proj.bias": "attn_proj_b",
    "ln_2.weight": "ln2_g",
    "ln_2.bias": "ln2_b",
    "mlp.c_fc.weight": "mlp_fc_w",
    "mlp.c_fc.bias": "mlp_fc_b",
    "mlp.c_proj.weight": "mlp_proj_w",
    "mlp.c_proj.bias": "mlp_proj_b",
}
# non-parameter buffers present in HF state dicts
_SKIP_SUFFIXES = (".attn.bias", ".attn.masked_bias")


def _to_numpy(v: Any) -> np.ndarray:
    """Torch tensor / jax array / numpy -> numpy, without importing torch."""
    detach = getattr(v, "detach", None)
    if detach is not None:  # torch tensor
        v = detach().cpu().numpy()
    return np.asarray(v)


def gpt2_params_from_state_dict(
    state_dict: Mapping[str, Any],
    config: GPT2Config,
    dtype: Optional[Any] = None,
) -> Dict[str, jnp.ndarray]:
    """Name-map a HF GPT-2 state dict into our flat param dict.

    Accepts ``GPT2Model`` or ``GPT2LMHeadModel`` state dicts (torch tensors
    or numpy arrays).  Every mapped tensor is shape-checked against
    :func:`..models.gpt2.param_shapes` for ``config``; missing or unknown
    parameter entries raise ``ValueError`` — silent partial loads are how
    wrong-model bugs hide.
    """
    dtype = dtype if dtype is not None else config.dtype
    expected = {k: shape for k, (shape, _) in param_shapes(config).items()}

    out: Dict[str, jnp.ndarray] = {}
    unknown = []
    for name, value in state_dict.items():
        if name.startswith("transformer."):
            name = name[len("transformer."):]
        if name == "lm_head.weight" or name.endswith(_SKIP_SUFFIXES):
            continue
        ours = _TOP_LEVEL.get(name)
        if ours is None and name.startswith("h."):
            _, idx, rest = name.split(".", 2)
            per = _PER_LAYER.get(rest)
            if per is not None and idx.isdigit():
                ours = f"h{idx}_{per}"
        if ours is None:
            unknown.append(name)
            continue
        arr = _to_numpy(value)
        want = expected.get(ours)
        if want is None:
            raise ValueError(
                f"{name!r} maps to {ours!r} which is not a parameter of "
                f"this config (n_layer={config.n_layer}?)"
            )
        if tuple(arr.shape) != tuple(want):
            raise ValueError(
                f"shape mismatch for {name!r} -> {ours!r}: "
                f"checkpoint {tuple(arr.shape)} vs config {tuple(want)}"
            )
        out[ours] = jnp.asarray(arr, dtype=dtype)

    if unknown:
        raise ValueError(f"unrecognized state-dict entries: {sorted(unknown)}")
    missing = sorted(set(expected) - set(out))
    if missing:
        raise ValueError(f"state dict is missing parameters: {missing}")
    return out


def config_from_hf(hf_config: Any, dtype: Any = jnp.float32) -> GPT2Config:
    """Our config from a ``transformers.GPT2Config`` (structure fields only)."""
    return GPT2Config(
        vocab_size=hf_config.vocab_size,
        n_positions=hf_config.n_positions,
        n_embd=hf_config.n_embd,
        n_layer=hf_config.n_layer,
        n_head=hf_config.n_head,
        dtype=dtype,
        ln_eps=getattr(hf_config, "layer_norm_epsilon", 1e-5),
    )


def load_gpt2_pretrained(
    model_name: str = "gpt2", dtype: Any = jnp.float32
) -> Tuple[GPT2Config, Dict[str, jnp.ndarray]]:
    """Load real GPT-2 weights via transformers -> (config, flat params).

    Requires the checkpoint in the local HF cache (this environment has no
    network egress); raises ``RuntimeError`` with that context otherwise.
    """
    try:
        from transformers import GPT2LMHeadModel
    except ImportError as e:  # pragma: no cover - transformers is baked in
        raise RuntimeError("transformers is required for HF ingestion") from e
    try:
        model = GPT2LMHeadModel.from_pretrained(
            model_name, local_files_only=True
        )
    except Exception as e:
        raise RuntimeError(
            f"could not load {model_name!r} from the local HF cache "
            f"(offline environment: the checkpoint must already be cached)"
        ) from e
    config = config_from_hf(model.config, dtype=dtype)
    return config, gpt2_params_from_state_dict(
        model.state_dict(), config, dtype=dtype
    )


def fit_params_to_dag(dag: Any, params: Dict[str, jnp.ndarray]) -> Dict[str, jnp.ndarray]:
    """Derive any DAG-build-specific params missing from a base checkpoint.

    Vocab-sharded builds (``build_gpt2_dag(vocab_shards=S)``) consume
    ``wte_shard_k`` row slices of the tied table; checkpoints carry only
    ``wte``.  Returns a new dict with every spec key the DAG's tasks
    reference present.
    """
    from .vocab_sharding import shard_bounds

    out = dict(params)
    shard_keys = sorted(
        k for k in dag.param_specs if k.startswith("wte_shard_")
    )
    if shard_keys:
        lo = shard_bounds(dag.config.vocab_size, len(shard_keys))
        for k, key in enumerate(shard_keys):
            out.setdefault(key, out["wte"][lo[k]:lo[k + 1]])
    missing = sorted(set(dag.param_specs) - set(out))
    if missing:
        raise ValueError(f"params missing for DAG {dag.graph.name}: {missing}")
    return out
