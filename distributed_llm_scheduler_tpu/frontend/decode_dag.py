"""KV-cache decode step as a task DAG: inference through the scheduler.

The task-graph path (the repo's thesis) and the whole-program decode loop
(:mod:`..models.decode`) are deliberately twinned everywhere else; this
builder closes the last gap (VERDICT r2 missing #4): the scheduling layer
never saw an inference workload.  One cached forward step — prefill
(``pos = 0``, ``step_len`` = prompt length) or a decode step
(``step_len = 1``) — becomes a per-layer task DAG where the **KV cache
slabs are placeable parameters**:

* layer ``i``'s task needs ``cache_k_i`` / ``cache_v_i`` (real bytes:
  ``B x Hkv x max_len x hd``), so *cache residency IS the placement
  problem* — the same param-cache-locality story the reference's MRU
  policy targets, with the model's largest decode-time tensors;
* each layer task outputs ``{"x", "k_new", "v_new", "pos"}`` — the
  functional cache-update slices the caller applies to its cache copy
  (retained via ``execute(keep_outputs=True).task_outputs``), so
  execution stays pure;
* the step position is a TRACED runtime input (``{"ids", "pos"}``),
  threaded through each task's output dict: attention masks against it,
  RoPE/wpe rows are dynamic-sliced at it, cache updates land at it.  ONE
  graph therefore serves every position of a given ``(step_len,
  max_len)`` class — an N-token generation compiles exactly two programs
  (prefill + decode step), not N (VERDICT r3 next #7).  Compute per step
  is O(max_len) regardless of position (the cache is scanned fully,
  masked), which is also what the FLOPs fields record.

All three families: :func:`build_decode_dag` (GPT-2),
:func:`build_backbone_decode_dag` (Llama / Mixtral — GQA cache layout,
RoPE dynamic-sliced at the traced position, MoE routing per step), and
the dispatching :func:`build_decode_dag_any`.  Oracle: the family's
``forward_cached`` on the same cache (logits exact, multi-step greedy
tokens exact — ``tests/test_decode_dag.py``).
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp

from ..core.graph import Task, TaskGraph
from ..models import decode as _decode
from ..models import gpt2
from ..models.gpt2 import GPT2Config
from .gpt2_dag import DEFAULT_EFFECTIVE_FLOPS, ModelDAG, make_task_adder


def cache_dims(config: Any) -> tuple:
    """``(n_layers, n_kv_heads, head_dim)`` for any family's config — the
    one place that knows gpt2 spells these ``n_layer``/``n_head`` while
    the llama backbone spells them ``n_layers``/``n_kv_heads``.  Callers
    allocating cache slabs must use this, not re-derive the attributes."""
    from ..parallel.decode import _family_of

    if _family_of(config) == "gpt2":
        return config.n_layer, config.n_head, config.head_dim
    return config.n_layers, config.n_kv_heads, config.head_dim


class DecodeDAG(ModelDAG):
    """ModelDAG whose graph input is ``{"ids": (B, T) int32, "pos": ()
    int32}`` — position is runtime data, so one graph serves every step
    of its ``(step_len, max_len)`` class.  ``default_pos`` seeds
    ``make_inputs`` (callers stepping a generation pass their own)."""

    default_pos: int = 0

    def make_inputs(self, key: Optional[jax.Array] = None,
                    pos: Optional[int] = None) -> Dict[str, jax.Array]:
        key = key if key is not None else jax.random.PRNGKey(1)
        shape = self.input_spec["ids"].shape
        return {
            "ids": jax.random.randint(
                key, shape, 0, self.config.vocab_size, dtype=jnp.int32
            ),
            "pos": jnp.asarray(
                self.default_pos if pos is None else pos, jnp.int32
            ),
        }


def decode_inputs(
    ids: jax.Array, pos, max_len: Optional[int] = None
) -> Dict[str, jax.Array]:
    """The decode graphs' input pytree for a concrete step.

    Pass ``max_len`` to get the bounds check the build-time guard can no
    longer provide (position is runtime data): an out-of-range position
    would otherwise CLAMP the cache write (``dynamic_update_slice``
    semantics) and silently corrupt the last cache row.
    """
    ids = jnp.asarray(ids, jnp.int32)
    if max_len is not None and not isinstance(pos, jax.core.Tracer):
        if int(pos) + ids.shape[-1] > max_len:
            raise ValueError(
                f"pos {int(pos)} + step_len {ids.shape[-1]} exceeds "
                f"max_len {max_len}"
            )
    return {"ids": ids, "pos": jnp.asarray(pos, jnp.int32)}


def build_decode_dag(
    config: Optional[GPT2Config] = None,
    batch: int = 1,
    step_len: int = 1,
    pos: int = 0,
    max_len: int = 128,
    effective_flops: float = DEFAULT_EFFECTIVE_FLOPS,
) -> ModelDAG:
    """Task DAG for one cached forward step; position is a runtime input.

    ``step_len > 1`` is the prefill class; ``step_len = 1`` the decode
    class — one graph per class covers every position (``pos`` here only
    seeds ``make_inputs``' default and validates against ``max_len``).
    Params are the model weights PLUS per-layer ``cache_k_{i}`` /
    ``cache_v_{i}`` slabs (zeros from ``init_params``; load real cache
    state by overwriting those entries).  The graph's sink is the logits
    task; each layer's cache-update dict is retained via
    ``execute(keep_outputs=True).task_outputs`` — apply updates with
    :func:`apply_cache_updates`.
    """
    config = config or GPT2Config.tiny()
    if pos + step_len > max_len:
        raise ValueError(
            f"pos {pos} + step_len {step_len} exceeds max_len {max_len}"
        )
    B, T, D, H = batch, step_len, config.n_embd, config.n_head
    hd, M = config.head_dim, max_len
    eps = config.ln_eps
    scale = 1.0 / math.sqrt(hd)

    specs = {
        name: jax.ShapeDtypeStruct(shape, dtype)
        for name, (shape, dtype) in gpt2.param_shapes(config).items()
    }
    for i in range(config.n_layer):
        specs[f"cache_k_{i}"] = jax.ShapeDtypeStruct(
            (B, H, M, hd), config.dtype
        )
        specs[f"cache_v_{i}"] = jax.ShapeDtypeStruct(
            (B, H, M, hd), config.dtype
        )
    input_spec = {
        "ids": jax.ShapeDtypeStruct((B, T), jnp.int32),
        "pos": jax.ShapeDtypeStruct((), jnp.int32),
    }

    tasks: List[Task] = []
    out_specs: Dict[str, Any] = {}
    add = make_task_adder(tasks, out_specs, specs, input_spec, effective_flops)

    def f_embed(p, inputs):
        # token embedding + position rows [pos, pos+T) — traced pos
        pos_t = inputs["pos"]
        wpe_rows = jax.lax.dynamic_slice(
            p["wpe"], (pos_t, jnp.int32(0)), (T, D)
        )
        return {"x": p["wte"][inputs["ids"]] + wpe_rows, "pos": pos_t}

    def f_layer(p, prev):
        """One cached transformer layer: attention over [0, pos+T) of the
        cache (this step's keys/values included), then the MLP.  Returns
        the residual stream, this step's cache-update slices, and the
        threaded position."""
        x, pos_t = prev["x"], prev["pos"]
        ln1 = gpt2.layer_norm(x, p["ln1_g"], p["ln1_b"], eps)
        qkv = ln1 @ p["qkv_w"] + p["qkv_b"]
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):
            return t.reshape(B, T, H, hd).transpose(0, 2, 1, 3)

        q, k, v = heads(q), heads(k), heads(v)
        k_cache = jax.lax.dynamic_update_slice(
            p["cache_k"], k.astype(p["cache_k"].dtype),
            (jnp.int32(0), jnp.int32(0), pos_t, jnp.int32(0)),
        )
        v_cache = jax.lax.dynamic_update_slice(
            p["cache_v"], v.astype(p["cache_v"].dtype),
            (jnp.int32(0), jnp.int32(0), pos_t, jnp.int32(0)),
        )
        att = _decode.cached_attention(q, k_cache, v_cache, pos_t, scale)
        att = att.transpose(0, 2, 1, 3).reshape(B, T, D)
        x = x + (att @ p["attn_proj_w"] + p["attn_proj_b"])
        ln2 = gpt2.layer_norm(x, p["ln2_g"], p["ln2_b"], eps)
        h = gpt2.ffn_contract(
            gpt2.ffn_activation(
                gpt2.ffn_expand(ln2, p["fc_w"], p["fc_b"])
            ),
            p["mlp_proj_w"], p["mlp_proj_b"],
        )
        return {"x": x + h, "k_new": k, "v_new": v, "pos": pos_t}

    def f_head(p, prev):
        x = gpt2.layer_norm(prev["x"], p["ln_f_g"], p["ln_f_b"], eps)
        return gpt2.output_projection(x, p["wte"])

    add("embed", f_embed, [], {"wte": "wte", "wpe": "wpe"},
        2.0 * B * T * D, "embed")
    prev = "embed"
    for i in range(config.n_layer):
        pre = f"h{i}_"
        alias = {
            "ln1_g": pre + "ln1_g", "ln1_b": pre + "ln1_b",
            "qkv_w": pre + "attn_qkv_w", "qkv_b": pre + "attn_qkv_b",
            "attn_proj_w": pre + "attn_proj_w",
            "attn_proj_b": pre + "attn_proj_b",
            "ln2_g": pre + "ln2_g", "ln2_b": pre + "ln2_b",
            "fc_w": pre + "mlp_fc_w", "fc_b": pre + "mlp_fc_b",
            "mlp_proj_w": pre + "mlp_proj_w",
            "mlp_proj_b": pre + "mlp_proj_b",
            "cache_k": f"cache_k_{i}", "cache_v": f"cache_v_{i}",
        }
        # FLOPs: projections on T tokens + attention over the FULL masked
        # cache (compute is O(M) at any position — static shapes)
        flops = (
            2.0 * B * T * D * 3 * D
            + 2.0 * 2.0 * B * H * T * M * hd
            + 2.0 * B * T * D * D
            + 2.0 * B * T * D * 4 * D * 2
        )
        tid = f"layer_{i}"
        add(tid, f_layer, [prev], alias, flops, f"layer_{i}")
        prev = tid
    add("logits", f_head, [prev], {
        "ln_f_g": "ln_f_g", "ln_f_b": "ln_f_b", "wte": "wte",
    }, 2.0 * B * T * D * config.vocab_size, "head")

    name = (
        f"gpt2dec_{config.n_layer}l_d{D}_b{B}_t{T}_m{M}"
        + ("" if config.dtype == jnp.float32
           else f"_{jnp.dtype(config.dtype).name}")
    )

    def init_fn(key):
        params = gpt2.init_params(config, key)
        for i in range(config.n_layer):
            params[f"cache_k_{i}"] = jnp.zeros((B, H, M, hd), config.dtype)
            params[f"cache_v_{i}"] = jnp.zeros((B, H, M, hd), config.dtype)
        return params

    def reference_forward(params, inputs):
        """Whole-program oracle over the same cache params: stacked-layer
        cache assembled from the per-layer slabs, models/decode math."""
        cache = {
            "k": jnp.stack(
                [params[f"cache_k_{i}"] for i in range(config.n_layer)]
            ),
            "v": jnp.stack(
                [params[f"cache_v_{i}"] for i in range(config.n_layer)]
            ),
        }
        model_params = {
            k: v for k, v in params.items() if not k.startswith("cache_")
        }
        logits, _ = gpt2.forward_cached(
            model_params, inputs["ids"], cache, inputs["pos"], config
        )
        return logits

    graph = TaskGraph(tasks, name=name).freeze()
    dag = DecodeDAG(
        graph=graph,
        config=config,
        input_spec=input_spec,
        param_specs=specs,
        reference_forward=reference_forward,
        init_fn=init_fn,
    )
    dag.default_pos = pos
    return dag


def build_backbone_decode_dag(
    config: Any,
    batch: int = 1,
    step_len: int = 1,
    pos: int = 0,
    max_len: int = 128,
    effective_flops: float = DEFAULT_EFFECTIVE_FLOPS,
) -> ModelDAG:
    """Llama-backbone decode-step DAG (Llama and Mixtral configs).

    Same contract as :func:`build_decode_dag`: per-layer tasks own
    ``cache_k_{i}`` / ``cache_v_{i}`` slabs (GQA layout:
    ``B x n_kv_heads x max_len x hd``), RoPE dynamic-sliced at the traced
    step position, Mixtral layers run their router + dense experts per
    step (routing is per-token, exactly as the fused cached forward
    does).  Oracle: the family's ``forward_cached`` over the stacked
    cache.
    """
    from ..models import llama as _llama
    from ..models import mixtral as _mixtral
    from ..parallel.decode import _family_of

    family = _family_of(config)
    if family not in ("llama", "mixtral"):
        raise ValueError(f"backbone decode DAG needs llama/mixtral, got {family}")
    mod = _llama if family == "llama" else _mixtral
    is_moe = family == "mixtral"
    if pos + step_len > max_len:
        raise ValueError(
            f"pos {pos} + step_len {step_len} exceeds max_len {max_len}"
        )
    B, T, D = batch, step_len, config.d_model
    nh, nkv, hd = config.n_heads, config.n_kv_heads, config.head_dim
    M, eps = max_len, config.rms_eps
    n_layers = config.n_layers
    scale = 1.0 / math.sqrt(hd)

    specs = {
        name: jax.ShapeDtypeStruct(shape, dtype)
        for name, (shape, dtype) in mod.param_shapes(config).items()
    }
    for i in range(n_layers):
        for kind in ("k", "v"):
            specs[f"cache_{kind}_{i}"] = jax.ShapeDtypeStruct(
                (B, nkv, M, hd), config.dtype
            )
    input_spec = {
        "ids": jax.ShapeDtypeStruct((B, T), jnp.int32),
        "pos": jax.ShapeDtypeStruct((), jnp.int32),
    }

    tasks: List[Task] = []
    out_specs: Dict[str, Any] = {}
    add = make_task_adder(tasks, out_specs, specs, input_spec, effective_flops)

    def f_embed(p, inputs):
        return {
            "x": _llama.embedding(inputs["ids"], p["tok_emb"]),
            "pos": inputs["pos"],
        }

    def f_layer(p, prev):
        x, pos_t = prev["x"], prev["pos"]
        h = _llama.rms_norm(x, p["attn_norm_g"], eps)
        q = (h @ p["wq"]).reshape(B, T, nh, hd).transpose(0, 2, 1, 3)
        k = (h @ p["wk"]).reshape(B, T, nkv, hd).transpose(0, 2, 1, 3)
        v = (h @ p["wv"]).reshape(B, T, nkv, hd).transpose(0, 2, 1, 3)
        cos_all, sin_all = _llama.rope_tables(M, hd, config.rope_theta)
        cos = jax.lax.dynamic_slice(cos_all, (pos_t, 0), (T, hd // 2))
        sin = jax.lax.dynamic_slice(sin_all, (pos_t, 0), (T, hd // 2))
        q, k = _llama.apply_rope(q, cos, sin), _llama.apply_rope(k, cos, sin)
        k_cache = jax.lax.dynamic_update_slice(
            p["cache_k"], k.astype(p["cache_k"].dtype),
            (jnp.int32(0), jnp.int32(0), pos_t, jnp.int32(0)),
        )
        v_cache = jax.lax.dynamic_update_slice(
            p["cache_v"], v.astype(p["cache_v"].dtype),
            (jnp.int32(0), jnp.int32(0), pos_t, jnp.int32(0)),
        )
        att = _decode.cached_attention(q, k_cache, v_cache, pos_t, scale)
        att = att.transpose(0, 2, 1, 3).reshape(B, T, nh * hd)
        x = x + att @ p["wo"]
        h2 = _llama.rms_norm(x, p["ffn_norm_g"], eps)
        if is_moe:
            ffn = _mixtral._moe(p, h2, config)
        else:
            ffn = _llama.ffn_down(
                _llama.ffn_glu(
                    _llama.ffn_gate(h2, p["w_gate"]),
                    _llama.ffn_up(h2, p["w_up"]),
                ),
                p["w_down"],
            )
        return {"x": x + ffn, "k_new": k, "v_new": v, "pos": pos_t}

    def f_head(p, prev):
        x = _llama.rms_norm(prev["x"], p["final_norm_g"], eps)
        return _llama.lm_head(x, p["lm_head"])

    add("embed", f_embed, [], {"tok_emb": "tok_emb"}, 2.0 * B * T * D, "embed")
    prev = "embed"
    for i in range(n_layers):
        pre = f"l{i}_"
        alias = {
            "attn_norm_g": pre + "attn_norm_g",
            "wq": pre + "wq", "wk": pre + "wk", "wv": pre + "wv",
            "wo": pre + "wo",
            "ffn_norm_g": pre + "ffn_norm_g",
            "cache_k": f"cache_k_{i}", "cache_v": f"cache_v_{i}",
        }
        if is_moe:
            alias["router"] = pre + "router"
            for e in range(config.n_experts):
                for s in ("w_gate", "w_up", "w_down"):
                    alias[f"e{e}_{s}"] = f"{pre}e{e}_{s}"
        else:
            for s in ("w_gate", "w_up", "w_down"):
                alias[s] = pre + s
        F = config.ffn_hidden
        if is_moe:
            # router + DENSE per-step expert sweep (every expert runs
            # every token — the disclosed dense-dispatch cost)
            ffn_flops = (
                2.0 * B * T * D * config.n_experts
                + config.n_experts * 3 * 2.0 * B * T * D * F
            )
        else:
            ffn_flops = 3 * 2.0 * B * T * D * F  # gate, up, down matmuls
        flops = (
            2.0 * B * T * D * (nh + 2 * nkv) * hd
            + 2.0 * 2.0 * B * nh * T * M * hd  # full masked cache, O(M)
            + 2.0 * B * T * nh * hd * D
            + ffn_flops
        )
        tid = f"layer_{i}"
        add(tid, f_layer, [prev], alias, flops, f"layer_{i}")
        prev = tid
    add("logits", f_head, [prev], {
        "final_norm_g": "final_norm_g", "lm_head": "lm_head",
    }, 2.0 * B * T * D * config.vocab_size, "head")

    name = (
        f"{family}dec_{n_layers}l_d{D}_b{B}_t{T}_m{M}"
        + ("" if config.dtype == jnp.float32
           else f"_{jnp.dtype(config.dtype).name}")
    )

    def init_fn(key):
        params = mod.init_params(config, key)
        for i in range(n_layers):
            params[f"cache_k_{i}"] = jnp.zeros((B, nkv, M, hd), config.dtype)
            params[f"cache_v_{i}"] = jnp.zeros((B, nkv, M, hd), config.dtype)
        return params

    def reference_forward(params, inputs):
        cache = {
            "k": jnp.stack(
                [params[f"cache_k_{i}"] for i in range(n_layers)]
            ),
            "v": jnp.stack(
                [params[f"cache_v_{i}"] for i in range(n_layers)]
            ),
        }
        model_params = {
            k: v for k, v in params.items() if not k.startswith("cache_")
        }
        logits, _ = mod.forward_cached(
            model_params, inputs["ids"], cache, inputs["pos"], config
        )
        return logits

    graph = TaskGraph(tasks, name=name).freeze()
    dag = DecodeDAG(
        graph=graph,
        config=config,
        input_spec=input_spec,
        param_specs=specs,
        reference_forward=reference_forward,
        init_fn=init_fn,
    )
    dag.default_pos = pos
    return dag


class PagedDecodeDAG(ModelDAG):
    """ModelDAG for the paged decode step: inputs are ``{"ids": (S, 1)
    int32, "lengths": (S,) int32}`` — per-slot ragged positions instead
    of one shared scalar — and the KV cache params are shared page pools
    plus the ``page_table`` param (:mod:`..models.kv_pages`)."""

    slots: int = 1
    page_size: int = 0
    pages_per_seq: int = 0
    #: attention impl baked into the layer tasks (None = op-level auto)
    attention_impl: Optional[str] = None

    def make_inputs(self, key: Optional[jax.Array] = None,
                    lengths: Optional[Any] = None) -> Dict[str, jax.Array]:
        key = key if key is not None else jax.random.PRNGKey(1)
        shape = self.input_spec["ids"].shape
        S = shape[0]
        return {
            "ids": jax.random.randint(
                key, shape, 0, self.config.vocab_size, dtype=jnp.int32
            ),
            "lengths": (
                jnp.zeros((S,), jnp.int32) if lengths is None
                else jnp.asarray(lengths, jnp.int32)
            ),
        }


def build_paged_decode_dag(
    config: Optional[GPT2Config] = None,
    slots: int = 4,
    page_size: int = 16,
    n_pages: int = 64,
    pages_per_seq: int = 8,
    effective_flops: float = DEFAULT_EFFECTIVE_FLOPS,
    attention_impl: Optional[str] = None,
) -> PagedDecodeDAG:
    """Paged single-token decode step as a task DAG (GPT-2 family).

    The dense decode DAG's per-layer ``cache_k_{i}``/``cache_v_{i}``
    slabs become shared page POOLS ``(n_pages, page_size, H, hd)`` and
    every layer task additionally aliases the ``page_table`` param
    ``(slots, pages_per_seq) int32`` — so placement and the analysis
    passes see the paged cache's real residency: the pool bytes are the
    per-layer page residency, and the table is the tiny shared indirection
    every layer reads (the DEC003 wiring contract).  Attention is the
    ragged paged op (:func:`...ops.attention.paged_decode_attention`):
    gathered by page table, masked per-slot at the runtime ``lengths``
    input, bit-identical to a dense cache of capacity ``pages_per_seq *
    page_size``.

    The step is scheduler-placed exactly like the dense decode DAG; the
    continuous-batching loop (``backends/decode_loop.py``) composes it
    into scanned K-step segments.

    ``attention_impl`` selects the paged attention implementation baked
    into every layer task (``"xla"`` gather, ``"pallas"`` fused kernel,
    ``"pallas_interpret"``, ``"auto"``); ``None`` leaves the op on its
    own auto dispatch (kernel on TPU when the geometry qualifies, gather
    otherwise).  The choice is part of the graph's identity — the graph
    name carries it, so schedules/compile caches keyed on the graph
    never alias two impls.
    """
    from ..models.kv_pages import TRASH_PAGE, init_paged_kv
    from ..ops.attention import paged_decode_attention, resolve_attention_impl

    if attention_impl is not None:
        # fail at build time on a typo, not at first trace inside a task
        resolve_attention_impl(attention_impl, lambda _i: True)
    config = config or GPT2Config.tiny()
    if n_pages < 2:
        raise ValueError(f"n_pages must be >= 2 (page 0 is reserved), "
                         f"got {n_pages}")
    S, D, H = slots, config.n_embd, config.n_head
    hd, ps = config.head_dim, page_size
    M = pages_per_seq * page_size  # per-slot gathered capacity
    eps = config.ln_eps
    scale = 1.0 / math.sqrt(hd)

    specs = {
        name: jax.ShapeDtypeStruct(shape, dtype)
        for name, (shape, dtype) in gpt2.param_shapes(config).items()
    }
    for i in range(config.n_layer):
        for kind in ("k", "v"):
            specs[f"cache_{kind}_{i}"] = jax.ShapeDtypeStruct(
                (n_pages, ps, H, hd), config.dtype
            )
    specs["page_table"] = jax.ShapeDtypeStruct((S, pages_per_seq), jnp.int32)
    input_spec = {
        "ids": jax.ShapeDtypeStruct((S, 1), jnp.int32),
        "lengths": jax.ShapeDtypeStruct((S,), jnp.int32),
    }

    tasks: List[Task] = []
    out_specs: Dict[str, Any] = {}
    add = make_task_adder(tasks, out_specs, specs, input_spec, effective_flops)

    def f_embed(p, inputs):
        # per-slot position rows: slot s sits at its own lengths[s]
        lengths = inputs["lengths"]
        wpe_rows = jnp.take(p["wpe"], lengths, axis=0)[:, None, :]
        return {
            "x": p["wte"][inputs["ids"]] + wpe_rows,
            "lengths": lengths,
        }

    def f_layer(p, prev):
        """One paged cached layer: ragged paged attention over the shared
        pools (this step's k/v inserted into the gathered view — the
        pool write itself is the loop composer's fold), then the MLP."""
        x, lengths = prev["x"], prev["lengths"]
        ln1 = gpt2.layer_norm(x, p["ln1_g"], p["ln1_b"], eps)
        qkv = ln1 @ p["qkv_w"] + p["qkv_b"]
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):
            return t.reshape(S, 1, H, hd).transpose(0, 2, 1, 3)

        q, k, v = heads(q), heads(k), heads(v)
        att = paged_decode_attention(
            q, p["cache_k"], p["cache_v"], p["page_table"], lengths,
            scale, k_new=k, v_new=v, impl=attention_impl,
        )
        att = att.transpose(0, 2, 1, 3).reshape(S, 1, D)
        x = x + (att @ p["attn_proj_w"] + p["attn_proj_b"])
        ln2 = gpt2.layer_norm(x, p["ln2_g"], p["ln2_b"], eps)
        h = gpt2.ffn_contract(
            gpt2.ffn_activation(
                gpt2.ffn_expand(ln2, p["fc_w"], p["fc_b"])
            ),
            p["mlp_proj_w"], p["mlp_proj_b"],
        )
        return {"x": x + h, "k_new": k, "v_new": v, "lengths": lengths}

    def f_head(p, prev):
        x = gpt2.layer_norm(prev["x"], p["ln_f_g"], p["ln_f_b"], eps)
        return gpt2.output_projection(x, p["wte"])

    add("embed", f_embed, [], {"wte": "wte", "wpe": "wpe"},
        2.0 * S * D, "embed")
    prev = "embed"
    for i in range(config.n_layer):
        pre = f"h{i}_"
        alias = {
            "ln1_g": pre + "ln1_g", "ln1_b": pre + "ln1_b",
            "qkv_w": pre + "attn_qkv_w", "qkv_b": pre + "attn_qkv_b",
            "attn_proj_w": pre + "attn_proj_w",
            "attn_proj_b": pre + "attn_proj_b",
            "ln2_g": pre + "ln2_g", "ln2_b": pre + "ln2_b",
            "fc_w": pre + "mlp_fc_w", "fc_b": pre + "mlp_fc_b",
            "mlp_proj_w": pre + "mlp_proj_w",
            "mlp_proj_b": pre + "mlp_proj_b",
            "cache_k": f"cache_k_{i}", "cache_v": f"cache_v_{i}",
            "page_table": "page_table",
        }
        # attention gathers the slot's full paged capacity every step
        flops = (
            2.0 * S * D * 3 * D
            + 2.0 * 2.0 * S * H * M * hd
            + 2.0 * S * D * D
            + 2.0 * S * D * 4 * D * 2
        )
        tid = f"layer_{i}"
        add(tid, f_layer, [prev], alias, flops, f"layer_{i}")
        prev = tid
    add("logits", f_head, [prev], {
        "ln_f_g": "ln_f_g", "ln_f_b": "ln_f_b", "wte": "wte",
    }, 2.0 * S * D * config.vocab_size, "head")

    name = (
        f"gpt2paged_{config.n_layer}l_d{D}_s{S}_ps{ps}_p{n_pages}"
        + ("" if config.dtype == jnp.float32
           else f"_{jnp.dtype(config.dtype).name}")
        + ("" if attention_impl is None else f"_att{attention_impl}")
    )

    def init_fn(key):
        params = gpt2.init_params(config, key)
        params.update(init_paged_kv(
            config.n_layer, n_pages, ps, H, hd, config.dtype
        ))
        params["page_table"] = jnp.full(
            (S, pages_per_seq), TRASH_PAGE, jnp.int32
        )
        return params

    def reference_forward(params, inputs):
        """Independent oracle: per-slot DENSE cached forward — gather
        each slot's pages into a dense (1, H, M, hd) cache and run the
        family's ``forward_cached`` at that slot's position.  Slow
        (python loop over slots) but shares no code with the paged op."""
        from ..models.kv_pages import gather_kv

        model_params = {
            k: v for k, v in params.items()
            if not k.startswith("cache_") and k != "page_table"
        }
        pt = params["page_table"]
        outs = []
        for s in range(S):
            cache = {
                "k": jnp.stack([
                    gather_kv(params[f"cache_k_{i}"], pt[s:s + 1])
                    for i in range(config.n_layer)
                ]),
                "v": jnp.stack([
                    gather_kv(params[f"cache_v_{i}"], pt[s:s + 1])
                    for i in range(config.n_layer)
                ]),
            }
            logits, _ = gpt2.forward_cached(
                model_params, inputs["ids"][s:s + 1], cache,
                inputs["lengths"][s], config,
            )
            outs.append(logits)
        return jnp.concatenate(outs, axis=0)

    graph = TaskGraph(tasks, name=name).freeze()
    # stamped on the graph too: the engine receives the bare TaskGraph
    # and keys its prefill compile-class cache on the impl
    graph.attention_impl = attention_impl
    dag = PagedDecodeDAG(
        graph=graph,
        config=config,
        input_spec=input_spec,
        param_specs=specs,
        reference_forward=reference_forward,
        init_fn=init_fn,
    )
    dag.slots = S
    dag.page_size = ps
    dag.pages_per_seq = pages_per_seq
    dag.attention_impl = attention_impl
    return dag


def build_decode_dag_any(config: Any, **kw) -> ModelDAG:
    """Family-dispatching decode-step DAG builder: GPT-2 configs go to
    :func:`build_decode_dag`, Llama/Mixtral to
    :func:`build_backbone_decode_dag`."""
    from ..parallel.decode import _family_of

    if _family_of(config) == "gpt2":
        return build_decode_dag(config, **kw)
    return build_backbone_decode_dag(config, **kw)


def apply_cache_updates(
    params: Dict[str, Any],
    task_outputs: Dict[str, Any],
    config: Any,
    pos: int,
) -> Dict[str, Any]:
    """Fold a run's per-layer ``k_new``/``v_new`` outputs back into the
    cache params — the functional step advance for the NEXT step's graph.

    ``task_outputs``: ``DeviceReport.task_outputs`` from
    ``execute(keep_outputs=True)`` — per-task dispatch retains every
    executed task's output, which includes each layer's update dict.
    Works for every family (:func:`cache_dims`).
    """
    n_layers, _, _ = cache_dims(config)
    out = dict(params)
    for i in range(n_layers):
        o = task_outputs.get(f"layer_{i}")
        if o is None:
            raise KeyError(f"layer_{i} output missing from task_outputs")
        for kind in ("k", "v"):
            buf = out[f"cache_{kind}_{i}"]
            new = o[f"{kind}_new"].astype(buf.dtype)
            out[f"cache_{kind}_{i}"] = jax.lax.dynamic_update_slice(
                buf, new, (0, 0, pos, 0)
            )
    return out
