"""Mixtral MoE forward DAG builder: expert nodes as tasks
(BASELINE.json config #4).

Per layer the tasks are {attn_norm, attention, attn_residual, ffn_norm,
router, expert_0..E-1, moe_combine, layer_output} — ``7 + E`` tasks/layer —
plus embedding, final_norm, lm_head: ``(7 + n_experts) * n_layers + 3``
(483 for Mixtral-8x7B).  Each expert task owns that expert's three FFN
matrices (~176 MB each for 8x7B), so placement of experts under per-core
HBM limits is exactly the param-cache-locality problem the reference's MRU
policy targets (SURVEY.md §7 stage 8: "expert-placement = param-cache
locality, MRU's sweet spot").  The reference itself has no MoE.

The backbone assembly lives in :mod:`.backbone`, shared with the Llama
frontend; only the router/experts/combine section is defined here.
Experts compute densely (see :mod:`..models.mixtral` for why XLA wants
that); expert-task FLOPs are recorded as the *useful* top_k/E fraction so
cost-model comparisons against measured dense timings expose the overhead.
"""

from __future__ import annotations

from typing import Optional


from ..models import mixtral
from ..models.mixtral import MixtralConfig
from .backbone import build_decoder_dag
from ..core.graph import mark_batch0
from .gpt2_dag import DEFAULT_EFFECTIVE_FLOPS, ModelDAG, graph_name_tags


def build_moe_dag(
    config: Optional[MixtralConfig] = None,
    batch: int = 1,
    seq_len: int = 512,
    microbatches: int = 1,
    vocab_shards: int = 1,
    effective_flops: float = DEFAULT_EFFECTIVE_FLOPS,
) -> ModelDAG:
    """Build the per-op forward DAG for a Mixtral config, one task per
    expert."""
    config = config or MixtralConfig.mixtral_8x7b()
    if microbatches < 1:
        raise ValueError(f"microbatches must be >= 1, got {microbatches}")
    D, F = config.d_model, config.ffn_hidden
    E, K = config.n_experts, config.top_k
    Bm = batch // microbatches
    T = seq_len

    @mark_batch0
    def f_router(p, x):
        return mixtral.router_weights(x, p["w"], config.top_k)

    @mark_batch0
    def f_expert(p, x):
        return mixtral.expert_ffn(x, p["w_gate"], p["w_up"], p["w_down"])

    @mark_batch0
    def f_combine(p, weights, *outs):
        return mixtral.moe_combine(weights, *outs)

    def ffn_section(add, mb, i, fnorm, grp):
        """Router + E dense expert tasks fanning out from the FFN norm,
        joined by the gate-weighted combine."""
        pre = f"l{i}_"
        router = f"{mb}layer_{i}_router"
        add(router, f_router, [fnorm], {"w": pre + "router"},
            2.0 * Bm * T * D * E, grp)

        expert_ids = []
        # useful-work fraction: each token activates top_k of E experts
        expert_flops = (6.0 * Bm * T * D * F) * (K / E)
        for e in range(E):
            ex = f"{mb}layer_{i}_expert_{e}"
            add(ex, f_expert, [fnorm],
                {"w_gate": f"{pre}e{e}_w_gate",
                 "w_up": f"{pre}e{e}_w_up",
                 "w_down": f"{pre}e{e}_w_down"},
                expert_flops, grp)
            expert_ids.append(ex)

        comb = f"{mb}layer_{i}_moe_combine"
        add(comb, f_combine, [router] + expert_ids, {},
            2.0 * Bm * T * D * E, grp)
        return comb

    name = f"mixtral_{config.n_layers}l_d{D}_e{E}_b{batch}_t{T}" + graph_name_tags(
        microbatches, vocab_shards, config.dtype
    )
    return build_decoder_dag(
        config, mixtral,
        batch=batch, seq_len=seq_len, microbatches=microbatches,
        effective_flops=effective_flops, ffn_section=ffn_section, name=name,
        vocab_shards=vocab_shards,
    )
