"""Mixtral MoE forward DAG builder: expert nodes as tasks
(BASELINE.json config #4).

Per layer the tasks are {attn_norm, attention, attn_residual, ffn_norm,
router, expert_0..E-1, moe_combine, layer_output} — ``7 + E`` tasks/layer —
plus embedding, final_norm, lm_head: ``(7 + n_experts) * n_layers + 3``
(483 for Mixtral-8x7B).  Each expert task owns that expert's three FFN
matrices (~176 MB each for 8x7B), so placement of experts under per-core
HBM limits is exactly the param-cache-locality problem the reference's MRU
policy targets (SURVEY.md §7 stage 8: "expert-placement = param-cache
locality, MRU's sweet spot").  The reference itself has no MoE.

The backbone assembly lives in :mod:`.backbone`, shared with the Llama
frontend; only the router/experts/combine section is defined here.

Two dispatch modes (VERDICT r3 next #4):

* ``routed=False`` (default): experts compute densely (see
  :mod:`..models.mixtral` for why XLA historically wants that);
  expert-task FLOPs are recorded as the *useful* top_k/E fraction so
  cost-model comparisons against measured dense timings expose the
  overhead.
* ``routed=True``: each expert task computes ONLY its capacity buffer —
  the router task emits static-shape routing metadata (top-k weights,
  expert ids, in-expert positions, keep mask), each expert task
  scatter-selects its own ``(C, D)`` buffer from the activations and
  runs SwiGLU on that, and the combine gathers outputs back by the
  metadata.  Measured calibration then times the top_k/E-scaled compute
  the FLOPs field claims — the disclosed E/k inflation is gone exactly
  where expert placement matters.  Routed task fns are NOT batch-axis-0
  polymorphic (capacity positions are global per microbatch), so they
  are never re-batched across microbatch siblings.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax.numpy as jnp

from ..models import mixtral
from ..models.mixtral import MixtralConfig
from .backbone import build_decoder_dag
from ..core.graph import mark_batch0
from .gpt2_dag import DEFAULT_EFFECTIVE_FLOPS, ModelDAG, graph_name_tags


def build_moe_dag(
    config: Optional[MixtralConfig] = None,
    batch: int = 1,
    seq_len: int = 512,
    microbatches: int = 1,
    vocab_shards: int = 1,
    effective_flops: float = DEFAULT_EFFECTIVE_FLOPS,
    routed: bool = False,
    capacity_factor: float = 2.0,
) -> ModelDAG:
    """Build the per-op forward DAG for a Mixtral config, one task per
    expert."""
    config = config or MixtralConfig.mixtral_8x7b()
    if microbatches < 1:
        raise ValueError(f"microbatches must be >= 1, got {microbatches}")
    D, F = config.d_model, config.ffn_hidden
    E, K = config.n_experts, config.top_k
    Bm = batch // microbatches
    T = seq_len

    @mark_batch0
    def f_router(p, x):
        return mixtral.router_weights(x, p["w"], config.top_k)

    @mark_batch0
    def f_expert(p, x):
        return mixtral.expert_ffn(x, p["w_gate"], p["w_up"], p["w_down"])

    @mark_batch0
    def f_combine(p, weights, *outs):
        return mixtral.moe_combine(weights, *outs)

    # routed mode: static capacity per microbatch; all dispatch math comes
    # from models.mixtral's shared primitives (route_topk /
    # routed_expert_buffer / routed_collect) — one source of truth with
    # the whole-program and EP paths
    N = Bm * T
    C = mixtral.moe_capacity(N, E, K, capacity_factor)

    def f_router_routed(p, x):
        """Top-k routing metadata with static shapes (the task-graph form
        of moe_routed's dispatch prologue)."""
        return mixtral.route_topk(x.reshape(N, D), p["w"], K, C, x.dtype)

    def f_expert_routed(p, x, route, *, expert):
        """Scatter-select THIS expert's capacity buffer, then SwiGLU on
        (C, D) — top_k/E of the dense compute, matching the FLOPs field."""
        buf = mixtral.routed_expert_buffer(x.reshape(N, D), route, expert, C)
        return mixtral.expert_ffn(buf, p["w_gate"], p["w_up"], p["w_down"])

    def f_combine_routed(p, route, *bufs):
        out = mixtral.routed_collect(jnp.stack(bufs), route, N)
        return out.reshape(Bm, T, D)

    # one fn object per expert index, shared across layers AND
    # microbatches (partial binds the static index; param_alias feeds each
    # task its own expert's weights) — E compiles total, not E x layers
    routed_expert_fns = [
        partial(f_expert_routed, expert=e) for e in range(E)
    ]

    def ffn_section(add, mb, i, fnorm, grp):
        """Router + E expert tasks fanning out from the FFN norm, joined
        by the gate-weighted combine.  Dense mode: every expert sees every
        token; routed mode: every expert sees only its capacity buffer."""
        pre = f"l{i}_"
        router = f"{mb}layer_{i}_router"
        add(router,
            f_router_routed if routed else f_router,
            [fnorm], {"w": pre + "router"},
            2.0 * Bm * T * D * E, grp)

        expert_ids = []
        # useful-work fraction: each token activates top_k of E experts.
        # Dense mode computes E/K times this (disclosed); routed mode
        # actually computes it (capacity slack included via C)
        expert_flops = (
            (6.0 * C * D * F) + N * K * D  # FFN on the buffer + dispatch
            if routed
            else (6.0 * Bm * T * D * F) * (K / E)
        )
        for e in range(E):
            ex = f"{mb}layer_{i}_expert_{e}"
            add(ex,
                routed_expert_fns[e] if routed else f_expert,
                [fnorm, router] if routed else [fnorm],
                {"w_gate": f"{pre}e{e}_w_gate",
                 "w_up": f"{pre}e{e}_w_up",
                 "w_down": f"{pre}e{e}_w_down"},
                expert_flops, grp)
            expert_ids.append(ex)

        comb = f"{mb}layer_{i}_moe_combine"
        add(comb,
            f_combine_routed if routed else f_combine,
            [router] + expert_ids, {},
            2.0 * Bm * T * D * E, grp)
        return comb

    name = (
        f"mixtral_{config.n_layers}l_d{D}_e{E}_b{batch}_t{T}"
        + ("_routed" if routed else "")
        + graph_name_tags(microbatches, vocab_shards, config.dtype)
    )
    dag = build_decoder_dag(
        config, mixtral,
        batch=batch, seq_len=seq_len, microbatches=microbatches,
        effective_flops=effective_flops, ffn_section=ffn_section, name=name,
        vocab_shards=vocab_shards,
    )
    if routed:
        # the oracle for a routed DAG is the routed whole-program forward
        # applied PER MICROBATCH: the DAG routes each microbatch
        # independently (its own capacity + arrival order), so a
        # whole-batch routing oracle would drop different assignments
        # whenever microbatches > 1 and capacity bites
        def routed_reference(p, ids):
            outs = [
                mixtral.forward(
                    p, ids[m * Bm:(m + 1) * Bm], config,
                    routed=True, capacity_factor=capacity_factor,
                )
                for m in range(microbatches)
            ]
            return outs[0] if len(outs) == 1 else jnp.concatenate(outs, 0)

        dag.reference_forward = routed_reference
    return dag
