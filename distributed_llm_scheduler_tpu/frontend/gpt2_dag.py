"""GPT-2 forward-pass DAG builder: the TPU-native LLMDAGExtractor.

Replaces the reference's torch/transformers extractor (reference
``test_gpt2.py:45-168``) with a JAX-native builder over our own model: the
same 8-tasks-per-layer structure (ln1, attention, attn_residual, ln2,
ffn_expand, ffn_activation, ffn_contract, layer_output) plus embedding,
final_ln, and a weight-tied output_projection — ``8*n_layer + 3`` tasks; 99
for GPT-2 small, matching the reference/paper count — but where the
reference stores only heuristic estimates, every task here carries:

* a **jittable fn** ``fn(params: Dict[str, Array], *dep_outputs)`` the
  device backend compiles and dispatches;
* **real param byte sizes** from the model's shapes (vs the reference's
  0.5 GB-per-param fiction, ``schedulers.py:70``);
* **real activation byte sizes** for its output via ``jax.eval_shape``
  (vs the reference's crude weight-shape product, ``test_gpt2.py:18-31``);
* an **analytic FLOP count**, turned into a seed ``compute_time`` estimate
  that the measured cost model later replaces (reference analog: the
  class-based constants in ``test_gpt2.py:33-43``).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp

from ..core.graph import (
    Task,
    TaskGraph,
    mark_batch0,
    mark_concat0,
    mark_rootslice,
)
from ..models import gpt2
from ..models.gpt2 import GPT2Config
from .vocab_sharding import logit_concat_fn, make_embed_partial_fn, shard_bounds

# Seed estimate for compute_time: effective sustained FLOP/s of one core on
# these op sizes.  Deliberately rough — the calibrated cost model
# (utils/costmodel) overwrites compute_time with measured timings.
DEFAULT_EFFECTIVE_FLOPS = 2.0e12


@dataclasses.dataclass
class ModelDAG:
    """A task graph plus everything needed to actually run it.

    Shared by every model-family frontend (GPT-2 here, Llama in
    ``llama_dag.py``, Mixtral in ``moe_dag.py``); ``config`` is the family's
    own config dataclass and ``init_fn`` its param initializer (defaults to
    GPT-2's for backward compatibility).
    """

    graph: TaskGraph
    config: Any
    input_spec: jax.ShapeDtypeStruct
    # param name -> ShapeDtypeStruct; materialize with init_params()
    param_specs: Dict[str, Any]
    # the fused single-program oracle: forward(params, input_ids)
    reference_forward: Callable[..., Any]
    # key -> flat params dict for this family's config
    init_fn: Callable[[Any], Dict[str, Any]] = None  # type: ignore[assignment]

    def init_params(self, key: Optional[jax.Array] = None) -> Dict[str, Any]:
        key = key if key is not None else jax.random.PRNGKey(0)
        if self.init_fn is None:
            raise ValueError(
                "ModelDAG has no init_fn; the family's builder must supply one"
            )
        return self.init_fn(key)

    def make_inputs(self, key: Optional[jax.Array] = None) -> jax.Array:
        key = key if key is not None else jax.random.PRNGKey(1)
        return jax.random.randint(
            key, self.input_spec.shape, 0, self.config.vocab_size, dtype=jnp.int32
        )


def _bytes_of(spec: Any) -> int:
    """Total bytes of a spec pytree (single ShapeDtypeStruct or any nest —
    train-DAG tasks output dicts of arrays)."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(spec):
        size = 1
        for s in leaf.shape:
            size *= s
        total += size * jnp.dtype(leaf.dtype).itemsize
    return total


_GB = 1024**3


def graph_name_tags(microbatches: int, vocab_shards: int, dtype: Any) -> str:
    """Cache-key-critical name suffix shared by every family builder.

    Graph names key the measured cost-model cache (utils/costmodel), so any
    build option that changes task structure or timings MUST appear here —
    one place, or families drift and stale timings get re-applied.
    """
    return (
        (f"_mb{microbatches}" if microbatches > 1 else "")
        # the 'a' marks lane-ALIGNED shard boundaries (vocab_sharding
        # .shard_bounds align=128): per-shard shapes differ from the old
        # balanced split, and the calibration cache validates by task-id
        # set only — the tag keeps stale pre-alignment caches from
        # replaying wrong per-task seconds
        + (f"_vs{vocab_shards}a" if vocab_shards > 1 else "")
        + ("" if dtype == jnp.float32 else f"_{jnp.dtype(dtype).name}")
    )


def make_task_adder(
    tasks: List["Task"],
    out_specs: Dict[str, Any],
    specs: Dict[str, Any],
    input_spec: Any,
    effective_flops: float,
) -> Callable[..., None]:
    """The one task-construction closure every frontend builder shares.

    Returns ``add(tid, fn, deps, alias, flops, group)``: infers the task's
    output spec with ``jax.eval_shape`` chained through ``out_specs``,
    computes real activation/param byte sizes, and appends a fully-wired
    :class:`Task`.  ``alias`` maps fn-local param names -> global param
    names; structurally identical tasks (every layer's ln1, ...) share ONE
    fn object so jit compiles each op shape once, not once per layer.
    """

    def add(
        tid: str,
        fn: Callable[..., Any],
        deps: List[str],
        alias: Dict[str, str],
        flops: float,
        group: str,
    ) -> None:
        dep_specs = [out_specs[d] for d in deps] if deps else [input_spec]
        pspec = {loc: specs[glob] for loc, glob in alias.items()}
        out = jax.eval_shape(lambda pd, *a: fn(pd, *a), pspec, *dep_specs)
        out_specs[tid] = out
        globals_ = list(alias.values())
        tasks.append(
            Task(
                tid,
                memory_required=_bytes_of(out) / _GB,
                compute_time=max(flops / effective_flops, 1e-7),
                dependencies=list(deps),
                params_needed=set(globals_),
                param_bytes={g: _bytes_of(specs[g]) for g in globals_},
                fn=fn,
                arg_tasks=list(deps),
                param_alias=dict(alias),
                out_shape=out,
                flops=flops,
                group=group,
            )
        )

    return add


def build_gpt2_dag(
    config: Optional[GPT2Config] = None,
    batch: int = 1,
    seq_len: int = 512,
    microbatches: int = 1,
    vocab_shards: int = 1,
    effective_flops: float = DEFAULT_EFFECTIVE_FLOPS,
) -> ModelDAG:
    """Build the per-op forward DAG for a GPT-2 config.

    Sequence length defaults to 512 like the reference's shape hint
    (test_gpt2.py:53).  Shapes are static; every task fn is traceable.

    ``microbatches > 1`` splits the batch into independent per-microbatch
    task chains sharing the layer weights, joined by a final concat — the
    DAG shape of pipeline parallelism.  Good placement keeps each layer's
    weights resident on one core while microbatches stream through
    (1F1B-style overlap emerges from list scheduling); naive placement
    reloads/transfers weights per microbatch.  With ``microbatches=1`` the
    graph is the reference's 99-task shape exactly.

    ``vocab_shards > 1`` splits the tied table into vocab-range row shards
    (``wte_shard_k``) and shards BOTH of its uses — task-graph tensor
    parallelism for the one parameter that dominates host-link load time:
    the embedding lookup becomes per-shard partial tasks summed by a combine
    task, and the weight-tied output projection becomes per-shard logit
    slices concatenated along the vocab axis.  Each logit-slice task shares
    its shard's group with the matching embedding partial, so placement
    naturally reuses the resident shard (tying preserved per shard) and the
    full ``wte`` table exists nowhere: its load spreads over as many device
    queues as the scheduler parks shards on, instead of gating the whole
    pipeline behind one sequential load.
    """
    config = config or GPT2Config.small()
    if seq_len > config.n_positions:
        raise ValueError(
            f"seq_len {seq_len} exceeds n_positions {config.n_positions}"
        )
    if batch % microbatches != 0:
        raise ValueError(f"batch {batch} not divisible by microbatches {microbatches}")
    B, T, D, H, V = batch, seq_len, config.n_embd, config.n_head, config.vocab_size
    Bm = B // microbatches
    S = vocab_shards
    eps = config.ln_eps

    specs = {
        name: jax.ShapeDtypeStruct(shape, dtype)
        for name, (shape, dtype) in gpt2.param_shapes(config).items()
    }
    shard_lo = shard_bounds(V, S)
    if S > 1:
        for k in range(S):
            specs[f"wte_shard_{k}"] = jax.ShapeDtypeStruct(
                (shard_lo[k + 1] - shard_lo[k], D), specs["wte"].dtype
            )
    input_spec = jax.ShapeDtypeStruct((B, T), jnp.int32)

    tasks: List[Task] = []
    # running map of task_id -> output spec, for eval_shape chaining
    out_specs: Dict[str, Any] = {}
    add = make_task_adder(tasks, out_specs, specs, input_spec, effective_flops)

    # ---- task fns: fn(params_dict, *dep_outputs), local param names ------
    def make_f_embedding(lo, hi):
        def f_embedding(p, input_ids):
            return gpt2.embedding(input_ids[lo:hi], p["wte"], p["wpe"])

        return mark_rootslice(
            f_embedding, "gpt2_embedding", lo, hi, make_f_embedding
        )

    # batch-axis-0-polymorphic ops are marked for the segment re-batching
    # pass (backends/rebatch.py): per-token math, safe to run on sibling
    # microbatches' concatenated inputs.  f_concat (axis-0 concat) is NOT
    # batch0; the embedding roots carry slice-family markers
    # (mark_rootslice) so co-located siblings merge into full-batch
    # gathers instead.
    @mark_batch0
    def f_embed_combine(p, *partials):
        T_ = partials[0].shape[-2]
        out = partials[0]
        for part in partials[1:]:
            out = out + part
        return out + p["wpe"][:T_]

    @mark_concat0
    def f_concat(p, *chunks):
        return jnp.concatenate(chunks, axis=0)

    @mark_batch0
    def f_ln(p, x):
        return gpt2.layer_norm(x, p["g"], p["b"], eps)

    @mark_batch0
    def f_attn(p, x):
        return gpt2.causal_attention(
            x, p["qkv_w"], p["qkv_b"], p["proj_w"], p["proj_b"], config.n_head
        )

    @mark_batch0
    def f_residual(p, a, b):
        return gpt2.residual_add(a, b)

    @mark_batch0
    def f_ffn_expand(p, x):
        return gpt2.ffn_expand(x, p["fc_w"], p["fc_b"])

    @mark_batch0
    def f_ffn_act(p, x):
        return gpt2.ffn_activation(x)

    @mark_batch0
    def f_ffn_contract(p, x):
        return gpt2.ffn_contract(x, p["proj_w"], p["proj_b"])

    @mark_batch0
    def f_output_projection(p, x):
        return gpt2.output_projection(x, p["wte"])

    @mark_batch0
    def f_logit_shard(p, x):
        """Logit slice via the tied table's row shard: x @ shard.T — runs
        wherever the embedding parked that shard, so the tied table is
        never loaded twice (nor anywhere in full)."""
        return x @ p["shard"].T

    # ---- graph assembly (8 tasks/layer + 3 per microbatch chain,
    # reference test_gpt2.py:54-166; mb prefix only when pipelining) -------
    hd = D // H
    mb_outputs: List[str] = []
    for m in range(microbatches):
        mb = f"mb{m}_" if microbatches > 1 else ""
        emb = f"{mb}embedding"
        if S > 1:
            part_ids = []
            for k in range(S):
                rows = specs[f"wte_shard_{k}"].shape[0]
                pid = f"{mb}embedding_shard_{k}"
                add(pid,
                    make_embed_partial_fn(m * Bm, (m + 1) * Bm, shard_lo[k], rows),
                    [], {"shard": f"wte_shard_{k}"},
                    3.0 * Bm * T * D, f"vocab_shard_{k}")
                part_ids.append(pid)
            add(emb, f_embed_combine, part_ids, {"wpe": "wpe"},
                (S + 1.0) * Bm * T * D, "embed")
        else:
            add(emb, make_f_embedding(m * Bm, (m + 1) * Bm), [],
                {"wte": "wte", "wpe": "wpe"}, 2.0 * Bm * T * D, "embed")

        prev = emb  # residual-stream carrier entering each layer
        for i in range(config.n_layer):
            pre, grp = f"h{i}_", f"layer_{i}"
            ln1 = f"{mb}layer_{i}_ln1"
            add(ln1, f_ln, [prev],
                {"g": pre + "ln1_g", "b": pre + "ln1_b"}, 5.0 * Bm * T * D, grp)

            attn = f"{mb}layer_{i}_attention"
            attn_flops = (
                2.0 * Bm * T * D * 3 * D          # qkv projection
                + 2.0 * 2.0 * Bm * H * T * T * hd  # scores + probs@v
                + 2.0 * Bm * T * D * D             # output projection
            )
            add(attn, f_attn, [ln1],
                {"qkv_w": pre + "attn_qkv_w", "qkv_b": pre + "attn_qkv_b",
                 "proj_w": pre + "attn_proj_w", "proj_b": pre + "attn_proj_b"},
                attn_flops, grp)

            attn_res = f"{mb}layer_{i}_attn_residual"
            add(attn_res, f_residual, [prev, attn], {}, 1.0 * Bm * T * D, grp)

            ln2 = f"{mb}layer_{i}_ln2"
            add(ln2, f_ln, [attn_res],
                {"g": pre + "ln2_g", "b": pre + "ln2_b"}, 5.0 * Bm * T * D, grp)

            expand = f"{mb}layer_{i}_ffn_expand"
            add(expand, f_ffn_expand, [ln2],
                {"fc_w": pre + "mlp_fc_w", "fc_b": pre + "mlp_fc_b"},
                2.0 * Bm * T * D * 4 * D, grp)

            act = f"{mb}layer_{i}_ffn_activation"
            add(act, f_ffn_act, [expand], {}, 8.0 * Bm * T * 4 * D, grp)

            contract = f"{mb}layer_{i}_ffn_contract"
            add(contract, f_ffn_contract, [act],
                {"proj_w": pre + "mlp_proj_w", "proj_b": pre + "mlp_proj_b"},
                2.0 * Bm * T * 4 * D * D, grp)

            layer_out = f"{mb}layer_{i}_output"
            add(layer_out, f_residual, [attn_res, contract], {},
                1.0 * Bm * T * D, grp)
            prev = layer_out

        fln = f"{mb}final_ln"
        add(fln, f_ln, [prev], {"g": "ln_f_g", "b": "ln_f_b"},
            5.0 * Bm * T * D, "head")
        # weight tying: reuses the embedding table (test_gpt2.py:160-166);
        # sharded builds tie per-shard, so the full table exists nowhere
        proj = f"{mb}output_projection"
        if S > 1:
            slice_ids = []
            for k in range(S):
                rows = specs[f"wte_shard_{k}"].shape[0]
                sid = f"{mb}output_projection_shard_{k}"
                add(sid, f_logit_shard, [fln], {"shard": f"wte_shard_{k}"},
                    2.0 * Bm * T * D * rows, f"vocab_shard_{k}")
                slice_ids.append(sid)
            add(proj, logit_concat_fn, slice_ids, {}, 1.0 * Bm * T * V, "head")
        else:
            add(proj, f_output_projection, [fln], {"wte": "wte"},
                2.0 * Bm * T * D * V, "head")
        mb_outputs.append(proj)

    if microbatches > 1:
        add("output_concat", f_concat, mb_outputs, {}, 1.0 * B * T * V, "head")

    name = f"gpt2_{config.n_layer}l_d{D}_b{B}_t{T}" + graph_name_tags(
        microbatches, S, config.dtype
    )

    def init_fn(key):
        params = gpt2.init_params(config, key)
        for k in range(S if S > 1 else 0):
            params[f"wte_shard_{k}"] = params["wte"][shard_lo[k]:shard_lo[k + 1]]
        return params

    graph = TaskGraph(tasks, name=name).freeze()
    return ModelDAG(
        graph=graph,
        config=config,
        input_spec=input_spec,
        param_specs=specs,
        reference_forward=partial(gpt2.forward, config=config),
        init_fn=init_fn,
    )


def execute_dag_locally(
    dag: ModelDAG, params: Dict[str, Any], input_ids: Any
) -> Any:
    """Run the DAG task-by-task in topo order on the default device.

    The single-device correctness oracle: must produce bit-identical output
    to ``dag.reference_forward`` modulo fusion-order float differences.
    Backends replace this with placed, timed execution.
    """
    outputs: Dict[str, Any] = {}
    jitted: Dict[Any, Any] = {}
    for tid in dag.graph.topo_order:
        task = dag.graph[tid]
        pd = {loc: params[glob] for loc, glob in task.param_items()}
        args = (
            [outputs[d] for d in (task.arg_tasks or task.dependencies)]
            if task.dependencies
            else [input_ids]
        )
        if task.fn not in jitted:
            jitted[task.fn] = jax.jit(task.fn)
        outputs[tid] = jitted[task.fn](pd, *args)
    return outputs[dag.graph.topo_order[-1]]
