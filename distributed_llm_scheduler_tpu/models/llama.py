"""Llama-3 in pure JAX: second model family (BASELINE.json config #3).

Same design as :mod:`.gpt2` — functional, flat ``Dict[str, jax.Array]``
params whose names are shared with the DAG frontend's ``params_needed``
vocabulary — but the Llama architecture: RMSNorm (no biases), rotary
position embeddings (no learned position table), grouped-query attention
(n_kv_heads < n_heads), SwiGLU FFN, untied LM head.

The reference never models Llama (its extractor is GPT-2-only, reference
``test_gpt2.py:45-168``); this family exists because the rebuild's baseline
configs call for "Llama-3 8B layer-wise DAG, pipeline-stage scheduling
across v5e-16".  Per-op functions are individually jittable so the DAG
frontend (``frontend/llama_dag.py``) wraps them as task fns; ``forward``
is the fused oracle.

TPU notes: all matmuls run in the model dtype (bfloat16 on TPU) for the
MXU; RMSNorm and softmax accumulate in float32.  RoPE tables are computed
inside the jitted fn from static shapes — XLA constant-folds them.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from ..ops.attention import gqa_mha as _fused_gqa


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128_256
    max_seq_len: int = 8192
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    ffn_hidden: int = 14_336
    rope_theta: float = 500_000.0
    rms_eps: float = 1e-5
    dtype: Any = jnp.float32

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @classmethod
    def llama3_8b(cls, **kw) -> "LlamaConfig":
        """Llama-3 8B (8.03B params): the config #3 target."""
        return cls(**kw)

    @classmethod
    def tiny(cls, **kw) -> "LlamaConfig":
        """Test-sized: 2 layers, 128 wide, GQA 4:2 — CPU-fast, same topology."""
        kw.setdefault("vocab_size", 512)
        kw.setdefault("max_seq_len", 128)
        kw.setdefault("d_model", 128)
        kw.setdefault("n_layers", 2)
        kw.setdefault("n_heads", 4)
        kw.setdefault("n_kv_heads", 2)
        kw.setdefault("ffn_hidden", 256)
        kw.setdefault("rope_theta", 10_000.0)
        return cls(**kw)


# -- parameter init ---------------------------------------------------------

def init_params(config: LlamaConfig, key: jax.Array) -> Dict[str, jax.Array]:
    """Flat naming scheme shared with the DAG frontend:
    ``tok_emb, l{i}_attn_norm_g, l{i}_wq/wk/wv/wo, l{i}_ffn_norm_g,
    l{i}_w_gate/w_up/w_down, final_norm_g, lm_head``."""
    std = 0.02
    d, dtype = config.d_model, config.dtype
    hd, nh, nkv = config.head_dim, config.n_heads, config.n_kv_heads
    f = config.ffn_hidden
    params: Dict[str, jax.Array] = {}

    def normal(key, shape, scale=std):
        return (scale * jax.random.normal(key, shape)).astype(dtype)

    keys = iter(jax.random.split(key, 2 + config.n_layers * 7))
    params["tok_emb"] = normal(next(keys), (config.vocab_size, d))
    out_scale = std / math.sqrt(2 * config.n_layers)
    for i in range(config.n_layers):
        p = f"l{i}_"
        params[p + "attn_norm_g"] = jnp.ones((d,), dtype)
        params[p + "wq"] = normal(next(keys), (d, nh * hd))
        params[p + "wk"] = normal(next(keys), (d, nkv * hd))
        params[p + "wv"] = normal(next(keys), (d, nkv * hd))
        params[p + "wo"] = normal(next(keys), (nh * hd, d), out_scale)
        params[p + "ffn_norm_g"] = jnp.ones((d,), dtype)
        params[p + "w_gate"] = normal(next(keys), (d, f))
        params[p + "w_up"] = normal(next(keys), (d, f))
        params[p + "w_down"] = normal(next(keys), (f, d), out_scale)
    params["final_norm_g"] = jnp.ones((d,), dtype)
    params["lm_head"] = normal(next(keys), (d, config.vocab_size))
    return params


def param_shapes(config: LlamaConfig) -> Dict[str, Tuple[Tuple[int, ...], Any]]:
    shaped = jax.eval_shape(
        lambda k: init_params(config, k), jax.ShapeDtypeStruct((2,), jnp.uint32)
    )
    return {k: (v.shape, v.dtype) for k, v in shaped.items()}


def num_params(config: LlamaConfig) -> int:
    return sum(math.prod(shape) for shape, _ in param_shapes(config).values())


# -- per-op functions (DAG task granularity) --------------------------------

def rms_norm(x: jax.Array, g: jax.Array, eps: float = 1e-5) -> jax.Array:
    xf = x.astype(jnp.float32)
    scale = jax.lax.rsqrt((xf * xf).mean(-1, keepdims=True) + eps)
    return (xf * scale * g.astype(jnp.float32)).astype(x.dtype)


def embedding(input_ids: jax.Array, tok_emb: jax.Array) -> jax.Array:
    return tok_emb[input_ids]


def rope_tables(T: int, head_dim: int, theta: float) -> Tuple[jax.Array, jax.Array]:
    """(cos, sin) of shape (T, head_dim//2), float32.  Static-shape; XLA
    constant-folds these when they appear inside a jitted task fn."""
    exponents = jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim
    inv_freq = 1.0 / (theta ** exponents)
    ang = jnp.arange(T, dtype=jnp.float32)[:, None] * inv_freq[None, :]
    return jnp.cos(ang), jnp.sin(ang)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """x: (B, H, T, hd) with interleaved (even, odd) rotation pairs."""
    x1, x2 = x[..., 0::2], x[..., 1::2]
    xf1, xf2 = x1.astype(jnp.float32), x2.astype(jnp.float32)
    r1 = xf1 * cos - xf2 * sin
    r2 = xf1 * sin + xf2 * cos
    out = jnp.stack([r1, r2], axis=-1).reshape(x.shape)
    return out.astype(x.dtype)


def gqa_attention(
    x: jax.Array,
    wq: jax.Array,
    wk: jax.Array,
    wv: jax.Array,
    wo: jax.Array,
    n_heads: int,
    n_kv_heads: int,
    rope_theta: float,
) -> jax.Array:
    """Causal grouped-query attention with RoPE, incl. output projection —
    one task, matching the per-layer "attention" granularity of the GPT-2
    DAG (reference test_gpt2.py:75-90 puts qkv+proj on a single task)."""
    B, T, D = x.shape
    hd = wq.shape[-1] // n_heads

    q = (x @ wq).reshape(B, T, n_heads, hd).transpose(0, 2, 1, 3)
    k = (x @ wk).reshape(B, T, n_kv_heads, hd).transpose(0, 2, 1, 3)
    v = (x @ wv).reshape(B, T, n_kv_heads, hd).transpose(0, 2, 1, 3)

    cos, sin = rope_tables(T, hd, rope_theta)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)

    # fused flash-attention kernel on TPU (KV heads broadcast across their
    # query group inside gqa_mha), plain-XLA path elsewhere (ops/)
    out = _fused_gqa(q, k, v, causal=True)
    out = out.transpose(0, 2, 1, 3).reshape(B, T, D)
    return out @ wo


def ffn_gate(x: jax.Array, w_gate: jax.Array) -> jax.Array:
    return x @ w_gate


def ffn_up(x: jax.Array, w_up: jax.Array) -> jax.Array:
    return x @ w_up


def ffn_glu(gate: jax.Array, up: jax.Array) -> jax.Array:
    return jax.nn.silu(gate) * up


def ffn_down(x: jax.Array, w_down: jax.Array) -> jax.Array:
    return x @ w_down


def residual_add(a: jax.Array, b: jax.Array) -> jax.Array:
    return a + b


def lm_head(x: jax.Array, w: jax.Array) -> jax.Array:
    return x @ w


# -- whole-model forward (fused baseline + correctness oracle) --------------

def transformer_block(
    block_params: Dict[str, jax.Array], x: jax.Array, config: LlamaConfig
) -> jax.Array:
    """One layer (RMSNorm + GQA + SwiGLU with residuals), params keyed by
    the unprefixed names — the rematerialization unit."""
    h = rms_norm(x, block_params["attn_norm_g"], config.rms_eps)
    h = gqa_attention(
        h, block_params["wq"], block_params["wk"], block_params["wv"],
        block_params["wo"], config.n_heads, config.n_kv_heads,
        config.rope_theta,
    )
    x = residual_add(x, h)
    h = rms_norm(x, block_params["ffn_norm_g"], config.rms_eps)
    g = ffn_gate(h, block_params["w_gate"])
    u = ffn_up(h, block_params["w_up"])
    h = ffn_down(ffn_glu(g, u), block_params["w_down"])
    return residual_add(x, h)


_BLOCK_KEYS = (
    "attn_norm_g", "wq", "wk", "wv", "wo", "ffn_norm_g",
    "w_gate", "w_up", "w_down",
)


def forward(
    params: Dict[str, jax.Array],
    input_ids: jax.Array,
    config: LlamaConfig,
    remat: bool = False,
) -> jax.Array:
    """``remat=True`` checkpoints each block (HBM for FLOPs), as in
    :func:`..gpt2.forward`."""
    return backbone_forward(
        params, input_ids, config, transformer_block, _BLOCK_KEYS,
        remat=remat,
    )


_LAYER_PREFIX_RE = None  # compiled lazily (module import stays light)


def stack_layers(
    params: Dict[str, jax.Array], n_layers: int, keys: Tuple[str, ...]
) -> Dict[str, jax.Array]:
    """Per-layer ``l{i}_*`` tensors -> stacked ``layers_*`` with a leading
    layer dim (non-layer params unchanged) — the scanned-forward layout.
    Shared by the Llama-backbone families (Mixtral reuses it)."""
    import re

    global _LAYER_PREFIX_RE
    if _LAYER_PREFIX_RE is None:
        _LAYER_PREFIX_RE = re.compile(r"^l\d+_")
    out = {k: v for k, v in params.items() if not _LAYER_PREFIX_RE.match(k)}
    for key in keys:
        out["layers_" + key] = jnp.stack(
            [params[f"l{i}_{key}"] for i in range(n_layers)]
        )
    return out


def backbone_forward(
    params: Dict[str, jax.Array],
    input_ids: jax.Array,
    config: Any,
    block_fn: Any,
    layer_keys: Tuple[str, ...],
    remat: bool = False,
    scan: bool = False,
) -> jax.Array:
    """The one Llama-backbone forward skeleton: embed -> n_layers x block
    -> final RMSNorm -> LM head.  Parameterized by the layer block so
    Llama, Mixtral (per-expert AND stacked-EP layouts), and their scanned
    variants all share it instead of drifting.  ``scan=True`` expects
    stacked ``layers_*`` params (:func:`stack_layers`) and runs the block
    under ``lax.scan`` — traced/compiled once regardless of depth;
    ``remat=True`` checkpoints the block either way.
    """
    block = (
        jax.checkpoint(block_fn, static_argnums=(2,)) if remat else block_fn
    )
    x = embedding(input_ids, params["tok_emb"])
    if scan:
        stacked = {k: params["layers_" + k] for k in layer_keys}

        def step(h, layer_params):
            return block(layer_params, h, config), None

        x, _ = jax.lax.scan(step, x, stacked)
    else:
        for i in range(config.n_layers):
            p = f"l{i}_"
            x = block({k: params[p + k] for k in layer_keys}, x, config)
    x = rms_norm(x, params["final_norm_g"], config.rms_eps)
    return lm_head(x, params["lm_head"])


def stack_layer_params(
    params: Dict[str, jax.Array], config: LlamaConfig
) -> Dict[str, jax.Array]:
    return stack_layers(params, config.n_layers, _BLOCK_KEYS)


def forward_scan(
    params: Dict[str, jax.Array],
    input_ids: jax.Array,
    config: LlamaConfig,
    remat: bool = False,
) -> jax.Array:
    """Forward over stacked layer params (cf. :func:`..gpt2.forward_scan`);
    matches :func:`forward` numerically."""
    return backbone_forward(
        params, input_ids, config, transformer_block, _BLOCK_KEYS,
        remat=remat, scan=True,
    )


# -- KV-cache decoding (models/decode.py drives this) ------------------------

def init_cache(config: LlamaConfig, batch: int, max_len: int):
    from . import decode

    return decode.init_cache(
        config.n_layers, batch, config.n_kv_heads, max_len,
        config.head_dim, config.dtype,
    )


def attention_cached(
    x: jax.Array,
    block_params: Dict[str, jax.Array],
    cache,
    layer: int,
    pos_start,
    config: Any,
):
    """GQA with RoPE at absolute positions [pos_start, pos_start+T), reading
    and writing the stacked-layer KV cache.  Shared with Mixtral (same
    Llama-backbone attention, reference-free — the reference has no
    attention math at all)."""
    from . import decode

    B, T, D = x.shape
    nh, nkv, hd = config.n_heads, config.n_kv_heads, config.head_dim

    q = (x @ block_params["wq"]).reshape(B, T, nh, hd).transpose(0, 2, 1, 3)
    k = (x @ block_params["wk"]).reshape(B, T, nkv, hd).transpose(0, 2, 1, 3)
    v = (x @ block_params["wv"]).reshape(B, T, nkv, hd).transpose(0, 2, 1, 3)

    # RoPE at absolute positions: tables for the full cache length (static),
    # sliced at the (possibly traced) write cursor
    M = cache["k"].shape[3]
    cos_all, sin_all = rope_tables(M, hd, config.rope_theta)
    cos = jax.lax.dynamic_slice_in_dim(cos_all, pos_start, T, axis=0)
    sin = jax.lax.dynamic_slice_in_dim(sin_all, pos_start, T, axis=0)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)

    cache = decode.update_layer_cache(cache, layer, k, v, pos_start)
    kc, vc, ks, vs = decode.layer_view(cache, layer)
    out = decode.cached_attention(
        q, kc, vc, pos_start, 1.0 / math.sqrt(hd),
        k_scale=ks, v_scale=vs,
    )
    out = out.transpose(0, 2, 1, 3).reshape(B, T, D)
    return out @ block_params["wo"], cache


def forward_cached(
    params: Dict[str, jax.Array],
    input_ids: jax.Array,
    cache,
    pos_start,
    config: LlamaConfig,
) -> Tuple[jax.Array, Any]:
    """Cached forward over positions [pos_start, pos_start + T); one code
    path for prefill and decode (cf. :func:`..gpt2.forward_cached`)."""
    pos_start = jnp.asarray(pos_start, jnp.int32)
    x = embedding(input_ids, params["tok_emb"])
    for i in range(config.n_layers):
        p = f"l{i}_"
        bp = {k: params[p + k] for k in _BLOCK_KEYS}
        h = rms_norm(x, bp["attn_norm_g"], config.rms_eps)
        h, cache = attention_cached(h, bp, cache, i, pos_start, config)
        x = residual_add(x, h)
        h = rms_norm(x, bp["ffn_norm_g"], config.rms_eps)
        g = ffn_gate(h, bp["w_gate"])
        u = ffn_up(h, bp["w_up"])
        h = ffn_down(ffn_glu(g, u), bp["w_down"])
        x = residual_add(x, h)
    x = rms_norm(x, params["final_norm_g"], config.rms_eps)
    return lm_head(x, params["lm_head"]), cache


def generate(
    params: Dict[str, jax.Array],
    prompt_ids: jax.Array,
    config: LlamaConfig,
    max_new_tokens: int,
    **kw,
) -> jax.Array:
    from . import decode

    return decode.generate(
        forward_cached, init_cache, params, prompt_ids, config,
        max_new_tokens, **kw,
    )


def loss_fn(
    params: Dict[str, jax.Array],
    input_ids: jax.Array,
    targets: jax.Array,
    config: LlamaConfig,
    remat: bool = False,
    scan: bool = False,
) -> jax.Array:
    fwd = forward_scan if scan else forward
    logits = fwd(params, input_ids, config, remat=remat)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return nll.mean()
