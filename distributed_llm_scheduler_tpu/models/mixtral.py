"""Mixtral-style sparse MoE in pure JAX: third model family
(BASELINE.json config #4: "Mixtral-8x7B MoE DAG, expert nodes as tasks").

Architecture = Llama backbone (RMSNorm, RoPE, GQA — reused from
:mod:`.llama`) with the SwiGLU FFN replaced by a router + N experts with
top-k gating.  The reference never models MoE (its extractor is GPT-2-only,
reference ``test_gpt2.py:45-168``); this family exists because expert
placement is exactly the param-cache-locality problem the reference's MRU
scheduler targets: each expert is a large, independently placeable set of
weights used by a data-dependent subset of tokens.

TPU/XLA note on routing — two static-shape formulations, both first-class:

* **Dense dispatch** (task DAGs, EP sharding, the default oracle): every
  expert processes every token; its output is scaled by the (possibly
  zero) top-k gate weight.  Simple, exact, placement-friendly (each
  expert is one task) — but computes ``n_experts/top_k``x the useful
  FLOPs.  The FLOP *estimates* on expert tasks are scaled by
  ``top_k/n_experts`` (the useful work) while the dense cost appears in
  measured calibration — the gap is visible, not hidden.
* **Routed dispatch** (:func:`moe_routed`, ``forward(..., routed=True)``):
  capacity-factor token routing with static capacity buffers — each
  expert computes only its top-k-assigned tokens up to capacity
  ``C = ceil(top_k * tokens / n_experts * capacity_factor)``; tokens
  beyond an expert's capacity are DROPPED (their gate contribution is
  zero), the standard static-shape sparse-MoE trade (Switch/GShard
  semantics).  At ``capacity_factor = n_experts/top_k`` nothing can drop
  and routed output equals dense output exactly (the oracle test).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from . import llama as _llama

# the Llama backbone ops are the same module-level functions
rms_norm = _llama.rms_norm
embedding = _llama.embedding
gqa_attention = _llama.gqa_attention
residual_add = _llama.residual_add
lm_head = _llama.lm_head


@dataclasses.dataclass(frozen=True)
class MixtralConfig:
    vocab_size: int = 32_000
    max_seq_len: int = 8192
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    ffn_hidden: int = 14_336
    n_experts: int = 8
    top_k: int = 2
    rope_theta: float = 1_000_000.0
    rms_eps: float = 1e-5
    dtype: Any = jnp.float32

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @classmethod
    def mixtral_8x7b(cls, **kw) -> "MixtralConfig":
        """Mixtral-8x7B (46.7B total / ~12.9B active params)."""
        return cls(**kw)

    @classmethod
    def tiny(cls, **kw) -> "MixtralConfig":
        """Test-sized: 2 layers, 4 experts, top-2 — CPU-fast, same topology."""
        kw.setdefault("vocab_size", 512)
        kw.setdefault("max_seq_len", 128)
        kw.setdefault("d_model", 64)
        kw.setdefault("n_layers", 2)
        kw.setdefault("n_heads", 4)
        kw.setdefault("n_kv_heads", 2)
        kw.setdefault("ffn_hidden", 128)
        kw.setdefault("n_experts", 4)
        kw.setdefault("top_k", 2)
        kw.setdefault("rope_theta", 10_000.0)
        return cls(**kw)


# -- parameter init ---------------------------------------------------------

def init_params(config: MixtralConfig, key: jax.Array) -> Dict[str, jax.Array]:
    """Flat naming scheme shared with the DAG frontend: the Llama names plus
    ``l{i}_router`` and per-expert ``l{i}_e{e}_w_gate/w_up/w_down``."""
    std = 0.02
    d, dtype = config.d_model, config.dtype
    hd, nh, nkv = config.head_dim, config.n_heads, config.n_kv_heads
    f, E = config.ffn_hidden, config.n_experts
    params: Dict[str, jax.Array] = {}

    def normal(key, shape, scale=std):
        return (scale * jax.random.normal(key, shape)).astype(dtype)

    keys = iter(jax.random.split(key, 2 + config.n_layers * (5 + 3 * E)))
    params["tok_emb"] = normal(next(keys), (config.vocab_size, d))
    out_scale = std / math.sqrt(2 * config.n_layers)
    for i in range(config.n_layers):
        p = f"l{i}_"
        params[p + "attn_norm_g"] = jnp.ones((d,), dtype)
        params[p + "wq"] = normal(next(keys), (d, nh * hd))
        params[p + "wk"] = normal(next(keys), (d, nkv * hd))
        params[p + "wv"] = normal(next(keys), (d, nkv * hd))
        params[p + "wo"] = normal(next(keys), (nh * hd, d), out_scale)
        params[p + "ffn_norm_g"] = jnp.ones((d,), dtype)
        params[p + "router"] = normal(next(keys), (d, E))
        for e in range(E):
            q = f"{p}e{e}_"
            params[q + "w_gate"] = normal(next(keys), (d, f))
            params[q + "w_up"] = normal(next(keys), (d, f))
            params[q + "w_down"] = normal(next(keys), (f, d), out_scale)
    params["final_norm_g"] = jnp.ones((d,), dtype)
    params["lm_head"] = normal(next(keys), (d, config.vocab_size))
    return params


def param_shapes(config: MixtralConfig) -> Dict[str, Tuple[Tuple[int, ...], Any]]:
    shaped = jax.eval_shape(
        lambda k: init_params(config, k), jax.ShapeDtypeStruct((2,), jnp.uint32)
    )
    return {k: (v.shape, v.dtype) for k, v in shaped.items()}


def num_params(config: MixtralConfig) -> int:
    return sum(math.prod(shape) for shape, _ in param_shapes(config).values())


def num_active_params(config: MixtralConfig) -> int:
    """Params touched per token: everything except the (E - top_k)
    non-selected experts per layer."""
    per_expert = 3 * config.d_model * config.ffn_hidden
    inactive = (config.n_experts - config.top_k) * per_expert * config.n_layers
    return num_params(config) - inactive


# -- MoE ops (DAG task granularity) -----------------------------------------

def router_weights(x: jax.Array, w_router: jax.Array, top_k: int) -> jax.Array:
    """Top-k gate weights, dense layout: (B, T, E) with zeros off the top-k.

    Softmax is taken over the selected logits only (Mixtral semantics:
    renormalized top-k), in float32.  Static shapes: lax.top_k + one-hot
    scatter-free reconstruction.
    """
    logits = (x @ w_router).astype(jnp.float32)  # (B, T, E)
    top_vals, top_idx = jax.lax.top_k(logits, top_k)  # (B, T, k)
    top_w = jax.nn.softmax(top_vals, axis=-1)  # (B, T, k)
    E = logits.shape[-1]
    onehot = jax.nn.one_hot(top_idx, E, dtype=top_w.dtype)  # (B, T, k, E)
    dense = jnp.einsum("btk,btke->bte", top_w, onehot)
    return dense.astype(x.dtype)


def expert_ffn(x: jax.Array, w_gate: jax.Array, w_up: jax.Array,
               w_down: jax.Array) -> jax.Array:
    """One expert's SwiGLU over ALL tokens (dense static-shape MoE)."""
    return (jax.nn.silu(x @ w_gate) * (x @ w_up)) @ w_down


def moe_combine(weights: jax.Array, *expert_outs: jax.Array) -> jax.Array:
    """Sum of expert outputs scaled by their dense gate column."""
    out = jnp.zeros_like(expert_outs[0])
    for e, eo in enumerate(expert_outs):
        out = out + weights[..., e : e + 1] * eo
    return out


def _moe(block_params: Dict[str, jax.Array], x: jax.Array,
         config: MixtralConfig) -> jax.Array:
    """Router + dense experts + combine over UNPREFIXED param names — the
    single implementation of the MoE layer math; :func:`moe_block` and
    :func:`transformer_block` both delegate here so the DAG path and the
    remat oracle cannot drift."""
    w = router_weights(x, block_params["router"], config.top_k)
    outs = [
        expert_ffn(
            x,
            block_params[f"e{e}_w_gate"],
            block_params[f"e{e}_w_up"],
            block_params[f"e{e}_w_down"],
        )
        for e in range(config.n_experts)
    ]
    return moe_combine(w, *outs)


# -- routed dispatch primitives ----------------------------------------------
# The ONE implementation of the capacity-buffer routing math, shared by the
# whole-program path (moe_routed), the EP-sharded path
# (parallel/expert.moe_routed_stacked), and the task-graph frontend
# (frontend/moe_dag routed tasks) — three consumers, one source of truth,
# so a change to capacity/position/tie-breaking semantics cannot silently
# break the oracle equivalences the tests pin.


def moe_capacity(N: int, E: int, k: int, capacity_factor: float) -> int:
    """Static per-expert capacity: ``ceil(k*N/E * cf)`` clamped to [1, N]."""
    return min(N, max(1, math.ceil(k * N / E * capacity_factor)))


def route_topk(
    xf: jax.Array, w_router: jax.Array, k: int, C: int, out_dtype
) -> Dict[str, jax.Array]:
    """Static-shape top-k routing metadata over flat tokens ``xf (N, D)``.

    Returns ``{top_w (N, k), flat_e (N*k,), pos (N*k,), keep (N*k,)}``:
    renormalized gate weights, expert id per (token, slot) assignment,
    position within the expert's arrival order (clamped to C-1 when
    dropped), and the under-capacity mask.
    """
    E = w_router.shape[-1]
    logits = (xf @ w_router).astype(jnp.float32)  # (N, E)
    top_vals, top_idx = jax.lax.top_k(logits, k)  # (N, k)
    top_w = jax.nn.softmax(top_vals, axis=-1).astype(out_dtype)

    flat_e = top_idx.reshape(-1)  # (N*k,) expert per assignment
    # position of each assignment within its expert's arrival order
    onehot = jax.nn.one_hot(flat_e, E, dtype=jnp.int32)  # (N*k, E)
    pos_all = jnp.cumsum(onehot, axis=0) - 1
    mypos = jnp.take_along_axis(pos_all, flat_e[:, None], axis=1)[:, 0]
    keep = mypos < C
    return {
        "top_w": top_w,
        "flat_e": flat_e,
        "pos": jnp.where(keep, mypos, C - 1),
        "keep": keep,
    }


def routed_dispatch(
    xf: jax.Array, route: Dict[str, jax.Array], E: int, C: int
) -> jax.Array:
    """Scatter kept assignments into the global ``(E, C, D)`` buffer."""
    N, D = xf.shape
    k = route["top_w"].shape[-1]
    tok_idx = jnp.repeat(jnp.arange(N), k)
    contrib = jnp.where(route["keep"][:, None], xf[tok_idx], 0)
    return jnp.zeros((E, C, D), xf.dtype).at[
        route["flat_e"], route["pos"]
    ].add(contrib)


def routed_expert_buffer(
    xf: jax.Array, route: Dict[str, jax.Array], expert: int, C: int
) -> jax.Array:
    """ONE expert's ``(C, D)`` capacity buffer — the task-graph form,
    where each expert task dispatches only its own tokens."""
    N, D = xf.shape
    k = route["top_w"].shape[-1]
    tok_idx = jnp.repeat(jnp.arange(N), k)
    mine = route["keep"] & (route["flat_e"] == expert)
    contrib = jnp.where(mine[:, None], xf[tok_idx], 0)
    return jnp.zeros((C, D), xf.dtype).at[route["pos"]].add(contrib)


def routed_collect(
    out_buf: jax.Array, route: Dict[str, jax.Array], N: int
) -> jax.Array:
    """Gather expert outputs ``(E, C, D)`` back to tokens ``(N, D)``,
    weighted by the renormalized gates; dropped assignments contribute 0."""
    D = out_buf.shape[-1]
    k = route["top_w"].shape[-1]
    gathered = out_buf[route["flat_e"], route["pos"]]  # (N*k, D)
    gathered = jnp.where(route["keep"][:, None], gathered, 0)
    tok_idx = jnp.repeat(jnp.arange(N), k)
    w_flat = route["top_w"].reshape(-1, 1)
    return jnp.zeros((N, D), out_buf.dtype).at[tok_idx].add(
        gathered * w_flat
    )


def route_stats(route: Dict[str, jax.Array], C: int) -> Dict[str, Any]:
    return {
        "capacity": C,
        "dropped_slots": jnp.sum(~route["keep"]),
        "total_slots": route["flat_e"].shape[0],
    }


def moe_routed(
    block_params: Dict[str, jax.Array],
    x: jax.Array,
    config: MixtralConfig,
    capacity_factor: float = 2.0,
    with_stats: bool = False,
):
    """Sparse top-k dispatch with static-shape capacity buffers.

    Every shape is static (XLA-compilable): per-expert position comes
    from a cumulative sum over the flattened (token, slot) assignment
    order, tokens land in an ``(E, C, D)`` buffer via scatter-add (each
    kept assignment owns a unique (expert, position) cell), experts run
    as ONE batched einsum over stacked weights, and outputs gather back
    weighted by the renormalized top-k gates.  Assignments whose expert
    is over capacity are dropped — their contribution is zero, exactly
    the Switch/GShard trade disclosed in the module docstring.  FLOPs
    scale with ``top_k/n_experts`` (+capacity slack) instead of running
    every expert on every token.

    Returns ``out`` or ``(out, stats)`` with ``stats = {capacity,
    dropped_slots, total_slots}`` when ``with_stats``.
    """
    B, T, D = x.shape
    E, k = config.n_experts, config.top_k
    N = B * T
    C = moe_capacity(N, E, k, capacity_factor)
    xf = x.reshape(N, D)

    route = route_topk(xf, block_params["router"], k, C, x.dtype)
    buf = routed_dispatch(xf, route, E, C)

    wg = jnp.stack([block_params[f"e{e}_w_gate"] for e in range(E)])
    wu = jnp.stack([block_params[f"e{e}_w_up"] for e in range(E)])
    wd = jnp.stack([block_params[f"e{e}_w_down"] for e in range(E)])
    h = jax.nn.silu(jnp.einsum("ecd,edf->ecf", buf, wg)) * jnp.einsum(
        "ecd,edf->ecf", buf, wu
    )
    out_buf = jnp.einsum("ecf,efd->ecd", h, wd)  # (E, C, D)

    out = routed_collect(out_buf, route, N).reshape(B, T, D)
    if with_stats:
        return out, route_stats(route, C)
    return out


def routed_transformer_block(
    block_params: Dict[str, jax.Array],
    x: jax.Array,
    config: MixtralConfig,
    capacity_factor: float = 2.0,
) -> jax.Array:
    """:func:`transformer_block` with the routed (capacity-buffer) MoE in
    place of dense dispatch — identical attention path (shared via
    :func:`_block_with_moe`), same param layout."""
    return _block_with_moe(
        block_params, x, config,
        lambda bp, h: moe_routed(bp, h, config, capacity_factor),
    )


def moe_block(params: Dict[str, jax.Array], x: jax.Array, layer: int,
              config: MixtralConfig) -> jax.Array:
    """Router + dense experts + combine, as the fused oracle composes it
    (layer-prefixed params; delegates to :func:`_moe`)."""
    p = f"l{layer}_"
    moe_keys = ["router"] + [
        f"e{e}_{s}"
        for e in range(config.n_experts)
        for s in ("w_gate", "w_up", "w_down")
    ]
    return _moe({k: params[p + k] for k in moe_keys}, x, config)


# -- whole-model forward (fused baseline + correctness oracle) --------------

def _layer_keys(config: MixtralConfig) -> Tuple[str, ...]:
    """Unprefixed per-layer param names (the remat block's vocabulary)."""
    keys = ["attn_norm_g", "wq", "wk", "wv", "wo", "ffn_norm_g", "router"]
    for e in range(config.n_experts):
        keys += [f"e{e}_w_gate", f"e{e}_w_up", f"e{e}_w_down"]
    return tuple(keys)


def _block_with_moe(
    block_params: Dict[str, jax.Array],
    x: jax.Array,
    config: MixtralConfig,
    moe_fn,
) -> jax.Array:
    """The one attention+residual block body, parameterized by the MoE
    dispatch (dense :func:`_moe` or :func:`moe_routed`) so the two block
    variants cannot drift apart on the attention path."""
    h = rms_norm(x, block_params["attn_norm_g"], config.rms_eps)
    h = gqa_attention(
        h, block_params["wq"], block_params["wk"], block_params["wv"],
        block_params["wo"], config.n_heads, config.n_kv_heads,
        config.rope_theta,
    )
    x = residual_add(x, h)
    h = rms_norm(x, block_params["ffn_norm_g"], config.rms_eps)
    return residual_add(x, moe_fn(block_params, h))


def transformer_block(
    block_params: Dict[str, jax.Array], x: jax.Array, config: MixtralConfig
) -> jax.Array:
    """One layer (RMSNorm + GQA + router/experts/combine with residuals),
    params keyed unprefixed — the rematerialization unit.  Same math as
    the prefixed :func:`moe_block` path."""
    return _block_with_moe(
        block_params, x, config, lambda bp, h: _moe(bp, h, config)
    )


def forward_with_block(
    params: Dict[str, jax.Array],
    input_ids: jax.Array,
    config: MixtralConfig,
    block_fn: Any,
    layer_keys: Tuple[str, ...],
    remat: bool = False,
    scan: bool = False,
) -> jax.Array:
    """Mixtral's forward skeleton IS the Llama backbone's
    (:func:`..llama.backbone_forward`): embed -> n_layers x block -> final
    norm -> LM head, parameterized by the layer block so the per-expert
    path (:func:`forward`), the stacked EP path
    (``parallel/expert.forward_ep``), and the scanned variants all share
    one implementation."""
    return _llama.backbone_forward(
        params, input_ids, config, block_fn, layer_keys,
        remat=remat, scan=scan,
    )


def nll_loss(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """Next-token cross-entropy in float32 (shared by both MoE paths)."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return nll.mean()


def forward(
    params: Dict[str, jax.Array],
    input_ids: jax.Array,
    config: MixtralConfig,
    remat: bool = False,
    routed: bool = False,
    capacity_factor: float = 2.0,
) -> jax.Array:
    """``remat=True`` checkpoints each block — especially valuable for MoE,
    whose dense-dispatch expert activations are ``n_experts`` times the
    dense model's.  ``routed=True`` switches every layer's MoE to the
    capacity-buffer sparse dispatch (:func:`moe_routed`) — top_k/n_experts
    the FLOPs, with the disclosed capacity-drop semantics."""
    if routed:
        import functools

        # keyword-frozen capacity keeps the (params, x, config) contract
        block = functools.partial(
            routed_transformer_block, capacity_factor=capacity_factor
        )
    else:
        block = transformer_block
    return forward_with_block(
        params, input_ids, config, block, _layer_keys(config),
        remat=remat,
    )


def stack_layer_params(
    params: Dict[str, jax.Array], config: MixtralConfig
) -> Dict[str, jax.Array]:
    """Scanned-forward layout via the shared :func:`..llama.stack_layers`;
    per-expert tensors stack to (n_layers, d, f) per expert key."""
    return _llama.stack_layers(params, config.n_layers, _layer_keys(config))


def forward_scan(
    params: Dict[str, jax.Array],
    input_ids: jax.Array,
    config: MixtralConfig,
    remat: bool = False,
) -> jax.Array:
    """Forward over stacked layer params via ``lax.scan`` — one compiled
    block regardless of depth.  Matches :func:`forward` numerically."""
    return forward_with_block(
        params, input_ids, config, transformer_block, _layer_keys(config),
        remat=remat, scan=True,
    )


# -- KV-cache decoding (models/decode.py drives this) ------------------------

def init_cache(config: MixtralConfig, batch: int, max_len: int):
    from . import decode

    return decode.init_cache(
        config.n_layers, batch, config.n_kv_heads, max_len,
        config.head_dim, config.dtype,
    )


def forward_cached(
    params: Dict[str, jax.Array],
    input_ids: jax.Array,
    cache,
    pos_start,
    config: MixtralConfig,
) -> Tuple[jax.Array, Any]:
    """Cached forward over positions [pos_start, pos_start + T).  Attention
    is the shared Llama-backbone cached path; the FFN is the same
    router/experts/combine math as :func:`transformer_block` — routing is
    per-token, so decode steps route each new token independently, exactly
    as the fused forward would."""
    pos_start = jnp.asarray(pos_start, jnp.int32)
    keys = _layer_keys(config)
    x = _llama.embedding(input_ids, params["tok_emb"])
    for i in range(config.n_layers):
        p = f"l{i}_"
        bp = {k: params[p + k] for k in keys}
        h = rms_norm(x, bp["attn_norm_g"], config.rms_eps)
        h, cache = _llama.attention_cached(h, bp, cache, i, pos_start, config)
        x = residual_add(x, h)
        h = rms_norm(x, bp["ffn_norm_g"], config.rms_eps)
        x = residual_add(x, _moe(bp, h, config))
    x = rms_norm(x, params["final_norm_g"], config.rms_eps)
    return _llama.lm_head(x, params["lm_head"]), cache


def generate(
    params: Dict[str, jax.Array],
    prompt_ids: jax.Array,
    config: MixtralConfig,
    max_new_tokens: int,
    **kw,
) -> jax.Array:
    from . import decode

    return decode.generate(
        forward_cached, init_cache, params, prompt_ids, config,
        max_new_tokens, **kw,
    )


def loss_fn(
    params: Dict[str, jax.Array],
    input_ids: jax.Array,
    targets: jax.Array,
    config: MixtralConfig,
    remat: bool = False,
    scan: bool = False,
    routed: bool = False,
) -> jax.Array:
    if routed:
        if scan:
            raise ValueError(
                "routed MoE is per-layer (stacked-expert einsums inside "
                "the block); use scan=False"
            )
        return nll_loss(
            forward(params, input_ids, config, remat=remat, routed=True),
            targets,
        )
    fwd = forward_scan if scan else forward
    return nll_loss(fwd(params, input_ids, config, remat=remat), targets)
