"""Block-allocated KV-cache pool: fixed-size pages + per-sequence tables.

The dense decode cache (:mod:`.decode`) reserves ``max_len`` rows for
every batch slot up front, so serving mixed-length traffic pays HBM for
the LONGEST request times the whole batch.  This module supplies the
vLLM-style alternative the Ragged Paged Attention line of work makes
TPU-native (PAPERS.md, arxiv 2604.15464): cache rows live in fixed-size
**pages** drawn from one shared pool, each sequence holds a **page
table** (logical page index -> physical page id), and a host-side
free-list allocator recycles pages as requests retire — so the pool is
sized for the *working set*, not ``slots x max_len``.

Three pieces, split by where they run:

* :class:`PagePool` — host-side free-list allocator with an
  HBM-budget-accounted capacity (``PagePool.from_budget`` sizes the pool
  off the device's reported memory via
  :func:`..utils.costmodel.device_hbm_bytes`).  Pure Python; never
  traced.
* :func:`init_paged_kv` — the device-side per-layer page pools
  (``(n_pages, page_size, n_kv_heads, head_dim)`` — the kernel-natural
  layout the ragged-paged-attention TPU kernels consume, pages on the
  leading axis so one gather assembles a sequence).
* scatter helpers (:func:`write_token_kv`, :func:`write_prompt_kv`) —
  static-shape jittable writes: one token's K/V row into its page slot
  (traced page id + slot), or a whole prefilled prompt page-reshaped
  into its allocated pages.

Physical page 0 is RESERVED as the trash page: unallocated page-table
entries point at it, and inactive batch slots redirect their writes to
it, so scatters never need a dynamic shape and gathers of a sequence's
unused tail read finite (masked-out) garbage instead of faulting.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

#: Default tokens per page.  16 keeps page-granularity waste under one
#: MXU sublane tile at bf16 while still amortizing the table indirection.
DEFAULT_PAGE_SIZE = 16

#: Physical page id reserved for unallocated table entries and inactive
#: slot writes (never handed out by the allocator).
TRASH_PAGE = 0


#: Schema tag for :meth:`PageOwnershipLog.snapshot`.
OWNERSHIP_SCHEMA = "dls.pages/1"


class PageOwnershipLog:
    """Append-only page ownership event stream — the static third leg of
    the page-accounting story next to the runtime ``pages_leaked`` gauge.

    Producers record four core event kinds: ``alloc``/``free`` (the
    :class:`PagePool` itself, with the pool's free/used counts after the
    event — the tiling witness) and ``assign``/``release`` (the decode
    engine, with the owning request id and the lifecycle edge —
    ``admit``/``retire``/``preempt``/``reset``).  Prefix sharing adds
    four more: ``share``/``unshare`` (the pool, refcount up/down without
    touching the free list — physical tiling counts ride along
    unchanged), ``cow`` (the engine: ``pages=[src, dst]`` of a
    copy-on-write split, dst allocated BEFORE src is released), and
    ``write`` (the engine: first generation write into a page — the
    witness PGL007 checks against live refcounts).  Ref-counted events
    carry a ``refcounts`` list (post-event, aligned with ``pages``);
    non-sharing producers omit the key entirely so disabled-sharing
    streams are byte-identical to pre-sharing ones.  The page-lifetime
    prover (:mod:`..analysis.page_pass`) replays the stream against an
    ownership lattice; recording is a dict append per pool operation and
    is completely off (zero overhead, bit-identical engine behavior)
    when no log is attached — the same None-guard contract as the
    memory profiler seam.
    """

    def __init__(self, n_pages: Optional[int] = None):
        self.n_pages = n_pages
        self.events: List[Dict[str, Any]] = []

    def record(
        self,
        kind: str,
        pages: Sequence[int],
        *,
        owner: Optional[str] = None,
        site: Optional[str] = None,
        free_pages: Optional[int] = None,
        used_pages: Optional[int] = None,
        refcounts: Optional[Sequence[int]] = None,
    ) -> None:
        e: Dict[str, Any] = {
            "seq": len(self.events),
            "kind": kind,
            "pages": [int(p) for p in pages],
            "owner": owner,
            "site": site,
            "free_pages": free_pages,
            "used_pages": used_pages,
        }
        if refcounts is not None:
            e["refcounts"] = [int(r) for r in refcounts]
        self.events.append(e)

    def __len__(self) -> int:
        return len(self.events)

    def snapshot(self) -> Dict[str, Any]:
        """JSON-ready view (schema ``dls.pages/1``) — what a serve/soak
        artifact embeds so ``doctor --serve`` can replay it offline."""
        return {
            "schema": OWNERSHIP_SCHEMA,
            "n_pages": self.n_pages,
            "events": [dict(e) for e in self.events],
        }


def pages_needed(n_tokens: int, page_size: int) -> int:
    """Pages covering ``n_tokens`` rows (ceil division)."""
    if n_tokens < 0:
        raise ValueError(f"n_tokens must be >= 0, got {n_tokens}")
    return -(-n_tokens // page_size)


def prefix_chunk_keys(tokens: Any, page_size: int) -> List[str]:
    """Chain-hash intern keys for every FULL page of a token prefix.

    Key ``i`` digests the entire prefix ``tokens[0:(i+1)*page_size]``,
    not just page ``i``'s own tokens — a KV row depends on every token
    before it, so two pages are interchangeable only when their whole
    prefixes match.  Chaining gives that for free: each key extends the
    previous digest, so a match on key ``i`` implies matches on all
    earlier keys.  Only full pages get keys (a partial tail page is
    always exclusive — generation writes into it).
    """
    if page_size < 1:
        raise ValueError(f"page_size must be >= 1, got {page_size}")
    toks = _flatten_tokens(tokens)
    h = hashlib.sha256()
    keys: List[str] = []
    for i in range(len(toks) // page_size):
        chunk = toks[i * page_size:(i + 1) * page_size]
        h.update((",".join(map(str, chunk)) + ";").encode())
        keys.append(h.hexdigest())
    return keys


def _flatten_tokens(tokens: Any) -> List[int]:
    """Host-side flatten of a token container (list, numpy row, or jax
    row) into plain ints — hashing never traces."""
    if hasattr(tokens, "reshape"):
        flat = tokens.reshape(-1)
        return [int(t) for t in flat.tolist()]
    return [int(t) for t in tokens]


def pool_bytes_per_layer(
    n_pages: int, page_size: int, n_kv_heads: int, head_dim: int, dtype: Any
) -> int:
    """HBM bytes of ONE layer's K+V pools at this geometry."""
    itemsize = jnp.dtype(dtype).itemsize
    return 2 * n_pages * page_size * n_kv_heads * head_dim * itemsize


@dataclass
class PagePool:
    """Host-side free-list page allocator over ``n_pages`` physical pages.

    Page ids are ints in ``[1, n_pages)`` — id 0 is :data:`TRASH_PAGE`
    and is never allocated.  ``alloc``/``free`` are O(k); exhaustion
    raises so callers (the continuous-batching engine) can hold requests
    queued instead of silently corrupting the pool — backpressure, not
    clamping.

    With ``sharing=True`` the pool additionally interns full prefix
    chunks (:func:`prefix_chunk_keys`): a resident page whose chain hash
    matches a new request's prefix is aliased via :meth:`share` instead
    of re-allocated, reference counts track logical owners per physical
    page, and :meth:`release_ref` returns a page to the LIFO free list
    only on last release.  The tiling witness generalizes — ``free +
    unique_used == n_pages - 1`` holds over *physical* pages at every
    event, while :attr:`logical_pages` counts what a non-sharing pool
    would have had to allocate.  With sharing off (the default) every
    page has refcount 1 and alloc/free behave — and record —
    bit-identically to the pre-sharing pool.
    """

    n_pages: int
    page_size: int = DEFAULT_PAGE_SIZE
    _free: List[int] = field(default_factory=list, repr=False)
    _allocated: set = field(default_factory=set, repr=False)
    #: optional :class:`PageOwnershipLog`; every alloc/free appends one
    #: event carrying the post-event free/used counts (the tiling
    #: witness).  None — the default — records nothing and costs nothing.
    ownlog: Optional[Any] = field(default=None, repr=False, compare=False)
    #: enable content-addressed prefix sharing (intern table + refcounts)
    sharing: bool = False
    _refs: Dict[int, int] = field(default_factory=dict, repr=False)
    _intern: Dict[str, int] = field(default_factory=dict, repr=False)
    _page_key: Dict[int, str] = field(default_factory=dict, repr=False)
    #: free pages whose intern entries are RETAINED (LRU cache of
    #: last-released shared prefixes).  Insertion-ordered dict used as an
    #: ordered set: insertion order == release order == eviction order.
    #: Always a subset of ``_free`` — cached pages are physically free
    #: (the books, the leak gauge, and the prover's tiling witness are
    #: untouched); only the intern table keeps pointing at them.
    _cached: Dict[int, None] = field(default_factory=dict, repr=False)

    def __post_init__(self) -> None:
        if self.n_pages < 2:
            raise ValueError(
                f"pool needs >= 2 pages (one is the reserved trash page), "
                f"got {self.n_pages}"
            )
        if self.page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {self.page_size}")
        # LIFO free list: recently-freed pages are re-issued first, which
        # keeps the hot working set compact
        self._free = list(range(self.n_pages - 1, TRASH_PAGE, -1))

    @classmethod
    def from_budget(
        cls,
        budget_bytes: int,
        n_layers: int,
        n_kv_heads: int,
        head_dim: int,
        dtype: Any,
        page_size: int = DEFAULT_PAGE_SIZE,
    ) -> "PagePool":
        """Size the pool so ALL layers' K+V pools fit ``budget_bytes``.

        The budget is typically a fraction of
        :func:`..utils.costmodel.device_hbm_bytes` — the costmodel owns
        what the device reports, this allocator owns staying under it.
        """
        per_page = n_layers * pool_bytes_per_layer(
            1, page_size, n_kv_heads, head_dim, dtype
        )
        n_pages = int(budget_bytes // per_page)
        if n_pages < 2:
            raise ValueError(
                f"budget {budget_bytes} bytes fits {n_pages} page(s); "
                f"need >= 2 ({per_page} bytes/page across {n_layers} "
                "layers)"
            )
        return cls(n_pages=n_pages, page_size=page_size)

    # -- accounting --------------------------------------------------------
    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def used_pages(self) -> int:
        """Physical pages allocated (unique — aliases count once)."""
        return len(self._allocated)

    @property
    def logical_pages(self) -> int:
        """Sum of refcounts: what a sharing-oblivious pool would hold.
        Equals :attr:`used_pages` whenever nothing is shared."""
        return sum(self._refs.values())

    @property
    def shared_pages(self) -> int:
        """Physical pages with more than one live reference."""
        return sum(1 for rc in self._refs.values() if rc > 1)

    def refcount(self, page: int) -> int:
        return self._refs.get(int(page), 0)

    @property
    def cached_pages(self) -> int:
        """Free pages whose prefix intern entries are retained (LRU)."""
        return len(self._cached)

    def is_cached(self, page: int) -> bool:
        """True when ``page`` is physically free but its intern entry is
        retained — a :meth:`match_prefix` hit on it costs one free-list
        page to revive (admission counts it as physical demand)."""
        return int(page) in self._cached

    def can_alloc(self, n: int) -> bool:
        return n <= len(self._free)

    # -- alloc / free ------------------------------------------------------
    def alloc(self, n: int) -> List[int]:
        """Take ``n`` pages off the free list; raises on exhaustion
        (callers queue the request — the pool never over-allocates)."""
        if n < 0:
            raise ValueError(f"cannot allocate {n} pages")
        if n > len(self._free):
            raise MemoryError(
                f"page pool exhausted: want {n}, have {len(self._free)} "
                f"free of {self.n_pages - 1} allocatable"
            )
        if not self._cached:
            pages = [self._free.pop() for _ in range(n)]
        else:
            # lazy LRU eviction: serve uncached free pages first (LIFO,
            # as before), and only under pressure evict cached prefixes,
            # oldest release first — a popular prefix stays matchable
            # until the allocator actually needs its page
            pages = []
            held: List[int] = []
            while len(pages) < n and self._free:
                p = self._free.pop()
                if p in self._cached:
                    held.append(p)
                else:
                    pages.append(p)
            self._free.extend(reversed(held))
            for p in list(self._cached):
                if len(pages) >= n:
                    break
                self._evict_cached(p)
                self._free.remove(p)
                pages.append(p)
        self._allocated.update(pages)
        for p in pages:
            self._refs[p] = 1
        if self.ownlog is not None:
            self.ownlog.record(
                "alloc", pages,
                free_pages=len(self._free), used_pages=len(self._allocated),
            )
        return pages

    def alloc_for_tokens(self, n_tokens: int) -> List[int]:
        return self.alloc(pages_needed(n_tokens, self.page_size))

    def free(self, pages: Sequence[int]) -> None:
        """Return pages to the free list; double-free and trash-page
        frees are hard errors (a silent one would hand the same page to
        two sequences), and so is freeing a page other references still
        alias (callers drop refs via :meth:`release_ref`)."""
        pages = list(pages)
        for p in pages:
            if p == TRASH_PAGE:
                raise ValueError("page 0 is reserved and never allocated")
            if p not in self._allocated:
                raise ValueError(f"double free of page {p}")
            if self._refs.get(p, 1) > 1:
                raise ValueError(
                    f"page {p} is shared (refcount "
                    f"{self._refs[p]}); release the reference instead"
                )
            self._allocated.discard(p)
            self._free.append(p)
            self._refs.pop(p, None)
            if self.sharing and p in self._page_key:
                # retain the intern entry: the page is physically free
                # (books unchanged) but stays matchable until alloc
                # pressure evicts it — LRU via _cached insertion order
                self._cached[p] = None
            else:
                key = self._page_key.pop(p, None)
                if key is not None and self._intern.get(key) == p:
                    del self._intern[key]
        if self.ownlog is not None:
            self.ownlog.record(
                "free", pages,
                free_pages=len(self._free), used_pages=len(self._allocated),
            )

    def _evict_cached(self, p: int) -> None:
        """Drop a cached-free page's retained intern entry (the page
        itself stays wherever the free-list caller put it)."""
        del self._cached[p]
        key = self._page_key.pop(p, None)
        if key is not None and self._intern.get(key) == p:
            del self._intern[key]

    def drop_cached(self) -> int:
        """Evict EVERY retained intern entry, returning how many were
        dropped.  Engine reset must call this: reset reinitialises the
        physical KV arrays, so a retained entry would point a future
        :meth:`match_prefix` hit at zeroed storage — and a warm cache
        across runs would also make same-seed repeats diverge."""
        n = len(self._cached)
        for p in list(self._cached):
            self._evict_cached(p)
        return n

    # -- prefix sharing ----------------------------------------------------
    def match_prefix(self, keys: Sequence[str]) -> Tuple[int, List[int]]:
        """Longest resident run of ``keys`` (chain hashes, in prefix
        order): returns ``(h, pages)`` where the first ``h`` keys are
        interned and ``pages`` are their physical ids.  Pure lookup — no
        refcounts move until the caller commits with :meth:`share`."""
        if not self.sharing:
            return 0, []
        pages: List[int] = []
        for k in keys:
            p = self._intern.get(k)
            if p is None:
                break
            pages.append(p)
        return len(pages), pages

    def share(self, pages: Sequence[int]) -> None:
        """Take one additional reference on each page (aliasing commit).

        A RESIDENT page bumps its refcount; free/used counts are
        untouched and the ``share`` event carries them so the prover's
        physical tiling witness extends across sharing traffic.  A
        CACHED-FREE page (retained intern entry, see :meth:`free`) is
        REVIVED instead: it leaves the free list with refcount 1 and is
        recorded as a plain ``alloc`` — to the prover a revival is
        indistinguishable from a fresh allocation, which is exactly the
        physical truth.  Callers must share matched pages BEFORE
        allocating fresh ones, or alloc pressure may evict the match out
        from under them."""
        if not self.sharing:
            raise ValueError("share() on a pool with sharing disabled")
        revived: List[int] = []
        bumped: List[int] = []
        for p in pages:
            p = int(p)
            if p in self._cached:
                del self._cached[p]
                self._free.remove(p)
                self._allocated.add(p)
                self._refs[p] = 1
                revived.append(p)
            elif p in self._allocated:
                self._refs[p] = self._refs.get(p, 0) + 1
                bumped.append(p)
            else:
                raise ValueError(f"share of unallocated page {p}")
        if self.ownlog is not None:
            if revived:
                self.ownlog.record(
                    "alloc", revived,
                    free_pages=len(self._free),
                    used_pages=len(self._allocated),
                )
            if bumped:
                self.ownlog.record(
                    "share", bumped,
                    free_pages=len(self._free),
                    used_pages=len(self._allocated),
                    refcounts=[self._refs[p] for p in bumped],
                )

    def register(self, page: int, key: str) -> None:
        """Intern ``page`` under chain-hash ``key`` (first writer wins —
        a duplicate key keeps the incumbent so its aliases stay valid).
        No-op with sharing disabled."""
        if not self.sharing:
            return
        page = int(page)
        if page not in self._allocated:
            raise ValueError(f"register of unallocated page {page}")
        if key in self._intern or page in self._page_key:
            return
        self._intern[key] = page
        self._page_key[page] = key

    def release_ref(self, pages: Sequence[int]) -> None:
        """Drop one reference per page: last release frees physically
        (normal ``free`` event, page returns to the LIFO free list and
        its intern entry is evicted); earlier releases only decrement
        and record ``unshare``."""
        to_free: List[int] = []
        unshared: List[int] = []
        for p in pages:
            p = int(p)
            if p not in self._allocated:
                raise ValueError(f"release_ref of unallocated page {p}")
            rc = self._refs.get(p, 1)
            if rc <= 1:
                to_free.append(p)
            else:
                self._refs[p] = rc - 1
                unshared.append(p)
        if unshared and self.ownlog is not None:
            self.ownlog.record(
                "unshare", unshared,
                free_pages=len(self._free), used_pages=len(self._allocated),
                refcounts=[self._refs[p] for p in unshared],
            )
        if to_free:
            self.free(to_free)


def init_paged_kv(
    n_layers: int,
    n_pages: int,
    page_size: int,
    n_kv_heads: int,
    head_dim: int,
    dtype: Any,
) -> Dict[str, jax.Array]:
    """Zeroed per-layer page pools keyed ``cache_k_{i}`` / ``cache_v_{i}``
    — the same naming contract the dense decode DAG uses, so
    ``split_cache_params`` and the analysis passes treat paged and dense
    caches uniformly.  Layout ``(n_pages, page_size, n_kv_heads,
    head_dim)``: pages lead, so assembling a sequence is one gather on
    axis 0."""
    shape = (n_pages, page_size, n_kv_heads, head_dim)
    out: Dict[str, jax.Array] = {}
    for i in range(n_layers):
        out[f"cache_k_{i}"] = jnp.zeros(shape, dtype)
        out[f"cache_v_{i}"] = jnp.zeros(shape, dtype)
    return out


def page_table_array(
    tables: Sequence[Sequence[int]], pages_per_seq: int
) -> jax.Array:
    """Stack per-sequence page-id lists into the device table
    ``(slots, pages_per_seq) int32``, padding unallocated entries with
    the trash page."""
    rows = []
    for t in tables:
        if len(t) > pages_per_seq:
            raise ValueError(
                f"sequence holds {len(t)} pages > pages_per_seq "
                f"{pages_per_seq}"
            )
        rows.append(list(t) + [TRASH_PAGE] * (pages_per_seq - len(t)))
    return jnp.asarray(rows, jnp.int32)


def write_token_kv(
    pool: jax.Array,
    new: jax.Array,
    page_table: jax.Array,
    lengths: jax.Array,
    active: jax.Array,
) -> jax.Array:
    """Scatter one step's K (or V) rows into their page slots.

    ``pool`` (P, ps, Hkv, hd); ``new`` (S, Hkv, 1, hd) — this step's row
    per slot; ``page_table`` (S, pages_per_seq) int32; ``lengths`` (S,)
    int32 — tokens already cached per slot (the write position);
    ``active`` (S,) bool.  Inactive slots write the trash page, so the
    scatter stays static-shape under an admission/retirement mask.
    """
    n_pages, ps = pool.shape[0], pool.shape[1]
    s_idx = jnp.arange(page_table.shape[0])
    logical = jnp.where(active, lengths // ps, 0)
    pid = jnp.where(active, page_table[s_idx, logical], TRASH_PAGE)
    slot = jnp.where(active, lengths % ps, 0)
    rows = new[:, :, 0, :].astype(pool.dtype)  # (S, Hkv, hd)
    # flat row index: one 1-D scatter instead of a 2-D one (inactive
    # slots land in the trash page's row 0)
    flat = pool.reshape(n_pages * ps, *pool.shape[2:])
    flat = flat.at[pid * ps + slot].set(rows, mode="drop")
    return flat.reshape(pool.shape)


def write_prompt_kv(
    pool: jax.Array, rows: jax.Array, pages: jax.Array
) -> jax.Array:
    """Scatter a prefilled prompt's rows into a sequence's pages.

    ``rows`` (cap, Hkv, hd) — the sequence's cache rows padded to its
    full page capacity ``cap = len(pages) * page_size``; ``pages``
    (n_pages_seq,) int32 physical ids (tail entries may be the trash
    page — overwriting it is harmless by design).
    """
    n_pg = pages.shape[0]
    ps = pool.shape[1]
    if rows.shape[0] != n_pg * ps:
        raise ValueError(
            f"rows cover {rows.shape[0]} tokens, pages cover {n_pg * ps}"
        )
    paged = rows.reshape(n_pg, ps, *rows.shape[1:]).astype(pool.dtype)
    return pool.at[pages].set(paged, mode="drop")


def gather_kv(
    pool: jax.Array, page_table: jax.Array
) -> jax.Array:
    """Assemble per-sequence contiguous KV views from the pool.

    ``pool`` (P, ps, Hkv, hd), ``page_table`` (S, n_pg) ->
    ``(S, Hkv, n_pg * ps, hd)`` — the dense-cache orientation
    (:func:`..models.decode.cached_attention`), so downstream attention
    math is shared verbatim with the dense path.  Unallocated table
    entries gather the trash page; its rows are masked by the caller's
    per-sequence lengths.

    Pays a materializing transpose to reach the dense orientation —
    right for oracles and tests; the hot attention path uses
    :func:`gather_kv_flat` instead.
    """
    S, n_pg = page_table.shape
    ps, hkv, hd = pool.shape[1], pool.shape[2], pool.shape[3]
    pages = jnp.take(pool, page_table.reshape(-1), axis=0)
    view = pages.reshape(S, n_pg, ps, hkv, hd)
    return view.transpose(0, 3, 1, 2, 4).reshape(S, hkv, n_pg * ps, hd)


def gather_kv_flat(
    pool: jax.Array, page_table: jax.Array
) -> jax.Array:
    """Token-major per-sequence view: ``(S, n_pg * ps, Hkv, hd)``.

    Same gather as :func:`gather_kv` but WITHOUT the transpose to the
    dense orientation — the reshape is free on the gather's contiguous
    output (pages arrive token-major already), so this is the layout the
    per-step XLA attention path uses; the caller permutes its
    ``dot_general`` batch dims instead of the data.  Token order is
    identical to the dense view's, so score/softmax reductions see the
    same operands in the same logical order (the bitwise-parity
    invariant the op tests pin).
    """
    S, n_pg = page_table.shape
    ps, hkv, hd = pool.shape[1], pool.shape[2], pool.shape[3]
    pages = jnp.take(pool, page_table.reshape(-1), axis=0)
    return pages.reshape(S, n_pg * ps, hkv, hd)


def paged_param_bytes(
    n_layers: int,
    n_pages: int,
    page_size: int,
    n_kv_heads: int,
    head_dim: int,
    dtype: Any,
    slots: int,
    pages_per_seq: int,
) -> Dict[str, int]:
    """Byte sizes of every paged-cache param the decode DAG declares —
    the page-residency numbers placement and the DEC analysis pass see."""
    per_pool = pool_bytes_per_layer(
        n_pages, page_size, n_kv_heads, head_dim, dtype
    ) // 2
    out: Dict[str, int] = {}
    for i in range(n_layers):
        out[f"cache_k_{i}"] = per_pool
        out[f"cache_v_{i}"] = per_pool
    out["page_table"] = slots * pages_per_seq * 4
    return out


__all__ = [
    "DEFAULT_PAGE_SIZE",
    "OWNERSHIP_SCHEMA",
    "TRASH_PAGE",
    "PageOwnershipLog",
    "PagePool",
    "pages_needed",
    "prefix_chunk_keys",
    "pool_bytes_per_layer",
    "init_paged_kv",
    "page_table_array",
    "write_token_kv",
    "write_prompt_kv",
    "gather_kv",
    "gather_kv_flat",
    "paged_param_bytes",
]
