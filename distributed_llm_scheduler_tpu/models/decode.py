"""Autoregressive KV-cache decoding shared by all model families.

The reference never *runs* a model (its forward pass is a simulated DAG
replay, reference ``simulation.py:216-278``), so it has no inference story
beyond "the DAG was scheduled".  The rebuild executes for real, and real
inference means token-by-token decoding — this module supplies the shared
machinery: a static-shape KV cache, masked cached attention, and a
``lax.scan`` generation loop with greedy/temperature sampling.

TPU notes (why the design looks like this):

- **Static shapes only.** The cache is allocated at ``max_len`` up front and
  every decode step attends over the full ``(B, H, 1, max_len)`` score
  matrix with a position mask — no growing tensors, so XLA compiles the
  step exactly once and `lax.scan` drives the whole generation as ONE
  compiled program (no per-token dispatch from Python).
- **Traced positions.** ``pos`` is an int32 scalar carried through the scan;
  cache writes use ``lax.dynamic_update_slice`` and RoPE/wpe lookups use
  ``lax.dynamic_slice``, both of which accept traced starts — nothing
  recompiles as generation advances.
- **Decode is bandwidth-bound, not MXU-bound** (one token's GEMVs), so the
  cached-attention path uses plain XLA einsums; the Pallas flash kernel
  (``ops/attention.py``) stays on the prefill/training path where the
  O(T^2) score matrix actually matters.

Each family module (``gpt2``, ``llama``, ``mixtral``) provides
``init_cache(config, batch, max_len)`` and
``forward_cached(params, ids, cache, pos_start, config)``; this module's
:func:`generate` drives any of them.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

KVCache = Dict[str, jax.Array]  # {"k": (L, B, Hkv, M, hd), "v": same}


def init_cache(
    n_layers: int,
    batch: int,
    n_kv_heads: int,
    max_len: int,
    head_dim: int,
    dtype: Any,
) -> KVCache:
    """Zeroed stacked-layer cache; positions >= the write cursor are masked
    out by :func:`cached_attention`, so zeros never leak into outputs."""
    shape = (n_layers, batch, n_kv_heads, max_len, head_dim)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def quantize_cache(cache: KVCache) -> KVCache:
    """An int8 container with the same (L, B, Hkv, M, hd) geometry: values
    as int8 plus one float32 absmax scale per cached row (L, B, Hkv, M, 1).

    Decode is bandwidth-bound and the cache buffer is re-read whole every
    step (module docstring), so halving its bytes is the same structural
    lever int8 weights are — at the cost of per-row quantization error
    (lossy: opt in via ``generate(kv_int8=True)``).  Init scales are 1.0
    but never read: every row is either written (getting a real scale)
    or masked out by :func:`cached_attention`."""
    L, B, H, M, _ = cache["k"].shape
    s = jnp.ones((L, B, H, M, 1), jnp.float32)
    return {
        "k": jnp.zeros(cache["k"].shape, jnp.int8), "k_scale": s,
        "v": jnp.zeros(cache["v"].shape, jnp.int8), "v_scale": s,
    }


def layer_view(cache: KVCache, layer: int):
    """(k, v, k_scale, v_scale) of one layer — scales are None for a
    dense cache, so family attention code handles both layouts with one
    call (gpt2 ``forward_cached``, llama ``attention_cached``)."""
    ks, vs = cache.get("k_scale"), cache.get("v_scale")
    return (
        cache["k"][layer],
        cache["v"][layer],
        None if ks is None else ks[layer],
        None if vs is None else vs[layer],
    )


def _quantize_rows(new: jax.Array):
    """(B, Hkv, T, hd) -> int8 values + per-row float32 absmax scales."""
    s = jnp.max(jnp.abs(new.astype(jnp.float32)), axis=-1, keepdims=True)
    s = jnp.where(s == 0, 1.0, s / 127.0)
    q = jnp.round(new.astype(jnp.float32) / s).astype(jnp.int8)
    return q, s


def update_layer_cache(
    cache: KVCache, layer: int, k_new: jax.Array, v_new: jax.Array,
    pos_start: jax.Array
) -> KVCache:
    """Write (B, Hkv, T_new, hd) keys/values at [pos_start, pos_start+T_new)
    of layer ``layer``.  ``pos_start`` may be traced.  An int8 cache
    (:func:`quantize_cache` layout) quantizes the incoming rows on write."""
    def put(buf, new):
        return jax.lax.dynamic_update_slice(
            buf, new[None].astype(buf.dtype), (layer, 0, 0, pos_start, 0)
        )

    if "k_scale" in cache:
        kq, ks = _quantize_rows(k_new)
        vq, vs = _quantize_rows(v_new)
        return {
            "k": put(cache["k"], kq), "k_scale": put(cache["k_scale"], ks),
            "v": put(cache["v"], vq), "v_scale": put(cache["v_scale"], vs),
        }
    return {"k": put(cache["k"], k_new), "v": put(cache["v"], v_new)}


def cached_attention(
    q: jax.Array,
    k_cache: jax.Array,
    v_cache: jax.Array,
    pos_start: jax.Array,
    sm_scale: float,
    k_scale: Optional[jax.Array] = None,
    v_scale: Optional[jax.Array] = None,
) -> jax.Array:
    """Causal attention of ``q`` (B, Hq, T_new, hd) over a full-length cache
    (B, Hkv, M, hd) whose rows beyond ``pos_start + T_new`` are invalid.

    Query row ``r`` (absolute position ``pos_start + r``) may attend cache
    columns ``c <= pos_start + r`` — this single mask covers both the
    "stale tail" of the cache and causality among the new tokens, so the
    same code path serves prefill (T_new = prompt) and decode (T_new = 1).
    KV heads broadcast across their query group (GQA).

    ``k_scale``/``v_scale`` (B, Hkv, M, 1) mark an int8 cache
    (:func:`quantize_cache`).  The cache stays int8 through the dots —
    the int8->compute-dtype convert fuses into the einsum's read — and
    the per-row scales fold into the score columns / softmax weights
    AFTER the contractions (algebraically exact: the scale is constant
    along the contracted head_dim axis).  Scaling the cache *before*
    the dot instead would materialize a full dequantized copy per step,
    which costs more HBM traffic than the int8 layout saves (measured:
    6.1k tok/s materialized vs 7.1k bf16 baseline on the v5e).
    """
    B, Hq, Tn, hd = q.shape
    Hkv, M = k_cache.shape[1], k_cache.shape[2]
    if Tn == 1:
        return _decode_attention_natural(
            q, k_cache, v_cache, pos_start, sm_scale, k_scale, v_scale
        )
    if Hq != Hkv:
        group = Hq // Hkv
        k_cache = jnp.repeat(k_cache, group, axis=1)
        v_cache = jnp.repeat(v_cache, group, axis=1)
        if k_scale is not None:
            k_scale = jnp.repeat(k_scale, group, axis=1)
        if v_scale is not None:
            v_scale = jnp.repeat(v_scale, group, axis=1)
    scores = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k_cache.astype(q.dtype)
    ) * sm_scale
    if k_scale is not None:
        # (B, H, M, 1) -> one multiplier per score column
        scores = scores * k_scale[..., 0][:, :, None, :].astype(
            scores.dtype
        )
    rows = pos_start + jax.lax.broadcasted_iota(jnp.int32, (Tn, M), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (Tn, M), 1)
    scores = jnp.where(cols <= rows, scores, jnp.finfo(scores.dtype).min)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
    if v_scale is not None:
        probs = probs * v_scale[..., 0][:, :, None, :]
    out_dtype = q.dtype
    return jnp.einsum(
        "bhqk,bhkd->bhqd",
        probs.astype(out_dtype), v_cache.astype(out_dtype),
    )


def _decode_attention_natural(
    q: jax.Array,
    k_cache: jax.Array,
    v_cache: jax.Array,
    pos: jax.Array,
    sm_scale: float,
    k_scale: Optional[jax.Array],
    v_scale: Optional[jax.Array],
) -> jax.Array:
    """Single-token cached attention in MXU-natural orientation.

    The prefill-orientation einsum (``bhqd,bhkd->bhqk``) at T_new = 1
    forces XLA to transpose the K cache every step — measured 120 GB/s
    effective on the v5e, ~1/5 of what the chip streams at these shapes.
    Computing scores as ``K @ q`` instead ((B, Hkv, M, G) with M on
    sublanes, exactly the cache's storage layout) runs the identical
    math at 576 GB/s (0.81 -> 0.29 ms/step on the 12-layer flagship, a
    same-session v5e probe; the committed ``DECODE_r04.json``
    attribution predates the fix and shows the transposing form at
    1.91 ms — 10.9% of its byte bound.  The r6 recapture ran on a host
    core, where the shipped orientation measures 4.7 of the 251 ms CPU
    step — ``DECODE_r06.json`` ``attribution.attn_ms`` — attention is a
    ~2% slice there, so the GB/s ratio above stays v5e-attributed).  A
    Pallas per-layer kernel was tried first
    and LOST: ~66 us fixed cost per pallas_call x 12 sequential layers
    swamps any in-kernel win — the right decode kernel here is the one
    XLA already has, fed shapes in its preferred orientation.

    GQA comes free: the query group joins the G axis (``bhgd`` below),
    so K/V stream ONCE per KV head — the prefill path's ``jnp.repeat``
    reads them ``group`` times.  int8 scale folding is unchanged in
    algebra, just applied along the natural axes.
    """
    B, Hq, _, hd = q.shape
    Hkv, M = k_cache.shape[1], k_cache.shape[2]
    G = Hq // Hkv
    qg = (q * sm_scale).reshape(B, Hkv, G, hd)
    # scores (B, Hkv, M, G): contract hd (lanes), batch (B, Hkv) — both
    # operands read in storage order, no transpose materialized
    s = jax.lax.dot_general(
        k_cache.astype(qg.dtype), qg,
        (((3,), (3,)), ((0, 1), (0, 1))),
        preferred_element_type=jnp.float32,
    )
    if k_scale is not None:
        s = s * k_scale.astype(s.dtype)  # (B, Hkv, M, 1) broadcasts over G
    rows = jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
    s = jnp.where(rows <= pos, s, jnp.finfo(s.dtype).min)
    m = s.max(axis=2, keepdims=True)
    p = jnp.exp(s - m)
    l = p.sum(axis=2, keepdims=True)
    if v_scale is not None:
        p = p * v_scale.astype(p.dtype)
    out_dtype = q.dtype
    # out (B, Hkv, G, hd): contract M (sublanes of both), batch (B, Hkv)
    o = jax.lax.dot_general(
        p.astype(out_dtype), v_cache.astype(out_dtype),
        (((2,), (2,)), ((0, 1), (0, 1))),
        preferred_element_type=jnp.float32,
    )
    return (o / l.reshape(B, Hkv, G, 1)).astype(out_dtype).reshape(
        B, Hq, 1, hd
    )


def sample_token(
    logits: jax.Array,
    key: Optional[jax.Array],
    temperature: float,
    top_k: int = 0,
) -> jax.Array:
    """(B, V) logits -> (B,) int32 token ids.

    ``temperature == 0`` is greedy argmax (no key needed).  ``top_k > 0``
    restricts sampling to the k most likely tokens (static k, so the
    lax.top_k shape is fixed under jit).
    """
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    assert key is not None, "temperature sampling needs a PRNG key"
    logits = logits.astype(jnp.float32) / temperature
    if top_k > 0:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, jnp.finfo(jnp.float32).min, logits)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


def _position_limit(config: Any) -> Optional[int]:
    """The family's maximum absolute position: GPT-2's learned table length
    or the Llama-backbone's trained RoPE horizon."""
    return getattr(config, "n_positions", None) or getattr(
        config, "max_seq_len", None
    )


@functools.lru_cache(maxsize=64)
def _compiled_run(
    forward_cached: Callable[..., Tuple[jax.Array, KVCache]],
    init_cache_fn: Callable[[Any, int, int], KVCache],
    config: Any,
    B: int,
    T: int,
    M: int,
    max_new_tokens: int,
    temperature: float,
    top_k: int,
    kv_int8: bool = False,
):
    """One compiled generation program per static configuration — repeated
    generate() calls with the same shapes reuse it instead of re-tracing
    (config is a frozen dataclass, so it hashes by value)."""

    @jax.jit
    def run(params, prompt_ids, key):
        cache = init_cache_fn(config, B, M)
        if kv_int8:
            cache = quantize_cache(cache)
        logits, cache = forward_cached(params, prompt_ids, cache, 0, config)
        key, sub = jax.random.split(key)
        first = sample_token(logits[:, -1, :], sub, temperature, top_k)

        def step(carry, _):
            cache, tok, pos, key = carry
            logits, cache = forward_cached(
                params, tok[:, None], cache, pos, config
            )
            key, sub = jax.random.split(key)
            nxt = sample_token(logits[:, -1, :], sub, temperature, top_k)
            return (cache, nxt, pos + 1, key), tok

        (_, last, _, _), toks = jax.lax.scan(
            step,
            (cache, first, jnp.int32(T), key),
            None,
            length=max_new_tokens - 1,
        ) if max_new_tokens > 1 else ((cache, first, None, key), None)
        new = (
            jnp.concatenate([toks.T, last[:, None]], axis=1)
            if toks is not None
            else last[:, None]
        )
        return jnp.concatenate([prompt_ids, new], axis=1)

    return run


def generate(
    forward_cached: Callable[..., Tuple[jax.Array, KVCache]],
    init_cache_fn: Callable[[Any, int, int], KVCache],
    params: Dict[str, jax.Array],
    prompt_ids: jax.Array,
    config: Any,
    max_new_tokens: int,
    temperature: float = 0.0,
    top_k: int = 0,
    key: Optional[jax.Array] = None,
    max_len: Optional[int] = None,
    kv_int8: bool = False,
) -> jax.Array:
    """Prefill the prompt, then scan ``max_new_tokens`` decode steps.

    ``kv_int8=True`` stores the KV cache as int8 with per-row scales
    (:func:`quantize_cache` — lossy, so opt-in): the cache buffer is the
    second-largest byte term a decode step re-reads.

    Returns (B, prompt_len + max_new_tokens) int32: prompt + generated.
    The whole loop is one jitted program — prefill compiles once for the
    prompt shape, the decode step compiles once and is iterated by
    ``lax.scan`` on device — and the compiled program is cached per static
    configuration, so repeated calls don't re-trace.
    """
    if max_new_tokens < 0:
        raise ValueError(f"max_new_tokens must be >= 0, got {max_new_tokens}")
    if max_new_tokens == 0:
        return prompt_ids
    B, T = prompt_ids.shape
    M = max_len if max_len is not None else T + max_new_tokens
    if M < T + max_new_tokens:
        # an undersized cache would CLAMP dynamic_update_slice writes and
        # silently corrupt generation — refuse loudly (not an assert: this
        # must survive python -O)
        raise ValueError(f"max_len {M} < prompt {T} + new {max_new_tokens}")
    limit = _position_limit(config)
    if limit is not None and T + max_new_tokens > limit:
        # past the position table/RoPE horizon, dynamic_slice would CLAMP
        # its start and silently repeat the last position's embedding
        raise ValueError(
            f"prompt ({T}) + max_new_tokens ({max_new_tokens}) exceeds the "
            f"model's position limit {limit}"
        )
    if key is None:
        key = jax.random.PRNGKey(0)
    run = _compiled_run(
        forward_cached, init_cache_fn, config, B, T, M, max_new_tokens,
        float(temperature), int(top_k), bool(kv_int8),
    )
    return run(params, prompt_ids, key)
