"""GPT-2 in pure JAX: the flagship model family.

A from-scratch functional implementation (no flax/haiku): params are a flat
``Dict[str, jax.Array]`` keyed by the same names the DAG frontend uses for
its tasks' ``params_needed`` sets, so scheduler placement and real execution
share one vocabulary.  The reference extracts model *structure* from
HuggingFace GPT2Model with random weights (reference ``test_gpt2.py:45-48``);
here the model is ours, so structure, weights, and per-op functions all come
from the same place.

Every per-op function (`layer_norm`, `attention`, `ffn_*`, …) is
individually jittable — the DAG frontend wraps them as task fns — and
`forward` composes them into the whole-model forward used as the fused
single-program baseline and the correctness oracle for DAG execution.

TPU notes: matmul-heavy ops run in the model dtype (bfloat16 by default on
TPU) to hit the MXU; layer norms accumulate in float32 for stability.
Static shapes everywhere; causal masking via `jnp.where` on an affine
index grid (no dynamic slicing), so XLA tiles cleanly.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from ..ops.attention import mha as _fused_mha


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    n_positions: int = 1024
    n_embd: int = 768
    n_layer: int = 12
    n_head: int = 12
    dtype: Any = jnp.float32
    ln_eps: float = 1e-5

    @property
    def head_dim(self) -> int:
        return self.n_embd // self.n_head

    @classmethod
    def small(cls, **kw) -> "GPT2Config":
        """124M — the reference's extraction target (test_gpt2.py:47)."""
        return cls(**kw)

    @classmethod
    def medium(cls, **kw) -> "GPT2Config":
        """355M (BASELINE.json config #2)."""
        return cls(n_embd=1024, n_layer=24, n_head=16, **kw)

    @classmethod
    def tiny(cls, **kw) -> "GPT2Config":
        """Test-sized: 2 layers, 128 wide — CPU-fast, same topology."""
        return cls(
            vocab_size=512, n_positions=128, n_embd=128, n_layer=2, n_head=4, **kw
        )


# -- parameter init --------------------------------------------------------

def init_params(config: GPT2Config, key: jax.Array) -> Dict[str, jax.Array]:
    """GPT-2 initialization: N(0, 0.02) weights, zero biases, unit LN gains.

    Flat naming scheme shared with the DAG frontend:
    ``wte, wpe, ln_f_g, ln_f_b, h{i}_ln1_g, h{i}_attn_qkv_w, ...``
    """
    std = 0.02
    d, dtype = config.n_embd, config.dtype
    params: Dict[str, jax.Array] = {}

    def normal(key, shape, scale=std):
        return (scale * jax.random.normal(key, shape)).astype(dtype)

    n_keys = 2 + config.n_layer * 4
    keys = iter(jax.random.split(key, n_keys))

    params["wte"] = normal(next(keys), (config.vocab_size, d))
    params["wpe"] = normal(next(keys), (config.n_positions, d))
    for i in range(config.n_layer):
        p = f"h{i}_"
        params[p + "ln1_g"] = jnp.ones((d,), dtype)
        params[p + "ln1_b"] = jnp.zeros((d,), dtype)
        params[p + "attn_qkv_w"] = normal(next(keys), (d, 3 * d))
        params[p + "attn_qkv_b"] = jnp.zeros((3 * d,), dtype)
        # residual-branch projections scaled down by sqrt(2*n_layer), as GPT-2
        params[p + "attn_proj_w"] = normal(
            next(keys), (d, d), std / math.sqrt(2 * config.n_layer)
        )
        params[p + "attn_proj_b"] = jnp.zeros((d,), dtype)
        params[p + "ln2_g"] = jnp.ones((d,), dtype)
        params[p + "ln2_b"] = jnp.zeros((d,), dtype)
        params[p + "mlp_fc_w"] = normal(next(keys), (d, 4 * d))
        params[p + "mlp_fc_b"] = jnp.zeros((4 * d,), dtype)
        params[p + "mlp_proj_w"] = normal(
            next(keys), (4 * d, d), std / math.sqrt(2 * config.n_layer)
        )
        params[p + "mlp_proj_b"] = jnp.zeros((d,), dtype)
    params["ln_f_g"] = jnp.ones((d,), dtype)
    params["ln_f_b"] = jnp.zeros((d,), dtype)
    return params


def param_shapes(config: GPT2Config) -> Dict[str, Tuple[Tuple[int, ...], Any]]:
    """(shape, dtype) per param without materializing arrays (eval_shape)."""
    shaped = jax.eval_shape(
        lambda k: init_params(config, k), jax.ShapeDtypeStruct((2,), jnp.uint32)
    )
    return {k: (v.shape, v.dtype) for k, v in shaped.items()}


# -- per-op functions (task granularity of the reference DAG) ---------------

def layer_norm(
    x: jax.Array, g: jax.Array, b: jax.Array, eps: float = 1e-5
) -> jax.Array:
    xf = x.astype(jnp.float32)
    mean = xf.mean(-1, keepdims=True)
    var = xf.var(-1, keepdims=True)
    out = (xf - mean) * jax.lax.rsqrt(var + eps)
    return (out * g.astype(jnp.float32) + b.astype(jnp.float32)).astype(x.dtype)


def embedding(input_ids: jax.Array, wte: jax.Array, wpe: jax.Array) -> jax.Array:
    T = input_ids.shape[-1]
    return wte[input_ids] + wpe[:T]


def causal_attention(
    x: jax.Array,
    qkv_w: jax.Array,
    qkv_b: jax.Array,
    proj_w: jax.Array,
    proj_b: jax.Array,
    n_head: int,
) -> jax.Array:
    """Multi-head causal self-attention incl. output projection — one task,
    matching the reference's per-layer "attention" granularity
    (reference test_gpt2.py:75-90: qkv + proj params on a single task)."""
    B, T, D = x.shape
    hd = D // n_head
    qkv = x @ qkv_w + qkv_b
    q, k, v = jnp.split(qkv, 3, axis=-1)

    def heads(t):  # (B, T, D) -> (B, n_head, T, hd)
        return t.reshape(B, T, n_head, hd).transpose(0, 2, 1, 3)

    q, k, v = heads(q), heads(k), heads(v)
    # fused flash-attention kernel on TPU, plain-XLA path elsewhere (ops/)
    out = _fused_mha(q, k, v, causal=True)
    out = out.transpose(0, 2, 1, 3).reshape(B, T, D)
    return out @ proj_w + proj_b


def ffn_expand(x: jax.Array, fc_w: jax.Array, fc_b: jax.Array) -> jax.Array:
    return x @ fc_w + fc_b


def ffn_activation(x: jax.Array) -> jax.Array:
    return jax.nn.gelu(x, approximate=True)


def ffn_contract(x: jax.Array, proj_w: jax.Array, proj_b: jax.Array) -> jax.Array:
    return x @ proj_w + proj_b


def residual_add(a: jax.Array, b: jax.Array) -> jax.Array:
    return a + b


def output_projection(x: jax.Array, wte: jax.Array) -> jax.Array:
    """Logits via weight tying with the embedding table
    (reference test_gpt2.py:160-166).

    At decode shapes (few rows against the full table) ``x @ wte.T``
    makes XLA stream the (V, D) table against its storage order — the
    same transposed-operand stall the decode attention fix measured at
    ~1/5 of HBM rate (models/decode._decode_attention_natural).  For
    small row counts the scores compute as ``wte · x`` instead — both
    operands contract their LAST axis (lanes), no transpose
    materialized — and only the tiny (V, rows) result transposes.  Row
    threshold 64: past that the matmul is MXU-compute-bound and the big
    output transpose would cost more than it saves.

    The fast path only handles the canonical (B, T, D) activations;
    pre-flattened (rows, D) inputs take the plain tied matmul."""
    if x.ndim == 3:
        B, T, D = x.shape
    else:
        B = 0  # disable the reshape fast path below
    if x.ndim == 3 and B * T <= 64:
        flat = x.reshape(B * T, D)
        scores = jax.lax.dot_general(
            wte, flat, (((1,), (1,)), ((), ()))
        )  # (V, B*T): wte rows on sublanes, contraction on lanes
        return scores.T.reshape(B, T, wte.shape[0])
    return x @ wte.T


# -- whole-model forward (fused baseline + correctness oracle) --------------

_BLOCK_KEYS = (
    "ln1_g", "ln1_b", "attn_qkv_w", "attn_qkv_b", "attn_proj_w",
    "attn_proj_b", "ln2_g", "ln2_b", "mlp_fc_w", "mlp_fc_b",
    "mlp_proj_w", "mlp_proj_b",
)


def transformer_block(
    block_params: Dict[str, jax.Array], x: jax.Array, config: GPT2Config
) -> jax.Array:
    """One layer (pre-LN attention + MLP with residuals), params keyed by
    the unprefixed ``_BLOCK_KEYS`` names.  The unit of rematerialization
    and of the scanned forward."""
    ln1 = layer_norm(x, block_params["ln1_g"], block_params["ln1_b"], config.ln_eps)
    attn = causal_attention(
        ln1,
        block_params["attn_qkv_w"],
        block_params["attn_qkv_b"],
        block_params["attn_proj_w"],
        block_params["attn_proj_b"],
        config.n_head,
    )
    x = residual_add(x, attn)
    ln2 = layer_norm(x, block_params["ln2_g"], block_params["ln2_b"], config.ln_eps)
    h = ffn_expand(ln2, block_params["mlp_fc_w"], block_params["mlp_fc_b"])
    h = ffn_activation(h)
    h = ffn_contract(h, block_params["mlp_proj_w"], block_params["mlp_proj_b"])
    return residual_add(x, h)


def _select_block(remat: bool):
    """The layer function both forwards iterate: checkpointed or plain."""
    if remat:
        return jax.checkpoint(transformer_block, static_argnums=(2,))
    return transformer_block


def _head(
    x: jax.Array, params: Dict[str, jax.Array], config: GPT2Config
) -> jax.Array:
    """Shared epilogue: final LN + weight-tied output projection."""
    x = layer_norm(x, params["ln_f_g"], params["ln_f_b"], config.ln_eps)
    return output_projection(x, params["wte"])


def forward(
    params: Dict[str, jax.Array],
    input_ids: jax.Array,
    config: GPT2Config,
    remat: bool = False,
) -> jax.Array:
    """Full forward pass composing exactly the per-op functions above.

    ``remat=True`` wraps each layer in ``jax.checkpoint`` so the backward
    pass recomputes block activations instead of storing them — the
    standard TPU HBM-for-FLOPs trade for training deep models.
    """
    block = _select_block(remat)
    x = embedding(input_ids, params["wte"], params["wpe"])
    for i in range(config.n_layer):
        p = f"h{i}_"
        x = block({k: params[p + k] for k in _BLOCK_KEYS}, x, config)
    return _head(x, params, config)


# -- scanned forward (stacked layers, one compiled block) --------------------

def stack_layer_params(
    params: Dict[str, jax.Array], config: GPT2Config
) -> Dict[str, jax.Array]:
    """Per-layer ``h{i}_*`` tensors -> stacked ``layers_*`` with a leading
    layer dim (plus the non-layer params unchanged).  The scanned-forward
    layout; numbers are identical to the flat layout."""
    out = {
        k: v for k, v in params.items() if not k.startswith("h")
    }
    for key in _BLOCK_KEYS:
        out["layers_" + key] = jnp.stack(
            [params[f"h{i}_{key}"] for i in range(config.n_layer)]
        )
    return out


def forward_scan(
    params: Dict[str, jax.Array],
    input_ids: jax.Array,
    config: GPT2Config,
    remat: bool = False,
) -> jax.Array:
    """Forward over stacked layer params via ``lax.scan``.

    XLA traces and compiles the transformer block ONCE instead of
    ``n_layer`` times — the idiomatic TPU formulation for deep models
    (compile time and program size stay O(1) in depth).  Combine with
    ``remat=True`` for the standard scan-over-remat-blocks training setup.
    Matches :func:`forward` numerically (same block math, same order).
    """
    block = _select_block(remat)
    stacked = {k: params["layers_" + k] for k in _BLOCK_KEYS}

    def step(x, layer_params):
        return block(layer_params, x, config), None

    x = embedding(input_ids, params["wte"], params["wpe"])
    x, _ = jax.lax.scan(step, x, stacked)
    return _head(x, params, config)


def loss_fn(
    params: Dict[str, jax.Array],
    input_ids: jax.Array,
    targets: jax.Array,
    config: GPT2Config,
    remat: bool = False,
    scan: bool = False,
) -> jax.Array:
    """Next-token cross-entropy (training-step DAGs and the parallel layer).

    ``scan=True`` expects stacked-layer params (:func:`stack_layer_params`)
    and runs the scanned forward."""
    fwd = forward_scan if scan else forward
    logits = fwd(params, input_ids, config, remat=remat)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return nll.mean()


def num_params(config: GPT2Config) -> int:
    return sum(math.prod(shape) for shape, _ in param_shapes(config).values())


# -- KV-cache decoding (models/decode.py drives this) ------------------------

def init_cache(config: GPT2Config, batch: int, max_len: int):
    from . import decode

    return decode.init_cache(
        config.n_layer, batch, config.n_head, max_len,
        config.head_dim, config.dtype,
    )


def forward_cached(
    params: Dict[str, jax.Array],
    input_ids: jax.Array,
    cache,
    pos_start,
    config: GPT2Config,
) -> Tuple[jax.Array, Any]:
    """Forward over ``input_ids`` occupying absolute positions
    [pos_start, pos_start + T), reading and writing the KV cache.

    One code path serves prefill (T = prompt length, pos_start = 0) and
    decode (T = 1); ``pos_start`` may be a traced int32 scalar.  Matches
    :func:`forward` exactly when the cache holds the full history
    (``tests/test_decode.py`` pins logits parity and greedy-token parity).
    """
    from . import decode

    B, T = input_ids.shape
    pos_start = jnp.asarray(pos_start, jnp.int32)
    nh, hd = config.n_head, config.head_dim
    scale = 1.0 / math.sqrt(hd)

    wpe = jax.lax.dynamic_slice_in_dim(params["wpe"], pos_start, T, axis=0)
    x = params["wte"][input_ids] + wpe
    for i in range(config.n_layer):
        p = f"h{i}_"
        ln1 = layer_norm(x, params[p + "ln1_g"], params[p + "ln1_b"], config.ln_eps)
        qkv = ln1 @ params[p + "attn_qkv_w"] + params[p + "attn_qkv_b"]
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):
            return t.reshape(B, T, nh, hd).transpose(0, 2, 1, 3)

        q, k, v = heads(q), heads(k), heads(v)
        cache = decode.update_layer_cache(cache, i, k, v, pos_start)
        kc, vc, ks, vs = decode.layer_view(cache, i)
        att = decode.cached_attention(
            q, kc, vc, pos_start, scale, k_scale=ks, v_scale=vs
        )
        att = att.transpose(0, 2, 1, 3).reshape(B, T, config.n_embd)
        x = x + (att @ params[p + "attn_proj_w"] + params[p + "attn_proj_b"])
        ln2 = layer_norm(x, params[p + "ln2_g"], params[p + "ln2_b"], config.ln_eps)
        h = ffn_contract(
            ffn_activation(
                ffn_expand(ln2, params[p + "mlp_fc_w"], params[p + "mlp_fc_b"])
            ),
            params[p + "mlp_proj_w"],
            params[p + "mlp_proj_b"],
        )
        x = x + h
    return _head(x, params, config), cache


def generate(
    params: Dict[str, jax.Array],
    prompt_ids: jax.Array,
    config: GPT2Config,
    max_new_tokens: int,
    **kw,
) -> jax.Array:
    """Autoregressive generation (greedy by default; see
    :func:`.decode.generate` for temperature/top-k)."""
    from . import decode

    return decode.generate(
        forward_cached, init_cache, params, prompt_ids, config,
        max_new_tokens, **kw,
    )
