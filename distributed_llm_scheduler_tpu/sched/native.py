"""NativeScheduler: the C++ engine behind the BaseScheduler interface.

Flattens (graph, cluster) into integer-indexed arrays, runs the requested
policy inside the native engine (:mod:`..native`), and reconstructs the same
:class:`Schedule` the pure-Python policy would emit — the parity tests assert
bit-identical per-node lists, assignment order, and failure sets.  Use it via
``get_scheduler("native:heft")`` etc., or set ``DLS_NATIVE=1`` to make
``get_scheduler`` transparently upgrade every supported policy.

Why it exists: scheduling wall-time is a first-class reported metric
(reference ``simulation.py:327-333``); on multi-thousand-task microbatched
DAGs the Python round loops are the bottleneck of a full evaluator sweep.
"""

from __future__ import annotations

import ctypes
import time
from typing import Dict, List

import numpy as np

from ..core.cluster import Cluster
from ..core.graph import TaskGraph, TaskStatus
from ..core.schedule import Schedule
from ..backends.sim import LinkModel
from .base import BaseScheduler


class NativeScheduler(BaseScheduler):
    """One of the nine policies, executed by the native engine."""

    def __init__(self, policy: str, link=None):
        from ..native import POLICY_IDS

        if policy not in POLICY_IDS:
            raise ValueError(
                f"no native implementation of {policy!r}; "
                f"available: {sorted(POLICY_IDS)}"
            )
        self.policy = policy
        self.name = f"native:{policy}"
        link = link or LinkModel()
        # None means "free" in LinkModel; the engine uses <=0 for the same
        self._link = (
            link.param_load_gbps or 0.0,
            link.interconnect_gbps or 0.0,
            link.latency_s,
        )
        # the C ABI carries a single flat link tier; a tiered (ICI/DCN)
        # model would be silently flattened to ICI — refuse rather than
        # let heft/pipeline optimize the wrong costs on multislice clusters
        from ..backends.sim import TieredLinkModel

        if isinstance(link, TieredLinkModel):
            raise ValueError(
                "NativeScheduler supports flat LinkModel only; use the "
                "Python policies for TieredLinkModel (DCN-aware) runs"
            )

    def schedule(self, graph: TaskGraph, cluster: Cluster) -> Schedule:
        from ..native import POLICY_IDS, load_engine

        engine = load_engine()
        graph.freeze()
        graph.reset()
        cluster.reset()
        # dls-lint: allow(DET001) scheduling_wall_s is reported metadata
        t0 = time.perf_counter()

        tids = graph.task_ids()
        tidx = {tid: i for i, tid in enumerate(tids)}
        n = len(tids)
        if n == 0:  # every policy's empty-graph behavior: empty schedule
            return Schedule(
                policy=self.policy,
                per_node={nid: [] for nid in cluster.ids()},
                # dls-lint: allow(DET001) reported metadata
                scheduling_wall_s=time.perf_counter() - t0,
            )
        # param ids assigned in sorted-name order: id order == name order,
        # which the engine's tie-breaks rely on
        params = sorted(graph.unique_params())
        pidx = {p: i for i, p in enumerate(params)}

        task_mem = np.empty(n, dtype=np.float64)
        task_time = np.empty(n, dtype=np.float64)
        dep_off = np.zeros(n + 1, dtype=np.int32)
        par_off = np.zeros(n + 1, dtype=np.int32)
        dep_ids: List[int] = []
        par_ids: List[int] = []
        for i, tid in enumerate(tids):
            t = graph[tid]
            task_mem[i] = t.memory_required
            task_time[i] = t.compute_time
            dep_ids.extend(tidx[d] for d in t.dependencies)
            dep_off[i + 1] = len(dep_ids)
            par_ids.extend(sorted(pidx[p] for p in t.params_needed))
            par_off[i + 1] = len(par_ids)
        dep_arr = np.asarray(dep_ids, dtype=np.int32)
        par_arr = np.asarray(par_ids, dtype=np.int32)
        out_gb = np.asarray(
            [graph.output_gb(tid) for tid in tids], dtype=np.float64
        )
        param_gb = np.asarray(
            [graph.param_size_gb(p) for p in params], dtype=np.float64
        )
        node_mem = np.asarray(
            [d.total_memory for d in cluster], dtype=np.float64
        )
        node_speed = np.asarray(
            [d.compute_speed for d in cluster], dtype=np.float64
        )
        link3 = np.asarray(self._link, dtype=np.float64)

        group_ids = None
        node_rank = None
        group_rank = None
        if self.policy in ("pipeline", "pack", "refine"):
            # group index by first appearance over the TOPO order, matching
            # the Python _group_stats ordering (ungrouped: singleton groups)
            gidx: Dict[str, int] = {}
            for t in graph.topo_order:
                glabel = graph[t].group or t
                if glabel not in gidx:
                    gidx[glabel] = len(gidx)
            group_ids = np.asarray(
                [gidx[graph[t].group or t] for t in tids], dtype=np.int32
            )
        if self.policy == "refine":
            # refine's tie-breaks compare node-id / group-name STRINGS
            # (bottleneck max, basin-hop glist = sorted(best)); the engine
            # only sees indices, so ship each id's lexicographic rank
            node_ids_ = cluster.ids()
            pos = {nid: i for i, nid in enumerate(node_ids_)}
            node_rank = np.empty(len(node_ids_), dtype=np.int32)
            for r, nid in enumerate(sorted(node_ids_)):
                node_rank[pos[nid]] = r
            group_rank = np.empty(len(gidx), dtype=np.int32)
            for r, glabel in enumerate(sorted(gidx)):
                group_rank[gidx[glabel]] = r

        out_assign = np.empty(n, dtype=np.int32)
        out_order = np.empty(max(n, 1), dtype=np.int32)
        out_n = np.zeros(1, dtype=np.int32)

        def ptr(a, typ):
            if a.size == 0:  # NULL is fine: engine never derefs empty CSR data
                return None
            return a.ctypes.data_as(ctypes.POINTER(typ))

        rc = engine.dls_schedule(
            POLICY_IDS[self.policy], n, len(params), len(cluster),
            ptr(task_mem, ctypes.c_double), ptr(task_time, ctypes.c_double),
            ptr(out_gb, ctypes.c_double),
            ptr(dep_off, ctypes.c_int32), ptr(dep_arr, ctypes.c_int32),
            ptr(par_off, ctypes.c_int32), ptr(par_arr, ctypes.c_int32),
            ptr(param_gb, ctypes.c_double), ptr(node_mem, ctypes.c_double),
            ptr(node_speed, ctypes.c_double), ptr(link3, ctypes.c_double),
            None if group_ids is None else ptr(group_ids, ctypes.c_int32),
            None if node_rank is None else ptr(node_rank, ctypes.c_int32),
            None if group_rank is None else ptr(group_rank, ctypes.c_int32),
            ptr(out_assign, ctypes.c_int32), ptr(out_order, ctypes.c_int32),
            ptr(out_n, ctypes.c_int32),
        )
        if rc != 0:
            raise RuntimeError(f"native engine returned {rc}")
        # dls-lint: allow(DET001) scheduling_wall_s is reported metadata
        wall = time.perf_counter() - t0

        node_ids = cluster.ids()
        per_node: Dict[str, List[str]] = {nid: [] for nid in node_ids}
        order: List[str] = []
        completed, failed = set(), set()
        for k in range(int(out_n[0])):
            i = int(out_order[k])
            tid = tids[i]
            order.append(tid)
            per_node[node_ids[out_assign[i]]].append(tid)
        for i, tid in enumerate(tids):
            task = graph[tid]
            if out_assign[i] >= 0:
                completed.add(tid)
                task.status = TaskStatus.COMPLETED
                task.assigned_node = node_ids[out_assign[i]]
            else:
                failed.add(tid)
                task.status = TaskStatus.FAILED
        return Schedule(
            policy=self.policy,  # report under the policy's own name so
            # evaluator rows group with the Python twin
            per_node=per_node,
            assignment_order=order,
            completed=completed,
            failed=failed,
            scheduling_wall_s=wall,
        )
