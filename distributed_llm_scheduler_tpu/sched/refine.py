"""Local-search refinement over group placements.

LPT packing (:mod:`.pack`) balances *parameter-load* unions greedily, but
its one-pass greedy choice is blind to two things the replay actually
charges: dependency-wait serialization (a balanced device can still idle on
cross-device inputs) and the interaction between load order and compute
overlap.  This policy closes that gap with plain hill climbing:

1. seed with pack's LPT group placement;
2. repeatedly propose **moves** (bottleneck-device group -> elsewhere) and
   **swaps** (bottleneck group <-> lighter-device group), scoring each
   candidate with the event simulation the ordering pass uses
   (:func:`..sched.eventsim.simulate_placement`).  This is a close
   SURROGATE of the replay's objective — same link charges, per-node
   serial execution, prefetch queue — but not the replay loop itself
   (``backends/sim.py`` additionally models host dispatch slots and its
   own cache accounting), so improvement under the surrogate is
   guaranteed only against the surrogate; in practice the two move
   together (tests pin refine <= pack under the replay on the covered
   graphs, and the flagship bench confirms it end-to-end);
3. first-improvement acceptance, stop when a full neighborhood pass finds
   nothing better or the evaluation budget runs out;
4. commit through pack's assignment path (same memory checks, same
   dependency-aware final ordering).

The reference has no search-based policy (its four schedulers are one-pass
list schedulers, reference ``schedulers.py:138-525``); this is new
capability in the rebuild's favor — a second optimization *tier* on top of
the policy set, the standard makespan play when scheduling time is cheap
relative to execution time (here: milliseconds of host search for
milliseconds of TPU makespan, re-spent every run of a static graph).

Memory feasibility mirrors pack exactly: a candidate device must hold the
union of its groups' params plus the largest single-task activation.
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional, Set, Tuple

from ..backends.sim import LinkModel
from ..core.schedule import Schedule
from .base import SchedulerRun
from .eventsim import simulate_placement
from .pack import GroupPackScheduler
from .pipeline import _group_stats


class _StaticMoveFilter:
    """Incremental static-analysis pre-filter for candidate placements.

    Mirrors the search's group -> device assignment into a placed
    :class:`Schedule` tracked by :class:`..analysis.IncrementalAnalyzer`.
    A candidate whose delta-recheck introduces ERROR diagnostics beyond
    the seed baseline (memory overcommit ``fits()`` under-models,
    placement-dependent typecheck breakage) is rejected *before* the
    eventsim replay is paid for.  Only active when the analyzer's exact
    fast path holds — a dirty baseline would force a full re-analysis
    per candidate, costing more than the replay it saves — otherwise
    every query answers True (no filtering, search unchanged).
    """

    def __init__(
        self,
        run: SchedulerRun,
        devices,
        group_of: Dict[str, str],
        assign: Dict[str, int],
    ):
        self.devices = devices
        self.enabled = False
        self.state = dict(assign)
        try:
            from ..analysis import IncrementalAnalyzer

            order = run.graph.topo_order
        except Exception:
            return
        # tasks per group in one fixed topo order, so every mirrored
        # per-node list is a subsequence of assignment_order — the
        # invariant the analyzer's fast path rests on
        self.tasks_of: Dict[str, List[str]] = {}
        per_node: Dict[str, List[str]] = {d.node_id: [] for d in devices}
        placed_order: List[str] = []
        for tid in order:
            g = group_of.get(tid)
            if g is None or g not in assign:
                continue
            self.tasks_of.setdefault(g, []).append(tid)
            per_node[devices[assign[g]].node_id].append(tid)
            placed_order.append(tid)
        mirror = Schedule(
            policy="refine-static",
            per_node=per_node,
            assignment_order=placed_order,
            completed=set(placed_order),
        )
        try:
            self._inc = IncrementalAnalyzer(run.graph, run.cluster, mirror)
        except Exception:
            return
        self.base_errors = self._inc.error_count()
        self.enabled = self._inc.exact_fast_path

    def _apply(self, frm: Dict[str, int], to: Dict[str, int]) -> None:
        for g, d in to.items():
            if frm.get(g) == d:
                continue
            dst = self.devices[d].node_id
            for tid in self.tasks_of.get(g, ()):
                self._inc.move_task(tid, dst)

    def ok(self, cand: Dict[str, int]) -> bool:
        """True iff ``cand`` adds no ERROR over the seed baseline."""
        if not self.enabled:
            return True
        self._apply(self.state, cand)
        good = self._inc.error_count() <= self.base_errors
        self._apply(cand, self.state)  # revert; subsequence re-insertion
        return good                    # restores the exact prior lists

    def sync(self, assign: Dict[str, int]) -> None:
        """Advance the mirror to an accepted incumbent so later ``ok()``
        queries diff against it (one or two group moves, not the whole
        drift from the seed)."""
        if not self.enabled:
            return
        self._apply(self.state, assign)
        self.state = dict(assign)


class RefinedPackScheduler(GroupPackScheduler):
    """Hill-climbed group placement (pack seed, event-sim objective)."""

    name = "refine"

    def __init__(
        self,
        link: Optional[LinkModel] = None,
        max_evals: int = 400,
        tol: float = 1e-9,
        seed: int = 0,
    ):
        super().__init__(link=link)
        self.max_evals = max_evals
        self.tol = tol
        self.seed = seed

    def run_policy(self, run: SchedulerRun) -> None:
        graph, devices = run.graph, run.cluster.devices
        placed = self.plan(graph, devices)
        if placed and len(devices) > 1:
            placed = self._search(run, placed)
        self.commit(run, placed)

    # -- search ------------------------------------------------------------
    def _search(
        self, run: SchedulerRun, placed: Dict[str, int]
    ) -> Dict[str, int]:
        graph, devices = run.graph, run.cluster.devices
        n_dev = len(devices)
        groups, compute, activ, gparams = _group_stats(graph)
        gidx = {g: i for i, g in enumerate(groups)}
        speeds = {d.node_id: d.compute_speed for d in devices}
        slices = run.cluster.slice_ids()
        group_of = {
            t.task_id: (t.group or t.task_id) for t in graph.tasks()
        }
        flt = _StaticMoveFilter(run, devices, group_of, placed)

        def union_gb(names: Set[str]) -> float:
            return sum(graph.param_size_gb(p) for p in sorted(names))

        def fits(assign: Dict[str, int], d: int) -> bool:
            members = [g for g, dd in assign.items() if dd == d]
            params: Set[str] = set()
            act = 0.0
            for g in members:
                params |= gparams[gidx[g]]
                act = max(act, activ[gidx[g]])
            return union_gb(params) + act <= devices[d].total_memory + 1e-9

        def evaluate(
            assign: Dict[str, int]
        ) -> Tuple[float, Dict[str, float]]:
            placement = {
                tid: devices[assign[g]].node_id
                for tid, g in group_of.items()
                if g in assign
            }
            _, makespan, node_finish = simulate_placement(
                graph, placement, speeds, self.link, slices
            )
            return makespan, node_finish

        evals = 0

        def climb(start: Dict[str, int], start_m, start_nf):
            """First-improvement hill climbing from one placement."""
            nonlocal evals
            cur, cur_m, node_finish = dict(start), start_m, start_nf
            improved = True
            while improved and evals < self.max_evals:
                improved = False
                # groups on the bottleneck device, heaviest param union
                # first — moving them is what shortens the critical device.
                # tie-break by node_id: node_finish iterates in set order,
                # so bare max() would be PYTHONHASHSEED-dependent on ties
                bottleneck = max(
                    node_finish.items(), key=lambda kv: (kv[1], kv[0])
                )[0]
                b_idx = next(
                    i for i, d in enumerate(devices)
                    if d.node_id == bottleneck
                )
                hot = sorted(
                    (g for g, d in cur.items() if d == b_idx),
                    key=lambda g: -union_gb(gparams[gidx[g]]),
                )
                # lighter devices first as destinations
                dests = sorted(
                    range(n_dev),
                    key=lambda d: node_finish.get(devices[d].node_id, 0.0),
                )
                for g in hot:
                    if evals >= self.max_evals or improved:
                        break
                    for d in dests:
                        if d == b_idx:
                            continue
                        # move g -> d
                        cand = dict(cur)
                        cand[g] = d
                        if fits(cand, d) and flt.ok(cand):
                            m, nf = evaluate(cand)
                            evals += 1
                            if m < cur_m - self.tol:
                                cur, cur_m, node_finish = cand, m, nf
                                flt.sync(cand)
                                improved = True
                                break
                            if evals >= self.max_evals:
                                break
                        # swap g <-> lightest group on d
                        there = [g2 for g2, dd in cur.items() if dd == d]
                        if not there:
                            continue
                        g2 = min(
                            there, key=lambda x: union_gb(gparams[gidx[x]])
                        )
                        cand = dict(cur)
                        cand[g], cand[g2] = d, b_idx
                        if (
                            fits(cand, d)
                            and fits(cand, b_idx)
                            and flt.ok(cand)
                        ):
                            m, nf = evaluate(cand)
                            evals += 1
                            if m < cur_m - self.tol:
                                cur, cur_m, node_finish = cand, m, nf
                                flt.sync(cand)
                                improved = True
                                break
                            if evals >= self.max_evals:
                                break
            return cur, cur_m, node_finish

        seed_m, seed_nf = evaluate(placed)
        evals += 1
        best, best_m, _ = climb(placed, seed_m, seed_nf)

        # basin hopping: hill climbing converges in tens of evals; spend
        # the remaining budget escaping its local optimum — perturb the
        # incumbent by a few random feasible group moves (explicit seed:
        # same-seed placements are bitwise reproducible cross-process)
        # and re-climb, keeping the global best
        rng = random.Random(self.seed)
        glist = sorted(best)
        stale = 0  # consecutive failures to produce any feasible change
        while evals + 2 < self.max_evals and glist and stale < 10:
            cand = dict(best)
            for _ in range(3):
                g = rng.choice(glist)
                d = rng.randrange(n_dev)
                if d != cand[g]:
                    moved = dict(cand)
                    moved[g] = d
                    if fits(moved, d):
                        cand = moved
            if cand == best or not flt.ok(cand):
                # every proposed move was infeasible (or the perturbed
                # placement fails the static pre-filter); don't burn the
                # whole budget re-evaluating or replaying it
                stale += 1
                continue
            stale = 0
            flt.sync(cand)
            m, nf = evaluate(cand)
            evals += 1
            cur, cur_m, _ = climb(cand, m, nf)
            if cur_m < best_m - self.tol:
                best, best_m = cur, cur_m
        return best
