"""Scheduler base: the memory-constrained list-scheduling state machine.

Behavior parity with the reference ``BaseScheduler`` (reference
``schedulers.py:31-135``), with its de-facto contract preserved:

* memory requirement of a task on a node = activation footprint + size of
  every needed param **not already cached** there
  (reference ``schedulers.py:63-76``);
* assignment loads params into the node cache (debiting memory permanently
  until evicted) and **immediately completes** the task, crediting back only
  the activation memory (reference ``schedulers.py:78-126``) — list
  scheduling decides placement and order, a backend decides time;
* a ready task that fits on no node is failed permanently
  (reference ``schedulers.py:198-200``);
* a full round with no progress fails all remaining pending tasks
  (reference ``schedulers.py:202-206``);
* round loop is bounded by ``2 * len(tasks)`` iterations
  (reference ``schedulers.py:160`` et al.).

Differences (deliberate):

* state lives in a per-run :class:`SchedulerRun`, so graphs/clusters need no
  deep-copying between trials (the reference deep-copies,
  ``simulation.py:309-317``);
* param sizes are real bytes via the graph-wide size table
  (``TaskGraph.param_size_gb``, fixed at freeze; 0.5 GB default);
* the returned :class:`Schedule` also records global assignment order.
"""

from __future__ import annotations

import time
from typing import Dict, List, Set, Tuple

from ..core.cluster import Cluster, DeviceState
from ..core.graph import Task, TaskGraph, TaskStatus
from ..core.schedule import Schedule


class SchedulerRun:
    """Mutable state for one scheduling pass over (graph, cluster)."""

    def __init__(self, graph: TaskGraph, cluster: Cluster):
        graph.freeze()
        graph.reset()
        cluster.reset()
        self.graph = graph
        self.cluster = cluster
        self.pending: Set[str] = set(graph.task_ids())
        self.completed: Set[str] = set()
        self.failed: Set[str] = set()
        # param -> set of node_ids currently holding it
        # (reference ``param_locations``, schedulers.py:40)
        self.param_locations: Dict[str, Set[str]] = {}
        self.per_node: Dict[str, List[str]] = {d.node_id: [] for d in cluster}
        self.assignment_order: List[str] = []
        # accumulated compute backlog (speed-adjusted seconds) per node;
        # feeds the load-band eligibility filter (BaseScheduler.load_band)
        self.busy: Dict[str, float] = {d.node_id: 0.0 for d in cluster}
        # (node_id, sorted param names) -> tasks of that exact param set
        # assigned there; bounds the full-hit band's co-location
        self.colocated: Dict[Tuple[str, Tuple[str, ...]], int] = {}
        # per-task params in name order, computed once: deterministic float
        # accumulation (native parity) without re-sorting in the hot loops
        self._sorted_params: Dict[str, Tuple[str, ...]] = {}

    def sorted_params(self, task) -> Tuple[str, ...]:
        sp = self._sorted_params.get(task.task_id)
        if sp is None:
            sp = tuple(sorted(task.params_needed))
            self._sorted_params[task.task_id] = sp
        return sp


class BaseScheduler:
    """Subclasses override :meth:`run_policy` (the reference's ``schedule``)."""

    name = "base"

    # -- queries -----------------------------------------------------------
    def is_task_ready(self, run: SchedulerRun, tid: str) -> bool:
        return all(d in run.completed for d in run.graph[tid].dependencies)

    def get_ready_tasks(self, run: SchedulerRun) -> List[Task]:
        """Pending tasks whose deps are all complete, in graph insertion order.

        Full scan per round, as the reference does (schedulers.py:55-61);
        insertion order kept for determinism parity.
        """
        return [
            run.graph[tid]
            for tid in run.graph.task_ids()
            if tid in run.pending and self.is_task_ready(run, tid)
        ]

    def memory_requirement(self, run: SchedulerRun, task: Task,
                           node: DeviceState) -> float:
        """Activation GB + GB of params that would need loading on `node`.

        All sizes come from the graph's table fixed at freeze() so debits
        and (eviction) credits can never disagree.
        """
        need = task.memory_required
        # name order: deterministic float accumulation (native-engine parity)
        for p in run.sorted_params(task):
            if p not in node.cached_params:
                need += run.graph.param_size_gb(p)
        return need

    def can_fit(self, run: SchedulerRun, task: Task, node: DeviceState) -> bool:
        return self.memory_requirement(run, task, node) <= node.available_memory + 1e-9

    # Load-band eligibility: how many task-times of compute backlog a
    # candidate may trail the least-backlogged candidate by and still be
    # preferred for locality.  The reference's policies have no load term
    # at all, which concentrates work catastrophically at scale — greedy
    # placed a 5,169-task Llama graph 11x worse than round-robin because
    # the node holding a layer's weights wins every microbatch of that
    # layer forever (ICI_r04.json; VERDICT r4 next #3).  2.0 keeps all
    # four banded policies within 1.7x of round-robin on that probe while
    # preserving 1.6-3x the cache hits; float('inf') recovers the
    # reference's unbanded behavior.  A node already holding EVERY param
    # the task needs adds zero load bytes, so locality is worth more
    # there: it earns the wider FULL_HIT band — without it, microbatch
    # siblings of an already-placed expert spill to fresh devices and the
    # expert's weights get duplicated (tests/test_mixtral.py expert
    # locality); concentration stays bounded, just at 4 task-times.
    LOAD_BAND_FACTOR = 2.0
    LOAD_BAND_FULL_HIT_FACTOR = 4.0
    # the full-hit exception's guard: a node may take at most this many
    # tasks of the SAME param set through the wider band.  Two microbatch
    # siblings of a placed expert co-locate (bounded serialization,
    # weights loaded once); the sixteen-microbatch stream of a cached
    # layer is cut off after this many and spills back to the base band —
    # the unguarded version re-created greedy's 6x probe blowup, and a
    # ready-set-pressure guard failed because the stream arrives one
    # microbatch per round, not all at once.  (All constants tuned on the
    # 5k-task Llama probe x the MoE expert-locality test jointly; the
    # sweep lives in the r5 build log.)
    LOAD_BAND_FULL_HIT_SIBLINGS = 2

    def load_band(self, run: SchedulerRun, task: Task,
                  nodes: List[DeviceState]) -> List[DeviceState]:
        """Filter ``nodes`` (fitting candidates) to those whose compute
        backlog is within ``LOAD_BAND_FACTOR`` task-times of the least
        backlogged.  A node that already caches EVERY param the task
        needs adds zero load bytes, so it earns the wider FULL_HIT band —
        capped at ``LOAD_BAND_FULL_HIT_SIBLINGS`` same-param-set tasks
        per node.  Never empties a non-empty list (the min-busy node is
        always eligible), so completion semantics are unchanged — only
        concentration is bounded."""
        if len(nodes) <= 1 or task.compute_time <= 0.0:
            return nodes
        min_busy = min(run.busy[n.node_id] for n in nodes)
        base = min_busy + self.LOAD_BAND_FACTOR * task.compute_time + 1e-12
        hit = (
            min_busy
            + self.LOAD_BAND_FULL_HIT_FACTOR * task.compute_time
            + 1e-12
        )
        sp = run.sorted_params(task)

        def full_hit_ok(n: DeviceState) -> bool:
            if not sp or not all(p in n.cached_params for p in sp):
                return False
            return (
                run.colocated.get((n.node_id, sp), 0)
                < self.LOAD_BAND_FULL_HIT_SIBLINGS
            )

        return [
            n for n in nodes
            if run.busy[n.node_id] <= base
            or (run.busy[n.node_id] <= hit and full_hit_ok(n))
        ]

    # -- transitions -------------------------------------------------------
    def assign(self, run: SchedulerRun, task: Task, node: DeviceState) -> None:
        """Load params, debit memory, place task — then instantly complete.

        Mirrors reference ``assign_task_to_node`` + ``complete_task``
        (schedulers.py:78-126): params stay cached after completion; only
        the activation footprint is returned.
        """
        for p in run.sorted_params(task):
            if p not in node.cached_params:
                node.cached_params.add(p)
                node.available_memory -= run.graph.param_size_gb(p)
                run.param_locations.setdefault(p, set()).add(node.node_id)
        node.available_memory -= task.memory_required
        # recency window, name order (reference schedulers.py:99 extends
        # with an unordered set; sorted here for determinism)
        node.last_used_params.extend(run.sorted_params(task))
        task.assigned_node = node.node_id
        task.status = TaskStatus.ASSIGNED
        node.running_tasks.append(task.task_id)
        run.per_node[node.node_id].append(task.task_id)
        run.assignment_order.append(task.task_id)
        run.pending.discard(task.task_id)
        run.busy[node.node_id] += task.compute_time / node.compute_speed
        key = (node.node_id, run.sorted_params(task))
        run.colocated[key] = run.colocated.get(key, 0) + 1
        self.complete(run, task, node)

    def complete(self, run: SchedulerRun, task: Task, node: DeviceState) -> None:
        node.available_memory += task.memory_required
        node.running_tasks.remove(task.task_id)
        node.completed_tasks.append(task.task_id)
        task.status = TaskStatus.COMPLETED
        run.completed.add(task.task_id)

    def fail(self, run: SchedulerRun, task: Task) -> None:
        task.status = TaskStatus.FAILED
        run.pending.discard(task.task_id)
        run.failed.add(task.task_id)

    def evict_param(self, run: SchedulerRun, node: DeviceState, param: str,
                    size_gb: float) -> None:
        """Drop a cached param from a node, crediting its memory back."""
        node.cached_params.discard(param)
        node.available_memory += size_gb
        locs = run.param_locations.get(param)
        if locs:
            locs.discard(node.node_id)

    # -- driver ------------------------------------------------------------
    def schedule(self, graph: TaskGraph, cluster: Cluster) -> Schedule:
        run = SchedulerRun(graph, cluster)
        # dls-lint: allow(DET001) scheduling_wall_s is reported metadata,
        t0 = time.perf_counter()
        self.run_policy(run)
        # dls-lint: allow(DET001) never an input to any decision
        wall = time.perf_counter() - t0
        return Schedule(
            policy=self.name,
            per_node=run.per_node,
            assignment_order=run.assignment_order,
            completed=run.completed,
            failed=run.failed,
            scheduling_wall_s=wall,
        )

    def run_policy(self, run: SchedulerRun) -> None:
        raise NotImplementedError

    # Shared round-loop skeleton used by every policy (reference quirks:
    # iteration bound, fail-on-no-fit, no-progress bailout).
    def _round_loop(self, run: SchedulerRun, order_fn, pick_node_fn) -> None:
        """Generic list-scheduling loop.

        ``order_fn(run, ready) -> List[Task]`` sorts the ready set;
        ``pick_node_fn(run, task, ready_ids) -> Optional[DeviceState]`` picks
        a target (may mutate state, e.g. MRU eviction on the chosen node).
        ``ready_ids`` is this round's still-pending ready set, so policies
        that score against it (MRU) need no per-pick graph rescans.
        """
        max_rounds = 2 * len(run.graph)
        rounds = 0
        while run.pending and rounds < max_rounds:
            rounds += 1
            ready = self.get_ready_tasks(run)
            if not ready:
                if run.pending:
                    # deps failed upstream (or graph bug): nothing will ever
                    # become ready — fail the remainder
                    for tid in sorted(run.pending):
                        self.fail(run, run.graph[tid])
                break
            progressed = False
            ordered = order_fn(run, ready)
            for task in ordered:
                ready_ids = [
                    t.task_id for t in ordered if t.task_id in run.pending
                ]
                node = pick_node_fn(run, task, ready_ids)
                if node is None:
                    self.fail(run, task)
                else:
                    self.assign(run, task, node)
                    progressed = True
            if not progressed and run.pending:
                for tid in sorted(run.pending):
                    self.fail(run, run.graph[tid])
                break
