"""HEFT: communication- and load-aware earliest-finish-time placement.

The reference's CriticalPathScheduler is "HEFT-inspired" (paper p.8) but
ignores communication entirely — it sorts by downstream path and takes the
fastest node (reference ``schedulers.py:299-372``).  This is the real
algorithm, extended with the cost model the backends actually charge
(``LinkModel``): per-task upward ranks include mean transfer cost, and node
choice minimizes *earliest finish time* accounting for

* node busy time (one task at a time per core),
* dependency data arrival (+ interconnect transfer when the producer sits
  on another node),
* parameter availability under the prefetch model (per-node host-link
  queue, matching ``SimulatedBackend(prefetch_params=True)`` and the device
  backend's pre-placement),
* per-node HBM budgets with the same cache/fit accounting as every other
  policy (tasks that fit nowhere fail, with their descendants).

This is the policy built to win the north-star benchmark: it optimizes the
same objective the replay measures, instead of a proxy.
"""

from __future__ import annotations

from typing import Dict, Optional

from ..backends.sim import LinkModel
from ..core.cluster import DeviceState
from .base import BaseScheduler, SchedulerRun


class HEFTScheduler(BaseScheduler):
    name = "heft"

    def __init__(self, link: Optional[LinkModel] = None):
        self.link = link or LinkModel()

    def run_policy(self, run: SchedulerRun) -> None:
        graph, cluster = run.graph, run.cluster
        n_nodes = len(cluster)
        # probability a dependency edge crosses nodes under uniform placement
        cross_frac = (n_nodes - 1) / n_nodes if n_nodes > 1 else 0.0
        mean_speed = sum(d.compute_speed for d in cluster) / n_nodes

        # upward rank: mean execution + mean communication to the critical child
        rank: Dict[str, float] = {}
        for tid in reversed(graph.topo_order):
            task = graph[tid]
            w = task.compute_time / mean_speed
            comm = cross_frac * self.link.transfer_time(graph.output_gb(tid))
            best_child = 0.0
            for c in graph.dependents(tid):
                best_child = max(best_child, comm + rank[c])
            rank[tid] = w + best_child

        # EFT assignment state.  Insertion-based processor selection: each
        # node keeps its busy intervals sorted; a task may slot into an idle
        # gap (pipeline warm-up/drain bubbles) rather than only appending.
        busy: Dict[str, list] = {d.node_id: [] for d in cluster}
        load_queue_end: Dict[str, float] = {d.node_id: 0.0 for d in cluster}
        param_ready_at: Dict[tuple, float] = {}
        finish: Dict[str, float] = {}
        start_at: Dict[str, float] = {}

        def earliest_slot(intervals, ready: float, dur: float) -> float:
            t = ready
            for s, e in intervals:
                if t + dur <= s:
                    return t
                t = max(t, e)
            return t

        order = sorted(graph.task_ids(), key=lambda t: -rank[t])
        for tid in order:
            task = graph[tid]
            if any(d in run.failed for d in task.dependencies):
                self.fail(run, task)
                continue

            best: Optional[DeviceState] = None
            best_eft = float("inf")
            best_start = 0.0
            params_sorted = sorted(task.params_needed)
            for node in cluster:
                if not self.can_fit(run, task, node):
                    continue
                nid = node.node_id
                # params: loads queue on the node's host link; cached params
                # may still be in flight from a predecessor's enqueue
                q_end = load_queue_end[nid]
                ready = 0.0
                for p in params_sorted:
                    if p in node.cached_params:
                        ready = max(ready, param_ready_at.get((nid, p), 0.0))
                    else:
                        q_end += self.link.param_load_time(
                            graph.param_size_gb(p)
                        )
                        ready = max(ready, q_end)
                for d in task.dependencies:
                    arrive = finish[d]
                    dep_nid = run.graph[d].assigned_node
                    if dep_nid != nid:
                        # topology-aware: cross-slice edges pay the DCN
                        # tier under a TieredLinkModel, so EFT naturally
                        # prefers keeping chatty edges inside a slice
                        arrive += self.link.transfer_time(
                            run.graph.output_gb(d),
                            src_slice=cluster[dep_nid].slice_id,
                            dst_slice=node.slice_id,
                        )
                    ready = max(ready, arrive)
                dur = task.compute_time / node.compute_speed
                start = earliest_slot(busy[nid], ready, dur)
                if start + dur < best_eft:
                    best, best_eft, best_start = node, start + dur, start
            if best is None:
                self.fail(run, task)
                continue

            nid = best.node_id
            # name order, so each param's queued ready-time is deterministic
            for p in params_sorted:
                if p not in best.cached_params:
                    load_queue_end[nid] += self.link.param_load_time(
                        graph.param_size_gb(p)
                    )
                    param_ready_at[(nid, p)] = load_queue_end[nid]
            self.assign(run, task, best)
            busy[nid].append((best_start, best_eft))
            busy[nid].sort()
            finish[tid] = best_eft
            start_at[tid] = best_start

        # Emit per-node lists and the global order sorted by intended start
        # time, so a sequential per-node replay realizes the inserted
        # interleaving (stable sort keeps rank order on ties; start times
        # respect dependencies by construction).
        pos = {tid: i for i, tid in enumerate(run.assignment_order)}
        run.assignment_order.sort(key=lambda t: (start_at.get(t, 0.0), pos[t]))
        for nid, tids in run.per_node.items():
            tids.sort(key=lambda t: (start_at.get(t, 0.0), pos[t]))
