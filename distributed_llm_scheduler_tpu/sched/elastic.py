"""Elastic recovery: re-place surviving work after node failures.

The reference's failure story stops at *scheduling-time* degradation (tasks
that fit nowhere are failed, reference ``schedulers.py:128-131,202-206``);
node churn is explicitly future work in its paper ("nodes may join or
leave... we focus on static configurations", §3.1.2; checkpointing/task
migration "unimplemented", §6.6.2).  This module implements that future
work for the rebuild:

* :func:`surviving_work` — partition a partially-executed run: outputs on
  dead nodes are LOST (a dead chip's HBM is gone), so completed tasks on
  dead nodes — and anything transitively depending only on them — must
  re-run; completed tasks on live nodes keep their outputs and become
  external inputs to the remainder.
* :func:`remainder_graph` — a re-schedulable TaskGraph of exactly the
  tasks that must (re-)run, with satisfied dependencies pruned and param
  requirements intact (params cached on a dead node must re-load onto
  whatever node inherits its work).  ``arg_tasks`` keep referencing the
  surviving producers; at execution time their live outputs are fed in
  via ``DeviceBackend.execute(ext_outputs=...)``.
* :func:`reschedule` — places the remainder on the surviving cluster with
  any registered policy, preserving the live nodes' completed placement
  (their caches still hold the params they loaded — the MRU locality model
  keeps paying after a failure).

Together with checkpoint/resume (``utils/checkpoint.py``) this upgrades
fail-and-continue into fail-and-recover: kill a node mid-replay, reschedule
the remainder, and total work done is bounded by (completed-on-survivors +
remainder) — tested against a full from-scratch re-run in
``tests/test_elastic.py``.
"""

from __future__ import annotations

import copy
from typing import Iterable, Optional, Set, Tuple

from ..core.cluster import Cluster
from ..core.graph import Task, TaskGraph
from ..core.schedule import Schedule


def surviving_work(
    graph: TaskGraph,
    schedule: Schedule,
    completed: Iterable[str],
    dead_nodes: Iterable[str],
    have_outputs: Optional[Iterable[str]] = None,
) -> Tuple[Set[str], Set[str]]:
    """Split tasks into (must_run, available) after node failures.

    ``available``: completed tasks whose outputs live on surviving nodes —
    they stay available to re-running consumers (a consumer re-run never
    forces its producer to re-run; the producer's output is alive and is
    fed in via ``DeviceBackend.execute(ext_outputs=...)``).
    ``must_run``: everything else — incomplete tasks and completed tasks
    whose outputs sat on dead nodes.

    ``have_outputs``: the task ids whose output values the caller actually
    retained (``DeviceBackend.execute(keep_outputs=True)`` ->
    ``DeviceReport.task_outputs``).  Completed-on-survivor tasks whose
    values were NOT kept (e.g. segment-internal values under fused
    dispatch) re-run too — availability means "I can hand its bytes to
    ext_outputs", not just "it once finished".
    """
    dead = set(dead_nodes)
    placement = schedule.placement
    done = set(completed)
    available: Set[str] = {
        t for t in done if placement.get(t) is not None
        and placement[t] not in dead
    }
    if have_outputs is not None:
        available &= set(have_outputs)
    # a completed-on-survivor task whose output feeds a re-running consumer
    # is still available (its output is alive); only dead-node outputs are
    # gone.  must_run closure: start from non-available, propagate nothing —
    # a task re-runs iff it is not available.
    must_run = {t.task_id for t in graph.tasks()} - available
    return must_run, available


def remainder_graph(
    graph: TaskGraph,
    must_run: Set[str],
    name: Optional[str] = None,
) -> TaskGraph:
    """A fresh TaskGraph of ``must_run`` tasks, dependencies on available
    tasks pruned (their outputs are external inputs at execution time).

    Tasks are deep-copied with scheduling state reset, so the remainder
    can be handed to any policy like a brand-new DAG.
    """
    sub = TaskGraph(name=name or f"{graph.name}_remainder")
    for tid in graph.topo_order:
        if tid not in must_run:
            continue
        t = graph[tid]
        nt = Task(
            task_id=t.task_id,
            memory_required=t.memory_required,
            compute_time=t.compute_time,
            dependencies=[d for d in t.dependencies if d in must_run],
            params_needed=set(t.params_needed),
            param_bytes=dict(t.param_bytes),
            fn=t.fn,
            # materialize the implicit args-are-deps default BEFORE pruning:
            # the remainder task's dependencies shrink, but its fn still
            # consumes the original producers' outputs (surviving ones via
            # DeviceBackend ext_outputs)
            arg_tasks=list(t.arg_tasks or t.dependencies),
            param_alias=copy.copy(t.param_alias),
            out_shape=t.out_shape,
            out_bytes=t.out_bytes,
            flops=t.flops,
            group=t.group,
        )
        sub.add_task(nt)
    sub.freeze()
    return sub


def reschedule(
    graph: TaskGraph,
    schedule: Schedule,
    completed: Iterable[str],
    dead_nodes: Iterable[str],
    cluster: Cluster,
    scheduler,
    have_outputs: Optional[Iterable[str]] = None,
) -> Tuple[Schedule, TaskGraph, Set[str], Set[str]]:
    """Re-place everything that must (re-)run after ``dead_nodes`` fail.

    Args:
      graph: the original full graph.
      schedule: the schedule that was executing when the failure hit.
      completed: task_ids finished before the failure.
      dead_nodes: node_ids lost (their HBM contents with them).
      cluster: the surviving cluster (must not contain dead nodes).
      scheduler: any policy instance (``get_scheduler(...)``).
      have_outputs: retained output ids (``DeviceReport.task_outputs``
        from ``execute(keep_outputs=True)``); see :func:`surviving_work`.

    Returns ``(new_schedule, remainder, must_run, available)`` — the
    remainder graph IS the one the schedule was computed over; execute
    that same object rather than rebuilding it.
    """
    dead = set(dead_nodes)
    still_dead = [d.node_id for d in cluster if d.node_id in dead]
    if still_dead:
        raise ValueError(
            f"surviving cluster still contains dead nodes {still_dead}"
        )
    must_run, available = surviving_work(
        graph, schedule, completed, dead, have_outputs
    )
    sub = remainder_graph(graph, must_run)
    new_schedule = scheduler.schedule(sub, cluster)
    return new_schedule, sub, must_run, available
