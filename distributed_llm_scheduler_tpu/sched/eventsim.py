"""Dependency-aware per-node ordering for a fixed placement.

A :class:`Schedule`'s per-node lists are executed **in order** by both
backends (``SimulatedBackend.execute`` replays ``assignment_order``;
``DeviceBackend`` dispatches the same way) — so a placement-correct schedule
can still serialize terribly if its order induces head-of-line blocking: a
task queued early on a node blocks everything behind it while it waits for a
slow cross-node input.  Round-loop policies emit Kahn-wave order, which for
microbatched pipeline DAGs is the worst case — every stage touches ALL
microbatches' op *k* before any microbatch's op *k+1*, turning the pipeline
fill into ``stages x stage_total``.

:func:`dependency_aware_order` fixes the *order* without touching the
*placement*: an event-driven simulation under the same cost model the replay
charges (per-node serial execution, cross-node ICI transfer on dependency
edges, prefetched parameter loads queued per node in first-use order).
Whenever a node is free it starts the **deepest** task whose inputs have
already arrived — depth-first within a node is what drives one microbatch
through a whole stage before starting the next, i.e. 1F1B interleaving
emerges from the DAG structure with no microbatch labels needed (plain
earliest-arrival FIFO degenerates to breadth-first waves again: all roots
arrive at t=0).  If nothing has arrived yet, the earliest-arriving task is
taken instead, so the node never idles waiting for a "deep" input while a
shallow one sits ready.  The returned order is sorted by simulated start
time, the convention HEFT's insertion pass uses (sched/heft.py).
"""

from __future__ import annotations

import heapq
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..backends.sim import LinkModel
from ..core.graph import TaskGraph

_EPS = 1e-12


@dataclass
class PlacementTimeline:
    """Full event-sim outcome for one placement.

    ``simulate_placement`` keeps its historical ``(order, makespan,
    node_finish)`` triple; the search tier (:mod:`.search`) additionally
    needs per-task ``start_at``/``finish`` to walk the simulated critical
    path, so the timeline is exposed whole here.
    """

    order: List[str] = field(default_factory=list)
    makespan: float = 0.0
    node_finish: Dict[str, float] = field(default_factory=dict)
    start_at: Dict[str, float] = field(default_factory=dict)
    finish: Dict[str, float] = field(default_factory=dict)


def dependency_aware_order(
    graph: TaskGraph,
    placement: Dict[str, str],
    speeds: Optional[Dict[str, float]] = None,
    link: Optional[LinkModel] = None,
    slices: Optional[Dict[str, int]] = None,
) -> List[str]:
    """Order placed tasks to minimize head-of-line blocking.

    Args:
      graph: frozen task graph (tasks not in ``placement`` are skipped —
        they failed placement and never become ready).
      placement: task_id -> node_id for every placed task.
      speeds: node_id -> compute speed (default 1.0).
      link: cost model for cross-node dependency transfers and parameter
        loads (defaults to :class:`LinkModel` defaults).
      slices: node_id -> slice_id (``Cluster.slice_ids()``); lets a
        :class:`~..backends.sim.TieredLinkModel` charge DCN on cross-slice
        edges.  Omitted: every hop is charged at the ICI tier.

    Returns:
      All placed task_ids ordered by simulated start time (ties broken by
      topological position).
    """
    order, _, _ = simulate_placement(graph, placement, speeds, link, slices)
    return order


def simulate_placement(
    graph: TaskGraph,
    placement: Dict[str, str],
    speeds: Optional[Dict[str, float]] = None,
    link: Optional[LinkModel] = None,
    slices: Optional[Dict[str, int]] = None,
) -> Tuple[List[str], float, Dict[str, float]]:
    """The event simulation behind :func:`dependency_aware_order`, with its
    cost estimates exposed: ``(order, makespan, node_finish)``.

    ``makespan`` is the max simulated finish over placed tasks and
    ``node_finish`` each node's last finish — the objective and the
    bottleneck signal the local-search refinement (:mod:`.refine`)
    hill-climbs on, using exactly the cost model the ordering pass and the
    replay charge (so the search can't optimize a different fiction).
    """
    tl = simulate_placement_timeline(graph, placement, speeds, link, slices)
    return tl.order, tl.makespan, tl.node_finish


def simulate_placement_timeline(
    graph: TaskGraph,
    placement: Dict[str, str],
    speeds: Optional[Dict[str, float]] = None,
    link: Optional[LinkModel] = None,
    slices: Optional[Dict[str, int]] = None,
) -> PlacementTimeline:
    """:func:`simulate_placement` with the per-task times kept: the
    annealed search (:mod:`.search`) walks ``start_at``/``finish``
    backward to find the simulated critical path its move proposals are
    biased toward."""
    link = link or LinkModel()
    speeds = speeds or {}
    slices = slices or {}
    topo_pos = {tid: i for i, tid in enumerate(graph.topo_order)}
    depth = graph.depths()

    # per-node ready lists: tasks whose deps all completed, with the time
    # their last input arrives on this node
    ready: Dict[str, List[Tuple[str, float]]] = {}
    node_free: Dict[str, float] = {}
    load_queue_end: Dict[str, float] = {}
    cached: Dict[str, set] = {}
    for nid in sorted(set(placement.values())):
        ready[nid] = []
        node_free[nid] = 0.0
        load_queue_end[nid] = 0.0
        cached[nid] = set()

    missing_deps: Dict[str, int] = {}
    arrival: Dict[str, float] = {}
    finish: Dict[str, float] = {}
    start_at: Dict[str, float] = {}

    for tid in graph.topo_order:
        if tid not in placement:
            continue
        placed_deps = [d for d in graph[tid].dependencies if d in placement]
        missing_deps[tid] = len(placed_deps)
        arrival[tid] = 0.0
        if not placed_deps:
            ready[placement[tid]].append((tid, 0.0))

    # completion event queue: (finish time, topo position, tid)
    events: List[Tuple[float, int, str]] = []

    def dispatch(nid: str) -> None:
        """If `nid` has ready work, start one task: the deepest among those
        whose inputs arrived by the time the node frees up (1F1B), else the
        one arriving soonest.  Params enqueue on the node's host link at
        first use, mirroring SimulatedBackend's prefetch model."""
        lst = ready[nid]
        if not lst:
            return
        now = node_free[nid]
        arrived = [
            (depth[t], -topo_pos[t], i)
            for i, (t, arr) in enumerate(lst)
            if arr <= now + _EPS
        ]
        if arrived:
            _, _, idx = max(arrived)
        else:
            idx = min(
                range(len(lst)), key=lambda i: (lst[i][1], topo_pos[lst[i][0]])
            )
        tid, dep_ready = lst.pop(idx)
        task = graph[tid]
        params_ready = 0.0
        for p in sorted(task.params_needed):
            if p not in cached[nid]:
                cached[nid].add(p)
                load_queue_end[nid] += link.param_load_time(
                    graph.param_size_gb(p)
                )
                params_ready = max(params_ready, load_queue_end[nid])
        start = max(now, dep_ready, params_ready)
        dur = task.compute_time / speeds.get(nid, 1.0)
        start_at[tid] = start
        finish[tid] = start + dur
        node_free[nid] = start + dur  # node committed (serial execution)
        heapq.heappush(events, (start + dur, topo_pos[tid], tid))

    for nid in ready:
        dispatch(nid)

    while events:
        t_done, _, tid = heapq.heappop(events)
        nid = placement[tid]
        for dep in graph.dependents(tid):
            if dep not in placement or dep not in missing_deps:
                continue
            dep_nid = placement[dep]
            arr = finish[tid]
            if dep_nid != nid:
                arr += link.transfer_time(
                    graph.output_gb(tid),
                    src_slice=slices.get(nid),
                    dst_slice=slices.get(dep_nid),
                )
            arrival[dep] = max(arrival[dep], arr)
            missing_deps[dep] -= 1
            if missing_deps[dep] == 0:
                ready[dep_nid].append((dep, arrival[dep]))
                if node_free[dep_nid] <= arrival[dep]:
                    dispatch(dep_nid)
        dispatch(nid)  # node just freed: start its next ready task

    # any still-undispatched ready tasks (nodes that went idle before work
    # arrived): flush deterministically
    for nid in ready:
        while ready[nid]:
            dispatch(nid)

    placed = [tid for tid in graph.topo_order if tid in placement]
    order = sorted(placed, key=lambda t: (start_at.get(t, 0.0), topo_pos[t]))
    node_finish = {nid: 0.0 for nid in ready}
    for tid, f in finish.items():
        nid = placement[tid]
        node_finish[nid] = max(node_finish[nid], f)
    makespan = max(node_finish.values(), default=0.0)
    return PlacementTimeline(
        order=order,
        makespan=makespan,
        node_finish=node_finish,
        start_at=start_at,
        finish=finish,
    )
