"""Per-device linearization of a placed schedule into a phase/exchange IR.

The compiled execution path (backends/compiled_schedule.py) lowers each
device's ENTIRE scheduled run into one XLA program, with cross-device
edges expressed as in-program collectives.  Collectives are rendezvous
points: every participating device must issue the same collective in the
same position of its program, or the mesh deadlocks — so the lowering
cannot reuse :meth:`DeviceBackend.dispatch_order`'s silent topological
fallback (harmless for host-mediated transfers, fatal once the transfer
is a ``ppermute`` both sides must reach).  This module produces the
intermediate representation the lowering and the COL00x analysis pass
(analysis/collective_pass.py) share:

* :func:`strict_dispatch_order` — the same greedy per-node-order merge as
  the interpreted path, but a cross-node ordering cycle raises
  :class:`OrderingDeadlock` (carrying the stuck queue heads) instead of
  silently re-linearizing;
* :func:`linearize` — cuts that global order into **phases** (per-device
  compute blocks separated by cross-device exchanges): a task lands in
  the earliest phase after every cross-device producer has been
  exchanged, never earlier than its same-device predecessor in the
  schedule's per-node order.  Phase boundaries carry the ordered
  :class:`Exchange` list — each lowered as one ``ppermute`` over the mesh
  axis, emitted identically on every device (SPMD), which is what makes
  the global collective order deadlock-free by construction.

The IR is deliberately tiny and pure-Python: the analysis pass verifies
properties on it (identical per-device collective sequences, permutation
validity) without tracing any JAX, and :meth:`ProgramIR.signature` gives
the deterministic identity the compiled-program cache keys off.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from ..core.graph import TaskGraph
from ..core.schedule import Schedule


class OrderingDeadlock(RuntimeError):
    """Per-node orders are mutually inconsistent: the greedy merge stalled
    with every queue head waiting on a task stuck behind another head.

    ``heads`` maps each stalled node to its blocking queue head and the
    unmet dependencies that head is waiting for.
    """

    def __init__(self, heads: Dict[str, Tuple[str, Tuple[str, ...]]]):
        self.heads = dict(heads)
        detail = "; ".join(
            f"{node}: {tid!r} waits on {list(deps)}"
            for node, (tid, deps) in sorted(self.heads.items())
        )
        super().__init__(
            f"per-node orders admit no global dispatch order ({detail})"
        )


def strict_dispatch_order(
    graph: TaskGraph, schedule: Schedule
) -> List[str]:
    """Global linearization honoring per-node order — or a hard error.

    Identical greedy merge to ``DeviceBackend.dispatch_order`` (emit the
    earliest-assigned ready queue head), except that a stall raises
    :class:`OrderingDeadlock` rather than falling back to topological
    order: a compiled program built from a re-linearized order would run,
    but its collective sequence would no longer be the schedule the
    policy decided — and in a true MPMD deployment the divergence is a
    deadlock, so it must surface as an error here (COL002).
    """
    placement = schedule.placement
    topo_pos = {tid: i for i, tid in enumerate(graph.topo_order)}
    prio = {tid: i for i, tid in enumerate(schedule.assignment_order)}
    queues = {
        n: [t for t in lst if t in topo_pos and placement.get(t) == n]
        for n, lst in schedule.per_node.items()
        if lst
    }
    queues = {n: q for n, q in queues.items() if q}
    idx = {n: 0 for n in queues}
    emitted: set = set()
    order: List[str] = []

    def unmet(t: str) -> Tuple[str, ...]:
        return tuple(
            d for d in graph[t].dependencies
            if d not in emitted and d in placement
        )

    total = sum(len(q) for q in queues.values())
    while len(order) < total:
        ready = [
            n for n in queues
            if idx[n] < len(queues[n]) and not unmet(queues[n][idx[n]])
        ]
        if not ready:
            heads = {
                n: (queues[n][idx[n]], unmet(queues[n][idx[n]]))
                for n in queues
                if idx[n] < len(queues[n])
            }
            raise OrderingDeadlock(heads)
        n = min(
            ready,
            key=lambda n: (
                prio.get(queues[n][idx[n]], topo_pos[queues[n][idx[n]]]),
                topo_pos[queues[n][idx[n]]],
            ),
        )
        t = queues[n][idx[n]]
        idx[n] += 1
        emitted.add(t)
        order.append(t)
    return order


@dataclass(frozen=True)
class Exchange:
    """One cross-device value movement at a phase boundary: the value of
    ``tid`` (computed on ``src``) becomes available on ``dst``.  Lowered
    as one ``lax.ppermute`` with ``perm=((src_index, dst_index),)``."""

    tid: str
    src: str
    dst: str


@dataclass(frozen=True)
class Phase:
    """One compute block: every device runs its ``compute`` tasks (in
    per-node schedule order), then all devices issue ``exchanges`` in
    listed order."""

    index: int
    compute: Dict[str, Tuple[str, ...]]
    exchanges: Tuple[Exchange, ...]


@dataclass(frozen=True)
class ProgramIR:
    """The whole-program lowering plan: devices in mesh order, the global
    linearization, and the phase/exchange alternation."""

    devices: Tuple[str, ...]
    order: Tuple[str, ...]
    phases: Tuple[Phase, ...]
    #: tasks whose values must survive their producing phase (consumed in
    #: a later phase, exchanged, per-device last, or the graph's final)
    live_out: Dict[int, Tuple[str, ...]] = field(default_factory=dict)

    @property
    def device_index(self) -> Dict[str, int]:
        return {d: i for i, d in enumerate(self.devices)}

    def collective_sequence(
        self, device: Optional[str] = None
    ) -> List[Tuple[str, Tuple[Tuple[int, int], ...], str]]:
        """The ordered collective ops the lowered program issues, as
        ``(primitive, perm, value_id)`` tuples.

        SPMD lowering emits every exchange on every device, so the
        sequence is device-independent — which is exactly the property
        the COL001 check verifies by comparing this per device.
        ``device`` is accepted so a corrupted/mocked IR (tests, future
        true-MPMD lowerings) can expose per-device divergence.
        """
        del device  # SPMD: identical everywhere, by construction
        dix = self.device_index
        seq = []
        for ph in self.phases:
            for ex in ph.exchanges:
                seq.append(
                    ("ppermute", ((dix[ex.src], dix[ex.dst]),), ex.tid)
                )
        return seq

    def signature(self) -> Tuple:
        """Hashable structural identity: equal signatures lower to the
        same program (deterministic-lowering contract)."""
        return (
            self.devices,
            self.order,
            tuple(
                (
                    ph.index,
                    tuple(sorted(
                        (n, ts) for n, ts in ph.compute.items()
                    )),
                    ph.exchanges,
                )
                for ph in self.phases
            ),
        )

    @property
    def n_exchanges(self) -> int:
        return sum(len(ph.exchanges) for ph in self.phases)


def linearize(
    graph: TaskGraph,
    schedule: Schedule,
    order: Optional[Sequence[str]] = None,
    device_order: Optional[Sequence[str]] = None,
) -> ProgramIR:
    """Cut a verified global order into the phase/exchange IR.

    ``order`` defaults to :func:`strict_dispatch_order` (raising
    :class:`OrderingDeadlock` on inconsistent per-node orders).  Tasks
    with unplaced (or transitively skipped) producers are dropped, like
    every execution path.  ``device_order`` fixes the mesh axis order
    (defaults to first-appearance order of nodes in the schedule's
    cluster iteration — callers pass the cluster's device order so mesh
    index == cluster index).

    Phase assignment: ``phase(t) = max(phase(same-device deps),
    phase(cross-device deps) + 1, phase(previous task on t's device))``.
    Each cross-device edge becomes an :class:`Exchange` at the boundary
    just before its consumer's phase, deduplicated per (value, dst) to
    the earliest consumer (received values persist in the consumer's
    registers).  Exchange order within a boundary is deterministic:
    producer's global-order position, then destination mesh index.
    """
    placement = schedule.placement
    if order is None:
        order = strict_dispatch_order(graph, schedule)
    # drop tasks whose transitive producers never run (fail-and-continue,
    # same filter as the segmented runner)
    alive: set = set()
    kept: List[str] = []
    for tid in order:
        if tid not in placement:
            continue
        aids = graph[tid].arg_tasks or graph[tid].dependencies
        if all(d in alive for d in aids):
            alive.add(tid)
            kept.append(tid)
    order = kept

    if device_order is None:
        seen: Dict[str, None] = {}
        for tid in order:
            seen.setdefault(placement[tid])
        devices = tuple(seen)
    else:
        used = {placement[t] for t in order}
        devices = tuple(d for d in device_order if d in used)

    opos = {t: i for i, t in enumerate(order)}
    dix = {d: i for i, d in enumerate(devices)}
    phase_of: Dict[str, int] = {}
    last_on: Dict[str, int] = {}
    for tid in order:
        node = placement[tid]
        p = last_on.get(node, 0)
        for d in graph[tid].arg_tasks or graph[tid].dependencies:
            if d not in phase_of:
                continue  # graph input / ext value: phase 0 is fine
            if placement[d] == node:
                p = max(p, phase_of[d])
            else:
                p = max(p, phase_of[d] + 1)
        phase_of[tid] = p
        last_on[node] = p

    n_phases = (max(phase_of.values()) + 1) if phase_of else 0
    compute: List[Dict[str, List[str]]] = [{} for _ in range(n_phases)]
    for tid in order:
        compute[phase_of[tid]].setdefault(placement[tid], []).append(tid)

    # one exchange per (value, dst), at the earliest consuming boundary
    first_need: Dict[Tuple[str, str], int] = {}
    for tid in order:
        node = placement[tid]
        for d in graph[tid].arg_tasks or graph[tid].dependencies:
            if d in phase_of and placement[d] != node:
                key = (d, node)
                b = phase_of[tid] - 1
                if key not in first_need or b < first_need[key]:
                    first_need[key] = b
    exchanges: List[List[Exchange]] = [[] for _ in range(n_phases)]
    for (val, dst), b in first_need.items():
        exchanges[b].append(Exchange(val, placement[val], dst))
    for b in range(n_phases):
        exchanges[b].sort(key=lambda ex: (opos[ex.tid], dix[ex.dst]))

    # liveness: a phase must export values consumed after it, exchanged
    # at-or-after its boundary, each device's final value (the fence
    # tip), and the graph's final output
    last_tid = {d: None for d in devices}
    for tid in order:
        last_tid[placement[tid]] = tid
    keep: set = set(t for t in last_tid.values() if t)
    if graph.topo_order and graph.topo_order[-1] in phase_of:
        keep.add(graph.topo_order[-1])
    needed_later: set = set(keep)
    for tid in order:
        for d in graph[tid].arg_tasks or graph[tid].dependencies:
            if d in phase_of and phase_of[d] < phase_of[tid]:
                needed_later.add(d)
    for exs in exchanges:
        for ex in exs:
            needed_later.add(ex.tid)
    live_out = {
        p: tuple(
            t for t in order
            if t in needed_later and phase_of[t] == p
        )
        for p in range(n_phases)
    }

    phases = tuple(
        Phase(
            index=p,
            compute={n: tuple(ts) for n, ts in compute[p].items()},
            exchanges=tuple(exchanges[p]),
        )
        for p in range(n_phases)
    )
    return ProgramIR(
        devices=devices, order=tuple(order), phases=phases,
        live_out=live_out,
    )
