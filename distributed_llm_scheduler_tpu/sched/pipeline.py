"""Pipeline-stage scheduler: contiguous layer groups -> devices.

The policy for BASELINE.json config #3 ("Llama-3 8B layer-wise DAG,
pipeline-stage scheduling across v5e-16").  The reference has no pipeline
*execution* — "pipeline" appears there only as a synthetic DAG shape
(reference ``simulation.py:116-151``) placed by generic list scheduling.
Here pipeline placement is a first-class policy:

1. tasks are bucketed by their ``group`` label (``embed``, ``layer_i``,
   ``head``) in topological order of first appearance — microbatch chains
   share groups, so one stage serves every microbatch (1F1B-style overlap
   then emerges in the replay/backend from task-level dependencies);
2. groups are partitioned into ``min(n_devices, n_groups)`` **contiguous**
   stages by a linear-partition DP minimizing the lexicographic
   (bottleneck stage cost, number of stages at that bottleneck), where a
   stage costs ``max(compute, param-load time)`` — loads overlap compute
   under the prefetch model, and the count tie-break leaves light stages
   free for parked root groups (re-packed onto them afterwards) — subject
   to per-stage memory feasibility (stage param union + max task
   activation must fit the stage's device);
3. stage *i* is pinned to device *i*; tasks are assigned in topo order.

Contiguity is what makes this a pipeline: every cross-stage edge flows
"forward" to the next device, so activations stream stage-to-stage over
ICI instead of bouncing arbitrarily.  If no memory-feasible contiguous
partition exists, a greedy sequential fill places as many groups as fit per
device and fails the overflow (the reference's graceful-degradation
contract, reference ``schedulers.py:198-206``).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

from ..backends.sim import LinkModel
from ..core.cluster import DeviceState
from ..core.graph import TaskGraph
from .base import BaseScheduler, SchedulerRun
from .eventsim import dependency_aware_order, simulate_placement

_INF = float("inf")


def _group_stats(
    graph: TaskGraph,
) -> Tuple[List[str], List[float], List[float], List[Set[str]]]:
    """Group labels in topo order of first appearance (ungrouped tasks are
    their own singleton group), with per-group total compute, max single-task
    activation, and param-name union."""
    order: List[str] = []
    gidx: Dict[str, int] = {}
    for tid in graph.topo_order:
        g = graph[tid].group or tid
        if g not in gidx:
            gidx[g] = len(order)
            order.append(g)
    compute = [0.0] * len(order)
    activ = [0.0] * len(order)
    gparams: List[Set[str]] = [set() for _ in order]
    for t in graph.tasks():
        i = gidx[t.group or t.task_id]
        compute[i] += t.compute_time
        activ[i] = max(activ[i], t.memory_required)
        gparams[i] |= t.params_needed
    return order, compute, activ, gparams


class PipelineStageScheduler(BaseScheduler):
    """Contiguous stage partitioning over ordered layer groups."""

    name = "pipeline"

    def __init__(self, n_stages: Optional[int] = None,
                 link: Optional[LinkModel] = None):
        self.n_stages = n_stages
        self.link = link or LinkModel()

    # -- stage planning ----------------------------------------------------
    def plan_stages(
        self,
        graph: TaskGraph,
        devices: List[DeviceState],
        stats: Optional[
            Tuple[List[str], List[float], List[float], List[Set[str]]]
        ] = None,
        reserved: Optional[List[float]] = None,
    ) -> Optional[List[int]]:
        """Return stage boundaries (k+1 indices into the group order; stage s
        covers groups [bounds[s], bounds[s+1])) — or None if no feasible
        partition.

        DP over (groups consumed, stages used) minimizing the lexicographic
        (bottleneck stage cost, count of stages at that bottleneck), stage
        cost = ``max(compute, param-load time)``; memory feasibility is
        checked against the actual device each stage lands on (minus any
        per-device ``reserved`` GB held by parked groups), so heterogeneous
        HBM budgets work.
        """
        groups, compute, activ, gparams = stats or _group_stats(graph)
        gsorted = [sorted(ps) for ps in gparams]  # name order, sorted ONCE
        n = len(groups)
        k = self.n_stages or min(len(devices), n)
        k = min(k, n, len(devices))
        # host-link rate converts a stage's param bytes into load time; the
        # stage's steady-state cost is max(compute, load) because parameter
        # DMA overlaps compute under the prefetch model (backends/sim.py)
        host = self.link.param_load_gbps or _INF

        prefix = [0.0]
        for c in compute:
            prefix.append(prefix[-1] + c)

        # best[j][s] = lexicographic (bottleneck stage cost, number of
        # stages at that bottleneck) covering first j groups with s stages;
        # choice[j][s] = start index of stage s.  The count tie-break is
        # what creates room for the parked-group repack: among equal-
        # bottleneck partitions it prefers the one with the FEWEST heavy
        # stages, leaving light stages for parked weights (folding load
        # into a summed stage cost over-weights it — measured r1; the
        # max() form with tie-break is the overlap-faithful version)
        best = [[(_INF, 0)] * (k + 1) for _ in range(n + 1)]
        choice = [[-1] * (k + 1) for _ in range(n + 1)]
        best[0][0] = (0.0, 0)
        for s in range(1, k + 1):
            cap = devices[s - 1].total_memory
            if reserved is not None:
                cap -= reserved[s - 1]  # parked groups' params
            for j in range(s, n + 1):
                # widen stage [i, j) by stepping i down, growing the param
                # union / activation max / size sum incrementally; stage
                # memory is monotone in the range, so break once over cap
                params: Set[str] = set()
                pg = 0.0
                act = 0.0
                for i in range(j - 1, s - 2, -1):
                    # name order: deterministic float accumulation (parity)
                    for p in gsorted[i]:
                        if p not in params:
                            params.add(p)
                            pg += graph.param_size_gb(p)
                    act = max(act, activ[i])
                    if pg + act > cap + 1e-9:
                        break
                    prev_b, prev_c = best[i][s - 1]
                    if prev_b == _INF:
                        continue
                    cost = max(prefix[j] - prefix[i], pg / host)
                    if cost > prev_b:
                        cand = (cost, 1)
                    elif cost == prev_b:
                        cand = (prev_b, prev_c + 1)
                    else:
                        cand = (prev_b, prev_c)
                    if cand < best[j][s]:
                        best[j][s] = cand
                        choice[j][s] = i
        # allow fewer stages than devices (tiny graphs / huge devices)
        feas = [s for s in range(1, k + 1) if best[n][s][0] < _INF]
        if not feas:
            return None
        s = min(feas, key=lambda s: best[n][s])
        bounds = [0] * (s + 1)
        bounds[s] = n
        j = n
        for t in range(s, 0, -1):
            j = choice[j][t]
            bounds[t - 1] = j
        return bounds

    def _fits_per_device(
        self,
        graph: TaskGraph,
        devices: List[DeviceState],
        all_groups: List[str],
        all_gparams: List[Set[str]],
        all_activ: List[float],
        stage_map: Dict[str, int],
    ) -> bool:
        """Per-device feasibility for interleaved plans: the DP checks each
        stage against its device's budget in isolation, but with v stages
        per device the param-union across stages is what must fit."""
        n_dev = len(devices)
        params: List[Set[str]] = [set() for _ in range(n_dev)]
        act = [0.0] * n_dev
        for gi, g in enumerate(all_groups):
            d = stage_map.get(g)
            if d is None:
                continue
            params[d] |= all_gparams[gi]
            act[d] = max(act[d], all_activ[gi])
        for d in range(n_dev):
            pg = sum(graph.param_size_gb(p) for p in sorted(params[d]))
            if pg + act[d] > devices[d].total_memory + 1e-9:
                return False
        return True

    # -- parked-group rebalancing -----------------------------------------
    def _rebalance_parked(
        self,
        graph: TaskGraph,
        devices: List[DeviceState],
        all_groups: List[str],
        all_gparams: List[Set[str]],
        all_activ: List[float],
        parked: List[int],
        stage_of: Dict[str, int],
    ) -> None:
        """Re-pack parked root groups onto the lightest stages.

        Parking runs *before* the stage partition exists, one group per
        least-reserved device — so a parked group can land on a device
        that then also draws a heavy stage.  In host-link-bound regimes
        (the measured TPU calibration: 1.55 GB/s host leg) the makespan
        floor is the heaviest device's param bytes, so once the DP has
        fixed stages, parked groups are greedily re-packed (largest
        first) onto the device minimizing the resulting param-union
        load.  The repack is adopted only if it strictly lowers the
        bottleneck load; all arithmetic runs in sorted-name order so the
        native engine twin reproduces it bit-for-bit.  Measured on the
        flagship bench graph: -11% replayed makespan vs park-first.
        """
        n_dev = len(devices)
        parked_set = set(parked)
        base_params: List[Set[str]] = [set() for _ in range(n_dev)]
        base_act = [0.0] * n_dev
        for gi, gname in enumerate(all_groups):
            if gi in parked_set or gname not in stage_of:
                continue
            d = stage_of[gname]
            base_params[d] |= all_gparams[gi]
            base_act[d] = max(base_act[d], all_activ[gi])

        def union_gb(names: Set[str]) -> float:
            return sum(graph.param_size_gb(p) for p in sorted(names))

        def max_load(assign: Dict[int, int]) -> float:
            params = [set(s) for s in base_params]
            for gi, d in assign.items():
                params[d] |= all_gparams[gi]
            return max(union_gb(s) for s in params)

        orig = {gi: stage_of[all_groups[gi]] for gi in parked}
        order = sorted(parked, key=lambda gi: (-union_gb(all_gparams[gi]), gi))
        params = [set(s) for s in base_params]
        act = list(base_act)
        repack: Dict[int, int] = {}
        for gi in order:
            best_d, best_load = None, None
            for d in range(n_dev):
                names = params[d] | all_gparams[gi]
                lg = union_gb(names)
                if lg + max(act[d], all_activ[gi]) > devices[d].total_memory + 1e-9:
                    continue
                # ties prefer the LATER device: stage s is pinned to device
                # s, and a parked load on an early stage queues ahead of
                # that stage's weights (first-use order), delaying the
                # pipeline fill; late stages have until the wave reaches
                # them (>= keeps the highest tied index)
                if best_load is None or lg <= best_load:
                    best_d, best_load = d, lg
            if best_d is None:
                return  # can't fit somewhere: keep the original parking
            repack[gi] = best_d
            params[best_d] |= all_gparams[gi]
            act[best_d] = max(act[best_d], all_activ[gi])
        if max_load(repack) < max_load(orig) - 1e-12:
            for gi, d in repack.items():
                stage_of[all_groups[gi]] = d

    # -- policy ------------------------------------------------------------
    def run_policy(self, run: SchedulerRun) -> None:
        graph, devices = run.graph, run.cluster.devices
        all_groups, all_compute, all_activ, all_gparams = _group_stats(graph)
        n_dev = len(devices)

        # Which groups contain root tasks?  Root-bearing groups (embedding,
        # or vocab-sharded embedding/logit partials — whose tied weight spans
        # both ends of the graph, so stage contiguity is impossible for them
        # anyway) have no upstream locality pull, but their parameters gate
        # the pipeline start: PARK them — one group per device,
        # largest-params first onto the least-reserved device — so their
        # host-link loads run in parallel across the cluster instead of
        # queueing behind one stage's weights.
        group_tasks: Dict[str, List[str]] = {}
        for tid in graph.topo_order:
            group_tasks.setdefault(graph[tid].group or tid, []).append(tid)
        is_root_group = {
            g: any(not graph[t].dependencies for t in tids)
            for g, tids in group_tasks.items()
        }

        reserved = [0.0] * n_dev
        stage_of: Dict[str, int] = {}

        def park(gi: int) -> bool:
            """Park group index `gi` (into all_groups) on the least-reserved
            device it fits; True on success."""
            pg = sum(graph.param_size_gb(p) for p in sorted(all_gparams[gi]))
            need = pg + all_activ[gi]
            order = sorted(range(n_dev), key=lambda d: (reserved[d], d))
            for d in order:
                if reserved[d] + need <= devices[d].total_memory + 1e-9:
                    stage_of[all_groups[gi]] = d
                    reserved[d] += pg
                    return True
            return False

        remaining = list(range(len(all_groups)))
        parked_placed: List[int] = []
        tail_parked = False
        if len(all_groups) > n_dev:  # tiny graphs: plain contiguous stages
            parked = [i for i in remaining if is_root_group[all_groups[i]]]
            for gi in sorted(
                parked,
                key=lambda i: -sum(
                    graph.param_size_gb(p) for p in sorted(all_gparams[i])
                ),
            ):
                if park(gi):
                    remaining.remove(gi)
                    parked_placed.append(gi)

            # Weight-tied tail (tied embedding/LM-head, reference
            # test_gpt2.py:160-166): co-locate the last group with the parked
            # group it shares params with, so the shared table is loaded over
            # the host link ONCE, early — otherwise the tail stage re-loads
            # it *behind* its own layer weights, putting the whole table's
            # load on the pipeline drain.  Standard pipeline-parallel
            # practice (Megatron/GPipe co-locate embedding + head).
            if remaining:
                ti = remaining[-1]
                parked_params_on: Dict[int, Set[str]] = {}
                for gi, g in enumerate(all_groups):
                    if g in stage_of:
                        parked_params_on.setdefault(
                            stage_of[g], set()
                        ).update(all_gparams[gi])
                tied_dev = next(
                    (
                        d for d, ps in sorted(parked_params_on.items())
                        if all_gparams[ti] & ps
                    ),
                    None,
                )
                if tied_dev is not None:
                    extra = sum(
                        graph.param_size_gb(p)
                        for p in sorted(all_gparams[ti] - parked_params_on[tied_dev])
                    )
                    if (
                        reserved[tied_dev] + extra + all_activ[ti]
                        <= devices[tied_dev].total_memory + 1e-9
                    ):
                        stage_of[all_groups[ti]] = tied_dev
                        reserved[tied_dev] += extra
                        remaining.remove(ti)
                        tail_parked = True

        stats = (
            [all_groups[i] for i in remaining],
            [all_compute[i] for i in remaining],
            [all_activ[i] for i in remaining],
            [all_gparams[i] for i in remaining],
        )
        groups, _, activ, gparams = stats

        # Virtual-stage interleaving (Megatron-LM style): stage s pins to
        # device s % n_dev, so v stages per device shrink the fill/drain
        # bubble from (S-1)/M of the makespan to ~(S-1)/(vM) while every
        # cross-stage edge still flows ring-forward.  Each candidate depth
        # is costed with the event simulation — the same model the replay
        # charges — and the best kept (ties prefer contiguous v=1, which
        # also minimizes cross-slice crossings).  Deep interleave cut the
        # 5k-task Llama probe's pipeline makespan from 2.7x to 1.8x of
        # round-robin (ICI_r05; VERDICT r4 next #3).  An explicit
        # ``n_stages`` skips the sweep (one stage per device, as before).
        vmax = (
            1 if self.n_stages
            else max(1, min(4, -(-len(groups) // max(n_dev, 1))))
        )
        speeds = {d.node_id: d.compute_speed for d in devices}
        slices = {d.node_id: d.slice_id for d in devices}
        candidates: List[Dict[str, int]] = []
        for v in range(1, vmax + 1):
            # a devices list repeated v times makes plan_stages' per-stage
            # cap lookup (devices[s-1]) index cyclically — stage s sees
            # device (s-1) % n_dev's budget
            cand_bounds = self.plan_stages(
                graph, devices * v, stats, reserved * v
            )
            if cand_bounds is None:
                continue
            cand_map = dict(stage_of)
            for s in range(len(cand_bounds) - 1):
                for i in range(cand_bounds[s], cand_bounds[s + 1]):
                    cand_map[groups[i]] = s % n_dev
            if v > 1 and not self._fits_per_device(
                graph, devices, all_groups, all_gparams, all_activ,
                cand_map,
            ):
                continue  # multi-stage union exceeds a device's budget
            candidates.append(cand_map)

        best_map: Optional[Dict[str, int]] = None
        if len(candidates) == 1:
            best_map = candidates[0]  # nothing to compare; skip the sim
        else:
            best_cost = None
            for cand_map in candidates:
                placement = {
                    tid: devices[cand_map[graph[tid].group or tid]].node_id
                    for tid in graph.topo_order
                    if (graph[tid].group or tid) in cand_map
                }
                _, cost, _ = simulate_placement(
                    graph, placement, speeds, self.link, slices
                )
                if best_cost is None or cost < best_cost:
                    best_cost, best_map = cost, cand_map

        if best_map is not None:
            stage_of.update(best_map)
            # load-aware repack of the parked groups now that stage loads
            # are known (skipped when the weight-tied tail was co-located:
            # moving its shard would break the tie locality it bought)
            if parked_placed and not tail_parked:
                self._rebalance_parked(
                    graph, devices, all_groups, all_gparams, all_activ,
                    parked_placed, stage_of,
                )
        else:
            # greedy sequential fill: walk groups in order, advancing to the
            # next device when the current one can't also hold this group
            # (budgets net of parked-group reservations)
            dev = 0
            held: Set[str] = set()
            for i, g in enumerate(groups):
                while dev < len(devices):
                    need_params = held | gparams[i]
                    need = sum(
                        graph.param_size_gb(p) for p in sorted(need_params)
                    ) + activ[i]
                    cap = devices[dev].total_memory - reserved[dev]
                    if need <= cap + 1e-9:
                        held = need_params
                        break
                    dev, held = dev + 1, set()
                stage_of[g] = min(dev, len(devices) - 1)

        for tid in graph.topo_order:
            task = graph[tid]
            if tid not in run.pending:
                continue
            if any(d in run.failed for d in task.dependencies):
                self.fail(run, task)
                continue
            node = devices[stage_of[task.group or tid]]
            if self.can_fit(run, task, node):
                self.assign(run, task, node)
            else:
                self.fail(run, task)

        # Re-order for execution: topo (Kahn-wave) order serializes the
        # pipeline under in-order per-node replay — every stage would touch
        # all microbatches' op k before any op k+1, making the fill cost
        # stages x stage_total.  The event simulation orders each node by
        # input-arrival time instead, so 1F1B microbatch interleaving
        # emerges from the DAG structure (see sched/eventsim.py).
        placement = {
            tid: run.graph[tid].assigned_node
            for tid in run.assignment_order
        }
        speeds = {d.node_id: d.compute_speed for d in run.cluster}
        order = dependency_aware_order(
            run.graph, placement, speeds, self.link,
            slices=run.cluster.slice_ids(),
        )
        run.assignment_order[:] = order
        pos = {tid: i for i, tid in enumerate(order)}
        for nid, tids in run.per_node.items():
            tids.sort(key=lambda t: pos[t])
