"""The policy registry: the reference's four policies plus two new ones.

DFS/Greedy/CriticalPath/MRU mirror the reference's observed behavior
(reference ``schedulers.py:138-525``); RoundRobin is the new comparator
baseline the north-star benchmark measures against (BASELINE.md); HEFT
(:mod:`.heft`) is the communication-aware policy built to win it.  The four
reference policies share the ``_round_loop`` skeleton in :mod:`.base`; each
supplies only a ready-set ordering and a node-picking rule.

The one deliberate divergence from the reference: MRU's node *scoring* is
side-effect free here.  The reference performs real evictions while merely
scoring candidate nodes (reference ``schedulers.py:492``, rolled back only
on shortfall) — we score with a hypothetical eviction plan and apply it only
on the chosen node, keeping the reference's scoring semantics without the
state-mutation bug (SURVEY.md §2 quirks).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..core.cluster import DeviceState
from ..core.graph import Task
from .base import BaseScheduler, SchedulerRun


class RoundRobinScheduler(BaseScheduler):
    """Cyclic placement, ignoring locality: the north-star comparator.

    Ready tasks are taken in DAG insertion order; each goes to the next
    device in cyclic order that can fit it (params + activation).  No
    cache-awareness, no load model — the "do nothing clever" baseline.
    """

    name = "roundrobin"

    def run_policy(self, run: SchedulerRun) -> None:
        cursor = [0]
        devices = run.cluster.devices

        def order(run, ready):
            return ready

        def pick(run, task, ready_ids) -> Optional[DeviceState]:
            n = len(devices)
            for i in range(n):
                node = devices[(cursor[0] + i) % n]
                if self.can_fit(run, task, node):
                    cursor[0] = (cursor[0] + i + 1) % n
                    return node
            return None

        self._round_loop(run, order, pick)


class DFSScheduler(BaseScheduler):
    """Depth-first policy (reference ``schedulers.py:138-208``).

    Each round sorts ready tasks deepest-first (DAG depth from roots) and
    assigns each to the fitting node with the most available memory.

    Divergence from the reference: candidates pass the load-band filter
    (``BaseScheduler.load_band``) first.  When params are shared across
    microbatches, available memory barely moves within a round, so the
    reference rule dumps an entire ready set on one node (3x round-robin
    on the 5k-task Llama probe).
    """

    name = "dfs"

    def run_policy(self, run: SchedulerRun) -> None:
        depth = run.graph.depths()

        def order(run, ready):
            return sorted(ready, key=lambda t: -depth[t.task_id])

        def pick(run, task, ready_ids) -> Optional[DeviceState]:
            fitting = [n for n in run.cluster if self.can_fit(run, task, n)]
            if not fitting:
                return None
            return max(self.load_band(run, task, fitting),
                       key=lambda n: n.available_memory)

        self._round_loop(run, order, pick)


class GreedyScheduler(BaseScheduler):
    """Parameter-locality greedy (reference ``schedulers.py:211-296``).

    Picks the node minimizing the number of params that would need loading,
    tie-broken by most available memory (the reference tie-break).  (The
    reference also defines a chain-identification helper its ``schedule``
    never calls — SURVEY.md §2; we implement the code's actual behavior.)

    Divergence from the reference: the load-band filter
    (``BaseScheduler.load_band``) bounds concentration.  Pure param-overlap
    scoring sends every microbatch of a layer to the node that cached the
    layer's weights first, forever — 11x worse than round-robin on the
    5k-task Llama probe (ICI_r04.json; VERDICT r4 next #3).
    """

    name = "greedy"

    # tighter base band than the other policies: greedy's primary key
    # (min params-to-load) ALWAYS takes the most-cached in-band node, so
    # at the default width it concentrates 2.5x round-robin on the
    # 5k-task probe; one task-time keeps it at 1.96x with the full-hit
    # exception still carrying expert locality
    LOAD_BAND_FACTOR = 1.0

    def run_policy(self, run: SchedulerRun) -> None:
        def order(run, ready):
            return ready

        def pick(run, task, ready_ids) -> Optional[DeviceState]:
            fitting = [n for n in run.cluster if self.can_fit(run, task, n)]
            best, best_key = None, None
            for node in self.load_band(run, task, fitting):
                to_load = sum(
                    1 for p in task.params_needed if p not in node.cached_params
                )
                key = (to_load, -node.available_memory)
                if best_key is None or key < best_key:
                    best, best_key = node, key
            return best

        self._round_loop(run, order, pick)


class CriticalPathScheduler(BaseScheduler):
    """HEFT-flavored makespan policy (reference ``schedulers.py:299-372``).

    Ready tasks sorted by longest downstream critical-path length (own time
    + max over dependents), assigned to the **fastest** fitting node.

    Divergence from the reference: the load-band filter
    (``BaseScheduler.load_band``) applies before the speed pick — without
    it, equal-speed clusters degrade to the dfs dump-on-one-node pathology
    (3x round-robin, and memory exhaustion from param duplication, on the
    5k-task Llama probe).
    """

    name = "critical"

    def run_policy(self, run: SchedulerRun) -> None:
        cpl = run.graph.critical_path_lengths()

        def order(run, ready):
            return sorted(ready, key=lambda t: -cpl[t.task_id])

        def pick(run, task, ready_ids) -> Optional[DeviceState]:
            fitting = [n for n in run.cluster if self.can_fit(run, task, n)]
            if not fitting:
                return None
            return max(self.load_band(run, task, fitting),
                       key=lambda n: (n.compute_speed, n.available_memory))

        self._round_loop(run, order, pick)


class MRUScheduler(BaseScheduler):
    """Cache-aware policy with predictive eviction (reference
    ``schedulers.py:375-525``).

    Keeps per-param usage frequency and recency under a logical clock;
    eviction score (higher = keep) is
    ``10*frequency + 100/(recency+1) + 1000 if needed by any ready pending
    task`` (reference ``schedulers.py:383-402``).  Node choice scores
    ``20*cached-param-overlap + (available_memory if the task fits without
    eviction else 5) - 0.5*completed-task count`` (reference
    ``schedulers.py:444-525`` — the two bonuses are mutually exclusive),
    and ready tasks are ordered by how many pending dependents they unblock.
    """

    name = "mru"

    # scoring weights, verbatim from the reference (SURVEY.md §2 #7)
    W_FREQ = 10.0
    W_RECENCY = 100.0
    W_NEEDED = 1000.0
    W_OVERLAP = 20.0
    W_FITS_AFTER_EVICT = 5.0
    W_LOAD_PENALTY = 0.5

    def run_policy(self, run: SchedulerRun) -> None:
        usage_count: Dict[str, int] = {}
        last_used: Dict[str, int] = {}
        clock = [0]

        def eviction_score(run: SchedulerRun, param: str,
                           ready_ids: List[str]) -> float:
            score = self.W_FREQ * usage_count.get(param, 0)
            recency = clock[0] - last_used.get(param, -clock[0])
            score += self.W_RECENCY / (recency + 1)
            for tid in ready_ids:
                if tid in run.pending and param in run.graph[tid].params_needed:
                    score += self.W_NEEDED
                    break
            return score

        def eviction_plan(run: SchedulerRun, task: Task, node: DeviceState,
                          ready_ids: List[str]) -> Optional[List[Tuple[str, float]]]:
            """Lowest-score-first params to evict so `task` fits; None if
            even evicting everything evictable isn't enough.  Pure."""
            need = self.memory_requirement(run, task, node)
            deficit = need - node.available_memory
            if deficit <= 1e-9:
                return []
            candidates = sorted(
                p for p in node.cached_params if p not in task.params_needed
            )
            # stable sort over the name-ordered list: ties break by name, so
            # eviction order is deterministic (and native-engine parity holds)
            candidates.sort(key=lambda p: eviction_score(run, p, ready_ids))
            plan: List[Tuple[str, float]] = []
            freed = 0.0
            for p in candidates:
                size = run.graph.param_size_gb(p)
                plan.append((p, size))
                freed += size
                if freed >= deficit - 1e-9:
                    return plan
            return None

        def order(run, ready):
            pending_dependents = {
                t.task_id: sum(
                    1 for d in run.graph.dependents(t.task_id) if d in run.pending
                )
                for t in ready
            }
            return sorted(ready, key=lambda t: -pending_dependents[t.task_id])

        def pick(run, task, ready_ids) -> Optional[DeviceState]:
            # candidates = nodes that fit (possibly after eviction); the
            # load band applies on top — the overlap bonus otherwise
            # concentrates shared-param work just like greedy (8x
            # round-robin on the 5k-task Llama probe, VERDICT r4 next #3)
            candidates = [
                (node, plan) for node in run.cluster
                if (plan := eviction_plan(run, task, node, ready_ids))
                is not None
            ]
            eligible = {
                n.node_id
                for n in self.load_band(run, task, [n for n, _ in candidates])
            }
            best, best_score, best_plan = None, None, None
            for node, plan in candidates:
                if node.node_id not in eligible:
                    continue
                overlap = len(task.params_needed & node.cached_params)
                # Reference conditional scoring (schedulers.py:487-493):
                # a node that fits WITHOUT eviction earns its available
                # memory; one that needs eviction earns only the flat +5.
                # The two bonuses are mutually exclusive — an empty plan
                # means no eviction needed (ADVICE r1 #3).
                score = (
                    self.W_OVERLAP * overlap
                    + (node.available_memory if not plan
                       else self.W_FITS_AFTER_EVICT)
                    - self.W_LOAD_PENALTY * len(node.completed_tasks)
                )
                if best_score is None or score > best_score:
                    best, best_score, best_plan = node, score, plan
            if best is None:
                return None
            for p, size in best_plan:
                self.evict_param(run, best, p, size)
            # usage bookkeeping under the logical clock
            for p in task.params_needed:
                usage_count[p] = usage_count.get(p, 0) + 1
                last_used[p] = clock[0]
            clock[0] += 1
            return best

        self._round_loop(run, order, pick)


from .heft import HEFTScheduler  # noqa: E402  (avoids a circular import)
from .pack import GroupPackScheduler  # noqa: E402
from .pipeline import PipelineStageScheduler  # noqa: E402
from .refine import RefinedPackScheduler  # noqa: E402
from .search import SearchScheduler  # noqa: E402

ALL_SCHEDULERS = {
    cls.name: cls
    for cls in (
        RoundRobinScheduler,
        DFSScheduler,
        GreedyScheduler,
        CriticalPathScheduler,
        MRUScheduler,
        HEFTScheduler,
        PipelineStageScheduler,
        GroupPackScheduler,
        RefinedPackScheduler,
        SearchScheduler,
    )
}


def get_scheduler(name: str, link=None, **kwargs) -> BaseScheduler:
    """Policy by name.  ``"native:<policy>"`` selects the C++ engine
    explicitly; ``DLS_NATIVE=1`` upgrades every natively-supported policy
    transparently (parity-tested: identical schedules, faster wall time).

    ``link`` hands link-aware policies (any whose constructor takes a
    ``link=`` keyword) the same cost model the replay charges — required
    for DCN-aware multislice runs.  An explicit ``"native:..."`` request
    with a tiered link raises (the C ABI is flat-link only); the
    ``DLS_NATIVE=1`` transparent upgrade instead falls back to the Python
    policy so the tiered costs are honored.

    Extra ``kwargs`` (e.g. ``budget``/``seed`` for the search tier) are
    forwarded only to policies whose constructor declares them, so one
    call site can configure the whole registry uniformly.
    """
    import inspect
    from ..backends.sim import TieredLinkModel
    from ..utils.config import env_str

    tiered = isinstance(link, TieredLinkModel)
    if name.startswith("native:"):
        from .native import NativeScheduler

        return NativeScheduler(name.split(":", 1)[1], link=link)
    if name not in ALL_SCHEDULERS:
        raise ValueError(
            f"unknown scheduler {name!r}; available: {sorted(ALL_SCHEDULERS)}"
        )
    if env_str("DLS_NATIVE") == "1" and not tiered:
        from .. import native as native_mod
        from .native import NativeScheduler

        if name in native_mod.POLICY_IDS and native_mod.available():
            return NativeScheduler(name, link=link)
    cls = ALL_SCHEDULERS[name]
    params = inspect.signature(cls.__init__).parameters
    accepted = {
        k: v for k, v in kwargs.items() if k in params and v is not None
    }
    if link is not None and "link" in params:
        accepted["link"] = link
    return cls(**accepted)
