"""Annealed iterated local search over task-level placements.

The third scheduling tier (heuristics -> :mod:`.refine` -> search).
:mod:`.refine` hill-climbs *group* moves — it cannot split a group across
devices, so its optimum is bounded by the group partition the heuristics
chose.  This policy searches the full task->device space:

1. **seed** from a portfolio of the existing heuristics (``pack`` /
   ``critical`` / ``heft`` by default; ``refine`` may be added), keeping
   whichever placement the event simulation
   (:func:`..sched.eventsim.simulate_placement_timeline`) scores best.
   ``refine`` is deliberately *not* in the default portfolio: seeding one
   local search with another's budget-truncated output strands the walk
   mid-descent in a state whose neighborhood the task-level sweeps no
   longer intersect — from the constructive ``pack`` seed the annealer
   owns the whole descent and lands strictly below refine's optimum;
2. **propose** task->device moves and task<->task swaps aimed at the
   *simulated critical path* (walked backward from the last finish, the
   same latest-release rule ``obs/attribution.py`` applies to measured
   traces).  The workhorse proposal is **hop healing**: the walk records
   every cross-device dependency edge that *binds* a start time — each
   one pays the link's transfer latency on the critical path — and the
   search proposes collapsing it by co-locating consumer with producer
   (either endpoint, singleton or whole group-slice).  Load-balance
   proposals (bottleneck-device rebalances, group-slice moves) round out
   the mix;
3. **pre-filter** every candidate through the incremental re-analysis
   engine (:class:`..analysis.IncrementalAnalyzer`, ``move_task`` deltas):
   a move that introduces new ERROR diagnostics is rejected *before* the
   event-sim replay is paid for;
4. **accept** under simulated annealing — always downhill, uphill with
   probability ``exp(-delta/T)`` under a geometric cooling schedule — and
   escape local optima with perturbation **kicks** (a few random feasible
   moves off the incumbent best, temperature re-warmed) once progress
   stalls;
5. **commit** the best placement found through pack's memory-checked
   assignment path, so the result is a legal :class:`~..core.schedule.
   Schedule` ordered by the same dependency-aware event simulation every
   other policy uses.

Everything is driven by one seeded ``random.Random`` and a hard evaluation
budget (optionally a wall-clock budget too), so the same seed + budget
yields the identical placement digest across processes — CI gates on that.

Grounded in "GDP: Generalized Device Placement for Dataflow Graphs" and
"The TensorFlow Partitioning and Scheduling Problem: It's the Critical
Path!" (PAPERS.md): search over placements scored by a calibrated
simulator, with critical-path-aware proposals.
"""

from __future__ import annotations

import hashlib
import math
import random
import time
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..backends.sim import LinkModel
from ..core.schedule import Schedule
from .base import SchedulerRun
from .eventsim import PlacementTimeline, simulate_placement_timeline
from .heft import HEFTScheduler
from .pack import GroupPackScheduler
from .refine import RefinedPackScheduler

_EPS = 1e-12


def placement_digest(placement: Dict[str, str]) -> str:
    """Stable cross-process fingerprint of a task->node placement."""
    h = hashlib.sha256()
    for tid in sorted(placement):
        h.update(f"{tid}\x00{placement[tid]}\x01".encode())
    return h.hexdigest()


class _TaskMoveFilter:
    """Incremental static pre-filter over task-level moves.

    The task-level twin of refine's ``_StaticMoveFilter``: the incumbent
    assignment is mirrored into a placed :class:`Schedule` (per-node lists
    in one fixed topo order, so each stays a subsequence of
    ``assignment_order`` — the analyzer's exact-fast-path invariant) and
    every candidate is diffed against it with ``move_task`` deltas.  A
    candidate that raises the ERROR count above the seed baseline is
    rejected before any event-sim evaluation.  Disabled (every query True)
    when the analyzer's fast path doesn't hold — a dirty baseline would
    force full re-analysis per candidate, costing more than it saves.
    """

    def __init__(self, run: SchedulerRun, devices, assign: Dict[str, int]):
        self.devices = devices
        self.enabled = False
        self.state = dict(assign)
        self.rejected = 0  # candidates killed before an eventsim eval
        try:
            from ..analysis import IncrementalAnalyzer
        except Exception:
            return
        per_node: Dict[str, List[str]] = {d.node_id: [] for d in devices}
        placed_order: List[str] = []
        for tid in run.graph.topo_order:
            d = assign.get(tid)
            if d is None:
                continue
            per_node[devices[d].node_id].append(tid)
            placed_order.append(tid)
        mirror = Schedule(
            policy="search-static",
            per_node=per_node,
            assignment_order=placed_order,
            completed=set(placed_order),
        )
        try:
            self._inc = IncrementalAnalyzer(run.graph, run.cluster, mirror)
        except Exception:
            return
        self.base_errors = self._inc.error_count()
        self.enabled = self._inc.exact_fast_path

    def _apply(self, frm: Dict[str, int], to: Dict[str, int]) -> None:
        for tid, d in to.items():
            if frm.get(tid) != d:
                self._inc.move_task(tid, self.devices[d].node_id)

    def ok(self, cand: Dict[str, int]) -> bool:
        """True iff ``cand`` adds no ERROR over the seed baseline."""
        if not self.enabled:
            return True
        self._apply(self.state, cand)
        good = self._inc.error_count() <= self.base_errors
        self._apply(cand, self.state)  # exact undo (subsequence re-insert)
        if not good:
            self.rejected += 1
        return good

    def sync(self, assign: Dict[str, int], verify: bool = False) -> None:
        """Advance the mirror to an accepted incumbent; with ``verify``
        the analyzer re-runs the full suite fresh and asserts the cached
        state matches diagnostic-for-diagnostic (test-only)."""
        if not self.enabled:
            return
        self._apply(self.state, assign)
        self.state = dict(assign)
        if verify:
            self._inc.verify()


class _DeviceLoads:
    """Incremental per-device memory model: param-name union GB + the
    largest single-task activation must fit ``total_memory`` — exactly the
    feasibility rule refine/pack enforce, maintained under task moves."""

    def __init__(self, graph, devices, assign: Dict[str, int]):
        self.graph = graph
        self.devices = devices
        n = len(devices)
        self.counts: List[Dict[str, int]] = [{} for _ in range(n)]
        self.union_gb = [0.0] * n
        self.acts: List[Dict[str, float]] = [{} for _ in range(n)]
        for tid, d in assign.items():
            self.add(tid, d)

    def add(self, tid: str, d: int) -> None:
        task = self.graph[tid]
        cnt = self.counts[d]
        for p in sorted(task.params_needed):
            c = cnt.get(p, 0)
            cnt[p] = c + 1
            if c == 0:
                self.union_gb[d] += self.graph.param_size_gb(p)
        self.acts[d][tid] = task.memory_required

    def remove(self, tid: str, d: int) -> None:
        task = self.graph[tid]
        cnt = self.counts[d]
        for p in sorted(task.params_needed):
            c = cnt[p] - 1
            if c == 0:
                del cnt[p]
                self.union_gb[d] -= self.graph.param_size_gb(p)
            else:
                cnt[p] = c
        del self.acts[d][tid]

    def fits(self, d: int) -> bool:
        act = max(self.acts[d].values(), default=0.0)
        return (
            self.union_gb[d] + act
            <= self.devices[d].total_memory + 1e-9
        )


class SearchScheduler(GroupPackScheduler):
    """Seeded iterated local search / simulated annealing over task moves."""

    name = "search"

    #: random-stream mix (only runs once both deterministic sweeps are
    #: exhausted).  Blind single-task moves almost always lose on a
    #: param-cached graph (any move onto a device that lacks the task's
    #: params duplicates a whole weight-set's load), so most of the
    #: stream stays param-free.
    P_REBALANCE = 0.70  # param-free rebalance off the bottleneck device
    P_SWAP = 0.25       # within single-move proposals: swap instead

    def __init__(
        self,
        link: Optional[LinkModel] = None,
        budget: int = 800,
        seed: int = 0,
        time_budget_s: Optional[float] = None,
        portfolio: Sequence[str] = ("pack", "critical", "heft"),
        tol: float = 1e-9,
        verify_filter: bool = False,
    ):
        super().__init__(link=link)
        self.budget = budget
        self.seed = seed
        self.time_budget_s = time_budget_s
        self.portfolio = tuple(portfolio)
        self.tol = tol
        self.verify_filter = verify_filter
        #: filled per schedule() call: evals / filtered / infeasible_mem /
        #: accepted / kicks / seed_policy / seed + best makespans
        self.stats: Dict[str, object] = {}

    # -- seeding -----------------------------------------------------------

    def _portfolio(self) -> List[Tuple[str, GroupPackScheduler]]:
        # late import: policies.py imports this module for the registry
        from .policies import CriticalPathScheduler

        avail = {
            "pack": lambda: GroupPackScheduler(link=self.link),
            "refine": lambda: RefinedPackScheduler(
                link=self.link, seed=self.seed
            ),
            "critical": lambda: CriticalPathScheduler(),
            "heft": lambda: HEFTScheduler(link=self.link),
        }
        return [(n, avail[n]()) for n in self.portfolio if n in avail]

    def run_policy(self, run: SchedulerRun) -> None:
        graph, devices = run.graph, run.cluster.devices
        self.stats = {
            "evals": 0, "filtered": 0, "infeasible_mem": 0,
            "accepted": 0, "kicks": 0, "seed_policy": "pack",
            "seed_makespan": 0.0, "best_makespan": 0.0,
        }
        if len(devices) < 2 or not graph.topo_order:
            self.commit(run, self.plan(graph, devices))
            return

        speeds = {d.node_id: d.compute_speed for d in devices}
        slices = run.cluster.slice_ids()
        dev_idx = {d.node_id: i for i, d in enumerate(devices)}

        def evaluate(assign: Dict[str, int]) -> PlacementTimeline:
            placement = {
                tid: devices[d].node_id for tid, d in assign.items()
            }
            return simulate_placement_timeline(
                graph, placement, speeds, self.link, slices
            )

        # -- portfolio seeding: each member runs on a fresh SchedulerRun
        # (which resets the shared graph/cluster), so restore both before
        # committing anything through *this* run
        best_seed: Optional[Dict[str, int]] = None
        best_key: Optional[Tuple[int, float]] = None
        for pname, sched in self._portfolio():
            try:
                s = sched.schedule(graph, run.cluster)
            except Exception:
                continue
            assign = {
                tid: dev_idx[node]
                for tid, node in s.placement.items()
                if node in dev_idx
            }
            if not assign:
                continue
            tl = evaluate(assign)
            key = (len(s.failed), tl.makespan)
            if best_key is None or key < best_key:
                best_key, best_seed = key, assign
                self.stats["seed_policy"] = pname
                self.stats["seed_makespan"] = tl.makespan
        run.graph.reset()
        run.cluster.reset()
        if best_seed is None:  # every heuristic failed: degrade to pack
            self.commit(run, self.plan(graph, devices))
            return

        best = self._anneal(run, best_seed, evaluate, slices)
        self.commit(run, best)

    # -- the annealed search ----------------------------------------------

    def _anneal(self, run, seed_assign, evaluate, slices) -> Dict[str, int]:
        devices = run.cluster.devices
        graph = run.graph
        n_dev = len(devices)
        rng = random.Random(self.seed)
        stats = self.stats

        cur = dict(seed_assign)
        tl = evaluate(cur)
        cur_m = tl.makespan
        best, best_m = dict(cur), cur_m
        stats["best_makespan"] = best_m
        if self.budget <= 0:
            return best

        loads = _DeviceLoads(graph, devices, cur)
        flt = _TaskMoveFilter(run, devices, cur)
        tids = sorted(cur)
        crit, hops = self._critical_tasks(graph, devices, cur, tl, slices)
        # time_budget_s users opt into a nondeterministic cutoff; the
        # deterministic knob (and the default) is the eval budget
        deadline = (
            # dls-lint: allow(DET001) opt-in wall-time budget
            time.perf_counter() + self.time_budget_s
            if self.time_budget_s is not None else None
        )

        # geometric cooling from a small fraction of the seed makespan
        # down ~4 orders of magnitude across the budget.  The fraction is
        # deliberately tiny: near a balanced seed the real improvements
        # are micro-moves worth ~1e-3 of the makespan, and a warmer start
        # accepts so much uphill drift the walk never exploits them
        t0 = max(cur_m, _EPS) * 0.002
        alpha = math.exp(math.log(1e-4) / max(self.budget, 1))
        temp = t0
        evals = 0
        stall = 0
        stall_limit = max(40, self.budget // 8)
        attempts = 0
        max_attempts = self.budget * 20  # proposal-storm backstop

        def bottleneck_dev() -> int:
            # sorted tie-break: node_finish iterates in set order
            nid = max(tl.node_finish.items(), key=lambda kv: (kv[1], kv[0]))[0]
            return next(
                i for i, d in enumerate(devices) if d.node_id == nid
            )

        def pick_dst(exclude: int) -> int:
            # min-of-two-uniforms over devices sorted lightest-first:
            # biased toward low node_finish, every device still reachable
            order = sorted(
                range(n_dev),
                key=lambda d: (
                    tl.node_finish.get(devices[d].node_id, 0.0),
                    devices[d].node_id,
                ),
            )
            d = order[min(rng.randrange(n_dev), rng.randrange(n_dev))]
            if d == exclude:
                d = order[rng.randrange(n_dev)]
            return d

        def added_gb(tid: str, d: int) -> float:
            """Param GB a move of ``tid`` onto ``d`` would newly load."""
            cnt = loads.counts[d]
            return sum(
                graph.param_size_gb(p)
                for p in sorted(graph[tid].params_needed)
                if p not in cnt
            )

        def cheap_dst(tid: str, src: int) -> int:
            """Lightest device already holding ``tid``'s params (a
            param-free rebalance), else the biased-light fallback."""
            free = [
                d for d in range(n_dev)
                if d != src and added_gb(tid, d) <= 1e-12
            ]
            if free:
                return min(
                    free,
                    key=lambda d: (
                        tl.node_finish.get(devices[d].node_id, 0.0),
                        devices[d].node_id,
                    ),
                )
            return pick_dst(src)

        group_of = {
            t.task_id: (t.group or t.task_id) for t in graph.tasks()
        }

        def slice_scan():
            """Bottleneck-device group slices (heaviest param union first)
            x destinations (lightest finish first) — refine's systematic
            neighborhood rebuilt at the task-slice level.  Walked by a
            cursor so every candidate is tried exactly once per incumbent
            (random sampling of the same set needs coupon-collector many
            draws to cover it, which is why a pure-random mix stalls)."""
            b = bottleneck_dev()
            by_g: Dict[str, List[str]] = {}
            for t in tids:
                if cur[t] == b:
                    by_g.setdefault(group_of.get(t, t), []).append(t)

            def gsize(g: str) -> float:
                names: Set[str] = set()
                for t in by_g[g]:
                    names.update(graph[t].params_needed)
                return sum(graph.param_size_gb(p) for p in sorted(names))

            gs = sorted(by_g, key=lambda g: (-gsize(g), g))
            dests = sorted(
                (d for d in range(n_dev) if d != b),
                key=lambda d: (
                    tl.node_finish.get(devices[d].node_id, 0.0),
                    devices[d].node_id,
                ),
            )
            return b, gs, by_g, dests

        # deterministic sweep cursors.  The heal cursor re-arms on every
        # accept (the critical path genuinely changes); the slice cursor
        # keeps its don't-look state unless the bottleneck signature
        # (device + its group set) changed — without that, every µs-scale
        # heal accept would trigger a futile full re-scan of a slice
        # neighborhood that was just proven improvement-free.
        heal_i = 0
        slice_i = 0
        b_idx, slice_gs, slice_members, slice_dests = slice_scan()
        slice_sig = (b_idx, tuple(slice_gs))

        def rearm() -> None:
            nonlocal heal_i, slice_i, slice_sig
            nonlocal b_idx, slice_gs, slice_members, slice_dests
            heal_i = 0
            b_idx, slice_gs, slice_members, slice_dests = slice_scan()
            sig = (b_idx, tuple(slice_gs))
            if sig != slice_sig:
                slice_sig = sig
                slice_i = 0

        while evals < self.budget and attempts < max_attempts:
            # dls-lint: allow(DET001) opt-in time_budget_s cutoff (see above)
            if deadline is not None and time.perf_counter() >= deadline:
                break
            attempts += 1

            # -- propose ---------------------------------------------------
            # Sweeps first: the two structured neighborhoods are walked
            # to exhaustion (first-improvement descent, cursors reset on
            # every accept) before any randomized proposal runs — an
            # annealed uphill accept mid-sweep would reset the cursors
            # and rob the pass of its coverage guarantee.  Once the
            # incumbent is locally optimal for both sweeps, the annealed
            # random stream (rebalances / blind moves / swaps) takes
            # over, and any accept there re-arms the sweeps.
            r = rng.random()
            moves: List[Tuple[str, int, int]] = []
            sweep = False  # deterministic-sweep candidates accept greedily
            if (
                slice_gs and slice_dests
                and slice_i < 2 * len(slice_gs) * len(slice_dests)
            ):
                # move one (group x bottleneck-device) slice wholesale —
                # the unit a param load is charged per, so this rebalances
                # whole weight-sets the way refine does, but per-slice —
                # or swap it with the lightest slice on the destination
                # (the swap form carries most of the improvement on
                # balanced seeds: a bare move just shifts the bottleneck,
                # an exchange keeps both unions level).  Group-major
                # sweep, heaviest slice first, move-then-swap per
                # destination (lightest first).  Runs before the heal
                # sweep: rebalancing moves the makespan in ~10µs steps,
                # hop healing in ~1µs steps, so the big-step neighborhood
                # must drain first.
                sweep = True
                pair, var = divmod(slice_i, 2)
                gi, di = divmod(pair, len(slice_dests))
                slice_i += 1
                g_name = slice_gs[gi]
                src, dst = b_idx, slice_dests[di]
                moves = [(t, src, dst) for t in slice_members[g_name]]
                if var == 1:
                    # swap: pull the lightest group-slice on dst back
                    there: Dict[str, List[str]] = {}
                    for t in tids:
                        if cur[t] == dst:
                            there.setdefault(
                                group_of.get(t, t), []
                            ).append(t)

                    def usize(gname: str) -> float:
                        names: Set[str] = set()
                        for t in there[gname]:
                            names.update(graph[t].params_needed)
                        return sum(
                            graph.param_size_gb(p) for p in sorted(names)
                        )

                    if not there:
                        continue
                    g2 = min(sorted(there), key=usize)
                    if g2 == g_name:
                        continue
                    moves += [(t, dst, src) for t in there[g2]]
            elif hops and heal_i < 4 * len(hops):
                # collapse a binding cross-device hop on the critical
                # path: co-locate its endpoints.  Four variants per hop —
                # move either endpoint, alone or with its whole
                # group-slice (the slice form keeps a weight-set's tasks
                # together; it is the shape of the winning "pull the tail
                # group onto its producer's device" moves).  Hop-major
                # sweep: all single moves across hops first, then slices.
                sweep = True
                k, var = heal_i % len(hops), heal_i // len(hops)
                heal_i += 1
                prod, cons = hops[k]
                if var % 2 == 0:
                    tid, dst = cons, cur[prod]  # push consumer to producer
                else:
                    tid, dst = prod, cur[cons]  # pull producer to consumer
                src = cur[tid]
                if dst == src:
                    continue
                if var >= 2:
                    g_name = group_of.get(tid, tid)
                    moves = [
                        (t, src, dst) for t in tids
                        if cur[t] == src and group_of.get(t, t) == g_name
                    ]
                else:
                    moves = [(tid, src, dst)]
            elif r < self.P_REBALANCE:
                # param-free rebalance off the bottleneck device
                on_b = [t for t in tids if cur[t] == b_idx]
                if not on_b:
                    continue
                tid = on_b[rng.randrange(len(on_b))]
                src = b_idx
                dst = cheap_dst(tid, src)
                if dst == src:
                    continue
                moves = [(tid, src, dst)]
                if rng.random() < self.P_SWAP:
                    there = [t for t in tids if cur[t] == dst]
                    if there:
                        t2 = there[rng.randrange(len(there))]
                        moves.append((t2, dst, src))
            else:
                # blind exploration: any task, biased-light destination
                tid = tids[rng.randrange(len(tids))]
                src = cur[tid]
                dst = pick_dst(src)
                if dst == src:
                    continue
                moves = [(tid, src, dst)]
                if rng.random() < self.P_SWAP:
                    there = [t for t in tids if cur[t] == dst]
                    if there:
                        t2 = there[rng.randrange(len(there))]
                        moves.append((t2, dst, src))

            # -- memory feasibility (cheap, incremental) ------------------
            for t, s, d in moves:
                loads.remove(t, s)
                loads.add(t, d)
            feasible = all(loads.fits(d) for _, _, d in moves)
            if not feasible:
                for t, s, d in reversed(moves):
                    loads.remove(t, d)
                    loads.add(t, s)
                stats["infeasible_mem"] += 1
                continue

            cand = dict(cur)
            for t, _, d in moves:
                cand[t] = d

            # -- static pre-filter: reject before paying for the replay ---
            if not flt.ok(cand):
                for t, s, d in reversed(moves):
                    loads.remove(t, d)
                    loads.add(t, s)
                continue

            # -- score + SA acceptance ------------------------------------
            cand_tl = evaluate(cand)
            evals += 1
            m = cand_tl.makespan
            delta = m - cur_m
            # sweep candidates accept strictly downhill only: an uphill
            # drift mid-sweep would reset the cursors and rob the
            # systematic pass of its coverage guarantee.  The random
            # stream anneals as usual.
            if delta < -self.tol or (
                not sweep
                and temp > _EPS
                and rng.random() < math.exp(-delta / temp)
            ):
                cur, cur_m, tl = cand, m, cand_tl
                flt.sync(cand, verify=self.verify_filter)
                crit, hops = self._critical_tasks(
                    graph, devices, cur, tl, slices
                )
                rearm()
                stats["accepted"] += 1
                if m < best_m - self.tol:
                    best, best_m = dict(cand), m
                    stall = 0
                else:
                    stall += 1
            else:
                for t, s, d in reversed(moves):
                    loads.remove(t, d)
                    loads.add(t, s)
                stall += 1
            temp *= alpha

            # -- kick: perturb off the best incumbent, re-warm ------------
            if stall >= stall_limit and evals < self.budget:
                stats["kicks"] += 1
                stall = 0
                # restore incumbent (and its memory/analyzer mirrors)
                for t, d in best.items():
                    if cur[t] != d:
                        loads.remove(t, cur[t])
                        loads.add(t, d)
                cur = dict(best)
                for _ in range(3):
                    t = tids[rng.randrange(len(tids))]
                    d = rng.randrange(n_dev)
                    if d == cur[t]:
                        continue
                    loads.remove(t, cur[t])
                    loads.add(t, d)
                    if loads.fits(d):
                        cur[t] = d
                    else:
                        loads.add(t, cur[t])
                        loads.remove(t, d)
                if flt.ok(cur):
                    flt.sync(cur, verify=self.verify_filter)
                else:
                    # perturbation statically invalid: fall back to best
                    for t, d in best.items():
                        if cur[t] != d:
                            loads.remove(t, cur[t])
                            loads.add(t, d)
                    cur = dict(best)
                    flt.sync(cur, verify=self.verify_filter)
                tl = evaluate(cur)
                evals += 1
                cur_m = tl.makespan
                crit, hops = self._critical_tasks(
                    graph, devices, cur, tl, slices
                )
                # a kick teleports the incumbent: all don't-look state is
                # invalid, so both sweeps restart from scratch
                rearm()
                slice_sig = (b_idx, tuple(slice_gs))
                slice_i = 0
                temp = t0 * 0.5

        stats["evals"] = evals
        stats["filtered"] = flt.rejected
        stats["best_makespan"] = best_m
        return best

    # -- critical path on the simulated timeline ---------------------------

    def _critical_tasks(
        self, graph, devices, assign: Dict[str, int],
        tl: PlacementTimeline, slices: Dict[str, int],
    ) -> Tuple[List[str], List[Tuple[str, str]]]:
        """Walk the event-sim timeline backward from the last finish,
        following the latest-release predecessor at each step (incoming
        dependency arrival incl. transfer vs. the prior task's finish on
        the same device) — the same binding rule ``obs/attribution.py``
        applies to measured traces.

        Returns ``(path, hops)``: the critical tasks, and every
        **cross-device dependency edge that bound a start time** as
        ``(producer, consumer)`` pairs.  Each hop pays the link's transfer
        latency on the critical path, so collapsing one (co-locating its
        endpoints) is the highest-yield move the search can propose — on
        load-balanced placements the residual makespan above the param
        floor is almost entirely these hops plus node serialization."""
        if not tl.finish:
            return [], []
        topo_pos = {t: i for i, t in enumerate(graph.topo_order)}
        node_of = {tid: devices[d].node_id for tid, d in assign.items()}
        by_node: Dict[str, List[str]] = {}
        for tid in sorted(
            tl.start_at, key=lambda t: (tl.start_at[t], topo_pos[t])
        ):
            by_node.setdefault(node_of[tid], []).append(tid)
        pos_on_node = {
            t: i for lst in by_node.values() for i, t in enumerate(lst)
        }
        cur = max(tl.finish, key=lambda t: (tl.finish[t], topo_pos[t]))
        path: List[str] = []
        hops: List[Tuple[str, str]] = []
        seen: Set[str] = set()
        while cur is not None and cur not in seen:
            seen.add(cur)
            path.append(cur)
            start = tl.start_at.get(cur, 0.0)
            if start <= _EPS:
                break
            nid = node_of[cur]
            best_rel, best = -1.0, None
            for dep in graph[cur].dependencies:
                if dep not in tl.finish:
                    continue
                rel = tl.finish[dep]
                if node_of[dep] != nid:
                    rel += self.link.transfer_time(
                        graph.output_gb(dep),
                        src_slice=slices.get(node_of[dep]),
                        dst_slice=slices.get(nid),
                    )
                if rel > best_rel:
                    best_rel, best = rel, dep
            i = pos_on_node[cur]
            if i > 0:
                prev = by_node[nid][i - 1]
                if tl.finish[prev] >= best_rel:
                    best_rel, best = tl.finish[prev], prev
            if best is not None and node_of[best] != nid:
                hops.append((best, cur))
            cur = best
        return path, hops


__all__ = ["SearchScheduler", "placement_digest"]
