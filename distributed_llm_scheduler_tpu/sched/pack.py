"""Group-pack policy: balanced, locality-first group packing.

Born from the measured-TPU bench regime (host link ~1.5 GB/s through the
tunnel): with parameter loads dominating, makespan floors at the heaviest
device's param bytes, and *contiguity* — the pipeline policy's defining
constraint — stops paying for itself because ICI transfers are two orders
of magnitude cheaper than host loads.  This policy drops contiguity and
solves the remaining problem directly:

1. bucket tasks by ``group`` (one weight-set per group, exactly the unit
   the reference's param-cache model revolves around — reference
   ``schedulers.py:63-76`` charges per-param load once per node);
2. pack groups onto devices, largest parameter footprint first, each onto
   the device minimizing the resulting param-union load time — classic
   LPT bin balancing with union-aware sizes, so weight-tied groups
   gravitate to the device already holding their shared table;
3. order execution with the dependency-aware event simulation
   (:mod:`.eventsim`), which recovers 1F1B-style interleaving from the
   DAG structure.

On the flagship bench graph this replays at 21.6 ms vs greedy's 23.3 ms
and pipeline's 23.3 ms under the measured link (load spread 26-31 MB/core
vs a 29 MB perfect split).  In compute-bound regimes it degrades toward
plain load balancing — the evaluator sweep keeps all policies comparable.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set

from ..backends.sim import LinkModel
from .base import BaseScheduler, SchedulerRun
from .eventsim import dependency_aware_order
from .pipeline import _group_stats


class GroupPackScheduler(BaseScheduler):
    """Non-contiguous balanced group packing (LPT over param-union loads)."""

    name = "pack"

    def __init__(self, link: Optional[LinkModel] = None):
        self.link = link or LinkModel()

    def plan(self, graph, devices) -> Dict[str, int]:
        """LPT group packing: group name -> device index (unplaceable
        groups absent).  The refinement policy (:mod:`.refine`) reuses this
        as its search seed."""
        n_dev = len(devices)
        groups, compute, activ, gparams = _group_stats(graph)

        def union_gb(names: Set[str]) -> float:
            # sorted-name accumulation: deterministic and native-parity-safe
            return sum(graph.param_size_gb(p) for p in sorted(names))

        dev_params: List[Set[str]] = [set() for _ in range(n_dev)]
        dev_act = [0.0] * n_dev
        placed: Dict[str, int] = {}
        # largest parameter footprint first (LPT), ties by group order
        order = sorted(
            range(len(groups)), key=lambda i: (-union_gb(gparams[i]), i)
        )
        for gi in order:
            best_d, best_load = None, None
            for d in range(n_dev):
                lg = union_gb(dev_params[d] | gparams[gi])
                if (
                    lg + max(dev_act[d], activ[gi])
                    > devices[d].total_memory + 1e-9
                ):
                    continue
                if best_load is None or lg < best_load:
                    best_d, best_load = d, lg
            if best_d is None:
                continue  # group fits nowhere: its tasks fail below
            placed[groups[gi]] = best_d
            dev_params[best_d] |= gparams[gi]
            dev_act[best_d] = max(dev_act[best_d], activ[gi])
        return placed

    def run_policy(self, run: SchedulerRun) -> None:
        self.commit(run, self.plan(run.graph, run.cluster.devices))

    def commit(self, run: SchedulerRun, placed: Dict[str, int]) -> None:
        """Assign tasks per the group placement, then order execution with
        the dependency-aware event simulation.

        Graceful degradation (VERDICT r4 next #2): a task whose group fit
        on no device whole — its param union exceeds every budget, the
        config-#5 pressure cliff — or whose planned device can no longer
        hold it is spilled through :meth:`spill_pick` instead of failed,
        so group packing degrades toward greedy per-task placement rather
        than zeroing out.  Completion-under-constraint is the reference's
        headline metric (reference ``simulation.py:418-563``)."""
        graph, devices = run.graph, run.cluster.devices
        for tid in graph.topo_order:
            task = graph[tid]
            if tid not in run.pending:
                continue
            if any(d in run.failed for d in task.dependencies):
                self.fail(run, task)
                continue
            # `placed` may be keyed by group (pack/refine plans) or by
            # task id (the search tier's task-level placements); a task
            # key always wins so search can split groups across devices
            d = placed.get(tid, placed.get(task.group or tid))
            if d is not None and self.can_fit(run, task, devices[d]):
                self.assign(run, task, devices[d])
                continue
            node = self.spill_pick(run, task, devices)
            if node is not None:
                self.assign(run, task, node)
            else:
                self.fail(run, task)

        # dependency-aware execution order (same post-pass as pipeline)
        placement = {
            tid: run.graph[tid].assigned_node for tid in run.assignment_order
        }
        speeds = {d.node_id: d.compute_speed for d in run.cluster}
        exec_order = dependency_aware_order(
            run.graph, placement, speeds, self.link,
            slices=run.cluster.slice_ids(),
        )
        run.assignment_order[:] = exec_order
        pos = {tid: i for i, tid in enumerate(exec_order)}
        for nid, tids in run.per_node.items():
            tids.sort(key=lambda t: pos[t])

    def spill_pick(self, run: SchedulerRun, task, devices):
        """Singleton fallback for a task the group plan could not place:
        the device needing the fewest new param bytes that can fit it
        (locality keeps total load bounded under pressure), ties to the
        lower device index.  Deterministic — strict `<` improvement over
        an index-ascending scan — for native-engine parity."""
        best, best_req = None, None
        for node in devices:
            req = self.memory_requirement(run, task, node)
            if req > node.available_memory + 1e-9:
                continue
            if best_req is None or req < best_req:
                best, best_req = node, req
        return best
