"""Cluster model: memory-limited accelerator cores.

Capability parity with the reference's ``Node`` (reference
``schedulers.py:19-29``): each device has a total memory budget, an available
counter, a compute-speed multiplier, a set of resident ("cached") parameters,
and an MRU recency deque.  TPU-first differences:

* a device can be bound to a real ``jax.Device`` (one TPU core of a mesh);
  its memory budget then defaults to the core's HBM capacity, and placement
  decisions made against this model are executed for real by the device
  backend.
* parameter sizes are real bytes (via the owning :class:`TaskGraph`), not a
  0.5 GB constant — the constant remains only as the default for synthetic
  workloads.
* heterogeneous ``compute_speed`` does not exist on a TPU slice (all cores
  are identical); we keep it for the simulated backend and parity tests, and
  reframe heterogeneity on real hardware as per-core HBM budgets.
"""

from __future__ import annotations

import random
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Set


@dataclass
class DeviceState:
    """One schedulable core: memory budget + parameter cache.

    ``jax_device`` is optionally a live ``jax.Device``; the scheduler layer
    never touches it, only the execution backend does.  Param *recency* is
    tracked by the MRU policy itself under its logical clock (the reference
    also keeps a per-node deque, ``schedulers.py:28``, but its scheduler
    reads its own usage dicts — we keep only the read path).

    ``slice_id`` is the device's TPU slice (pod) membership: transfers
    between cores of the same slice ride ICI; transfers between slices ride
    the much slower DCN (:class:`~..backends.sim.TieredLinkModel`).  The
    reference has no notion of network topology at all.
    """

    node_id: str
    total_memory: float  # GB
    compute_speed: float = 1.0
    jax_device: Optional[Any] = None
    slice_id: int = 0

    available_memory: float = field(init=False)
    cached_params: Set[str] = field(default_factory=set)
    running_tasks: List[str] = field(default_factory=list)
    completed_tasks: List[str] = field(default_factory=list)
    # reference parity: per-node MRU recency window, written on every
    # assignment (reference schedulers.py:29,99 — the reference never reads
    # it back, and neither do our policies, which track recency under the
    # MRU logical clock; the state exists for inspection parity)
    last_used_params: deque = field(
        default_factory=lambda: deque(maxlen=10)
    )

    def __post_init__(self) -> None:
        self.available_memory = self.total_memory

    def reset(self) -> None:
        self.available_memory = self.total_memory
        self.cached_params.clear()
        self.running_tasks.clear()
        self.completed_tasks.clear()
        self.last_used_params.clear()

    @property
    def used_memory(self) -> float:
        return self.total_memory - self.available_memory

    def __repr__(self) -> str:
        return (
            f"DeviceState({self.node_id!r}, {self.available_memory:.2f}/"
            f"{self.total_memory:.2f}GB free, speed={self.compute_speed}, "
            f"{len(self.cached_params)} params cached)"
        )


class Cluster:
    """An ordered collection of :class:`DeviceState`.

    Constructors cover the reference's provisioning profiles (reference
    ``simulation.py:161-190`` and ``test_gpt2.py:278-283``) plus a
    TPU-backed constructor that derives budgets from live device HBM.
    """

    def __init__(self, devices: Sequence[DeviceState]):
        if not devices:
            raise ValueError("cluster needs at least one device")
        ids = [d.node_id for d in devices]
        if len(set(ids)) != len(ids):
            raise ValueError(f"duplicate device ids: {ids}")
        self.devices: List[DeviceState] = list(devices)
        self._by_id: Dict[str, DeviceState] = {d.node_id: d for d in devices}

    def __len__(self) -> int:
        return len(self.devices)

    def __iter__(self):
        return iter(self.devices)

    def __getitem__(self, node_id: str) -> DeviceState:
        return self._by_id[node_id]

    def __contains__(self, node_id: str) -> bool:
        return node_id in self._by_id

    def ids(self) -> List[str]:
        return [d.node_id for d in self.devices]

    def total_memory(self) -> float:
        return sum(d.total_memory for d in self.devices)

    def reset(self) -> None:
        for d in self.devices:
            d.reset()

    # -- provisioning profiles --------------------------------------------
    @classmethod
    def uniform(cls, n: int, memory_gb: float, speed: float = 1.0,
                prefix: str = "core") -> "Cluster":
        return cls([
            DeviceState(f"{prefix}_{i}", memory_gb, speed) for i in range(n)
        ])

    @classmethod
    def heterogeneous(cls, total_memory: float, num_nodes: int,
                      rng: Optional[random.Random] = None) -> "Cluster":
        """Reference memory-regime provisioning profiles.

        2 nodes: 60/40 split, speeds 1.2/1.0; 4 nodes: 35/25/25/15, speeds
        1.2/1.0/1.0/0.8; otherwise equal split with speeds drawn uniformly
        from 0.7-1.3 (reference ``simulation.py:161-190``), seedable here
        (the reference draws unseeded, so its sweeps aren't reproducible).
        """
        rng = rng or random.Random(0)
        if num_nodes == 2:
            fracs, speeds = [0.60, 0.40], [1.2, 1.0]
        elif num_nodes == 4:
            fracs, speeds = [0.35, 0.25, 0.25, 0.15], [1.2, 1.0, 1.0, 0.8]
        else:
            fracs = [1.0 / num_nodes] * num_nodes
            speeds = [rng.uniform(0.7, 1.3) for _ in range(num_nodes)]
        return cls([
            DeviceState(f"node_{i}", total_memory * f, s)
            for i, (f, s) in enumerate(zip(fracs, speeds))
        ])

    @classmethod
    def multislice(cls, n_slices: int, cores_per_slice: int,
                   memory_gb: float, speed: float = 1.0,
                   prefix: str = "core") -> "Cluster":
        """Multi-slice TPU topology (BASELINE config #3: 2 x v5e-8 = 16
        cores, DCN between slices).  Devices are ordered slice-by-slice, so
        contiguous pipeline stages cross DCN only at slice boundaries."""
        return cls([
            DeviceState(
                f"{prefix}_{s}_{i}", memory_gb, speed, slice_id=s
            )
            for s in range(n_slices)
            for i in range(cores_per_slice)
        ])

    def without(self, *node_ids: str) -> "Cluster":
        """A new cluster of fresh DeviceStates minus ``node_ids`` — the
        survivor set after failures (elastic recovery).  Copies every
        identity field (incl. jax_device binding and slice topology) so
        callers can't drift by hand-rebuilding DeviceStates."""
        dead = set(node_ids)
        return Cluster([
            DeviceState(
                d.node_id, d.total_memory, d.compute_speed,
                jax_device=d.jax_device, slice_id=d.slice_id,
            )
            for d in self.devices if d.node_id not in dead
        ])

    def slice_ids(self) -> Dict[str, int]:
        """node_id -> slice_id (for topology-aware cost call sites)."""
        return {d.node_id: d.slice_id for d in self.devices}

    @classmethod
    def laptops(cls) -> "Cluster":
        """The reference's 4-laptop fleet (reference test_gpt2.py:278-283)."""
        profile = [("laptop_0", 8.0, 1.0), ("laptop_1", 8.0, 1.2),
                   ("laptop_2", 6.0, 0.8), ("laptop_3", 6.0, 0.9)]
        return cls([DeviceState(n, m, s) for n, m, s in profile])

    @classmethod
    def from_jax_devices(cls, devices: Optional[Sequence[Any]] = None,
                         hbm_cap_gb: Optional[float] = None) -> "Cluster":
        """Build from live JAX devices (one DeviceState per core).

        HBM budget per core comes from ``memory_stats()`` when the platform
        reports it (TPU does), else ``hbm_cap_gb``, else a conservative
        default.  Cores are identical, so ``compute_speed`` is 1.0; use
        ``hbm_cap_gb`` to emulate constrained memory regimes on real
        hardware (the TPU analog of the reference's regime sweep).
        """
        import jax

        devices = list(devices if devices is not None else jax.devices())
        out = []
        for i, dev in enumerate(devices):
            cap = hbm_cap_gb
            if cap is None:
                try:
                    stats = dev.memory_stats() or {}
                    limit = stats.get("bytes_limit")
                    cap = limit / 1024**3 if limit else 16.0
                except Exception:
                    cap = 16.0
            out.append(DeviceState(
                f"core_{i}", cap, 1.0, jax_device=dev,
                slice_id=getattr(dev, "slice_index", None) or 0,
            ))
        return cls(out)

    def __repr__(self) -> str:
        return (
            f"Cluster({len(self.devices)} devices, "
            f"{self.total_memory():.1f}GB total)"
        )


def estimate_cluster_memory_needed(graph) -> float:
    """Lower-bound cluster memory for a graph: the reference's estimator.

    max single-task activation footprint + per-param cache cost over unique
    params (reference ``simulation.py:194-214``), generalized to real param
    sizes.  Used to size memory regimes.
    """
    return graph.max_task_memory() + graph.total_param_gb()
