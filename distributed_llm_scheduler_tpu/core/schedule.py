"""Schedule data structures.

The reference returns a bare ``Dict[node_id, List[task_id]]`` whose list
order *is* the execution order (reference ``schedulers.py:133-135``), plus
side-band state on the scheduler (completed/failed sets).  We make that an
explicit :class:`Schedule` object carrying:

* the ordered per-node task lists (reference-compatible view),
* the global assignment order (needed for faithful cache replay),
* completed/failed task sets,
* optionally, per-task timestamps filled in by a backend (simulated or
  measured), from which Gantt charts and makespan derive.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set


@dataclass
class TaskTiming:
    """Start/finish of one task on one node, seconds from schedule start."""

    task_id: str
    node_id: str
    start: float
    finish: float

    @property
    def duration(self) -> float:
        return self.finish - self.start


@dataclass
class Schedule:
    """Output of a scheduling policy over (graph, cluster)."""

    policy: str
    per_node: Dict[str, List[str]] = field(default_factory=dict)
    assignment_order: List[str] = field(default_factory=list)
    completed: Set[str] = field(default_factory=set)
    failed: Set[str] = field(default_factory=set)
    # host-side wall seconds spent inside schedule() — the reference's
    # ``execution_time`` metric (reference simulation.py:327-333)
    scheduling_wall_s: float = 0.0
    # filled by a backend
    timings: Dict[str, TaskTiming] = field(default_factory=dict)

    def node_of(self, task_id: str) -> Optional[str]:
        for node_id, tasks in self.per_node.items():
            if task_id in tasks:
                return node_id
        return None

    @property
    def placement(self) -> Dict[str, str]:
        """task_id -> node_id for all placed tasks."""
        out: Dict[str, str] = {}
        for node_id, tasks in self.per_node.items():
            for tid in tasks:
                out[tid] = node_id
        return out

    def signature(self) -> tuple:
        """Hashable identity of the scheduling DECISION: policy, per-node
        ordered task lists, and global assignment order — everything a
        dispatch plan is a pure function of.  Two schedules with equal
        signatures must produce identical dispatch plans
        (:mod:`..backends.dispatch_plan`); mutable backend-filled state
        (timings) and bookkeeping (completed/failed, wall time) are
        deliberately excluded."""
        return (
            self.policy,
            tuple((n, tuple(ts)) for n, ts in sorted(self.per_node.items())),
            tuple(self.assignment_order),
        )

    def completion_rate(self, total_tasks: int) -> float:
        return len(self.completed) / total_tasks if total_tasks else 0.0

    @property
    def makespan(self) -> float:
        """Max finish time over timed tasks (0 if no backend ran yet)."""
        if not self.timings:
            return 0.0
        return max(t.finish for t in self.timings.values())

    def summary(self) -> Dict[str, object]:
        return {
            "policy": self.policy,
            "completed": len(self.completed),
            "failed": len(self.failed),
            "per_node_counts": {n: len(ts) for n, ts in self.per_node.items()},
            "scheduling_wall_s": self.scheduling_wall_s,
            "makespan": self.makespan,
        }
