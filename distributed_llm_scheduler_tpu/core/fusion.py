"""Task fusion: merge linear chains into composite tasks.

The reference's task granularity (8 tasks per transformer layer, reference
``test_gpt2.py:63-147``) is right for *placement* but wasteful for
*dispatch*: every task costs a host-side dispatch (~10-100 µs) plus the
replay's per-edge latency floor, and a LayerNorm task finishes in single-
digit µs.  SURVEY.md §7 ranks this the #1 hard part of the rebuild: fuse
trivial ops into their neighbors so the dispatch count drops without
changing what the scheduler can decide.

:func:`fuse_linear_chains` rewrites a graph by collapsing maximal linear
chains — runs ``a → b → …`` where each link is the only dependent of its
predecessor and the only dependency of its successor, and every member
shares the same ``group`` — into one composite task:

* the fused task keeps the **last** member's id, so downstream dependency
  lists (and any code holding task ids of chain exits) are untouched;
* its ``fn`` composes the member fns with namespaced parameter aliases
  (``t0_…, t1_…``), and composite fns are cached per member-fn tuple so
  structurally identical chains (every layer's ln2→ffn run) share ONE fn
  object and jit compiles each fused shape once;
* compute_time/flops sum; params/bytes union; activation footprint is the
  max member output (intermediates live transiently inside the fused fn).

Placement granularity is preserved where it matters: chains never span
groups, so pipeline stages and parked shard groups see the same group
structure, just fewer tasks inside each.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

from .graph import Task, TaskGraph

def _make_fused_fn(member_fns: List[Callable[..., Any]],
                   member_locals: List[List[str]],
                   cache: Dict[Tuple, Callable[..., Any]]) -> Callable[..., Any]:
    """Compose a linear chain of task fns into one fn.

    The fused fn reads params by namespaced local names (``t{i}_{local}``);
    member 0 receives the external inputs, each later member receives its
    predecessor's output (linear chain contract).  ``cache`` is scoped to
    one :func:`fuse_linear_chains` call — member fns are per-build closures,
    so within-graph sharing (every layer's identical chain → one fused fn →
    one jit compile per shape) is all the sharing that exists; a global
    cache would only pin dead graphs' closures.  Fns are identity-hashed;
    locals are part of the key because the same fn tuple can appear with
    different param namings in alias-free graphs.
    """
    key = (tuple(member_fns), tuple(tuple(l) for l in member_locals))
    cached = cache.get(key)
    if cached is not None:
        return cached

    def fused(p, *ext_inputs):
        sub = {loc: p[f"t0_{loc}"] for loc in member_locals[0]}
        x = member_fns[0](sub, *ext_inputs)
        for i in range(1, len(member_fns)):
            sub = {loc: p[f"t{i}_{loc}"] for loc in member_locals[i]}
            x = member_fns[i](sub, x)
        return x

    # a chain of batch-axis-0-polymorphic ops is itself batch-axis-0
    # polymorphic: the composite inherits rebatch eligibility
    from .graph import is_batch0, mark_batch0

    if all(is_batch0(f) for f in member_fns):
        mark_batch0(fused)
    cache[key] = fused
    return fused


def _fuse_chain(members: List[Task],
                fn_cache: Dict[Tuple, Callable[..., Any]]) -> Task:
    """Build the composite task for a maximal chain (>= 2 members)."""
    first, last = members[0], members[-1]
    have_fns = all(t.fn is not None for t in members)

    alias: Dict[str, str] = {}
    param_bytes: Dict[str, int] = {}
    params: set = set()
    member_locals: List[List[str]] = []
    for i, t in enumerate(members):
        locals_i = []
        for loc, glob in t.param_items():
            alias[f"t{i}_{loc}"] = glob
            locals_i.append(loc)
            params.add(glob)
        member_locals.append(locals_i)
        param_bytes.update(t.param_bytes)

    fn = (
        _make_fused_fn([t.fn for t in members], member_locals, fn_cache)
        if have_fns else None
    )
    return Task(
        last.task_id,  # keep the exit id: downstream dep lists unchanged
        memory_required=max(t.memory_required for t in members),
        compute_time=sum(t.compute_time for t in members),
        dependencies=list(first.dependencies),
        params_needed=params,
        param_bytes=param_bytes,
        fn=fn,
        arg_tasks=list(first.arg_tasks or first.dependencies),
        param_alias=alias if fn is not None else None,
        out_shape=last.out_shape,
        flops=sum(t.flops or 0.0 for t in members) or None,
        group=last.group,
    )


def fuse_linear_chains(
    graph: TaskGraph,
    min_chain: int = 2,
    max_chain: Optional[int] = None,
) -> TaskGraph:
    """Return a new graph with maximal same-group linear chains fused.

    Args:
      graph: frozen source graph (unchanged).
      min_chain: only fuse runs of at least this many tasks.
      max_chain: optional cap on members per fused task (None = unlimited).

    The result's name gains a ``_fused`` suffix so measured cost-model
    caches never mix fused and unfused timings.
    """
    graph.freeze()

    def can_extend(a: str, b: str) -> bool:
        """b directly follows a in a linear same-group chain.

        ``b`` must actually CONSUME ``a``'s output as its sole fn input:
        the Task contract allows ``arg_tasks`` to differ from
        ``dependencies`` (control-only edges, reordered inputs), and fusing
        such a task would silently feed the predecessor's output into an fn
        that doesn't want it (ADVICE r1).
        """
        ta, tb = graph[a], graph[b]
        return (
            len(graph.dependents(a)) == 1
            and len(tb.dependencies) == 1
            and tb.dependencies[0] == a
            and (tb.arg_tasks is None or tb.arg_tasks == tb.dependencies)
            and ta.group == tb.group
            and (ta.fn is None) == (tb.fn is None)
        )

    chains: List[List[str]] = []
    in_chain: Dict[str, int] = {}
    for tid in graph.topo_order:
        if tid in in_chain:
            continue
        chain = [tid]
        while True:
            if max_chain is not None and len(chain) >= max_chain:
                break
            deps_out = graph.dependents(chain[-1])
            if len(deps_out) == 1 and can_extend(chain[-1], deps_out[0]):
                chain.append(deps_out[0])
            else:
                break
        chains.append(chain)
        for t in chain:
            in_chain[t] = len(chains) - 1

    tasks: List[Task] = []
    fn_cache: Dict[Tuple, Callable[..., Any]] = {}
    for chain in chains:
        if len(chain) >= min_chain:
            tasks.append(_fuse_chain([graph[t] for t in chain], fn_cache))
        else:
            # every member survives unfused (chains can be shorter than
            # min_chain but still hold interior tasks when min_chain > 2)
            for tid in chain:
                src = graph[tid]
                # shallow re-create: the fused graph owns fresh mutable state
                tasks.append(Task(
                    src.task_id,
                    memory_required=src.memory_required,
                    compute_time=src.compute_time,
                    dependencies=list(src.dependencies),
                    params_needed=set(src.params_needed),
                    param_bytes=dict(src.param_bytes),
                    fn=src.fn,
                    arg_tasks=list(src.arg_tasks) if src.arg_tasks else None,
                    param_alias=dict(src.param_alias) if src.param_alias else None,
                    out_shape=src.out_shape,
                    flops=src.flops,
                    group=src.group,
                ))

    # remap any dependency that points at a fused-away (non-exit) member;
    # only members of chains that actually fused are remapped — sub-min
    # chains keep all their tasks and internal edges
    exit_of: Dict[str, str] = {}
    for chain in chains:
        if len(chain) >= min_chain:
            for t in chain:
                exit_of[t] = chain[-1]
    for t in tasks:
        t.dependencies = [exit_of.get(d, d) for d in t.dependencies]
        if t.arg_tasks is not None:
            t.arg_tasks = [exit_of.get(d, d) for d in t.arg_tasks]

    return TaskGraph(tasks, name=f"{graph.name}_fused").freeze()
