"""Core task-graph model.

The unit of work is a :class:`Task`: a named computation with an activation
memory footprint, an (estimated or measured) compute time, a set of
dependencies, and a set of named parameters it needs resident on whichever
device executes it.  A :class:`TaskGraph` is a validated DAG of tasks with the
topological utilities every scheduling policy needs (topo order, DAG depth,
downstream critical-path length).

Capability parity: mirrors the reference's ``Task`` (reference
``schedulers.py:7-17``) but TPU-first:

* parameters carry **real byte sizes** (``param_bytes``) instead of the
  reference's hard-coded 0.5 GB unit (reference ``schedulers.py:70,89``);
  the 0.5 GB unit survives only as the *default* for tasks that don't
  specify sizes, so synthetic workloads reproduce reference behavior.
* a task may own a jittable ``fn`` plus abstract input/output specs so the
  device backend can compile and dispatch it on a TPU core; the scheduler
  layer never looks at ``fn``.
* mutable scheduling state (status, assigned node) lives on the task, as in
  the reference, but graph structure is immutable after ``freeze()``.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterable, List, Optional, Set, Tuple

# The reference models every parameter as exactly 0.5 GB
# (reference schedulers.py:70,89 and simulation.py:202,211).  We keep that
# as the *default* size so synthetic DAGs and parity tests reproduce the
# reference numbers; real model frontends supply true byte sizes.
DEFAULT_PARAM_GB: float = 0.5
GB: int = 1024**3


def mark_batch0(fn):
    """Declare ``fn`` batch-axis-0 polymorphic: for any split of its array
    arguments along axis 0, ``fn(p, concat(xs, 0), ...) ==
    concat([fn(p, x, ...) for x], 0)``.  True of per-token/per-row ops
    (layer norms, matmuls on trailing dims, attention over independent
    batch entries, residual adds) and false of axis-0 reductions or
    axis-0 concats.  The segment re-batching pass
    (:mod:`..backends.rebatch`) only folds sibling tasks whose fns carry
    this marker — an unmarked fn is never batched, so correctness is
    opt-in per op, not guessed."""
    fn._dls_batch0 = True
    return fn


def is_batch0(fn) -> bool:
    return bool(getattr(fn, "_dls_batch0", False))


def mark_concat0(fn):
    """Declare ``fn(p, x1, ..., xn) == concatenate(xs, axis=0)`` (ignoring
    params).  The re-batching pass uses this to skip materializing a
    concat whose inputs are exactly a batched class's members in order —
    the batched value IS the concat, so the op becomes identity instead
    of a slice-and-recopy round-trip of the full output."""
    fn._dls_concat0 = True
    return fn


def is_concat0(fn) -> bool:
    return bool(getattr(fn, "_dls_concat0", False))


def mark_rootslice(fn, family, lo: int, hi: int, make):
    """Declare a ROOT task fn (consumes the shared graph input, no task
    args) to be the static batch-slice ``[lo, hi)`` instance of a slice
    family: ``make(a, b)`` builds the family's fn for any range, and for
    any split point ``a <= b <= c``::

        make(a, c)(p, x) == concat([make(a, b)(p, x), make(b, c)(p, x)], 0)

    True of per-row input transforms (embedding gathers over a batch
    slice); the segment re-batching pass merges sibling roots whose
    slices tile one contiguous range into a single ``make(lo0, hiN)``
    call — the fused forward's full-batch gather, recovered whenever
    placement co-locates the roots.  ``family`` must pin every closure
    variable other than the slice (e.g. the vocab-shard bounds) so only
    true siblings compare equal."""
    fn._dls_rootslice = (family, int(lo), int(hi), make)
    return fn


def rootslice_of(fn):
    """The ``(family, lo, hi, make)`` marker, or None."""
    return getattr(fn, "_dls_rootslice", None)


class TaskStatus(enum.Enum):
    PENDING = "pending"
    ASSIGNED = "assigned"
    RUNNING = "running"
    COMPLETED = "completed"
    FAILED = "failed"


@dataclass
class Task:
    """One schedulable unit of work.

    Args:
      task_id: unique name, e.g. ``"layer_3_attention"``.
      memory_required: activation/workspace footprint in GB while running.
      compute_time: estimated wall seconds on a speed-1.0 device.  Replaced
        by measured compiled timings when a cost model calibration runs.
      dependencies: task_ids that must complete before this task starts.
      params_needed: names of weight tensors that must be resident.
      param_bytes: optional true sizes for (a subset of) ``params_needed``;
        missing entries fall back to ``DEFAULT_PARAM_GB``.
      fn: optional jittable computation ``fn(params_dict, *inputs) -> output``.
      arg_tasks: which dependency outputs feed ``fn``, in order.  Defaults to
        ``dependencies`` order.
      param_alias: optional mapping local->global param names.  When set,
        ``fn`` reads params by *local* name (e.g. ``"g"``) and the backend
        feeds it ``{local: params[global]}``.  This lets structurally
        identical tasks (every layer's ln1) share ONE fn object, so jit
        compiles each op shape once instead of once per layer.
      out_shape: optional ``jax.ShapeDtypeStruct``-like spec of the output.
      out_bytes: optional true output size in bytes (set by the pre-flight
        XLA memory analysis); cost models charge cross-node transfers by
        this when present, falling back to ``memory_required`` (which also
        covers temps and so over-charges transfers).
      flops: optional analytic FLOP count (feeds the cost model).
      group: optional label (e.g. layer index) for fusion/visualization.
    """

    task_id: str
    memory_required: float
    compute_time: float
    dependencies: List[str] = field(default_factory=list)
    params_needed: Set[str] = field(default_factory=set)
    param_bytes: Dict[str, int] = field(default_factory=dict)
    fn: Optional[Callable[..., Any]] = None
    arg_tasks: Optional[List[str]] = None
    param_alias: Optional[Dict[str, str]] = None
    out_shape: Optional[Any] = None
    out_bytes: Optional[int] = None
    flops: Optional[float] = None
    group: Optional[str] = None

    # mutable scheduling state
    status: TaskStatus = TaskStatus.PENDING
    assigned_node: Optional[str] = None

    def __post_init__(self) -> None:
        self.dependencies = list(self.dependencies)
        self.params_needed = set(self.params_needed)

    def param_items(self) -> List[Tuple[str, str]]:
        """(fn-facing local name, global param name) pairs.

        Without an alias the names coincide; with one, backends feed ``fn``
        a dict keyed by local names resolved from global param storage.
        """
        if self.param_alias is not None:
            return list(self.param_alias.items())
        return [(p, p) for p in sorted(self.params_needed)]

    # -- param sizing ------------------------------------------------------
    def param_size_gb(self, param: str) -> float:
        """Size of one named parameter in GB **as declared on this task**
        (0.5 GB default).  Declaration-local: a task using a param another
        task declared sees the default here.  All scheduling/memory
        accounting uses the authoritative graph-wide table instead
        (``TaskGraph.param_size_gb``, fixed at ``freeze()``)."""
        if param in self.param_bytes:
            return self.param_bytes[param] / GB
        return DEFAULT_PARAM_GB

    def total_param_gb(self) -> float:
        """Declaration-local total; see :meth:`param_size_gb`."""
        return sum(self.param_size_gb(p) for p in self.params_needed)

    @property
    def completed(self) -> bool:
        return self.status is TaskStatus.COMPLETED

    @property
    def failed(self) -> bool:
        return self.status is TaskStatus.FAILED

    def reset(self) -> None:
        """Clear scheduling state (graphs are reused across scheduler runs)."""
        self.status = TaskStatus.PENDING
        self.assigned_node = None

    def __repr__(self) -> str:  # concise, used in error messages
        return (
            f"Task({self.task_id!r}, mem={self.memory_required:.3f}GB, "
            f"t={self.compute_time:.4f}s, deps={len(self.dependencies)}, "
            f"params={len(self.params_needed)})"
        )


class GraphValidationError(ValueError):
    pass


class TaskGraph:
    """A validated DAG of tasks plus the topological utilities schedulers use.

    Unlike the reference — where the "graph" is an implicit dict inside the
    scheduler (reference ``schedulers.py:34-48``) — the graph is a first-class
    object: built once, validated (missing deps, duplicate ids, cycles),
    frozen, and shared read-only by schedulers, backends, and visualization.
    Per-run mutable state lives in scheduler-owned structures, not here, so a
    graph can be scheduled many times without deep copies (the reference must
    deep-copy tasks per trial, reference ``simulation.py:309-317``).
    """

    def __init__(self, tasks: Iterable[Task] = (), name: str = "dag"):
        self.name = name
        self._tasks: Dict[str, Task] = {}
        self._dependents: Dict[str, List[str]] = {}
        self._param_gb: Dict[str, float] = {}
        self._topo: Optional[List[str]] = None
        for t in tasks:
            self.add_task(t)

    # -- construction ------------------------------------------------------
    def add_task(self, task: Task) -> None:
        if task.task_id in self._tasks:
            raise GraphValidationError(f"duplicate task id {task.task_id!r}")
        self._tasks[task.task_id] = task
        self._topo = None  # invalidate

    def freeze(self) -> "TaskGraph":
        """Validate, compute topo order, and fix the param size table.

        The size table is the single source of truth for every byte of
        scheduler memory accounting: a param's size is its ``param_bytes``
        entry (first task to declare one wins; conflicting declarations
        raise) or ``DEFAULT_PARAM_GB``.  Idempotent.
        """
        self._validate()
        self._dependents = {tid: [] for tid in self._tasks}
        for t in self._tasks.values():
            for d in t.dependencies:
                self._dependents[d].append(t.task_id)
        self._topo = self._toposort()
        self._param_gb = {}
        for t in self._tasks.values():
            for p in t.params_needed:
                declared = t.param_bytes.get(p)
                size = declared / GB if declared is not None else None
                prev = self._param_gb.get(p)
                if prev is None:
                    if size is not None:
                        self._param_gb[p] = size
                elif size is not None and abs(prev - size) > 1e-12:
                    raise GraphValidationError(
                        f"param {p!r} declared with conflicting sizes "
                        f"({prev:.6f} vs {size:.6f} GB)"
                    )
        return self

    def _validate(self) -> None:
        for t in self._tasks.values():
            for d in t.dependencies:
                if d not in self._tasks:
                    raise GraphValidationError(
                        f"task {t.task_id!r} depends on unknown task {d!r}"
                    )
            if t.memory_required < 0:
                raise GraphValidationError(
                    f"task {t.task_id!r} has negative memory"
                )

    def _toposort(self) -> List[str]:
        """Kahn's algorithm over self._dependents; stable w.r.t. insertion
        order for determinism."""
        indeg = {tid: len(t.dependencies) for tid, t in self._tasks.items()}
        ready = [tid for tid in self._tasks if indeg[tid] == 0]
        order: List[str] = []
        i = 0
        while i < len(ready):
            tid = ready[i]
            i += 1
            order.append(tid)
            for dep in self._dependents[tid]:
                indeg[dep] -= 1
                if indeg[dep] == 0:
                    ready.append(dep)
        if len(order) != len(self._tasks):
            cyclic = sorted(set(self._tasks) - set(order))
            raise GraphValidationError(f"cycle involving tasks {cyclic[:5]}")
        return order

    # -- access ------------------------------------------------------------
    def __len__(self) -> int:
        return len(self._tasks)

    def __contains__(self, tid: str) -> bool:
        return tid in self._tasks

    def __iter__(self):
        return iter(self.tasks())

    def __getitem__(self, tid: str) -> Task:
        return self._tasks[tid]

    def get(self, tid: str) -> Optional[Task]:
        return self._tasks.get(tid)

    def task_ids(self) -> List[str]:
        return list(self._tasks)

    def tasks(self) -> List[Task]:
        return list(self._tasks.values())

    @property
    def topo_order(self) -> List[str]:
        if self._topo is None:
            self.freeze()
        return list(self._topo)

    def dependents(self, tid: str) -> List[str]:
        if self._topo is None:
            self.freeze()
        return list(self._dependents[tid])

    def roots(self) -> List[str]:
        return [tid for tid, t in self._tasks.items() if not t.dependencies]

    def leaves(self) -> List[str]:
        if self._topo is None:
            self.freeze()
        return [tid for tid in self._tasks if not self._dependents[tid]]

    def reset(self) -> None:
        for t in self._tasks.values():
            t.reset()

    # -- analysis (mirrors reference analyze_dag, test_gpt2.py:218-243) ----
    def unique_params(self) -> Set[str]:
        out: Set[str] = set()
        for t in self._tasks.values():
            out |= t.params_needed
        return out

    def param_size_gb(self, param: str) -> float:
        """O(1) lookup in the size table fixed at freeze()."""
        if self._topo is None:
            self.freeze()
        return self._param_gb.get(param, DEFAULT_PARAM_GB)

    def total_param_gb(self) -> float:
        return sum(self.param_size_gb(p) for p in self.unique_params())

    def output_gb(self, tid: str) -> float:
        """Bytes a consumer actually receives from ``tid``: the task's true
        output size when known (pre-flight analysis), else its activation
        footprint (the reference-era proxy, which also counts temps)."""
        t = self._tasks[tid]
        if t.out_bytes is not None:
            return t.out_bytes / GB
        return t.memory_required

    def total_activation_gb(self) -> float:
        return sum(t.memory_required for t in self._tasks.values())

    def total_compute_time(self) -> float:
        return sum(t.compute_time for t in self._tasks.values())

    def max_task_memory(self) -> float:
        return max((t.memory_required for t in self._tasks.values()), default=0.0)

    # -- topological metrics used by policies ------------------------------
    def depths(self) -> Dict[str, int]:
        """Depth from roots: root=0, else 1 + max(dep depth).

        Same quantity DFSScheduler memoizes per-task (reference
        ``schedulers.py:140-152``), computed here in one topo pass.
        """
        depth: Dict[str, int] = {}
        for tid in self.topo_order:
            deps = self._tasks[tid].dependencies
            depth[tid] = 0 if not deps else 1 + max(depth[d] for d in deps)
        return depth

    def critical_path_lengths(self) -> Dict[str, float]:
        """Downstream critical-path length: own time + max over dependents.

        Same quantity CriticalPathScheduler memoizes (reference
        ``schedulers.py:301-321``), one reverse-topo pass.
        """
        cpl: Dict[str, float] = {}
        for tid in reversed(self.topo_order):
            t = self._tasks[tid]
            down = [cpl[d] for d in self._dependents[tid]]
            cpl[tid] = t.compute_time + (max(down) if down else 0.0)
        return cpl

    def critical_path_time(self) -> float:
        """Length of the DAG's critical path in compute seconds (speed 1.0)."""
        cpl = self.critical_path_lengths()
        return max(cpl.values(), default=0.0)

    def summary(self) -> Dict[str, Any]:
        """Headline DAG statistics (parity with reference analyze_dag)."""
        n = len(self._tasks)
        deps = [len(t.dependencies) for t in self._tasks.values()]
        return {
            "name": self.name,
            "num_tasks": n,
            "total_activation_gb": self.total_activation_gb(),
            "max_task_memory_gb": self.max_task_memory(),
            "num_unique_params": len(self.unique_params()),
            "total_param_gb": self.total_param_gb(),
            "sequential_compute_s": self.total_compute_time(),
            "critical_path_s": self.critical_path_time(),
            "max_deps": max(deps, default=0),
            "avg_deps": (sum(deps) / n) if n else 0.0,
        }

    def __repr__(self) -> str:
        return f"TaskGraph({self.name!r}, {len(self._tasks)} tasks)"
