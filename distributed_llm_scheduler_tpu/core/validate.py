"""Independent schedule validation: the framework's "race detector".

The reference is single-threaded by construction, so it has no sanitizer
(SURVEY.md §5.2); the TPU-native analog is an *independent checker pass*
over a placed schedule — written against the :class:`Schedule` contract
only, sharing no code with the policies it checks — that catches the
failure modes a wrong scheduler would smuggle past the backends:

* **dependency order**: backends execute per-node lists in order and the
  replay reads each dependency's finish time in global assignment order; a
  task ordered before one of its dependencies would silently under-wait
  (``SimulatedBackend.execute`` skips deps it hasn't seen) or deadlock a
  real dispatch.  Both the global order and every per-node list must be
  dependency-consistent.
* **placement integrity**: completed tasks placed exactly once, per-node
  lists a partition of the global order, completed/failed disjoint and
  exhaustive over placed work, no task completed while a dependency failed.
* **memory feasibility**: a task whose own activation + parameter
  footprint exceeds its node's capacity can never run there (hard
  violation).  Peak no-eviction residency per node is also replayed; under
  ``strict=True`` exceeding capacity is a violation, otherwise it is
  reported as diagnostics (cache-aware policies like MRU legitimately rely
  on eviction, which the Schedule does not record).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List

from .cluster import Cluster
from .graph import TaskGraph
from .schedule import Schedule


@dataclass
class ValidationReport:
    violations: List[str] = field(default_factory=list)
    # diagnostics: per-node peak resident GB if nothing is ever evicted
    peak_no_evict_gb: Dict[str, float] = field(default_factory=dict)
    requires_eviction: List[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.violations

    def summary(self) -> str:
        if self.ok:
            extra = (
                f" (eviction required on {len(self.requires_eviction)} nodes)"
                if self.requires_eviction else ""
            )
            return f"schedule valid{extra}"
        head = "; ".join(self.violations[:5])
        more = len(self.violations) - 5
        return f"{len(self.violations)} violations: {head}" + (
            f"; +{more} more" if more > 0 else ""
        )


def validate_schedule(
    graph: TaskGraph,
    cluster: Cluster,
    schedule: Schedule,
    strict: bool = False,
) -> ValidationReport:
    """Check a schedule against the graph/cluster it claims to place."""
    rep = ValidationReport()
    v = rep.violations.append
    graph.freeze()

    placed: Dict[str, str] = {}
    for nid, tids in schedule.per_node.items():
        if nid not in cluster:
            v(f"per_node references unknown device {nid!r}")
            continue
        for tid in tids:
            if tid not in graph:
                v(f"{tid!r} on {nid} is not a graph task")
            elif tid in placed:
                v(f"{tid!r} placed on both {placed[tid]} and {nid}")
            else:
                placed[tid] = nid

    # global order: a permutation of placed tasks
    order = schedule.assignment_order
    if sorted(order) != sorted(placed):
        v("assignment_order is not a permutation of the placed tasks")
    pos = {tid: i for i, tid in enumerate(order)}

    # per-node lists must be subsequences of the global order
    for nid, tids in schedule.per_node.items():
        ranks = [pos[t] for t in tids if t in pos]
        if ranks != sorted(ranks):
            v(f"per_node[{nid}] order disagrees with assignment_order")

    # completed/failed partition — and total coverage: a scheduler that
    # silently DROPS tasks (or returns an empty schedule) must not validate
    if schedule.completed & schedule.failed:
        v("completed and failed sets overlap")
    unaccounted = (
        set(graph.task_ids()) - schedule.completed - schedule.failed
    )
    for tid in sorted(unaccounted)[:20]:
        v(f"{tid!r} neither completed nor failed")
    if len(unaccounted) > 20:
        v(f"...and {len(unaccounted) - 20} more unaccounted tasks")
    for tid in schedule.completed:
        if tid not in placed:
            v(f"completed task {tid!r} has no placement")
    for tid in placed:
        if tid not in schedule.completed:
            v(f"placed task {tid!r} not marked completed")

    # dependency order + failed-dependency propagation
    for tid in placed:
        for d in graph[tid].dependencies:
            if d in schedule.failed:
                v(f"{tid!r} completed but its dependency {d!r} failed")
            elif d not in placed:
                v(f"{tid!r} placed but its dependency {d!r} is unplaced")
            elif pos.get(d, -1) > pos.get(tid, -1):
                v(f"{tid!r} ordered before its dependency {d!r}")

    # memory feasibility: hard per-task footprint + no-evict residency replay
    resident: Dict[str, Dict[str, float]] = {d.node_id: {} for d in cluster}
    peak = {d.node_id: 0.0 for d in cluster}
    for tid in order:
        nid = placed.get(tid)
        if nid is None or tid not in graph:
            continue
        task = graph[tid]
        cap = cluster[nid].total_memory
        own = task.memory_required + sum(
            graph.param_size_gb(p) for p in task.params_needed
        )
        if own > cap + 1e-9:
            v(
                f"{tid!r} needs {own:.2f} GB alone but {nid} has {cap:.2f} GB"
            )
        for p in task.params_needed:
            resident[nid].setdefault(p, graph.param_size_gb(p))
        now = sum(resident[nid].values()) + task.memory_required
        peak[nid] = max(peak[nid], now)
    for nid, pk in peak.items():
        rep.peak_no_evict_gb[nid] = pk
        if pk > cluster[nid].total_memory + 1e-9:
            if strict:
                v(
                    f"{nid} peak no-evict residency {pk:.2f} GB exceeds "
                    f"{cluster[nid].total_memory:.2f} GB"
                )
            else:
                rep.requires_eviction.append(nid)

    return rep
