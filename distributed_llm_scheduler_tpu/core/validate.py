"""Independent schedule validation: the framework's "race detector".

Historical entry point, now a thin shim over the static-analysis
subsystem (``analysis/``): :func:`validate_schedule` runs the
schedule-consistency and memory-feasibility passes and re-shapes their
structured diagnostics into the original :class:`ValidationReport`
(message texts unchanged — callers and tests match on substrings).  New
code should call :func:`analysis.analyze` directly for coded diagnostics;
see docs/ANALYSIS.md for the taxonomy.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List

from .cluster import Cluster
from .graph import TaskGraph
from .schedule import Schedule


@dataclass
class ValidationReport:
    violations: List[str] = field(default_factory=list)
    # diagnostics: per-node peak resident GB if nothing is ever evicted
    peak_no_evict_gb: Dict[str, float] = field(default_factory=dict)
    requires_eviction: List[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.violations

    def summary(self) -> str:
        if self.ok:
            extra = (
                f" (eviction required on {len(self.requires_eviction)} nodes)"
                if self.requires_eviction else ""
            )
            return f"schedule valid{extra}"
        head = "; ".join(self.violations[:5])
        more = len(self.violations) - 5
        return f"{len(self.violations)} violations: {head}" + (
            f"; +{more} more" if more > 0 else ""
        )


def validate_schedule(
    graph: TaskGraph,
    cluster: Cluster,
    schedule: Schedule,
    strict: bool = False,
) -> ValidationReport:
    """Check a schedule against the graph/cluster it claims to place."""
    from ..analysis import analyze_memory, analyze_schedule

    graph.freeze()
    rep = ValidationReport()
    consistency = analyze_schedule(graph, cluster, schedule)
    memory = analyze_memory(graph, cluster, schedule, strict=strict)
    # MEM004 (param larger than any device) is a graph-level finding the
    # historical validator never made; the lint CLI surfaces it instead
    for d in consistency.errors + memory.errors:
        if d.code != "MEM004":
            rep.violations.append(d.message)
    for d in memory.by_code("MEM001"):
        rep.peak_no_evict_gb[d.node] = d.data["peak_gb"]
    if not strict:
        rep.requires_eviction = [d.node for d in memory.by_code("MEM002")]
    return rep
