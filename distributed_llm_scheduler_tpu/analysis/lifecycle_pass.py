"""Pass — request-lifecycle protocol checker (LCY001-LCY005).

Replays any per-request lifecycle record — an engine ``dls.requests/1``
snapshot, the frontend's merged serving rows, or a bare row list —
against the request state machine

    submitted -> queued -> admitted -> prefill_done -> decoding
              -> retired | preempted | shed

checking transition legality (each timestamp implies the states that
must precede it), timestamp monotonicity (shared, to the message, with
``obs.reqlog.validate_request_log`` via
:func:`~..obs.reqlog.timestamp_order_errors`), token accounting against
the delivery series, and — for a finished run — terminal-state
exhaustiveness.  Admission and preemption bugs surface here as named
diagnostics instead of digest mismatches three tests away.

======  ==========================================================
LCY001  illegal transition: a timestamp/state combination the state
        machine cannot produce (e.g. first token without admission,
        ``t_retire`` on a preempted record)
LCY002  time travel: a later lifecycle timestamp strictly precedes
        an earlier one (ties are legal — the virtual clock stamps
        coalesced events identically)
LCY003  non-terminal state in a finished log (``final=True``)
LCY004  unknown or missing state name
LCY005  ``n_tokens`` disagrees with the delivery series
======  ==========================================================
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ..obs.reqlog import STATES, timestamp_order_errors
from .diagnostics import AnalysisReport, Severity

#: every state any layer may record: the engine's lifecycle states plus
#: the frontend-only ``shed`` (rejected at admission, never admitted)
KNOWN_STATES = frozenset(STATES) | {"shed"}

#: a finished run leaves every request in one of these
TERMINAL_STATES = frozenset({"retired", "preempted", "shed"})


def _rows_of(source: Any) -> List[Dict[str, Any]]:
    """Normalize a RequestLog, its snapshot dict, or a row list."""
    if source is None:
        return []
    snap = getattr(source, "snapshot", None)
    if callable(snap):
        source = snap()
    if isinstance(source, dict):
        return list(source.get("requests", []))
    return list(source)


def analyze_lifecycle(
    source: Any,
    *,
    final: bool = False,
    label: Optional[str] = None,
) -> AnalysisReport:
    """Protocol-check per-request lifecycle rows.

    ``final=True`` additionally requires every request to have reached a
    terminal state (LCY003) — use it for completed runs/artifacts, not
    live logs.  ``label`` prefixes messages when several logs are linted
    into one report (e.g. per artifact leg).
    """
    rep = AnalysisReport()
    tag = f"{label}: " if label else ""
    for i, row in enumerate(_rows_of(source)):
        if not isinstance(row, dict):
            rep.add(
                "LCY004",
                Severity.ERROR,
                f"{tag}requests[{i}] is not a record",
            )
            continue
        rid = str(row.get("rid", f"requests[{i}]"))
        state = row.get("state")
        if state not in KNOWN_STATES:
            rep.add(
                "LCY004",
                Severity.ERROR,
                f"{tag}request {rid}: unknown state {state!r}",
                task=rid,
                data={"state": state},
            )
            continue

        for msg in timestamp_order_errors(row):
            rep.add(
                "LCY002",
                Severity.ERROR,
                f"{tag}request {rid}: {msg}",
                task=rid,
            )

        t_admit = row.get("t_admit")
        t_ft = row.get("t_first_token")
        t_ret = row.get("t_retire")

        def illegal(why: str) -> None:
            rep.add(
                "LCY001",
                Severity.ERROR,
                f"{tag}request {rid}: {why}",
                task=rid,
                data={"state": state},
            )

        # timestamps imply the states that must have preceded them
        if t_ft is not None and t_admit is None:
            illegal("t_first_token set but t_admit is null "
                    "(prefill without admission)")
        if t_ret is not None and state != "retired":
            illegal(f"t_retire set but state is {state!r}")
        if state == "retired":
            if t_ret is None:
                illegal("retired but t_retire is null")
            if t_ft is None:
                illegal("retired but t_first_token is null")
        elif state == "preempted":
            if t_admit is None:
                illegal("preempted but t_admit is null "
                        "(only admitted requests hold pages)")
            if t_ft is None:
                illegal("preempted but t_first_token is null")
        elif state == "decoding":
            if t_ft is None:
                illegal("decoding but t_first_token is null")
        elif state == "shed":
            if t_admit is not None:
                illegal("shed but t_admit is set "
                        "(shedding happens at admission)")
        elif state in ("submitted", "queued"):
            if t_ft is not None:
                illegal(f"state {state!r} but t_first_token is set")

        # token accounting vs the delivery series
        dl = row.get("deliveries")
        n_tok = row.get("n_tokens", 0) or 0
        if isinstance(dl, list) and all(
            isinstance(d, (list, tuple)) and len(d) == 2 for d in dl
        ):
            delivered = sum(int(d[1]) for d in dl)
            if int(n_tok) != delivered:
                rep.add(
                    "LCY005",
                    Severity.ERROR,
                    f"{tag}request {rid}: n_tokens ({n_tok}) != sum of "
                    f"deliveries ({delivered})",
                    task=rid,
                    data={"n_tokens": n_tok, "delivered": delivered},
                )
        elif int(n_tok) > 0:
            rep.add(
                "LCY005",
                Severity.ERROR,
                f"{tag}request {rid}: {n_tok} tokens counted but the "
                "delivery series is missing or malformed",
                task=rid,
            )

        if final and state not in TERMINAL_STATES:
            rep.add(
                "LCY003",
                Severity.ERROR,
                f"{tag}request {rid}: non-terminal state {state!r} in a "
                "finished log",
                task=rid,
                data={"state": state},
            )
    return rep
